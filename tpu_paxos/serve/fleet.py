"""Fleet serving: many tenant value streams per dispatch — the serve
driver's whole dispatch window vmapped over a ``[lanes]`` axis, with
per-lane SLO verdicts reduced ON DEVICE.

The PR-9 harness serves ONE value stream per process; production is
millions of users spread over many tenant clusters, each with its own
arrival process and SLO (ROADMAP item 2).  This module lifts the
open-loop serve loop onto fleet lanes exactly the way ``fleet/runner``
lifted the stress engine: the per-lane dispatch window — ingest-stamp
scatter, ``admit_block`` queue append, ``rounds_per_window``
recorder-armed engine rounds per sub-window, and the on-device
summary epilogue — is ONE traced function ``vmap``-ed over stacked
lane state, so a whole tenant fleet advances per XLA dispatch.  The
per-lane :class:`~tpu_paxos.serve.driver.ServeLoopState` (engine
state, recorder accumulators incl. the ``[W]`` windowed rings, ingest
table) rides as ONE donated ``[lanes]``-stacked argument; per-lane
``ArrivalPlan`` admission blocks upload as ``[lanes, S, P, K]``
runtime data.  Lanes differ in arrivals, seeds, and SLOs — never in
compiled program: ``fleet/envelope.serve_fleet_for`` memoizes one
:class:`ServeFleetRunner` per serve envelope (geometry, protocol,
i.i.d. knobs, queue/vid shapes, window spans), and lane count /
windows-per-dispatch / admit width are call shapes of the one cached
callable, so a whole (lanes x offered-rates) sweep costs one compile
per lane-count shape and ZERO warm compiles across the grid
(BENCH_serve_fleet.json pins it).

The SLO monitor moves on device: each dispatch reduces every lane's
windowed latency series (global AND per-region — see
``telemetry/recorder.region_window_hist``) against runtime burn-rate
thresholds to a ``[lanes]`` breach vector (:func:`_slo_breach`), so
the per-dispatch host sync is four small vectors (done / round /
decided / breach) and ONLY breaching lanes ever pay the windowed
series transfer + the host judge that names their breach windows per
(lane, region).  The device verdict is a conservative superset of the
host judge (``BURN_EPS`` covers the judge's 3-decimal rounding), so a
lane the host would flag is never silently skipped.

Lane-for-lane the fleet is DECISION-LOG-IDENTICAL to single
``serve/harness.serve_run`` executions of the same (cfg, stream,
seed) at the same dispatch granularity — the engine build is the
single driver's, and ``jax_threefry_partitionable`` makes the batched
draws equal the per-lane draws (tests/test_serve_fleet.py pins the
sha256 per lane on a heterogeneous-rate stack).  Scale-out mirrors
``fleet/runner``: the lane axis tiles over a device mesh via
``shard_map`` (lanes are independent — no collectives), bitwise
parity pinned on the test conftest's virtual mesh.
"""

from __future__ import annotations

import argparse
import bisect
import dataclasses
import json
import sys
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.analysis import tracecount
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.core import values as val
from tpu_paxos.serve import arrivals as arrv
from tpu_paxos.serve import driver as drv
from tpu_paxos.serve import harness as sh
from tpu_paxos.telemetry import recorder as telem
from tpu_paxos.utils import prng

#: Margin subtracted from the burn threshold by the ON-DEVICE verdict
#: (:func:`_slo_breach`).  The host judge (harness._judge_series)
#: rounds each window's burn rate to 3 decimals before comparing, so
#: a window at burn >= burn_breach - 0.0005 can round UP into a named
#: breach; the device verdict must flag every such lane (it is the
#: transfer gate — a missed flag would silently hide a breach), so it
#: compares against the threshold minus this margin.  The cost of the
#: asymmetry is one spurious lane transfer within the margin, which
#: the host judge then renders as a no-breach verdict.
BURN_EPS = 5e-4


class ServeLane(NamedTuple):
    """One tenant stream: per-proposer vid sequences, their arrival
    rounds (nondecreasing per proposer — the queue is FIFO), and the
    lane's PRNG seed (the single-run twin is ``serve_run`` on
    ``dataclasses.replace(cfg, seed=seed)``)."""

    workload: list
    arrivals: list
    seed: int


def _slo_args(slo, region_names):
    """Runtime SLO-threshold arrays for one dispatch: ``(k, region_k,
    budget_milli, burn_milli)``.  Thresholds are RUNTIME inputs of the
    compiled dispatch, so every SLO declaration (and none at all)
    rides one executable: ``slo=None`` lowers to inert thresholds
    (bucket index = NUM_LAT_BUCKETS — nothing is ever bad).

    A declared region missing from ``region_names`` has no per-region
    series on device; its threshold folds into the GLOBAL series
    bucket index (min — more buckets count as bad), keeping the device
    verdict a superset of the host judge's global-series fallback."""
    b = telem.NUM_LAT_BUCKETS
    rk = np.full((telem.NUM_REGIONS,), b, np.int32)
    if slo is None:
        return (np.int32(b), rk, np.int32(1), np.int32(1000))
    k = bisect.bisect_right(telem.LAT_EDGES, int(slo.latency_rounds))
    names = tuple(region_names)
    for name, lat in slo.regions:
        kr = bisect.bisect_right(telem.LAT_EDGES, int(lat))
        if name in names:
            rk[names.index(name)] = kr
        else:
            k = min(k, kr)
    return (
        np.int32(k), rk,
        np.int32(max(int(slo.budget_milli), 1)),
        np.int32(round(float(slo.burn_breach) * 1000)),
    )


def _slo_breach(lat_hist, region_hist, slo_k, region_k, budget_milli,
                burn_milli):
    """The on-device per-lane SLO verdict: ``[lanes]`` bool — does any
    window of the lane's global series (threshold bucket ``slo_k``) or
    any region's own series (``region_k[r]``) burn at or above the
    breach threshold?  Float32 on both sides of the device/host seam
    (the host confirm judge uses the same expression), with the
    comparison shifted by :data:`BURN_EPS` so the device flag is a
    conservative superset of the host judge's rounded verdict.
    ``lat_hist`` is ``[lanes, W, B]``; ``region_hist`` is
    ``[lanes, R, W, B]``."""
    b = lat_hist.shape[-1]
    ar = jnp.arange(b, dtype=jnp.int32)
    thresh = (
        burn_milli.astype(jnp.float32) / jnp.float32(1000.0)
        - jnp.float32(BURN_EPS)
    )

    def burns(hist, bad_mask):
        tot = hist.sum(axis=-1)
        bad = (hist * bad_mask).sum(axis=-1)
        num = (bad * 1000).astype(jnp.float32)
        den = (tot * budget_milli).astype(jnp.float32)
        return (tot > 0) & (num >= thresh * den)

    g = burns(lat_hist, (ar >= slo_k).astype(lat_hist.dtype))
    rmask = (ar[None, :] >= region_k[:, None]).astype(region_hist.dtype)
    r = burns(region_hist, rmask[None, :, None, :])
    return g.any(axis=-1) | r.any(axis=(-1, -2))


class ServeFleetRunner:
    """Compile-once fleet serving front end for one serve envelope:
    the jitted, vmapped (optionally shard_map-tiled) dispatch-window
    program with the ``[lanes]``-stacked loop state donated.  ``run``
    — the host loop — lives in :func:`serve_fleet_run`; this class
    owns every jitted surface so the audit's unregistered-function
    sweep covers the module (entry ``serve.fleet_window``).

    The engine build is EXACTLY the single serve driver's
    (``build_engine(cfg, queue_cap, vid_cap=0, telemetry=True,
    window_rounds=ww)``), which is what makes a fleet lane
    decision-log-identical to its ``serve_run`` twin."""

    def __init__(
        self,
        cfg: SimConfig,
        queue_cap: int,
        vid_bound: int,
        rounds_per_window: int,
        window_rounds: int,
        mesh=None,
    ):
        if cfg.faults.schedule is not None:
            raise ValueError(
                "serve engines take no fault schedule (correlated-fault "
                "serving rides the fleet envelope, not this driver)"
            )
        ww = int(window_rounds)
        if ww <= 0:
            raise ValueError(
                "fleet serving always rides the windowed plane (the "
                "on-device SLO verdict reads it); window_rounds must "
                "be positive"
            )
        self.cfg = cfg
        self.queue_cap = int(queue_cap)
        self.vid_bound = int(vid_bound)
        self.rounds_per_window = int(rounds_per_window)
        self.window_rounds = ww
        self.mesh = mesh
        round_fn = simm.build_engine(
            cfg, self.queue_cap, vid_cap=0, telemetry=True, window_rounds=ww
        )
        r = self.rounds_per_window
        v_bound = self.vid_bound

        def lane(ss, root, admits, arrs, vid_region, rmap):
            s = admits.shape[0]

            def sub(i, carry):
                st, tl, ingest = carry
                admit, arr = admits[i], arrs[i]
                # ingest-time stamping, exactly the single driver's
                flat_v = admit.reshape(-1)
                idx = jnp.where(
                    (flat_v >= 0) & (flat_v < v_bound), flat_v, v_bound
                )
                ingest = ingest.at[idx].set(arr.reshape(-1), mode="drop")
                st = simm.admit_block(st, admit)

                def body(_, c):
                    return round_fn(root, c[0], tele=c[1])

                st, tl = jax.lax.fori_loop(0, r, body, (st, tl))
                return drv.ServeLoopState(st, tl, ingest)

            st, tl, ingest = jax.lax.fori_loop(
                0, s, sub, drv.ServeLoopState(*ss)
            )
            adm = telem.serve_admit_rounds(ingest, st.met.chosen_vid)
            base, wins = tl
            summ = telem.summarize(
                base._replace(admit_round=adm), st, 0, rmap
            )
            wsum = telem.summarize_windows(
                wins, adm, st.met.chosen_vid, st.met.chosen_round, ww,
                batch_round=base.admit_round,
                learned_round=base.learned_round,
                committed_round=base.committed_round,
            )
            rw = telem.region_window_hist(
                adm, st.met.chosen_vid, st.met.chosen_round, vid_region, ww
            )
            return (
                drv.ServeLoopState(st, tl, ingest),
                st.done, st.t, summ, wsum, rw,
            )

        fl = jax.vmap(lane)
        if mesh is not None and mesh.size > 1:
            from tpu_paxos.parallel import mesh as pmesh

            # lane-axis spec from the mesh module (SH001: axis names
            # route through parallel/, never hand-built here)
            spec = pmesh.instance_spec(mesh)
            fl = pmesh.shard_map(
                fl, mesh, in_specs=(spec,) * 6, out_specs=(spec,) * 6
            )

        def dispatch(sss, roots, admits, arrs, vid_regions, rmaps,
                     slo_k, region_k, budget_milli, burn_milli):
            sss, done, t, summ, wsum, rw = fl(
                sss, roots, admits, arrs, vid_regions, rmaps
            )
            breach = _slo_breach(
                wsum.lat_hist, rw, slo_k, region_k, budget_milli,
                burn_milli,
            )
            # real stamped values decided per lane (hist mass — the
            # noop-fill-free count the harness's `decided` means)
            decided = jnp.sum(summ.lat_hist, axis=-1)
            return sss, done, t, decided, breach, summ, wsum, rw

        self._fn = jax.jit(dispatch, donate_argnums=(0,))

        def init_lane(pend, gate, tail, root):
            st = simm.init_state(cfg, pend, gate, tail, root)
            tele = (
                telem.init_telemetry(
                    cfg.n_instances, len(cfg.proposers), cfg.n_nodes
                ),
                telem.init_windows(cfg.n_nodes),
            )
            ingest = jnp.full((v_bound,), val.NONE, jnp.int32)
            return drv.ServeLoopState(st, tele, ingest)

        self._init = jax.jit(jax.vmap(init_lane))


@dataclasses.dataclass
class ServeFleetReport:
    """One fleet serve run's outcome.  The per-lane summaries, the
    windowed series, and the per-region series stay ON DEVICE — the
    per-dispatch sync was four ``[lanes]`` vectors, and ``slo`` holds
    host-confirmed verdicts for the lanes the on-device monitor
    flagged (only those paid the series transfer)."""

    cfg: SimConfig
    n_lanes: int
    seeds: list
    rounds_per_window: int
    windows_per_dispatch: int
    admit_width: int
    window_rounds: int
    dispatches: int
    rounds: int
    done: bool
    n_values: list  # per-lane planned stream sizes
    decided: np.ndarray  # [lanes] real stamped values decided
    wall_seconds: float
    breach: np.ndarray  # [lanes] bool — the final on-device verdict
    first_breach_dispatch: list  # [lanes] 1-based dispatch | None
    slo: dict | None  # {lane: slo_windows verdict} for flagged lanes
    region_names: tuple
    final: object  # device [lanes]-stacked ServeLoopState
    summaries: object  # device [lanes] TelemetrySummary
    windows: object  # device [lanes, W] WindowSummary
    region_windows: object  # device [lanes, R, W, B] int32

    @property
    def decided_total(self) -> int:
        return int(self.decided.sum())

    @property
    def backlog(self) -> int:
        return int(sum(self.n_values)) - self.decided_total

    @property
    def values_per_sec(self) -> float:
        """Aggregate sustained throughput across every lane — the
        fleet's one clock served all of them."""
        return self.decided_total / max(self.wall_seconds, 1e-9)

    def lane_chosen(self, i: int):
        """One lane's decision arrays (chosen_vid, chosen_ballot) —
        the decision-log parity hand-off; transfers one lane."""
        met = self.final.sim.met
        return (
            np.asarray(met.chosen_vid[i]),
            np.asarray(met.chosen_ballot[i]),
        )

    def lane_summary(self, i: int) -> dict:
        """One lane's flight-recorder summary dict (incl. the
        windowed block) — transfers that lane only."""
        one = jax.tree.map(lambda x: x[i], self.summaries)
        wone = jax.tree.map(lambda x: x[i], self.windows)
        return telem.summary_to_dict(
            one, wone, self.window_rounds,
            region_names=tuple(self.region_names),
        )

    def lane_region_windows(self, i: int) -> np.ndarray:
        """One lane's ``[R, W, B]`` per-region windowed latency
        histograms — transfers that lane only."""
        return np.asarray(self.region_windows[i])


def _check_lane(cfg: SimConfig, lane: ServeLane, li: int):
    wl = [np.asarray(w, np.int32).reshape(-1) for w in lane.workload]
    if len(wl) != len(cfg.proposers):
        raise ValueError(
            f"lane {li}: one value stream per proposer required"
        )
    return ServeLane(wl, list(lane.arrivals), int(lane.seed))


def serve_fleet_run(
    cfg: SimConfig,
    lanes,
    *,
    rounds_per_window: int = sh.ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = sh.WINDOWS_PER_DISPATCH,
    admit_width: int | None = None,
    pipelined: bool = True,
    window_rounds: int | None = None,
    slo: sh.ServeSLO | None = None,
    region_map=None,
    region_names: tuple = (),
    mesh=None,
) -> ServeFleetReport:
    """Serve a fleet of tenant streams open-loop to completion (or
    the round budget): ``lanes[i]`` is a :class:`ServeLane` (or a
    ``(workload, arrivals, seed)`` triple).  Every lane advances in
    lockstep on the shared virtual clock — lanes whose plans end
    early run decision-neutral drain windows, exactly like the single
    harness past quiescence — and a 1-lane run is decision-log
    sha256-identical to ``serve_run`` at the same dispatch
    granularity.

    ``slo`` arms the ON-DEVICE burn-rate monitor: each dispatch
    reduces every lane's windowed series (and, with ``region_map`` +
    ``region_names``, each region's OWN series) to a ``[lanes]``
    breach vector, and only flagged lanes pay the series transfer +
    the host judge that names breach windows per (lane, region).
    ``mesh`` tiles the lane axis over devices via ``shard_map``
    (lane count must tile the mesh)."""
    from tpu_paxos.fleet import envelope as envm

    lanes = [
        _check_lane(cfg, ln if isinstance(ln, ServeLane) else ServeLane(*ln), i)
        for i, ln in enumerate(lanes)
    ]
    if not lanes:
        raise ValueError("at least one lane required")
    n_lanes = len(lanes)
    if mesh is not None and n_lanes % max(mesh.size, 1):
        raise ValueError(
            f"{n_lanes} lanes do not tile over {mesh.size} devices"
        )
    plans = [
        arrv.ArrivalPlan(ln.workload, ln.arrivals, rounds_per_window)
        for ln in lanes
    ]
    k = int(admit_width or max(p.max_block for p in plans))
    if max(p.max_block for p in plans) > k:
        raise ValueError(
            f"admit_width {k} below this fleet's max block "
            f"{max(p.max_block for p in plans)}"
        )
    s = int(windows_per_dispatch)
    if s < 1:
        raise ValueError("windows_per_dispatch must be >= 1")
    if window_rounds is None:
        window_rounds = sh.WINDOWS_PER_BUCKET * rounds_per_window
    ww = int(window_rounds)
    if slo is not None and not ww:
        raise ValueError(
            "the SLO monitor reads the windowed series; "
            "window_rounds=0 disarms it"
        )
    # envelope shapes: queue capacity and vid bound cover every lane
    # (capacity follows prepare_queues' proof per lane, so the bound
    # over lanes keeps every lane clamp-free)
    c = max(simm.prepare_queues(cfg, ln.workload)[3] for ln in lanes)
    v_bound = max(drv.vid_bound_of(ln.workload) for ln in lanes)
    runner = envm.serve_fleet_for(
        cfg, c, v_bound, rounds_per_window,
        window_rounds=ww, mesh=mesh,
    )
    p = len(cfg.proposers)
    width = c + cfg.assign_window
    pend = np.full((n_lanes, p, width), int(val.NONE), np.int32)
    gate = np.full((n_lanes, p, width), int(val.NONE), np.int32)
    tail = np.zeros((n_lanes, p), np.int32)
    roots = jnp.stack([prng.root_key(ln.seed) for ln in lanes])
    a = cfg.n_nodes
    if region_map is None:
        rmap = np.zeros((a,), np.int32)
    else:
        rmap = np.asarray(region_map, np.int32).reshape(a)
    rmaps = np.broadcast_to(rmap, (n_lanes, a))
    vid_regions = np.zeros((n_lanes, v_bound), np.int32)
    for li, ln in enumerate(lanes):
        for node, stream in zip(cfg.proposers, ln.workload):
            vid_regions[li, stream] = rmap[node]
    slo_args = tuple(
        jnp.asarray(x) for x in _slo_args(slo, region_names)
    )
    n_disp_admit = max((pl.n_windows + s - 1) // s for pl in plans)
    disp_cap = max(
        cfg.round_budget // (rounds_per_window * s) + 1, n_disp_admit
    )
    empty = (
        jnp.full((n_lanes, s, p, k), val.NONE, jnp.int32),
        jnp.zeros((n_lanes, s, p, k), jnp.int32),
    )

    def super_block(d):
        """Stack dispatch ``d``'s S admission windows for every lane
        ([lanes, S, P, K]); lanes past their plan get empty rows."""
        adm = np.stack([
            np.stack([pl.block(d * s + i, k)[0] for i in range(s)])
            for pl in plans
        ])
        arr = np.stack([
            np.stack([pl.block(d * s + i, k)[1] for i in range(s)])
            for pl in plans
        ])
        return jnp.asarray(adm), jnp.asarray(arr)

    first_breach: list = [None] * n_lanes

    def harvest(out):
        # the one host sync per dispatch: four [lanes] vectors — the
        # stop scalars, the decided counts, and the ON-DEVICE SLO
        # verdict; the windowed series stay on device
        done, t, decided, breach = (
            np.asarray(out[0]), np.asarray(out[1]),  # paxlint: allow[JAX103] the harvest IS the per-dispatch sync point: four [lanes] vectors by design, double-buffered by the caller
            np.asarray(out[2]), np.asarray(out[3]),
        )
        for i in np.flatnonzero(breach):
            if first_breach[int(i)] is None:
                first_breach[int(i)] = harvested + 1
        return done, t, decided, breach

    pending = None
    last_done = np.zeros((n_lanes,), bool)
    last_t = np.zeros((n_lanes,), np.int32)
    last_decided = np.zeros((n_lanes,), np.int32)
    last_breach = np.zeros((n_lanes,), bool)
    last_dev = None
    d = harvested = 0
    t0 = time.perf_counter()  # paxlint: allow[DET001] wall metric only; never reaches artifacts
    with tracecount.engine_scope("serve_fleet"):
        sss = runner._init(
            jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail), roots
        )
        while True:
            blk = super_block(d) if d < n_disp_admit else empty
            out = runner._fn(
                sss, roots, *blk, jnp.asarray(vid_regions),
                jnp.asarray(rmaps), *slo_args,
            )
            sss = out[0]
            d += 1
            if pipelined:
                if pending is not None:
                    last_done, last_t, last_decided, last_breach = (
                        harvest(pending[:4])
                    )
                    last_dev = pending[4:]
                    harvested += 1
                pending = out[1:]
            else:
                last_done, last_t, last_decided, last_breach = harvest(
                    out[1:5]
                )
                last_dev = out[5:]
                harvested += 1
            if harvested >= n_disp_admit and last_done.all():
                break
            if d >= disp_cap:
                break
        if pending is not None:
            last_done, last_t, last_decided, last_breach = harvest(
                pending[:4]
            )
            last_dev = pending[4:]
            harvested += 1
    wall = time.perf_counter() - t0  # paxlint: allow[DET001] wall metric only; never reaches artifacts

    summaries, windows, region_windows = last_dev
    # Host-confirmed verdicts for the flagged lanes ONLY — the named
    # (lane, region) breach windows; everything else never transfers.
    slo_dict = None
    if slo is not None:
        slo_dict = {}
        from tpu_paxos.telemetry import diagnose as diag

        for i in np.flatnonzero(last_breach):
            i = int(i)
            # post-clock confirm: ONLY flagged lanes transfer — the
            # lane's full windowed series + summary feed the host
            # judge AND the breach-attribution classifier
            lane_w = jax.tree.map(lambda x, i=i: np.asarray(x[i]), windows)  # paxlint: allow[JAX103] post-clock confirm: ONLY flagged lanes transfer, one slice each — the monitor's whole point
            lane_s = jax.tree.map(lambda x, i=i: np.asarray(x[i]), summaries)  # paxlint: allow[JAX103] same flagged-lane confirm transfer
            sd_i = telem.summary_to_dict(
                lane_s, lane_w, ww, region_names=tuple(region_names)
            )
            wd_i = sd_i["windows"]
            verdict = sh.slo_windows(
                wd_i,
                slo,
                region_series=np.asarray(region_windows[i]),
                region_names=region_names,
            )
            diag.attach_diagnosis(
                verdict, wd_i,
                region_map=np.asarray(rmap),
                region_names=tuple(region_names),
                region_pairs=sd_i.get("region_pairs"),
                region_series=np.asarray(region_windows[i]),
            )
            slo_dict[i] = verdict
    return ServeFleetReport(
        cfg=cfg,
        n_lanes=n_lanes,
        seeds=[ln.seed for ln in lanes],
        rounds_per_window=int(rounds_per_window),
        windows_per_dispatch=s,
        admit_width=k,
        window_rounds=ww,
        dispatches=d,
        rounds=int(last_t.max()),
        done=bool(last_done.all()),
        n_values=[pl.n_values for pl in plans],
        decided=last_decided,
        wall_seconds=wall,
        breach=last_breach,
        first_breach_dispatch=first_breach,
        slo=slo_dict,
        region_names=tuple(region_names),
        final=sss,
        summaries=summaries,
        windows=windows,
        region_windows=region_windows,
    )


# ---------------- the (lanes x offered-rates) surface ----------------


def _agg_windows_hist(rep: ServeFleetReport) -> tuple[np.ndarray, int]:
    """Fleet-aggregate windowed latency histogram ``[W, B]`` and the
    observed latency max — reduced ON DEVICE over the lane axis, so
    only the small aggregate transfers."""
    hist = np.asarray(jnp.sum(rep.windows.lat_hist, axis=0))
    lat_max = int(np.asarray(jnp.max(rep.summaries.lat_max)))
    return hist, lat_max


def _steady_p50_of(hist: np.ndarray, lat_max: int) -> int | None:
    """Steady-state median over a ``[W, B]`` windowed histogram — the
    harness's ``_steady_p50`` on an aggregate series (median of the
    active buckets' bucket-edge medians)."""
    p50s = [
        telem.latency_quantile(row, 0.50, lat_max)
        for row in hist
    ]
    p50s = [p for p in p50s if p >= 0]
    if not p50s:
        return None
    return sorted(p50s)[len(p50s) // 2]


def _fleet_point(rate_milli: int, rep: ServeFleetReport) -> dict:
    hist, lat_max = _agg_windows_hist(rep)
    total = hist.sum(axis=0)
    steady = _steady_p50_of(hist, lat_max)
    return {
        "rate_milli": int(rate_milli),
        "lanes": rep.n_lanes,
        "decided": rep.decided_total,
        "backlog": rep.backlog,
        "done": rep.done,
        "rounds": rep.rounds,
        "dispatches": rep.dispatches,
        "wall_seconds": round(rep.wall_seconds, 4),
        "values_per_sec": round(rep.values_per_sec, 1),
        "sustained": bool(rep.done and rep.backlog == 0),
        "p50": telem.latency_quantile(total, 0.50, lat_max),
        "p99": telem.latency_quantile(total, 0.99, lat_max),
        **({"p50_steady": steady} if steady is not None else {}),
        "breach_lanes": [int(i) for i in np.flatnonzero(rep.breach)],
        **({
            "slo": {str(i): v for i, v in rep.slo.items()}
        } if rep.slo else {}),
    }


def fleet_lanes(
    cfg: SimConfig,
    n_lanes: int,
    n_values: int,
    rate_milli: int,
    seed: int,
    arrivals: str = "poisson",
) -> list[ServeLane]:
    """Build one tenant fleet: ``n_lanes`` independent streams of
    ``n_values`` values each at offered rate ``rate_milli`` — every
    lane draws its OWN arrival process (seed-mixed per lane) and its
    own engine seed, deterministically per (seed, lane)."""
    build = arrv.ARRIVAL_BUILDERS[arrivals]
    vids = np.arange(int(n_values), dtype=np.int32)
    n_prop = len(cfg.proposers)
    out = []
    for li in range(int(n_lanes)):
        rounds = build(n_values, int(rate_milli), seed + 101 * li)
        streams, arrs = arrv.split_round_robin(vids, rounds, n_prop)
        out.append(ServeLane(streams, arrs, seed + li))
    return out


def grid_admit_width(
    cfg: SimConfig,
    n_values: int,
    lane_counts,
    rates_milli,
    *,
    seed: int = 0,
    rounds_per_window: int = sh.ROUNDS_PER_WINDOW,
    arrivals: str = "poisson",
) -> int:
    """ONE admit width covering every (lane count x rate) cell of a
    sweep grid: the (L, S, K) call shape keys the executable, so the
    grid must not fork it per rate.  Shared by :func:`sweep_fleet_load`
    and the bench (which needs the width BEFORE its warm pass)."""
    width = 1
    for lc in lane_counts:
        for rm in rates_milli:
            for ln in fleet_lanes(cfg, lc, n_values, rm, seed, arrivals):
                width = max(
                    width,
                    arrv.ArrivalPlan(
                        ln.workload, ln.arrivals, rounds_per_window
                    ).max_block,
                )
    return width


def sweep_fleet_load(
    cfg: SimConfig,
    n_values: int,
    lane_counts,
    rates_milli,
    *,
    seed: int = 0,
    rounds_per_window: int = sh.ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = sh.WINDOWS_PER_DISPATCH,
    admit_width: int | None = None,
    window_rounds: int | None = None,
    knee_factor: float = 2.0,
    slo: sh.ServeSLO | None = None,
    region_map=None,
    region_names: tuple = (),
    mesh=None,
    arrivals: str = "poisson",
    control=None,
) -> dict:
    """The headline SURFACE: aggregate sustained values/sec and the
    saturation knee over (lane count x offered rate).  One cell = one
    fleet run of ``lane_count`` tenant streams, each ``n_values``
    values at ``rate_milli``; every cell of a lane count shares the
    envelope's one cached executable (admit width is the max over the
    whole grid, so the call shape never varies within a lane count),
    and the knee per lane count is ``harness.judge_knee`` over that
    row — a knee SURFACE, not a knee point.

    ``control`` (a ``serve/control.ControlPolicy``; requires ``slo``)
    arms the per-lane admission controller in EVERY cell — points
    then carry their shed/decision ledgers and the exit verdict must
    go through :func:`sweep_verdict`, which refuses a floor-rate cell
    that only drained by shedding."""
    lane_counts = [int(x) for x in lane_counts]
    rates = sorted(int(x) for x in rates_milli)
    if control is not None:
        # lazy: the controller module is jax-bearing and only the
        # controlled sweep pays its import (DET-closure discipline)
        from tpu_paxos.serve import control as ctlm

        if slo is None:
            raise ValueError(
                "a controlled sweep reads SLO verdicts; declare an slo"
            )
    # an explicit admit_width is AUTHORITATIVE (the caller computed it
    # via grid_admit_width and may have warmed executables at exactly
    # that shape — recomputing here would duplicate the whole grid's
    # plan construction); a too-narrow width fails loudly per run
    width = (
        int(admit_width) if admit_width
        else grid_admit_width(
            cfg, n_values, lane_counts, rates, seed=seed,
            rounds_per_window=rounds_per_window, arrivals=arrivals,
        )
    )
    cells = {}
    knee_surface = []
    surface = {}
    for lc in lane_counts:
        points = []
        for rm in rates:
            lanes = fleet_lanes(cfg, lc, n_values, rm, seed, arrivals)
            if control is not None:
                rep = ctlm.controlled_fleet_run(
                    cfg, lanes,
                    control=control,
                    rounds_per_window=rounds_per_window,
                    windows_per_dispatch=windows_per_dispatch,
                    admit_width=width,
                    window_rounds=window_rounds,
                    slo=slo,
                    region_map=region_map,
                    region_names=region_names,
                    mesh=mesh,
                )
            else:
                rep = serve_fleet_run(
                    cfg, lanes,
                    rounds_per_window=rounds_per_window,
                    windows_per_dispatch=windows_per_dispatch,
                    admit_width=width,
                    window_rounds=window_rounds,
                    slo=slo,
                    region_map=region_map,
                    region_names=region_names,
                    mesh=mesh,
                )
            pt = _fleet_point(rm, rep)
            if control is not None:
                pt["shed"] = rep.shed_total
                pt["lane_shed"] = rep.lane_shed
                pt["control_decisions"] = len(rep.decisions)
            points.append(pt)
        knee = sh.judge_knee(points, knee_factor)
        cells[str(lc)] = {"points": points, "knee": knee}
        knee_surface.append({"lanes": lc, **knee})
        surface[str(lc)] = {
            str(pt["rate_milli"]): pt["values_per_sec"] for pt in points
        }
    return {
        "metric": "serve_fleet_latency_at_load_surface",
        "n_values": int(n_values),
        "arrivals": arrivals,
        "rounds_per_window": int(rounds_per_window),
        "windows_per_dispatch": int(windows_per_dispatch),
        "admit_width": width,
        "lane_counts": lane_counts,
        "rates_milli": rates,
        "values_per_sec_surface": surface,
        "cells": cells,
        "knee_surface": knee_surface,
        **({
            "control": ctlm.policy_to_dict(control)
        } if control is not None else {}),
    }


def sweep_verdict(summary: dict) -> bool:
    """The sweep's exit verdict: every lane count's FLOOR-rate cell
    must drain (the every-lane-count rule — a fleet that saturates at
    the floor rate is broken no matter how the single-lane row looks).

    Controller-armed sweeps (``summary["control"]``) are judged
    HARDER at the floor, not softer: the floor cell must drain with
    ZERO sheds and no host-confirmed floor breach — a controller that
    sheds its way to zero backlog at the floor rate is masking
    saturation, and this verdict is what keeps it from exiting 0.
    Higher-rate cells of a controlled sweep are exploratory (the
    knee hunt EXPECTS breaches there, mitigated); uncontrolled
    sweeps keep the old rule — any host-confirmed breach reds the
    whole surface."""
    cells = summary.get("cells", {})
    if not cells:
        return False
    controlled = "control" in summary
    for c in cells.values():
        floor = c["points"][0]
        if not floor["sustained"]:
            return False
        if controlled:
            if floor.get("shed", 0):
                return False
            if floor.get("slo") and not all(
                v["ok"] for v in floor["slo"].values()
            ):
                return False
        else:
            for pt in c["points"]:
                if pt.get("slo") and not all(
                    v["ok"] for v in pt["slo"].values()
                ):
                    return False
    return True


# ---------------- CLI ----------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos serve --fleet",
        description="fleet serving: many tenant streams per dispatch "
        "(vmapped serve windows, donated stacked loop state, on-device "
        "per-lane SLO verdicts); single-cell run or the (lanes x "
        "rates) sustained-load + knee surface",
    )
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--proposers", type=int, default=2)
    ap.add_argument("--lanes", type=int, default=4,
                    help="tenant streams per dispatch")
    ap.add_argument("--lane-counts", type=str, default="",
                    help="comma-separated lane counts: sweep the "
                    "(lanes x rates) SURFACE instead of one cell")
    ap.add_argument("--values", type=int, default=128,
                    help="values per lane stream")
    ap.add_argument("--rate-milli", type=int, default=4000)
    ap.add_argument("--sweep", type=str, default="",
                    help="comma-separated rate_milli list (the "
                    "surface's rate axis; single-cell otherwise)")
    ap.add_argument("--arrivals", type=str, default="poisson",
                    choices=sorted(arrv.ARRIVAL_BUILDERS),
                    help="arrival process per lane (serve/arrivals.py)")
    ap.add_argument("--rounds-per-window", type=int,
                    default=sh.ROUNDS_PER_WINDOW)
    ap.add_argument("--windows-per-dispatch", type=int,
                    default=sh.WINDOWS_PER_DISPATCH)
    ap.add_argument("--window-rounds", type=int, default=-1,
                    help="windowed bucket width in rounds (-1 = 4 "
                    "admission windows)")
    ap.add_argument("--slo-latency", type=int, default=0,
                    help="latency SLO in rounds; arms the on-device "
                    "per-lane burn-rate verdict (0 = no SLO)")
    ap.add_argument("--slo-budget-milli", type=int, default=100)
    ap.add_argument("--control", action="store_true",
                    help="arm the per-lane admission controller "
                    "(serve/control.py) in every cell; requires "
                    "--slo-latency.  The sweep verdict then refuses "
                    "a floor-rate cell that only drained by shedding")
    ap.add_argument("--priority-tiers", type=int, default=3,
                    help="declared per-value priority tiers for "
                    "--control (tier 0 = always admit)")
    ap.add_argument("--instances", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-rounds", type=int, default=20_000)
    ap.add_argument("--drop-rate", type=int, default=0)
    ap.add_argument("--dup-rate", type=int, default=0)
    ap.add_argument("--max-delay", type=int, default=0)
    ap.add_argument("--crash-rate", type=int, default=0)
    ap.add_argument("--mesh", type=int, default=0,
                    help="tile the lane axis over an N-device mesh "
                    "(shard_map; lanes must tile it)")
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    args = ap.parse_args(argv)
    from tpu_paxos.__main__ import _select_backend

    _select_backend(args.backend)
    n_inst = args.instances or max(64, 2 * args.values)
    cfg = SimConfig(
        n_nodes=args.nodes,
        n_instances=n_inst,
        proposers=tuple(range(args.proposers)),
        seed=args.seed,
        max_rounds=args.max_rounds,
        faults=FaultConfig(
            drop_rate=args.drop_rate,
            dup_rate=args.dup_rate,
            max_delay=args.max_delay,
            crash_rate=args.crash_rate,
        ),
    )
    mesh = None
    if args.mesh > 1:
        from tpu_paxos.parallel import mesh as pmesh

        mesh = pmesh.make_instance_mesh(args.mesh)
    w_rounds = None if args.window_rounds < 0 else args.window_rounds
    slo = (
        sh.ServeSLO(latency_rounds=args.slo_latency,
                    budget_milli=args.slo_budget_milli)
        if args.slo_latency else None
    )
    policy = None
    if args.control:
        from tpu_paxos.serve import control as ctlm

        if slo is None:
            raise SystemExit(
                "--control reads SLO verdicts; declare --slo-latency"
            )
        n_tiers = args.priority_tiers
        policy = ctlm.ControlPolicy(
            n_tiers=n_tiers,
            defer_tier=max(n_tiers - 1, 1),
            shed_tier=max(n_tiers - 1, 1),
        )
    if args.sweep or args.lane_counts:
        rates = (
            [int(x) for x in args.sweep.split(",") if x.strip()]
            if args.sweep else [args.rate_milli]
        )
        lane_counts = (
            [int(x) for x in args.lane_counts.split(",") if x.strip()]
            if args.lane_counts else [args.lanes]
        )
        summary = sweep_fleet_load(
            cfg, args.values, lane_counts, rates,
            seed=args.seed,
            rounds_per_window=args.rounds_per_window,
            windows_per_dispatch=args.windows_per_dispatch,
            window_rounds=w_rounds,
            slo=slo,
            mesh=mesh,
            arrivals=args.arrivals,
            control=policy,
        )
        # every lane count's LOWEST-rate cell must drain (a fleet
        # that saturates even at the floor rate is broken regardless
        # of how the single-lane row looks); a controller-armed cell
        # must additionally drain WITHOUT shedding at the floor —
        # sweep_verdict() is the one exit gate for both shapes
        summary["ok"] = sweep_verdict(summary)
    else:
        lanes = fleet_lanes(cfg, args.lanes, args.values,
                            args.rate_milli, args.seed, args.arrivals)
        if policy is not None:
            rep = ctlm.controlled_fleet_run(
                cfg, lanes,
                control=policy,
                rounds_per_window=args.rounds_per_window,
                windows_per_dispatch=args.windows_per_dispatch,
                window_rounds=w_rounds,
                slo=slo,
                mesh=mesh,
            )
        else:
            rep = serve_fleet_run(
                cfg, lanes,
                rounds_per_window=args.rounds_per_window,
                windows_per_dispatch=args.windows_per_dispatch,
                window_rounds=w_rounds,
                slo=slo,
                mesh=mesh,
            )
        summary = {
            "metric": "serve_fleet",
            "arrivals": args.arrivals,
            **_fleet_point(args.rate_milli, rep),
            "first_breach_dispatch": [
                fb for fb in rep.first_breach_dispatch
            ],
            "ok": bool(
                rep.done and rep.backlog == 0
                and (not rep.slo
                     or all(v["ok"] for v in rep.slo.values()))
            ),
        }
        if policy is not None:
            summary["shed"] = rep.shed_total
            summary["lane_shed"] = rep.lane_shed
            summary["control_decisions"] = len(rep.decisions)
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["ok"] else 1


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------


def audit_entries():
    """Canonical fleet serve-window trace (analysis/registry.py): 2
    lanes of the audit config geometry with i.i.d. faults on, a
    2-sub-window dispatch of real admission blocks through the
    vmapped stamp + append + recorder-armed round spans, the
    on-device per-lane summary/window/region epilogues, and the
    runtime-threshold SLO breach reduction.  ``donate_argnums=(0,)``
    arms the HLO tier's aliasing checker on every leaf of the
    ``[lanes]``-stacked loop state (``hlo_build`` lowers through the
    product jit itself)."""
    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.core.sim import audit_canonical_cfg

    r_window, s_windows, k_admit = 8, 2, 4
    w_rounds = r_window * 4

    def _setup(mesh=None, n_lanes=2):
        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000, max_delay=2),
        )
        workload = simm.default_workload(cfg)
        v_bound = drv.vid_bound_of(workload)
        _, _, _, c = simm.prepare_queues(cfg, workload)
        runner = ServeFleetRunner(
            cfg, c, v_bound, r_window, w_rounds, mesh=mesh
        )
        p = len(cfg.proposers)
        width = c + cfg.assign_window
        pend = np.full((n_lanes, p, width), int(val.NONE), np.int32)
        gate = np.full((n_lanes, p, width), int(val.NONE), np.int32)
        tail = np.zeros((n_lanes, p), np.int32)
        roots = jnp.stack([prng.root_key(s) for s in range(n_lanes)])
        sss = runner._init(
            jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail), roots
        )
        admits = np.full(
            (n_lanes, s_windows, p, k_admit), int(val.NONE), np.int32
        )
        arrs = np.zeros((n_lanes, s_windows, p, k_admit), np.int32)
        for pi, w in enumerate(workload):
            w = np.asarray(w, np.int32)
            for si in range(s_windows):
                blk = w[si * k_admit:(si + 1) * k_admit]
                admits[:, si, pi, :len(blk)] = blk
                arrs[:, si, pi, :len(blk)] = si * r_window
        vid_regions = np.zeros((n_lanes, v_bound), np.int32)
        rmaps = np.zeros((n_lanes, cfg.n_nodes), np.int32)
        slo_args = tuple(
            jnp.asarray(x)
            for x in _slo_args(
                sh.ServeSLO(latency_rounds=16, budget_milli=100,
                            regions=(("us", 8),)),
                ("us",),
            )
        )
        args = (
            sss, roots, jnp.asarray(admits), jnp.asarray(arrs),
            jnp.asarray(vid_regions), jnp.asarray(rmaps), *slo_args,
        )
        return runner._fn, args

    def build():
        return _setup()

    def hlo_build():
        fn, args = _setup()
        return fn, args, {}

    def shard_build(mesh):
        # 8 lanes tile every shape of the committed mesh grid; the
        # canonical 2-lane trace stays the jaxpr/hlo-budget anchor
        return _setup(mesh=mesh, n_lanes=8)

    def shard_state():
        # the [lanes]-stacked serve-loop state the partition table
        # must cover (SH301); the leading lane axis is the sharded one
        _, args = _setup()
        return "serve", args[0]

    def shard_parity(n_devices):
        import hashlib

        from tpu_paxos.parallel import mesh as pmesh
        from tpu_paxos.replay.decision_log import decision_log

        mesh = (
            pmesh.make_instance_mesh(n_devices) if n_devices > 1 else None
        )
        cfg = SimConfig(
            n_nodes=3, n_instances=16, proposers=(0, 1), seed=0,
            max_rounds=256,
            faults=FaultConfig(drop_rate=500, max_delay=2),
        )
        lanes = fleet_lanes(cfg, 8, 6, 1500, 0)
        rep = serve_fleet_run(
            cfg, lanes,
            rounds_per_window=r_window, windows_per_dispatch=s_windows,
            admit_width=6, mesh=mesh,
            slo=sh.ServeSLO(latency_rounds=16, budget_milli=100),
        )
        verdicts = "".join(
            format(
                (int(rep.decided[i]) == rep.n_values[i]) << 1
                | int(bool(rep.breach[i])),
                "x",
            )
            for i in range(rep.n_lanes)
        )
        logs = []
        for i in range(rep.n_lanes):
            cv, cb = rep.lane_chosen(i)
            text = decision_log(cv, cb, stride=30, n_instances=len(cv))
            logs.append(hashlib.sha256(text.encode()).hexdigest())
        return {"verdicts": verdicts, "lane_logs": logs}

    ir204_why = (
        "the vmapped window body IS core/sim's round_fn — same "
        "unique-key compaction sorts as sim.run_rounds"
    )
    return [
        AuditEntry(
            "serve.fleet_window", build,
            covers=("ServeFleetRunner.__init__",),
            allow=("IR204",), why=ir204_why,
            donate_argnums=(0,),
            hlo_build=hlo_build,
            hlo_golden=True,
            shard_build=shard_build,
            shard_state=shard_state,
            shard_parity=shard_parity,
        ),
    ]


if __name__ == "__main__":
    sys.exit(main())
