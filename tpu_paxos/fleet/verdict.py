"""On-device per-lane verdicts: the invariant subset that decides
which lanes pay host transfer.

The full invariant suite (``harness/validate``) is host-side numpy
over the whole learned matrix — fine for one run, ruinous for a fleet
(hundreds of lanes would serialize through the device tunnel).  The
fleet instead reduces a SUBSET of the invariants to one boolean per
lane INSIDE the fleet dispatch, so only failing lanes are ever
transferred and re-judged by the full suite (and then shrunk,
``harness/shrink.py``):

- **agreement** — no two nodes learned different values for the same
  instance (the core safety property; exact, not a subset);
- **chosen-coverage** — every workload value whose proposer survived
  was chosen (the crash-aware liveness rule of
  ``shrink.validate_run``: a crashed proposer's undrained queue is
  legitimately lost, a paused/partitioned one's is owed);
- **quiescence-by-budget** — the engine's ``done`` predicate held
  within the round budget, excused only when every proposer crashed
  (mirrors ``shrink.check_run``).

What the subset does NOT re-check on device: exactly-once (subsumed
for fleet workloads — coverage counts distinct chosen cells against
distinct workload vids, and a double-chosen value would leave some
other value uncovered), executed-identical and in-order clients
(host-side sequence properties).  A lane can therefore pass the
device verdict and still fail the full suite in principle; the fleet
trades that tail for not transferring the 99% of green lanes, and the
stress sweep's ``--fleet`` mode documents the same contract.  The
``max_round`` output feeds the search's ``decision_round_max`` wedge
knob (the artifact-recorded extra check) host-side.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from tpu_paxos.config import SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.core import values as val


class LaneVerdict(NamedTuple):
    """Per-lane verdict vector(s); scalar per lane unbatched, [L]
    under the fleet vmap."""

    ok: jnp.ndarray  # every subset invariant green
    agreement: jnp.ndarray
    coverage: jnp.ndarray
    quiescent: jnp.ndarray
    rounds: jnp.ndarray  # int32 rounds simulated
    max_round: jnp.ndarray  # int32 latest decision round (-1: none)


def expected_owners(
    cfg: SimConfig, workload: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray]:
    """``(expected [V] int32, owner_node [V] int32)``: the distinct
    workload vids and, per vid, the NODE of the proposer that queues
    it (the crash-excusal key).  Shared by every lane of a fleet —
    the runner asserts per-lane workloads agree on this set."""
    vids, owners = [], []
    for pi, w in enumerate(workload):
        node = cfg.proposers[pi]
        for v in np.asarray(w, np.int32).reshape(-1):
            vids.append(int(v))
            owners.append(node)
    order = np.argsort(vids, kind="stable")
    vids = np.asarray(vids, np.int32)[order]
    owners = np.asarray(owners, np.int32)[order]
    uniq, first = np.unique(vids, return_index=True)
    return uniq.astype(np.int32), owners[first].astype(np.int32)


def lane_verdict(
    cfg: SimConfig,
    final: simm.SimState,
    expected: np.ndarray,
    owner_node: np.ndarray,
    vid_cap: int | None = None,
    geom=None,
) -> LaneVerdict:
    """Judge one (unbatched) final engine state on device — the fleet
    runner vmaps this over the lane axis inside the same jit as the
    round loop, so the verdict costs no extra dispatch.

    ``expected``/``owner_node`` may be host numpy (static) or TRACED
    ``[V]`` arrays — the fleet's per-lane runtime workload tables.
    Traced callers must pass ``vid_cap`` (the static bitmap bound,
    the envelope's vid space) and may pad unused slots with ``-1``:
    padded slots are vacuously covered, so lanes with fewer distinct
    vids than the envelope's table width judge correctly."""
    learned = final.learned  # [A, I]
    known = learned != val.NONE
    # agreement: every knowing node matches the max over knowing nodes
    best = jnp.max(jnp.where(known, learned, jnp.iinfo(jnp.int32).min), axis=0)
    agreement = ~jnp.any(known & (learned != best[None]))

    # coverage via a chosen-membership bitmap (vid_cap is the static
    # bitmap bound; derived here only for concrete host arrays)
    chosen = final.met.chosen_vid  # [I]
    if vid_cap is None:
        expected = np.asarray(expected)
        vid_cap = int(expected.max()) + 1 if expected.size else 1
    bitmap = jnp.zeros((vid_cap,), jnp.bool_).at[
        jnp.where(chosen >= 0, chosen, vid_cap)
    ].set(True, mode="drop")
    exp = jnp.asarray(expected, jnp.int32)
    own = jnp.asarray(owner_node, jnp.int32)
    valid = exp >= 0  # [V]; False = table padding, vacuously covered
    owner_crashed = final.crashed[jnp.clip(own, 0, cfg.n_nodes - 1)]  # [V]
    covered = bitmap[jnp.clip(exp, 0, vid_cap - 1)]
    coverage = jnp.all(~valid | covered | owner_crashed)

    if geom is None:
        pn = jnp.asarray(cfg.proposers, jnp.int32)
        all_props_crashed = jnp.all(final.crashed[pn])
    else:
        # padded lanes: pad proposer slots gather node 0 through the
        # pn 0-padding — count them as vacuously crashed so only TRUE
        # proposers can excuse a non-quiescent lane
        all_props_crashed = jnp.all(final.crashed[geom.pn] | ~geom.prop_mask)
    quiescent = final.done | all_props_crashed

    max_round = jnp.max(
        jnp.where(chosen != val.NONE, final.met.chosen_round, -1)
    )
    ok = agreement & coverage & quiescent
    return LaneVerdict(
        ok=ok,
        agreement=agreement,
        coverage=coverage,
        quiescent=quiescent,
        rounds=final.t,
        max_round=max_round,
    )
