"""The certified selection loop: mutate-and-select wedge hunting over
fleet, membership, and serve lanes.

``fleet/search.py`` samples schedules blind — every generation is a
fresh i.i.d. draw from the grammar.  This module closes ROADMAP item
1's loop: a population is a ``[lanes]`` stack of GENOMES (fault
schedules, per-edge WAN knob matrices, churn-event tables, per-tenant
arrival plans under weather presets), one generation is ONE fleet
dispatch through the shared envelope cache (zero warm compiles after
generation 0 — census-pinned), fitness is the climbing signal the
flight recorder already emits, and selection/mutation/crossover
operate on the SAME grammar samplers ``search`` draws from (the
shared :class:`~tpu_paxos.fleet.search.Alphabet` — the two samplers
cannot drift):

- **fleet axis** — fitness is the per-lane minimum stall margin
  (``telemetry/recorder.lane_stall_margins``): the tightest liveness
  headroom each genome reached.  Lower is fitter; a flagged lane (the
  on-device verdict subset, plus the optional synthetic
  ``decision_round_max`` bound) dominates everything.
- **member axis** — genomes carry a churn schedule
  (``search.sample_churn_schedule``) plus a member-legal fault
  schedule; fitness is rounds-to-finish (slower = closer to a stall),
  a red member verdict dominates.  Recall is measured against the
  302-scenario ``churn`` mc-scope denominator.
- **serve axis** — genomes are offered-load shapes under quantized
  weather presets (``serve/breach.py``); fitness is the windowed SLO
  burn rate, a breaching lane dominates, and the breach verdict
  carries the judge's diagnosis.

``diagnose.py``'s stable cause labels make the hunt CAUSE-TARGETED:
``--hunt gray-region`` biases mutation's episode draws toward the
gene families that produce that label (:data:`CAUSE_FAMILIES`) and
bonuses lanes whose own windowed series showed it (per-lane
attribution via ``search.lane_cause_series`` — the aggregate series
would credit the wrong genome).

Every flagged fleet lane re-derives single-run -> full judge ->
batched shrinker -> schema-closed artifact exactly like ``search``.
Recall is CERTIFIED (``--certified``): with
``TPU_PAXOS_SEEDED_WEDGE=takeover`` armed, the loop must find AND
shrink the wedge within <= 1/4 of the exhaustive quick-scope lane
budget — the denominator is read from ``mc_certificate.json``
(``scenarios_reduced``), never hard-coded — the shrunk artifact must
replay byte-identically, and warm compiles must be zero; the
BENCH_evolve.json record is withheld on any guard failure.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import importlib
import json
import os
import sys
import time

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as fltm
from tpu_paxos.fleet import search as srch

#: Cause label -> the episode-kind families whose genes produce it
#: (the mutation bias table).  Keys are diagnose.CAUSES members; the
#: mapping is part of the hunt contract (tests/test_evolve.py) —
#: appending a family is additive, existing entries never move.
CAUSE_FAMILIES = {
    "gray-region": ("gray",),
    "partition": ("partition", "one_way"),
    "duel-churn": ("pause", "crash"),
    "saturation": ("burst",),
}

#: Hunted-family draw odds: a biased episode draw lands inside the
#: hunted family HUNT_BIAS times out of HUNT_BIAS + 1.
HUNT_BIAS = 4

#: Fraction of the population carried verbatim into the next
#: generation (at least one).
ELITE_FRAC = 0.25

#: Fraction of each generation replaced by FRESH grammar draws
#: (hunt-biased).  Pure mutate-and-select collapses onto the gen-0
#: lineages within a few generations — local moves around non-wedge
#: schedules rarely assemble a multi-episode interplay (the takeover
#: wedge needs a pause AND a crash in one schedule) — so the loop
#: keeps the blind sampler's full-draw coverage as an exploration
#: floor and lets selection climb the near-misses on top of it.
IMMIGRANT_FRAC = 0.25

#: Fitness dominance offsets (margin units): a genuinely flagged lane
#: must outrank every near-miss, and a hunted-cause sighting must
#: outrank an equal margin without one.
WEDGE_BONUS = 1_000_000.0
CAUSE_BONUS = 1_000.0

#: certificate scope whose ``scenarios_reduced`` is the recall
#: denominator, per axis (serve has no exhaustive twin — no budget).
BUDGET_SCOPES = {"fleet": "quick", "member": "churn"}

#: the certified-recall contract: evolve must find the wedge within
#: scenarios_reduced // BUDGET_DIV lanes.
BUDGET_DIV = 4

#: engine-scope label per axis (tracecount.engine_scope) — the warm-
#: compile census reads these.
ENGINE_SCOPES = {"fleet": "fleet", "member": "member", "serve": "serve_fleet"}

# module-level census singleton (jax.monitoring has no listener-
# removal API — same pattern as analysis/mc_member._mc_census)
_evolve_census = None


@dataclasses.dataclass(frozen=True)
class Genome:
    """One fleet/member individual: a fault schedule, an engine seed,
    and the optional WAN knob-matrix / churn-table genes."""

    schedule: fltm.FaultSchedule
    seed: int
    knobs: FaultConfig | None = None
    churn: object | None = None  # membership ChurnSchedule | None

    def to_dict(self) -> dict:
        d: dict = {
            "seed": int(self.seed),
            "schedule": self.schedule.to_dict(),
        }
        if self.knobs is not None:
            # EdgeFaultConfig canonicalizes rows to int tuples, so
            # asdict is already JSON-stable
            d["knobs"] = dataclasses.asdict(self.knobs)
        if self.churn is not None:
            d["churn"] = [
                {"vid": int(e.vid), "t0": int(e.t0), "wait": int(e.wait)}
                for e in self.churn.events
            ]
        return d


def _genome_dict(g) -> dict:
    return g.to_dict() if hasattr(g, "to_dict") else dataclasses.asdict(g)


def population_sha(pop) -> str:
    """sha256 over the population's stable JSON — the elitism-
    determinism pin (same seed -> same population, byte-for-byte)."""
    text = json.dumps([_genome_dict(g) for g in pop], sort_keys=True)
    return hashlib.sha256(text.encode()).hexdigest()


def hunt_kinds(alphabet: srch.Alphabet, hunt: str | None) -> tuple:
    """The hunted cause's episode-kind family, intersected with the
    alphabet (empty tuple = no bias)."""
    fam = CAUSE_FAMILIES.get(hunt or "", ())
    return tuple(k for k in fam if k in alphabet.kinds)


def draw_episode(
    rng, alphabet: srch.Alphabet, n_nodes: int,
    crashed=frozenset(), hunt: str | None = None,
):
    """One mutation-step episode draw: with a hunt armed, the draw
    lands inside the hunted family HUNT_BIAS/(HUNT_BIAS+1) of the
    time (kind drawn first, then the alphabet's episode sampler runs
    narrowed to it — the unbiased path consumes the identical draw
    sequence as ``Alphabet.sample_episode``)."""
    fam = hunt_kinds(alphabet, hunt)
    if fam and int(rng.integers(0, HUNT_BIAS + 1)):
        kind = fam[int(rng.integers(0, len(fam)))]
        return alphabet.sample_episode(
            rng, n_nodes, crashed=crashed, kinds=(kind,)
        )
    return alphabet.sample_episode(rng, n_nodes, crashed=crashed)


def fresh_schedule(
    rng, alphabet: srch.Alphabet, n_nodes: int,
    hunt: str | None = None, protected=frozenset(),
) -> fltm.FaultSchedule:
    """An immigrant's schedule: a full grammar draw, with one episode
    spliced to the hunted family when the draw carried none of it (a
    ``--hunt`` immigrant always brings at least one hunted gene)."""
    sched = alphabet.sample(rng, n_nodes)
    fam = hunt_kinds(alphabet, hunt)
    if fam and not any(e.kind in fam for e in sched.episodes):
        eps = list(sched.episodes)
        crashed = frozenset(protected) | {
            int(n) for e in eps if e.kind == "crash" for n in e.nodes
        }
        kind = fam[int(rng.integers(0, len(fam)))]
        eps[int(rng.integers(0, len(eps)))] = alphabet.sample_episode(
            rng, n_nodes, crashed=crashed, kinds=(kind,)
        )
        eps = legal_episodes(eps, n_nodes, protected=protected)
        if eps:
            sched = fltm.FaultSchedule(tuple(eps))
    return sched


def legal_episodes(eps, n_nodes: int, protected=frozenset()) -> tuple:
    """Re-impose the sampler's crash discipline on a spliced/crossed
    episode list: scheduled crashes keep the TOTAL crashed set a
    minority (majority-crash = no quorum = every lane reds vacuously)
    and never hit ``protected`` nodes (the member axis's driver node
    and churn targets).  Offending crash episodes drop; everything
    else passes through in order."""
    out: list = []
    crashed: set = set()
    cap = (n_nodes - 1) // 2
    for e in eps:
        if e.kind == "crash":
            nodes = set(int(x) for x in e.nodes)
            if nodes & set(protected):
                continue
            if len(crashed | nodes) > cap:
                continue
            crashed |= nodes
        out.append(e)
    return tuple(out)


def jitter_episode(rng, e, horizon: int):
    """Shift one episode's interval by a quantized delta (the
    episode-interval jitter move), width preserved, clipped inside
    ``[0, horizon]``."""
    step = max(1, horizon // srch.CRASH_GRID)
    delta = (int(rng.integers(0, 5)) - 2) * step
    width = max(int(e.t1) - int(e.t0), 1)
    t0 = min(max(int(e.t0) + delta, 0), max(horizon - width, 0))
    return dataclasses.replace(e, t0=t0, t1=t0 + width)


def mutate_schedule(
    rng, sched: fltm.FaultSchedule, alphabet: srch.Alphabet,
    n_nodes: int, hunt: str | None = None, protected=frozenset(),
) -> fltm.FaultSchedule:
    """One schedule mutation: splice (replace an episode with a fresh
    cause-biased draw), jitter (shift an interval), add, or drop —
    then the crash discipline re-applies."""
    eps = list(sched.episodes)
    move = int(rng.integers(0, 4))
    crashed = frozenset(protected) | {
        int(n) for e in eps if e.kind == "crash" for n in e.nodes
    }
    if move == 0 or not eps:  # splice
        j = int(rng.integers(0, max(len(eps), 1)))
        fresh = draw_episode(
            rng, alphabet, n_nodes, crashed=crashed, hunt=hunt
        )
        if eps:
            eps[j] = fresh
        else:
            eps.append(fresh)
    elif move == 1:  # jitter
        j = int(rng.integers(0, len(eps)))
        eps[j] = jitter_episode(rng, eps[j], alphabet.horizon)
    elif move == 2 and len(eps) < alphabet.max_episodes:  # add
        eps.append(
            draw_episode(rng, alphabet, n_nodes, crashed=crashed, hunt=hunt)
        )
    elif len(eps) > 1:  # drop
        eps.pop(int(rng.integers(0, len(eps))))
    out = legal_episodes(eps, n_nodes, protected=protected)
    if not out:
        out = (draw_episode(rng, alphabet, n_nodes, hunt=hunt),)
    return fltm.FaultSchedule(out)


def crossover_schedules(
    rng, a: fltm.FaultSchedule, b: fltm.FaultSchedule,
    alphabet: srch.Alphabet, n_nodes: int, protected=frozenset(),
) -> fltm.FaultSchedule:
    """Episode-list crossover: parent A's prefix + parent B's suffix
    at drawn split points, capped at the alphabet's episode bound,
    crash discipline re-applied (a legal child even when both parents
    carry crash genes)."""
    ea, eb = list(a.episodes), list(b.episodes)
    ka = int(rng.integers(0, len(ea) + 1))
    kb = int(rng.integers(0, len(eb) + 1))
    eps = (ea[:ka] + eb[kb:])[: alphabet.max_episodes]
    out = legal_episodes(eps, n_nodes, protected=protected)
    if not out:
        out = legal_episodes(ea, n_nodes, protected=protected) or (
            draw_episode(rng, alphabet, n_nodes),
        )
    return fltm.FaultSchedule(tuple(out))


def select(rng, pop, scores, make_child, make_fresh=None):
    """Elitist (mu+lambda)-style selection: rank ascending by score
    (ties break on lane index — fully deterministic), carry the elite
    fraction verbatim, fill the middle with children of parents drawn
    from the top half, and replace the tail with fresh immigrants
    (:data:`IMMIGRANT_FRAC`, when ``make_fresh`` is given) so the
    population never loses the blind sampler's coverage.
    Deterministic per rng stream: same seed -> same next population
    (pinned via :func:`population_sha`)."""
    n = len(pop)
    order = sorted(range(n), key=lambda i: (scores[i], i))
    n_elite = max(1, int(ELITE_FRAC * n))
    n_fresh = min(int(IMMIGRANT_FRAC * n), n - n_elite) if make_fresh else 0
    out = [pop[i] for i in order[:n_elite]]
    parents = order[: max(2, n // 2)]
    while len(out) < n - n_fresh:
        pa = pop[parents[int(rng.integers(0, len(parents)))]]
        pb = pop[parents[int(rng.integers(0, len(parents)))]]
        out.append(make_child(rng, pa, pb))
    while len(out) < n:
        out.append(make_fresh(rng))
    return out


def _census():
    global _evolve_census
    tracecount = importlib.import_module("tpu_paxos.analysis.tracecount")
    if _evolve_census is None:
        _evolve_census = tracecount.CompileCensus()
    return _evolve_census.start()


def _budget_lanes(axis: str, cert_path: str | None) -> tuple:
    """(budget_lanes | None, scope_name | None, denominator | None):
    the certified-recall lane budget — ``scenarios_reduced // 4``
    read LIVE from the mc certificate, never hard-coded."""
    scope = BUDGET_SCOPES.get(axis)
    if scope is None:
        return None, None, None
    mc = importlib.import_module("tpu_paxos.analysis.modelcheck")
    certs = mc.load_certificates(
        *( (cert_path,) if cert_path else () )
    )
    cert = certs.get(scope)
    if not cert or "scenarios_reduced" not in cert:
        return None, scope, None
    denom = int(cert["scenarios_reduced"])
    return denom // BUDGET_DIV, scope, denom


# ---------------------------------------------------------------
# fleet axis
# ---------------------------------------------------------------


def _evolve_fleet(
    n_lanes, generations, base_seed, alphabet, hunt, certified,
    budget, triage_dir, decision_round_max, n_nodes, n_prop,
    fault_kw, max_wedges, mesh, logger,
):
    from tpu_paxos.core.sim import IDLE_RESTART_ROUNDS
    from tpu_paxos.fleet import envelope as env
    from tpu_paxos.fleet import runner as frun
    from tpu_paxos.harness import shrink as shr
    from tpu_paxos.telemetry import recorder as telem

    strs = importlib.import_module("tpu_paxos.harness.stress")
    wl_rng = np.random.default_rng(base_seed)
    workload, gates, chains = strs._workload(n_prop, wl_rng)
    protocol = alphabet.protocol()
    fault_kw = dict(
        fault_kw or dict(drop_rate=300, dup_rate=500, max_delay=2)
    )
    cfg = SimConfig(
        n_nodes=n_nodes,
        n_instances=2 * sum(len(w) for w in workload),
        proposers=tuple(range(n_prop)),
        seed=base_seed,
        max_rounds=20_000,
        faults=FaultConfig(**fault_kw),
        **({"protocol": protocol} if protocol is not None else {}),
    )
    runner = env.runner_for(
        cfg, workload, gates, mesh=mesh,
        max_episodes=max(alphabet.max_episodes, frun.MAX_EPISODES),
        telemetry=True,
    )
    lane_workloads = [(workload, gates)] * n_lanes
    extra = (
        {"decision_round_max": int(decision_round_max)}
        if decision_round_max else {}
    )
    scope = ENGINE_SCOPES["fleet"]
    census = _census()

    # generation 0: fresh grammar draws (every gene a sample)
    rng0 = np.random.default_rng((base_seed, 0, 11))
    pop = [
        Genome(
            schedule=alphabet.sample(rng0, n_nodes),
            seed=int(rng0.integers(0, 1 << 16)),
            knobs=(
                srch.sample_edge_knobs(
                    rng0, n_nodes, runner.delay_bound,
                    base_drop=cfg.faults.drop_rate,
                )
                if alphabet.wan else None
            ),
        )
        for _ in range(n_lanes)
    ]

    lanes_total = 0
    first_find = None
    first_shrunk = None
    first_artifact = None
    wedges: list = []
    anomalies: list = []
    gen_summaries: list = []
    compiles_per_gen: list = []
    for g in range(generations):
        if (
            certified and budget is not None
            and lanes_total + n_lanes > budget
        ):
            logger.info(
                "certified lane budget (%d) would be exceeded; stopping",
                budget,
            )
            break
        before = census.engine_counts.get(scope, 0)
        rep = runner.run(
            [gn.seed for gn in pop],
            [gn.schedule for gn in pop],
            workloads=lane_workloads,
            knobs=[gn.knobs or cfg.faults for gn in pop],
        )
        compiles_per_gen.append(
            census.engine_counts.get(scope, 0) - before
        )
        lanes_total += n_lanes
        real_flagged = set(rep.failing)
        flagged = set(real_flagged)
        if decision_round_max is not None:
            flagged |= {
                i for i in range(n_lanes)
                if int(rep.verdict.max_round[i]) > decision_round_max
            }
        # fitness: per-lane minimum stall margin (LOWER = fitter),
        # flagged lanes dominate, hunted-cause sightings bonus
        ws = getattr(rep, "windows", None)
        if ws is not None:
            margins = telem.lane_stall_margins(ws, IDLE_RESTART_ROUNDS)
        else:
            margins = [0.0] * n_lanes
        scores = [float(m) for m in margins]
        lane_causes = (
            srch.lane_cause_series(rep, range(n_lanes)) if hunt else {}
        )
        for i in range(n_lanes):
            if hunt and hunt in (lane_causes.get(i) or []):
                scores[i] -= CAUSE_BONUS
            if i in flagged:
                scores[i] -= WEDGE_BONUS
        logger.info(
            "generation %d: %d lanes, %d flagged (%.1f lanes/sec)",
            g, n_lanes, len(flagged), rep.lanes_per_sec,
        )
        gen_summaries.append({
            "generation": g,
            "lanes": n_lanes,
            "flagged": len(flagged),
            "best_margin": min(margins) if margins else None,
            "margins": srch._generation_margins(rep, flagged=flagged),
        })
        for i in sorted(flagged):
            if len(wedges) >= max_wedges:
                break
            case = shr.ReproCase(
                cfg=rep.lane_cfg(i), workload=workload, gates=gates,
                chains=chains,
                extra_checks={} if i in real_flagged else dict(extra),
            )
            _, viol = shr.run_case(case)
            if viol is None:
                anomalies.append({
                    "generation": g, "lane": i, "seed": rep.seeds[i],
                    "verdict": {
                        f: bool(getattr(rep.verdict, f)[i])
                        for f in ("ok", "agreement", "coverage",
                                  "quiescent")
                    },
                })
                continue
            if first_find is None:
                first_find = lanes_total
            wedge = {
                "generation": g,
                "lane": i,
                "seed": rep.seeds[i],
                "violation": viol[:300],
                "synthetic": "decision_round_max" in (viol or ""),
                "schedule": rep.schedules[i].to_dict(),
            }
            if triage_dir:
                os.makedirs(triage_dir, exist_ok=True)
                path = os.path.join(
                    triage_dir, f"repro_evolve_g{g}_lane{i}.json"
                )
                try:
                    art = shr.triage(case, path, logger=logger)
                    wedge["artifact"] = path
                    wedge["shrink_seconds"] = art.get("shrink_seconds")
                    wedge["shrink_evals"] = art.get("shrink_evals")
                    if first_shrunk is None:
                        # the certified accounting: fleet lanes spent
                        # to the find PLUS the shrinker's candidate
                        # evaluations (each one lane of its batched
                        # dispatches)
                        first_shrunk = lanes_total + int(
                            art.get("shrink_evals", 0)
                        )
                        first_artifact = path
                    logger.info("wedge shrunk -> %s", path)
                except Exception as te:
                    wedge["triage_error"] = str(te)[:300]
            wedges.append(wedge)
        if certified and first_shrunk is not None:
            logger.info("certified find complete; stopping early")
            break
        if len(wedges) >= max_wedges and not certified:
            logger.info("wedge budget (%d) reached", max_wedges)
            break
        # next generation
        rng_g = np.random.default_rng((base_seed, g + 1, 11))

        def child(rng, pa, pb):
            sched = crossover_schedules(
                rng, pa.schedule, pb.schedule, alphabet, n_nodes
            )
            sched = mutate_schedule(
                rng, sched, alphabet, n_nodes, hunt=hunt
            )
            seed = (
                pa.seed if int(rng.integers(0, 2))
                else int(rng.integers(0, 1 << 16))
            )
            knobs = None
            if alphabet.wan:
                knobs = (
                    pa.knobs if int(rng.integers(0, 2))
                    else srch.sample_edge_knobs(
                        rng, n_nodes, runner.delay_bound,
                        base_drop=cfg.faults.drop_rate,
                    )
                )
            return Genome(schedule=sched, seed=seed, knobs=knobs)

        def fresh(rng):
            return Genome(
                schedule=fresh_schedule(rng, alphabet, n_nodes, hunt=hunt),
                seed=int(rng.integers(0, 1 << 16)),
                knobs=(
                    srch.sample_edge_knobs(
                        rng, n_nodes, runner.delay_bound,
                        base_drop=cfg.faults.drop_rate,
                    )
                    if alphabet.wan else None
                ),
            )

        pop = select(rng_g, pop, scores, child, make_fresh=fresh)
    return {
        "pop": pop,
        "lanes_total": lanes_total,
        "first_find": first_find,
        "first_shrunk": first_shrunk,
        "first_artifact": first_artifact,
        "wedges": wedges,
        "anomalies": anomalies,
        "generation_telemetry": gen_summaries,
        "compiles_per_generation": compiles_per_gen,
    }


# ---------------------------------------------------------------
# member axis
# ---------------------------------------------------------------


def _evolve_member(
    n_lanes, generations, base_seed, alphabet, hunt, certified,
    budget, triage_dir, n_nodes, n_instances, max_rounds,
    max_wedges, logger,
):
    from tpu_paxos.fleet import envelope as env

    alphabet = alphabet.member()
    runner = env.member_runner_for(
        n_nodes, n_instances,
        max_episodes=max(alphabet.max_episodes, 2),
        max_rounds=max_rounds,
    )
    scope = ENGINE_SCOPES["member"]
    census = _census()
    horizon = min(alphabet.horizon, max_rounds)
    alphabet = dataclasses.replace(alphabet, horizon=horizon)

    def fresh(rng):
        churn = srch.sample_churn_schedule(rng, n_nodes, horizon=horizon)
        return Genome(
            schedule=srch.sample_member_schedule(
                rng, n_nodes, churn=churn,
                max_episodes=alphabet.max_episodes, horizon=horizon,
                kinds=alphabet.kinds,
            ),
            seed=int(rng.integers(0, 1 << 16)),
            churn=churn,
        )

    rng0 = np.random.default_rng((base_seed, 0, 13))
    pop = [fresh(rng0) for _ in range(n_lanes)]

    lanes_total = 0
    first_find = None
    wedges: list = []
    gen_summaries: list = []
    compiles_per_gen: list = []
    for g in range(generations):
        if (
            certified and budget is not None
            and lanes_total + n_lanes > budget
        ):
            logger.info(
                "certified lane budget (%d) would be exceeded; stopping",
                budget,
            )
            break
        before = census.engine_counts.get(scope, 0)
        rep = runner.run(
            [gn.seed for gn in pop],
            [gn.churn for gn in pop],
            [gn.schedule for gn in pop],
        )
        compiles_per_gen.append(
            census.engine_counts.get(scope, 0) - before
        )
        lanes_total += n_lanes
        v = rep.verdict
        flagged = set(rep.failing)
        # fitness: MORE rounds = closer to a stall (the round budget
        # is the liveness patience here); red lanes dominate
        scores = [-float(v.rounds[i]) for i in range(n_lanes)]
        for i in sorted(flagged):
            scores[i] -= WEDGE_BONUS
        logger.info(
            "member generation %d: %d lanes, %d flagged "
            "(%.1f lanes/sec)",
            g, n_lanes, len(flagged), rep.lanes_per_sec,
        )
        gen_summaries.append({
            "generation": g,
            "lanes": n_lanes,
            "flagged": len(flagged),
            "rounds_max": int(np.max(v.rounds)) if n_lanes else None,
        })
        for i in sorted(flagged):
            if len(wedges) >= max_wedges:
                break
            if first_find is None:
                first_find = lanes_total
            log_text = rep.lane_log(i)
            cx = {
                "generation": g,
                "lane": i,
                "seed": rep.seeds[i],
                "churn": _genome_dict(pop[i]).get("churn"),
                "schedule": pop[i].schedule.to_dict(),
                "verdict": {
                    "quorum": bool(v.quorum[i]),
                    "catchup": bool(v.catchup[i]),
                    "coverage": bool(v.coverage[i]),
                    "completed": bool(v.completed[i]),
                    "rounds": int(v.rounds[i]),
                },
                "decision_log_sha256": hashlib.sha256(
                    log_text.encode()
                ).hexdigest(),
            }
            if triage_dir:
                os.makedirs(triage_dir, exist_ok=True)
                path = os.path.join(
                    triage_dir, f"evolve_member_g{g}_lane{i}.json"
                )
                with open(path, "w") as f:
                    json.dump(cx, f, indent=1, sort_keys=True)
                    f.write("\n")
                cx["artifact"] = path
            wedges.append(cx)
        if certified and first_find is not None:
            break
        if len(wedges) >= max_wedges and not certified:
            break
        rng_g = np.random.default_rng((base_seed, g + 1, 13))

        def child(rng, pa, pb):
            move = int(rng.integers(0, 4))
            churn = pa.churn
            if move == 0:  # fresh churn draw; schedule re-legalized
                churn = srch.sample_churn_schedule(
                    rng, n_nodes, horizon=horizon
                )
            protected = frozenset({0} | srch.churn_targets(churn))
            sched = crossover_schedules(
                rng, pa.schedule, pb.schedule, alphabet, n_nodes,
                protected=protected,
            )
            if move != 1:  # 1 = crossover-only (inheritance move)
                sched = mutate_schedule(
                    rng, sched, alphabet, n_nodes, hunt=hunt,
                    protected=protected,
                )
            seed = (
                pa.seed if int(rng.integers(0, 2))
                else int(rng.integers(0, 1 << 16))
            )
            return Genome(schedule=sched, seed=seed, churn=churn)

        pop = select(rng_g, pop, scores, child, make_fresh=fresh)
    return {
        "pop": pop,
        "lanes_total": lanes_total,
        "first_find": first_find,
        "first_shrunk": first_find,  # no shrinker on the member axis
        "first_artifact": None,
        "wedges": wedges,
        "anomalies": [],
        "generation_telemetry": gen_summaries,
        "compiles_per_generation": compiles_per_gen,
    }


# ---------------------------------------------------------------
# serve axis
# ---------------------------------------------------------------


def _serve_workload(n_prop: int) -> list:
    """The serve axis's fixed per-tenant vid streams (the genome is
    the LOAD SHAPE, not the values): 10 vids per proposer stream,
    disjoint ranges."""
    return [
        np.arange(20 * t, 20 * t + 10, dtype=np.int32)
        for t in range(n_prop)
    ]


def _evolve_serve(
    n_lanes, generations, base_seed, hunt, triage_dir,
    max_wedges, logger, latency_rounds, budget_milli,
):
    brch = importlib.import_module("tpu_paxos.serve.breach")
    sh = importlib.import_module("tpu_paxos.serve.harness")

    workload = _serve_workload(2)
    cfg = SimConfig(
        n_nodes=3, n_instances=48, proposers=(0, 1), seed=base_seed,
        max_rounds=4000,
    )
    slo = sh.ServeSLO(
        latency_rounds=latency_rounds, budget_milli=budget_milli
    )
    scope = ENGINE_SCOPES["serve"]
    census = _census()
    names = brch.WEATHER_NAMES
    # fixed weather slots: lane i's preset never changes (the preset
    # IS the envelope; per-slot lane counts are compile shapes)
    slot_of = [names[i * len(names) // n_lanes] for i in range(n_lanes)]
    rng0 = np.random.default_rng((base_seed, 0, 17))
    pop = [
        brch.sample_serve_genome(rng0, workload, slot_of[i], hunt=hunt)
        for i in range(n_lanes)
    ]
    admit_width = max(len(w) for w in workload)

    lanes_total = 0
    first_find = None
    breaches: list = []
    gen_summaries: list = []
    compiles_per_gen: list = []
    for g in range(generations):
        before = census.engine_counts.get(scope, 0)
        ev = brch.evaluate(
            cfg, pop, workload, slo=slo, admit_width=admit_width
        )
        compiles_per_gen.append(
            census.engine_counts.get(scope, 0) - before
        )
        lanes_total += n_lanes
        # fitness: max windowed burn (HIGHER = fitter); breaching
        # lanes dominate, hunted-cause diagnoses bonus
        scores = [-float(b) for b in ev["burn"]]
        for i in range(n_lanes):
            if hunt and hunt in ev["causes"].get(i, []):
                scores[i] -= CAUSE_BONUS
            if ev["breach"][i]:
                scores[i] -= WEDGE_BONUS
        flagged = [i for i in range(n_lanes) if ev["breach"][i]]
        logger.info(
            "serve generation %d: %d lanes, %d breached "
            "(burn max %.3f)",
            g, n_lanes, len(flagged), max(ev["burn"] or [0.0]),
        )
        gen_summaries.append({
            "generation": g,
            "lanes": n_lanes,
            "flagged": len(flagged),
            "burn_max": max(ev["burn"] or [0.0]),
        })
        for i in flagged:
            if len(breaches) >= max_wedges:
                break
            causes = ev["causes"].get(i, [])
            if first_find is None and (hunt is None or hunt in causes):
                first_find = lanes_total
            rec = {
                "generation": g,
                "lane": i,
                "genome": _genome_dict(pop[i]),
                "burn": float(ev["burn"][i]),
                "causes": causes,
            }
            if triage_dir:
                os.makedirs(triage_dir, exist_ok=True)
                path = os.path.join(
                    triage_dir, f"evolve_serve_g{g}_lane{i}.json"
                )
                with open(path, "w") as f:
                    json.dump(
                        dict(rec, verdict=ev["verdicts"].get(i)),
                        f, indent=1, sort_keys=True, default=str,
                    )
                    f.write("\n")
                rec["artifact"] = path
            breaches.append(rec)
        if len(breaches) >= max_wedges:
            break
        rng_g = np.random.default_rng((base_seed, g + 1, 17))

        def child(rng, pa, pb):
            # per-tenant gene mix (weather slots must match — select
            # runs per slot below, so they always do)
            ks = tuple(
                (pa if int(rng.integers(0, 2)) else pb).kinds[t]
                for t in range(len(pa.kinds))
            )
            rs = tuple(
                (pa if int(rng.integers(0, 2)) else pb).rates[t]
                for t in range(len(pa.rates))
            )
            g2 = dataclasses.replace(pa, kinds=ks, rates=rs)
            return brch.mutate_serve_genome(rng, g2, hunt=hunt)

        # selection runs PER WEATHER SLOT: slot sizes are compile
        # shapes, and crossover across presets would move a genome's
        # envelope
        nxt = list(pop)
        for name in names:
            idx = [i for i in range(n_lanes) if slot_of[i] == name]
            if not idx:
                continue
            sub = select(
                rng_g, [pop[i] for i in idx], [scores[i] for i in idx],
                child,
                make_fresh=lambda rng, name=name: brch.sample_serve_genome(
                    rng, workload, name, hunt=hunt
                ),
            )
            for i, gn in zip(idx, sub):
                nxt[i] = gn
        pop = nxt
    return {
        "pop": pop,
        "lanes_total": lanes_total,
        "first_find": first_find,
        "first_shrunk": first_find,  # no shrinker on the serve axis
        "first_artifact": None,
        "wedges": breaches,
        "anomalies": [],
        "generation_telemetry": gen_summaries,
        "compiles_per_generation": compiles_per_gen,
    }


# ---------------------------------------------------------------
# the loop
# ---------------------------------------------------------------


def evolve(
    axis: str = "fleet",
    n_lanes: int = 8,
    generations: int = 4,
    base_seed: int = 0,
    hunt: str | None = None,
    certified: bool = False,
    triage_dir: str | None = None,
    decision_round_max: int | None = None,
    n_nodes: int = 5,
    n_prop: int = 2,
    fault_kw: dict | None = None,
    max_wedges: int = 4,
    mesh=None,
    verbose: bool = True,
    gray: bool = False,
    wan: bool = False,
    alphabet: srch.Alphabet | None = None,
    cert_path: str | None = None,
    member_nodes: int = 3,
    member_instances: int = 8,
    member_rounds: int = 200,
    serve_latency_rounds: int = 8,
    serve_budget_milli: int = 200,
) -> dict:
    """Run the mutate-and-select loop on one axis; returns the
    JSON-ready summary.  ``certified`` flips the exit semantics: the
    run is ok IFF the hunt found (and, on the fleet axis, shrank) a
    wedge within the certificate-derived lane budget, the artifact
    replays byte-identically, and warm compiles are zero."""
    from tpu_paxos.utils import log as logm

    if axis not in ("fleet", "member", "serve"):
        raise ValueError(f"unknown axis {axis!r}")
    if hunt is not None:
        diag = importlib.import_module("tpu_paxos.telemetry.diagnose")
        if hunt not in diag.CAUSES:
            raise ValueError(
                f"unknown hunt cause {hunt!r} "
                f"(known: {', '.join(diag.CAUSES)})"
            )
    logger = logm.get_logger(
        "evolve", logm.parse_level("INFO" if verbose else "WARN")
    )
    if alphabet is None:
        alphabet = srch.Alphabet.classic(gray=gray, wan=wan)
    budget, budget_scope, denom = _budget_lanes(axis, cert_path)
    if certified and budget is None:
        raise ValueError(
            f"--certified needs a '{BUDGET_SCOPES.get(axis)}' mc "
            "certificate (run make mc-quick / the churn scope first)"
        )
    t0 = time.perf_counter()  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
    if axis == "fleet":
        r = _evolve_fleet(
            n_lanes, generations, base_seed, alphabet, hunt,
            certified, budget, triage_dir, decision_round_max,
            n_nodes, n_prop, fault_kw, max_wedges, mesh, logger,
        )
    elif axis == "member":
        r = _evolve_member(
            n_lanes, generations, base_seed, alphabet, hunt,
            certified, budget, triage_dir, member_nodes,
            member_instances, member_rounds, max_wedges, logger,
        )
    else:
        r = _evolve_serve(
            n_lanes, generations, base_seed, hunt, triage_dir,
            max_wedges, logger, serve_latency_rounds,
            serve_budget_milli,
        )
    seconds = time.perf_counter() - t0  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
    compiles = r["compiles_per_generation"]
    warm = sum(compiles[1:]) if len(compiles) > 1 else 0
    replay_match = None
    if r["first_artifact"] is not None:
        from tpu_paxos.harness import shrink as shr

        replay_match = bool(shr.reproduce(r["first_artifact"])["match"])
    found_in_budget = (
        r["first_shrunk"] is not None
        and (budget is None or r["first_shrunk"] <= budget)
    )
    if certified:
        cert_ok = (
            found_in_budget
            and warm == 0
            and (replay_match is None or replay_match)
            # the fleet axis MUST have a replayable artifact
            and (axis != "fleet" or replay_match is True)
        )
    else:
        cert_ok = None
    real = [w for w in r["wedges"] if not w.get("synthetic", False)]
    if certified:
        ok = bool(cert_ok)
    else:
        ok = not real and not r["anomalies"]
    return {
        "metric": "evolve",
        "axis": axis,
        "hunt": hunt,
        "base_seed": base_seed,
        "lanes": n_lanes,
        "generations_run": len(compiles),
        "lanes_total": r["lanes_total"],
        "lanes_per_sec": round(
            r["lanes_total"] / max(seconds, 1e-9), 2
        ),
        "seconds": round(seconds, 1),
        "budget_scope": budget_scope,
        "budget_denominator": denom,
        "budget_lanes": budget,
        "lanes_to_first_find": r["first_find"],
        "lanes_to_shrunk_artifact": r["first_shrunk"],
        "artifact": r["first_artifact"],
        "replay_match": replay_match,
        "compiles_per_generation": compiles,
        "warm_compiles": warm,
        "population_sha256": population_sha(r["pop"]),
        "wedges_found": len(r["wedges"]),
        "real_violations": len(real),
        "wedges": r["wedges"],
        "anomalies": r["anomalies"],
        "generation_telemetry": r["generation_telemetry"],
        "certified": cert_ok,
        "ok": ok,
    }


def bench_record(summary: dict, wedge_env: str) -> dict | None:
    """The BENCH_evolve.json record for one certified run — or None
    (WITHHELD) when any guard fails: the find must be inside the
    certificate budget, the artifact must replay byte-identically
    (fleet axis), and generations past the first must have compiled
    nothing."""
    if not summary.get("certified"):
        return None
    return {
        "metric": "evolve_recall",
        "axis": summary["axis"],
        "seeded_wedge": wedge_env,
        "hunt": summary["hunt"],
        "population": summary["lanes"],
        "base_seed": summary["base_seed"],
        "budget_scope": summary["budget_scope"],
        "budget_denominator": summary["budget_denominator"],
        "budget_lanes": summary["budget_lanes"],
        "lanes_to_first_find": summary["lanes_to_first_find"],
        "lanes_to_shrunk_artifact": summary["lanes_to_shrunk_artifact"],
        "replay_match": summary["replay_match"],
        "warm_compiles": summary["warm_compiles"],
        "generations_run": summary["generations_run"],
        "compiles_per_generation": summary["compiles_per_generation"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos evolve",
        description="mutate-and-select wedge hunting: evolve fault/"
        "churn/load genomes over fleet lanes, one dispatch per "
        "generation, certified recall against the mc certificate",
    )
    ap.add_argument("--axis", choices=("fleet", "member", "serve"),
                    default="fleet")
    ap.add_argument("--lanes", type=int, default=0,
                    help="population size (0 = backend default)")
    ap.add_argument("--generations", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--hunt", type=str, default="",
                    help="bias mutation toward the gene families that "
                    "produce this diagnose.py cause label")
    ap.add_argument("--certified", action="store_true",
                    help="certified-recall mode: ok iff the wedge is "
                    "found+shrunk within the mc-certificate lane "
                    "budget, replays byte-identically, and warm "
                    "compiles are zero")
    ap.add_argument("--bench-out", type=str, default="",
                    help="write the BENCH_evolve.json record here "
                    "(withheld unless every certified guard passes)")
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--proposers", type=int, default=2)
    ap.add_argument("--max-wedges", type=int, default=4)
    ap.add_argument("--decision-round-max", type=int, default=0,
                    help="flag lanes whose latest decision lands "
                    "after this round (synthetic wedge knob; 0 = off)")
    ap.add_argument("--gray", action="store_true")
    ap.add_argument("--wan", action="store_true")
    ap.add_argument("--triage-dir", type=str, default="")
    ap.add_argument("--cert-file", type=str, default="",
                    help="mc certificate path (default: the "
                    "committed analysis/mc_certificate.json)")
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--mesh", type=int, default=0)
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    _select_backend = importlib.import_module(
        "tpu_paxos.__main__"
    )._select_backend
    mesh = None
    if args.mesh:
        backend = "cpu" if args.backend == "auto" else args.backend
        _select_backend(backend, args.mesh)
        from tpu_paxos.parallel import mesh as pmesh

        mesh = pmesh.make_instance_mesh(args.mesh)
        if mesh.size != args.mesh:
            raise SystemExit(
                f"--mesh {args.mesh} requested but only {mesh.size} "
                "device(s) came up"
            )
    else:
        _select_backend(args.backend)
    from tpu_paxos.fleet import runner as frun
    n_lanes = args.lanes or frun.default_lane_count()
    if mesh is not None:
        n_lanes += (-n_lanes) % mesh.size
    summary = evolve(
        axis=args.axis,
        n_lanes=n_lanes,
        generations=args.generations,
        base_seed=args.seed,
        hunt=args.hunt or None,
        certified=args.certified,
        triage_dir=args.triage_dir or None,
        decision_round_max=args.decision_round_max or None,
        n_nodes=args.nodes,
        n_prop=args.proposers,
        max_wedges=args.max_wedges,
        mesh=mesh,
        verbose=not args.quiet,
        gray=args.gray,
        wan=args.wan,
        cert_path=args.cert_file or None,
    )
    if args.bench_out:
        wedge = os.environ.get("TPU_PAXOS_SEEDED_WEDGE", "")
        rec = bench_record(summary, wedge)
        if rec is None:
            print(
                "bench record WITHHELD: certified guards failed",
                file=sys.stderr,
            )
        else:
            with open(args.bench_out, "w") as f:
                json.dump(rec, f, indent=1, sort_keys=True)
                f.write("\n")
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
