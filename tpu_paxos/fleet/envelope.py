"""Envelope-keyed executable cache: ONE compiled fleet program per
stress envelope, shared by the stress sweep, the schedule search, and
the greedy shrinker.

An *envelope* is everything the compiled lane program actually bakes
in: the cluster geometry (nodes / proposers / instances), the
protocol knobs, the round budget, the queue/table shapes of the
workload template, the schedule-table episode capacity, the verdict's
vid space, and the DELAY RING BOUND (the arrival calendars are
statically sized to ``max_delay + 2`` slots).  Everything else — the
seed, the episode schedule, the i.i.d. fault knobs, and the workload
vids — is a runtime input of the cached executable
(``fleet/runner.FleetRunner`` built with ``runtime_schedule`` +
``runtime_knobs``).

``runner_for`` normalizes a caller's config onto its envelope
(schedule stripped, i.i.d. knobs zeroed, ``max_delay`` raised to the
ring bound) and memoizes one :class:`~tpu_paxos.fleet.runner.FleetRunner`
per distinct envelope key.  Distinct knob mixes, schedules, and
shrink candidates then cost dispatches, not compiles: all four stress
episode mixes share one (5-node, 2-proposer) envelope, a knob sweep
is a knob vector, and every greedy-shrink candidate of a case rides
the same executable its sweep compiled.

Cache discipline: the key pins the template's expected-vid/owner
TABLES and shapes, not its queue ORDER — callers that depend on a
specific queue order (everyone: decision logs are order-sensitive)
must pass explicit per-lane ``workloads=`` to ``run()`` rather than
relying on the cached runner's template queues.  The stress sweep,
the search, and the shrinker all do.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.fleet import runner as frun
from tpu_paxos.fleet import verdict as vdt

#: Default envelope delay-ring bound: covers every stress mix's
#: ``max_delay`` (the sweep peaks at 6) with headroom, so all mixes of
#: a geometry share one ring size — ring size is decision-log-neutral
#: (net.FaultKnobs docstring) and the [S, P, A] calendars are tiny.
MAX_DELAY_BOUND = 8

_CACHE: dict = {}


def clear_cache() -> None:
    """Drop every cached runner (tests; frees the compiled
    executables with them)."""
    _CACHE.clear()


def _mesh_key(mesh):
    if mesh is None:
        return None
    return (
        tuple(mesh.axis_names),
        tuple(int(d.id) for d in np.asarray(mesh.devices).reshape(-1)),
    )


def envelope_key(
    cfg: SimConfig,
    workload,
    gates,
    max_episodes: int,
    delay_bound: int,
    mesh,
    telemetry: bool = False,
    geometry=None,
) -> tuple:
    """The hashable envelope of a (cfg, workload-template) pair —
    exactly the static facts the compiled lane program depends on.
    ``telemetry`` is part of the key: arming the flight recorder is a
    different traced program (the recorder rides the loop carry).
    So is the seeded-wedge flag (core/sim.seeded_wedge): an armed
    build compiles the takeover OUT, and a cache hit across the flag
    would silently run the wrong engine.

    Under a ``geometry`` envelope (core/geom.GeometryEnvelope) the
    key COLLAPSES: ``cfg`` must already be the bound cfg, the menu
    replaces the per-geometry (n_nodes, proposers) facts, and the
    protocol-knob tuple drops out entirely (protocol knobs are traced
    per-dispatch data of the padded engine) — one warm executable
    then serves every (geometry on the menu x protocol mix)."""
    wl = [np.asarray(w, np.int32).reshape(-1) for w in workload]
    expected, owner = vdt.expected_owners(cfg, wl)
    gate_sig = (
        None if gates is None
        else tuple(len(np.asarray(g).reshape(-1)) for g in gates)
    )
    return (
        bool(telemetry),
        bool(cfg.faults.delivery_cut),  # compile-time engine flag
        simm.seeded_wedge(),
        cfg.n_nodes,
        cfg.proposers,
        cfg.n_instances,
        cfg.assign_window,
        cfg.max_rounds,
        (
            dataclasses.astuple(cfg.protocol)
            if geometry is None else "runtime-protocol"
        ),
        None if geometry is None else ("geom", geometry.menu),
        int(delay_bound),
        int(max_episodes),
        tuple(len(w) for w in wl),
        gate_sig,
        tuple(int(v) for v in expected),
        tuple(int(o) for o in owner),
        simm.gates_vid_cap(wl, gates),
        _mesh_key(mesh),
    )


def runner_for(
    cfg: SimConfig,
    workload,
    gates=None,
    *,
    max_episodes: int = frun.MAX_EPISODES,
    delay_bound: int | None = None,
    mesh=None,
    telemetry: bool = False,
    geometry=None,
) -> frun.FleetRunner:
    """The shared compiled runner for ``cfg``'s envelope.

    ``geometry`` (a core/geom.GeometryEnvelope) hands back the
    geometry-PADDED runner of the envelope bound: ``cfg`` may name any
    true geometry (it normalizes to ``geometry.bound_cfg``), the
    workload template pads to the proposer bound, and the cache key
    collapses over the menu and the protocol knobs — every tenant
    geometry <= the bound shares ONE warm executable.  Dispatch with
    ``run(geometry=(n_nodes, proposers), protocol=...)``.

    ``telemetry=True`` hands back the flight-recorder-armed twin of
    the envelope (its own cache slot: the recorder changes the traced
    program).  The stress sweep, the schedule search, and the shrink
    evaluator all arm it, so the whole runtime triage stack still
    shares ONE executable per geometry.

    ``cfg.faults`` is normalized away (the i.i.d. knobs and the
    schedule are runtime inputs of the returned runner — pass them to
    ``run()`` per lane); only ``cfg.faults.max_delay`` survives, as a
    floor on the ring bound.  Callers MUST pass explicit per-lane
    ``workloads=`` and ``knobs=`` to ``run()`` — the cache does not
    pin the template's queue order or the base knob mix (enforced:
    the returned runner rejects implicit inputs)."""
    if delay_bound is None:
        delay_bound = max(cfg.faults.max_delay, MAX_DELAY_BOUND)
    if cfg.faults.max_delay > delay_bound:
        raise ValueError(
            f"cfg max_delay {cfg.faults.max_delay} exceeds the "
            f"requested envelope delay bound {delay_bound}"
        )
    if geometry is not None:
        # normalize ONTO the envelope bound before keying: every true
        # geometry <= the bound lands on the same cache slot (the
        # bound cfg + the padded template are the compile facts; the
        # per-dispatch true geometry is menu-checked by run())
        if (
            cfg.n_nodes > geometry.bound_nodes
            or len(cfg.proposers) > geometry.bound_proposers
        ):
            raise ValueError(
                f"geometry ({cfg.n_nodes}, {cfg.proposers}) exceeds "
                f"the envelope geometry bound ({geometry.bound_nodes} "
                f"nodes, {geometry.bound_proposers} proposers)"
            )
        cfg = geometry.bound_cfg(cfg)
        workload, gates = frun._pad_geometry_workload(
            workload, gates, geometry.bound_proposers
        )
    key = envelope_key(
        cfg, workload, gates, max_episodes, delay_bound, mesh,
        telemetry=telemetry, geometry=geometry,
    )
    runner = _CACHE.get(key)
    if runner is None:
        base = dataclasses.replace(
            cfg, seed=0, faults=FaultConfig(
                max_delay=delay_bound,
                delivery_cut=cfg.faults.delivery_cut,
            )
        )
        runner = frun.FleetRunner(
            base, workload, gates, mesh=mesh, max_episodes=max_episodes,
            telemetry=telemetry, geometry=geometry,
        )
        # the MUST above is enforced: run() rejects implicit
        # workloads/knobs on cache-shared runners
        runner.explicit_inputs_only = True
        _CACHE[key] = runner
    return runner


def serve_envelope_key(
    cfg: SimConfig,
    queue_cap: int,
    vid_bound: int,
    rounds_per_window: int,
    window_rounds: int,
    mesh,
) -> tuple:
    """The hashable envelope of a serve FLEET — exactly the static
    facts the compiled multi-tenant dispatch window depends on: the
    cluster geometry, the protocol knobs, the compile-time i.i.d.
    fault mix (serve engines take no schedule and no runtime knobs —
    per-lane variation is arrivals/seeds/SLOs, all runtime data), the
    queue capacity + ingest-table vid bound, the admission-window
    span, the windowed-plane bucket width, and the device mesh.  Lane
    count, windows-per-dispatch, and admit width are CALL SHAPES of
    the cached callable, not key components — a whole
    (lanes x offered-rates) sweep shares one cached runner.

    The engine's compile-time facts come from the driver's ONE
    authoritative list (``serve/driver.engine_static_key`` — also
    ``window_for``'s key), so a new engine-build fact cannot land in
    one cache key and miss the other."""
    # importlib: keep the serve stack out of the replay-critical
    # import closure (see serve_fleet_for)
    import importlib

    sdrv = importlib.import_module("tpu_paxos.serve.driver")
    return (
        "serve",
        sdrv.engine_static_key(cfg),
        int(queue_cap),
        int(vid_bound),
        int(rounds_per_window),
        int(window_rounds),
        _mesh_key(mesh),
    )


def serve_fleet_for(
    cfg: SimConfig,
    queue_cap: int,
    vid_bound: int,
    rounds_per_window: int,
    *,
    window_rounds: int,
    mesh=None,
):
    """The shared compiled fleet-serving runner for this envelope
    (``serve/fleet.ServeFleetRunner``), memoized in the same cache
    the sim and membership envelopes share: every tenant mix, offered
    rate, and SLO declaration of a geometry then costs dispatches,
    not compiles (SLO thresholds are runtime inputs; lane count /
    admit width are call shapes)."""
    # importlib (the lazy-package idiom): the serve stack is NOT part
    # of the replay-critical import closure — a static import here
    # would pull serve's host harness (and its CLI imports) into the
    # DET lint scope via harness/shrink.py -> this module
    import importlib

    sflt = importlib.import_module("tpu_paxos.serve.fleet")

    if cfg.faults.schedule is not None:
        # checked HERE like serve/driver.window_for: the key ignores
        # the schedule, so a schedule-bearing cfg would otherwise HIT
        # a warm cache and silently drop its correlated faults
        raise ValueError(
            "serve engines take no fault schedule (correlated-fault "
            "serving rides the stress fleet envelope, not this driver)"
        )
    key = serve_envelope_key(
        cfg, queue_cap, vid_bound, rounds_per_window, window_rounds, mesh
    )
    runner = _CACHE.get(key)
    if runner is None:
        runner = sflt.ServeFleetRunner(
            cfg, queue_cap, vid_bound, rounds_per_window,
            window_rounds, mesh=mesh,
        )
        _CACHE[key] = runner
    return runner


def serve_control_for(
    cfg: SimConfig,
    queue_cap: int,
    vid_bound: int,
    rounds_per_window: int,
    *,
    window_rounds: int,
    mesh=None,
):
    """The shared compiled CONTROLLED fleet-serving runner for this
    envelope (``serve/control.ControlFleetRunner``) — the adaptive-
    admission twin of :func:`serve_fleet_for`, in the same shared
    cache under its own engine tag (the keep-mask program is a
    different traced function).  A controlled (lanes x rates) sweep
    then shares ONE executable per call shape: policies, priority
    tiers, and SLO thresholds are runtime data, so arming the
    controller costs dispatches, not compiles."""
    import importlib

    sctl = importlib.import_module("tpu_paxos.serve.control")

    if cfg.faults.schedule is not None:
        # checked HERE like serve_fleet_for: the key ignores the
        # schedule, so a schedule-bearing cfg would otherwise HIT a
        # warm cache and silently drop its correlated faults
        raise ValueError(
            "serve engines take no fault schedule (correlated-fault "
            "serving rides the stress fleet envelope, not this driver)"
        )
    key = (
        "serve_control",
        *serve_envelope_key(
            cfg, queue_cap, vid_bound, rounds_per_window,
            window_rounds, mesh,
        )[1:],
    )
    runner = _CACHE.get(key)
    if runner is None:
        runner = sctl.ControlFleetRunner(
            cfg, queue_cap, vid_bound, rounds_per_window,
            window_rounds, mesh=mesh,
        )
        _CACHE[key] = runner
    return runner


def member_envelope_key(
    n_nodes: int,
    n_instances: int,
    max_events: int,
    max_episodes: int,
    crash_rate: int,
    max_rounds: int,
    geometry=None,
) -> tuple:
    """The hashable envelope of a membership fleet — exactly the
    static facts the compiled churn-lane program depends on: the
    cluster geometry, the churn-table event capacity, the
    fault-schedule episode capacity, the i.i.d. crash rate (a traced
    draw's presence is a compile-time fact in the member engine), and
    the round budget.  Everything else — seeds, churn scenarios,
    episode mixes — is a runtime input of the cached executable.
    Under a ``geometry`` envelope the node count COLLAPSES to the
    menu: one warm churn executable per bound, the true node count a
    per-dispatch input."""
    return (
        "member",
        (
            int(n_nodes) if geometry is None
            else ("geom", geometry.menu)
        ),
        int(n_instances),
        int(max_events),
        int(max_episodes),
        int(crash_rate),
        int(max_rounds),
    )


def member_runner_for(
    n_nodes: int,
    n_instances: int,
    *,
    max_events: int | None = None,
    max_episodes: int = frun.MAX_EPISODES,
    crash_rate: int = 0,
    max_rounds: int = 2000,
    geometry=None,
):
    """The shared compiled membership-fleet runner for this envelope
    (``fleet/member_runner.MemberFleetRunner``), memoized in the same
    cache the sim envelopes share: distinct churn scenarios, episode
    mixes, and seeds then cost dispatches, not compiles.  With a
    ``geometry`` envelope, ``n_nodes`` may be any menu node count (it
    normalizes to the bound and is re-declared per dispatch:
    ``run(n_nodes=...)``) and every geometry on the menu shares ONE
    cached runner."""
    from tpu_paxos.fleet import member_runner as mrun
    from tpu_paxos.membership import churn_table as ctm

    if max_events is None:
        max_events = ctm.MAX_EVENTS
    if geometry is not None:
        geometry.index_of_nodes(n_nodes)  # named menu/bound rejection
        n_nodes = geometry.bound_nodes
    key = member_envelope_key(
        n_nodes, n_instances, max_events, max_episodes, crash_rate,
        max_rounds, geometry=geometry,
    )
    runner = _CACHE.get(key)
    if runner is None:
        runner = mrun.MemberFleetRunner(
            n_nodes, n_instances, max_events=max_events,
            max_episodes=max_episodes, crash_rate=crash_rate,
            max_rounds=max_rounds, geometry=geometry,
        )
        _CACHE[key] = runner
    return runner
