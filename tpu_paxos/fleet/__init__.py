"""Device-batched simulation fleets: hundreds of (seed x schedule)
lanes of the general engine per XLA dispatch, judged on device.

Submodules are lazily re-exported (PEP 562), mirroring ``core``:
``schedule_table`` is imported by ``core.sim`` when an engine is built
with runtime schedules, and that must not eagerly drag in the runner /
search stack (which imports the harness).
"""

_SUBMODULES = (
    "envelope", "evolve", "member_runner", "runner", "schedule_table",
    "search", "verdict",
)


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"tpu_paxos.fleet.{name}")
    raise AttributeError(f"module 'tpu_paxos.fleet' has no attribute {name!r}")
