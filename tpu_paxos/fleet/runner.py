"""Device-batched fleet runner: many (seed x schedule) lanes of the
general engine per XLA dispatch, judged on device.

``core/sim`` runs ONE simulation per host-loop iteration; the stress
sweep therefore pays a dispatch (and, per episode mix, a compile) per
seed.  The fleet instead ``vmap``s the engine's whole-run surface —
the ``lax.while_loop`` over ``round_fn`` that ``sim._run_loop``
drives — over a LANE axis of PRNG roots, initial states, and runtime
schedule tables (``fleet/schedule_table.py``), with the per-lane
invariant subset (``fleet/verdict.py``) reduced to a ``[lanes]``
verdict vector inside the same jit.  One compiled executable then
covers every (seed, episode-mix) combination of a fixed geometry, and
only failing lanes ever pay host transfer + the full
``harness/validate`` suite + the ``harness/shrink.py`` repro path.

Lane-for-lane the fleet is DECISION-LOG-IDENTICAL to single
``core/sim.run`` executions of the same (cfg, schedule, seed):
``jax_threefry_partitionable`` (pinned in utils/prng) makes the
batched PRNG draws equal the per-lane draws, and the runtime mask
computation equals the compiled tables row for row
(tests/test_fleet.py pins the sha256 per lane).  That parity is what
lets a wedge found in a fleet lane be re-run, shrunk, and replayed by
the ordinary single-run triage stack.

Scale-out: the lane axis tiles over a device mesh via ``shard_map``
(lanes are independent — no collectives), so a v5e-8 runs 8x the
lanes of a chip at the same wall clock; the 2-core CPU box default
stays modest (``default_lane_count``).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.analysis import tracecount
from tpu_paxos.config import SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.fleet import schedule_table as stm
from tpu_paxos.fleet import verdict as vdt
from tpu_paxos.utils import prng

#: Default episode capacity of a runner's compiled envelope: every
#: lane's schedule must fit (the stress mixes peak at 4; the search
#: grammar samples at most this many).
MAX_EPISODES = 8


def default_lane_count(backend: str | None = None) -> int:
    """Lanes per dispatch by backend: wide where the hardware is (a
    TPU chip streams hundreds of 5-node lanes per HBM pass), modest on
    the 2-core CPU dev box where lanes cost host vector lanes."""
    backend = backend or jax.default_backend()
    if backend == "tpu":
        return 256
    if backend == "gpu":
        return 128
    return 8


@dataclasses.dataclass
class FleetReport:
    """One dispatch's outcome.  ``final`` stays ON DEVICE — only the
    [lanes]-sized verdict vectors transfer here; callers extract full
    per-lane results (``lane_result``) for failing lanes only."""

    cfg: SimConfig
    n_lanes: int
    seeds: list[int]
    schedules: list
    verdict: vdt.LaneVerdict  # host numpy, [lanes] per field
    final: simm.SimState  # device, lane-leading
    expected: np.ndarray
    seconds: float

    @property
    def lanes_per_sec(self) -> float:
        return self.n_lanes / max(self.seconds, 1e-9)

    @property
    def failing(self) -> list[int]:
        return [i for i in range(self.n_lanes) if not bool(self.verdict.ok[i])]

    def lane_result(self, i: int) -> simm.SimResult:
        """Transfer ONE lane's final state and marshal it as the
        single-run result type (the full-suite / shrink hand-off)."""
        one = jax.tree.map(lambda x: x[i], self.final)
        return simm.to_result(one, self.expected)

    def lane_cfg(self, i: int) -> SimConfig:
        """The single-run config this lane is decision-log-identical
        to: base cfg with the lane's seed and schedule baked back in."""
        return dataclasses.replace(
            self.cfg,
            seed=self.seeds[i],
            faults=dataclasses.replace(
                self.cfg.faults, schedule=self.schedules[i]
            ),
        )


class FleetRunner:
    """Compile-once fleet front end for one geometry: the jitted
    vmapped (and optionally shard_map-tiled) lane program plus its
    static workload template.  ``run()`` is called per generation /
    per mix with fresh seeds and schedules — same executable."""

    def __init__(
        self,
        cfg: SimConfig,
        workload: list[np.ndarray],
        gates: list[np.ndarray] | None = None,
        mesh=None,
        max_episodes: int = MAX_EPISODES,
    ):
        if cfg.faults.schedule is not None:
            raise ValueError(
                "fleet base cfg must not bake a schedule; schedules "
                "are per-lane runtime tables"
            )
        self.cfg = cfg
        self.workload = [np.asarray(w, np.int32) for w in workload]
        self.gates = gates
        self.mesh = mesh
        self.max_episodes = max_episodes
        self.expected, self.owner = vdt.expected_owners(cfg, self.workload)
        pend, gate, tail, c = simm.prepare_queues(cfg, self.workload, gates)
        self._tmpl = (pend, gate, tail)
        self.queue_cap = c
        round_fn = simm.build_engine(
            cfg, c,
            vid_cap=simm.gates_vid_cap(self.workload, gates),
            runtime_schedule=True,
        )
        expected, owner = self.expected, self.owner

        def lane(root, st, tab):
            def cond(s):
                return (~s.done) & (s.t < cfg.max_rounds + tab.horizon)

            final = jax.lax.while_loop(
                cond, lambda s: round_fn(root, s, tab), st
            )
            return final, vdt.lane_verdict(cfg, final, expected, owner)

        fl = jax.vmap(lane)
        if mesh is not None and mesh.size > 1:
            from jax.sharding import PartitionSpec as P

            from tpu_paxos.parallel import mesh as pmesh

            spec = P(pmesh.instance_axes(mesh))
            fl = pmesh.shard_map(
                fl, mesh,
                in_specs=(spec, spec, spec),
                out_specs=(spec, spec),
            )
        self._fn = jax.jit(fl)

        def init_lane(pend, gate, tail, root):
            return simm.init_state(cfg, pend, gate, tail, root)

        self._init = jax.jit(jax.vmap(init_lane))

    def _queues(self, n_lanes: int, workloads):
        """Stacked per-lane (pend, gate, tail).  Per-lane workloads
        must match the template's shapes (same per-proposer lengths)
        and its expected-vid set — one verdict bitmap and one compiled
        queue capacity serve every lane."""
        if workloads is None:
            pend, gate, tail = self._tmpl
            stack = lambda a: np.broadcast_to(a, (n_lanes,) + a.shape)  # noqa: E731
            return stack(pend), stack(gate), stack(tail)
        pends, gates_, tails = [], [], []
        for wl_lane, g_lane in workloads:
            exp, own = vdt.expected_owners(self.cfg, wl_lane)
            if not np.array_equal(exp, self.expected) or not np.array_equal(
                own, self.owner
            ):
                # the owner map is the verdict's crash-excusal key: a
                # vid owned by a different proposer than the template's
                # would be excused (or owed) against the wrong node
                raise ValueError(
                    "per-lane workload changes the expected-vid set or "
                    "its vid->proposer owner map; the fleet's coverage "
                    "verdict is compiled against the template's"
                )
            p, g, t, c = simm.prepare_queues(self.cfg, wl_lane, g_lane)
            if c != self.queue_cap or p.shape != self._tmpl[0].shape:
                raise ValueError(
                    "per-lane workload shapes must match the template "
                    f"(capacity {c} vs {self.queue_cap})"
                )
            pends.append(p)
            gates_.append(g)
            tails.append(t)
        return np.stack(pends), np.stack(gates_), np.stack(tails)

    def run(
        self,
        seeds,
        schedules,
        workloads=None,
    ) -> FleetReport:
        """One fleet dispatch: ``seeds[i]`` and ``schedules[i]``
        (FaultSchedule or None) drive lane ``i``; ``workloads``
        optionally carries per-lane ``(workload, gates)`` pairs
        (template-shaped).  Returns once the verdict vector is on the
        host; the per-lane states stay on device."""
        seeds = [int(s) for s in seeds]
        schedules = list(schedules)
        n_lanes = len(seeds)
        if len(schedules) != n_lanes:
            raise ValueError("one schedule per lane required")
        if self.mesh is not None and n_lanes % max(self.mesh.size, 1):
            raise ValueError(
                f"{n_lanes} lanes do not tile over {self.mesh.size} devices"
            )
        tabs = jax.tree.map(
            jnp.asarray,
            stm.encode_batch(
                schedules, self.cfg.n_nodes, self.max_episodes
            ),
        )
        roots = jnp.stack([prng.root_key(s) for s in seeds])
        pend, gate, tail = self._queues(n_lanes, workloads)
        t0 = time.perf_counter()
        with tracecount.engine_scope("fleet"):
            states = self._init(
                jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail),
                roots,
            )
            final, v = self._fn(roots, states, tabs)
        verdict = vdt.LaneVerdict(*(np.asarray(x) for x in v))
        seconds = time.perf_counter() - t0  # verdict transfer = the sync
        return FleetReport(
            cfg=self.cfg,
            n_lanes=n_lanes,
            seeds=seeds,
            schedules=schedules,
            verdict=verdict,
            final=final,
            expected=self.expected,
            seconds=seconds,
        )


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical fleet trace (analysis/registry.py): 2 lanes of the
    audit config geometry with distinct episode mixes through the
    vmapped while-loop + on-device verdict — the runtime-mask path
    (masks_at inside the round body) and the verdict reductions are
    all in the traced program the op budget pins."""
    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.core import faults as fltm
    from tpu_paxos.core.sim import audit_canonical_cfg

    def build():
        import dataclasses as dc

        cfg = dc.replace(
            audit_canonical_cfg(),
            faults=dc.replace(audit_canonical_cfg().faults, schedule=None),
        )
        workload = simm.default_workload(cfg)
        runner = FleetRunner(cfg, workload, max_episodes=2)
        scheds = [
            fltm.FaultSchedule((fltm.partition(2, 6, (0,), (1, 2)),)),
            fltm.FaultSchedule((
                fltm.pause(1, 4, 1), fltm.burst(2, 5, 1500),
            )),
        ]
        tabs = jax.tree.map(
            jnp.asarray, stm.encode_batch(scheds, cfg.n_nodes, 2)
        )
        roots = jnp.stack([prng.root_key(s) for s in (0, 1)])
        pend, gate, tail = runner._queues(2, None)
        states = runner._init(
            jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail), roots
        )
        return runner._fn, (roots, states, tabs)

    return [AuditEntry(
        "fleet.run_lanes", build,
        covers=("FleetRunner.__init__",),
        allow=("IR204",),
        why="the vmapped lane body IS core/sim's round_fn — same "
            "unique-key compaction sorts as sim.run_rounds",
    )]
