"""Device-batched fleet runner: many (seed x schedule x knob-mix)
lanes of the general engine per XLA dispatch, judged on device.

``core/sim`` runs ONE simulation per host-loop iteration; the stress
sweep therefore pays a dispatch (and, per episode mix, a compile) per
seed.  The fleet instead ``vmap``s the engine's whole-run surface —
the ``lax.while_loop`` over ``round_fn`` that ``sim._run_loop``
drives — over a LANE axis of PRNG roots, initial states, runtime
schedule tables (``fleet/schedule_table.py``), runtime i.i.d. fault
knobs (``core/net.FaultKnobs``: drop/dup/delay/crash as traced
``[lanes]`` vectors), and runtime workload tables (the per-lane queue
arrays plus the verdict's expected-vid/owner tables), with the
per-lane invariant subset (``fleet/verdict.py``) reduced to a
``[lanes]`` verdict vector inside the same jit.  One compiled
executable then covers every (seed, episode-mix, knob-mix, workload)
combination of a fixed ENVELOPE — ``(n_nodes, n_instances,
max_delay bound, max_episodes)`` plus the queue/table shapes — and
only failing lanes ever pay host transfer + the full
``harness/validate`` suite + the ``harness/shrink.py`` repro path.
``fleet/envelope.py`` keys a shared runner cache on exactly that
envelope, so the stress sweep, the schedule search, and the greedy
shrinker all reuse one compile.

Lane-for-lane the fleet is DECISION-LOG-IDENTICAL to single
``core/sim.run`` executions of the same (cfg, schedule, seed):
``jax_threefry_partitionable`` (pinned in utils/prng) makes the
batched PRNG draws equal the per-lane draws, the runtime mask
computation equals the compiled tables row for row
(tests/test_fleet.py pins the sha256 per lane), and the runtime-knob
sampling equals the static branches knob for knob
(tests/test_knobs.py pins the sha256 over a knob grid).  That parity
is what lets a wedge found in a fleet lane be re-run, shrunk, and
replayed by the ordinary single-run triage stack.

Scale-out: the lane axis tiles over a device mesh via ``shard_map``
(lanes are independent — no collectives), so a v5e-8 runs 8x the
lanes of a chip at the same wall clock; the 2-core CPU box default
stays modest (``default_lane_count``).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.analysis import tracecount
from tpu_paxos.config import EdgeFaultConfig, FaultConfig, SimConfig
from tpu_paxos.core import geom as geo
from tpu_paxos.core import net as netm
from tpu_paxos.core import sim as simm
from tpu_paxos.core import values as val
from tpu_paxos.fleet import schedule_table as stm
from tpu_paxos.fleet import verdict as vdt
from tpu_paxos.utils import prng

#: Default episode capacity of a runner's compiled envelope: every
#: lane's schedule must fit (the stress mixes peak at 4; the search
#: grammar samples at most this many).
MAX_EPISODES = 8


def default_lane_count(backend: str | None = None) -> int:
    """Lanes per dispatch by backend: wide where the hardware is (a
    TPU chip streams hundreds of 5-node lanes per HBM pass), modest on
    the 2-core CPU dev box where lanes cost host vector lanes."""
    backend = backend or jax.default_backend()
    if backend == "tpu":
        return 256
    if backend == "gpu":
        return 128
    return 8


def _pad_geometry_workload(workload, gates, bound_p: int):
    """Workload/gate rows padded with EMPTY rows to the envelope's
    proposer bound: pad proposer slots own no values (their queues
    drain vacuously), so vid sets, queue capacity, and the verdict
    tables are untouched.  A workload naming more proposers than the
    bound is rejected by name."""
    workload = [np.asarray(w, np.int32) for w in workload]
    if len(workload) > bound_p:
        raise ValueError(
            f"workload names {len(workload)} proposers; the envelope "
            f"geometry bound is {bound_p} proposers"
        )
    pad = bound_p - len(workload)
    wl = workload + [np.zeros((0,), np.int32)] * pad
    g = None
    if gates is not None:
        g = list(gates) + [np.zeros((0,), np.int32)] * pad
    return wl, g


@dataclasses.dataclass
class FleetReport:
    """One dispatch's outcome.  ``final`` stays ON DEVICE — only the
    [lanes]-sized verdict vectors transfer here; callers extract full
    per-lane results (``lane_result``) for failing lanes only."""

    cfg: SimConfig
    n_lanes: int
    seeds: list[int]
    schedules: list
    verdict: vdt.LaneVerdict  # host numpy, [lanes] per field
    final: simm.SimState  # device, lane-leading
    expected: np.ndarray  # the runner's template expected-vid set
    seconds: float
    #: flight-recorder summaries, [lanes]-leading host numpy
    #: (telemetry/recorder.TelemetrySummary) — None unless the runner
    #: was built with ``telemetry=True``.  Reduced ON DEVICE inside
    #: the lane jit; only these fixed small shapes ever transfer.
    telemetry: object = None
    #: windowed time-series, [lanes, W]-leading host numpy
    #: (telemetry/recorder.WindowSummary) — armed runners always
    #: carry the windowed plane (bucket width
    #: ``recorder.WINDOW_ROUNDS``); None when recorder-free.
    windows: object = None
    #: per-lane i.i.d. FaultConfig (schedule-free) — the knob mix each
    #: lane actually ran, whether passed explicitly or defaulted from
    #: the runner's base cfg; the source ``lane_cfg`` bakes back in.
    fault_cfgs: list = dataclasses.field(default_factory=list)
    #: per-lane expected-vid arrays (== ``expected`` for template
    #: lanes; per-lane for runtime workload tables)
    expected_lanes: list = dataclasses.field(default_factory=list)

    @property
    def lanes_per_sec(self) -> float:
        return self.n_lanes / max(self.seconds, 1e-9)

    @property
    def failing(self) -> list[int]:
        return [i for i in range(self.n_lanes) if not bool(self.verdict.ok[i])]

    def lane_result(self, i: int) -> simm.SimResult:
        """Transfer ONE lane's final state and marshal it as the
        single-run result type (the full-suite / shrink hand-off)."""
        one = jax.tree.map(lambda x: x[i], self.final)
        exp = self.expected_lanes[i] if self.expected_lanes else self.expected
        return simm.to_result(one, exp)

    def lane_telemetry(self, i: int):
        """One lane's flight-recorder summary as a JSON-ready dict
        (telemetry/recorder.summary_to_dict, incl. the windowed
        ``"windows"`` block); None when the runner ran
        recorder-free."""
        if self.telemetry is None:
            return None
        from tpu_paxos.telemetry import recorder as telem

        one = jax.tree.map(lambda x: x[i], self.telemetry)
        wone = (
            jax.tree.map(lambda x: x[i], self.windows)
            if self.windows is not None else None
        )
        return telem.summary_to_dict(one, wone, telem.WINDOW_ROUNDS)

    def lane_cfg(self, i: int) -> SimConfig:
        """The single-run config this lane is decision-log-identical
        to: base cfg with the lane's seed, i.i.d. knobs, and schedule
        baked back in."""
        fc = self.fault_cfgs[i] if self.fault_cfgs else self.cfg.faults
        return dataclasses.replace(
            self.cfg,
            seed=self.seeds[i],
            faults=dataclasses.replace(fc, schedule=self.schedules[i]),
        )


class FleetRunner:
    """Compile-once fleet front end for one envelope: the jitted
    vmapped (and optionally shard_map-tiled) lane program plus its
    static workload template.  ``run()`` is called per generation /
    per mix / per shrink candidate with fresh seeds, schedules, knob
    vectors, and workload tables — same executable.

    ``cfg.faults`` plays two roles: its ``max_delay`` is the
    envelope's RING BOUND (every lane's runtime ``max_delay`` must
    stay <= it), and its i.i.d. knobs are the default per-lane knob
    mix when ``run(knobs=None)``.  ``cfg.faults.schedule`` must be
    None — schedules are per-lane runtime tables."""

    def __init__(
        self,
        cfg: SimConfig,
        workload: list[np.ndarray],
        gates: list[np.ndarray] | None = None,
        mesh=None,
        max_episodes: int = MAX_EPISODES,
        telemetry: bool = False,
        geometry: geo.GeometryEnvelope | None = None,
    ):
        if cfg.faults.schedule is not None:
            raise ValueError(
                "fleet base cfg must not bake a schedule; schedules "
                "are per-lane runtime tables"
            )
        if geometry is not None:
            # padded runner: the build cfg IS the envelope bound; the
            # true geometry + protocol knobs arrive per run() dispatch
            if (
                cfg.n_nodes != geometry.bound_nodes
                or tuple(cfg.proposers)
                != tuple(range(geometry.bound_proposers))
            ):
                raise ValueError(
                    "a geometry-padded fleet runner must be built at "
                    "the envelope bound; use geometry.bound_cfg(cfg)"
                )
            workload, gates = _pad_geometry_workload(
                workload, gates, geometry.bound_proposers
            )
        self.geometry = geometry
        self.cfg = cfg
        self.workload = [np.asarray(w, np.int32) for w in workload]
        self.gates = gates
        self.mesh = mesh
        self.max_episodes = max_episodes
        self.telemetry = telemetry
        self.delay_bound = cfg.faults.max_delay
        #: set by fleet/envelope.runner_for: a cache-shared runner's
        #: template queues and base knobs are whatever caller warmed
        #: the cache, so run() REQUIRES explicit workloads= and knobs=
        self.explicit_inputs_only = False
        self.expected, self.owner = vdt.expected_owners(cfg, self.workload)
        #: static bitmap bound of the verdict's chosen-membership
        #: bitmap — the envelope's vid space; every lane's vids must
        #: fall below it
        self.vid_bound = (
            int(self.expected.max()) + 1 if self.expected.size else 1
        )
        #: static width of the per-lane expected/owner tables; lanes
        #: with fewer distinct vids pad with -1 (vacuously covered)
        self.v_cap = max(len(self.expected), 1)
        pend, gate, tail, c = simm.prepare_queues(cfg, self.workload, gates)
        self._tmpl = (pend, gate, tail)
        self.queue_cap = c
        self._gate_vid_cap = simm.gates_vid_cap(self.workload, gates)
        if telemetry:
            from tpu_paxos.telemetry import recorder as _telem
        round_fn = simm.build_engine(
            cfg, c,
            vid_cap=self._gate_vid_cap,
            runtime_schedule=True,
            runtime_knobs=True,
            telemetry=telemetry,
            window_rounds=_telem.WINDOW_ROUNDS if telemetry else 0,
            geometry=geometry,
            runtime_protocol=geometry is not None,
        )
        vid_bound = self.vid_bound

        # geometry-padded lanes carry two trailing [lanes]-stacked
        # pytrees (Geometry, ProtocolKnobs); bound-free lanes carry
        # none — the *gp splat keeps ONE lane body for both builds
        if telemetry:
            from tpu_paxos.telemetry import recorder as telem

            def lane(root, st, tab, kn, exp, own, rmap, *gp):
                gm, pkn = gp if gp else (None, None)

                def cond(c):
                    return (~c[0].done) & (
                        c[0].t < cfg.max_rounds + tab.horizon
                    )

                # the zeroed accumulators are trace-time constants —
                # no lane-axis plumbing needed; armed lanes always
                # carry the windowed plane (bucket width
                # recorder.WINDOW_ROUNDS — part of the envelope's
                # traced program, shared by every armed consumer)
                tele0 = (
                    telem.init_telemetry(
                        cfg.n_instances, len(cfg.proposers), cfg.n_nodes
                    ),
                    telem.init_windows(cfg.n_nodes),
                )
                final, (tl, ws) = jax.lax.while_loop(
                    cond,
                    lambda c: round_fn(
                        root, c[0], tab, kn, tele=c[1],
                        geom=gm, pknobs=pkn,
                    ),
                    (st, tele0),
                )
                return (
                    final,
                    vdt.lane_verdict(
                        cfg, final, exp, own, vid_cap=vid_bound, geom=gm
                    ),
                    telem.summarize(tl, final, tab.horizon, rmap),
                    telem.summarize_windows(
                        ws, tl.admit_round, final.met.chosen_vid,
                        final.met.chosen_round, telem.WINDOW_ROUNDS,
                        batch_round=tl.admit_round,
                        learned_round=tl.learned_round,
                        committed_round=tl.committed_round,
                    ),
                )
        else:
            def lane(root, st, tab, kn, exp, own, *gp):
                gm, pkn = gp if gp else (None, None)

                def cond(s):
                    return (~s.done) & (s.t < cfg.max_rounds + tab.horizon)

                final = jax.lax.while_loop(
                    cond,
                    lambda s: round_fn(
                        root, s, tab, kn, geom=gm, pknobs=pkn
                    ),
                    st,
                )
                return final, vdt.lane_verdict(
                    cfg, final, exp, own, vid_cap=vid_bound, geom=gm
                )

        fl = jax.vmap(lane)
        if mesh is not None and mesh.size > 1:
            from tpu_paxos.parallel import mesh as pmesh

            # lane-axis spec from the mesh module (SH001: axis names
            # route through parallel/, never hand-built here)
            spec = pmesh.instance_spec(mesh)
            n_in = (7 if telemetry else 6) + (
                2 if geometry is not None else 0
            )
            fl = pmesh.shard_map(
                fl, mesh,
                in_specs=(spec,) * n_in,
                out_specs=(spec,) * (4 if telemetry else 2),
            )
        self._fn = jax.jit(fl)

        if geometry is None:
            def init_lane(pend, gate, tail, root):
                return simm.init_state(cfg, pend, gate, tail, root)
        else:
            def init_lane(pend, gate, tail, root, gm, pkn):
                return simm.init_state(
                    cfg, pend, gate, tail, root,
                    geometry=geometry, geom=gm, pknobs=pkn,
                )

        self._init = jax.jit(jax.vmap(init_lane))

    def _pad_vtab(self, exp: np.ndarray, own: np.ndarray):
        """Pad a lane's expected/owner arrays to the envelope's table
        width (-1 expected = vacuous slot; its owner index is unused
        but must stay in node range for the gather)."""
        pe = np.full((self.v_cap,), -1, np.int32)
        po = np.zeros((self.v_cap,), np.int32)
        pe[: len(exp)] = exp
        po[: len(own)] = own
        return pe, po

    def _queues(self, n_lanes: int, workloads, owner_cfg=None):
        """Stacked per-lane (pend, gate, tail, expected, owner) plus
        the per-lane expected-vid list.  Per-lane workloads must match
        the template's SHAPES (same per-proposer lengths, same queue
        capacity) and fit the envelope's vid space — the vid SET and
        its vid->proposer owner map are runtime verdict tables, free
        to vary per lane."""
        def stack(arrays):
            first = arrays[0]
            if all(a is first for a in arrays):
                # identical per-lane arrays (e.g. the search passing
                # one (workload, gates) pair for every lane): a
                # broadcast view, not n_lanes materialized copies
                return np.broadcast_to(first, (n_lanes,) + first.shape)
            return np.stack(arrays)

        if workloads is None:
            exp_t, own_t = self._pad_vtab(self.expected, self.owner)
            pend, gate, tail = self._tmpl
            return (
                stack([pend]), stack([gate]), stack([tail]),
                stack([exp_t]), stack([own_t]),
                [self.expected] * n_lanes,
            )
        lanes, cache = [], {}
        for wl_lane, g_lane in workloads:
            key = (id(wl_lane), id(g_lane))
            if key not in cache:
                cache[key] = self._lane_tables(wl_lane, g_lane, owner_cfg)
            lanes.append(cache[key])
        return (
            stack([ln[0] for ln in lanes]), stack([ln[1] for ln in lanes]),
            stack([ln[2] for ln in lanes]), stack([ln[3] for ln in lanes]),
            stack([ln[4] for ln in lanes]), [ln[5] for ln in lanes],
        )

    def _lane_tables(self, wl_lane, g_lane, owner_cfg=None):
        """Validate one lane's (workload, gates) against the envelope
        and return its (pend, gate, tail, expected, owner, exp).
        ``owner_cfg`` (geometry-padded dispatches) carries the TRUE
        geometry the verdict's vid->owner-node map is computed
        against; the queue tables themselves pad to the bound."""
        exp, own = vdt.expected_owners(owner_cfg or self.cfg, wl_lane)
        if self.geometry is not None:
            wl_lane, g_lane = _pad_geometry_workload(
                wl_lane, g_lane, self.geometry.bound_proposers
            )
        if exp.size and int(exp.max()) >= self.vid_bound:
            raise ValueError(
                f"per-lane workload vid {int(exp.max())} exceeds "
                f"the envelope's vid bound {self.vid_bound}; build "
                "the runner with a template covering the vid space"
            )
        if len(exp) > self.v_cap:
            raise ValueError(
                f"per-lane workload has {len(exp)} distinct vids; "
                f"the envelope's verdict table holds {self.v_cap}"
            )
        if g_lane is not None and self._gate_vid_cap == 0 and any(
            len(g) and (np.asarray(g) != int(val.NONE)).any()
            for g in g_lane
        ):
            raise ValueError(
                "per-lane gates need a gate-bearing template: the "
                "engine compiles gate logic in only when the "
                "template has gates"
            )
        p, g, t, c = simm.prepare_queues(self.cfg, wl_lane, g_lane)
        if c != self.queue_cap or p.shape != self._tmpl[0].shape:
            raise ValueError(
                "per-lane workload shapes must match the template "
                f"(capacity {c} vs {self.queue_cap})"
            )
        pe, po = self._pad_vtab(exp, own)
        return p, g, t, pe, po, exp

    def _knob_arrays(self, n_lanes: int, knobs):
        """[lanes]-stacked ``FaultKnobs`` plus the per-lane
        (schedule-free) FaultConfig list — the shrink hand-off's
        ``lane_cfg`` source.  ``knobs[i]`` may be a FaultConfig (edge
        matrices welcome) or a host FaultKnobs (scalar or matrix
        form); None defaults every lane to the base cfg's i.i.d.
        knobs.

        Every lane NORMALIZES to matrix form (``net.matrix_knobs``:
        scalar knobs become a uniform ``[A, A]`` matrix, bit-identical
        by the FaultKnobs parity contract), so ONE compiled executable
        covers scalar mixes and WAN topologies alike — per-edge
        tables are just another runtime input of the envelope."""
        if knobs is None:
            knobs = [self.cfg.faults] * n_lanes
        knobs = list(knobs)
        if len(knobs) != n_lanes:
            raise ValueError("one knob set per lane required")
        a = self.cfg.n_nodes
        fcs = []
        for k in knobs:
            if isinstance(k, netm.FaultKnobs):
                # routes through FaultConfig validation (rate ranges,
                # min <= max — per edge for matrix-form knobs)
                if np.ndim(k.drop_rate) >= 2:
                    # EdgeFaultConfig canonicalizes the (host numpy)
                    # rows to int tuples itself
                    k = FaultConfig(
                        max_delay=int(np.max(k.max_delay)),
                        crash_rate=int(k.crash_rate),
                        edges=EdgeFaultConfig(
                            drop_rate=k.drop_rate,
                            dup_rate=k.dup_rate,
                            min_delay=k.min_delay,
                            max_delay=k.max_delay,
                        ),
                    )
                else:
                    k = FaultConfig(
                        drop_rate=int(k.drop_rate),
                        dup_rate=int(k.dup_rate),
                        min_delay=int(k.min_delay),
                        max_delay=int(k.max_delay),
                        crash_rate=int(k.crash_rate),
                    )
            if not isinstance(k, FaultConfig):
                raise TypeError(
                    f"per-lane knobs must be FaultConfig or FaultKnobs, "
                    f"got {type(k).__name__}"
                )
            if k.schedule is not None:
                raise ValueError(
                    "per-lane knobs must not carry a schedule; "
                    "schedules are per-lane runtime tables"
                )
            if k.max_delay > self.delay_bound:
                raise ValueError(
                    f"lane max_delay {k.max_delay} exceeds the "
                    f"envelope's ring bound {self.delay_bound} "
                    "(cfg.faults.max_delay)"
                )
            if k.delivery_cut != self.cfg.faults.delivery_cut:
                raise ValueError(
                    "delivery_cut is a compile-time engine flag: every "
                    f"lane must match the runner's build "
                    f"({self.cfg.faults.delivery_cut}); build a "
                    "separate runner for the other semantics"
                )
            fcs.append(k)
        mats = [netm.matrix_knobs(fc, a) for fc in fcs]
        if self.geometry is not None:
            # true-size [n, n] edge tables pad to the bound with zeros
            # (menu branches slice the TRUE leading block back out);
            # scalar mixes already broadcast uniformly at the bound
            mats = [netm.pad_matrix_knobs(m, a) for m in mats]
        stacked = netm.FaultKnobs(
            drop_rate=np.stack([m.drop_rate for m in mats]),
            dup_rate=np.stack([m.dup_rate for m in mats]),
            min_delay=np.stack([m.min_delay for m in mats]),
            max_delay=np.stack([m.max_delay for m in mats]),
            crash_rate=np.asarray([fc.crash_rate for fc in fcs], np.int32),
            # the gray clamp is each lane's OWN declared bound (what
            # lane_cfg() replays single-run), never the envelope ring
            delay_bound=np.asarray(
                [fc.max_delay for fc in fcs], np.int32
            ),
        )
        return stacked, fcs

    def run(
        self,
        seeds,
        schedules,
        workloads=None,
        knobs=None,
        regions=None,
        geometry=None,
        protocol=None,
    ) -> FleetReport:
        """One fleet dispatch: ``seeds[i]``, ``schedules[i]``
        (FaultSchedule or None), and ``knobs[i]`` (FaultConfig /
        FaultKnobs or None for the base cfg's mix — per-edge matrix
        configs welcome: every lane normalizes to matrix form) drive
        lane ``i``; ``workloads`` optionally carries per-lane
        ``(workload, gates)`` pairs (template-shaped; vid sets free
        within the envelope's vid bound); ``regions`` (telemetry
        runners only) optionally carries per-lane ``[A]`` int32
        node->region maps for the recorder's per-region-pair fault
        counters (None = all-zero maps — same executable).  Returns
        once the verdict vector is on the host; the per-lane states
        stay on device.

        Runners from the envelope cache (``fleet/envelope.runner_for``)
        REJECT ``workloads=None`` / ``knobs=None``: the cached
        template's queue order and base knobs belong to whichever
        caller warmed the cache, so defaulting to them would silently
        run the wrong faults (the cache normalizes knobs to zero) or
        the wrong queue order."""
        if self.explicit_inputs_only and (workloads is None or knobs is None):
            raise ValueError(
                "this runner came from the envelope cache "
                "(fleet/envelope.runner_for): pass explicit workloads= "
                "and knobs= — its template queues and base knob mix "
                "are cache-normalized, not yours"
            )
        if self.geometry is None:
            if geometry is not None or protocol is not None:
                raise ValueError(
                    "geometry=/protocol= are geometry-padded dispatch "
                    "inputs; build the runner with a GeometryEnvelope "
                    "(FleetRunner(geometry=...))"
                )
            gm_host = pkn_host = None
            report_cfg = self.cfg
        else:
            if geometry is None:
                raise ValueError(
                    "a geometry-padded runner takes its TRUE geometry "
                    "per dispatch: run(geometry=(n_nodes, proposers))"
                )
            if workloads is None:
                raise ValueError(
                    "a geometry-padded dispatch needs explicit "
                    "workloads= (the verdict's vid->owner map is "
                    "computed against the TRUE geometry, not the "
                    "bound cfg)"
                )
            n_true, true_props = geometry
            true_props = tuple(int(x) for x in true_props)
            pc = protocol if protocol is not None else self.cfg.protocol
            # named rejections: off-menu / over-bound geometries via
            # GeometryEnvelope.index_of, out-of-span knobs via
            # config.PROTOCOL_SPANS in geo.protocol_knobs
            gm_host = geo.geometry_for(self.geometry, n_true, true_props)
            pkn_host = geo.protocol_knobs(
                pc, stall_patience=simm.IDLE_RESTART_ROUNDS
            )
            report_cfg = dataclasses.replace(
                self.cfg,
                n_nodes=int(n_true),
                proposers=true_props,
                protocol=pc,
            )
        seeds = [int(s) for s in seeds]
        schedules = list(schedules)
        n_lanes = len(seeds)
        if len(schedules) != n_lanes:
            raise ValueError("one schedule per lane required")
        if self.mesh is not None and n_lanes % max(self.mesh.size, 1):
            raise ValueError(
                f"{n_lanes} lanes do not tile over {self.mesh.size} devices"
            )
        tabs = jax.tree.map(
            jnp.asarray,
            stm.encode_batch(
                schedules, self.cfg.n_nodes, self.max_episodes
            ),
        )
        kn, fault_cfgs = self._knob_arrays(n_lanes, knobs)
        # NAMED rejection, never silent exclusion (the FaultConfig
        # compile-time check's runtime-table twin): a gray episode on
        # a lane whose declared bound is 0 would clamp to a no-op
        for i, (fc_i, s_i) in enumerate(zip(fault_cfgs, schedules)):
            if (
                fc_i.max_delay == 0
                and s_i is not None
                and any(e.kind == "gray" for e in s_i.episodes)
            ):
                raise ValueError(
                    f"lane {i}: gray episodes need a nonzero lane "
                    "max_delay (the delay-inflation clamp is the "
                    "lane's own declared bound; at 0 every gray "
                    "episode is a no-op)"
                )
        roots = jnp.stack([prng.root_key(s) for s in seeds])
        pend, gate, tail, exp, own, exp_list = self._queues(
            n_lanes, workloads,
            owner_cfg=None if self.geometry is None else report_cfg,
        )
        if self.geometry is not None:
            # one true geometry per dispatch, broadcast [lanes]-leading
            # (views, not copies) so every lane axis — and the mesh
            # tiling — sees uniformly stacked inputs
            def _bl(x):
                x = np.asarray(x)
                return np.broadcast_to(x, (n_lanes,) + x.shape)

            gm_lanes = jax.tree.map(_bl, gm_host)
            pkn_lanes = jax.tree.map(_bl, pkn_host)
        if regions is not None and not self.telemetry:
            raise ValueError(
                "regions maps feed the flight recorder's region-pair "
                "counters; build the runner with telemetry=True"
            )
        if self.telemetry:
            a = self.cfg.n_nodes
            if regions is None:
                rmaps = np.zeros((n_lanes, a), np.int32)
            else:
                regions = list(regions)
                if len(regions) != n_lanes:
                    raise ValueError("one region map per lane required")
                rmaps = np.stack([
                    np.zeros((a,), np.int32) if r is None
                    else np.asarray(r, np.int32).reshape(a)
                    for r in regions
                ])
        t0 = time.perf_counter()  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
        tsum = wsum = None
        with tracecount.engine_scope("fleet"):
            init_args = (
                jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail),
                roots,
            )
            if self.geometry is not None:
                init_args = init_args + (
                    jax.tree.map(jnp.asarray, gm_lanes),
                    jax.tree.map(jnp.asarray, pkn_lanes),
                )
            states = self._init(*init_args)
            args = (
                roots, states, tabs,
                jax.tree.map(jnp.asarray, kn),
                jnp.asarray(exp), jnp.asarray(own),
            )
            if self.telemetry:
                args = args + (jnp.asarray(rmaps),)
            if self.geometry is not None:
                args = args + (
                    jax.tree.map(jnp.asarray, gm_lanes),
                    jax.tree.map(jnp.asarray, pkn_lanes),
                )
            out = self._fn(*args)
            if self.telemetry:
                final, v, tsum, wsum = out
            else:
                final, v = out
        verdict = vdt.LaneVerdict(*(np.asarray(x) for x in v))
        if tsum is not None:
            tsum = jax.tree.map(np.asarray, tsum)
            wsum = jax.tree.map(np.asarray, wsum)
        seconds = time.perf_counter() - t0  # verdict transfer = the sync  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
        return FleetReport(
            cfg=report_cfg,
            n_lanes=n_lanes,
            seeds=seeds,
            schedules=schedules,
            verdict=verdict,
            final=final,
            expected=self.expected,
            seconds=seconds,
            telemetry=tsum,
            windows=wsum,
            fault_cfgs=fault_cfgs,
            expected_lanes=exp_list,
        )


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical fleet trace (analysis/registry.py): 2 lanes of the
    audit config geometry with distinct episode mixes AND distinct
    i.i.d. knob mixes through the vmapped while-loop + on-device
    verdict — the runtime-mask path (masks_at inside the round body),
    the runtime-knob sampling, the runtime verdict tables, and the
    verdict reductions are all in the traced program the op budget
    pins."""
    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.core import faults as fltm
    from tpu_paxos.core.sim import audit_canonical_cfg

    def _audit_cfg():
        import dataclasses as dc

        return dc.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000, max_delay=2),
        )

    def _audit_scheds(n_lanes: int):
        """The canonical 2-lane episode mix, cycled over ``n_lanes``
        (same program whatever the lane count)."""
        base = [
            fltm.FaultSchedule((fltm.partition(2, 6, (0,), (1, 2)),)),
            fltm.FaultSchedule((
                fltm.pause(1, 4, 1), fltm.gray(2, 5, 2, delay=2),
            )),
        ]
        return [base[i % 2] for i in range(n_lanes)]

    def _build(telemetry: bool, mesh=None, n_lanes: int = 2):
        cfg = _audit_cfg()
        workload = simm.default_workload(cfg)
        runner = FleetRunner(
            cfg, workload, max_episodes=2, telemetry=telemetry,
            mesh=mesh,
        )
        scheds = _audit_scheds(n_lanes)
        tabs = jax.tree.map(
            jnp.asarray, stm.encode_batch(scheds, cfg.n_nodes, 2)
        )
        roots = jnp.stack([prng.root_key(s) for s in range(n_lanes)])
        # one scalar mix + one per-edge WAN matrix: both normalize to
        # [lanes, A, A] matrix knobs — the envelope's one program
        from tpu_paxos.config import EdgeFaultConfig as _E

        mixes = [cfg.faults, FaultConfig(
            max_delay=2,
            edges=_E.uniform(cfg.n_nodes, dup_rate=1000, max_delay=1),
        )]
        kn, _ = runner._knob_arrays(
            n_lanes, [mixes[i % 2] for i in range(n_lanes)]
        )
        pend, gate, tail, exp, own, _ = runner._queues(n_lanes, None)
        states = runner._init(
            jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail), roots
        )
        args = (
            roots, states, tabs,
            jax.tree.map(jnp.asarray, kn),
            jnp.asarray(exp), jnp.asarray(own),
        )
        if telemetry:
            args = args + (
                jnp.zeros((n_lanes, cfg.n_nodes), jnp.int32),
            )
        return runner._fn, args

    def _genv():
        # the canonical audit geometry (3 nodes, 2 proposers) one menu
        # step below a 5-node / 3-proposer bound — the smallest
        # envelope whose padding is visible in every padded axis
        return geo.GeometryEnvelope(menu=((3, (0, 1)), (5, (0, 1, 2))))

    def _build_envelope(mesh=None, n_lanes: int = 2):
        import dataclasses as dc

        cfg = _audit_cfg()
        genv = _genv()
        workload = simm.default_workload(cfg)
        runner = FleetRunner(
            genv.bound_cfg(cfg), workload, max_episodes=2,
            geometry=genv, mesh=mesh,
        )
        scheds = _audit_scheds(n_lanes)
        tabs = jax.tree.map(
            jnp.asarray,
            stm.encode_batch(scheds, genv.bound_nodes, 2),
        )
        roots = jnp.stack([prng.root_key(s) for s in range(n_lanes)])
        from tpu_paxos.config import EdgeFaultConfig as _E

        # one scalar mix + one TRUE-geometry WAN matrix: both pad to
        # [lanes, A_bound, A_bound] — the padded envelope's one program
        mixes = [cfg.faults, FaultConfig(
            max_delay=2,
            edges=_E.uniform(cfg.n_nodes, dup_rate=1000, max_delay=1),
        )]
        kn, _ = runner._knob_arrays(
            n_lanes, [mixes[i % 2] for i in range(n_lanes)]
        )
        owner_cfg = dc.replace(
            runner.cfg, n_nodes=cfg.n_nodes, proposers=cfg.proposers
        )
        pend, gate, tail, exp, own, _ = runner._queues(
            n_lanes, [(workload, None)] * n_lanes, owner_cfg=owner_cfg
        )
        gm = geo.geometry_for(genv, cfg.n_nodes, cfg.proposers)
        pkn = geo.protocol_knobs(
            cfg.protocol, stall_patience=simm.IDLE_RESTART_ROUNDS
        )

        def _bl(x):
            x = np.asarray(x)
            return jnp.asarray(
                np.broadcast_to(x, (n_lanes,) + x.shape)
            )

        gm_l = jax.tree.map(_bl, gm)
        pkn_l = jax.tree.map(_bl, pkn)
        states = runner._init(
            jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail),
            roots, gm_l, pkn_l,
        )
        args = (
            roots, states, tabs, jax.tree.map(jnp.asarray, kn),
            jnp.asarray(exp), jnp.asarray(own), gm_l, pkn_l,
        )
        return runner._fn, args

    def shard_build(mesh):
        # 8 lanes tile the whole {1, 2, 4, 8} grid; the lane program
        # is the mesh=None one — only the tiling changes
        return _build(False, mesh=mesh, n_lanes=8)

    def shard_state():
        # the [lanes]-stacked SimState the partition table must cover
        _fn, args = _build(False)
        return "fleet", args[1]

    def shard_parity(n_devices: int):
        """SH304: one fleet dispatch per mesh shape — per-lane verdict
        nibbles (ok|agreement|coverage|quiescent) + decision-log
        sha256 must be bitwise mesh-invariant (lanes are independent;
        jax_threefry_partitionable makes tiled draws equal vmapped
        draws — the PR-4/5 parity argument, certified per mesh)."""
        import hashlib

        from tpu_paxos.parallel import mesh as pmesh
        from tpu_paxos.replay.decision_log import decision_log

        mesh = (
            pmesh.make_instance_mesh(n_devices) if n_devices > 1 else None
        )
        cfg = _audit_cfg()
        workload = simm.default_workload(cfg)
        runner = FleetRunner(cfg, workload, max_episodes=2, mesh=mesh)
        rep = runner.run(list(range(8)), _audit_scheds(8))
        v = rep.verdict
        verdicts = "".join(
            format(
                (int(bool(v.ok[i])) << 3)
                | (int(bool(v.agreement[i])) << 2)
                | (int(bool(v.coverage[i])) << 1)
                | int(bool(v.quiescent[i])),
                "x",
            )
            for i in range(rep.n_lanes)
        )
        met = rep.final.met
        stride = runner.vid_bound  # covers every canonical vid
        logs = [
            hashlib.sha256(decision_log(
                np.asarray(met.chosen_vid[i]),
                np.asarray(met.chosen_ballot[i]),
                stride, cfg.n_instances,
            ).encode()).hexdigest()
            for i in range(rep.n_lanes)
        ]
        return {"verdicts": verdicts, "lane_logs": logs}

    def shard_build_envelope(mesh):
        return _build_envelope(mesh=mesh, n_lanes=8)

    def shard_state_envelope():
        # the [lanes]-stacked PADDED SimState: every bound-shaped leaf
        # must still match the committed fleet partition rules
        _fn, args = _build_envelope()
        return "fleet", args[1]

    def shard_parity_envelope(n_devices: int):
        """SH304, padded twin: one 3-in-5 dispatch per mesh shape —
        verdict nibbles + decision-log sha256 bitwise mesh-invariant
        THROUGH the geometry padding (the menu-switched draws must
        stay lane-local under the tiling)."""
        import hashlib

        from tpu_paxos.parallel import mesh as pmesh
        from tpu_paxos.replay.decision_log import decision_log

        mesh = (
            pmesh.make_instance_mesh(n_devices) if n_devices > 1 else None
        )
        cfg = _audit_cfg()
        genv = _genv()
        workload = simm.default_workload(cfg)
        runner = FleetRunner(
            genv.bound_cfg(cfg), workload, max_episodes=2,
            geometry=genv, mesh=mesh,
        )
        rep = runner.run(
            list(range(8)), _audit_scheds(8),
            workloads=[(workload, None)] * 8,
            knobs=[cfg.faults] * 8,
            geometry=(cfg.n_nodes, cfg.proposers),
        )
        v = rep.verdict
        verdicts = "".join(
            format(
                (int(bool(v.ok[i])) << 3)
                | (int(bool(v.agreement[i])) << 2)
                | (int(bool(v.coverage[i])) << 1)
                | int(bool(v.quiescent[i])),
                "x",
            )
            for i in range(rep.n_lanes)
        )
        met = rep.final.met
        stride = runner.vid_bound
        logs = [
            hashlib.sha256(decision_log(
                np.asarray(met.chosen_vid[i]),
                np.asarray(met.chosen_ballot[i]),
                stride, cfg.n_instances,
            ).encode()).hexdigest()
            for i in range(rep.n_lanes)
        ]
        return {"verdicts": verdicts, "lane_logs": logs}

    ir204_why = (
        "the vmapped lane body IS core/sim's round_fn — same "
        "unique-key compaction sorts as sim.run_rounds"
    )
    return [
        AuditEntry(
            "fleet.run_lanes", lambda: _build(False),
            covers=("FleetRunner.__init__",),
            allow=("IR204",), why=ir204_why, hlo_golden=True,
            shard_build=shard_build,
            shard_state=shard_state,
            shard_parity=shard_parity,
        ),
        AuditEntry(
            # the geometry-padded twin: node/proposer axes at the menu
            # bound, Geometry + ProtocolKnobs as trailing lane-stacked
            # runtime inputs — the padding toll is pinned per
            # primitive (op/hlo budgets) and the padded program
            # certifies over the same {1, 2, 4, 8} mesh grid
            "fleet.run_lanes_envelope", _build_envelope,
            allow=("IR204",), why=ir204_why, hlo_golden=True,
            shard_build=shard_build_envelope,
            shard_state=shard_state_envelope,
            shard_parity=shard_parity_envelope,
        ),
        AuditEntry(
            # the telemetry-armed twin: recorder accumulators (incl.
            # the [W] windowed rings — armed lanes always carry the
            # windowed plane) in the lane carry + the on-device
            # summary/window reductions; IR201 (no host transfers in
            # the loop) is the load-bearing contract here — the
            # ledger must never leave the device
            "fleet.run_lanes_telemetry", lambda: _build(True),
            allow=("IR204",), why=ir204_why, hlo_golden=True,
        ),
    ]
