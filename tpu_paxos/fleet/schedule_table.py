"""Runtime fault-schedule encoding: episodes as dense device arrays.

``core/faults.compile_schedule`` lowers a ``FaultSchedule`` to
per-round tables baked into the engine closure as COMPILE-TIME
constants — the right trade for a single run (mask gathers cost one
row index, schedule-free dimensions elide entirely), but fatal for a
fleet: every distinct episode mix would be its own XLA program, and a
randomized schedule search would compile per candidate.

This module is the runtime twin: a schedule becomes a
:class:`ScheduleTable` of per-EPISODE arrays — interval bounds
``t0``/``t1`` plus the episode's static masks from
``faults.episode_tables`` (cut edges, paused nodes, burst rate) —
padded to a fixed episode capacity, and the per-round reach / pause /
drop masks are computed INSIDE the traced step (:func:`masks_at`):

    active[e] = t0[e] <= t < t1[e]
    reach     = ~any_e(active[e] & cut[e])        (diagonal never cut)
    paused    =  any_e(active[e] & paused[e])
    extra     =  min(sum_e(active[e] * drop[e]), 10000)
    gray      =  sum_e(active[e] * gray[e])       (per-node delay add)

plus the one-sided crash-point mask (:func:`crashes_at` — crash
episodes are permanent, so their activity test is ``t0[e] <= t`` with
no upper bound, matching the compiled lowering's cumulative rows):

    crash     =  any_e((t0[e] <= t) & crash[e])

Episode composition therefore matches the compile-time lowering
exactly — cuts AND their reachability, pauses OR, burst rates add —
and the parity is pinned per round by tests/test_schedule_table.py
(table-encoded masks == compiled table rows for every episode kind)
and end-to-end by the fleet's lane-by-lane decision-log sha256 test.

Tables are plain data (numpy on host, jnp once traced), stack along a
leading lane axis (:func:`encode_batch`), and make one compiled
executable cover EVERY episode mix of a given ``(max_episodes,
n_nodes)`` envelope — the fleet's lane axis vmaps over them.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from tpu_paxos.core import faults as fltm


class ScheduleTable(NamedTuple):
    """One lane's schedule as dense runtime arrays (host: numpy;
    traced: jnp with an optional leading lane axis).  Padding slots
    hold ``t0 == t1 == 0`` — never active — so any schedule with at
    most ``E`` episodes fits the same shapes."""

    t0: np.ndarray  # [E] int32 episode starts
    t1: np.ndarray  # [E] int32 episode ends (t1 <= t0 = never active)
    cut: np.ndarray  # [E, N, N] bool edges severed while active
    paused: np.ndarray  # [E, N] bool nodes paused while active
    extra_drop: np.ndarray  # [E] int32 per-1e4 burst addition
    crash: np.ndarray  # [E, N] bool crash points (permanent from t0;
    #     padding slots are all-false, so the t0 <= t read in
    #     crashes_at stays inert for them)
    gray: np.ndarray  # [E, N] int32 per-node extra delay while active
    horizon: np.ndarray  # [] int32 first round with every episode over


def encode_schedule(
    sched: fltm.FaultSchedule | None,
    n_nodes: int,
    max_episodes: int | None = None,
) -> ScheduleTable:
    """Encode one schedule (None/empty = the all-clear table: masks
    read healed at every round and ``horizon`` is 0, so the engine's
    heal gate never delays quiescence)."""
    eps = () if sched is None else sched.episodes
    e_cap = len(eps) if max_episodes is None else max_episodes
    e_cap = max(e_cap, 1)  # zero-length episode axes break vmap stacking
    if len(eps) > e_cap:
        raise ValueError(
            f"schedule has {len(eps)} episodes; table capacity is {e_cap}"
        )
    t0 = np.zeros((e_cap,), np.int32)
    t1 = np.zeros((e_cap,), np.int32)
    cut = np.zeros((e_cap, n_nodes, n_nodes), bool)
    paused = np.zeros((e_cap, n_nodes), bool)
    extra = np.zeros((e_cap,), np.int32)
    crash = np.zeros((e_cap, n_nodes), bool)
    gray = np.zeros((e_cap, n_nodes), np.int32)
    for i, e in enumerate(eps):
        c, p, x, cm, gv = fltm.episode_tables(e, n_nodes)
        t0[i], t1[i] = e.t0, e.t1
        cut[i], paused[i], extra[i], crash[i], gray[i] = c, p, x, cm, gv
    return ScheduleTable(
        t0=t0,
        t1=t1,
        cut=cut,
        paused=paused,
        extra_drop=extra,
        crash=crash,
        gray=gray,
        horizon=np.int32(sched.horizon if sched is not None else 0),
    )


def encode_batch(
    schedules,
    n_nodes: int,
    max_episodes: int | None = None,
) -> ScheduleTable:
    """Stack one table per lane along a leading lane axis.  All lanes
    share one episode capacity (the max over lanes unless given), so
    the batch vmaps as a single pytree."""
    schedules = list(schedules)
    if not schedules:
        raise ValueError("encode_batch needs at least one lane")
    if max_episodes is None:
        max_episodes = max(
            len(s.episodes) if s is not None else 0 for s in schedules
        )
    tabs = [encode_schedule(s, n_nodes, max_episodes) for s in schedules]
    return ScheduleTable(
        *(np.stack([getattr(t, f) for t in tabs]) for f in ScheduleTable._fields)
    )


def masks_at(tab: ScheduleTable, t):
    """Per-round masks from a (traced) table: ``(reach [N, N] bool,
    paused [N] bool, extra_drop int32, gray [N] int32)``.  Pure jnp —
    called inside the engine's round function; composition semantics
    match ``faults.compile_schedule`` row ``t`` exactly (module
    doc)."""
    import jax.numpy as jnp

    t = jnp.asarray(t, jnp.int32)
    active = (tab.t0 <= t) & (t < tab.t1)  # [E]
    reach = ~jnp.any(active[:, None, None] & tab.cut, axis=0)  # [N, N]
    paused = jnp.any(active[:, None] & tab.paused, axis=0)  # [N]
    extra = jnp.minimum(
        jnp.sum(jnp.where(active, tab.extra_drop, jnp.int32(0))),
        jnp.int32(10_000),
    )
    gray = jnp.sum(
        jnp.where(active[:, None], tab.gray, jnp.int32(0)), axis=0
    )  # [N]; the engine clamps the inflated delay at its ring bound
    return reach, paused, extra, gray


def crashes_at(tab: ScheduleTable, t):
    """Scheduled-crash mask at round ``t``: ``[N] bool``, true from a
    crash point's ``t0`` FOREVER (crashes never heal, so the activity
    test is one-sided; padding slots have an all-false crash row and
    stay inert).  Matches ``faults.compile_schedule``'s cumulative
    ``crashed`` rows exactly (tests/test_schedule_table.py)."""
    import jax.numpy as jnp

    t = jnp.asarray(t, jnp.int32)
    started = tab.t0 <= t  # [E]; one-sided: crash points are permanent
    return jnp.any(started[:, None] & tab.crash, axis=0)  # [N]
