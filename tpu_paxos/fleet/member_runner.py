"""Device-batched MEMBERSHIP fleets: many (seed x churn-schedule x
fault-schedule) lanes of the churn engine per XLA dispatch, judged on
device.

The general-engine fleet (fleet/runner.py) vmaps ``sim``'s whole-run
while-loop over lanes of schedule tables and knob vectors; this
module is its membership twin.  Each lane runs the device-resident
churn driver (``membership/engine.ChurnEngine``'s loop: inject ->
round -> done?) end to end — the churn table
(``membership/churn_table.ChurnTable``) and the fault-schedule table
(``fleet/schedule_table.ScheduleTable``, crash points included) are
per-lane runtime arrays, so ONE compiled executable covers every
(churn scenario, episode mix, seed) combination of a fixed envelope
``(n_nodes, n_instances, max_events, max_episodes, crash_rate,
max_rounds)``.  ``fleet/envelope.member_runner_for`` memoizes one
runner per envelope key, the same cache discipline the sim fleet
earned in PR 5.

On-device MEMBERSHIP invariants (``member_lane_verdict``) reduce each
lane to booleans inside the same jit, so only failing lanes ever pay
host transfer:

- **quorum intersection across epochs** — the observable consequence
  of same-view quorums intersecting across acceptor-set changes: no
  learner holds a value different from the chosen record for its
  instance (a divergent learn is exactly what non-intersecting
  epoch quorums would produce), and no event vid is chosen in two
  instances (an epoch-boundary double choose);
- **learner catch-up** — every live node listed as a learner in node
  0's final view has learned every chosen instance (the anti-entropy
  pull drained before the run completed);
- **coverage** — every churn-event vid was chosen, a lane-crashed
  injecting node excusing its events (the crash-aware rule of the
  sim fleet's verdict);
- **completed** — the driver's run-complete predicate held inside
  the round budget.

Lane-for-lane the fleet is DECISION-LOG-IDENTICAL to single
``ChurnEngine.run`` executions of the same (churn, schedule, seed):
``jax_threefry_partitionable`` makes batched draws equal per-lane
draws (the PR-4/5 parity argument), pinned by
tests/test_member_fleet.py.
"""

from __future__ import annotations

import dataclasses
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.analysis import tracecount
from tpu_paxos.fleet import runner as frun
from tpu_paxos.fleet import schedule_table as stm
from tpu_paxos.membership import churn_table as ctm
from tpu_paxos.membership import engine as meng
from tpu_paxos.utils import prng


class MemberLaneVerdict(NamedTuple):
    """Per-lane membership verdict vector(s); scalar per lane
    unbatched, [L] under the fleet vmap (module doc)."""

    ok: jnp.ndarray
    quorum: jnp.ndarray  # quorum-intersection observable (agreement)
    catchup: jnp.ndarray  # learner catch-up
    coverage: jnp.ndarray  # crash-excused event-vid coverage
    completed: jnp.ndarray  # run-complete inside the budget
    rounds: jnp.ndarray  # int32 rounds simulated


def member_lane_verdict(
    st: "meng.MemberState", ctab, done
) -> MemberLaneVerdict:
    """Judge one (unbatched) final churn-engine state on device — the
    fleet runner vmaps this inside the same jit as the round loop, so
    the verdict costs no extra dispatch."""
    from tpu_paxos.core import values as val

    chosen = st.chosen_vid  # [I]
    known = st.learned != val.NONE  # [I, N]
    # quorum intersection across epochs, as observed: a learner cell
    # disagreeing with the chosen record (incl. a learn where nothing
    # was chosen — chosen == NONE never equals a learned vid >= 0)
    agree = jnp.all(~known | (st.learned == chosen[:, None]))
    evalid = ctab.vid != val.NONE  # [E]; padding slots vacuous
    hit = ctab.vid[:, None] == chosen[None, :]  # [E, I]
    n_hit = jnp.sum(hit, axis=1, dtype=jnp.int32)  # [E]
    no_double = jnp.all(~evalid | (n_hit <= 1))
    quorum = agree & no_double

    n = st.crashed.shape[0]
    owed = (~st.crashed) & st.learners[0]  # [N]
    chosen_i = chosen != val.NONE  # [I]
    catchup = jnp.all(~chosen_i[:, None] | known | ~owed[None, :])

    via_crashed = st.crashed[jnp.clip(ctab.via, 0, n - 1)]  # [E]
    coverage = jnp.all(~evalid | (n_hit >= 1) | via_crashed)

    ok = quorum & catchup & coverage & done
    return MemberLaneVerdict(
        ok=ok,
        quorum=quorum,
        catchup=catchup,
        coverage=coverage,
        completed=done,
        rounds=st.t,
    )


@dataclasses.dataclass
class MemberFleetReport:
    """One dispatch's outcome.  ``final`` stays ON DEVICE — only the
    [lanes]-sized verdict vectors transfer here; callers extract full
    per-lane states (``lane_state`` / ``lane_log``) for failing lanes
    only."""

    n_nodes: int
    n_lanes: int
    seeds: list
    churns: list
    schedules: list
    verdict: MemberLaneVerdict  # host numpy, [lanes] per field
    final: object  # device MemberState, lane-leading
    injected: np.ndarray  # [lanes] events injected
    seconds: float

    @property
    def lanes_per_sec(self) -> float:
        return self.n_lanes / max(self.seconds, 1e-9)

    @property
    def failing(self) -> list:
        return [
            i for i in range(self.n_lanes) if not bool(self.verdict.ok[i])
        ]

    def lane_state(self, i: int):
        """Transfer ONE lane's final state (the triage hand-off)."""
        return jax.tree.map(lambda x: x[i], self.final)

    def lane_log(self, i: int) -> str:
        """One lane's canonical decision log — byte-equal to the
        single ``ChurnEngine.run`` of ``(churns[i], schedules[i],
        seeds[i])`` (the parity contract).  ``n_nodes`` is the
        dispatch's TRUE node count, so a geometry-padded lane's log
        lists applied[] rows only for nodes that exist."""
        return meng.decision_log_of(self.lane_state(i), self.n_nodes)


class MemberFleetRunner:
    """Compile-once membership-fleet front end for one envelope: the
    jitted vmapped whole-run churn driver plus the on-device member
    verdict.  ``run()`` is called per generation / per scenario batch
    with fresh seeds, churn schedules, and fault schedules — same
    executable."""

    def __init__(
        self,
        n_nodes: int,
        n_instances: int,
        *,
        max_events: int = ctm.MAX_EVENTS,
        max_episodes: int = frun.MAX_EPISODES,
        crash_rate: int = 0,
        max_rounds: int = 2000,
        mesh=None,
        geometry=None,
    ):
        if geometry is not None and n_nodes != geometry.bound_nodes:
            raise ValueError(
                "a geometry-padded member fleet must be built at the "
                f"envelope node bound ({geometry.bound_nodes}), got "
                f"n_nodes={n_nodes}"
            )
        self.geometry = geometry
        self.n = n_nodes
        self.i = n_instances
        self.c = n_instances * 2 + 8
        self.max_events = int(max_events)
        self.max_episodes = int(max_episodes)
        self.crash_rate = int(crash_rate)
        self.max_rounds = int(max_rounds)
        self.mesh = mesh
        round_fn = meng._build_round(
            n_nodes, n_instances, self.c, crash_rate,
            runtime_schedule=True, geometry=geometry,
        )
        # the SAME whole-run loop ChurnEngine dispatches for single
        # runs — shared so the lane body can never drift from the
        # parity twin the tests compare against
        loop = meng._build_churn_loop(
            round_fn, self.c, self.max_rounds, runtime_tables=True,
            padded=geometry is not None,
        )

        def lane(root, st, ctab, ftab, *gp):
            final, cur, done = loop(root, st, ctab, ftab, *gp)
            return final, cur, member_lane_verdict(final, ctab, done)

        # the shared initial state broadcasts (in_axes=None): the [I]-
        # sized arrays upload once, not per lane; padded lanes carry a
        # trailing [lanes] menu-index vector
        in_axes = (0, None, 0, 0) + ((0,) if geometry is not None else ())
        fl = jax.vmap(lane, in_axes=in_axes)
        if mesh is not None and mesh.size > 1:
            from tpu_paxos.parallel import mesh as pmesh

            # lane-axis tile, same shape as the sim fleet's: the
            # broadcast initial state stays replicated (every device
            # vmaps its lane block over the same st0); lane-stacked
            # roots/tables/outputs split on the leading lane axis
            # (SH001: the specs come from parallel/, never hand-built)
            spec = pmesh.instance_spec(mesh)
            in_specs = (spec, pmesh.replicated_spec(), spec, spec)
            if geometry is not None:
                in_specs = in_specs + (spec,)
            fl = pmesh.shard_map(
                fl, mesh,
                in_specs=in_specs,
                out_specs=spec,
            )
        self._fn = jax.jit(fl)

    def run(self, seeds, churns, schedules, n_nodes=None) -> MemberFleetReport:
        """One fleet dispatch: ``seeds[i]``, ``churns[i]``
        (ChurnSchedule or None), and ``schedules[i]`` (FaultSchedule
        or None) drive lane ``i``.  A geometry-padded runner takes the
        dispatch's TRUE node count via ``n_nodes=`` (menu-checked by
        name; churn events and schedules may only name true nodes).
        Returns once the verdict vector is on the host; the per-lane
        states stay on device."""
        if self.geometry is None:
            if n_nodes is not None:
                raise ValueError(
                    "n_nodes= is a geometry-padded dispatch input; "
                    "build the runner with a GeometryEnvelope"
                )
            gidx = None
        else:
            if n_nodes is None:
                raise ValueError(
                    "a geometry-padded member fleet takes its TRUE "
                    "node count per dispatch: run(n_nodes=...)"
                )
            gidx = self.geometry.index_of_nodes(n_nodes)
        seeds = [int(s) for s in seeds]
        churns = list(churns)
        schedules = list(schedules)
        n_lanes = len(seeds)
        if len(churns) != n_lanes or len(schedules) != n_lanes:
            raise ValueError("one churn + one schedule per lane required")
        if self.mesh is not None and n_lanes % max(self.mesh.size, 1):
            raise ValueError(
                f"{n_lanes} lanes do not tile over {self.mesh.size} devices"
            )
        for s in schedules:
            meng._check_member_schedule(s)
        # the capacity proof is the single-run engine's, applied per
        # lane BEFORE the batch encode (one implementation — a
        # headroom-rule change cannot diverge between paths)
        for li, churn_lane in enumerate(churns):
            meng._check_churn_capacity(
                ctm.encode_churn(churn_lane, self.n, self.max_events),
                self.i, self.c, lane=li,
            )
        ctabs = ctm.encode_churn_batch(churns, self.n, self.max_events)
        ftabs = stm.encode_batch(schedules, self.n, self.max_episodes)
        roots = jnp.stack([prng.root_key(s) for s in seeds])
        st0 = meng._init(self.n, self.i, self.c)
        t0 = time.perf_counter()  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
        with tracecount.engine_scope("member"):
            args = (
                roots, st0,
                jax.tree.map(jnp.asarray, ctabs),
                jax.tree.map(jnp.asarray, ftabs),
            )
            if gidx is not None:
                args = args + (
                    jnp.full((n_lanes,), gidx, jnp.int32),
                )
            final, cur, v = self._fn(*args)
        verdict = MemberLaneVerdict(*(np.asarray(x) for x in v))
        seconds = time.perf_counter() - t0  # verdict transfer = the sync  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
        return MemberFleetReport(
            n_nodes=self.n if self.geometry is None else int(n_nodes),
            n_lanes=n_lanes,
            seeds=seeds,
            churns=churns,
            schedules=schedules,
            verdict=verdict,
            final=final,
            injected=np.asarray(cur),
            seconds=seconds,
        )


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical membership-fleet trace (analysis/registry.py): 2
    lanes of a small geometry with distinct churn scenarios AND
    distinct episode mixes (a pause and a deterministic crash point)
    through the vmapped whole-run churn driver + the on-device member
    verdict — the runtime churn-table evaluation, the runtime fault
    masks, and the verdict reductions are all in the traced program
    the op budget pins."""
    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.core import faults as fltm

    def _scenarios(n_lanes):
        """The two canonical (churn, schedule) pairs, cycled over the
        lane count — distinct adjacent lanes so the mesh tiles never
        see a uniform fleet."""
        churns = [
            ctm.ChurnSchedule((
                ctm.ChurnEvent(vid=100),
                ctm.ChurnEvent(
                    vid=meng.change_vid(1, meng.ADD_ACCEPTOR),
                    wait=ctm.WAIT_CHOSEN,
                ),
            )),
            ctm.ChurnSchedule((
                ctm.ChurnEvent(vid=200),
                ctm.ChurnEvent(vid=201, wait=ctm.WAIT_CHOSEN),
                ctm.ChurnEvent(
                    vid=meng.change_vid(2, meng.ADD_ACCEPTOR),
                    wait=ctm.WAIT_APPLIED,
                ),
            )),
        ]
        scheds = [
            fltm.FaultSchedule((fltm.pause(2, 5, 1),)),
            fltm.FaultSchedule((fltm.crash(8, 2),)),
        ]
        return (
            [churns[i % 2] for i in range(n_lanes)],
            [scheds[i % 2] for i in range(n_lanes)],
        )

    def _runner(mesh=None):
        return MemberFleetRunner(
            3, 8, max_events=4, max_episodes=2, crash_rate=500,
            max_rounds=64, mesh=mesh,
        )

    def _setup(mesh=None, n_lanes=2):
        n = 3
        runner = _runner(mesh)
        churns, scheds = _scenarios(n_lanes)
        ctabs = jax.tree.map(
            jnp.asarray, ctm.encode_churn_batch(churns, n, 4)
        )
        ftabs = jax.tree.map(
            jnp.asarray, stm.encode_batch(scheds, n, 2)
        )
        roots = jnp.stack([prng.root_key(s) for s in range(n_lanes)])
        st0 = meng._init(n, 8, runner.c)
        return runner._fn, (roots, st0, ctabs, ftabs)

    def build():
        return _setup()

    def shard_build(mesh):
        # 8 lanes tile every shape of the committed mesh grid; the
        # canonical 2-lane trace stays the jaxpr/hlo-budget anchor
        return _setup(mesh=mesh, n_lanes=8)

    def shard_state():
        # st0 broadcasts under the fleet vmap (in_axes=None); the
        # SH301 tree is the lane-stacked view the tile actually maps,
        # so stack it to the canonical 2-lane shape here
        _, args = _setup()
        st0 = args[1]
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (2,) + x.shape), st0
        )
        return "member", stacked

    def shard_parity(n_devices):
        import hashlib

        from tpu_paxos.parallel import mesh as pmesh

        mesh = (
            pmesh.make_instance_mesh(n_devices) if n_devices > 1 else None
        )
        runner = _runner(mesh)
        churns, scheds = _scenarios(8)
        rep = runner.run(list(range(8)), churns, scheds)
        v = rep.verdict
        verdicts = "".join(
            format(
                (int(bool(v.quorum[i])) << 3)
                | (int(bool(v.catchup[i])) << 2)
                | (int(bool(v.coverage[i])) << 1)
                | int(bool(v.completed[i])),
                "x",
            )
            for i in range(rep.n_lanes)
        )
        logs = [
            hashlib.sha256(rep.lane_log(i).encode()).hexdigest()
            for i in range(rep.n_lanes)
        ]
        return {"verdicts": verdicts, "lane_logs": logs}

    return [
        AuditEntry(
            "member.fleet_lanes", build,
            covers=("MemberFleetRunner.__init__",), hlo_golden=True,
            shard_build=shard_build,
            shard_state=shard_state,
            shard_parity=shard_parity,
        ),
    ]
