"""Randomized schedule search: generate episode schedules from a
seeded grammar, run them as fleet lanes, shrink every wedge found.

The ROADMAP's fault-schedule follow-on asks for randomized schedule
*generation* — searching for minimal wedging schedules instead of
replaying the four hand-written stress mixes.  This module is that
searcher, built on the fleet runner so candidate schedules cost lanes
(one XLA dispatch per generation), not compiles:

1. per lane, sample a schedule from the seeded grammar
   (:func:`sample_schedule`: partition / one-way / pause / burst with
   jittered intervals, random groups, and random burst rates) and a
   fresh engine seed;
2. run the whole generation as one fleet dispatch; the on-device
   verdict subset plus the optional ``decision_round_max`` bound (the
   artifact-recorded extra check the triage stack already judges)
   flag suspicious lanes;
3. every flagged lane is re-run through the single-run engine — the
   fleet's lane-for-lane decision-log parity makes this a pure
   re-derivation — judged by the FULL invariant suite, greedily
   shrunk (``harness/shrink.py``), and written as a one-command repro
   artifact that ``python -m tpu_paxos repro`` replays
   byte-identically;
4. iterate generations until the budget runs out.

``python -m tpu_paxos fleet`` (or ``make fleet`` / ``make
fleet-quick``) prints ONE JSON summary line — lanes/sec, wedges
found, artifact paths — and exits non-zero only when a REAL invariant
violation was found (a ``decision_round_max`` bound is a synthetic
wedge knob: useful for exercising the triage path and for
convergence-latency hunting, but not a correctness failure).
"""

from __future__ import annotations

import argparse
import dataclasses
import importlib
import json
import os
import sys
import time

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as fltm

KINDS = ("partition", "one_way", "pause", "burst", "crash")

#: The WAN-extended grammar (``--gray``): gray failures join the draw
#: alphabet.  Opt-in, NOT the default — adding a kind changes the
#: seeded draw sequence, and the committed fleet-quick wedge artifact
#: (and its trace golden) are pinned against the classic alphabet.
KINDS_GRAY = KINDS + ("gray",)

#: Gray-episode delay-inflation draw bound (rounds).  Inflated delays
#: clamp at the envelope's ring bound inside the engine either way.
GRAY_DELAY_MAX = 5

#: Edge-matrix gene base-latency cap (``--wan``), matching the
#: committed WAN presets' range (core/wan.py peaks at 4+1 jitter,
#: which the stress WAN mixes prove convergent under the default
#: retry ladder).
GENE_LAT_MAX = 4

#: Crash-point grid resolution: crash ``t0`` draws land on this many
#: quantized slots across the first 3/4 of the horizon (the model
#: checker's (node, round)-grid discipline, analysis/modelcheck.py —
#: late crash points mostly land after convergence and waste draws).
CRASH_GRID = 8

#: Churn-event injection-round grid (``sample_churn_schedule``): t0
#: draws quantize to this many slots, the churn checker's t0_grid
#: discipline (analysis/mc_member.py).
CHURN_T0_GRID = 8

#: Plain-value vid base for churn-schedule draws.  Must stay equal to
#: ``analysis/mc_member.PLAIN_VID_BASE`` (pinned by test) — the
#: sampler cannot import the checker (the checker lives outside the
#: replay-critical DET closure this module is inside).
CHURN_PLAIN_VID_BASE = 100


@dataclasses.dataclass(frozen=True)
class Alphabet:
    """The declarative search-grammar spec, shared by ``search`` and
    ``fleet/evolve`` so the two samplers cannot drift: which episode
    kinds are drawable (in DRAW ORDER — reordering changes every
    seeded draw sequence), whether per-edge WAN fault matrices are
    genes, and the schedule-shape bounds.

    ``classic()`` reproduces the historical ``--gray``/``--wan``
    booleans exactly: the kinds tuples are the committed ``KINDS`` /
    ``KINDS_GRAY`` objects, so every seeded draw sequence — and the
    committed fleet-quick wedge artifact pinned against the classic
    grammar — is unchanged."""

    kinds: tuple = KINDS
    wan: bool = False
    max_episodes: int = 4
    horizon: int = 96

    def __post_init__(self) -> None:
        if not self.kinds:
            raise ValueError("alphabet needs at least one episode kind")
        bad = sorted(set(self.kinds) - set(KINDS_GRAY))
        if bad:
            raise ValueError(
                f"unknown episode kind(s): {', '.join(bad)} "
                f"(drawable: {', '.join(KINDS_GRAY)})"
            )
        if len(set(self.kinds)) != len(self.kinds):
            raise ValueError("alphabet kinds must be distinct")
        if self.max_episodes < 1:
            raise ValueError("max_episodes must be >= 1")
        if self.horizon < 8:
            raise ValueError("horizon must be >= 8 rounds")

    @classmethod
    def classic(
        cls, gray: bool = False, wan: bool = False,
        max_episodes: int = 4, horizon: int = 96,
    ) -> "Alphabet":
        return cls(
            kinds=KINDS_GRAY if gray else KINDS, wan=wan,
            max_episodes=max_episodes, horizon=horizon,
        )

    @property
    def gray(self) -> bool:
        return "gray" in self.kinds

    def member(self) -> "Alphabet":
        """The member-legal subset: gray is compiled out of the
        membership engine's synchronous network
        (analysis/mc_member.MEMBER_UNSUPPORTED_KINDS names the
        rejection), and the membership fleet takes no per-edge
        matrix knobs."""
        kinds = tuple(k for k in self.kinds if k != "gray")
        if not kinds:
            raise ValueError(
                "alphabet has no member-legal kinds (gray is the "
                "only kind and the membership engine rejects it)"
            )
        return dataclasses.replace(self, kinds=kinds, wan=False)

    def protocol(self):
        """WAN alphabets scale the retry ladder to the gene RTT —
        one protocol config for every lane keeps one envelope (see
        ``search`` for why LAN timeouts livelock under WAN genes)."""
        if not self.wan:
            return None
        from tpu_paxos.config import ProtocolConfig

        rtt = 2 * GENE_LAT_MAX + 2
        return ProtocolConfig(
            prepare_delay_max=rtt,
            prepare_retry_timeout=rtt,
            accept_retry_timeout=rtt,
            commit_retry_timeout=rtt,
        )

    def sample(self, rng: np.random.Generator, n_nodes: int):
        """One schedule draw under this alphabet (delegates to
        :func:`sample_schedule` — same draw sequence)."""
        return sample_schedule(
            rng, n_nodes, self.max_episodes, self.horizon,
            kinds=self.kinds,
        )

    def sample_episode(
        self, rng: np.random.Generator, n_nodes: int,
        crashed=frozenset(), kinds=None,
    ):
        """One episode draw under this alphabet (``kinds`` narrows
        the draw set for cause-targeted mutation; must be a subset)."""
        use = self.kinds if kinds is None else tuple(kinds)
        bad = sorted(set(use) - set(self.kinds))
        if bad:
            raise ValueError(
                f"kind(s) outside this alphabet: {', '.join(bad)}"
            )
        return sample_episode(
            rng, n_nodes, self.horizon, crashed=crashed, kinds=use
        )


def sample_episode(
    rng: np.random.Generator, n_nodes: int, horizon: int,
    crashed=frozenset(),
    kinds=KINDS,
) -> fltm.Episode:
    """One grammar draw: a kind, a jittered interval inside
    ``[0, horizon)``, and kind-specific random structure (groups /
    directions / pause sets / burst rates / crash points).

    ``crashed`` is the set of nodes earlier episodes of the SAME
    schedule already crash: the deterministic ``crash`` kind (PR 8's
    fail-stop crash points, never healed) must keep the schedule's
    TOTAL crashed set a minority — a majority-crash schedule has no
    quorum, so every lane would red on liveness and the search would
    drown in false wedges.  A crash draw without minority room falls
    back to a burst.  (The two branches consume DIFFERENT rng-draw
    counts; seeded reproducibility still holds because ``room`` is
    itself a deterministic function of the seeded draw history — the
    same seed always takes the same branch.  Don't compare draws
    across different ``crashed`` histories at one seed.)"""
    kind = kinds[int(rng.integers(len(kinds)))]
    t0 = int(rng.integers(0, max(1, horizon - 6)))
    width = int(rng.integers(4, max(5, horizon // 2)))
    t1 = min(t0 + width, horizon)
    if t1 <= t0:
        t1 = t0 + 1
    if kind == "crash":
        room = (n_nodes - 1) // 2 - len(crashed)
        avail = np.asarray(
            [n for n in range(n_nodes) if n not in crashed]
        )
        if room >= 1:
            k = int(rng.integers(1, room + 1))
            nodes = rng.permutation(avail)[:k]
            step = max(1, (3 * horizon // 4) // CRASH_GRID)
            t0c = int(rng.integers(0, CRASH_GRID)) * step
            return fltm.crash(t0c, *(int(x) for x in nodes))
        kind = "burst"  # no minority room left in this schedule
    if kind == "partition":
        nodes = rng.permutation(n_nodes)
        k = int(rng.integers(1, n_nodes))  # both sides non-empty
        return fltm.partition(
            t0, t1, tuple(int(x) for x in nodes[:k]),
            tuple(int(x) for x in nodes[k:]),
        )
    if kind == "one_way":
        nodes = rng.permutation(n_nodes)
        ns = int(rng.integers(1, n_nodes))
        nd = int(rng.integers(1, n_nodes))
        src = tuple(int(x) for x in nodes[:ns])
        dst = tuple(int(x) for x in rng.permutation(n_nodes)[:nd])
        return fltm.one_way(t0, t1, src, dst)
    if kind == "pause":
        n_paused = int(rng.integers(1, max(2, n_nodes // 2 + 1)))
        nodes = rng.permutation(n_nodes)[:n_paused]
        return fltm.pause(t0, t1, *(int(x) for x in nodes))
    if kind == "gray":
        # gray failures may hit ANY number of nodes (they are slow,
        # not dead — no quorum math caps the set), with a drawn
        # per-message delay inflation
        n_gray = int(rng.integers(1, n_nodes + 1))
        nodes = rng.permutation(n_nodes)[:n_gray]
        d = int(rng.integers(1, GRAY_DELAY_MAX + 1))
        return fltm.gray(t0, t1, *(int(x) for x in nodes), delay=d)
    return fltm.burst(t0, t1, int(rng.integers(500, 6000)))


def sample_schedule(
    rng: np.random.Generator,
    n_nodes: int,
    max_episodes: int = 4,
    horizon: int = 96,
    kinds=KINDS,
) -> fltm.FaultSchedule:
    n_eps = int(rng.integers(1, max_episodes + 1))
    eps, crashed = [], set()
    for _ in range(n_eps):
        e = sample_episode(rng, n_nodes, horizon, crashed=crashed,
                           kinds=kinds)
        if e.kind == "crash":
            crashed.update(e.nodes)
        eps.append(e)
    return fltm.FaultSchedule(tuple(eps))


def sample_edge_knobs(
    rng: np.random.Generator,
    n_nodes: int,
    delay_bound: int,
    base_drop: int = 300,
) -> FaultConfig:
    """One grammar draw over the per-edge FAULT MATRIX axis
    (``--wan``): a random node->"region" clustering whose cross-
    cluster edges carry drawn latency (+1 jitter) and drawn
    asymmetric loss on top of ``base_drop`` — WAN-shaped mixes as
    mutable search genes, riding the same envelope executable as
    every scalar mix (the fleet normalizes every lane to matrix
    knobs).  Base latencies are capped at the committed presets'
    range (``GENE_LAT_MAX``): the protocol's retry timeouts are
    static rounds, so a gene with EVERY edge slower than the retry
    ladder's patience livelocks the duel — a non-convergence the
    search would misreport as a wedge of the schedule."""
    from tpu_paxos.config import EdgeFaultConfig

    n_groups = int(rng.integers(2, max(3, n_nodes // 2 + 2)))
    gmap = rng.integers(0, n_groups, size=n_nodes)
    lat = rng.integers(1, 3, size=(n_groups, n_groups))
    lat = np.minimum(lat + lat.T, GENE_LAT_MAX)  # symmetric-ish base
    np.fill_diagonal(lat, 0)
    loss = rng.integers(0, 1200, size=(n_nodes, n_nodes))
    cross = gmap[:, None] != gmap[None, :]
    mind = lat[gmap[:, None], gmap[None, :]].astype(np.int64)
    maxd = np.minimum(mind + 1, delay_bound)
    drop = np.where(cross, base_drop + loss, base_drop)
    drop = np.minimum(drop, 10_000)
    np.fill_diagonal(drop, 0)
    # EdgeFaultConfig canonicalizes numpy rows to int tuples itself
    return FaultConfig(
        max_delay=int(delay_bound),
        edges=EdgeFaultConfig(
            drop_rate=drop,
            dup_rate=np.zeros_like(drop),
            min_delay=mind,
            max_delay=maxd,
        ),
    )


def sample_churn_schedule(
    rng: np.random.Generator,
    n_nodes: int,
    max_events: int = 3,
    horizon: int = 96,
    plain_values: int = 2,
    wait_gates: tuple = (0, 2),
):
    """One grammar draw over the MEMBERSHIP-schedule axis (ROADMAP
    item 3's named follow-on): a bounded sequence of ``ChurnEvent``
    genes — kind (plain value / add acceptor / del acceptor) x target
    x quantized ``t0`` (:data:`CHURN_T0_GRID` slots) x wait gate —
    legal by construction under the churn checker's rules
    (analysis/mc_member._seq_valid): vids are distinct (a target is
    added at most once, deleted at most once, and only after its
    add), node 0 (the harness driver) is never a target, and the
    first event's gate is ``WAIT_NONE``.  Returns ``None`` for the
    empty draw — the fault-only lane the checker's variant 0 is.

    ``wait_gates`` defaults to ``(WAIT_NONE, WAIT_APPLIED)`` — the
    committed churn scope's gate set (analysis/mc_scope.json)."""
    from tpu_paxos.membership import churn_table as ctm
    from tpu_paxos.membership import engine as meng

    n_ev = int(rng.integers(0, max_events + 1))
    if n_ev == 0:
        return None
    step = max(1, horizon // CHURN_T0_GRID)
    events = []
    plain_used: set = set()
    added_ever: set = set()
    live: set = set()
    for j in range(n_ev):
        t0 = int(rng.integers(0, CHURN_T0_GRID)) * step
        wait = (
            ctm.WAIT_NONE if j == 0
            else int(wait_gates[int(rng.integers(len(wait_gates)))])
        )
        plain_avail = [
            i for i in range(plain_values) if i not in plain_used
        ]
        add_avail = [
            n for n in range(1, n_nodes) if n not in added_ever
        ]
        del_avail = sorted(live)
        classes = (
            (["plain"] if plain_avail else [])
            + (["add"] if add_avail else [])
            + (["del"] if del_avail else [])
        )
        if not classes:
            break  # alphabet exhausted — shorter schedule, still legal
        kind = classes[int(rng.integers(len(classes)))]
        if kind == "plain":
            i = plain_avail[int(rng.integers(len(plain_avail)))]
            plain_used.add(i)
            vid = CHURN_PLAIN_VID_BASE + i
        elif kind == "add":
            tgt = add_avail[int(rng.integers(len(add_avail)))]
            added_ever.add(tgt)
            live.add(tgt)
            vid = meng.change_vid(tgt, meng.ADD_ACCEPTOR)
        else:
            tgt = del_avail[int(rng.integers(len(del_avail)))]
            live.discard(tgt)
            vid = meng.change_vid(tgt, meng.DEL_ACCEPTOR)
        events.append(ctm.ChurnEvent(vid=vid, t0=t0, wait=wait))
    if not events:
        return None
    return ctm.ChurnSchedule(tuple(events))


def churn_targets(churn) -> set:
    """The acceptor nodes a churn schedule's change events name —
    the crash-protected set (``{0} | targets``: a scheduled crash
    inside the epoch acceptor set can wedge its quorum forever,
    making liveness vacuously unjudgeable; same rule as
    analysis/mc_member.ChurnEnum.combo_feasible)."""
    from tpu_paxos.membership import engine as meng

    out: set = set()
    if churn is None:
        return out
    for e in churn.events:
        if int(e.vid) >= meng.CHANGE_BASE:
            out.add(meng.decode_change(int(e.vid))[0])
    return out


def sample_member_schedule(
    rng: np.random.Generator,
    n_nodes: int,
    churn=None,
    max_episodes: int = 2,
    horizon: int = 96,
    kinds=None,
) -> fltm.FaultSchedule:
    """A fault-schedule draw legal for MEMBERSHIP lanes: member-legal
    letters only (no gray — the member engine's synchronous network
    rejects it by name) and scheduled crashes avoid node 0 plus the
    churn schedule's named targets (passed pre-crashed into the
    episode sampler, so crash draws land outside the protected set
    by construction)."""
    if kinds is None:
        kinds = tuple(k for k in KINDS if k != "gray")
    protected = frozenset({0} | churn_targets(churn))
    n_eps = int(rng.integers(1, max_episodes + 1))
    eps, crashed = [], set(protected)
    for _ in range(n_eps):
        e = sample_episode(rng, n_nodes, horizon, crashed=crashed,
                           kinds=kinds)
        if e.kind == "crash":
            crashed.update(e.nodes)
        eps.append(e)
    return fltm.FaultSchedule(tuple(eps))


def lane_cause_series(rep, lanes) -> dict:
    """Per-LANE breach attribution (telemetry/diagnose.label_windows
    on one lane's own windowed series): ``{lane: cause series}`` for
    the requested lanes.  The aggregate ``cause_series`` in
    ``_generation_margins`` blames the generation; this blames the
    GENOME — evolve's cause-targeted mutation weighting credits the
    lane that actually produced the label, not whichever lane
    dominated the aggregate.  Lanes without telemetry are skipped."""
    from tpu_paxos.telemetry import diagnose as diag

    out: dict = {}
    for i in lanes:
        d = rep.lane_telemetry(int(i))
        if not d or "windows" not in d:
            continue
        out[int(i)] = diag.label_windows(
            d["windows"], region_pairs=d.get("region_pairs")
        )
    return out


def _generation_margins(rep, flagged=()) -> dict:
    """Reduce one generation's [lanes] flight-recorder summaries to
    the near-miss margin vector: the closest any lane came to a
    liveness wedge (prep for ROADMAP item 2's fitness selection).
    Margins shrink as lanes get closer to wedging — a fitness
    function minimizes heal_gap and maximizes the depth fields.

    The windowed SERIES fields turn the scalar margins into a
    trajectory the selection loop can climb: ``stall_margin_series``
    is, per virtual-clock bucket, the minimum over lanes of the
    stall headroom left before the engine's idle-restart/takeover
    patience (``core/sim.IDLE_RESTART_ROUNDS``) trips — a bucket at
    or below 0 means some lane actually stalled out there — and
    ``latency_p99_series``/``drop_series`` localize the latency and
    loss pressure to the buckets that produced them.  JSON schema
    stays additive: the scalar keys are unchanged."""
    from tpu_paxos.core.sim import IDLE_RESTART_ROUNDS
    from tpu_paxos.telemetry import recorder as telem

    ts = rep.telemetry
    if ts is None:
        return {}
    ws = getattr(rep, "windows", None)
    agg = telem.reduce_lanes(ts, ws)
    out = {k: agg[k] for k in (
        "heal_gap_min", "stall_depth_max", "duel_depth_max",
        "rounds_max", "takeovers", "latency_p99", "latency_max",
    )}
    if ws is not None:
        out["window_rounds"] = agg["windows"]["window_rounds"]
        out["stall_margin_series"] = telem.stall_margin_series(
            ws, IDLE_RESTART_ROUNDS
        )
        out["latency_p99_series"] = agg["windows"]["latency_p99"]
        out["drop_series"] = agg["windows"]["dropped"]
        # breach attribution over the generation's aggregate series
        # (telemetry/diagnose.py): the top cause per active bucket —
        # a schedule that saturates reads differently from one that
        # grays a region, and the selection loop can weight them
        from tpu_paxos.telemetry import diagnose as diag

        out["cause_series"] = diag.label_windows(
            agg["windows"], region_pairs=agg.get("region_pairs")
        )
        # per-lane attribution for the FLAGGED lanes: the aggregate
        # series blames the generation, these blame the genome — a
        # cause-targeted selection loop must credit the lane that
        # produced the label (one saturating lane would otherwise
        # paint every flagged lane's genes "saturation")
        if flagged:
            out["lane_causes"] = {
                str(i): c
                for i, c in lane_cause_series(rep, sorted(flagged)).items()
            }
    return out


def search(
    n_lanes: int,
    generations: int,
    base_seed: int = 0,
    triage_dir: str | None = None,
    decision_round_max: int | None = None,
    n_nodes: int = 5,
    n_prop: int = 2,
    fault_kw: dict | None = None,
    max_episodes: int = 4,
    horizon: int = 96,
    max_wedges: int = 8,
    mesh=None,
    verbose: bool = True,
    gray: bool = False,
    wan: bool = False,
    alphabet: Alphabet | None = None,
) -> dict:
    """Run the generation loop; returns the JSON-ready summary.

    The grammar is declared by ``alphabet`` (shared with
    ``fleet/evolve`` so the samplers cannot drift); when None, the
    legacy ``gray``/``wan`` booleans build the classic one:
    ``gray=True`` adds gray-failure episodes to the draw alphabet
    (``KINDS_GRAY``) and ``wan=True`` mutates the per-edge fault
    MATRIX per lane (``sample_edge_knobs``) — both opt-in: they
    change the seeded draw sequences, and the committed fleet-quick
    wedge artifact is pinned against the classic grammar."""
    from tpu_paxos.fleet import envelope as env
    from tpu_paxos.harness import shrink as shr
    from tpu_paxos.utils import log as logm

    # the stress workload builder lives outside the replay-critical
    # DET closure (it drives sweeps, it never makes replayed bytes) —
    # importlib keeps it out, the same way envelope.py keeps serve out
    strs = importlib.import_module("tpu_paxos.harness.stress")
    logger = logm.get_logger(
        "fleet", logm.parse_level("INFO" if verbose else "WARN")
    )
    if alphabet is None:
        alphabet = Alphabet.classic(
            gray=gray, wan=wan, max_episodes=max_episodes,
            horizon=horizon,
        )
    fault_kw = dict(fault_kw or dict(drop_rate=300, dup_rate=500, max_delay=2))
    wl_rng = np.random.default_rng(base_seed)
    workload, gates, chains = strs._workload(n_prop, wl_rng)
    # WAN genes need WAN timeouts: the default retry ladder is
    # LAN-tuned (2-round timeouts), so a matrix whose edges all
    # carry multi-round latency livelocks the duel and every lane
    # reds on liveness — noise, not signal.  Production WAN
    # deployments scale patience to RTT; so does the search
    # (one protocol config for all lanes = one envelope).
    protocol = alphabet.protocol()
    cfg = SimConfig(
        n_nodes=n_nodes,
        n_instances=2 * sum(len(w) for w in workload),
        proposers=tuple(range(n_prop)),
        seed=base_seed,
        max_rounds=20_000,
        faults=FaultConfig(**fault_kw),
        **({"protocol": protocol} if protocol is not None else {}),
    )
    # Shared envelope cache: the search rides the same compiled
    # executable as the stress sweep's fleet mixes and the shrinker's
    # candidate evaluations (schedules, knobs, seeds, and workloads
    # are all runtime inputs; cache users pass workloads explicitly —
    # the cache does not pin the template's queue order).  The episode
    # capacity floors at frun.MAX_EPISODES so the shrinker's candidate
    # evaluator (harness/shrink._runtime_candidate_eval, same floor)
    # lands on THIS envelope key and reuses the compile — capacity is
    # decision-log-neutral (unused episode rows are inert).
    from tpu_paxos.fleet import runner as frun

    runner = env.runner_for(
        cfg, workload, gates, mesh=mesh,
        max_episodes=max(alphabet.max_episodes, frun.MAX_EPISODES),
        telemetry=True,
    )
    lane_workloads = [(workload, gates)] * n_lanes
    lane_knobs = [cfg.faults] * n_lanes
    extra = (
        {"decision_round_max": int(decision_round_max)}
        if decision_round_max else {}
    )
    t0 = time.perf_counter()  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
    lanes_total = 0
    wedges: list[dict] = []
    anomalies: list[dict] = []
    gen_summaries: list[dict] = []
    for g in range(generations):
        sched_rng = np.random.default_rng((base_seed, g))
        schedules = [
            alphabet.sample(sched_rng, n_nodes)
            for _ in range(n_lanes)
        ]
        if alphabet.wan:
            # per-lane edge-matrix genes, re-drawn each generation
            # from their own seeded stream (schedule draws untouched)
            knob_rng = np.random.default_rng((base_seed, g, 7))
            lane_knobs = [
                sample_edge_knobs(
                    knob_rng, n_nodes, runner.delay_bound,
                    base_drop=cfg.faults.drop_rate,
                )
                for _ in range(n_lanes)
            ]
        seeds = [base_seed + g * n_lanes + i for i in range(n_lanes)]
        rep = runner.run(
            seeds, schedules,
            workloads=lane_workloads,
            knobs=lane_knobs,
        )
        lanes_total += n_lanes
        real_flagged = set(rep.failing)
        flagged = set(real_flagged)
        if decision_round_max is not None:
            flagged |= {
                i for i in range(n_lanes)
                if int(rep.verdict.max_round[i]) > decision_round_max
            }
        logger.info(
            "generation %d: %d lanes, %d flagged (%.1f lanes/sec)",
            g, n_lanes, len(flagged), rep.lanes_per_sec,
        )
        # Near-miss margin vector (telemetry/recorder.margins_vector):
        # how close the generation's closest lane came to a liveness
        # wedge — ROADMAP item 2's fitness signal, recorded per
        # generation so mutate-and-select has a gradient to climb.
        gen_summaries.append({
            "generation": g,
            "lanes": n_lanes,
            "flagged": len(flagged),
            "margins": _generation_margins(rep, flagged=flagged),
        })
        for i in sorted(flagged):
            if len(wedges) >= max_wedges:
                break
            # The synthetic decision_round_max check is attached ONLY
            # to lanes flagged by it alone: a lane red on the REAL
            # verdict must shrink against real invariants — with the
            # synthetic bound in its case, the greedy shrinker (which
            # accepts ANY still-failing candidate) could trade the
            # real violation for a harmless latency wedge and lose
            # the actual bug's minimal repro.
            case = shr.ReproCase(
                cfg=rep.lane_cfg(i), workload=workload, gates=gates,
                chains=chains,
                extra_checks={} if i in real_flagged else dict(extra),
            )
            _, viol = shr.run_case(case)
            if viol is None:
                # the on-device subset flagged a lane the full suite
                # clears — surface it, never hide it (a parity break
                # would show up exactly here)
                anomalies.append({
                    "generation": g, "lane": i, "seed": rep.seeds[i],
                    "verdict": {
                        f: bool(getattr(rep.verdict, f)[i])
                        for f in ("ok", "agreement", "coverage", "quiescent")
                    },
                })
                continue
            wedge = {
                "generation": g,
                "lane": i,
                "seed": rep.seeds[i],
                "violation": viol[:300],
                "synthetic": "decision_round_max" in (viol or ""),
                "schedule": rep.schedules[i].to_dict(),
            }
            if triage_dir:
                os.makedirs(triage_dir, exist_ok=True)
                path = os.path.join(
                    triage_dir, f"repro_fleet_g{g}_lane{i}.json"
                )
                try:
                    art = shr.triage(case, path, logger=logger)
                    wedge["artifact"] = path
                    wedge["shrink_seconds"] = art.get("shrink_seconds")
                    logger.info("wedge shrunk -> %s", path)
                except Exception as te:  # triage must never mask a find
                    wedge["triage_error"] = str(te)[:300]
            wedges.append(wedge)
        if len(wedges) >= max_wedges:
            logger.info("wedge budget (%d) reached", max_wedges)
            break
    seconds = time.perf_counter() - t0  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
    real = [w for w in wedges if not w["synthetic"]]
    return {
        "metric": "fleet_search",
        "lanes": n_lanes,
        "generations": generations,
        "lanes_total": lanes_total,
        "lanes_per_sec": round(lanes_total / max(seconds, 1e-9), 2),
        "seconds": round(seconds, 1),
        "wedges_found": len(wedges),
        "real_violations": len(real),
        "wedges": wedges,
        "anomalies": anomalies,
        "generation_telemetry": gen_summaries,
        "ok": not real and not anomalies,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos fleet",
        description="device-batched schedule search: sample episode "
        "schedules per lane, run them as one fleet dispatch per "
        "generation, shrink every wedge to a repro artifact",
    )
    ap.add_argument("--lanes", type=int, default=0,
                    help="lanes per generation (0 = backend default)")
    ap.add_argument("--generations", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--proposers", type=int, default=2)
    ap.add_argument("--max-episodes", type=int, default=4)
    ap.add_argument("--horizon", type=int, default=96,
                    help="grammar bound: every sampled episode ends "
                    "by this round")
    ap.add_argument("--max-wedges", type=int, default=8)
    ap.add_argument("--decision-round-max", type=int, default=0,
                    help="flag lanes whose latest decision lands "
                    "after this round (synthetic wedge knob; 0 = off)")
    ap.add_argument("--gray", action="store_true",
                    help="add gray-failure episodes (per-node delay "
                    "inflation) to the grammar alphabet")
    ap.add_argument("--wan", action="store_true",
                    help="mutate the per-edge fault matrix per lane "
                    "(WAN-shaped drop/latency genes)")
    ap.add_argument("--drop-rate", type=int, default=300)
    ap.add_argument("--dup-rate", type=int, default=500)
    ap.add_argument("--max-delay", type=int, default=2)
    ap.add_argument("--crash-rate", type=int, default=0)
    ap.add_argument("--triage-dir", type=str, default="",
                    help="shrink every wedge into a repro artifact "
                    "here (replay: python -m tpu_paxos repro <path>)")
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--mesh", type=int, default=0,
                    help="tile the lane axis over this many devices "
                    "(shard_map; lanes must divide evenly; with "
                    "--backend cpu, virtual devices are provisioned)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    # same backend/provisioning path as the repro CLI: a --mesh
    # request coerces auto -> cpu so virtual devices actually get
    # provisioned, and a short mesh fails loudly — silently running
    # unmeshed would let the user believe the tile was exercised
    # (importlib: the CLI module is not replay-critical and must not
    # join this module's DET closure)
    _select_backend = importlib.import_module(
        "tpu_paxos.__main__"
    )._select_backend
    mesh = None
    if args.mesh:
        backend = "cpu" if args.backend == "auto" else args.backend
        _select_backend(backend, args.mesh)
        from tpu_paxos.parallel import mesh as pmesh

        mesh = pmesh.make_instance_mesh(args.mesh)
        if mesh.size != args.mesh:
            raise SystemExit(
                f"--mesh {args.mesh} requested but only {mesh.size} "
                "device(s) came up (use --backend cpu for virtual "
                "provisioning)"
            )
    else:
        _select_backend(args.backend)
    from tpu_paxos.fleet import runner as frun
    n_lanes = args.lanes or frun.default_lane_count()
    if mesh is not None:
        n_lanes += (-n_lanes) % mesh.size  # lanes must tile the mesh
    summary = search(
        n_lanes=n_lanes,
        generations=args.generations,
        base_seed=args.seed,
        triage_dir=args.triage_dir or None,
        decision_round_max=args.decision_round_max or None,
        n_nodes=args.nodes,
        n_prop=args.proposers,
        fault_kw=dict(
            drop_rate=args.drop_rate, dup_rate=args.dup_rate,
            max_delay=args.max_delay, crash_rate=args.crash_rate,
        ),
        max_episodes=args.max_episodes,
        horizon=args.horizon,
        max_wedges=args.max_wedges,
        mesh=mesh,
        verbose=not args.quiet,
        gray=args.gray,
        wan=args.wan,
    )
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
