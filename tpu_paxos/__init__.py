"""tpu_paxos — a TPU-native multi-Paxos framework.

A from-scratch reimplementation of the capabilities of the reference
C++ multi-Paxos verifier (yuchenkan/multi-paxos), re-designed for TPU
hardware: per-instance consensus state lives in SoA arrays of shape
``[instances, nodes]``, the protocol runs as a bulk-synchronous round
function (pure JAX under ``jit``/``vmap``/``lax.scan``), communication
is node-axis reductions, cross-chip scale-out shards the instance axis
with ``shard_map`` + ``psum`` over ICI, and all asynchrony (network
drop/dup/delay, retries, dueling-proposer backoff, crashes) is
expressed as per-round masks and counters driven by ``jax.random``.

Layer map (mirrors SURVEY.md §1 for the reference):

- L0 primitives:   ``utils/prng.py`` (deterministic PRNG streams)
- L1 determinism:  ``replay/`` (decision logs in the reference
  grammar; replay = re-execution from the same seed)
- L2 embedder SPI: ``config.py`` (protocol/fault/sim knobs)
- L3 protocol:     ``core/fast.py`` (fused fault-free pipeline),
  ``core/sim.py`` (general fault-tolerant multi-round engine),
  ``core/net.py`` (arrival calendars + THNetWork fault masks),
  ``core/ballot.py``, ``core/apply.py``
- L4 value model:  ``core/values.py`` (interned int32 value ids)
- L5 harness:      ``harness/`` (whole-run invariant validation)
- scale-out:       ``parallel/`` (mesh, shard_map round loops)
- membership:      ``membership/`` (member/ parity: per-node role
  views, version-gated quorums, live reconfiguration)
- meta:            ``analysis/`` (paxlint static analysis of the
  determinism/jit-hygiene contract, repro-artifact schema, and the
  compile-census regression guard — pure AST, imports without jax)
"""

from tpu_paxos.config import (
    FaultConfig,
    ProtocolConfig,
    SimConfig,
)

__version__ = "0.2.0"

__all__ = [
    "ProtocolConfig",
    "FaultConfig",
    "SimConfig",
    "ballot",
    "values",
    "__version__",
]


def __getattr__(name):
    # Lazy re-exports (PEP 562): importing the package must not touch
    # jax — ``core.ballot``/``core.values`` build device constants at
    # import, which would initialize the backend before the CLI
    # (``python -m tpu_paxos`` imports this module first) can select
    # ``--backend``/``--mesh`` device provisioning.
    if name in ("ballot", "values"):
        import importlib

        return importlib.import_module(f"tpu_paxos.core.{name}")
    raise AttributeError(f"module 'tpu_paxos' has no attribute {name!r}")
