"""tpu_paxos — a TPU-native multi-Paxos framework.

A from-scratch reimplementation of the capabilities of the reference
C++ multi-Paxos verifier (yuchenkan/multi-paxos), re-designed for TPU
hardware: per-instance consensus state lives in SoA arrays of shape
``[instances, nodes]``, the protocol runs as a bulk-synchronous round
function (pure JAX under ``jit``/``vmap``/``lax.scan``), communication
is node-axis reductions, cross-chip scale-out shards the instance axis
with ``shard_map`` + ``psum`` over ICI, and all asynchrony (network
drop/dup/delay, retries, dueling-proposer backoff, crashes) is
expressed as per-round masks and counters driven by ``jax.random``.

Layer map (mirrors SURVEY.md §1 for the reference):

- L0 primitives:   ``utils/`` (PRNG streams, round counters, logging)
- L1 determinism:  ``replay/`` (seeded replay, decision logs)
- L2 embedder SPI: ``config.py`` + harness seams (workload, network
  fault model, state-machine apply hooks)
- L3 protocol:     ``core/`` (acceptor/proposer/learner round fns)
- L4 value model:  ``core/values.py`` (interned int32 value ids)
- L5 harness:      ``harness/`` (simulators, validation, CLI)
- scale-out:       ``parallel/`` (mesh, shard_map round loops)
- membership:      ``membership/`` (member/ parity: role masks,
  versions, reconfiguration)
- native runtime:  ``native/`` (C++ decision-log codec + invariant
  checker, loaded via ctypes)
"""

from tpu_paxos.config import (
    FaultConfig,
    ProtocolConfig,
    SimConfig,
)
from tpu_paxos.core import ballot, values

__version__ = "0.2.0"

__all__ = [
    "ProtocolConfig",
    "FaultConfig",
    "SimConfig",
    "ballot",
    "values",
    "__version__",
]
