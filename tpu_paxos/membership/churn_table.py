"""Runtime churn-schedule encoding: membership change events as data.

``MemberSim`` is a HOST churn driver: a Python program decides, round
by round, when to inject the next membership change or value —
faithful to member/main.cpp's wall-clock-paced driver, but it forces
a host round-trip per round (the baselined JAX103 debt PRs 2-11
carried) and caps the engine at a few rounds per second regardless of
how fast the round body runs.

This module is the ``ScheduleTable`` pattern (fleet/schedule_table.py)
applied to churn: SNIPPETS.md's observation that "member/'s
reconfiguration path is expressed as a per-round boolean membership
mask on the node axis" means churn is just DATA — so the driver's
decisions can be encoded once, up front, and evaluated INSIDE the
traced round loop.  A :class:`ChurnSchedule` is an ordered tuple of
:class:`ChurnEvent`\\ s; each event injects one value id (a plain
value or a membership-change vid, ``engine.change_vid``) into one
node's pending queue at the first round ``t >= t0`` where its WAIT
GATE holds:

- ``WAIT_NONE``    — ready as soon as the previous event is injected
  (the host driver's back-to-back ``propose(); add_acceptor()``);
- ``WAIT_CHOSEN``  — the previous event's vid has been chosen;
- ``WAIT_APPLIED`` — the previous event's vid is *Applied*: a
  majority of node 0's current acceptor view has learned it (the
  predicate the reference churn driver waits on,
  ref member/main.cpp:138-140, ``MemberSim.applied``).

Events inject strictly in order, at most one per round — a cursor
walks the table, so the whole driver is a pure function of
(table, engine state) and runs identically on host (the host-stepped
twin, ``engine.ChurnEngine.run_host``) and inside the
device-resident ``lax.while_loop`` (``engine.ChurnEngine.run``):
decision-log sha256 parity between the two is the pinned contract
(tests/test_churn_table.py).

Deterministic ``crash(t0, nodes)`` points are NOT encoded here: they
are fault-schedule episodes (core/faults.py) and ride the same
compiled-constant / runtime-``ScheduleTable`` lowerings as every
other episode kind — the membership engine now accepts them (dense
per-round node-axis masks, ``schedule_table.crashes_at``).

Like ``ScheduleTable``, a :class:`ChurnTable` is plain data (numpy on
host, jnp once traced), pads to a fixed event capacity (padding slots
hold ``vid == NONE`` and never inject), stacks along a leading lane
axis (:func:`encode_churn_batch`), and makes one compiled executable
cover every churn scenario of a ``(max_events, n_nodes)`` envelope —
the fleet's membership lanes vmap over it
(fleet/member_runner.py).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import numpy as np

from tpu_paxos.core import values as val

#: Wait-gate kinds (see module doc).
WAIT_NONE = 0
WAIT_CHOSEN = 1
WAIT_APPLIED = 2
WAIT_KINDS = (WAIT_NONE, WAIT_CHOSEN, WAIT_APPLIED)

#: Default event capacity of a churn envelope (the config-5 grow/
#: shrink scenario is 14 events at one value per step, 20 at two;
#: fleet scenarios stay smaller).
MAX_EVENTS = 24


@dataclasses.dataclass(frozen=True)
class ChurnEvent:
    """One driver decision: inject ``vid`` via node ``via`` at the
    first round ``t >= t0`` where the wait gate on the PREVIOUS event
    holds (module doc)."""

    vid: int
    via: int = 0
    t0: int = 0
    wait: int = WAIT_NONE

    def __post_init__(self) -> None:
        if self.vid < 0:
            raise ValueError(f"event vid must be >= 0, got {self.vid}")
        if self.via < 0:
            raise ValueError(f"event via must be a node index, got {self.via}")
        if self.t0 < 0:
            raise ValueError(f"event t0 must be >= 0, got {self.t0}")
        if self.wait not in WAIT_KINDS:
            raise ValueError(
                f"event wait must be one of {WAIT_KINDS}, got {self.wait}"
            )


@dataclasses.dataclass(frozen=True)
class ChurnSchedule:
    """An immutable ordered sequence of churn events (module doc)."""

    events: tuple[ChurnEvent, ...] = ()

    def __post_init__(self) -> None:
        object.__setattr__(self, "events", tuple(self.events))
        for e in self.events:
            if not isinstance(e, ChurnEvent):
                raise TypeError(f"events must be ChurnEvent, got {type(e)}")
        if self.events and self.events[0].wait != WAIT_NONE:
            # event 0 has no predecessor to wait on; a non-NONE gate
            # would silently never fire on the device path
            raise ValueError("the first event's wait gate must be WAIT_NONE")
        vids = [e.vid for e in self.events]
        if len(vids) != len(set(vids)):
            raise ValueError(
                "event vids must be distinct (the wait gates and the "
                "run-complete predicate identify events by vid)"
            )

    # -- JSON plumbing (injection logs / repro artifacts) --
    def to_dict(self) -> dict:
        return {
            "events": [
                {"vid": e.vid, "via": e.via, "t0": e.t0, "wait": e.wait}
                for e in self.events
            ]
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ChurnSchedule":
        return cls(tuple(
            ChurnEvent(
                vid=e["vid"], via=e.get("via", 0), t0=e.get("t0", 0),
                wait=e.get("wait", WAIT_NONE),
            )
            for e in d.get("events", [])
        ))


class ChurnTable(NamedTuple):
    """One scenario's churn schedule as dense runtime arrays (host:
    numpy; traced: jnp with an optional leading lane axis).  Padding
    slots hold ``vid == NONE`` and sit past ``n_events``, so any
    schedule with at most ``E`` events fits the same shapes."""

    t0: np.ndarray  # [E] int32 earliest injection rounds
    via: np.ndarray  # [E] int32 injecting node per event
    vid: np.ndarray  # [E] int32 value ids (padding: NONE)
    wait: np.ndarray  # [E] int32 wait-gate kind per event
    is_change: np.ndarray  # [E] bool vid >= CHANGE_BASE
    n_events: np.ndarray  # [] int32 real (un-padded) event count


def encode_churn(
    sched: ChurnSchedule | None,
    n_nodes: int,
    max_events: int | None = None,
) -> ChurnTable:
    """Encode one schedule (None/empty = the no-churn table: the
    cursor starts satisfied and the driver just runs the engine)."""
    from tpu_paxos.membership import engine as meng

    eps = () if sched is None else sched.events
    e_cap = len(eps) if max_events is None else max_events
    e_cap = max(e_cap, 1)  # zero-length event axes break vmap stacking
    if len(eps) > e_cap:
        raise ValueError(
            f"churn schedule has {len(eps)} events; table capacity is "
            f"{e_cap}"
        )
    t0 = np.zeros((e_cap,), np.int32)
    via = np.zeros((e_cap,), np.int32)
    vid = np.full((e_cap,), int(val.NONE), np.int32)
    wait = np.zeros((e_cap,), np.int32)
    for i, e in enumerate(eps):
        if e.via >= n_nodes:
            raise ValueError(
                f"event {i} injects via node {e.via} but the cluster "
                f"has {n_nodes} nodes"
            )
        if e.vid >= meng.CHANGE_BASE:
            tgt, kind = meng.decode_change(e.vid)
            if tgt >= n_nodes:
                raise ValueError(
                    f"event {i} changes node {tgt} but the cluster "
                    f"has {n_nodes} nodes"
                )
        t0[i], via[i], vid[i], wait[i] = e.t0, e.via, e.vid, e.wait
    return ChurnTable(
        t0=t0,
        via=via,
        vid=vid,
        wait=wait,
        is_change=vid >= np.int32(meng.CHANGE_BASE),
        n_events=np.int32(len(eps)),
    )


def encode_churn_batch(
    schedules,
    n_nodes: int,
    max_events: int | None = None,
) -> ChurnTable:
    """Stack one table per lane along a leading lane axis (the fleet's
    membership-lane input).  All lanes share one event capacity (the
    max over lanes unless given)."""
    schedules = list(schedules)
    if not schedules:
        raise ValueError("encode_churn_batch needs at least one lane")
    if max_events is None:
        max_events = max(
            len(s.events) if s is not None else 0 for s in schedules
        )
    tabs = [encode_churn(s, n_nodes, max_events) for s in schedules]
    return ChurnTable(
        *(np.stack([getattr(t, f) for t in tabs]) for f in ChurnTable._fields)
    )


def grow_shrink_schedule(
    grow_to: int,
    shrink_to: int,
    values_per_step: int = 1,
    first_vid: int = 100,
) -> ChurnSchedule:
    """The canonical BASELINE config-5 churn scenario as a table: grow
    the acceptor set ``{0} -> {0..grow_to-1}`` one AddAcceptor at a
    time with ``values_per_step`` plain values proposed before each
    change, then shrink back to ``{0..shrink_to-1}`` — each change
    waits for the previous change's Applied, exactly the host driver
    sequence ``bench_member_record`` and the config-5 churn test
    step."""
    from tpu_paxos.membership import engine as meng

    if not 1 <= shrink_to <= grow_to:
        raise ValueError("need 1 <= shrink_to <= grow_to")
    events: list[ChurnEvent] = []
    vid = first_vid
    for tgt in range(1, grow_to):
        # each step waits for the PREVIOUS change's Applied, then its
        # values ride ahead of its own change back-to-back (the host
        # driver's propose(); add_acceptor() sequence) — the gate sits
        # on whichever event opens the step, so the sequencing holds
        # even with values_per_step=0
        step_wait = WAIT_APPLIED if events else WAIT_NONE
        for _ in range(values_per_step):
            events.append(ChurnEvent(vid=vid, via=0, wait=step_wait))
            step_wait = WAIT_NONE
            vid += 1
        events.append(ChurnEvent(
            vid=meng.change_vid(tgt, meng.ADD_ACCEPTOR), via=0,
            wait=step_wait,
        ))
    for tgt in range(grow_to - 1, shrink_to - 1, -1):
        events.append(ChurnEvent(
            vid=meng.change_vid(tgt, meng.DEL_ACCEPTOR), via=0,
            wait=WAIT_APPLIED,
        ))
    return ChurnSchedule(tuple(events))
