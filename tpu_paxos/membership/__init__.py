"""Membership engine — member/ parity (live reconfiguration).

The reference's ``member/`` variant supports live membership change:
roles form a ladder Learner <-> Proposer <-> Acceptor with six
transition types (ref member/paxos.cpp:61-69), composite operations
like AddAcceptor = [ADD_LEARNER, LEARNER_TO_PROPOSER,
PROPOSER_TO_ACCEPTOR] ride the log as a single value
(ref member/paxos.cpp:650-657), every node applies changes when its
own learner frontier reaches them (ref member/paxos.cpp:1862-1964
ChangeMemberships), acceptor-set changes bump a Version that gates
all prepare/accept processing (ref member/paxos.cpp:1702, 1747), and
a change is "Applied" once a majority of the current acceptors have
learned it (ref member/paxos.cpp:1716-1733 OnLearnReply) — the
sequencing point the churn harness waits on
(ref member/main.cpp:138-140).

Here the cluster state is node-axis boolean role masks per *viewing
node* (each node has its own view, updated at its own apply
frontier), versions are per-node ints, and the protocol runs as a
synchronous bulk round loop — faithful to member/'s network, which
delivers synchronously by calling the peer's OnReceive directly
(ref member/main.cpp:65-79).
"""

from tpu_paxos.membership.churn_table import (
    WAIT_APPLIED,
    WAIT_CHOSEN,
    WAIT_NONE,
    ChurnEvent,
    ChurnSchedule,
    ChurnTable,
    encode_churn,
    encode_churn_batch,
    grow_shrink_schedule,
)
from tpu_paxos.membership.engine import (
    ADD_ACCEPTOR,
    DEL_ACCEPTOR,
    ChurnEngine,
    ChurnResult,
    MemberSim,
    change_vid,
    decision_log_of,
    decode_change,
    is_change_vid,
    membership_suffix,
)

__all__ = [
    "ADD_ACCEPTOR",
    "DEL_ACCEPTOR",
    "WAIT_APPLIED",
    "WAIT_CHOSEN",
    "WAIT_NONE",
    "ChurnEngine",
    "ChurnEvent",
    "ChurnResult",
    "ChurnSchedule",
    "ChurnTable",
    "MemberSim",
    "change_vid",
    "decision_log_of",
    "decode_change",
    "encode_churn",
    "encode_churn_batch",
    "grow_shrink_schedule",
    "is_change_vid",
    "membership_suffix",
]
