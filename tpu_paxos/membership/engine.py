"""Synchronous-round membership engine (member/ parity).

One loop iteration = one synchronous message exchange — faithful to
member/'s network, which delivers by calling the peer's ``OnReceive``
inline (ref member/main.cpp:65-79).  There are no drops or delays in
this variant (member/'s network is reliable); liveness needs only the
anti-dueling prepare backoff and an accept-staleness restart (covering
version races, ref Proposer::AcceptorsChanged member/paxos.cpp:1862-1908).

Crash injection (the member/ fault model): each live node crashes with
probability ``crash_rate``/1e6 per round, the round analog of
``Thread::RandomFailure`` firing with failure_rate/1e6 per log call
(ref member/indet.h:146-150, member/debug.conf.sample field 3).  A
crashed node is fail-stop silent: it grants no promises, acks no
accepts, learns nothing, applies nothing, and proposes nothing.  Its
entries in everyone's views persist — quorum denominators do NOT
shrink on crash; only a DEL_ACCEPTOR through the log shrinks them.
Two deliberate strengthenings over the reference, whose RandomFailure
aborts the entire simulation process and validates only the replayed
prefix: (a) crashes here are per-node and the surviving majority keeps
running (prefix consistency must hold across dead and live logs
alike), so admission is capped — a crash is only admitted if every
live node's view retains a live majority of its acceptors — and
(b) node 0 never crashes, because it plays the reference harness's
driver role (member/main.cpp proposes and churns through nodes[0]).
The cap holds at crash time only: a later DEL_ACCEPTOR of a live node
can shrink a view below live majority, and an ADD_ACCEPTOR of a
crashed node can inflate the quorum denominator without adding a live
acceptor — ``MemberSim.add_acceptor``/``del_acceptor`` guard against
both host-side.

Cluster bootstrap: every node's view starts as {0} in all three role
sets (ref NodeImpl::Loop, member/paxos.cpp:729-737: only node ``first_``
exists; only it instantiates Proposer+Acceptor).  All growth happens
through the log.

Membership-change values: one log entry carries a whole change vector
(ref ProposedValue(changes, cb), member/paxos.cpp:650-657) — encoded
here as a single vid >= CHANGE_BASE with a (target node, kind) pair,
where composite kinds expand to the reference's vectors:
ADD_ACCEPTOR -> [ADD_LEARNER, LEARNER_TO_PROPOSER,
PROPOSER_TO_ACCEPTOR], DEL_ACCEPTOR -> [ACCEPTOR_TO_PROPOSER,
PROPOSER_TO_LEARNER, DEL_LEARNER].

Version gating: prepare and accept messages carry the sender's
version and acceptors drop them unless it equals their own
(ref member/paxos.cpp:1702, 1747); each acceptor-set change bumps the
applying node's version by one (ref member/paxos.cpp:1897, 1951), so
two nodes agree on version iff they have applied the same number of
acceptor changes — i.e. the gate enforces same-view quorums.

Applied semantics: a chosen value is *Applied* once a majority of the
(current-view) acceptors have learned it
(ref Proposer::OnLearnReply, member/paxos.cpp:1716-1733); the churn
driver waits for Applied before issuing the next change
(ref member/main.cpp:138-140) — ``MemberSim.applied`` exposes exactly
this predicate.

Ordering and scale intent: member/'s reference harness has no
in-order clients (that is multi/'s workload, covered by core/sim's
gate arrays); its only ordering constraint is the host driver waiting
on Applied/chosen between dependent proposals — the same pattern
``MemberSim.run_until`` provides, and
``MemberSim.propose_in_order`` packages (see
tests/test_membership.py).  This engine is the *control-plane*
variant: churn events are rare, so it optimizes for reconfiguration
semantics, not instance throughput — bulk data-plane consensus at
scale is core/sim + parallel/sharded_sim, whose benchmarks carry the
throughput story.

Two drivers share the round function.  ``MemberSim`` is the HOST
driver — an arbitrary Python program decides the injections round by
round (faithful to member/main.cpp; the injection log makes it
replayable) at the cost of a dispatch + predicate reads per round.
``ChurnEngine`` is the DEVICE-RESIDENT driver: the decisions are
encoded up front as a runtime ``ChurnTable``
(membership/churn_table.py) and evaluated inside a
``lax.while_loop``, so a whole churn scenario is one dispatch — the
``sim._run_loop`` analog, decision-log sha256-identical to its
host-stepped twin (``ChurnEngine.run_host``).  Deterministic
``crash(t0, nodes)`` episodes (core/faults.py) are accepted by both
drivers on both the compiled-constant and runtime-table schedule
paths; only node 0 — the harness driver's seat — may not be
crash-scheduled.
"""

from __future__ import annotations

import hashlib
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.analysis import tracecount
from tpu_paxos.core import ballot as bal
from tpu_paxos.core import geom as geo
from tpu_paxos.core import values as val
from tpu_paxos.membership import churn_table as ctm
from tpu_paxos.utils import prng

# Change kinds (ref member/paxos.cpp:61-69 enum MembershipChangeType)
ADD_LEARNER = 0
LEARNER_TO_PROPOSER = 1
PROPOSER_TO_ACCEPTOR = 2
DEL_LEARNER = 3
PROPOSER_TO_LEARNER = 4
ACCEPTOR_TO_PROPOSER = 5
# Composites (one log entry each, ref member/paxos.cpp:650-657, 706-714)
ADD_ACCEPTOR = 6
DEL_ACCEPTOR = 7

CHANGE_BASE = 2**28
COMMITTED_BALLOT = jnp.int32(2**30)
_NEG = jnp.int32(jnp.iinfo(jnp.int32).min)

ACCEPT_STALE_ROUNDS = 4  # restart prepare if a batch stalls this long

# Idle-liveness patience (core/sim's IDLE_RESTART_ROUNDS transplanted):
# an idle live proposer re-prepares after this many rounds whenever the
# log is unresolved — a hole below the chosen high-water mark, or a
# value accepted by a live acceptor but never chosen because its
# proposer crashed mid-accept.  The fresh prepare's adoption re-accepts
# the orphan and no-op fill plugs the hole.
REPAIR_STALL_ROUNDS = 8


def _file_sha256(path) -> str:
    """Content hash of a checkpoint artifact — pins a rejoin's input
    file in the injection log so replay can detect a swapped file."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def change_vid(node: int, kind: int) -> int:
    """Encode a membership change as a value id."""
    return CHANGE_BASE + node * 8 + kind


def is_change_vid(vid) -> bool:
    return np.asarray(vid) >= CHANGE_BASE


def decode_change(vid: int) -> tuple[int, int]:
    """-> (target node, kind)."""
    k = int(vid) - CHANGE_BASE
    return k // 8, k % 8


def membership_suffix(vid: int) -> str | None:
    """Decision-log suffix in the reference grammar
    (ref multi/paxos.cpp:20-22): ``m+id=ip:port`` for additive
    changes, ``m-id`` for removals; None for non-change vids.  Node
    addresses are synthetic, as in the reference harness where the
    port is just the peer index (ref multi/main.cpp:265-268)."""
    if vid < CHANGE_BASE:
        return None
    node, kind = decode_change(vid)
    additive = kind in (
        ADD_LEARNER,
        LEARNER_TO_PROPOSER,
        PROPOSER_TO_ACCEPTOR,
        ADD_ACCEPTOR,
    )
    return f"m+{node}=node:{node}" if additive else f"m-{node}"


class MemberState(NamedTuple):
    t: jax.Array
    crashed: jax.Array  # [N] bool fail-stop crash mask
    # per-viewing-node role masks: row v = node v's view
    learners: jax.Array  # [N, N] bool
    proposers: jax.Array  # [N, N] bool
    acceptors: jax.Array  # [N, N] bool
    version: jax.Array  # [N] int32
    # acceptor state
    promised: jax.Array  # [N] int32
    max_seen: jax.Array  # [N] int32
    acc_ballot: jax.Array  # [I, N] int32
    acc_vid: jax.Array  # [I, N] int32
    # learner state
    learned: jax.Array  # [I, N] int32
    applied_upto: jax.Array  # [N] int32 apply frontier
    # proposer state
    count: jax.Array  # [N] int32
    ballot: jax.Array  # [N] int32
    pmax: jax.Array  # [N] int32 max ballot seen via rejects
    prepared: jax.Array  # [N] bool
    delay_until: jax.Array  # [N] int32 prepare backoff
    adopted_b: jax.Array  # [N, I] int32
    adopted_v: jax.Array  # [N, I] int32
    cur_batch: jax.Array  # [N, I] int32
    acks: jax.Array  # [N, I, N] bool
    batch_age: jax.Array  # [N] int32 rounds since batch progress
    own_assign: jax.Array  # [N, I] int32
    pend: jax.Array  # [N, C] int32
    head: jax.Array  # [N] int32
    tail: jax.Array  # [N] int32
    stall: jax.Array  # [N] int32 idle rounds while the log is unresolved
    # decisions
    chosen_vid: jax.Array  # [I] int32
    chosen_round: jax.Array  # [I] int32
    chosen_ballot: jax.Array  # [I] int32


def _init(n: int, i: int, c: int) -> MemberState:
    none = lambda *sh: jnp.full(sh, bal.NONE, jnp.int32)  # noqa: E731
    zero = lambda *sh: jnp.zeros(sh, jnp.int32)  # noqa: E731
    seed_view = jnp.zeros((n, n), jnp.bool_).at[:, 0].set(True)
    return MemberState(
        t=jnp.int32(0),
        crashed=jnp.zeros((n,), jnp.bool_),
        learners=seed_view,
        proposers=seed_view,
        acceptors=seed_view,
        version=zero(n),
        promised=zero(n),
        max_seen=zero(n),
        acc_ballot=none(i, n),
        acc_vid=none(i, n),
        learned=none(i, n),
        applied_upto=zero(n),
        count=zero(n),
        ballot=zero(n),
        pmax=zero(n),
        prepared=jnp.zeros((n,), jnp.bool_),
        delay_until=zero(n),
        adopted_b=none(n, i),
        adopted_v=none(n, i),
        cur_batch=none(n, i),
        acks=jnp.zeros((n, i, n), jnp.bool_),
        batch_age=zero(n),
        own_assign=none(n, i),
        pend=none(n, c),
        head=zero(n),
        tail=zero(n),
        stall=zero(n),
        chosen_vid=none(i),
        chosen_round=none(i),
        chosen_ballot=none(i),
    )


def _build_round(
    n: int,
    i_cap: int,
    c: int,
    crash_rate: int = 0,
    comp=None,
    runtime_schedule: bool = False,
    geometry=None,
):
    """``geometry`` (core/geom.GeometryEnvelope) builds the
    geometry-PADDED round: ``n`` must be the envelope's node bound and
    the round takes a traced menu index (``round_fn(root, st, tab,
    gidx)``), which dispatches the engine's two node-shaped PRNG draws
    — the anti-dueling backoff and the i.i.d. crash coins — through
    ``lax.switch`` branches at each entry's TRUE node count (threefry
    bits are shape-dependent), bit-identical to the unpadded build.
    The member engine is already runtime-membership everywhere else:
    nodes beyond the true count never join a view, so every mask-
    driven phase ignores them for free.  Requires
    ``runtime_schedule=True`` (the padded engine is fleet data).

    ``comp`` is a compiled fault schedule (core/faults.py) or None;
    with ``runtime_schedule=True`` the schedule instead arrives as a
    traced ``fleet/schedule_table.ScheduleTable`` argument (the
    round becomes ``round_fn(root, st, tab)``) and the per-round masks
    are computed inside the step — one compiled program covers every
    episode mix of the table's envelope, decision-log-identical to the
    compiled-constant path (tests/test_churn_table.py pins it).
    member/'s network is synchronous — request and reply happen in one
    step — so an edge functions only when reachability holds in BOTH
    directions; one-way cuts therefore sever the whole exchange on the
    affected edges (the asymmetric-delivery story belongs to the
    calendar network of core/sim).  Pauses subtract from the alive
    mask like crashes but preserve state and heal at episode end.
    Deterministic ``crash(t0, nodes)`` episodes fail-stop at the END
    of round ``t0`` (the i.i.d. injection's timing) and compose with
    the i.i.d. admission cap: scheduled crashes land first, so the
    cap's live-majority room accounts for them."""
    from tpu_paxos.fleet import schedule_table as stm

    if geometry is not None:
        if not runtime_schedule:
            raise ValueError(
                "a geometry-padded member round needs "
                "runtime_schedule=True (the padded engine is fleet "
                "data, not a compiled constant)"
            )
        if n != geometry.bound_nodes:
            raise ValueError(
                "a geometry-padded member round must be built at the "
                f"envelope node bound ({geometry.bound_nodes}), got "
                f"n={n}"
            )
    idx = jnp.arange(i_cap, dtype=jnp.int32)
    rows = jnp.arange(n)
    horizon = comp.horizon if comp is not None else 0
    pause_tab = (
        jnp.asarray(comp.paused) if comp is not None and comp.has_pause else None
    )
    reach_tab = (
        jnp.asarray(comp.reach) if comp is not None and comp.has_reach else None
    )
    crash_tab = (
        jnp.asarray(comp.crashed) if comp is not None and comp.has_crash else None
    )

    def _round_core(root, st: MemberState, tab, gidx=None) -> MemberState:
        t = st.t
        exist = ~st.crashed  # [N] not-crashed (excusals key off this)
        if runtime_schedule:
            reach_t, pause_t, _extra, _gray = stm.masks_at(tab, t)
            reach2_t = reach_t & reach_t.T  # synchronous exchange
            sched_crash = stm.crashes_at(tab, t)
            alive = exist & ~pause_t
        else:
            tt = jnp.minimum(t, jnp.int32(horizon)) if comp is not None else None
            alive = exist  # [N] I/O-alive: crashed/paused act in no role
            if pause_tab is not None:
                alive = alive & ~pause_tab[tt]
            if reach_tab is not None:
                reach_t = reach_tab[tt]
                reach2_t = reach_t & reach_t.T  # synchronous exchange
            else:
                reach_t = reach2_t = None
            sched_crash = crash_tab[tt] if crash_tab is not None else None
        # node-local roles (a node acts on its OWN view of itself;
        # crashed nodes act in no role)
        is_prop = st.proposers[rows, rows] & alive  # [N]
        is_accp = st.acceptors[rows, rows] & alive  # [N]
        quorum_v = (
            jnp.sum(st.acceptors, axis=1, dtype=jnp.int32) // 2 + 1
        )  # [N] majority of each node's view (crashes do NOT shrink it)

        # ---------- ACCEPT phase (batches from previously prepared) ----
        send_acc = (
            st.prepared & jnp.any(st.cur_batch != val.NONE, axis=1) & alive
        )
        # version gate: acceptor a processes proposer v iff equal
        # versions (ref member/paxos.cpp:1747) and a is an acceptor in
        # v's view and its own
        edge = (
            send_acc[:, None]
            & st.acceptors[:, :]  # v targets its view's acceptors
            & is_accp[None, :]
            & (st.version[:, None] == st.version[None, :])
        )  # [V, A]
        if reach2_t is not None:
            edge = edge & reach2_t
        elig = edge & (st.ballot[:, None] >= st.promised[None, :])
        max_seen = jnp.maximum(
            st.max_seen,
            jnp.max(jnp.where(edge, st.ballot[:, None], bal.NONE), axis=0),
        )
        # rejects flow back synchronously
        rejed = edge & ~elig
        pmax = jnp.maximum(
            st.pmax, jnp.max(jnp.where(rejed.T, max_seen[:, None], bal.NONE).T, axis=1),
        )

        # The [V, I, A]-cube work — stores, ack accumulation, quorum
        # detection, learn broadcast — runs only while a prepared
        # proposer has an open batch (the port of core/sim.py's
        # event gating).  send_acc covers EVERY round the block can
        # change anything: elig ⊆ edge ⊆ send_acc, and inst_chosen
        # needs an open batch, which a cleared/unprepared proposer
        # cannot have (cur_batch is NONE'd the round prepared drops) —
        # so even the quorum-shrinks-under-an-accumulated-ack-set case
        # stays inside the gate.  The proposer axis is unrolled into
        # running elementwise maxes (exact: ballots are unique per
        # node; chosen values agree per instance) instead of the old
        # argmax + gather cubes.
        any_acc = jnp.any(send_acc)

        def _accept_phase(acc_ballot, acc_vid, acks, cvid, cround, cballot,
                          learned):
            is_comm = learned != val.NONE  # [I, A]
            best_b = jnp.full((i_cap, n), bal.NONE, jnp.int32)
            best_v = jnp.full((i_cap, n), val.NONE, jnp.int32)
            lbest = jnp.full((i_cap, n), _NEG, jnp.int32)
            any_new = jnp.zeros((i_cap,), jnp.bool_)
            new_v = jnp.full((i_cap,), _NEG, jnp.int32)
            new_b = jnp.full((i_cap,), _NEG, jnp.int32)
            none_yet = cvid == val.NONE  # [I]
            new_acks, newly_rows = [], []
            w_has = st.cur_batch != val.NONE  # [V, I]
            # Per-proposer cond: only proposers with an open accept
            # batch this round (send_acc[v]) pay their [I, A] passes.
            # Exact by the same argument as the outer gate — for
            # ~send_acc[v], ackv is all-false (elig[v] ⊆ send_acc[v]),
            # so best/acks/lbest contributions are identities, and
            # inst_chosen[v] is all-false (an open batch implies
            # prepared & alive, which with w_has is send_acc).  In the
            # common churn regime ONE proposer drives, so this turns
            # a V-fold unrolled cube walk into a single pass.
            for v in range(n):
                def _active(ops, v=v):
                    best_b, best_v, lbest, any_new, new_v, new_b = ops
                    batv = st.cur_batch[v]  # [I]
                    ackv = (
                        elig[v][None, :]
                        & w_has[v][:, None]
                        & jnp.where(
                            is_comm,
                            batv[:, None] == learned,
                            st.ballot[v] >= acc_ballot,
                        )
                    )  # [I, A]
                    candv = jnp.where(
                        ackv & ~is_comm, st.ballot[v], bal.NONE
                    )
                    take = candv > best_b
                    best_b = jnp.where(take, candv, best_b)
                    best_v = jnp.where(
                        take,
                        jnp.broadcast_to(batv[:, None], best_v.shape),
                        best_v,
                    )
                    av_new = acks[v] | ackv
                    # per-instance quorum over the proposer's view
                    n_ack = jnp.sum(
                        av_new & st.acceptors[v][None, :], axis=-1,
                        dtype=jnp.int32,
                    )
                    # A crashed proposer can no longer detect (or
                    # broadcast) a choice even if its accumulated acks
                    # reach quorum; the value stays accepted-by-quorum
                    # until some live proposer re-prepares and adopts
                    # it.
                    chosen_v = (
                        w_has[v] & (n_ack >= quorum_v[v]) & alive[v]
                    )
                    newly_v = chosen_v & none_yet
                    any_new = any_new | newly_v
                    new_v = jnp.maximum(
                        new_v, jnp.where(newly_v, batv, _NEG)
                    )
                    new_b = jnp.maximum(
                        new_b, jnp.where(newly_v, st.ballot[v], _NEG)
                    )
                    # LEARN broadcast (synchronous, to the chooser's
                    # view-learners; ref Learner::OnLearn) — chosen
                    # values reach every listed learner this round
                    le_v = (
                        chosen_v[:, None]
                        & st.learners[v][None, :]
                        & alive[None, :]  # crashed/paused learn nothing
                    )  # [I, L]
                    if reach_t is not None:
                        le_v = le_v & reach_t[v][None, :]
                    lbest = jnp.maximum(
                        lbest, jnp.where(le_v, batv[:, None], _NEG)
                    )
                    return (
                        (best_b, best_v, lbest, any_new, new_v, new_b),
                        av_new,
                        jnp.any(newly_v),
                    )

                def _idle(ops, v=v):
                    return ops, acks[v], jnp.bool_(False)

                ops = (best_b, best_v, lbest, any_new, new_v, new_b)
                (best_b, best_v, lbest, any_new, new_v, new_b), av_new, \
                    newly_v_any = jax.lax.cond(
                        send_acc[v], _active, _idle, ops
                    )
                new_acks.append(av_new)
                newly_rows.append(newly_v_any)
            acks = jnp.stack(new_acks)
            do_store = best_b != bal.NONE
            acc_ballot = jnp.where(do_store, best_b, acc_ballot)
            acc_vid = jnp.where(do_store, best_v, acc_vid)
            cvid = jnp.where(any_new, new_v, cvid)
            cround = jnp.where(any_new, t, cround)
            cballot = jnp.where(any_new, new_b, cballot)
            learned = jnp.where(
                (lbest != _NEG) & (learned == val.NONE), lbest, learned
            )
            return (acc_ballot, acc_vid, acks, cvid, cround, cballot,
                    learned, jnp.stack(newly_rows))

        (acc_ballot, acc_vid, acks, chosen_vid, chosen_round,
         chosen_ballot, learned, newly_any) = jax.lax.cond(
            any_acc,
            _accept_phase,
            lambda ab, av, ak, cv, cr, cb, lr: (
                ab, av, ak, cv, cr, cb, lr, jnp.zeros((n,), jnp.bool_),
            ),
            st.acc_ballot, st.acc_vid, st.acks, st.chosen_vid,
            st.chosen_round, st.chosen_ballot, st.learned,
        )

        # anti-entropy pull at each node's first learned-gap (the
        # reference's learner-side Learn retry for unlearned instances,
        # ref member/paxos.cpp:1029-1073): one instance per round.
        # Node nn may pull from any donor m that has it and whose view
        # lists nn as a learner (st.learners[m, nn]).  The frontier
        # (= length of the leading learned run) is the first-gap
        # index: argmax of the gap mask, one fused pass where the old
        # cumprod+sum scan paid several (exact: argmax returns the
        # FIRST max, i.e. the first gap; a gapless log falls back to
        # the same i_cap the run-length sum produced, then clips).
        gap = learned.T == val.NONE  # [N, I]
        f = jnp.clip(
            jnp.where(
                jnp.any(gap, axis=1),
                jnp.argmax(gap, axis=1).astype(jnp.int32),
                jnp.int32(i_cap),
            ),
            0,
            i_cap - 1,
        )  # [N]
        mine = learned[f, rows]  # [N] nn's own copy at its frontier
        l_at_f = learned[f, :]  # [N, M] row nn = all holders of f[nn]
        donor_ok = (
            (l_at_f != val.NONE) & st.learners.T & alive[None, :]  # [nn, m]
        )
        if reach_t is not None:
            donor_ok = donor_ok & reach_t.T  # pull rides an m -> nn send
        can_pull = jnp.any(donor_ok, axis=1) & (mine == val.NONE) & alive
        pulled = jnp.max(jnp.where(donor_ok, l_at_f, _NEG), axis=1)
        learned = learned.at[f, rows].set(
            jnp.where(can_pull, pulled, mine)
        )

        # ---------- apply frontier ----------
        # Plain values batch-apply (the frontier jumps over the whole
        # learned run, ref Learner::Apply walks while next is learned,
        # member/paxos.cpp:1029-1060); membership changes apply at
        # most one per node per round (each mutates the view the next
        # entries are interpreted under).
        fa = st.applied_upto  # [N]
        lme = learned.T  # [N, I]
        app = lme != val.NONE
        nonchg = app & (lme < CHANGE_BASE)
        pre = idx[None] < fa[:, None]
        # run_total = length of the leading applicable run == first
        # blocker index (argmax of the stop mask; blocker-free rows
        # fall back to i_cap) — one fused pass, same value as the old
        # cumprod+sum run-length scan
        stop = ~(nonchg | pre)  # [N, I]
        run_total = jnp.where(
            jnp.any(stop, axis=1),
            jnp.argmax(stop, axis=1).astype(jnp.int32),
            jnp.int32(i_cap),
        )
        run = jnp.maximum(run_total - fa, 0)  # plain values applied now
        run = jnp.where(alive, run, 0)  # crashed logs freeze at crash
        f2 = jnp.clip(fa + run, 0, i_cap - 1)
        head_v = learned[f2, rows]  # [N] entry right after the run
        can_apply = (
            (head_v != val.NONE)
            & (fa + run < i_cap)
            & (head_v >= CHANGE_BASE)
            & alive
        )
        is_chg = can_apply
        k = jnp.where(is_chg, head_v - CHANGE_BASE, 0)
        tgt = k // 8
        kind = k % 8
        addl = is_chg & ((kind == ADD_LEARNER) | (kind == ADD_ACCEPTOR))
        dell = is_chg & ((kind == DEL_LEARNER) | (kind == DEL_ACCEPTOR))
        addp = is_chg & (
            (kind == LEARNER_TO_PROPOSER) | (kind == ADD_ACCEPTOR)
        )
        delp = is_chg & (
            (kind == PROPOSER_TO_LEARNER) | (kind == DEL_ACCEPTOR)
        )
        adda = is_chg & (
            (kind == PROPOSER_TO_ACCEPTOR) | (kind == ADD_ACCEPTOR)
        )
        dela = is_chg & (
            (kind == ACCEPTOR_TO_PROPOSER) | (kind == DEL_ACCEPTOR)
        )
        cur_l = st.learners[rows, tgt]
        learners_v = st.learners.at[rows, tgt].set(
            jnp.where(addl, True, jnp.where(dell, False, cur_l))
        )
        cur_p = st.proposers[rows, tgt]
        proposers_v = st.proposers.at[rows, tgt].set(
            jnp.where(addp, True, jnp.where(delp, False, cur_p))
        )
        cur_a = st.acceptors[rows, tgt]
        acceptors_v = st.acceptors.at[rows, tgt].set(
            jnp.where(adda, True, jnp.where(dela, False, cur_a))
        )
        acc_changed = adda | dela
        version = st.version + acc_changed.astype(jnp.int32)
        applied_upto = fa + run + can_apply.astype(jnp.int32)
        # AcceptorsChanged -> proposer restarts its prepare
        # (ref member/paxos.cpp:1895-1908)
        prepared = st.prepared & ~acc_changed

        # batch staleness: no progress for too long -> restart prepare
        progress = newly_any  # [N] from the gated accept phase
        outstanding = jnp.any(
            (st.cur_batch != val.NONE)
            & (chosen_vid[None] == val.NONE),
            axis=1,
        )
        batch_age = jnp.where(
            progress | ~outstanding, 0, st.batch_age + 1
        )
        stale = outstanding & (batch_age >= ACCEPT_STALE_ROUNDS)
        prepared = prepared & ~stale
        kd = prng.stream(root, prng.STREAM_PREPARE_DELAY, t)
        if geometry is None:
            backoff = jax.random.randint(kd, (n,), 0, 4, dtype=jnp.int32)
        else:
            # menu-switched draw at the TRUE node count (pad nodes
            # never prepare, so their 0 backoff is never consulted)
            backoff = geo.menu_randint(
                geometry, gidx, kd, "nodes", 0, 4, pad_value=0
            )
        delay_until = jnp.where(stale, t + 1 + backoff, st.delay_until)
        batch_age = jnp.where(stale, 0, batch_age)

        # conflict re-proposal / own completion (ref OnLearn conflict
        # path; same semantics as core/sim)
        learned_me = learned.T  # [N, I] each node's own learner column
        own_has = (st.own_assign != val.NONE) & alive[:, None]
        conflict = own_has & (learned_me != val.NONE) & (
            learned_me != st.own_assign
        )
        own_done = own_has & (learned_me == st.own_assign)
        # requeue cumsum + ring scatter only on conflict rounds; the
        # own_assign clear only when something completed or conflicted
        # (same gating core/sim.py uses)
        any_conf = jnp.any(conflict)

        def _requeue(pend, tail):
            nreq = jnp.sum(conflict, axis=1, dtype=jnp.int32)
            rr = jnp.cumsum(conflict.astype(jnp.int32), axis=1) - 1
            req_pos = jnp.where(conflict, tail[:, None] + rr, c)
            pend = pend.at[rows[:, None], req_pos].set(
                st.own_assign, mode="drop"
            )
            return pend, tail + nreq

        pend, tail = jax.lax.cond(
            any_conf, _requeue, lambda pe, tl: (pe, tl), st.pend, st.tail
        )
        own_assign = jax.lax.cond(
            jnp.any(conflict | own_done),
            lambda oa: jnp.where(conflict | own_done, val.NONE, oa),
            lambda oa: oa,
            st.own_assign,
        )

        # drop chosen instances from batches (quiesce bookkeeping)
        cur_batch = jnp.where(
            chosen_vid[None] != val.NONE, val.NONE, st.cur_batch
        )
        cur_batch = jnp.where(prepared[:, None], cur_batch, val.NONE)
        acks = jnp.where(prepared[:, None, None], acks, False)

        # ---------- idle-liveness repair ----------
        # Unresolved log: a hole below the chosen high-water mark, or a
        # value some live acceptor holds accepted that nobody chose
        # (its proposer crashed mid-accept).  An idle live proposer
        # restarts its prepare after REPAIR_STALL_ROUNDS; adoption and
        # no-op fill then resolve both cases.
        hw = jnp.max(jnp.where(chosen_vid != val.NONE, idx, -1))
        hole = jnp.any((chosen_vid == val.NONE) & (idx <= hw))
        # An orphan held only by nodes outside every live node's
        # current acceptor view is unresolvable (no prepare will ever
        # reach its holder) — repair must not chase it forever.
        in_view = jnp.any(acceptors_v & alive[:, None], axis=0)  # [N]
        orphan = jnp.any(
            (chosen_vid == val.NONE)
            & jnp.any(
                (acc_vid != val.NONE) & alive[None, :] & in_view[None, :],
                axis=1,
            )
        )
        unresolved = hole | orphan
        no_work = (st.head >= tail) & jnp.all(own_assign == val.NONE, axis=1)
        batch_open = jnp.any(
            (st.cur_batch != val.NONE) & (chosen_vid[None] == val.NONE),
            axis=1,
        )
        idle = is_prop & no_work & ~batch_open
        stall = jnp.where(idle & unresolved, st.stall + 1, 0)
        # gate on delay_until so a kick is never consumed without
        # producing a prepare (want_prep requires t >= delay_until)
        repair_kick = (
            is_prop & (stall >= REPAIR_STALL_ROUNDS) & (t >= delay_until)
        )
        # re-arm the patience window so a stubborn unresolved log kicks
        # once per window, not once per round (an every-round kick would
        # bump the ballot count without bound)
        stall = jnp.where(repair_kick, 0, stall)
        prepared = prepared & ~repair_kick

        # ---------- PREPARE phase ----------
        committed_me = learned_me != val.NONE  # [N, I]
        has_work = (st.head < tail) | jnp.any(own_assign != val.NONE, axis=1)
        want_prep = (
            is_prop & ~prepared & (has_work | repair_kick) & (t >= delay_until)
        )
        ncnt, nbal = bal.bump_past(
            st.count, rows.astype(jnp.int32), jnp.maximum(pmax, st.ballot)
        )
        count = jnp.where(want_prep, ncnt, st.count)
        ballot = jnp.where(want_prep, nbal, st.ballot)
        pedge = (
            want_prep[:, None]
            & acceptors_v
            & is_accp[None, :]
            & (version[:, None] == version[None, :])
        )
        if reach2_t is not None:
            pedge = pedge & reach2_t
        grant = pedge & (ballot[:, None] > st.promised[None, :])
        promised = jnp.maximum(
            st.promised, jnp.max(jnp.where(grant, ballot[:, None], bal.NONE), axis=0)
        )
        max_seen = jnp.maximum(
            max_seen, jnp.max(jnp.where(pedge, ballot[:, None], bal.NONE), axis=0)
        )
        pmax = jnp.maximum(
            pmax,
            jnp.max(
                jnp.where((pedge & ~grant).T, max_seen[:, None], bal.NONE).T,
                axis=1,
            ),
        )
        n_prom = jnp.sum(grant & acceptors_v, axis=1, dtype=jnp.int32)
        now_prep = want_prep & (n_prom >= quorum_v)
        prepared = prepared | now_prep
        delay_until = jnp.where(
            want_prep & ~now_prep, t + 1 + backoff, delay_until
        )
        # Snapshot reply + adoption + batch skeleton, cond-gated on a
        # prepare actually being in flight (the port of core/sim.py's
        # optimization this engine lacked): the old unconditional path
        # materialized two [V, I, A] cubes (broadcast + argmax +
        # take_along_axis) every round — at the config-5 literal size
        # that is ~10^8 wasted elements per quiet round.  Adoption is
        # a two-pass masked max, exact because cells tied at the max
        # ballot hold the same value (one proposer per ballot sends
        # one value per instance; committed-sentinel cells all hold
        # the agreed chosen value — same argument as core/sim._adopt).
        any_prep = jnp.any(want_prep)

        def _adopt_and_build(cur_batch, acks):
            # committed values at the sentinel ballot; snap_b [I, A]
            snap_b = jnp.where(
                learned != val.NONE, COMMITTED_BALLOT, acc_ballot
            )
            snap_v = jnp.where(learned != val.NONE, learned, acc_vid)
            nones_row = jnp.full((i_cap,), bal.NONE, jnp.int32)
            ab_rows, av_rows, cb_rows, ak_rows = [], [], [], []
            # Per-proposer cond (the accept phase's discipline): only
            # proposers with a prepare in flight (want_prep[v]) pay
            # the [I, A] snapshot-reply max passes — for everyone
            # else the adopted rows are NONE and batch/acks pass
            # through, exactly what the masked forms computed
            # (now_prep ⊆ want_prep).
            for v in range(n):
                def _active(cb_v, ak_v, v=v):
                    repb = jnp.where(
                        grant[v][None, :], snap_b, bal.NONE
                    )  # [I, A]
                    best_ab = jnp.max(repb, axis=-1)  # [I]
                    sel = (repb == best_ab[:, None]) & (repb != bal.NONE)
                    best_av = jnp.max(
                        jnp.where(
                            sel, snap_v, jnp.iinfo(jnp.int32).min
                        ),
                        axis=-1,
                    )
                    adopted_b_v = jnp.where(
                        now_prep[v],
                        jnp.where(best_ab > 0, best_ab, bal.NONE),
                        bal.NONE,
                    )
                    adopted_v_v = jnp.where(
                        now_prep[v] & (best_ab > 0), best_av, val.NONE
                    )
                    # batch skeleton: adopted + noop holes + own tail
                    use_adopt = (
                        ~committed_me[v] & (adopted_b_v != bal.NONE)
                    )
                    covered0 = committed_me[v] | use_adopt
                    hi = jnp.max(jnp.where(covered0, idx, -1))
                    below = idx <= hi
                    noop_fill = below & ~covered0
                    use_own = ~below & (own_assign[v] != val.NONE)
                    batch0 = jnp.where(
                        use_adopt,
                        adopted_v_v,
                        jnp.where(
                            noop_fill,
                            val.noop_vid(idx, jnp.int32(v), i_cap),
                            jnp.where(use_own, own_assign[v], val.NONE),
                        ),
                    )
                    batch0 = jnp.where(committed_me[v], val.NONE, batch0)
                    return (
                        adopted_b_v,
                        adopted_v_v,
                        jnp.where(now_prep[v], batch0, cb_v),
                        jnp.where(now_prep[v], False, ak_v),
                    )

                def _idle(cb_v, ak_v):
                    return nones_row, nones_row, cb_v, ak_v

                ab_v, av_v, cb_v, ak_v = jax.lax.cond(
                    want_prep[v], _active, _idle, cur_batch[v], acks[v]
                )
                ab_rows.append(ab_v)
                av_rows.append(av_v)
                cb_rows.append(cb_v)
                ak_rows.append(ak_v)
            return (
                jnp.stack(ab_rows),
                jnp.stack(av_rows),
                jnp.stack(cb_rows),
                jnp.stack(ak_rows),
            )

        def _no_prep(cur_batch, acks):
            nones = jnp.full((n, i_cap), bal.NONE, jnp.int32)
            return nones, nones, cur_batch, acks

        adopted_b, adopted_v, cur_batch, acks = jax.lax.cond(
            any_prep, _adopt_and_build, _no_prep, cur_batch, acks
        )
        batch_age = jnp.where(now_prep, 0, batch_age)

        # new-value assignment for prepared proposers (first-fit over
        # the open tail; same shape as core/sim), gated on a prepared
        # proposer actually having queue entries
        can_assign = prepared & alive
        has_q = can_assign & (tail > st.head)

        def _assign(cur_batch, own_assign, head):
            activity = (
                committed_me
                | (cur_batch != val.NONE)
                | (own_assign != val.NONE)
            )
            hi2 = jnp.max(jnp.where(activity, idx[None], -1), axis=1)
            free = idx[None] > hi2[:, None]
            qn = jnp.minimum(tail - head, jnp.int32(i_cap))
            free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1
            kk = jnp.minimum(qn, jnp.sum(free, axis=1, dtype=jnp.int32))
            kk = jnp.where(can_assign, kk, 0)
            takev = free & (free_rank < kk[:, None])
            qpos = jnp.clip(head[:, None] + free_rank, 0, c - 1)
            newv = jnp.take_along_axis(pend, qpos, axis=1)
            return (
                jnp.where(takev, newv, cur_batch),
                jnp.where(takev, newv, own_assign),
                head + kk,
            )

        cur_batch, own_assign, head = jax.lax.cond(
            jnp.any(has_q),
            _assign,
            lambda cb, oa, hd: (cb, oa, hd),
            cur_batch, own_assign, st.head,
        )

        # ---------- crash injection ----------
        # Deterministic crash points land first: a ``crash(t0, nodes)``
        # episode fail-stops its nodes at the END of round t0 — the
        # same takes-effect-next-round timing as the i.i.d. draw below
        # — and, landing first, shrinks the live-majority room the
        # i.i.d. admission cap sees (the composition order the general
        # engine uses).  Scheduled crashes are NOT admission-capped:
        # the schedule is the author's deterministic fault model, the
        # same contract as the general engine's crash episodes.
        base = exist if sched_crash is None else exist & ~sched_crash
        # Bernoulli(crash_rate/1e6) per live node per round (ref
        # member/indet.h:146-150 RandomFailure), admitted one candidate
        # at a time: a crash is allowed only if every node that would
        # remain alive keeps a live majority of its own view's
        # acceptors (the cap that lets survivors keep running where the
        # reference aborts the whole process).  Node 0 is the harness
        # driver and never crashes (scheduled crashes of node 0 are
        # rejected host-side at build time).  Static unroll over
        # candidates — n is the node count, <= 32 by construction.
        if crash_rate:
            ku = prng.stream(root, prng.STREAM_CRASH, t)
            if geometry is None:
                u = jax.random.randint(ku, (n,), 0, 1_000_000)
            else:
                # pad coin 1_000_000 never crashes: the comparison
                # below is strict `<` and crash_rate <= 1_000_000
                u = geo.menu_randint(
                    geometry, gidx, ku, "nodes", 0, 1_000_000,
                    pad_value=1_000_000,
                )
            # admission works over the not-crashed mask (`base`), NOT
            # the I/O-alive one: a paused node resumes, so it still
            # counts toward live majorities and must never be folded
            # into the crash set by the `~alive_c` complement below
            want = (u < crash_rate) & base
            qv_new = jnp.sum(acceptors_v, axis=1, dtype=jnp.int32) // 2 + 1
            alive_c = base
            for x in range(1, n):
                still = alive_c & (rows != x)
                live_acc = jnp.sum(
                    acceptors_v & still[None, :], axis=1, dtype=jnp.int32
                )
                ok = jnp.all(~still | (live_acc >= qv_new))
                alive_c = jnp.where(want[x] & ok, still, alive_c)
            crashed = ~alive_c
        else:
            crashed = ~base

        return MemberState(
            t=t + 1,
            crashed=crashed,
            learners=learners_v,
            proposers=proposers_v,
            acceptors=acceptors_v,
            version=version,
            promised=promised,
            max_seen=max_seen,
            acc_ballot=acc_ballot,
            acc_vid=acc_vid,
            learned=learned,
            applied_upto=applied_upto,
            count=count,
            ballot=ballot,
            pmax=pmax,
            prepared=prepared,
            delay_until=delay_until,
            adopted_b=adopted_b,
            adopted_v=adopted_v,
            cur_batch=cur_batch,
            acks=acks,
            batch_age=batch_age,
            own_assign=own_assign,
            pend=pend,
            head=head,
            tail=tail,
            stall=stall,
            chosen_vid=chosen_vid,
            chosen_round=chosen_round,
            chosen_ballot=chosen_ballot,
        )

    if geometry is not None:
        def round_fn(root, st: MemberState, tab, gidx) -> MemberState:
            return _round_core(root, st, tab, gidx)
    elif runtime_schedule:
        def round_fn(root, st: MemberState, tab) -> MemberState:
            return _round_core(root, st, tab)
    else:
        def round_fn(root, st: MemberState) -> MemberState:
            return _round_core(root, st, None)

    return round_fn


def _check_member_schedule(schedule) -> None:
    """Membership-engine schedule constraints: deterministic crash
    episodes are accepted (dense per-round node-axis masks on both
    the compiled-constant and runtime-table paths) — but never of
    node 0, which plays the reference harness's driver role
    (member/main.cpp proposes and churns through nodes[0]; the
    host ``crash()`` injector enforces the same rule).  ``gray``
    episodes are REJECTED by name: member/'s network is synchronous
    (request and reply in one step — there is no arrival calendar to
    inflate), so gray delay inflation has no lowering here; the
    WAN-shaped gray model belongs to the calendar network of
    core/sim."""
    if schedule is None:
        return
    for e in schedule.episodes:
        if e.kind == "crash" and 0 in e.nodes:
            raise ValueError(
                "node 0 is the harness driver; it stays up (crash "
                f"episode at t0={e.t0} names node 0)"
            )
        if e.kind == "gray":
            raise ValueError(
                "the membership engine does not support gray episodes "
                "(synchronous network — no arrival calendar to "
                f"inflate; gray episode at [{e.t0},{e.t1}))"
            )


# ---------------- device-resident churn driver ----------------------

def applied_log_of(state: MemberState, node: int) -> np.ndarray:
    """Real (non-noop, non-change) values ``node`` has applied, in
    order — what the reference's checking StateMachine collects
    (ref member/main.cpp:223-233).  Free function over a final state
    so both drivers (host-stepped ``MemberSim`` and the device
    ``ChurnEngine``) share one decision-log surface."""
    upto = int(state.applied_upto[node])
    col = np.asarray(state.learned[:upto, node])
    return col[(col >= 0) & (col < CHANGE_BASE)]


def decision_log_of(state: MemberState, n_nodes: int | None = None) -> str:
    """Canonical decision-log text — chosen (vid, round, ballot) per
    instance plus each node's applied log — the byte-compare surface
    for record-vs-replay AND for host-stepped-vs-device-resident
    driver parity (mirrors member/diff.sh diffing two runs' logs).
    The node count comes from the state itself, so a caller can never
    truncate or over-read the applied[] lines — except a
    geometry-PADDED caller, which passes its TRUE ``n_nodes`` so the
    log is byte-equal to the unpadded run's (pad nodes never exist;
    emitting their empty applied[] rows would fork the format, not
    the decisions)."""
    cv = np.asarray(state.chosen_vid)
    cr = np.asarray(state.chosen_round)
    cb = np.asarray(state.chosen_ballot)
    lines = [
        f"[{i}] = <{cv[i]}>@{cr[i]}#{cb[i]}"
        for i in np.flatnonzero(cv != int(val.NONE))
    ]
    n = state.crashed.shape[0] if n_nodes is None else int(n_nodes)
    for node in range(n):
        seq = " ".join(map(str, applied_log_of(state, node).tolist()))
        lines.append(f"applied[{node}] = {seq}")
    return "\n".join(lines) + "\n"


def _chosen_applied(st: MemberState, vid):
    """Traced ``(chosen, applied)`` pair for one vid — the wait-gate
    predicates, computed exactly as ``MemberSim.chosen`` /
    ``MemberSim.applied(viewer=0)`` read them on host: Applied = a
    majority of node 0's CURRENT acceptor view has learned the
    instance where ``vid`` was chosen."""
    inst = st.chosen_vid == vid  # [I]
    chosen = jnp.any(inst)
    k = jnp.argmax(inst).astype(jnp.int32)  # first hit (unique per vid)
    row = st.learned[k]  # [N] learner copies at that instance
    acc0 = st.acceptors[0]
    quorum = jnp.sum(acc0, dtype=jnp.int32) // 2 + 1
    n_learned = jnp.sum(acc0 & (row != val.NONE), dtype=jnp.int32)
    return chosen, chosen & (n_learned >= quorum)


def _churn_inject(ctab, cursor, st: MemberState, c: int):
    """One driver decision inside the traced step: if the cursor's
    event is ready (t >= t0 and the wait gate on the previous event
    holds), push its vid into ``via``'s pending ring at the tail and
    advance the cursor.  At most one injection per round — the
    sequential pacing of the reference churn driver.  Returns
    ``(st, cursor)``."""
    e_cap = ctab.vid.shape[0]
    e = jnp.minimum(cursor, jnp.int32(e_cap - 1))
    valid = cursor < ctab.n_events
    w = ctab.wait[e]
    prev_vid = ctab.vid[jnp.maximum(e - 1, 0)]
    prev_chosen, prev_applied = _chosen_applied(st, prev_vid)
    gate = (
        (w == jnp.int32(ctm.WAIT_NONE))
        | ((w == jnp.int32(ctm.WAIT_CHOSEN)) & prev_chosen)
        | ((w == jnp.int32(ctm.WAIT_APPLIED)) & prev_applied)
    )
    ready = valid & (st.t >= ctab.t0[e]) & gate
    via = ctab.via[e]
    # guarded scatter: a not-ready round writes to the out-of-range
    # slot and drops — no [N, C]-sized select ever materializes
    pos = jnp.where(ready, st.tail[via], jnp.int32(c))
    pend = st.pend.at[via, pos].set(ctab.vid[e], mode="drop")
    tail = st.tail.at[via].add(jnp.where(ready, 1, 0))
    return (
        st._replace(pend=pend, tail=tail),
        cursor + ready.astype(jnp.int32),
    )


def _churn_done(ctab, cursor, st: MemberState):
    """Run-complete predicate: every event injected, every event vid
    chosen, the LAST change event Applied (changes are wait-sequenced,
    so earlier changes were each other's gates), and every live
    learner in node 0's final view caught up to the chosen log (the
    anti-entropy pull has drained).  The full check is cond-gated on
    all-injected, so steady-state rounds pay one scalar compare."""
    e_cap = ctab.vid.shape[0]
    all_injected = cursor >= ctab.n_events

    def _full(st):
        eix = jnp.arange(e_cap, dtype=jnp.int32)
        evalid = eix < ctab.n_events
        hit = ctab.vid[:, None] == st.chosen_vid[None, :]  # [E, I]
        chosen_all = jnp.all(jnp.any(hit, axis=1) | ~evalid)
        is_chg = ctab.is_change & evalid
        last = jnp.max(jnp.where(is_chg, eix, jnp.int32(-1)))
        _, last_applied = _chosen_applied(
            st, ctab.vid[jnp.maximum(last, 0)]
        )
        changes_ok = (last < 0) | last_applied
        chosen_i = st.chosen_vid != val.NONE  # [I]
        known = st.learned != val.NONE  # [I, N]
        owed = (~st.crashed) & st.learners[0]  # [N]
        caught_up = jnp.all(
            ~chosen_i[:, None] | known | ~owed[None, :]
        )
        return chosen_all & changes_ok & caught_up

    return jax.lax.cond(
        all_injected, _full, lambda st: jnp.bool_(False), st
    )


def _applied_host(st: MemberState, vid: int) -> bool:
    """Host mirror of the traced Applied predicate (`_chosen_applied`):
    same formula over np reads of the same state values."""
    cv = np.asarray(st.chosen_vid)
    hits = np.flatnonzero(cv == vid)
    if not hits.size:
        return False
    row = np.asarray(st.learned[int(hits[0])])
    acc0 = np.asarray(st.acceptors[0])
    return int((acc0 & (row != int(val.NONE))).sum()) >= int(acc0.sum()) // 2 + 1


def _ready_host(ctab, cur: int, st: MemberState) -> bool:
    """Host mirror of the traced injection gate in `_churn_inject`.
    Each call transfers the decision inputs to host — the per-round
    sync the device-resident driver exists to remove."""
    if cur >= int(ctab.n_events) or int(st.t) < int(ctab.t0[cur]):
        return False
    w = int(ctab.wait[cur])
    if w == ctm.WAIT_NONE:
        return True
    prev_vid = int(ctab.vid[max(cur - 1, 0)])
    chosen = bool((np.asarray(st.chosen_vid) == prev_vid).any())
    if w == ctm.WAIT_CHOSEN:
        return chosen
    return chosen and _applied_host(st, prev_vid)


def _done_host(ctab, cur: int, st: MemberState) -> bool:
    """Host mirror of the traced run-complete predicate `_churn_done`."""
    n_events = int(ctab.n_events)
    if cur < n_events:
        return False
    cv = np.asarray(st.chosen_vid)
    vids = np.asarray(ctab.vid)[:n_events]
    if not np.isin(vids, cv).all():
        return False
    chg = np.flatnonzero(np.asarray(ctab.is_change)[:n_events])
    if chg.size and not _applied_host(st, int(vids[int(chg[-1])])):
        return False
    learned = np.asarray(st.learned)  # [I, N]
    owed = ~np.asarray(st.crashed) & np.asarray(st.learners[0])
    chosen_i = cv != int(val.NONE)
    return not (
        chosen_i[:, None] & (learned == int(val.NONE)) & owed[None, :]
    ).any()


def _check_churn_capacity(
    ctab, i_cap: int, c: int, lane: int | None = None
) -> None:
    """The pending-ring capacity proof, ONE implementation for both
    drivers and the fleet (MemberSim.propose's headroom rule): i_cap
    slots stay reserved for conflict requeues, so all of a node's
    injected events must fit below ``c - i_cap`` — then the device
    path's guarded tail scatter provably never clamps."""
    per_via = np.bincount(
        np.asarray(ctab.via)[: int(ctab.n_events)], minlength=1
    )
    if per_via.size and int(per_via.max()) > c - i_cap:
        where = (
            f"lane {lane}'s churn schedule" if lane is not None
            else "churn schedule"
        )
        raise ValueError(
            f"{where} injects {int(per_via.max())} events via one "
            f"node; the pending ring holds {c - i_cap} (requeue "
            "headroom reserved)"
        )


def _build_churn_loop(round_fn, c: int, max_rounds: int,
                      runtime_tables: bool, padded: bool = False):
    """The whole-run churn loop — inject -> round -> run-complete? as
    one ``lax.while_loop`` — shared by ``ChurnEngine`` (single runs)
    and the fleet lane body (``fleet/member_runner.py`` vmaps it), so
    the two can never drift apart on termination or injection
    ordering.  Returns ``go(root, st, ctab, ftab) -> (final_state,
    cursor, done)`` — with ``padded=True`` (a geometry-padded
    ``round_fn``) the loop instead returns ``go(root, st, ctab, ftab,
    gidx)`` and threads the traced menu index through every round.
    The round budget extends past the fault table's (traced) horizon,
    the heal-then-converge contract."""
    budget = jnp.int32(max_rounds)

    def go(root, st: MemberState, ctab, ftab, *gp):
        def cond(carry):
            s, _cur, done = carry
            return (~done) & (
                s.t < budget + jnp.asarray(ftab.horizon, jnp.int32)
            )

        def body(carry):
            s, cur, _done = carry
            s, cur = _churn_inject(ctab, cur, s, c)
            if padded:
                s = round_fn(root, s, ftab, gp[0])
            elif runtime_tables:
                s = round_fn(root, s, ftab)
            else:
                s = round_fn(root, s)
            return s, cur, _churn_done(ctab, cur, s)

        return jax.lax.while_loop(
            cond, body, (st, jnp.int32(0), jnp.bool_(False))
        )

    return go


class ChurnResult(NamedTuple):
    """One churn run's outcome (host-side wrapper)."""

    state: MemberState
    rounds: int
    done: bool
    injected: int

    def decision_log(self) -> str:
        return decision_log_of(self.state)


class ChurnEngine:
    """Device-resident churn driver: the whole (inject -> round ->
    done?) loop as ONE ``lax.while_loop`` dispatch — the membership
    analog of ``sim._run_loop``.  The host driver's per-round
    decisions (``MemberSim`` + a Python churn loop) become data: a
    :class:`~tpu_paxos.membership.churn_table.ChurnTable` of events
    evaluated inside the traced step, so no per-round host sync
    remains and the engine runs at the round body's speed.

    Two build modes, decision-log sha256-identical per (churn,
    schedule, seed) — the ``ScheduleTable`` parity discipline:

    - **compile-time-constant** (default): ``churn`` and ``schedule``
      bake into the closure as constants — the single-run default,
      zero per-round table overhead beyond the masks themselves;
    - **runtime tables** (``runtime_tables=True``): the churn table
      AND the fault-schedule table arrive per ``run()`` call, so one
      compiled executable covers every (churn, schedule, seed) mix of
      the ``(max_events, max_episodes)`` envelope — the surface the
      fleet's membership lanes vmap (fleet/member_runner.py).

    ``run_host()`` drives the SAME tables with the legacy host-stepped
    loop (one jitted round per dispatch, injection and termination
    decided from per-round host reads) — the honest baseline the
    BENCH_member comparison times, and the parity twin the sha256
    contract is pinned against."""

    def __init__(
        self,
        n_nodes: int,
        n_instances: int,
        *,
        churn=None,
        schedule=None,
        crash_rate: int = 0,
        max_rounds: int = 2000,
        runtime_tables: bool = False,
        max_events: int | None = None,
        max_episodes: int | None = None,
    ):
        from tpu_paxos.core import faults as fltm
        from tpu_paxos.fleet import schedule_table as stm

        self.n = n_nodes
        self.i = n_instances
        self.c = n_instances * 2 + 8
        self.crash_rate = crash_rate
        self.max_rounds = int(max_rounds)
        self.runtime_tables = bool(runtime_tables)
        self._round = _build_round(
            n_nodes, n_instances, self.c, crash_rate,
            comp=(
                None if runtime_tables
                else fltm.compile_schedule(schedule, n_nodes)
            ),
            runtime_schedule=runtime_tables,
        )
        if runtime_tables:
            if churn is not None or schedule is not None:
                raise ValueError(
                    "runtime_tables=True takes churn/schedule per "
                    "run() call, not at build time"
                )
            self.max_events = (
                ctm.MAX_EVENTS if max_events is None else int(max_events)
            )
            from tpu_paxos.fleet import runner as frun

            self.max_episodes = (
                frun.MAX_EPISODES if max_episodes is None
                else int(max_episodes)
            )
            self._ctab = self._ftab = None
            self.schedule = self.churn = None
        else:
            _check_member_schedule(schedule)
            self.schedule = schedule
            self.churn = churn
            self._ctab = ctm.encode_churn(churn, n_nodes)
            self._ftab = stm.encode_schedule(schedule, n_nodes)
            self.max_events = int(self._ctab.vid.shape[0])
            self.max_episodes = int(self._ftab.t0.shape[0])
        self._validate_capacity = self._capacity_checker()
        if not runtime_tables:
            self._validate_capacity(self._ctab)
        _go = _build_churn_loop(
            self._round, self.c, self.max_rounds, runtime_tables
        )
        if runtime_tables:
            self._go = jax.jit(_go)
        else:
            ctab_c = jax.tree.map(jnp.asarray, self._ctab)
            ftab_c = jax.tree.map(jnp.asarray, self._ftab)
            self._go = jax.jit(
                lambda root, st: _go(root, st, ctab_c, ftab_c)
            )
        # the host-stepped twin's single-round step: injection applied
        # on device, but DECIDED from host-side reads (run_host)
        self._step = jax.jit(self._round)

    def _capacity_checker(self):
        i_cap, c = self.i, self.c

        def check(ctab) -> None:
            _check_churn_capacity(ctab, i_cap, c)

        return check

    def _tables(self, churn, schedule):
        from tpu_paxos.fleet import schedule_table as stm

        if not self.runtime_tables:
            if churn is not None or schedule is not None:
                raise ValueError(
                    "this engine baked its tables at build time; "
                    "build with runtime_tables=True to pass them per "
                    "run"
                )
            return self._ctab, self._ftab
        _check_member_schedule(schedule)
        ctab = ctm.encode_churn(churn, self.n, self.max_events)
        ftab = stm.encode_schedule(schedule, self.n, self.max_episodes)
        return ctab, ftab

    def run(self, seed: int = 0, churn=None, schedule=None) -> ChurnResult:
        """One dispatch: init -> while_loop -> final state.  In
        runtime-table mode ``churn``/``schedule`` select the lane of
        the envelope this run rides."""
        ctab, ftab = self._tables(churn, schedule)
        self._validate_capacity(ctab)
        root = prng.root_key(seed)
        st0 = _init(self.n, self.i, self.c)
        with tracecount.engine_scope("member"):
            if self.runtime_tables:
                final, cur, done = self._go(
                    root, st0,
                    jax.tree.map(jnp.asarray, ctab),
                    jax.tree.map(jnp.asarray, ftab),
                )
            else:
                final, cur, done = self._go(root, st0)
        return ChurnResult(
            state=final, rounds=int(final.t), done=bool(done),
            injected=int(cur),
        )

    def run_host(self, seed: int = 0, churn=None, schedule=None) -> ChurnResult:
        """The host-stepped twin: one jitted round per host-loop
        iteration, the injection and termination decisions recomputed
        each round from HOST-side numpy reads of the device state
        (``_ready_host`` / ``_done_host``) — exactly the per-round
        sync cost the device loop removes, and the honest baseline
        ``bench_member_record`` times.  Decision-log byte-identical
        to :meth:`run` on the same (churn, schedule, seed): the
        predicates are the same formulas over the same state values
        (pinned by tests/test_churn_table.py)."""
        ctab, ftab = self._tables(churn, schedule)
        self._validate_capacity(ctab)
        root = prng.root_key(seed)
        st = _init(self.n, self.i, self.c)
        budget = self.max_rounds + int(ftab.horizon)
        cur = 0
        done = False
        ftab_d = jax.tree.map(jnp.asarray, ftab)
        with tracecount.engine_scope("member"):
            while not done and int(st.t) < budget:
                if _ready_host(ctab, cur, st):
                    via = int(ctab.via[cur])
                    pos = int(st.tail[via])
                    st = st._replace(
                        pend=st.pend.at[via, pos].set(int(ctab.vid[cur])),
                        tail=st.tail.at[via].add(1),
                    )
                    cur += 1
                st = (
                    self._step(root, st, ftab_d) if self.runtime_tables
                    else self._step(root, st)
                )
                done = _done_host(ctab, cur, st)
        return ChurnResult(
            state=st, rounds=int(st.t), done=done, injected=cur,
        )


class MemberSim:
    """Host driver around the synchronous membership engine — plays
    the role of member/main.cpp: injects proposals and membership
    changes, steps the engine, exposes the Applied predicate and the
    per-node applied logs."""

    def __init__(
        self,
        n_nodes: int,
        n_instances: int,
        seed: int = 0,
        crash_rate: int = 0,
        schedule=None,
    ):
        from tpu_paxos.core import faults as fltm

        self.n = n_nodes
        self.i = n_instances
        self.c = n_instances * 2 + 8
        self.root = prng.root_key(seed)
        self.state = _init(n_nodes, n_instances, self.c)
        self.schedule = schedule  # FaultSchedule | None (core/faults.py)
        _check_member_schedule(schedule)
        comp = fltm.compile_schedule(schedule, n_nodes)
        self._round = jax.jit(
            _build_round(n_nodes, n_instances, self.c, crash_rate, comp)
        )
        # Injection log: every (round, op, args) a host driver feeds
        # in.  The engine itself is a pure function of (seed, round),
        # but the DRIVER is an arbitrary nondeterministic host program
        # — it may pace itself by wall clock, sleeps, or external I/O,
        # so WHICH round each injection lands on is the one piece of
        # host nondeterminism in the composite.  Recording it makes
        # the whole run replayable: the TPU-native equivalent of the
        # reference's Indet record/replay subsystem, which logs every
        # clock read and lock-acquire order to replay a
        # nondeterministic host (ref member/indet.h:182-194,
        # member/indet.cpp:24-119, member/run.sh:10-16).
        self._init_args = {
            "n_nodes": n_nodes,
            "n_instances": n_instances,
            "seed": seed,
            "crash_rate": crash_rate,
            # the episode schedule is part of the run's deterministic
            # identity — a replay must re-inject the same partitions/
            # pauses or the engine diverges from the recorded log
            "schedule": schedule.to_dict() if schedule is not None else None,
        }
        self.injections: list[list] = []
        self.crash_rate = crash_rate
        self._sched_crashes = schedule is not None and any(
            e.kind == "crash" for e in schedule.episodes
        )
        # Round at which each node's CURRENT crash was observed — the
        # rejoin guard ties a checkpoint to this epoch, or a stale
        # snapshot from an earlier crash of the same node could roll
        # back promises granted in between (the lost-promise hazard).
        self._crash_round: dict[int, int] = {}

    # -- injection (between rounds, host-side; the reference's
    # Node::Propose / AddAcceptor / DelAcceptor surface) --
    def propose(self, node: int, vid: int) -> None:
        st = self.state
        if bool(st.crashed[node]):
            # The reference would have aborted the whole run by now; a
            # silent enqueue to a dead node would just hang the caller.
            raise RuntimeError(f"node {node} has crashed; propose elsewhere")
        pos = int(st.tail[node])
        # Reserve n_instances slots of headroom for conflict requeues:
        # assignments only target instances above the committed
        # high-water mark and a conflicted instance is committed, so at
        # most n_instances requeues can ever be scattered at the tail
        # (same capacity proof as core/sim.prepare_queues).
        if pos >= self.c - self.i:
            raise RuntimeError(
                "pending queue full (headroom reserved for requeues)"
            )
        self.state = st._replace(
            pend=st.pend.at[node, pos].set(vid),
            tail=st.tail.at[node].add(1),
        )
        # logged only once it actually landed (post-guards)
        self.injections.append([int(st.t), "propose", [int(node), int(vid)]])

    def propose_in_order(
        self, node: int, vids, max_rounds_each: int = 2000
    ) -> bool:
        """In-order client: propose each vid only after the previous
        one is chosen (the host-gating pattern the reference driver
        uses for dependent proposals, ref member/main.cpp:138-140;
        multi/'s in-order clients are the core/sim gate arrays).
        Returns True when every value was chosen in order."""
        for v in vids:
            self.propose(node, int(v))
            if not self.run_until(
                lambda: self.chosen(int(v)), max_rounds=max_rounds_each
            ):
                return False
        return True

    def add_acceptor(
        self, target: int, via: int = 0, force: bool = False
    ) -> int:
        """Propose adding ``target`` to the acceptor set.

        Guard (host-side, advisory): adding a CRASHED node inflates the
        quorum denominator without adding a live acceptor — the mirror
        image of the del_acceptor hazard.  (Adding a live node is
        always safe: numerator and denominator grow together.)"""
        if not force and bool(self.state.crashed[target]):
            raise ValueError(
                f"node {target} has crashed; adding it would inflate the "
                "quorum without a live acceptor (or pass force=True)"
            )
        vid = change_vid(target, ADD_ACCEPTOR)
        self.propose(via, vid)
        return vid

    def del_acceptor(
        self, target: int, via: int = 0, force: bool = False
    ) -> int:
        """Propose removing ``target`` from the acceptor set.

        Guard (host-side, advisory): deleting a LIVE acceptor while
        crashed ones remain can shrink the view below a live majority
        and wedge the cluster — the crash-admission cap only holds at
        crash time.  Delete crashed members first; ``force=True``
        overrides (the reference has no such guard because its crashes
        abort the whole run)."""
        if not force:
            acc_new = self._projected_acceptors(via)
            acc_new[target] = False
            alive = ~np.asarray(self.state.crashed)
            q_new = int(acc_new.sum()) // 2 + 1
            live_new = int((acc_new & alive).sum())
            if live_new < q_new:
                raise ValueError(
                    f"deleting acceptor {target} would leave {live_new} "
                    f"live acceptors of a {q_new}-quorum view; delete "
                    "crashed members first (or pass force=True)"
                )
        vid = change_vid(target, DEL_ACCEPTOR)
        self.propose(via, vid)
        return vid

    def _projected_acceptors(self, via: int) -> np.ndarray:
        """``via``'s acceptor view with every in-flight membership
        change applied: chosen-but-unapplied log entries, own
        assignments in flight, and the pending ring.  The del/add
        guards check against this projection so pipelined changes
        queued before any applies can't jointly wedge the cluster."""
        st = self.state
        acc = np.asarray(st.acceptors[via]).copy()

        def apply_vid(v: int) -> None:
            if v < CHANGE_BASE:
                return
            tgt, kind = decode_change(v)
            if kind in (ADD_ACCEPTOR, PROPOSER_TO_ACCEPTOR):
                acc[tgt] = True
            elif kind in (DEL_ACCEPTOR, ACCEPTOR_TO_PROPOSER):
                acc[tgt] = False

        chosen = np.asarray(st.chosen_vid)
        upto = int(st.applied_upto[via])
        for v in chosen[upto:]:
            if v != int(val.NONE):
                apply_vid(int(v))
        for v in np.asarray(st.own_assign[via]):
            if v != int(val.NONE):
                apply_vid(int(v))
        pend = np.asarray(st.pend[via])
        for pos in range(int(st.head[via]), min(int(st.tail[via]), self.c)):
            if pend[pos] != int(val.NONE):
                apply_vid(int(pend[pos]))
        return acc

    # -- stepping --
    def run_rounds(self, k: int) -> None:
        with tracecount.engine_scope("member"):
            self._run_rounds(k)

    def _run_rounds(self, k: int) -> None:
        for _ in range(k):
            self.state = self._round(self.root, self.state)
        if self.crash_rate or self._sched_crashes:
            # Engine-injected crashes don't pass through crash();
            # observe them so the rejoin epoch guard stays sound.
            # Observed ONCE per stepping call, not per round (the
            # PR-2-baselined per-round sync is gone): a host can only
            # checkpoint between run_rounds calls, so stamping the
            # block-end round is indistinguishable from the exact
            # crash round for every snapshot a host can actually take
            # — and only conservative (later stamp = stricter epoch
            # guard) for hand-crafted ones.
            for nn in np.flatnonzero(np.asarray(self.state.crashed)):
                self._crash_round.setdefault(int(nn), int(self.state.t))
        # Capacity proof holds at runtime: the conflict-requeue scatter
        # (mode="drop") must never have been pushed past the ring.
        if int(np.max(np.asarray(self.state.tail))) > self.c:
            raise RuntimeError("pending ring overflow: requeue lost")

    def run_until(self, pred, max_rounds: int = 2000, step: int = 4) -> bool:
        for _ in range(0, max_rounds, step):
            if pred():
                return True
            self.run_rounds(step)
        return pred()

    # -- predicates / views --
    def chosen(self, vid: int) -> bool:
        return bool(np.any(np.asarray(self.state.chosen_vid) == vid))

    def applied(self, vid: int, viewer: int = 0) -> bool:
        """Applied = a majority of the viewer's current acceptors have
        learned the value (ref member/paxos.cpp:1716-1733)."""
        st = self.state
        cv = np.asarray(st.chosen_vid)
        where = np.flatnonzero(cv == vid)
        if not where.size:
            return False
        i = int(where[0])
        acc = np.asarray(st.acceptors[viewer])
        learned = np.asarray(st.learned[i]) != int(val.NONE)
        return int((acc & learned).sum()) >= int(acc.sum()) // 2 + 1

    def applied_log(self, node: int) -> np.ndarray:
        """Real (non-noop, non-change) values node has applied, in
        order — what the reference's checking StateMachine collects
        (ref member/main.cpp:223-233)."""
        return applied_log_of(self.state, node)

    def crashed_set(self) -> set[int]:
        return set(np.flatnonzero(np.asarray(self.state.crashed)).tolist())

    def next_shrink_target(self, viewer: int = 0) -> int | None:
        """The safe deletion order when shrinking back to {0}: crashed
        acceptors first (their removal restores live-majority headroom
        — the policy the del_acceptor guard enforces), then the highest
        live one.  None once only node 0 remains."""
        accs = self.acceptor_set(viewer) - {0}
        if not accs:
            return None
        dead = sorted(accs & self.crashed_set())
        return dead[0] if dead else max(accs)

    def acceptor_set(self, viewer: int = 0) -> set[int]:
        return set(np.flatnonzero(np.asarray(self.state.acceptors[viewer])).tolist())

    # -- crash / rejoin --
    def crash(self, node: int) -> None:
        """Inject a deterministic fail-stop crash (the randomized
        schedule lives in the engine, ref member/indet.h:146-150).
        Guarded by the same admission rule the engine uses: every
        survivor must keep a live majority of its own view's
        acceptors, or the cluster would wedge.  Node 0 is the harness
        driver and never crashes."""
        if node == 0:
            raise ValueError("node 0 is the harness driver; it stays up")
        st = self.state
        alive_after = ~np.asarray(st.crashed)
        alive_after[node] = False
        acc = np.asarray(st.acceptors)
        for v in np.flatnonzero(alive_after):
            q = int(acc[v].sum()) // 2 + 1
            if int((acc[v] & alive_after).sum()) < q:
                raise ValueError(
                    f"crashing node {node} would leave node {v} without "
                    "a live majority of its acceptor view"
                )
        self.state = st._replace(crashed=st.crashed.at[node].set(True))
        self._crash_round[node] = int(st.t)
        self.injections.append([int(st.t), "crash", [int(node)]])

    def rejoin_from_checkpoint(self, node: int, path) -> None:
        """Crash-rejoin durability — EXCEEDS the reference, which
        persists nothing (SURVEY §5: "promises don't survive a
        crash"): restore ``node``'s durable per-node state from a
        checkpoint taken AT OR AFTER its crash, clear the crash bit,
        and let the engine's anti-entropy pull + apply frontier catch
        it up.  A crashed node's arrays are frozen (fail-stop), so
        such a snapshot equals its state at the failure point —
        restoring an earlier snapshot would be the classic
        lost-promise hazard (promises granted between snapshot and
        crash forgotten), which is why the checkpoint must show the
        node already crashed."""
        from tpu_paxos import checkpoint as ckpt

        st = self.state
        if not bool(st.crashed[node]):
            # double-rejoin / live-node call: restoring would roll a
            # LIVE node's promises back to crash-time values
            raise ValueError(
                f"node {node} is not crashed; rejoin would overwrite "
                "live state with the snapshot"
            )
        snap, _meta = ckpt.restore(path, like=st)
        if not bool(snap.crashed[node]):
            raise ValueError(
                f"checkpoint predates node {node}'s crash — restoring it "
                "would forget promises granted after the snapshot"
            )
        cr = self._crash_round.get(node)
        if cr is not None and int(snap.t) < cr:
            # a snapshot from an EARLIER crash epoch of the same node:
            # promises granted between its rejoin and the current
            # crash would be forgotten
            raise ValueError(
                f"checkpoint is from round {int(snap.t)}, before node "
                f"{node}'s current crash at round {cr} — stale epoch"
            )

        # Per-node leaves, restored by their node-axis position; the
        # completeness check below turns a future MemberState field
        # that is neither listed nor global into a hard failure
        # instead of a silently-unrestored leaf.
        node_major = (
            "learners", "proposers", "acceptors", "version", "promised",
            "max_seen", "applied_upto", "count", "ballot", "pmax",
            "prepared", "delay_until", "adopted_b", "adopted_v",
            "cur_batch", "acks", "batch_age", "own_assign", "pend",
            "head", "tail", "stall",
        )
        node_minor = ("acc_ballot", "acc_vid", "learned")  # [I, N]
        cluster_global = {"t", "chosen_vid", "chosen_round", "chosen_ballot"}
        kw = {"crashed": st.crashed.at[node].set(False)}
        for f in node_major:
            kw[f] = getattr(st, f).at[node].set(getattr(snap, f)[node])
        for f in node_minor:
            kw[f] = getattr(st, f).at[:, node].set(
                getattr(snap, f)[:, node]
            )
        uncovered = set(type(st)._fields) - set(kw) - cluster_global
        if uncovered:
            raise RuntimeError(
                "rejoin_from_checkpoint does not cover MemberState "
                f"fields {sorted(uncovered)}; classify them as "
                "node-major, node-minor, or cluster-global"
            )
        self.state = st._replace(**kw)
        self._crash_round.pop(node, None)
        # Replaying a rejoin needs the checkpoint artifact to still
        # exist at the recorded path — and to still be the SAME file:
        # the injection log pins its sha256 and geometry at record
        # time, and replay() verifies both before restoring, so a
        # moved/rewritten checkpoint fails loudly instead of silently
        # diverging from the recorded run.
        self.injections.append(
            [
                int(st.t),
                "rejoin",
                [
                    int(node),
                    str(path),
                    {
                        "sha256": _file_sha256(path),
                        "n_nodes": self.n,
                        "n_instances": self.i,
                    },
                ],
            ]
        )

    # -- host-injection record / replay (component 9's escape hatch;
    # ref member/indet.cpp:24-119 record/replay, member/diff.sh:1-3) --
    def save_injections(self, path) -> None:
        """Write the injection schedule as the replay artifact: engine
        geometry + seed, the (round, op, args) stream, and the final
        round count.  A driver paced by wall clock produces a
        different schedule every run; the artifact pins the one that
        happened."""
        import json

        with open(path, "w") as f:
            json.dump(
                {
                    "version": 1,
                    **self._init_args,
                    "ops": self.injections,
                    "final_t": int(self.state.t),
                },
                f,
            )

    @classmethod
    def replay(cls, path) -> "MemberSim":
        """Re-execute a recorded run: same engine seed, every injection
        applied at the recorded round, stepped to the recorded final
        round.  The result is bit-identical to the recorded run (the
        engine is deterministic in (seed, round, schedule); the log
        supplies the host's side), decision_log() byte-compares equal."""
        import json

        from tpu_paxos.core import faults as fltm

        with open(path) as f:
            log = json.load(f)
        if log.get("version") != 1:
            raise ValueError(f"unknown injection-log version {log.get('version')}")
        sched = (
            fltm.FaultSchedule.from_dict(log["schedule"])
            if log.get("schedule")
            else None
        )
        ms = cls(
            n_nodes=log["n_nodes"],
            n_instances=log["n_instances"],
            seed=log["seed"],
            crash_rate=log["crash_rate"],
            schedule=sched,
        )
        for t_op, op, args in log["ops"]:
            if int(ms.state.t) > t_op:
                raise RuntimeError(
                    f"injection log out of order: at round {int(ms.state.t)} "
                    f"but op recorded for round {t_op}"
                )
            while int(ms.state.t) < t_op:
                ms.run_rounds(1)
            if op == "propose":  # add/del/transition ops record as propose
                ms.propose(*args)
            elif op == "crash":
                ms.crash(*args)
            elif op == "rejoin":
                # Integrity gate BEFORE restoring: the recorded run pinned
                # the checkpoint's content hash and geometry; a replay
                # against a moved/rewritten/misconfigured file must fail
                # with a named cause, not diverge silently.  (Logs from
                # before the pinning carry 2-element args; those replay
                # unverified, as recorded.)
                node, ck_path = args[0], args[1]
                if len(args) > 2 and args[2]:
                    meta = args[2]
                    if not os.path.exists(ck_path):
                        raise ValueError(
                            f"rejoin checkpoint {ck_path!r} missing at "
                            "replay time"
                        )
                    got = _file_sha256(ck_path)
                    if got != meta.get("sha256"):
                        raise ValueError(
                            f"rejoin checkpoint {ck_path!r} sha256 "
                            f"{got[:16]}... != recorded "
                            f"{str(meta.get('sha256'))[:16]}... — the file "
                            "changed since the run was recorded"
                        )
                    if (
                        meta.get("n_nodes") != ms.n
                        or meta.get("n_instances") != ms.i
                    ):
                        raise ValueError(
                            "rejoin checkpoint geometry "
                            f"({meta.get('n_nodes')} nodes x "
                            f"{meta.get('n_instances')} instances) does not "
                            f"match the replayed run ({ms.n} x {ms.i})"
                        )
                ms.rejoin_from_checkpoint(node, ck_path)
            else:
                raise ValueError(f"unknown op {op!r} in injection log")
        while int(ms.state.t) < log["final_t"]:
            ms.run_rounds(1)
        return ms

    def decision_log(self) -> str:
        """Canonical decision-log text — chosen (vid, round, ballot)
        per instance plus each node's applied log — the byte-compare
        surface for record-vs-replay (mirrors member/diff.sh diffing
        two runs' logs)."""
        return decision_log_of(self.state)

    def learner_set(self, viewer: int = 0) -> set[int]:
        return set(np.flatnonzero(np.asarray(self.state.learners[viewer])).tolist())


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical traces of the membership engine (analysis/registry.py):
    the single host-stepped round (crash_rate on, so the
    crash-admission sampling is in the traced program the op budget
    pins), the schedule-bearing replay round, the churn-table device
    step, and the device-resident whole-run driver."""
    from tpu_paxos.analysis.registry import AuditEntry

    def build():
        n, i = 3, 8
        c = i * 2 + 8
        root = prng.root_key(0)
        state = _init(n, i, c)
        fn = _build_round(n, i, c, crash_rate=500, comp=None)
        return fn, (root, state)

    def build_replay():
        # The replay() configuration (the PR-3 follow-on ROADMAP item
        # 3 called out as un-audited): replay reconstructs MemberSim
        # with the RECORDED fault schedule, so the round it steps is
        # the schedule-bearing build — compiled reach/pause tables as
        # baked constants (what IR205's const budget watches here),
        # the heal-horizon clamp, the paused-receiver drops, and (new)
        # the cumulative crash-point rows all in the traced program.
        # A regression in this trace is a replay that diverges from
        # its recording.
        from tpu_paxos.core import faults as fltm

        n, i = 3, 8
        c = i * 2 + 8
        sched = fltm.FaultSchedule((
            fltm.partition(2, 10, (0,), (1, 2)),
            fltm.pause(4, 9, 1),
            fltm.crash(6, 2),
        ))
        comp = fltm.compile_schedule(sched, n)
        root = prng.root_key(0)
        state = _init(n, i, c)
        fn = _build_round(n, i, c, crash_rate=500, comp=comp)
        return fn, (root, state)

    def _small_tables():
        from tpu_paxos.core import faults as fltm
        from tpu_paxos.fleet import schedule_table as stm

        n = 3
        churn = ctm.ChurnSchedule((
            ctm.ChurnEvent(vid=100),
            ctm.ChurnEvent(
                vid=change_vid(1, ADD_ACCEPTOR), wait=ctm.WAIT_CHOSEN
            ),
            ctm.ChurnEvent(vid=101, wait=ctm.WAIT_APPLIED),
        ))
        sched = fltm.FaultSchedule((
            fltm.pause(2, 5, 1), fltm.crash(8, 2),
        ))
        ctab = jax.tree.map(jnp.asarray, ctm.encode_churn(churn, n, 4))
        ftab = jax.tree.map(
            jnp.asarray, stm.encode_schedule(sched, n, 2)
        )
        return n, ctab, ftab

    def build_churn_table():
        # The churn-table device kernel in isolation: the injection
        # gate (wait predicates over chosen/applied), the guarded
        # pending-ring scatter, and the cond-gated run-complete
        # reduction — the per-round cost every churn lane pays rides
        # in THIS program, so its op budget is the knob that keeps
        # table evaluation from outgrowing the round body.
        n, i = 3, 8
        c = i * 2 + 8
        _, ctab, _ = _small_tables()
        state = _init(n, i, c)

        def fn(ctab, cursor, st):
            st2, cur2 = _churn_inject(ctab, cursor, st, c)
            return st2, cur2, _churn_done(ctab, cur2, st2)

        return fn, (ctab, jnp.int32(0), state)

    def build_run_loop():
        # The device-resident whole-run driver (the sim._run_loop
        # analog): runtime churn + fault tables through the
        # while_loop, injection and termination inside the traced
        # step.  IR201 is the load-bearing contract — NO host
        # transfers in the loop body; that is the whole point of the
        # driver.
        n, i = 3, 8
        eng = ChurnEngine(
            n, i, runtime_tables=True, max_events=4, max_episodes=2,
            crash_rate=500, max_rounds=64,
        )
        _, ctab, ftab = _small_tables()
        root = prng.root_key(0)
        state = _init(n, i, eng.c)
        return eng._go, (root, state, ctab, ftab)

    return [
        AuditEntry("member.round", build,
                   covers=("MemberSim.__init__",)),
        AuditEntry("member.round_replay", build_replay),
        AuditEntry("member.churn_table", build_churn_table),
        AuditEntry("member.run_loop", build_run_loop,
                   covers=("ChurnEngine.__init__",), hlo_golden=True),
    ]
