"""Synchronous-round membership engine (member/ parity).

One loop iteration = one synchronous message exchange — faithful to
member/'s network, which delivers by calling the peer's ``OnReceive``
inline (ref member/main.cpp:65-79).  There are no drops or delays in
this variant (member/'s network is reliable); liveness needs only the
anti-dueling prepare backoff and an accept-staleness restart (covering
version races, ref Proposer::AcceptorsChanged member/paxos.cpp:1862-1908).

Crash injection (the member/ fault model): each live node crashes with
probability ``crash_rate``/1e6 per round, the round analog of
``Thread::RandomFailure`` firing with failure_rate/1e6 per log call
(ref member/indet.h:146-150, member/debug.conf.sample field 3).  A
crashed node is fail-stop silent: it grants no promises, acks no
accepts, learns nothing, applies nothing, and proposes nothing.  Its
entries in everyone's views persist — quorum denominators do NOT
shrink on crash; only a DEL_ACCEPTOR through the log shrinks them.
Two deliberate strengthenings over the reference, whose RandomFailure
aborts the entire simulation process and validates only the replayed
prefix: (a) crashes here are per-node and the surviving majority keeps
running (prefix consistency must hold across dead and live logs
alike), so admission is capped — a crash is only admitted if every
live node's view retains a live majority of its acceptors — and
(b) node 0 never crashes, because it plays the reference harness's
driver role (member/main.cpp proposes and churns through nodes[0]).
The cap holds at crash time only: a later DEL_ACCEPTOR of a live node
can shrink a view below live majority, and an ADD_ACCEPTOR of a
crashed node can inflate the quorum denominator without adding a live
acceptor — ``MemberSim.add_acceptor``/``del_acceptor`` guard against
both host-side.

Cluster bootstrap: every node's view starts as {0} in all three role
sets (ref NodeImpl::Loop, member/paxos.cpp:729-737: only node ``first_``
exists; only it instantiates Proposer+Acceptor).  All growth happens
through the log.

Membership-change values: one log entry carries a whole change vector
(ref ProposedValue(changes, cb), member/paxos.cpp:650-657) — encoded
here as a single vid >= CHANGE_BASE with a (target node, kind) pair,
where composite kinds expand to the reference's vectors:
ADD_ACCEPTOR -> [ADD_LEARNER, LEARNER_TO_PROPOSER,
PROPOSER_TO_ACCEPTOR], DEL_ACCEPTOR -> [ACCEPTOR_TO_PROPOSER,
PROPOSER_TO_LEARNER, DEL_LEARNER].

Version gating: prepare and accept messages carry the sender's
version and acceptors drop them unless it equals their own
(ref member/paxos.cpp:1702, 1747); each acceptor-set change bumps the
applying node's version by one (ref member/paxos.cpp:1897, 1951), so
two nodes agree on version iff they have applied the same number of
acceptor changes — i.e. the gate enforces same-view quorums.

Applied semantics: a chosen value is *Applied* once a majority of the
(current-view) acceptors have learned it
(ref Proposer::OnLearnReply, member/paxos.cpp:1716-1733); the churn
driver waits for Applied before issuing the next change
(ref member/main.cpp:138-140) — ``MemberSim.applied`` exposes exactly
this predicate.

Ordering and scale intent: member/'s reference harness has no
in-order clients (that is multi/'s workload, covered by core/sim's
gate arrays); its only ordering constraint is the host driver waiting
on Applied/chosen between dependent proposals — the same pattern
``MemberSim.run_until`` provides, and
``MemberSim.propose_in_order`` packages (see
tests/test_membership.py).  This engine is the *control-plane*
variant: churn events are rare and host-paced, so it optimizes for
reconfiguration semantics, not instance throughput — bulk data-plane
consensus at scale is core/sim + parallel/sharded_sim, whose
benchmarks carry the throughput story.
"""

from __future__ import annotations

import hashlib
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.analysis import tracecount
from tpu_paxos.core import ballot as bal
from tpu_paxos.core import values as val
from tpu_paxos.utils import prng

# Change kinds (ref member/paxos.cpp:61-69 enum MembershipChangeType)
ADD_LEARNER = 0
LEARNER_TO_PROPOSER = 1
PROPOSER_TO_ACCEPTOR = 2
DEL_LEARNER = 3
PROPOSER_TO_LEARNER = 4
ACCEPTOR_TO_PROPOSER = 5
# Composites (one log entry each, ref member/paxos.cpp:650-657, 706-714)
ADD_ACCEPTOR = 6
DEL_ACCEPTOR = 7

CHANGE_BASE = 2**28
COMMITTED_BALLOT = jnp.int32(2**30)
_NEG = jnp.int32(jnp.iinfo(jnp.int32).min)

ACCEPT_STALE_ROUNDS = 4  # restart prepare if a batch stalls this long

# Idle-liveness patience (core/sim's IDLE_RESTART_ROUNDS transplanted):
# an idle live proposer re-prepares after this many rounds whenever the
# log is unresolved — a hole below the chosen high-water mark, or a
# value accepted by a live acceptor but never chosen because its
# proposer crashed mid-accept.  The fresh prepare's adoption re-accepts
# the orphan and no-op fill plugs the hole.
REPAIR_STALL_ROUNDS = 8


def _file_sha256(path) -> str:
    """Content hash of a checkpoint artifact — pins a rejoin's input
    file in the injection log so replay can detect a swapped file."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def change_vid(node: int, kind: int) -> int:
    """Encode a membership change as a value id."""
    return CHANGE_BASE + node * 8 + kind


def is_change_vid(vid) -> bool:
    return np.asarray(vid) >= CHANGE_BASE


def decode_change(vid: int) -> tuple[int, int]:
    """-> (target node, kind)."""
    k = int(vid) - CHANGE_BASE
    return k // 8, k % 8


def membership_suffix(vid: int) -> str | None:
    """Decision-log suffix in the reference grammar
    (ref multi/paxos.cpp:20-22): ``m+id=ip:port`` for additive
    changes, ``m-id`` for removals; None for non-change vids.  Node
    addresses are synthetic, as in the reference harness where the
    port is just the peer index (ref multi/main.cpp:265-268)."""
    if vid < CHANGE_BASE:
        return None
    node, kind = decode_change(vid)
    additive = kind in (
        ADD_LEARNER,
        LEARNER_TO_PROPOSER,
        PROPOSER_TO_ACCEPTOR,
        ADD_ACCEPTOR,
    )
    return f"m+{node}=node:{node}" if additive else f"m-{node}"


class MemberState(NamedTuple):
    t: jax.Array
    crashed: jax.Array  # [N] bool fail-stop crash mask
    # per-viewing-node role masks: row v = node v's view
    learners: jax.Array  # [N, N] bool
    proposers: jax.Array  # [N, N] bool
    acceptors: jax.Array  # [N, N] bool
    version: jax.Array  # [N] int32
    # acceptor state
    promised: jax.Array  # [N] int32
    max_seen: jax.Array  # [N] int32
    acc_ballot: jax.Array  # [I, N] int32
    acc_vid: jax.Array  # [I, N] int32
    # learner state
    learned: jax.Array  # [I, N] int32
    applied_upto: jax.Array  # [N] int32 apply frontier
    # proposer state
    count: jax.Array  # [N] int32
    ballot: jax.Array  # [N] int32
    pmax: jax.Array  # [N] int32 max ballot seen via rejects
    prepared: jax.Array  # [N] bool
    delay_until: jax.Array  # [N] int32 prepare backoff
    adopted_b: jax.Array  # [N, I] int32
    adopted_v: jax.Array  # [N, I] int32
    cur_batch: jax.Array  # [N, I] int32
    acks: jax.Array  # [N, I, N] bool
    batch_age: jax.Array  # [N] int32 rounds since batch progress
    own_assign: jax.Array  # [N, I] int32
    pend: jax.Array  # [N, C] int32
    head: jax.Array  # [N] int32
    tail: jax.Array  # [N] int32
    stall: jax.Array  # [N] int32 idle rounds while the log is unresolved
    # decisions
    chosen_vid: jax.Array  # [I] int32
    chosen_round: jax.Array  # [I] int32
    chosen_ballot: jax.Array  # [I] int32


def _init(n: int, i: int, c: int) -> MemberState:
    none = lambda *sh: jnp.full(sh, bal.NONE, jnp.int32)  # noqa: E731
    zero = lambda *sh: jnp.zeros(sh, jnp.int32)  # noqa: E731
    seed_view = jnp.zeros((n, n), jnp.bool_).at[:, 0].set(True)
    return MemberState(
        t=jnp.int32(0),
        crashed=jnp.zeros((n,), jnp.bool_),
        learners=seed_view,
        proposers=seed_view,
        acceptors=seed_view,
        version=zero(n),
        promised=zero(n),
        max_seen=zero(n),
        acc_ballot=none(i, n),
        acc_vid=none(i, n),
        learned=none(i, n),
        applied_upto=zero(n),
        count=zero(n),
        ballot=zero(n),
        pmax=zero(n),
        prepared=jnp.zeros((n,), jnp.bool_),
        delay_until=zero(n),
        adopted_b=none(n, i),
        adopted_v=none(n, i),
        cur_batch=none(n, i),
        acks=jnp.zeros((n, i, n), jnp.bool_),
        batch_age=zero(n),
        own_assign=none(n, i),
        pend=none(n, c),
        head=zero(n),
        tail=zero(n),
        stall=zero(n),
        chosen_vid=none(i),
        chosen_round=none(i),
        chosen_ballot=none(i),
    )


def _build_round(
    n: int,
    i_cap: int,
    c: int,
    root: jax.Array,
    crash_rate: int = 0,
    comp=None,
):
    """``comp`` is a compiled fault schedule (core/faults.py) or None.
    member/'s network is synchronous — request and reply happen in one
    step — so an edge functions only when reachability holds in BOTH
    directions; one-way cuts therefore sever the whole exchange on the
    affected edges (the asymmetric-delivery story belongs to the
    calendar network of core/sim).  Pauses subtract from the alive
    mask like crashes but preserve state and heal at episode end."""
    idx = jnp.arange(i_cap, dtype=jnp.int32)
    rows = jnp.arange(n)
    horizon = comp.horizon if comp is not None else 0
    pause_tab = (
        jnp.asarray(comp.paused) if comp is not None and comp.has_pause else None
    )
    reach_tab = (
        jnp.asarray(comp.reach) if comp is not None and comp.has_reach else None
    )

    def round_fn(st: MemberState) -> MemberState:
        t = st.t
        tt = jnp.minimum(t, jnp.int32(horizon)) if comp is not None else None
        exist = ~st.crashed  # [N] not-crashed (excusals key off this)
        alive = exist  # [N] I/O-alive: crashed or paused act in no role
        if pause_tab is not None:
            alive = alive & ~pause_tab[tt]
        if reach_tab is not None:
            reach_t = reach_tab[tt]
            reach2_t = reach_t & reach_t.T  # synchronous exchange
        else:
            reach_t = reach2_t = None
        # node-local roles (a node acts on its OWN view of itself;
        # crashed nodes act in no role)
        is_prop = st.proposers[rows, rows] & alive  # [N]
        is_accp = st.acceptors[rows, rows] & alive  # [N]
        quorum_v = (
            jnp.sum(st.acceptors, axis=1, dtype=jnp.int32) // 2 + 1
        )  # [N] majority of each node's view (crashes do NOT shrink it)

        # ---------- ACCEPT phase (batches from previously prepared) ----
        send_acc = (
            st.prepared & jnp.any(st.cur_batch != val.NONE, axis=1) & alive
        )
        # version gate: acceptor a processes proposer v iff equal
        # versions (ref member/paxos.cpp:1747) and a is an acceptor in
        # v's view and its own
        edge = (
            send_acc[:, None]
            & st.acceptors[:, :]  # v targets its view's acceptors
            & is_accp[None, :]
            & (st.version[:, None] == st.version[None, :])
        )  # [V, A]
        if reach2_t is not None:
            edge = edge & reach2_t
        elig = edge & (st.ballot[:, None] >= st.promised[None, :])
        max_seen = jnp.maximum(
            st.max_seen,
            jnp.max(jnp.where(edge, st.ballot[:, None], bal.NONE), axis=0),
        )
        # rejects flow back synchronously
        rejed = edge & ~elig
        pmax = jnp.maximum(
            st.pmax, jnp.max(jnp.where(rejed.T, max_seen[:, None], bal.NONE).T, axis=1),
        )

        # The [V, I, A]-cube work — stores, ack accumulation, quorum
        # detection, learn broadcast — runs only while a prepared
        # proposer has an open batch (the port of core/sim.py's
        # event gating).  send_acc covers EVERY round the block can
        # change anything: elig ⊆ edge ⊆ send_acc, and inst_chosen
        # needs an open batch, which a cleared/unprepared proposer
        # cannot have (cur_batch is NONE'd the round prepared drops) —
        # so even the quorum-shrinks-under-an-accumulated-ack-set case
        # stays inside the gate.  The proposer axis is unrolled into
        # running elementwise maxes (exact: ballots are unique per
        # node; chosen values agree per instance) instead of the old
        # argmax + gather cubes.
        any_acc = jnp.any(send_acc)

        def _accept_phase(acc_ballot, acc_vid, acks, cvid, cround, cballot,
                          learned):
            is_comm = learned != val.NONE  # [I, A]
            best_b = jnp.full((i_cap, n), bal.NONE, jnp.int32)
            best_v = jnp.full((i_cap, n), val.NONE, jnp.int32)
            lbest = jnp.full((i_cap, n), _NEG, jnp.int32)
            new_acks, n_ack_rows = [], []
            w_has = st.cur_batch != val.NONE  # [V, I]
            for v in range(n):
                batv = st.cur_batch[v]  # [I]
                ackv = (
                    elig[v][None, :]
                    & w_has[v][:, None]
                    & jnp.where(
                        is_comm,
                        batv[:, None] == learned,
                        st.ballot[v] >= acc_ballot,
                    )
                )  # [I, A]
                candv = jnp.where(ackv & ~is_comm, st.ballot[v], bal.NONE)
                take = candv > best_b
                best_b = jnp.where(take, candv, best_b)
                best_v = jnp.where(
                    take, jnp.broadcast_to(batv[:, None], best_v.shape),
                    best_v,
                )
                av_new = acks[v] | ackv
                new_acks.append(av_new)
                # per-instance quorum over the proposer's view acceptors
                n_ack_rows.append(jnp.sum(
                    av_new & st.acceptors[v][None, :], axis=-1,
                    dtype=jnp.int32,
                ))
            acks = jnp.stack(new_acks)
            n_ack = jnp.stack(n_ack_rows)  # [V, I]
            do_store = best_b != bal.NONE
            acc_ballot = jnp.where(do_store, best_b, acc_ballot)
            acc_vid = jnp.where(do_store, best_v, acc_vid)
            # A crashed proposer can no longer detect (or broadcast) a
            # choice even if its accumulated acks reach quorum; the
            # value stays accepted-by-quorum until some live proposer
            # re-prepares and adopts it.
            inst_chosen = (
                w_has & (n_ack >= quorum_v[:, None]) & alive[:, None]
            )
            newly = inst_chosen & (cvid[None] == val.NONE)
            any_new = jnp.any(newly, axis=0)
            new_v = jnp.max(jnp.where(newly, st.cur_batch, _NEG), axis=0)
            new_b = jnp.max(
                jnp.where(newly, st.ballot[:, None], _NEG), axis=0
            )
            cvid = jnp.where(any_new, new_v, cvid)
            cround = jnp.where(any_new, t, cround)
            cballot = jnp.where(any_new, new_b, cballot)

            # LEARN broadcast (synchronous, to the chooser's
            # view-learners; ref Learner::OnLearn) — chosen values
            # reach every listed learner this round
            for v in range(n):
                le_v = (
                    inst_chosen[v][:, None]
                    & st.learners[v][None, :]
                    & alive[None, :]  # crashed/paused learners learn nothing
                )  # [I, L]
                if reach_t is not None:
                    le_v = le_v & reach_t[v][None, :]
                lbest = jnp.maximum(
                    lbest,
                    jnp.where(le_v, st.cur_batch[v][:, None], _NEG),
                )
            learned = jnp.where(
                (lbest != _NEG) & (learned == val.NONE), lbest, learned
            )
            return (acc_ballot, acc_vid, acks, cvid, cround, cballot,
                    learned, jnp.any(newly, axis=1))

        (acc_ballot, acc_vid, acks, chosen_vid, chosen_round,
         chosen_ballot, learned, newly_any) = jax.lax.cond(
            any_acc,
            _accept_phase,
            lambda ab, av, ak, cv, cr, cb, lr: (
                ab, av, ak, cv, cr, cb, lr, jnp.zeros((n,), jnp.bool_),
            ),
            st.acc_ballot, st.acc_vid, st.acks, st.chosen_vid,
            st.chosen_round, st.chosen_ballot, st.learned,
        )

        # anti-entropy pull at each node's first learned-gap (the
        # reference's learner-side Learn retry for unlearned instances,
        # ref member/paxos.cpp:1029-1073): one instance per round.
        # Node nn may pull from any donor m that has it and whose view
        # lists nn as a learner (st.learners[m, nn]).
        f = jnp.clip(
            jnp.sum(
                jnp.cumprod((learned.T != val.NONE).astype(jnp.int32), axis=1),
                axis=1,
            ),
            0,
            i_cap - 1,
        )  # [N]
        mine = learned[f, rows]  # [N] nn's own copy at its frontier
        l_at_f = learned[f, :]  # [N, M] row nn = all holders of f[nn]
        donor_ok = (
            (l_at_f != val.NONE) & st.learners.T & alive[None, :]  # [nn, m]
        )
        if reach_t is not None:
            donor_ok = donor_ok & reach_t.T  # pull rides an m -> nn send
        can_pull = jnp.any(donor_ok, axis=1) & (mine == val.NONE) & alive
        pulled = jnp.max(jnp.where(donor_ok, l_at_f, _NEG), axis=1)
        learned = learned.at[f, rows].set(
            jnp.where(can_pull, pulled, mine)
        )

        # ---------- apply frontier ----------
        # Plain values batch-apply (the frontier jumps over the whole
        # learned run, ref Learner::Apply walks while next is learned,
        # member/paxos.cpp:1029-1060); membership changes apply at
        # most one per node per round (each mutates the view the next
        # entries are interpreted under).
        fa = st.applied_upto  # [N]
        lme = learned.T  # [N, I]
        app = lme != val.NONE
        nonchg = app & (lme < CHANGE_BASE)
        pre = idx[None] < fa[:, None]
        run_total = jnp.sum(
            jnp.cumprod((nonchg | pre).astype(jnp.int32), axis=1), axis=1
        )
        run = jnp.maximum(run_total - fa, 0)  # plain values applied now
        run = jnp.where(alive, run, 0)  # crashed logs freeze at crash
        f2 = jnp.clip(fa + run, 0, i_cap - 1)
        head_v = learned[f2, rows]  # [N] entry right after the run
        can_apply = (
            (head_v != val.NONE)
            & (fa + run < i_cap)
            & (head_v >= CHANGE_BASE)
            & alive
        )
        is_chg = can_apply
        k = jnp.where(is_chg, head_v - CHANGE_BASE, 0)
        tgt = k // 8
        kind = k % 8
        addl = is_chg & ((kind == ADD_LEARNER) | (kind == ADD_ACCEPTOR))
        dell = is_chg & ((kind == DEL_LEARNER) | (kind == DEL_ACCEPTOR))
        addp = is_chg & (
            (kind == LEARNER_TO_PROPOSER) | (kind == ADD_ACCEPTOR)
        )
        delp = is_chg & (
            (kind == PROPOSER_TO_LEARNER) | (kind == DEL_ACCEPTOR)
        )
        adda = is_chg & (
            (kind == PROPOSER_TO_ACCEPTOR) | (kind == ADD_ACCEPTOR)
        )
        dela = is_chg & (
            (kind == ACCEPTOR_TO_PROPOSER) | (kind == DEL_ACCEPTOR)
        )
        cur_l = st.learners[rows, tgt]
        learners_v = st.learners.at[rows, tgt].set(
            jnp.where(addl, True, jnp.where(dell, False, cur_l))
        )
        cur_p = st.proposers[rows, tgt]
        proposers_v = st.proposers.at[rows, tgt].set(
            jnp.where(addp, True, jnp.where(delp, False, cur_p))
        )
        cur_a = st.acceptors[rows, tgt]
        acceptors_v = st.acceptors.at[rows, tgt].set(
            jnp.where(adda, True, jnp.where(dela, False, cur_a))
        )
        acc_changed = adda | dela
        version = st.version + acc_changed.astype(jnp.int32)
        applied_upto = fa + run + can_apply.astype(jnp.int32)
        # AcceptorsChanged -> proposer restarts its prepare
        # (ref member/paxos.cpp:1895-1908)
        prepared = st.prepared & ~acc_changed

        # batch staleness: no progress for too long -> restart prepare
        progress = newly_any  # [N] from the gated accept phase
        outstanding = jnp.any(
            (st.cur_batch != val.NONE)
            & (chosen_vid[None] == val.NONE),
            axis=1,
        )
        batch_age = jnp.where(
            progress | ~outstanding, 0, st.batch_age + 1
        )
        stale = outstanding & (batch_age >= ACCEPT_STALE_ROUNDS)
        prepared = prepared & ~stale
        kd = prng.stream(root, prng.STREAM_PREPARE_DELAY, t)
        backoff = jax.random.randint(kd, (n,), 0, 4, dtype=jnp.int32)
        delay_until = jnp.where(stale, t + 1 + backoff, st.delay_until)
        batch_age = jnp.where(stale, 0, batch_age)

        # conflict re-proposal / own completion (ref OnLearn conflict
        # path; same semantics as core/sim)
        learned_me = learned.T  # [N, I] each node's own learner column
        own_has = (st.own_assign != val.NONE) & alive[:, None]
        conflict = own_has & (learned_me != val.NONE) & (
            learned_me != st.own_assign
        )
        own_done = own_has & (learned_me == st.own_assign)
        # requeue cumsum + ring scatter only on conflict rounds; the
        # own_assign clear only when something completed or conflicted
        # (same gating core/sim.py uses)
        any_conf = jnp.any(conflict)

        def _requeue(pend, tail):
            nreq = jnp.sum(conflict, axis=1, dtype=jnp.int32)
            rr = jnp.cumsum(conflict.astype(jnp.int32), axis=1) - 1
            req_pos = jnp.where(conflict, tail[:, None] + rr, c)
            pend = pend.at[rows[:, None], req_pos].set(
                st.own_assign, mode="drop"
            )
            return pend, tail + nreq

        pend, tail = jax.lax.cond(
            any_conf, _requeue, lambda pe, tl: (pe, tl), st.pend, st.tail
        )
        own_assign = jax.lax.cond(
            jnp.any(conflict | own_done),
            lambda oa: jnp.where(conflict | own_done, val.NONE, oa),
            lambda oa: oa,
            st.own_assign,
        )

        # drop chosen instances from batches (quiesce bookkeeping)
        cur_batch = jnp.where(
            chosen_vid[None] != val.NONE, val.NONE, st.cur_batch
        )
        cur_batch = jnp.where(prepared[:, None], cur_batch, val.NONE)
        acks = jnp.where(prepared[:, None, None], acks, False)

        # ---------- idle-liveness repair ----------
        # Unresolved log: a hole below the chosen high-water mark, or a
        # value some live acceptor holds accepted that nobody chose
        # (its proposer crashed mid-accept).  An idle live proposer
        # restarts its prepare after REPAIR_STALL_ROUNDS; adoption and
        # no-op fill then resolve both cases.
        hw = jnp.max(jnp.where(chosen_vid != val.NONE, idx, -1))
        hole = jnp.any((chosen_vid == val.NONE) & (idx <= hw))
        # An orphan held only by nodes outside every live node's
        # current acceptor view is unresolvable (no prepare will ever
        # reach its holder) — repair must not chase it forever.
        in_view = jnp.any(acceptors_v & alive[:, None], axis=0)  # [N]
        orphan = jnp.any(
            (chosen_vid == val.NONE)
            & jnp.any(
                (acc_vid != val.NONE) & alive[None, :] & in_view[None, :],
                axis=1,
            )
        )
        unresolved = hole | orphan
        no_work = (st.head >= tail) & jnp.all(own_assign == val.NONE, axis=1)
        batch_open = jnp.any(
            (st.cur_batch != val.NONE) & (chosen_vid[None] == val.NONE),
            axis=1,
        )
        idle = is_prop & no_work & ~batch_open
        stall = jnp.where(idle & unresolved, st.stall + 1, 0)
        # gate on delay_until so a kick is never consumed without
        # producing a prepare (want_prep requires t >= delay_until)
        repair_kick = (
            is_prop & (stall >= REPAIR_STALL_ROUNDS) & (t >= delay_until)
        )
        # re-arm the patience window so a stubborn unresolved log kicks
        # once per window, not once per round (an every-round kick would
        # bump the ballot count without bound)
        stall = jnp.where(repair_kick, 0, stall)
        prepared = prepared & ~repair_kick

        # ---------- PREPARE phase ----------
        committed_me = learned_me != val.NONE  # [N, I]
        has_work = (st.head < tail) | jnp.any(own_assign != val.NONE, axis=1)
        want_prep = (
            is_prop & ~prepared & (has_work | repair_kick) & (t >= delay_until)
        )
        ncnt, nbal = bal.bump_past(
            st.count, rows.astype(jnp.int32), jnp.maximum(pmax, st.ballot)
        )
        count = jnp.where(want_prep, ncnt, st.count)
        ballot = jnp.where(want_prep, nbal, st.ballot)
        pedge = (
            want_prep[:, None]
            & acceptors_v
            & is_accp[None, :]
            & (version[:, None] == version[None, :])
        )
        if reach2_t is not None:
            pedge = pedge & reach2_t
        grant = pedge & (ballot[:, None] > st.promised[None, :])
        promised = jnp.maximum(
            st.promised, jnp.max(jnp.where(grant, ballot[:, None], bal.NONE), axis=0)
        )
        max_seen = jnp.maximum(
            max_seen, jnp.max(jnp.where(pedge, ballot[:, None], bal.NONE), axis=0)
        )
        pmax = jnp.maximum(
            pmax,
            jnp.max(
                jnp.where((pedge & ~grant).T, max_seen[:, None], bal.NONE).T,
                axis=1,
            ),
        )
        n_prom = jnp.sum(grant & acceptors_v, axis=1, dtype=jnp.int32)
        now_prep = want_prep & (n_prom >= quorum_v)
        prepared = prepared | now_prep
        delay_until = jnp.where(
            want_prep & ~now_prep, t + 1 + backoff, delay_until
        )
        # Snapshot reply + adoption + batch skeleton, cond-gated on a
        # prepare actually being in flight (the port of core/sim.py's
        # optimization this engine lacked): the old unconditional path
        # materialized two [V, I, A] cubes (broadcast + argmax +
        # take_along_axis) every round — at the config-5 literal size
        # that is ~10^8 wasted elements per quiet round.  Adoption is
        # a two-pass masked max, exact because cells tied at the max
        # ballot hold the same value (one proposer per ballot sends
        # one value per instance; committed-sentinel cells all hold
        # the agreed chosen value — same argument as core/sim._adopt).
        any_prep = jnp.any(want_prep)

        def _adopt_and_build(cur_batch, acks):
            # committed values at the sentinel ballot; snap_b [I, A]
            snap_b = jnp.where(
                learned != val.NONE, COMMITTED_BALLOT, acc_ballot
            )
            snap_v = jnp.where(learned != val.NONE, learned, acc_vid)
            repb = jnp.where(
                grant[:, None, :],
                jnp.broadcast_to(snap_b[None], (n, i_cap, n)),
                bal.NONE,
            )
            best_ab = jnp.max(repb, axis=-1)  # [V, I]
            sel = (repb == best_ab[..., None]) & (repb != bal.NONE)
            best_av = jnp.max(
                jnp.where(sel, snap_v[None], jnp.iinfo(jnp.int32).min),
                axis=-1,
            )
            adopted_b = jnp.where(
                now_prep[:, None],
                jnp.where(best_ab > 0, best_ab, bal.NONE),
                bal.NONE,
            )
            adopted_v = jnp.where(
                now_prep[:, None] & (best_ab > 0), best_av, val.NONE
            )

            # batch skeleton for the newly prepared: adopted + noop holes
            use_adopt = ~committed_me & (adopted_b != bal.NONE)
            covered0 = committed_me | use_adopt
            hi = jnp.max(jnp.where(covered0, idx[None], -1), axis=1)
            below = idx[None] <= hi[:, None]
            noop_fill = below & ~covered0
            use_own = ~below & (own_assign != val.NONE)
            batch0 = jnp.where(
                use_adopt,
                adopted_v,
                jnp.where(
                    noop_fill,
                    val.noop_vid(idx[None], rows[:, None], i_cap),
                    jnp.where(use_own, own_assign, val.NONE),
                ),
            )
            batch0 = jnp.where(committed_me, val.NONE, batch0)
            return (
                adopted_b,
                adopted_v,
                jnp.where(now_prep[:, None], batch0, cur_batch),
                jnp.where(now_prep[:, None, None], False, acks),
            )

        def _no_prep(cur_batch, acks):
            nones = jnp.full((n, i_cap), bal.NONE, jnp.int32)
            return nones, nones, cur_batch, acks

        adopted_b, adopted_v, cur_batch, acks = jax.lax.cond(
            any_prep, _adopt_and_build, _no_prep, cur_batch, acks
        )
        batch_age = jnp.where(now_prep, 0, batch_age)

        # new-value assignment for prepared proposers (first-fit over
        # the open tail; same shape as core/sim), gated on a prepared
        # proposer actually having queue entries
        can_assign = prepared & alive
        has_q = can_assign & (tail > st.head)

        def _assign(cur_batch, own_assign, head):
            activity = (
                committed_me
                | (cur_batch != val.NONE)
                | (own_assign != val.NONE)
            )
            hi2 = jnp.max(jnp.where(activity, idx[None], -1), axis=1)
            free = idx[None] > hi2[:, None]
            qn = jnp.minimum(tail - head, jnp.int32(i_cap))
            free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1
            kk = jnp.minimum(qn, jnp.sum(free, axis=1, dtype=jnp.int32))
            kk = jnp.where(can_assign, kk, 0)
            takev = free & (free_rank < kk[:, None])
            qpos = jnp.clip(head[:, None] + free_rank, 0, c - 1)
            newv = jnp.take_along_axis(pend, qpos, axis=1)
            return (
                jnp.where(takev, newv, cur_batch),
                jnp.where(takev, newv, own_assign),
                head + kk,
            )

        cur_batch, own_assign, head = jax.lax.cond(
            jnp.any(has_q),
            _assign,
            lambda cb, oa, hd: (cb, oa, hd),
            cur_batch, own_assign, st.head,
        )

        # ---------- crash injection ----------
        # Bernoulli(crash_rate/1e6) per live node per round (ref
        # member/indet.h:146-150 RandomFailure), admitted one candidate
        # at a time: a crash is allowed only if every node that would
        # remain alive keeps a live majority of its own view's
        # acceptors (the cap that lets survivors keep running where the
        # reference aborts the whole process).  Node 0 is the harness
        # driver and never crashes.  Static unroll over candidates — n
        # is the node count, <= 32 by construction.
        crashed = st.crashed
        if crash_rate:
            ku = prng.stream(root, prng.STREAM_CRASH, t)
            u = jax.random.randint(ku, (n,), 0, 1_000_000)
            # admission works over the not-crashed mask (`exist`), NOT
            # the I/O-alive one: a paused node resumes, so it still
            # counts toward live majorities and must never be folded
            # into the crash set by the `~alive_c` complement below
            want = (u < crash_rate) & exist
            qv_new = jnp.sum(acceptors_v, axis=1, dtype=jnp.int32) // 2 + 1
            alive_c = exist
            for x in range(1, n):
                still = alive_c & (rows != x)
                live_acc = jnp.sum(
                    acceptors_v & still[None, :], axis=1, dtype=jnp.int32
                )
                ok = jnp.all(~still | (live_acc >= qv_new))
                alive_c = jnp.where(want[x] & ok, still, alive_c)
            crashed = ~alive_c

        return MemberState(
            t=t + 1,
            crashed=crashed,
            learners=learners_v,
            proposers=proposers_v,
            acceptors=acceptors_v,
            version=version,
            promised=promised,
            max_seen=max_seen,
            acc_ballot=acc_ballot,
            acc_vid=acc_vid,
            learned=learned,
            applied_upto=applied_upto,
            count=count,
            ballot=ballot,
            pmax=pmax,
            prepared=prepared,
            delay_until=delay_until,
            adopted_b=adopted_b,
            adopted_v=adopted_v,
            cur_batch=cur_batch,
            acks=acks,
            batch_age=batch_age,
            own_assign=own_assign,
            pend=pend,
            head=head,
            tail=tail,
            stall=stall,
            chosen_vid=chosen_vid,
            chosen_round=chosen_round,
            chosen_ballot=chosen_ballot,
        )

    return round_fn


class MemberSim:
    """Host driver around the synchronous membership engine — plays
    the role of member/main.cpp: injects proposals and membership
    changes, steps the engine, exposes the Applied predicate and the
    per-node applied logs."""

    def __init__(
        self,
        n_nodes: int,
        n_instances: int,
        seed: int = 0,
        crash_rate: int = 0,
        schedule=None,
    ):
        from tpu_paxos.core import faults as fltm

        self.n = n_nodes
        self.i = n_instances
        self.c = n_instances * 2 + 8
        self.root = prng.root_key(seed)
        self.state = _init(n_nodes, n_instances, self.c)
        self.schedule = schedule  # FaultSchedule | None (core/faults.py)
        if schedule is not None and any(
            e.kind == "crash" for e in schedule.episodes
        ):
            # deterministic crash points are a general-engine feature;
            # this engine's crash model is the host-driven i.i.d. one
            # (its round body never reads the compiled crash rows, so
            # accepting them would silently ignore the fault)
            raise ValueError(
                "membership engine does not support crash episodes; "
                "use crash_rate"
            )
        comp = fltm.compile_schedule(schedule, n_nodes)
        self._round = jax.jit(
            _build_round(
                n_nodes, n_instances, self.c, self.root, crash_rate, comp
            )
        )
        # Injection log: every (round, op, args) a host driver feeds
        # in.  The engine itself is a pure function of (seed, round),
        # but the DRIVER is an arbitrary nondeterministic host program
        # — it may pace itself by wall clock, sleeps, or external I/O,
        # so WHICH round each injection lands on is the one piece of
        # host nondeterminism in the composite.  Recording it makes
        # the whole run replayable: the TPU-native equivalent of the
        # reference's Indet record/replay subsystem, which logs every
        # clock read and lock-acquire order to replay a
        # nondeterministic host (ref member/indet.h:182-194,
        # member/indet.cpp:24-119, member/run.sh:10-16).
        self._init_args = {
            "n_nodes": n_nodes,
            "n_instances": n_instances,
            "seed": seed,
            "crash_rate": crash_rate,
            # the episode schedule is part of the run's deterministic
            # identity — a replay must re-inject the same partitions/
            # pauses or the engine diverges from the recorded log
            "schedule": schedule.to_dict() if schedule is not None else None,
        }
        self.injections: list[list] = []
        self.crash_rate = crash_rate
        # Round at which each node's CURRENT crash was observed — the
        # rejoin guard ties a checkpoint to this epoch, or a stale
        # snapshot from an earlier crash of the same node could roll
        # back promises granted in between (the lost-promise hazard).
        self._crash_round: dict[int, int] = {}

    # -- injection (between rounds, host-side; the reference's
    # Node::Propose / AddAcceptor / DelAcceptor surface) --
    def propose(self, node: int, vid: int) -> None:
        st = self.state
        if bool(st.crashed[node]):
            # The reference would have aborted the whole run by now; a
            # silent enqueue to a dead node would just hang the caller.
            raise RuntimeError(f"node {node} has crashed; propose elsewhere")
        pos = int(st.tail[node])
        # Reserve n_instances slots of headroom for conflict requeues:
        # assignments only target instances above the committed
        # high-water mark and a conflicted instance is committed, so at
        # most n_instances requeues can ever be scattered at the tail
        # (same capacity proof as core/sim.prepare_queues).
        if pos >= self.c - self.i:
            raise RuntimeError(
                "pending queue full (headroom reserved for requeues)"
            )
        self.state = st._replace(
            pend=st.pend.at[node, pos].set(vid),
            tail=st.tail.at[node].add(1),
        )
        # logged only once it actually landed (post-guards)
        self.injections.append([int(st.t), "propose", [int(node), int(vid)]])

    def propose_in_order(
        self, node: int, vids, max_rounds_each: int = 2000
    ) -> bool:
        """In-order client: propose each vid only after the previous
        one is chosen (the host-gating pattern the reference driver
        uses for dependent proposals, ref member/main.cpp:138-140;
        multi/'s in-order clients are the core/sim gate arrays).
        Returns True when every value was chosen in order."""
        for v in vids:
            self.propose(node, int(v))
            if not self.run_until(
                lambda: self.chosen(int(v)), max_rounds=max_rounds_each
            ):
                return False
        return True

    def add_acceptor(
        self, target: int, via: int = 0, force: bool = False
    ) -> int:
        """Propose adding ``target`` to the acceptor set.

        Guard (host-side, advisory): adding a CRASHED node inflates the
        quorum denominator without adding a live acceptor — the mirror
        image of the del_acceptor hazard.  (Adding a live node is
        always safe: numerator and denominator grow together.)"""
        if not force and bool(self.state.crashed[target]):
            raise ValueError(
                f"node {target} has crashed; adding it would inflate the "
                "quorum without a live acceptor (or pass force=True)"
            )
        vid = change_vid(target, ADD_ACCEPTOR)
        self.propose(via, vid)
        return vid

    def del_acceptor(
        self, target: int, via: int = 0, force: bool = False
    ) -> int:
        """Propose removing ``target`` from the acceptor set.

        Guard (host-side, advisory): deleting a LIVE acceptor while
        crashed ones remain can shrink the view below a live majority
        and wedge the cluster — the crash-admission cap only holds at
        crash time.  Delete crashed members first; ``force=True``
        overrides (the reference has no such guard because its crashes
        abort the whole run)."""
        if not force:
            acc_new = self._projected_acceptors(via)
            acc_new[target] = False
            alive = ~np.asarray(self.state.crashed)
            q_new = int(acc_new.sum()) // 2 + 1
            live_new = int((acc_new & alive).sum())
            if live_new < q_new:
                raise ValueError(
                    f"deleting acceptor {target} would leave {live_new} "
                    f"live acceptors of a {q_new}-quorum view; delete "
                    "crashed members first (or pass force=True)"
                )
        vid = change_vid(target, DEL_ACCEPTOR)
        self.propose(via, vid)
        return vid

    def _projected_acceptors(self, via: int) -> np.ndarray:
        """``via``'s acceptor view with every in-flight membership
        change applied: chosen-but-unapplied log entries, own
        assignments in flight, and the pending ring.  The del/add
        guards check against this projection so pipelined changes
        queued before any applies can't jointly wedge the cluster."""
        st = self.state
        acc = np.asarray(st.acceptors[via]).copy()

        def apply_vid(v: int) -> None:
            if v < CHANGE_BASE:
                return
            tgt, kind = decode_change(v)
            if kind in (ADD_ACCEPTOR, PROPOSER_TO_ACCEPTOR):
                acc[tgt] = True
            elif kind in (DEL_ACCEPTOR, ACCEPTOR_TO_PROPOSER):
                acc[tgt] = False

        chosen = np.asarray(st.chosen_vid)
        upto = int(st.applied_upto[via])
        for v in chosen[upto:]:
            if v != int(val.NONE):
                apply_vid(int(v))
        for v in np.asarray(st.own_assign[via]):
            if v != int(val.NONE):
                apply_vid(int(v))
        pend = np.asarray(st.pend[via])
        for pos in range(int(st.head[via]), min(int(st.tail[via]), self.c)):
            if pend[pos] != int(val.NONE):
                apply_vid(int(pend[pos]))
        return acc

    # -- stepping --
    def run_rounds(self, k: int) -> None:
        with tracecount.engine_scope("member"):
            self._run_rounds(k)

    def _run_rounds(self, k: int) -> None:
        for _ in range(k):
            self.state = self._round(self.state)
            if self.crash_rate:
                # engine-injected crashes don't pass through crash();
                # observe them so the rejoin epoch guard stays sound
                # (deterministic: the schedule is a function of
                # (seed, round), so replays see the same rounds)
                for nn in np.flatnonzero(np.asarray(self.state.crashed)):
                    self._crash_round.setdefault(int(nn), int(self.state.t))
        # Capacity proof holds at runtime: the conflict-requeue scatter
        # (mode="drop") must never have been pushed past the ring.
        if int(np.max(np.asarray(self.state.tail))) > self.c:
            raise RuntimeError("pending ring overflow: requeue lost")

    def run_until(self, pred, max_rounds: int = 2000, step: int = 4) -> bool:
        for _ in range(0, max_rounds, step):
            if pred():
                return True
            self.run_rounds(step)
        return pred()

    # -- predicates / views --
    def chosen(self, vid: int) -> bool:
        return bool(np.any(np.asarray(self.state.chosen_vid) == vid))

    def applied(self, vid: int, viewer: int = 0) -> bool:
        """Applied = a majority of the viewer's current acceptors have
        learned the value (ref member/paxos.cpp:1716-1733)."""
        st = self.state
        cv = np.asarray(st.chosen_vid)
        where = np.flatnonzero(cv == vid)
        if not where.size:
            return False
        i = int(where[0])
        acc = np.asarray(st.acceptors[viewer])
        learned = np.asarray(st.learned[i]) != int(val.NONE)
        return int((acc & learned).sum()) >= int(acc.sum()) // 2 + 1

    def applied_log(self, node: int) -> np.ndarray:
        """Real (non-noop, non-change) values node has applied, in
        order — what the reference's checking StateMachine collects
        (ref member/main.cpp:223-233)."""
        st = self.state
        upto = int(st.applied_upto[node])
        col = np.asarray(st.learned[:upto, node])
        return col[(col >= 0) & (col < CHANGE_BASE)]

    def crashed_set(self) -> set[int]:
        return set(np.flatnonzero(np.asarray(self.state.crashed)).tolist())

    def next_shrink_target(self, viewer: int = 0) -> int | None:
        """The safe deletion order when shrinking back to {0}: crashed
        acceptors first (their removal restores live-majority headroom
        — the policy the del_acceptor guard enforces), then the highest
        live one.  None once only node 0 remains."""
        accs = self.acceptor_set(viewer) - {0}
        if not accs:
            return None
        dead = sorted(accs & self.crashed_set())
        return dead[0] if dead else max(accs)

    def acceptor_set(self, viewer: int = 0) -> set[int]:
        return set(np.flatnonzero(np.asarray(self.state.acceptors[viewer])).tolist())

    # -- crash / rejoin --
    def crash(self, node: int) -> None:
        """Inject a deterministic fail-stop crash (the randomized
        schedule lives in the engine, ref member/indet.h:146-150).
        Guarded by the same admission rule the engine uses: every
        survivor must keep a live majority of its own view's
        acceptors, or the cluster would wedge.  Node 0 is the harness
        driver and never crashes."""
        if node == 0:
            raise ValueError("node 0 is the harness driver; it stays up")
        st = self.state
        alive_after = ~np.asarray(st.crashed)
        alive_after[node] = False
        acc = np.asarray(st.acceptors)
        for v in np.flatnonzero(alive_after):
            q = int(acc[v].sum()) // 2 + 1
            if int((acc[v] & alive_after).sum()) < q:
                raise ValueError(
                    f"crashing node {node} would leave node {v} without "
                    "a live majority of its acceptor view"
                )
        self.state = st._replace(crashed=st.crashed.at[node].set(True))
        self._crash_round[node] = int(st.t)
        self.injections.append([int(st.t), "crash", [int(node)]])

    def rejoin_from_checkpoint(self, node: int, path) -> None:
        """Crash-rejoin durability — EXCEEDS the reference, which
        persists nothing (SURVEY §5: "promises don't survive a
        crash"): restore ``node``'s durable per-node state from a
        checkpoint taken AT OR AFTER its crash, clear the crash bit,
        and let the engine's anti-entropy pull + apply frontier catch
        it up.  A crashed node's arrays are frozen (fail-stop), so
        such a snapshot equals its state at the failure point —
        restoring an earlier snapshot would be the classic
        lost-promise hazard (promises granted between snapshot and
        crash forgotten), which is why the checkpoint must show the
        node already crashed."""
        from tpu_paxos import checkpoint as ckpt

        st = self.state
        if not bool(st.crashed[node]):
            # double-rejoin / live-node call: restoring would roll a
            # LIVE node's promises back to crash-time values
            raise ValueError(
                f"node {node} is not crashed; rejoin would overwrite "
                "live state with the snapshot"
            )
        snap, _meta = ckpt.restore(path, like=st)
        if not bool(snap.crashed[node]):
            raise ValueError(
                f"checkpoint predates node {node}'s crash — restoring it "
                "would forget promises granted after the snapshot"
            )
        cr = self._crash_round.get(node)
        if cr is not None and int(snap.t) < cr:
            # a snapshot from an EARLIER crash epoch of the same node:
            # promises granted between its rejoin and the current
            # crash would be forgotten
            raise ValueError(
                f"checkpoint is from round {int(snap.t)}, before node "
                f"{node}'s current crash at round {cr} — stale epoch"
            )

        # Per-node leaves, restored by their node-axis position; the
        # completeness check below turns a future MemberState field
        # that is neither listed nor global into a hard failure
        # instead of a silently-unrestored leaf.
        node_major = (
            "learners", "proposers", "acceptors", "version", "promised",
            "max_seen", "applied_upto", "count", "ballot", "pmax",
            "prepared", "delay_until", "adopted_b", "adopted_v",
            "cur_batch", "acks", "batch_age", "own_assign", "pend",
            "head", "tail", "stall",
        )
        node_minor = ("acc_ballot", "acc_vid", "learned")  # [I, N]
        cluster_global = {"t", "chosen_vid", "chosen_round", "chosen_ballot"}
        kw = {"crashed": st.crashed.at[node].set(False)}
        for f in node_major:
            kw[f] = getattr(st, f).at[node].set(getattr(snap, f)[node])
        for f in node_minor:
            kw[f] = getattr(st, f).at[:, node].set(
                getattr(snap, f)[:, node]
            )
        uncovered = set(type(st)._fields) - set(kw) - cluster_global
        if uncovered:
            raise RuntimeError(
                "rejoin_from_checkpoint does not cover MemberState "
                f"fields {sorted(uncovered)}; classify them as "
                "node-major, node-minor, or cluster-global"
            )
        self.state = st._replace(**kw)
        self._crash_round.pop(node, None)
        # Replaying a rejoin needs the checkpoint artifact to still
        # exist at the recorded path — and to still be the SAME file:
        # the injection log pins its sha256 and geometry at record
        # time, and replay() verifies both before restoring, so a
        # moved/rewritten checkpoint fails loudly instead of silently
        # diverging from the recorded run.
        self.injections.append(
            [
                int(st.t),
                "rejoin",
                [
                    int(node),
                    str(path),
                    {
                        "sha256": _file_sha256(path),
                        "n_nodes": self.n,
                        "n_instances": self.i,
                    },
                ],
            ]
        )

    # -- host-injection record / replay (component 9's escape hatch;
    # ref member/indet.cpp:24-119 record/replay, member/diff.sh:1-3) --
    def save_injections(self, path) -> None:
        """Write the injection schedule as the replay artifact: engine
        geometry + seed, the (round, op, args) stream, and the final
        round count.  A driver paced by wall clock produces a
        different schedule every run; the artifact pins the one that
        happened."""
        import json

        with open(path, "w") as f:
            json.dump(
                {
                    "version": 1,
                    **self._init_args,
                    "ops": self.injections,
                    "final_t": int(self.state.t),
                },
                f,
            )

    @classmethod
    def replay(cls, path) -> "MemberSim":
        """Re-execute a recorded run: same engine seed, every injection
        applied at the recorded round, stepped to the recorded final
        round.  The result is bit-identical to the recorded run (the
        engine is deterministic in (seed, round, schedule); the log
        supplies the host's side), decision_log() byte-compares equal."""
        import json

        from tpu_paxos.core import faults as fltm

        with open(path) as f:
            log = json.load(f)
        if log.get("version") != 1:
            raise ValueError(f"unknown injection-log version {log.get('version')}")
        sched = (
            fltm.FaultSchedule.from_dict(log["schedule"])
            if log.get("schedule")
            else None
        )
        ms = cls(
            n_nodes=log["n_nodes"],
            n_instances=log["n_instances"],
            seed=log["seed"],
            crash_rate=log["crash_rate"],
            schedule=sched,
        )
        for t_op, op, args in log["ops"]:
            if int(ms.state.t) > t_op:
                raise RuntimeError(
                    f"injection log out of order: at round {int(ms.state.t)} "
                    f"but op recorded for round {t_op}"
                )
            while int(ms.state.t) < t_op:
                ms.run_rounds(1)
            if op == "propose":  # add/del/transition ops record as propose
                ms.propose(*args)
            elif op == "crash":
                ms.crash(*args)
            elif op == "rejoin":
                # Integrity gate BEFORE restoring: the recorded run pinned
                # the checkpoint's content hash and geometry; a replay
                # against a moved/rewritten/misconfigured file must fail
                # with a named cause, not diverge silently.  (Logs from
                # before the pinning carry 2-element args; those replay
                # unverified, as recorded.)
                node, ck_path = args[0], args[1]
                if len(args) > 2 and args[2]:
                    meta = args[2]
                    if not os.path.exists(ck_path):
                        raise ValueError(
                            f"rejoin checkpoint {ck_path!r} missing at "
                            "replay time"
                        )
                    got = _file_sha256(ck_path)
                    if got != meta.get("sha256"):
                        raise ValueError(
                            f"rejoin checkpoint {ck_path!r} sha256 "
                            f"{got[:16]}... != recorded "
                            f"{str(meta.get('sha256'))[:16]}... — the file "
                            "changed since the run was recorded"
                        )
                    if (
                        meta.get("n_nodes") != ms.n
                        or meta.get("n_instances") != ms.i
                    ):
                        raise ValueError(
                            "rejoin checkpoint geometry "
                            f"({meta.get('n_nodes')} nodes x "
                            f"{meta.get('n_instances')} instances) does not "
                            f"match the replayed run ({ms.n} x {ms.i})"
                        )
                ms.rejoin_from_checkpoint(node, ck_path)
            else:
                raise ValueError(f"unknown op {op!r} in injection log")
        while int(ms.state.t) < log["final_t"]:
            ms.run_rounds(1)
        return ms

    def decision_log(self) -> str:
        """Canonical decision-log text — chosen (vid, round, ballot)
        per instance plus each node's applied log — the byte-compare
        surface for record-vs-replay (mirrors member/diff.sh diffing
        two runs' logs)."""
        st = self.state
        cv = np.asarray(st.chosen_vid)
        cr = np.asarray(st.chosen_round)
        cb = np.asarray(st.chosen_ballot)
        lines = [
            f"[{i}] = <{cv[i]}>@{cr[i]}#{cb[i]}"
            for i in np.flatnonzero(cv != int(val.NONE))
        ]
        for node in range(self.n):
            seq = " ".join(map(str, self.applied_log(node).tolist()))
            lines.append(f"applied[{node}] = {seq}")
        return "\n".join(lines) + "\n"

    def learner_set(self, viewer: int = 0) -> set[int]:
        return set(np.flatnonzero(np.asarray(self.state.learners[viewer])).tolist())


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical trace of the membership round (analysis/registry.py):
    crash_rate on, so the crash-admission sampling is in the traced
    program the op budget pins."""
    from tpu_paxos.analysis.registry import AuditEntry

    def build():
        n, i = 3, 8
        c = i * 2 + 8
        root = prng.root_key(0)
        state = _init(n, i, c)
        fn = _build_round(n, i, c, root, crash_rate=500, comp=None)
        return fn, (state,)

    def build_replay():
        # The replay() configuration (the PR-3 follow-on ROADMAP item
        # 3 called out as un-audited): replay reconstructs MemberSim
        # with the RECORDED fault schedule, so the round it steps is
        # the schedule-bearing build — compiled reach/pause tables as
        # baked constants (what IR205's const budget watches here),
        # the heal-horizon clamp, and the paused-receiver drops all in
        # the traced program.  A regression in this trace is a replay
        # that diverges from its recording.
        from tpu_paxos.core import faults as fltm

        n, i = 3, 8
        c = i * 2 + 8
        sched = fltm.FaultSchedule((
            fltm.partition(2, 10, (0,), (1, 2)),
            fltm.pause(4, 9, 1),
        ))
        comp = fltm.compile_schedule(sched, n)
        root = prng.root_key(0)
        state = _init(n, i, c)
        fn = _build_round(n, i, c, root, crash_rate=500, comp=comp)
        return fn, (state,)

    return [
        AuditEntry("member.round", build,
                   covers=("MemberSim.__init__",)),
        AuditEntry("member.round_replay", build_replay),
    ]
