"""Trace-level dump helpers — the DumpHex / debug-format analog.

The reference hex-dumps wire bytes at TRACE level
(ref multi/paxos.cpp:32-44 ``DumpHex``, used by the harness at
multi/main.cpp:137-146).  This framework's wire format is typed
arrays, so the analog is a compact array dump: shape/dtype header plus
a bounded, greppable element listing, built for the leveled logger's
TRACE sink (utils/log.py)."""

from __future__ import annotations

import numpy as np


def dump_hex(buf: bytes, limit: int = 256) -> str:
    """Byte-for-byte port of the reference's hex format: uppercase hex
    pairs separated by spaces (ref multi/paxos.cpp:32-44), truncated
    at ``limit`` bytes with an ellipsis marker."""
    shown = buf[:limit]
    body = " ".join(f"{b:02X}" for b in shown)
    if len(buf) > limit:
        body += f" .. (+{len(buf) - limit} bytes)"
    return body


def dump_array(name: str, arr, limit: int = 32) -> str:
    """One-line array dump for TRACE logs: name, shape, dtype, and the
    first ``limit`` elements (row-major), with NONE sentinels shown as
    '.' to keep decision tensors readable."""
    a = np.asarray(arr)
    flat = a.reshape(-1)[:limit]
    body = " ".join("." if int(v) == -1 else str(int(v)) for v in flat)
    more = a.size - min(a.size, limit)
    tail = f" .. (+{more})" if more else ""
    return f"{name}{list(a.shape)}:{a.dtype}= {body}{tail}"
