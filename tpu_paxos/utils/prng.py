"""Deterministic randomness — the replacement for the reference's LCG.

The reference seeds a hand-rolled LCG (``x = x*1103515245 + 12345``,
ref multi/paxos.h:172-185) and, in member/, derives child thread seeds
from the parent's stream so record/replay runs see identical random
sequences (ref member/indet.h:111-131).  Here the same property comes
from counter-based ``jax.random``: every consumer folds a static tag
and the round number into the root key, so randomness is a pure
function of (seed, tag, round) — replay for free, and identical across
hosts in a multi-host mesh.
"""

from __future__ import annotations

import jax

# Pin the threefry implementation: partitionable counter-based keys.
# The flag CHANGES THE SAMPLED VALUES, so it is part of the engine's
# determinism contract — a repro artifact or injection log recorded
# under one setting must replay identically in any host (pytest's
# conftest sets True; older jax defaults False — without this pin the
# same seed produced different runs in-process vs via the CLI).  Also
# required for identical streams across shard counts on a mesh.
jax.config.update("jax_threefry_partitionable", True)

# Stable stream tags (fold_in indices). Adding a stream = appending here.
STREAM_PREPARE_DELAY = 0
STREAM_NET_DROP = 1
STREAM_NET_DUP = 2
STREAM_NET_DELAY = 3
STREAM_CRASH = 4
STREAM_WORKLOAD = 5


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def stream(key: jax.Array, tag: int, round_idx) -> jax.Array:
    """Key for one (stream, round) — pure function of its inputs."""
    return jax.random.fold_in(jax.random.fold_in(key, tag), round_idx)
