"""Leveled host-side logger — the reference Logger, TPU-framework style.

The reference serializes 7-level log lines with a ms timestamp, thread
name, file:line and function to stdout under a spinlock
(ref multi/paxos.cpp:74-103, levels at multi/paxos.h:90-110:
TRACE, DEBUG, INFO, NOTICE, WARNING, ERROR, CRITICAL).  The TPU build
keeps the same surface for the *host* side of the framework — harness
drivers, runners, the CLI — while on-device visibility goes through
dumped decision tensors (``trace_dump``) and the jax profiler
(``profile_trace``), which is where TPU debugging actually happens.

Line format (reference shape, ref multi/paxos.cpp:95-101):

    [2026-07-29 12:00:00.123]\t[INFO]\t[name]\t[file.py:42]\t[fn]\tmsg
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time

TRACE, DEBUG, INFO, NOTICE, WARNING, ERROR, CRITICAL = range(7)
LEVEL_NAMES = ("TRACE", "DEBUG", "INFO", "NOTICE", "WARNING", "ERROR", "CRITICAL")

_lock = threading.Lock()  # stdout serialization (ref Logger's SpinLock)

#: Fixed stamp emitted in deterministic mode: same width/format as a
#: real one, so line-oriented consumers (and byte-compares) see a
#: stable prefix instead of wall clock.
ZERO_STAMP = "0000-00-00 00:00:00.000"


def deterministic_mode() -> bool:
    """True when log output must be byte-stable across runs
    (``TPU_PAXOS_DETERMINISTIC=1``).  Replay surfaces — ``python -m
    tpu_paxos repro`` and ``--replay-injections`` — switch this on so
    nothing a byte-compare might capture carries wall-clock time; the
    env var is read per call so tests can toggle it."""
    return os.environ.get("TPU_PAXOS_DETERMINISTIC", "") not in ("", "0")


def _stamp() -> str:
    """Wall-clock line stamp, zeroed under deterministic_mode().  The
    one sanctioned wall-clock read in the replay-critical import
    closure: it exists only for humans tailing stderr and is
    suppressed whenever bytes must replay."""
    if deterministic_mode():
        return ZERO_STAMP
    now = time.time()  # paxlint: allow[DET001] zeroed in deterministic mode
    # paxlint: allow[DET001] zeroed in deterministic mode
    base = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(now))
    return f"{base}.{int((now % 1) * 1000):03d}"


def parse_level(raw: str, default: int = INFO) -> int:
    """Numeric level from a name or digit; clamps digits to the valid
    range, accepts the common WARN/ERR aliases, and falls back to
    ``default`` on anything unrecognized."""
    if not raw:
        return default
    if raw.isdigit():
        return max(0, min(int(raw), CRITICAL))
    name = {"WARN": "WARNING", "ERR": "ERROR", "CRIT": "CRITICAL"}.get(
        raw.upper(), raw.upper()
    )
    try:
        return LEVEL_NAMES.index(name)
    except ValueError:
        return default


def level_from_env(default: int = INFO) -> int:
    """Numeric level from TPU_PAXOS_LOG_LEVEL, mirroring the
    reference's ``--log-level=N`` flag (ref multi/main.cpp:469)."""
    return parse_level(os.environ.get("TPU_PAXOS_LOG_LEVEL", ""), default)


class Logger:
    """Leveled logger; messages below ``level`` are dropped."""

    def __init__(self, name: str = "tpu_paxos", level: int | None = None,
                 stream=None):
        self.name = name
        self.level = level_from_env() if level is None else level
        self.stream = stream if stream is not None else sys.stderr

    def log(self, level: int, msg: str, *args) -> None:
        self._log(level, msg, args, depth=1)

    def _log(self, level: int, msg: str, args, depth: int) -> None:
        if level < self.level:
            return
        try:
            frame = sys._getframe(depth + 1)
        except ValueError:
            frame = sys._getframe()
        where = f"{os.path.basename(frame.f_code.co_filename)}:{frame.f_lineno}"
        fn = frame.f_code.co_name
        text = msg % args if args else msg
        line = (
            f"[{_stamp()}]\t[{LEVEL_NAMES[level]}]\t[{self.name}]\t"
            f"[{where}]\t[{fn}]\t{text}\n"
        )
        with _lock:
            self.stream.write(line)

    def trace(self, msg, *a):
        self._log(TRACE, msg, a, depth=1)

    def debug(self, msg, *a):
        self._log(DEBUG, msg, a, depth=1)

    def info(self, msg, *a):
        self._log(INFO, msg, a, depth=1)

    def notice(self, msg, *a):
        self._log(NOTICE, msg, a, depth=1)

    def warning(self, msg, *a):
        self._log(WARNING, msg, a, depth=1)

    def error(self, msg, *a):
        self._log(ERROR, msg, a, depth=1)

    def critical(self, msg, *a):
        self._log(CRITICAL, msg, a, depth=1)


_default = Logger()


def get_logger(name: str | None = None, level: int | None = None) -> Logger:
    if name is None and level is None:
        return _default
    return Logger(name or "tpu_paxos", level)


def trace_dump(logger: Logger, label: str, arr, limit: int = 64) -> None:
    """TRACE-level dump of a (small prefix of a) decision tensor — the
    array analog of the reference's DumpHex wire dumps
    (ref multi/paxos.cpp:32-44)."""
    if TRACE < logger.level:
        return
    import numpy as np

    a = np.asarray(arr)
    flat = a.reshape(-1)
    head = np.array2string(flat[:limit], max_line_width=120)
    suffix = f" …(+{flat.size - limit})" if flat.size > limit else ""
    logger.log(TRACE, "%s shape=%s %s%s", label, a.shape, head, suffix)


@contextlib.contextmanager
def profile_trace(out_dir: str | None):
    """jax profiler window (for the bench harness); no-op when
    ``out_dir`` is falsy."""
    if not out_dir:
        yield
        return
    import jax

    with jax.profiler.trace(out_dir):
        yield
