"""L0 primitives: PRNG streams (utils/prng.py), leveled host logging
(utils/log.py — the reference Logger analog, ref multi/paxos.cpp:74-103),
and TRACE dump helpers (utils/dump.py — the DumpHex analog)."""

from tpu_paxos.utils.dump import dump_array, dump_hex  # noqa: F401
from tpu_paxos.utils.log import Logger, get_logger  # noqa: F401
