"""L0 primitives: PRNG streams (utils/prng.py), leveled host logging
(utils/log.py — the reference Logger analog, ref multi/paxos.cpp:74-103)."""

from tpu_paxos.utils.log import Logger, get_logger  # noqa: F401
