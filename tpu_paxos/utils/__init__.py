"""L0 primitives: PRNG streams, host logging."""
