"""``python -m tpu_paxos`` — the reference CLI, TPU-framework edition.

Mirrors the reference's argument surface (ref multi/main.cpp:456-521:
positional ``srvcnt cltcnt idcnt [propose_interval]`` + ``--key=value``
flags; canonical line in multi/debug.conf.sample:1) with the TPU-build
extensions: ``--backend``, ``--mesh``, ``--engine``.  Wall-clock
milliseconds become integer rounds of the bulk-synchronous schedule
(config.py), so the debug.conf line transliterates with delay values
scaled to rounds; ``propose_interval`` is accepted for fidelity and
ignored (client pacing is subsumed by the round schedule and gates).

Output: the decision log in the reference grammar
(ref multi/paxos.cpp:18-22) on stdout, then an invariant verdict line
— the same checks as the reference epilogue (ref multi/main.cpp:566-573).
Exit code 0 iff every invariant holds.

``python -m tpu_paxos repro <artifact.json>`` is the failure-triage
entry point: it re-executes a shrunk repro artifact written by the
stress sweep (harness/shrink.py), prints the decision log, and exits
0 iff the recorded violation recurs with a byte-identical decision
log (sha256 compare — the member/diff.sh workflow for the general
engine).

``python -m tpu_paxos trace <artifact.json>`` renders the same
artifact as a Chrome-trace/Perfetto timeline instead (flight-recorder
telemetry recomputed at replay; telemetry/export.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_paxos",
        description="TPU-native multi-Paxos simulation harness",
    )
    p.add_argument("srvcnt", type=int, help="number of server nodes")
    p.add_argument("cltcnt", type=int, help="number of clients")
    p.add_argument("idcnt", type=int, help="ids proposed per client")
    p.add_argument(
        "propose_interval",
        type=int,
        nargs="?",
        default=0,
        help="accepted for reference-CLI fidelity; pacing is subsumed "
        "by the round schedule",
    )
    p.add_argument("--seed", type=int, default=0)
    # paxos::Config knobs, in rounds (ref multi/paxos.h:251-274).
    p.add_argument("--paxos-prepare-delay-min", type=int, default=0)
    p.add_argument("--paxos-prepare-delay-max", type=int, default=4)
    p.add_argument("--paxos-prepare-retry-count", type=int, default=3)
    p.add_argument("--paxos-prepare-retry-timeout", type=int, default=2)
    p.add_argument("--paxos-accept-retry-count", type=int, default=3)
    p.add_argument("--paxos-accept-retry-timeout", type=int, default=2)
    p.add_argument("--paxos-commit-retry-timeout", type=int, default=2)
    # THNetWork knobs (ref multi/main.cpp:51-162); delays in rounds.
    p.add_argument("--net-drop-rate", type=int, default=0)
    p.add_argument("--net-dup-rate", type=int, default=0)
    p.add_argument("--net-min-delay", type=int, default=0)
    p.add_argument("--net-max-delay", type=int, default=0)
    p.add_argument("--crash-rate", type=int, default=0,
                   help="per-node fail-stop crash rate per 1e6 per round "
                   "(ref member/indet.h:146-150)")
    p.add_argument("--log-level", type=str, default="INFO")
    p.add_argument("--max-rounds", type=int, default=10_000)
    # TPU-build extensions.
    p.add_argument("--backend", choices=("tpu", "cpu", "auto"), default="auto")
    p.add_argument("--mesh", type=int, default=0,
                   help="shard the instance axis over this many devices "
                   "(0 = unsharded)")
    p.add_argument("--dcn-hosts", type=int, default=1,
                   help="with --mesh, arrange devices as a 2-D "
                   "(dcn-hosts x chips) multi-host mesh; collectives "
                   "reduce over both axes")
    p.add_argument("--engine", choices=("sim", "fast", "member"),
                   default="sim")
    p.add_argument("--json", action="store_true",
                   help="emit a JSON summary instead of the verdict line")
    p.add_argument("--trace-dir", type=str, default="",
                   help="write a jax profiler trace of the engine run "
                   "here (view with tensorboard/xprof)")
    p.add_argument("--save-state", type=str, default="",
                   help="dump the run's decision tensors (chosen/learned/"
                   "metrics arrays) to this .npz path")
    p.add_argument("--record-injections", type=str, default="",
                   help="--engine=member: save the run's (round, op, args) "
                   "host-injection log here for later replay (the "
                   "reference's indet record pass, ref member/run.sh)")
    p.add_argument("--replay-injections", type=str, default="",
                   help="--engine=member: instead of running the churn "
                   "scenario, re-execute a recorded injection log; the "
                   "emitted decision-log hash must match the recording "
                   "run's (the reference's replay + diff pass, ref "
                   "member/run.sh:10-16, member/diff.sh)")
    return p


def _select_backend(backend: str, mesh: int = 0) -> None:
    if backend == "auto":
        return
    os.environ["JAX_PLATFORMS"] = backend
    import jax

    try:
        # platform/provisioning flags select WHERE the program runs,
        # not what it computes — value-affecting flags (threefry etc.)
        # live in utils/prng.py (the determinism contract's home)
        # paxlint: allow[DET004] platform selection, value-neutral
        jax.config.update("jax_platforms", backend)
        if backend == "cpu" and mesh > 1:
            # provision enough virtual CPU devices for the requested
            # mesh (a dev box has one CPU device by default)
            try:
                # paxlint: allow[DET004] device provisioning, value-neutral
                jax.config.update("jax_num_cpu_devices", mesh)
            except AttributeError:  # pre-0.5 jax: use the XLA flag
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={mesh}"
                )
    except RuntimeError:
        pass  # backend already initialized; env var did its best


def run_sim(args) -> int:
    import numpy as np

    from tpu_paxos import config as cfgm
    from tpu_paxos.core import sim
    from tpu_paxos.harness import reference_runner as refr
    from tpu_paxos.harness import validate
    from tpu_paxos.replay.decision_log import decision_log as render_log
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger("cli", _level(args))
    workload, gates, in_order = refr.equivalent_workload(
        args.srvcnt, args.cltcnt, args.idcnt
    )
    cfg = cfgm.SimConfig(
        n_nodes=args.srvcnt,
        n_instances=args.cltcnt * args.idcnt * 2,
        proposers=tuple(range(args.srvcnt)),
        seed=args.seed,
        max_rounds=args.max_rounds,
        protocol=cfgm.ProtocolConfig(
            prepare_delay_min=args.paxos_prepare_delay_min,
            prepare_delay_max=args.paxos_prepare_delay_max,
            prepare_retry_count=args.paxos_prepare_retry_count,
            prepare_retry_timeout=args.paxos_prepare_retry_timeout,
            accept_retry_count=args.paxos_accept_retry_count,
            accept_retry_timeout=args.paxos_accept_retry_timeout,
            commit_retry_timeout=args.paxos_commit_retry_timeout,
        ),
        faults=cfgm.FaultConfig(
            drop_rate=args.net_drop_rate,
            dup_rate=args.net_dup_rate,
            min_delay=args.net_min_delay,
            max_delay=args.net_max_delay,
            crash_rate=args.crash_rate,
        ),
    )
    logger.info(
        "sim: %d nodes, %d clients x %d ids, seed %d",
        args.srvcnt, args.cltcnt, args.idcnt, args.seed,
    )
    if args.mesh:
        import dataclasses

        from tpu_paxos.parallel import mesh as pmesh
        from tpu_paxos.parallel import sharded_sim

        # build the mesh first: it may have fewer devices than
        # requested, and the padding must match its actual size
        mesh = pmesh.make_instance_mesh(args.mesh, dcn_hosts=args.dcn_hosts)
        # The chain-aware split keeps each client's gate chain on one
        # shard, so per-shard demand is set by the largest chain
        # cluster, not n_instances/D (e.g. 8 shards, 2 chains: two
        # shards carry everything and the rest sit idle).
        need = sharded_sim.min_instances(workload, gates, mesh.size)
        n_inst = max(cfg.n_instances, need)
        n_inst += (-n_inst) % mesh.size
        if n_inst != cfg.n_instances:
            cfg = dataclasses.replace(cfg, n_instances=n_inst)
        logger.info("instance axis sharded over %d devices", mesh.size)
        runner = lambda: sharded_sim.run_sharded(cfg, mesh, workload, gates)  # noqa: E731
    else:
        runner = lambda: sim.run(cfg, workload, gates)  # noqa: E731
    res = _with_trace(args, runner)
    _maybe_save_result(args, res, logger)
    sys.stdout.write(
        render_log(
            res.chosen_vid, res.chosen_ballot,
            stride=args.idcnt, n_instances=cfg.n_instances,
        )
    )
    ok, verdict = True, []
    try:
        seqs = validate.check_all(res.learned, res.expected_vids)
        validate.check_in_order_clients(seqs[0], in_order)
        if not res.done:
            raise validate.InvariantViolation(
                f"did not quiesce in {res.rounds} rounds"
            )
        verdict = ["agreement", "exactly_once", "in_order_clients",
                   "quiescence"]
    except validate.InvariantViolation as e:
        ok = False
        logger.error("invariant violated: %s", e)
    summary = {
        "engine": "sim",
        "rounds": res.rounds,
        "done": res.done,
        "chosen": int((res.chosen_vid != -1).sum()),
        "executed": int((res.chosen_vid >= 0).sum()),
        "crashed": int(res.crashed.sum()),
        "msgs": res.msgs.tolist(),
        "invariants": verdict,
        "ok": ok,
    }
    _emit(args, summary)
    return 0 if ok else 1


def run_fast(args) -> int:
    import jax.numpy as jnp
    import numpy as np

    from tpu_paxos.core import fast
    from tpu_paxos.harness import validate
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger("cli", _level(args))
    n = args.cltcnt * args.idcnt
    quorum = args.srvcnt // 2 + 1
    vids = jnp.arange(n, dtype=jnp.int32)
    n_devices = 1

    def _go():
        nonlocal n_devices
        from tpu_paxos.analysis import tracecount

        if args.mesh:
            from tpu_paxos.parallel import mesh as pmesh
            from tpu_paxos.parallel import sharded

            mesh = pmesh.make_instance_mesh(args.mesh, dcn_hosts=args.dcn_hosts)
            n_devices = mesh.size  # may be fewer than requested
            st = sharded.init_sharded_state(mesh, n, args.srvcnt)
            step = sharded.sharded_choose_all(mesh, proposer=0, quorum=quorum)
            return step(st, pmesh.shard_instances(mesh, vids))
        st = fast.init_state(n, args.srvcnt)
        with tracecount.engine_scope("fast"):
            return fast.choose_all_jit(st, vids, proposer=0, quorum=quorum)

    state, n_chosen = _with_trace(args, _go)
    if args.save_state:
        # all tensors in the validators' [instances, nodes] convention
        # (the on-device layout is [A, I]; see core/fast.py)
        np.savez(
            args.save_state,
            learned=fast.learned_ia(state),
            acc_ballot=np.asarray(state.acc_ballot).T,
            acc_vid=np.asarray(state.acc_vid).T,
            n_chosen=np.int64(int(n_chosen)),
        )
        logger.info("decision tensors saved to %s", args.save_state)
    ok = True
    try:
        validate.check_all(fast.learned_ia(state), np.arange(n))
    except validate.InvariantViolation as e:
        ok = False
        logger.error("invariant violated: %s", e)
    _emit(args, {
        "engine": "fast",
        "chosen": int(n_chosen),
        "devices": n_devices,
        "invariants": ["agreement", "exactly_once"] if ok else [],
        "ok": ok and int(n_chosen) == n,
    })
    return 0 if ok and int(n_chosen) == n else 1


def run_member(args) -> int:
    """member/ churn scenario: grow the cluster from 1 to srvcnt
    acceptors, propose cltcnt*idcnt values meanwhile, shrink back, and
    validate prefix consistency (ref member/main.cpp:101-161, 260-265)."""
    return _with_trace(args, lambda: _run_member_body(args))


def _run_member_body(args) -> int:
    from tpu_paxos.harness import validate
    from tpu_paxos.membership import engine as mem
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger("cli", _level(args))
    if args.replay_injections:
        if args.record_injections:
            raise SystemExit(
                "--replay-injections and --record-injections are "
                "mutually exclusive (replay re-executes an existing "
                "log; it does not re-record)"
            )
        # replay pass: the engine re-derives everything from the
        # recorded (seed, geometry, schedule) — positional geometry,
        # --seed and --crash-rate on THIS command line are ignored in
        # favor of the log's own parameters; log stamps go
        # deterministic so byte-compares of the output are stable
        os.environ.setdefault("TPU_PAXOS_DETERMINISTIC", "1")
        logger.info(
            "replaying %s: geometry/seed/crash-rate come from the log",
            args.replay_injections,
        )
        sim = mem.MemberSim.replay(args.replay_injections)
        _emit(args, {
            "engine": "member",
            "replayed_from": args.replay_injections,
            "rounds": int(sim.state.t),
            "injections": len(sim.injections),
            "decision_log_sha256": _sha256(sim.decision_log()),
            "ok": True,
        })
        return 0

    def _member_emit(sim, payload: dict) -> None:
        # the injection log saves on EVERY exit — a failing schedule
        # is exactly the one worth replaying — and every member
        # verdict carries the decision-log hash
        if args.record_injections:
            sim.save_injections(args.record_injections)
            logger.info(
                "injection log saved to %s", args.record_injections
            )
        payload["decision_log_sha256"] = _sha256(sim.decision_log())
        _emit(args, payload)
    n = args.srvcnt
    nvals = args.cltcnt * args.idcnt
    sim = mem.MemberSim(n, n_instances=max(4 * (nvals + 4 * n), 64),
                        seed=args.seed, crash_rate=args.crash_rate)
    vid = 0
    for tgt in range(1, n):
        if tgt in sim.crashed_set():
            logger.info("skipping crashed add target %d", tgt)
            continue
        cv = sim.add_acceptor(tgt)
        if vid < nvals:
            sim.propose(0, vid); vid += 1
        if not sim.run_until(lambda: sim.applied(cv), args.max_rounds):
            logger.error("add_acceptor(%d) never applied", tgt)
            _member_emit(sim, {"engine": "member", "ok": False})
            return 1
    # Propose via node 0 — the one node whose proposer role survives
    # the whole churn schedule (the reference's driver also proposes
    # through a fixed node, ref member/main.cpp:204-212).
    while vid < nvals:
        sim.propose(0, vid)
        vid += 1
        sim.run_rounds(2)
    # Shrink: MemberSim.next_shrink_target orders crashed members
    # first, restoring the live-majority headroom the del guard
    # enforces.
    for _ in range(2 * n):
        tgt = sim.next_shrink_target()
        if tgt is None:
            break
        cv = sim.del_acceptor(tgt)
        if not sim.run_until(lambda: sim.applied(cv), args.max_rounds):
            logger.error("del_acceptor(%d) never applied", tgt)
            _member_emit(sim, {"engine": "member", "ok": False})
            return 1
    # Drain: every proposed value applied at node 0 before the verdict.
    drained = sim.run_until(
        lambda: set(range(nvals)) <= set(sim.applied_log(0).tolist())
        and sim.acceptor_set() == {0},
        args.max_rounds,
    )
    logs = [sim.applied_log(a) for a in range(n)]
    ok = True
    if not drained:
        ok = False
        logger.error(
            "drain incomplete: %d/%d values applied at node 0, "
            "acceptors=%s", len(set(logs[0].tolist()) & set(range(nvals))),
            nvals, sorted(sim.acceptor_set()),
        )
    try:
        validate.check_prefix_consistency(logs)
    except validate.InvariantViolation as e:
        ok = False
        logger.error("invariant violated: %s", e)
    if args.save_state:
        from tpu_paxos import checkpoint

        checkpoint.save(
            args.save_state, sim.state, {"engine": "member", "seed": args.seed}
        )
        logger.info("member state saved to %s", args.save_state)
    _member_emit(sim, {
        "engine": "member",
        "rounds": int(sim.state.t),
        "applied_node0": len(logs[0]),
        "final_acceptors": sorted(sim.acceptor_set()),
        "invariants": ["prefix_consistency"] if ok else [],
        "ok": ok,
    })
    return 0 if ok else 1


def _sha256(text: str) -> str:
    import hashlib

    return hashlib.sha256(text.encode()).hexdigest()[:16]


def _level(args) -> int:
    from tpu_paxos.utils import log as logm

    return logm.parse_level(args.log_level)


def _with_trace(args, runner):
    """Run ``runner`` under a jax profiler trace when --trace-dir is
    set (the bench-harness profiling hook; view with tensorboard)."""
    if not args.trace_dir:
        return runner()
    import jax

    with jax.profiler.trace(args.trace_dir):
        return runner()


def _maybe_save_result(args, res, logger) -> None:
    """--save-state: dump the run's decision tensors (the trace-dump
    analog of the reference's final committed-log print,
    ref multi/paxos.cpp:1694-1703) to an .npz."""
    if not args.save_state:
        return
    import numpy as np

    np.savez(
        args.save_state,
        chosen_vid=res.chosen_vid,
        chosen_round=res.chosen_round,
        chosen_ballot=res.chosen_ballot,
        learned=res.learned,
        crashed=res.crashed,
        msgs=res.msgs,
        rounds=np.int64(res.rounds),
        done=np.bool_(res.done),
    )
    logger.info("decision tensors saved to %s", args.save_state)


def _emit(args, summary: dict) -> None:
    # both shapes leave the process and get scraped/diffed by harness
    # scripts — key order must not depend on which code path built the
    # summary dict (DET003)
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        status = "ALL INVARIANTS GREEN" if summary.get("ok") else "FAILED"
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(summary.items()) if k != "ok"
        )
        print(f"[{summary.get('engine')}] {status} ({detail})")


def run_repro(argv) -> int:
    """``python -m tpu_paxos repro <artifact>`` — re-execute a shrunk
    repro artifact and verify it reproduces: identical violation,
    byte-identical decision log (sha256)."""
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos repro",
        description="replay a stress-triage repro artifact",
    )
    ap.add_argument("artifact", help="path to a repro .json "
                    "(written by the stress sweep's --triage-dir)")
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON summary instead of the verdict line")
    ap.add_argument("--log-level", type=str, default="INFO")
    args = ap.parse_args(argv)
    # replay surface: log stamps must not re-introduce wall clock into
    # anything a byte-compare might capture (utils/log.deterministic_mode)
    os.environ.setdefault("TPU_PAXOS_DETERMINISTIC", "1")
    # Peek the artifact header BEFORE backend init: a sharded artifact
    # records the device count its decision log was produced at, and
    # the mesh must be provisioned up front (virtual CPU devices
    # cannot be added after the backend initializes).  Unreadable /
    # malformed artifacts fall through — load_artifact produces the
    # clean exit-2 schema error below.
    devices = 1
    engine = "sim"
    try:
        with open(args.artifact) as f:
            hdr = json.load(f)
        if isinstance(hdr, dict):
            engine = hdr.get("engine", "sim")
        if engine == "sharded":
            devices = int(hdr.get("devices", 1))
    except (OSError, ValueError, TypeError):
        # TypeError: a non-numeric "devices" (null/list) — like the
        # other malformed shapes, it falls through to load_artifact's
        # exit-2 schema error naming the field
        devices = 1
    if devices > 1:
        backend = "cpu" if args.backend == "auto" else args.backend
        _select_backend(backend, mesh=devices)
    else:
        _select_backend(args.backend)
    from tpu_paxos.utils import log as logm

    if engine == "serve":
        # controlled-serve artifacts replay through the admission
        # controller (serve/control.reproduce): same schema surface,
        # decision log extended with the control trail
        from tpu_paxos.serve import control as shr
    elif engine == "mc-control":
        # controller-invariant counterexamples replay as a pure host
        # decide() trail (analysis/mc_control.reproduce): the artifact
        # carries the full policy, so no wedge env is needed
        from tpu_paxos.analysis import mc_control as shr
    else:
        from tpu_paxos.harness import shrink as shr

    logger = logm.get_logger("repro", _level(args))
    try:
        rep = shr.reproduce(args.artifact)
    except Exception as e:
        from tpu_paxos.analysis.artifact_schema import ArtifactSchemaError

        if not isinstance(e, ArtifactSchemaError):
            raise
        # malformed artifact: fail before the engine does, naming the
        # offending field (analysis/artifact_schema.py)
        logger.error("%s", e)
        _emit(args, {
            "engine": "repro", "ok": False,
            "schema_error": {"field": e.field, "problem": e.problem},
        })
        return 2
    sys.stdout.write(rep.pop("decision_log"))
    if rep["match"]:
        logger.info(
            "reproduced: %s (decision log sha256 %s)",
            rep["violation"], rep["decision_log_sha256"][:16],
        )
    else:
        logger.error(
            "did NOT reproduce: violation %r vs recorded %r, log sha %s "
            "vs recorded %s",
            rep["violation"], rep["recorded_violation"],
            rep["decision_log_sha256"][:16], rep["recorded_sha256"][:16],
        )
    _emit(args, {"engine": "repro", "ok": rep["match"], **rep})
    return 0 if rep["match"] else 1


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "repro":
        # subcommand form: the positional grammar below is the
        # reference CLI's (srvcnt cltcnt idcnt); repro takes a path
        return run_repro(argv[1:])
    if argv and argv[0] == "trace":
        # observability: render a repro artifact as a Chrome-trace/
        # Perfetto timeline (telemetry recomputed at replay)
        from tpu_paxos.telemetry import export as texport

        return texport.main(argv[1:])
    if argv and argv[0] == "serve":
        if "--fleet" in argv[1:]:
            # fleet serving: many tenant streams per dispatch
            # (vmapped serve windows, on-device per-lane SLO
            # verdicts); the (lanes x rates) surface (serve/fleet.py)
            from tpu_paxos.serve import fleet as serve_fleet

            return serve_fleet.main(
                [a for a in argv[1:] if a != "--fleet"]
            )
        # open-loop serving: Poisson / trace arrivals admitted
        # mid-flight through double-buffered dispatch windows;
        # latency-at-load + knee sweep (tpu_paxos/serve/)
        from tpu_paxos.serve import harness as serve_harness

        return serve_harness.main(argv[1:])
    if argv and argv[0] == "fleet":
        # device-batched schedule search: (seed x schedule) lanes per
        # XLA dispatch, wedges shrunk to repro artifacts
        from tpu_paxos.fleet import search as fsearch

        return fsearch.main(argv[1:])
    if argv and argv[0] == "evolve":
        # mutate-and-select wedge hunting: evolve fault/churn/load
        # genomes over fleet lanes, certified recall against the mc
        # certificate's exhaustive denominator
        from tpu_paxos.fleet import evolve as fevolve

        return fevolve.main(argv[1:])
    if argv and argv[0] == "mc":
        # exhaustive bounded model checking: enumerate a declared
        # scope's full scenario cross product as chunked fleet lanes,
        # gate on the pinned scope certificate
        from tpu_paxos.analysis import modelcheck

        return modelcheck.main(argv[1:])
    if argv and argv[0] == "lint":
        # static analysis: pure-AST, deliberately runs without jax
        from tpu_paxos.analysis import lint as lintm

        return lintm.main(argv[1:])
    if argv and argv[0] == "audit":
        # trace-time IR contracts + op/cost budget (needs jax: the
        # provider modules are the engines; only --rules is jax-free)
        from tpu_paxos.analysis import jaxpr_audit

        return jaxpr_audit.main(argv[1:])
    args = build_parser().parse_args(argv)
    _select_backend(args.backend, args.mesh)
    if args.engine == "sim":
        return run_sim(args)
    if args.engine == "fast":
        return run_fast(args)
    return run_member(args)


if __name__ == "__main__":
    sys.exit(main())
