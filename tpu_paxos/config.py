"""Configuration dataclasses — the TPU equivalent of the reference SPI knobs.

The reference exposes retry/timeout knobs through ``paxos::Config``
(ref multi/paxos.h:251-274: prepare_delay_min/max, prepare_retry_count,
prepare_retry_timeout, accept_retry_count, accept_retry_timeout,
commit_retry_timeout) and fault-injection knobs through the harness CLI
(ref multi/main.cpp:467-496: --net-drop-rate, --net-dup-rate,
--net-min-delay, --net-max-delay, --seed).

Here wall-clock milliseconds become integer *rounds* of the
bulk-synchronous schedule: one round is one full message exchange
(request leg + reply leg).  A retry timeout of ``k`` means "if the
quorum has not been reached ``k`` rounds after sending, resend".
"""

from __future__ import annotations

import dataclasses

from tpu_paxos.core.faults import FaultSchedule


@dataclasses.dataclass(frozen=True)
class ProtocolConfig:
    """Protocol liveness knobs, in units of rounds.

    Mirrors ``paxos::Config`` (ref multi/paxos.h:251-274) with
    milliseconds mapped to round counts.
    """

    # Randomized delay before (re)starting a prepare — the anti-dueling
    # backoff (ref multi/paxos.cpp:1244-1247 samples uniformly in
    # [prepare_delay_min_, prepare_delay_max_]).
    prepare_delay_min: int = 0
    prepare_delay_max: int = 4
    # Prepare is resent this many times, prepare_retry_timeout rounds
    # apart, before restarting with a higher ballot
    # (ref multi/paxos.cpp:757-801).
    prepare_retry_count: int = 3
    prepare_retry_timeout: int = 2
    # Accept is resent this many times before falling back to prepare
    # (AcceptRejected, ref multi/paxos.cpp:969-983, 1328-1343).
    accept_retry_count: int = 3
    accept_retry_timeout: int = 2
    # Commit/learn is retried forever, this many rounds apart, until
    # every node has replied (ref multi/paxos.cpp:1022-1027, 1625-1641).
    commit_retry_timeout: int = 2

    def __post_init__(self) -> None:
        if self.prepare_delay_min < 0:
            raise ValueError("prepare_delay_min must be >= 0")
        if self.prepare_delay_min > self.prepare_delay_max:
            raise ValueError("prepare_delay_min > prepare_delay_max")
        for name in (
            "prepare_retry_count",
            "prepare_retry_timeout",
            "accept_retry_count",
            "accept_retry_timeout",
            "commit_retry_timeout",
        ):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")


#: Declared spans for the TRACED protocol knobs
#: (core/geom.ProtocolKnobs).  A padded-envelope executable is shared
#: across protocol-knob mixes, so a knob outside its declared span is
#: rejected BY NAME at encode time (geom.protocol_knobs) instead of
#: silently running a configuration the envelope was never validated
#: for.  ``stall_patience`` is the idle-liveness restart patience
#: (sim.IDLE_RESTART_ROUNDS is the compile-time default).
PROTOCOL_SPANS: dict = {
    "prepare_delay_min": (0, 64),
    "prepare_delay_max": (0, 64),
    "prepare_retry_count": (1, 64),
    "prepare_retry_timeout": (1, 256),
    "accept_retry_count": (1, 64),
    "accept_retry_timeout": (1, 256),
    "commit_retry_timeout": (1, 256),
    "stall_patience": (1, 1024),
}


def _matrix(field: str, m, n: int | None) -> tuple:
    """Canonicalize one per-edge table to a square tuple-of-tuples of
    ints; ``n`` (if known) pins the side length."""
    rows = tuple(tuple(int(x) for x in row) for row in m)
    side = len(rows)
    if n is not None and side != n:
        raise ValueError(f"{field} must be {n}x{n}, got {side} rows")
    for r in rows:
        if len(r) != side:
            raise ValueError(f"{field} must be square ({side}x{side})")
    return rows


@dataclasses.dataclass(frozen=True)
class EdgeFaultConfig:
    """Per-edge ``[A, A]`` i.i.d. fault tables — the WAN-shaped
    generalization of the scalar THNetWork knobs.  Entry ``[s][d]``
    governs messages from node ``s`` to node ``d``: drop/dup rates
    per 1e4 and a uniform delay span in rounds, all free to be
    asymmetric.  A uniform matrix is bit-identical to the equivalent
    scalar knobs (the exact-at-zero masked-sampling contract,
    core/net.py — sha256 parity pinned by tests/test_geo.py), so
    every scalar config is the degenerate case of this model.

    Plain tuples of ints: hashable, JSON-serializable (repro
    artifacts), and structurally comparable like every other config
    dataclass."""

    drop_rate: tuple  # [A][A] per 10_000
    dup_rate: tuple  # [A][A] per 10_000
    min_delay: tuple  # [A][A] rounds
    max_delay: tuple  # [A][A] rounds

    def __post_init__(self) -> None:
        d = _matrix("edges.drop_rate", self.drop_rate, None)
        n = len(d)
        if n < 1:
            raise ValueError("edges tables must name at least one node")
        object.__setattr__(self, "drop_rate", d)
        for f in ("dup_rate", "min_delay", "max_delay"):
            object.__setattr__(self, f, _matrix(f"edges.{f}", getattr(self, f), n))
        for f in ("drop_rate", "dup_rate"):
            for row in getattr(self, f):
                for v in row:
                    if not 0 <= v <= 10_000:
                        raise ValueError(f"edges.{f} must be in [0, 10000]")
        for s in range(n):
            for t in range(n):
                lo, hi = self.min_delay[s][t], self.max_delay[s][t]
                if lo < 0 or lo > hi:
                    raise ValueError(
                        f"edges delay span [{lo}, {hi}] on edge "
                        f"{s}->{t} must satisfy 0 <= min <= max"
                    )

    @property
    def n_nodes(self) -> int:
        return len(self.drop_rate)

    @property
    def delay_bound(self) -> int:
        """Largest per-edge max_delay — the ring bound this matrix
        needs."""
        return max(max(row) for row in self.max_delay)

    @classmethod
    def uniform(cls, n_nodes: int, drop_rate: int = 0, dup_rate: int = 0,
                min_delay: int = 0, max_delay: int = 0) -> "EdgeFaultConfig":
        """The uniform matrix equivalent of scalar knobs (the sha256
        parity anchor)."""
        def full(v):
            return tuple((int(v),) * n_nodes for _ in range(n_nodes))

        return cls(full(drop_rate), full(dup_rate), full(min_delay),
                   full(max_delay))

    def to_dict(self) -> dict:
        return {
            f: [list(r) for r in getattr(self, f)]
            for f in ("drop_rate", "dup_rate", "min_delay", "max_delay")
        }

    @classmethod
    def from_dict(cls, d: dict) -> "EdgeFaultConfig":
        return cls(**{
            f: tuple(tuple(r) for r in d[f])
            for f in ("drop_rate", "dup_rate", "min_delay", "max_delay")
        })


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Network fault injection, THNetWork semantics.

    The reference drops a message with probability drop_rate/10000,
    duplicates with dup_rate/10000 (up to 3 copies, recursively), and
    delays by a uniform sample in [min_delay, max_delay] milliseconds
    (ref multi/main.cpp:51-162).  Here delays are integer rounds; a
    dropped message simply never arrives (the protocol's retry ladder
    provides liveness), and duplicates are re-deliveries of idempotent
    messages (they additionally improve effective delivery probability,
    which is how they are modelled: an edge delivers if any of its
    1 + dup copies survives the drop coin).
    """

    drop_rate: int = 0  # per 10_000
    dup_rate: int = 0  # per 10_000
    min_delay: int = 0  # rounds
    max_delay: int = 0  # rounds
    # member/ style random process crashes: probability per node per
    # round, per 1_000_000 (ref member/indet.h:146-150 crashes with
    # failure_rate/1e6 on every log call).
    crash_rate: int = 0  # per 1_000_000
    # Correlated-fault layer on top of the i.i.d. knobs above: a
    # deterministic schedule of partition / one-way-cut / pause /
    # burst-loss / crash-point / gray episodes (core/faults.py).
    # None = no episodes.
    schedule: FaultSchedule | None = None
    # Per-edge [A, A] drop/dup/delay tables (WAN topologies,
    # asymmetric loss).  When set, the tables REPLACE the scalar
    # drop/dup/min_delay knobs (which must stay 0 — one unambiguous
    # source of truth) and the scalar ``max_delay`` becomes the RING
    # BOUND: it must cover every per-edge max_delay (the arrival
    # calendars are statically sized to ``max_delay + 2`` slots).
    edges: EdgeFaultConfig | None = None
    # Delivery-time partition semantics (the PR-1 follow-on): with
    # True, in-flight copies whose edge is cut on their ARRIVAL round
    # are dropped at the partition edge (same-side copies deliver
    # untouched).  Default False keeps the send-time-only semantics
    # every existing schedule, artifact, and certificate was recorded
    # under — it is a compile-time engine flag, not a runtime knob.
    delivery_cut: bool = False

    def __post_init__(self) -> None:
        if not 0 <= self.drop_rate <= 10_000:
            raise ValueError("drop_rate must be in [0, 10000]")
        if not 0 <= self.dup_rate <= 10_000:
            raise ValueError("dup_rate must be in [0, 10000]")
        if self.min_delay > self.max_delay:
            raise ValueError("min_delay > max_delay")
        if self.min_delay < 0:
            raise ValueError("min_delay must be >= 0")
        if not 0 <= self.crash_rate <= 1_000_000:
            raise ValueError("crash_rate must be in [0, 1000000]")
        if self.schedule is not None and not isinstance(
            self.schedule, FaultSchedule
        ):
            raise TypeError("schedule must be a FaultSchedule or None")
        if (
            self.schedule is not None
            and self.max_delay == 0
            and any(e.kind == "gray" for e in self.schedule.episodes)
        ):
            # NAMED rejection, never silent exclusion (the mc-scope /
            # membership discipline): gray inflation clamps at the
            # ring bound, so at max_delay=0 every gray episode would
            # be a complete no-op — the user would believe they
            # verified gray behavior when no fault was injected
            raise ValueError(
                "gray episodes need a nonzero ring bound: with "
                "max_delay=0 the delay-inflation clamp reduces every "
                "gray episode to a no-op (set max_delay to the delay "
                "headroom gray messages may use)"
            )
        if self.edges is not None:
            if not isinstance(self.edges, EdgeFaultConfig):
                raise TypeError("edges must be an EdgeFaultConfig or None")
            if self.drop_rate or self.dup_rate or self.min_delay:
                raise ValueError(
                    "edges tables replace the scalar drop/dup/delay "
                    "knobs; keep drop_rate/dup_rate/min_delay at 0"
                )
            if self.edges.delay_bound > self.max_delay:
                raise ValueError(
                    f"edges max_delay {self.edges.delay_bound} exceeds "
                    f"the ring bound max_delay={self.max_delay}"
                )

    @property
    def is_reliable(self) -> bool:
        return (
            self.drop_rate == 0
            and self.min_delay == 0
            and self.max_delay == 0
            and self.crash_rate == 0
            and self.edges is None
            and (self.schedule is None or not self.schedule.episodes)
        )


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Whole-simulation shape: the TPU analog of the reference CLI line
    ``srvcnt cltcnt idcnt propose_interval --seed=...``
    (ref multi/main.cpp:456-521, multi/debug.conf.sample:1)."""

    n_nodes: int = 3
    n_instances: int = 100
    # Which nodes act as proposers.  () means node 0 only.
    proposers: tuple[int, ...] = (0,)
    seed: int = 0
    # Hard cap on simulated rounds (liveness watchdog, not a protocol
    # knob).  The scan exits early once every instance is chosen.
    max_rounds: int = 10_000
    # Queue entries a proposer may assign per round (static first-fit
    # window).  The default suits correctness runs; large-instance
    # throughput runs raise it — assignment rate is assign_window per
    # proposer per round at O(window^2) one-hot cost.
    assign_window: int = 64
    protocol: ProtocolConfig = dataclasses.field(default_factory=ProtocolConfig)
    faults: FaultConfig = dataclasses.field(default_factory=FaultConfig)

    def __post_init__(self) -> None:
        if self.n_nodes < 1:
            raise ValueError("n_nodes must be >= 1")
        if self.n_instances < 1:
            raise ValueError("n_instances must be >= 1")
        if self.assign_window < 1:
            raise ValueError("assign_window must be >= 1")
        props = self.proposers or (0,)
        object.__setattr__(self, "proposers", tuple(sorted(set(props))))
        for p in self.proposers:
            if not 0 <= p < self.n_nodes:
                raise ValueError(f"proposer {p} out of range")
        if (
            self.faults.edges is not None
            and self.faults.edges.n_nodes != self.n_nodes
        ):
            raise ValueError(
                f"faults.edges is {self.faults.edges.n_nodes}x"
                f"{self.faults.edges.n_nodes} but the cluster has "
                f"{self.n_nodes} nodes"
            )

    @property
    def quorum(self) -> int:
        # Majority quorum, ref multi/paxos.cpp:1047: n/2 + 1.
        return self.n_nodes // 2 + 1

    @property
    def round_budget(self) -> int:
        """Liveness-watchdog round cap.  With a fault schedule, the
        full ``max_rounds`` budget starts only at the last heal —
        convergence is owed AFTER the final episode ends, however long
        the schedule itself runs (the heal-then-converge contract,
        core/faults.py)."""
        s = self.faults.schedule
        return self.max_rounds + (s.horizon if s is not None else 0)
