"""Configuration dataclasses — the TPU equivalent of the reference SPI knobs.

The reference exposes retry/timeout knobs through ``paxos::Config``
(ref multi/paxos.h:251-274: prepare_delay_min/max, prepare_retry_count,
prepare_retry_timeout, accept_retry_count, accept_retry_timeout,
commit_retry_timeout) and fault-injection knobs through the harness CLI
(ref multi/main.cpp:467-496: --net-drop-rate, --net-dup-rate,
--net-min-delay, --net-max-delay, --seed).

Here wall-clock milliseconds become integer *rounds* of the
bulk-synchronous schedule: one round is one full message exchange
(request leg + reply leg).  A retry timeout of ``k`` means "if the
quorum has not been reached ``k`` rounds after sending, resend".
"""

from __future__ import annotations

import dataclasses

from tpu_paxos.core.faults import FaultSchedule


@dataclasses.dataclass(frozen=True)
class ProtocolConfig:
    """Protocol liveness knobs, in units of rounds.

    Mirrors ``paxos::Config`` (ref multi/paxos.h:251-274) with
    milliseconds mapped to round counts.
    """

    # Randomized delay before (re)starting a prepare — the anti-dueling
    # backoff (ref multi/paxos.cpp:1244-1247 samples uniformly in
    # [prepare_delay_min_, prepare_delay_max_]).
    prepare_delay_min: int = 0
    prepare_delay_max: int = 4
    # Prepare is resent this many times, prepare_retry_timeout rounds
    # apart, before restarting with a higher ballot
    # (ref multi/paxos.cpp:757-801).
    prepare_retry_count: int = 3
    prepare_retry_timeout: int = 2
    # Accept is resent this many times before falling back to prepare
    # (AcceptRejected, ref multi/paxos.cpp:969-983, 1328-1343).
    accept_retry_count: int = 3
    accept_retry_timeout: int = 2
    # Commit/learn is retried forever, this many rounds apart, until
    # every node has replied (ref multi/paxos.cpp:1022-1027, 1625-1641).
    commit_retry_timeout: int = 2

    def __post_init__(self) -> None:
        if self.prepare_delay_min < 0:
            raise ValueError("prepare_delay_min must be >= 0")
        if self.prepare_delay_min > self.prepare_delay_max:
            raise ValueError("prepare_delay_min > prepare_delay_max")
        for name in (
            "prepare_retry_count",
            "prepare_retry_timeout",
            "accept_retry_count",
            "accept_retry_timeout",
            "commit_retry_timeout",
        ):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Network fault injection, THNetWork semantics.

    The reference drops a message with probability drop_rate/10000,
    duplicates with dup_rate/10000 (up to 3 copies, recursively), and
    delays by a uniform sample in [min_delay, max_delay] milliseconds
    (ref multi/main.cpp:51-162).  Here delays are integer rounds; a
    dropped message simply never arrives (the protocol's retry ladder
    provides liveness), and duplicates are re-deliveries of idempotent
    messages (they additionally improve effective delivery probability,
    which is how they are modelled: an edge delivers if any of its
    1 + dup copies survives the drop coin).
    """

    drop_rate: int = 0  # per 10_000
    dup_rate: int = 0  # per 10_000
    min_delay: int = 0  # rounds
    max_delay: int = 0  # rounds
    # member/ style random process crashes: probability per node per
    # round, per 1_000_000 (ref member/indet.h:146-150 crashes with
    # failure_rate/1e6 on every log call).
    crash_rate: int = 0  # per 1_000_000
    # Correlated-fault layer on top of the i.i.d. knobs above: a
    # deterministic schedule of partition / one-way-cut / pause /
    # burst-loss episodes (core/faults.py).  None = no episodes.
    schedule: FaultSchedule | None = None

    def __post_init__(self) -> None:
        if not 0 <= self.drop_rate <= 10_000:
            raise ValueError("drop_rate must be in [0, 10000]")
        if not 0 <= self.dup_rate <= 10_000:
            raise ValueError("dup_rate must be in [0, 10000]")
        if self.min_delay > self.max_delay:
            raise ValueError("min_delay > max_delay")
        if self.min_delay < 0:
            raise ValueError("min_delay must be >= 0")
        if not 0 <= self.crash_rate <= 1_000_000:
            raise ValueError("crash_rate must be in [0, 1000000]")
        if self.schedule is not None and not isinstance(
            self.schedule, FaultSchedule
        ):
            raise TypeError("schedule must be a FaultSchedule or None")

    @property
    def is_reliable(self) -> bool:
        return (
            self.drop_rate == 0
            and self.min_delay == 0
            and self.max_delay == 0
            and self.crash_rate == 0
            and (self.schedule is None or not self.schedule.episodes)
        )


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Whole-simulation shape: the TPU analog of the reference CLI line
    ``srvcnt cltcnt idcnt propose_interval --seed=...``
    (ref multi/main.cpp:456-521, multi/debug.conf.sample:1)."""

    n_nodes: int = 3
    n_instances: int = 100
    # Which nodes act as proposers.  () means node 0 only.
    proposers: tuple[int, ...] = (0,)
    seed: int = 0
    # Hard cap on simulated rounds (liveness watchdog, not a protocol
    # knob).  The scan exits early once every instance is chosen.
    max_rounds: int = 10_000
    # Queue entries a proposer may assign per round (static first-fit
    # window).  The default suits correctness runs; large-instance
    # throughput runs raise it — assignment rate is assign_window per
    # proposer per round at O(window^2) one-hot cost.
    assign_window: int = 64
    protocol: ProtocolConfig = dataclasses.field(default_factory=ProtocolConfig)
    faults: FaultConfig = dataclasses.field(default_factory=FaultConfig)

    def __post_init__(self) -> None:
        if self.n_nodes < 1:
            raise ValueError("n_nodes must be >= 1")
        if self.n_instances < 1:
            raise ValueError("n_instances must be >= 1")
        if self.assign_window < 1:
            raise ValueError("assign_window must be >= 1")
        props = self.proposers or (0,)
        object.__setattr__(self, "proposers", tuple(sorted(set(props))))
        for p in self.proposers:
            if not 0 <= p < self.n_nodes:
                raise ValueError(f"proposer {p} out of range")

    @property
    def quorum(self) -> int:
        # Majority quorum, ref multi/paxos.cpp:1047: n/2 + 1.
        return self.n_nodes // 2 + 1

    @property
    def round_budget(self) -> int:
        """Liveness-watchdog round cap.  With a fault schedule, the
        full ``max_rounds`` budget starts only at the last heal —
        convergence is owed AFTER the final episode ends, however long
        the schedule itself runs (the heal-then-converge contract,
        core/faults.py)."""
        s = self.faults.schedule
        return self.max_rounds + (s.horizon if s is not None else 0)
