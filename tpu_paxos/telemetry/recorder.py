"""The flight recorder: on-device accumulators for the traced round
loops of the general engine and the fleet.

The only windows into a run used to be the terminal verdict, the
decision-log sha256, and a post-hoc shrink — *that* a lane failed,
never *how it got there*.  The recorder answers "how" without leaving
the device: a small :class:`Telemetry` NamedTuple rides the loop carry
next to ``SimState`` (``core/sim.build_engine(..., telemetry=True)``),
every field updated from values the round function already computes,
and a :class:`TelemetrySummary` of fixed small shapes is reduced on
device at the end of the run — under the fleet vmap that means
``[lanes, ...]`` summaries and nothing per-instance ever crosses to
host.

Three field families:

- **protocol counters** — per message type (``MSG_NAMES`` order, the
  ``Metrics.msgs`` convention): copies dropped / duplicated / delayed
  by the fault layer on offered edges, plus event counts (newly
  learned cells, commit-ack replies delivered, commit takeovers,
  conflict requeues, ballot restarts);
- **latency ledger** — round-of-admission per instance (the first
  round the instance had a value in an accept batch), reduced against
  ``chosen_round`` into a fixed-bucket commit-latency histogram
  (``LAT_EDGES``);
- **near-miss margins** — the fitness vector guided adversarial
  search wants (ROADMAP item 2): heal-to-quiesce gap, max
  commit-ladder stall depth, max duel depth (ballot count), first
  takeover round per proposer.

A fourth, TIME-RESOLVED plane rides alongside when the engine is
built with ``window_rounds``: :class:`TelemetryWindows` buckets the
fault-layer counters, stall depth, and takeover/restart events by
virtual round into ``NUM_WINDOWS`` fixed-shape ``[W]`` rings (last
bucket = overflow), and :func:`summarize_windows` derives per-bucket
commit counts and latency-histogram deltas from the decision metrics
at the epilogue — so "when did p99 blow out relative to the fault"
is answerable without storing anything per-round.  Same neutrality
contract; ``[lanes, W]`` under the fleet vmap.

Neutrality contract: the recorder is READ-ONLY — it consumes no PRNG
streams and never feeds back into ``SimState``, so a telemetry-armed
engine is decision-log-identical to the plain one (sha256 parity
pinned by tests/test_telemetry.py for the general engine, fleet
lanes, and the runtime-knob path), and a ``telemetry=False`` build
traces the exact program it traced before (compile-census zero-delta
on warmed envelopes).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from tpu_paxos.core import values as val

#: Message-type order of every [7] counter (the ``Metrics.msgs``
#: convention in core/sim.py's message-counter block).
MSG_NAMES = (
    "prepare",
    "prepare_reply",
    "reject",
    "accept",
    "accept_reply",
    "commit",
    "commit_reply",
)

#: Commit-latency histogram bucket upper edges, in rounds; the last
#: bucket is the overflow (> LAT_EDGES[-1]).  Fixed at trace time so
#: the summary shape never depends on the run.
LAT_EDGES = (1, 2, 4, 8, 16, 32, 64, 128, 256)
NUM_LAT_BUCKETS = len(LAT_EDGES) + 1

#: Windowed time-series plane: NUM_WINDOWS fixed-shape buckets over
#: the virtual clock, each ``window_rounds`` rounds wide; the last
#: bucket is the overflow (everything at and past round
#: ``(NUM_WINDOWS - 1) * window_rounds``).  The bucket COUNT is a
#: module constant so every ``[W]`` series shares one shape; the
#: bucket WIDTH is a trace-time build parameter (``window_rounds``)
#: so each driver picks its own time resolution — the serve driver
#: aligns buckets with its admission windows, the fleet and the
#: single-run engine default to :data:`WINDOW_ROUNDS`.
NUM_WINDOWS = 16
WINDOW_ROUNDS = 16

#: The per-instance PHASE LEDGER's phase order (PR 15): every decided
#: value's commit latency decomposes into queue-wait (ingest to first
#: accept batch — zero on the closed loop, where admission IS the
#: first batch), consensus (first batch to chosen), commit-ladder
#: (chosen to fully commit-acked by every live node), and
#: learn-propagation (chosen to learned by an Applied quorum).  The
#: windowed decomposition (``WindowSummary.phase_hist``) is the
#: diagnosis plane's primary input (telemetry/diagnose.py):
#: queue-dominated latency says saturation, consensus-dominated says
#: duel churn, commit/learn-dominated says a slow or dark receiver.
PHASE_NAMES = ("queue", "consensus", "commit", "learn")
NUM_PHASES = len(PHASE_NAMES)
PHASE_QUEUE, PHASE_CONSENSUS, PHASE_COMMIT, PHASE_LEARN = range(NUM_PHASES)

#: Fixed region capacity of the per-REGION-pair fault counters: the
#: node->region assignment is a RUNTIME ``[A]`` int32 map (clamped
#: into this bound), so one compiled program serves every WAN
#: topology preset — 3-region and 5-region runs share the same
#: ``[R, R]`` summary shape and the same executable.  Unassigned runs
#: default to the all-zero map: every edge lands in region pair
#: (0, 0).
NUM_REGIONS = 8


class Telemetry(NamedTuple):
    """Per-round accumulators carried through the traced loop (one
    lane; ``[lanes, ...]`` under the fleet vmap).  ``admit_round`` is
    the only per-instance field and never leaves the device — it is
    reduced into the latency histogram by :func:`summarize`."""

    offered: np.ndarray  # [7] int32 edges offered to the fault layer
    #     (post-cut: a message lost at a severed edge's NIC never
    #     reaches the drop sampler, so observed-vs-configured rate
    #     comparisons stay exact under schedule cuts)
    dropped: np.ndarray  # [7] int32 copies dropped on offered edges
    duped: np.ndarray  # [7] int32 duplicate copies spawned
    delayed: np.ndarray  # [7] int32 surviving copies with delay > 0
    learns: np.ndarray  # int32 newly learned (node, instance) cells
    commit_acks: np.ndarray  # int32 commit-ack replies delivered
    takeovers: np.ndarray  # int32 instances adopted by commit takeover
    requeues: np.ndarray  # int32 conflict requeues appended
    restarts: np.ndarray  # int32 proposer ballot restarts
    admit_round: np.ndarray  # [I] int32 first round in an accept batch
    learned_round: np.ndarray  # [I] int32 first round an Applied
    #     quorum (majority of nodes) had learned the instance (NONE:
    #     never) — the phase ledger's learn-propagation stamp
    committed_round: np.ndarray  # [I] int32 first round the commit
    #     ladder completed: some proposer's commitment acked by every
    #     non-crashed node (NONE: never) — the commit-ladder stamp
    takeover_round: np.ndarray  # [P] int32 first takeover round (NONE)
    stall_max: np.ndarray  # int32 max stall counter ever observed
    edge_offered: np.ndarray  # [A, A] int32 per-edge offered copies
    #     (all message types; post-cut like ``offered``) — the
    #     WAN-shaped breakdown summarize() reduces to per-REGION-pair
    #     totals, so a gray/lossy link is visible without an [A, A]
    #     series crossing per round
    edge_dropped: np.ndarray  # [A, A] int32 per-edge dropped copies
    edge_cut: np.ndarray  # [A, A] int32 per-edge copies lost at a
    #     SEVERED edge (pre-cut send mask minus post-cut): offered
    #     stays post-cut for drop-rate exactness, so partitions are
    #     invisible in the drop counters — this counter is where they
    #     show, and region_cut names the severed pair


class TelemetryWindows(NamedTuple):
    """Per-round windowed accumulators (one lane; ``[lanes, W]``
    under the fleet vmap): the fields that CANNOT be recovered from
    the final state — fault-layer counters read from ephemeral copy
    plans, stall depth, and event counts — bucketed by the virtual
    round at accumulation time.  Same neutrality contract as
    :class:`Telemetry`: read-only, no PRNG, no feedback into state.
    Decision-time series (per-bucket commit counts, latency-histogram
    deltas) are derived at the epilogue by :func:`summarize_windows`
    from ``chosen_round`` — they need no per-round accumulation."""

    offered: np.ndarray  # [W] int32 edges offered (all message types)
    dropped: np.ndarray  # [W] int32 copies dropped
    duped: np.ndarray  # [W] int32 duplicate copies spawned
    delayed: np.ndarray  # [W] int32 surviving copies with delay > 0
    stall_max: np.ndarray  # [W] int32 max stall depth seen in bucket
    takeovers: np.ndarray  # [W] int32 commit-takeover adoptions
    restarts: np.ndarray  # [W] int32 proposer ballot restarts
    cut: np.ndarray  # [W] int32 copies lost at severed edges — the
    #     partition signature the post-cut drop counters cannot show
    backlog_max: np.ndarray  # [W] int32 max total queue backlog
    #     (sum over proposers of tail - head) seen in the bucket —
    #     growth across buckets is the saturation signature
    node_offered: np.ndarray  # [W, A] int32 offered copies touching
    #     each node (charged to BOTH endpoints) per bucket
    node_delay: np.ndarray  # [W, A] int32 summed sampled delays of
    #     surviving copies touching each node per bucket — divided by
    #     node_offered this is a per-node mean-delay series: a gray
    #     node's inflation is visible against its OWN earlier buckets
    #     even on a WAN preset whose baseline is already asymmetric


class WindowSummary(NamedTuple):
    """The windowed series that crosses to host (``[lanes, W, ...]``
    under the fleet vmap): the accumulated rings plus the
    decision-time series derived on device by
    :func:`summarize_windows`."""

    offered: np.ndarray  # [W] int32
    dropped: np.ndarray  # [W] int32
    duped: np.ndarray  # [W] int32
    delayed: np.ndarray  # [W] int32
    stall_max: np.ndarray  # [W] int32
    takeovers: np.ndarray  # [W] int32
    restarts: np.ndarray  # [W] int32
    cut: np.ndarray  # [W] int32
    backlog_max: np.ndarray  # [W] int32
    node_offered: np.ndarray  # [W, A] int32
    node_delay: np.ndarray  # [W, A] int32
    decided: np.ndarray  # [W] int32 decisions per bucket
    lat_hist: np.ndarray  # [W, NUM_LAT_BUCKETS] int32 latency deltas
    phase_hist: np.ndarray  # [W, NUM_PHASES, NUM_LAT_BUCKETS] int32
    #     phase-latency decomposition (PHASE_NAMES order), derived at
    #     the epilogue from the phase ledger: each decided value's
    #     phases bin in the window of its DECISION round, so the
    #     consensus row sums to lat_hist exactly on the closed loop
    #     (queue-wait is zero there — admission IS the first batch)


class TelemetrySummary(NamedTuple):
    """The reduced, fixed-shape summary that crosses to host (scalar
    fields per lane; ``[lanes, ...]`` under the fleet vmap)."""

    msgs: np.ndarray  # [7] int32 logical sends (pre-fault, = met.msgs)
    offered: np.ndarray  # [7] int32 edges offered to the fault layer
    dropped: np.ndarray  # [7] int32
    duped: np.ndarray  # [7] int32
    delayed: np.ndarray  # [7] int32
    learns: np.ndarray  # int32
    commit_acks: np.ndarray  # int32
    takeovers: np.ndarray  # int32
    requeues: np.ndarray  # int32
    restarts: np.ndarray  # int32
    decided: np.ndarray  # int32 instances decided
    lat_hist: np.ndarray  # [NUM_LAT_BUCKETS] int32 commit-latency
    lat_max: np.ndarray  # int32 max commit latency (-1: none decided)
    heal_gap: np.ndarray  # int32 quiesce round - last heal (-1: never)
    stall_max: np.ndarray  # int32 max commit-ladder stall depth
    duel_max: np.ndarray  # int32 max ballot count (duel depth)
    takeover_round: np.ndarray  # [P] int32 first takeover round (NONE)
    rounds: np.ndarray  # int32 rounds simulated
    quiescent: np.ndarray  # bool the engine's done predicate held
    region_offered: np.ndarray  # [R, R] int32 offered per region pair
    region_dropped: np.ndarray  # [R, R] int32 dropped per region pair
    region_cut: np.ndarray  # [R, R] int32 copies lost at severed
    #     edges per region pair — the partition attribution signal


def init_telemetry(
    n_instances: int, n_proposers: int, n_nodes: int
) -> Telemetry:
    """Zeroed accumulators for one lane (host numpy: the fleet runner
    feeds these through ``jnp.asarray`` like every other lane input)."""
    import jax.numpy as jnp

    return Telemetry(
        offered=jnp.zeros((7,), jnp.int32),
        dropped=jnp.zeros((7,), jnp.int32),
        duped=jnp.zeros((7,), jnp.int32),
        delayed=jnp.zeros((7,), jnp.int32),
        learns=jnp.int32(0),
        commit_acks=jnp.int32(0),
        takeovers=jnp.int32(0),
        requeues=jnp.int32(0),
        restarts=jnp.int32(0),
        admit_round=jnp.full((n_instances,), val.NONE, jnp.int32),
        learned_round=jnp.full((n_instances,), val.NONE, jnp.int32),
        committed_round=jnp.full((n_instances,), val.NONE, jnp.int32),
        takeover_round=jnp.full((n_proposers,), val.NONE, jnp.int32),
        stall_max=jnp.int32(0),
        edge_offered=jnp.zeros((n_nodes, n_nodes), jnp.int32),
        edge_dropped=jnp.zeros((n_nodes, n_nodes), jnp.int32),
        edge_cut=jnp.zeros((n_nodes, n_nodes), jnp.int32),
    )


def init_windows(n_nodes: int) -> TelemetryWindows:
    """Zeroed windowed accumulators for one lane.  One DISTINCT
    buffer per field: the serve driver donates the whole loop state,
    and donating one buffer through two tree leaves is an XLA
    error."""
    import jax.numpy as jnp

    def z():
        return jnp.zeros((NUM_WINDOWS,), jnp.int32)

    def za():
        return jnp.zeros((NUM_WINDOWS, n_nodes), jnp.int32)

    return TelemetryWindows(
        offered=z(), dropped=z(), duped=z(), delayed=z(),
        stall_max=z(), takeovers=z(), restarts=z(),
        cut=z(), backlog_max=z(),
        node_offered=za(), node_delay=za(),
    )


def window_bucket(t, window_rounds: int):
    """Bucket index of virtual round ``t``: ``t // window_rounds``,
    clamped into the overflow bucket.  A round landing exactly on a
    bucket boundary opens the NEXT bucket (round ``window_rounds``
    is the first round of bucket 1)."""
    import jax.numpy as jnp

    return jnp.minimum(
        jnp.asarray(t, jnp.int32) // jnp.int32(window_rounds),
        jnp.int32(NUM_WINDOWS - 1),
    )


def summarize_windows(
    wins: TelemetryWindows,
    admit_round,
    chosen_vid,
    chosen_round,
    window_rounds: int,
    batch_round=None,
    learned_round=None,
    committed_round=None,
) -> WindowSummary:
    """Close one lane's windowed series, on device: the accumulated
    rings pass through; per-bucket commit counts and latency-histogram
    deltas are derived here from the decision metrics (each decided
    instance lands in the bucket of its DECISION round; its latency —
    decision minus admission, ingest-stamped on the serve path — bins
    against ``LAT_EDGES`` exactly like the run-total histogram, so the
    windowed histograms sum to the cumulative one bucket-for-bucket).
    No-op fills count as decisions but never enter the latency series
    (their admission stamp is NONE), matching :func:`summarize`.

    The PHASE LEDGER stamps (``batch_round`` = the in-loop
    first-accept-batch ledger, ``learned_round``/``committed_round``
    from :class:`Telemetry`) additionally derive the ``[W, NUM_PHASES,
    B]`` phase-latency decomposition: queue-wait = batch - admission
    (real only where admission is ingest-stamped — the serve path),
    consensus = chosen - batch, commit-ladder = committed - chosen,
    learn-propagation = learned - chosen.  All four phases gate on the
    SAME population as ``lat_hist`` (decided, admission stamped), so
    the consensus row equals ``lat_hist`` bucket-for-bucket on the
    closed loop.  ``None`` ledger stamps (legacy callers) leave the
    corresponding rows empty (``batch_round=None`` treats admission as
    the batch stamp: queue-wait all-zero, consensus = the latency)."""
    import jax.numpy as jnp

    decided_mask = chosen_vid != val.NONE  # [I]
    lat_ok = decided_mask & (admit_round != val.NONE)
    lat = jnp.where(lat_ok, jnp.maximum(chosen_round - admit_round, 0), 0)
    wb = window_bucket(jnp.where(decided_mask, chosen_round, 0),
                       window_rounds)  # [I]
    decided = jnp.zeros((NUM_WINDOWS,), jnp.int32).at[wb].add(
        decided_mask.astype(jnp.int32)
    )
    edges = jnp.asarray(LAT_EDGES, jnp.int32)
    lb = jnp.sum(lat[:, None] > edges[None, :], axis=1)  # [I] in 0..B-1
    lat_hist = jnp.zeros(
        (NUM_WINDOWS, NUM_LAT_BUCKETS), jnp.int32
    ).at[wb, lb].add(lat_ok.astype(jnp.int32))
    # ---- phase-latency decomposition (the phase ledger's epilogue)
    if batch_round is None:
        batch_round = admit_round
    zero = jnp.zeros_like(lat)
    q_ok = lat_ok & (batch_round != val.NONE)
    q_dur = jnp.where(q_ok, jnp.maximum(batch_round - admit_round, 0), 0)
    c_dur = jnp.where(q_ok, jnp.maximum(chosen_round - batch_round, 0), 0)
    if committed_round is None:
        com_ok, com_dur = jnp.zeros_like(lat_ok), zero
    else:
        com_ok = lat_ok & (committed_round != val.NONE)
        com_dur = jnp.where(
            com_ok, jnp.maximum(committed_round - chosen_round, 0), 0
        )
    if learned_round is None:
        lrn_ok, lrn_dur = jnp.zeros_like(lat_ok), zero
    else:
        lrn_ok = lat_ok & (learned_round != val.NONE)
        lrn_dur = jnp.where(
            lrn_ok, jnp.maximum(learned_round - chosen_round, 0), 0
        )
    durs = jnp.stack([q_dur, c_dur, com_dur, lrn_dur], axis=1)  # [I, 4]
    oks = jnp.stack([q_ok, q_ok, com_ok, lrn_ok], axis=1)  # [I, 4]
    pb = jnp.sum(durs[:, :, None] > edges[None, None, :], axis=2)
    phase_hist = jnp.zeros(
        (NUM_WINDOWS, NUM_PHASES, NUM_LAT_BUCKETS), jnp.int32
    ).at[
        wb[:, None], jnp.arange(NUM_PHASES)[None, :], pb
    ].add(oks.astype(jnp.int32))
    return WindowSummary(
        offered=wins.offered,
        dropped=wins.dropped,
        duped=wins.duped,
        delayed=wins.delayed,
        stall_max=wins.stall_max,
        takeovers=wins.takeovers,
        restarts=wins.restarts,
        cut=wins.cut,
        backlog_max=wins.backlog_max,
        node_offered=wins.node_offered,
        node_delay=wins.node_delay,
        decided=decided,
        lat_hist=lat_hist,
        phase_hist=phase_hist,
    )


def count_copies(al, dl, mask):
    """One message type's fault-layer counters from the already-sampled
    copy plan (``net.copy_plan`` output) and the (post-cut) send mask:
    (offered, dropped, duped, delayed) int32 scalars.  Copy 0 is the
    original; copies 1..3 are the duplicate chain (never dropped)."""
    import jax.numpy as jnp

    offered = jnp.sum(mask, dtype=jnp.int32)
    dropped = jnp.sum(mask & ~al[0], dtype=jnp.int32)
    duped = jnp.sum(mask[None] & al[1:], dtype=jnp.int32)
    delayed = jnp.sum(mask[None] & al & (dl > 0), dtype=jnp.int32)
    return offered, dropped, duped, delayed


def serve_admit_rounds(ingest, chosen_vid):
    """Ingest-time admission for the open-loop serving harness
    (tpu_paxos/serve/): per-instance admission rounds gathered from
    the harness's per-vid ``ingest`` table (``[V]`` int32, the round
    each value was uploaded into the queue — stamped at INGEST time,
    where the closed-loop ledger stamps at first-accept-batch time).
    Substituted for ``Telemetry.admit_round`` before :func:`summarize`
    so the same on-device histogram reduction measures arrival-to-
    commit latency including queueing delay.  No-op hole fills
    (negative vids) and out-of-table vids reduce to NONE — excluded
    from the histogram like undecided instances.  On device, inside
    the serve window jit."""
    import jax.numpy as jnp

    v = ingest.shape[0]
    ok = (chosen_vid >= 0) & (chosen_vid < v)
    adm = ingest[jnp.clip(chosen_vid, 0, v - 1)]
    return jnp.where(ok, adm, val.NONE)


def region_window_hist(
    admit_round, chosen_vid, chosen_round, vid_region, window_rounds: int
):
    """Per-REGION windowed commit-latency histograms, on device:
    ``[NUM_REGIONS, NUM_WINDOWS, NUM_LAT_BUCKETS]`` int32 — the
    windowed series split by the region of each decided value's OWNER
    (``vid_region``: ``[V]`` int32, the region of the proposer that
    serves vid ``v``, clamped into the region bound).  Exactly the
    :func:`summarize_windows` latency bucketing (decision round picks
    the window, ingest-stamped latency picks the bucket), so summing
    over the region axis recovers the global windowed histogram
    bucket-for-bucket — the per-region series are a PARTITION of the
    global one, and a region's SLO can be judged on its own traffic
    (serve/harness.ServeSLO.regions) instead of the cluster-wide
    series.  No-op fills and out-of-table vids are excluded like
    everywhere else (their admission stamp is NONE)."""
    import jax.numpy as jnp

    decided_mask = chosen_vid != val.NONE  # [I]
    lat_ok = decided_mask & (admit_round != val.NONE)
    lat = jnp.where(lat_ok, jnp.maximum(chosen_round - admit_round, 0), 0)
    wb = window_bucket(jnp.where(decided_mask, chosen_round, 0),
                       window_rounds)  # [I]
    edges = jnp.asarray(LAT_EDGES, jnp.int32)
    lb = jnp.sum(lat[:, None] > edges[None, :], axis=1)  # [I]
    v = vid_region.shape[0]
    reg_tab = jnp.clip(
        jnp.asarray(vid_region, jnp.int32), 0, NUM_REGIONS - 1
    )  # [V]
    in_tab = (chosen_vid >= 0) & (chosen_vid < v)
    reg = jnp.where(
        in_tab, reg_tab[jnp.clip(chosen_vid, 0, v - 1)], 0
    )  # [I]
    return jnp.zeros(
        (NUM_REGIONS, NUM_WINDOWS, NUM_LAT_BUCKETS), jnp.int32
    ).at[reg, wb, lb].add((lat_ok & in_tab).astype(jnp.int32))


def region_window_hist_host(
    ingest, chosen_vid, chosen_round, vid_region, window_rounds: int
) -> np.ndarray:
    """Post-clock host twin of :func:`region_window_hist` for the
    single-stream serve harness: the same per-region windowed latency
    histograms recomputed in numpy from the harness's own ingest
    table (``[V]`` arrival round per vid) and the final decision
    arrays — zero change to the compiled serve window, because the
    arrays it needs already transfer after the clock stops.  Pinned
    equal to the on-device fleet-lane version by
    tests/test_serve_fleet.py (single-lane parity)."""
    ingest = np.asarray(ingest, np.int64)
    chosen_vid = np.asarray(chosen_vid, np.int64)
    chosen_round = np.asarray(chosen_round, np.int64)
    vid_region = np.asarray(vid_region, np.int64)
    v = len(ingest)
    in_tab = (chosen_vid >= 0) & (chosen_vid < v)
    adm = np.where(in_tab, ingest[np.clip(chosen_vid, 0, v - 1)],
                   int(val.NONE))
    lat_ok = in_tab & (adm != int(val.NONE))
    lat = np.where(lat_ok, np.maximum(chosen_round - adm, 0), 0)
    wb = np.minimum(
        np.where(lat_ok, chosen_round, 0) // int(window_rounds),
        NUM_WINDOWS - 1,
    )
    edges = np.asarray(LAT_EDGES, np.int64)
    lb = (lat[:, None] > edges[None, :]).sum(axis=1)
    reg_tab = np.clip(vid_region, 0, NUM_REGIONS - 1)
    reg = np.where(in_tab, reg_tab[np.clip(chosen_vid, 0, v - 1)], 0)
    hist = np.zeros((NUM_REGIONS, NUM_WINDOWS, NUM_LAT_BUCKETS), np.int32)
    np.add.at(hist, (reg[lat_ok], wb[lat_ok], lb[lat_ok]), 1)
    return hist


def region_reduce(edge_counts, region_map):
    """Reduce one ``[A, A]`` per-edge counter to fixed-shape
    ``[NUM_REGIONS, NUM_REGIONS]`` per-region-pair totals via the
    runtime node->region map (``[A]`` int32, clamped into the region
    bound so a malformed map can never scatter out of shape).  On
    device, inside the summary epilogue."""
    import jax.numpy as jnp

    r = jnp.clip(
        jnp.asarray(region_map, jnp.int32), 0, NUM_REGIONS - 1
    )  # [A]
    return jnp.zeros((NUM_REGIONS, NUM_REGIONS), jnp.int32).at[
        r[:, None], r[None, :]
    ].add(edge_counts)


def summarize(
    tele: Telemetry, final, horizon, region_map=None
) -> TelemetrySummary:
    """Reduce one lane's accumulators + final state to the fixed-shape
    summary, on device.  ``final`` is the engine's final ``SimState``;
    ``horizon`` is the schedule's last-heal round (int, or a traced
    scalar from a runtime ``ScheduleTable``); ``region_map`` is the
    ``[A]`` int32 node->region assignment for the per-region-pair
    fault counters (None = every node in region 0 — the same traced
    program, a constant zero map)."""
    import jax.numpy as jnp

    met = final.met
    decided_mask = met.chosen_vid != val.NONE  # [I]
    decided = jnp.sum(decided_mask, dtype=jnp.int32)
    # Commit latency per decided instance: round-of-chosen minus
    # round-of-admission (admission always precedes the decision — a
    # decision requires acks on a batch the admission pass observed).
    lat = met.chosen_round - tele.admit_round  # [I]
    lat_ok = decided_mask & (tele.admit_round != val.NONE)
    lat = jnp.where(lat_ok, jnp.maximum(lat, 0), 0)
    edges = jnp.asarray(LAT_EDGES, jnp.int32)
    bucket = jnp.sum(lat[:, None] > edges[None, :], axis=1)  # [I] in 0..B-1
    lat_hist = jnp.zeros((NUM_LAT_BUCKETS,), jnp.int32).at[bucket].add(
        lat_ok.astype(jnp.int32)
    )
    lat_max = jnp.max(jnp.where(lat_ok, lat, -1))
    heal_gap = jnp.where(
        final.done, final.t - jnp.asarray(horizon, jnp.int32), jnp.int32(-1)
    )
    if region_map is None:
        region_map = jnp.zeros(
            (tele.edge_offered.shape[0],), jnp.int32
        )
    return TelemetrySummary(
        msgs=met.msgs,
        offered=tele.offered,
        dropped=tele.dropped,
        duped=tele.duped,
        delayed=tele.delayed,
        learns=tele.learns,
        commit_acks=tele.commit_acks,
        takeovers=tele.takeovers,
        requeues=tele.requeues,
        restarts=tele.restarts,
        decided=decided,
        lat_hist=lat_hist,
        lat_max=lat_max,
        heal_gap=heal_gap,
        stall_max=tele.stall_max,
        duel_max=jnp.max(final.prop.count),
        takeover_round=tele.takeover_round,
        rounds=final.t,
        quiescent=final.done,
        region_offered=region_reduce(tele.edge_offered, region_map),
        region_dropped=region_reduce(tele.edge_dropped, region_map),
        region_cut=region_reduce(tele.edge_cut, region_map),
    )


# ---------------- host-side rendering ----------------


def region_pairs_dict(
    region_offered, region_dropped, region_cut=None, region_names=(),
) -> dict:
    """The per-region-pair offered/dropped block, TRIMMED to the used
    region prefix (the [R, R] device shape is a fixed envelope; a
    3-region run renders 3x3).  Always at least 1x1 — region 0 holds
    everything for unassigned runs.  ``region_cut`` adds the
    severed-edge loss rows (partitions are invisible in the post-cut
    drop counters); ``region_names`` adds preset region NAMES
    (``core/wan.py`` — ``us``/``eu``/``ap``) so operators read pairs
    by name, not index (short names fill in for regions past the
    given prefix)."""
    off = np.asarray(region_offered)
    drp = np.asarray(region_dropped)
    cut = None if region_cut is None else np.asarray(region_cut)
    used = np.flatnonzero(
        off.any(axis=0) | off.any(axis=1) | drp.any(axis=0) | drp.any(axis=1)
        | (cut.any(axis=0) | cut.any(axis=1) if cut is not None else False)
    )
    r = int(used.max()) + 1 if used.size else 1
    out = {
        "n_regions": r,
        "offered": off[:r, :r].tolist(),
        "dropped": drp[:r, :r].tolist(),
        "drop_rate_observed": [
            [
                round(1e4 * float(d) / float(o), 1) if int(o) else 0.0
                for d, o in zip(drow, orow)
            ]
            for drow, orow in zip(drp[:r, :r], off[:r, :r])
        ],
    }
    if cut is not None:
        out["cut"] = cut[:r, :r].tolist()
    if region_names:
        out["names"] = region_prefix_names(region_names, r)
    return out


def region_prefix_names(region_names, r: int) -> list:
    """The first ``r`` region names, padded with ``r<i>`` index names
    past the declared prefix (a 5-node run on a 3-region preset never
    pads; an undeclared region that somehow carried traffic still gets
    a stable name)."""
    names = [str(n) for n in region_names[:r]]
    names += [f"r{i}" for i in range(len(names), r)]
    return names


def region_pair_name(region_names, s: int, d: int) -> str:
    """One directed region pair as a name (``us->ap``), falling back
    to index names without a preset in scope."""
    names = region_prefix_names(region_names, max(s, d) + 1)
    return f"{names[s]}->{names[d]}"


def latency_quantile(hist: np.ndarray, q: float, lat_max: int) -> int:
    """Bucket-resolution quantile estimate: upper edge of the bucket
    the q-th decided instance falls in, clamped to the observed max
    (so p50 <= p99 <= latency_max always holds; the overflow bucket
    reports the exact observed max).  -1 when nothing was decided."""
    hist = np.asarray(hist)
    total = int(hist.sum())
    if total == 0:
        return -1
    target = q * total
    cum = 0
    for b, n in enumerate(hist.tolist()):
        cum += n
        if cum >= target and n:
            if b < len(LAT_EDGES):
                return min(int(LAT_EDGES[b]), int(lat_max))
            return int(lat_max)
    return int(lat_max)


#: Phase-quantile clamp: phase durations are not bounded by the run's
#: commit-latency max (the commit ladder and learn propagation finish
#: AFTER the decision), so their bucket-edge quantiles clamp at twice
#: the histogram grid instead of ``lat_max``.
PHASE_LAT_CAP = 2 * LAT_EDGES[-1]


def windows_to_dict(
    w: WindowSummary, window_rounds: int, lat_max: int
) -> dict:
    """One lane's windowed series as a JSON-ready dict of [W] lists
    (the time-resolved twin of :func:`summary_to_dict`).  Per-bucket
    latency quantiles are bucket-edge estimates clamped to the RUN's
    observed max (``lat_max``); empty buckets report -1."""
    hist = np.asarray(w.lat_hist)  # [W, B]
    phist = np.asarray(w.phase_hist)  # [W, NUM_PHASES, B]
    return {
        "cut": np.asarray(w.cut).tolist(),
        "backlog_max": np.asarray(w.backlog_max).tolist(),
        "node_offered": np.asarray(w.node_offered).tolist(),
        "node_delay": np.asarray(w.node_delay).tolist(),
        "phases": list(PHASE_NAMES),
        "phase_hist": phist.tolist(),  # [W][NUM_PHASES][B]
        "phase_p50": {
            name: [
                latency_quantile(phist[wi, pi], 0.50, PHASE_LAT_CAP)
                for wi in range(phist.shape[0])
            ]
            for pi, name in enumerate(PHASE_NAMES)
        },
        "window_rounds": int(window_rounds),
        "n_windows": int(hist.shape[0]),
        "decided": np.asarray(w.decided).tolist(),
        "offered": np.asarray(w.offered).tolist(),
        "dropped": np.asarray(w.dropped).tolist(),
        "duped": np.asarray(w.duped).tolist(),
        "delayed": np.asarray(w.delayed).tolist(),
        "drop_rate_observed": [
            round(1e4 * float(d) / float(o), 1) if int(o) else 0.0
            for d, o in zip(np.asarray(w.dropped), np.asarray(w.offered))
        ],
        "stall_max": np.asarray(w.stall_max).tolist(),
        "takeovers": np.asarray(w.takeovers).tolist(),
        "restarts": np.asarray(w.restarts).tolist(),
        "latency_p50": [
            latency_quantile(row, 0.50, lat_max) for row in hist
        ],
        "latency_p99": [
            latency_quantile(row, 0.99, lat_max) for row in hist
        ],
        "lat_hist": hist.tolist(),  # [W, B] — the SLO monitor's input
        "latency_edges": list(LAT_EDGES),
    }


def summary_to_dict(
    s: TelemetrySummary,
    windows: WindowSummary | None = None,
    window_rounds: int = WINDOW_ROUNDS,
    region_names: tuple = (),
) -> dict:
    """One lane's summary as a JSON-ready dict (plain ints/lists),
    with derived p50/p99 latency estimates; ``windows`` (one lane's
    :class:`WindowSummary`) adds the time-resolved ``"windows"``
    block; ``region_names`` (a WAN preset's region tuple) names the
    ``region_pairs`` block's rows.  Under the fleet vmap index the
    summary first (``jax.tree.map(lambda x: x[i], s)``)."""
    hist = np.asarray(s.lat_hist)
    lat_max = int(s.lat_max)
    offered = np.asarray(s.offered)
    dropped = np.asarray(s.dropped)
    return {
        "msgs": {n: int(v) for n, v in zip(MSG_NAMES, np.asarray(s.msgs))},
        "offered": {n: int(v) for n, v in zip(MSG_NAMES, offered)},
        "dropped": {n: int(v) for n, v in zip(MSG_NAMES, dropped)},
        "duped": {n: int(v) for n, v in zip(MSG_NAMES, np.asarray(s.duped))},
        "delayed": {
            n: int(v) for n, v in zip(MSG_NAMES, np.asarray(s.delayed))
        },
        "offered_total": int(offered.sum()),
        "dropped_total": int(dropped.sum()),
        "drop_rate_observed": (
            round(1e4 * float(dropped.sum()) / float(offered.sum()), 1)
            if int(offered.sum()) else 0.0
        ),
        "learns": int(s.learns),
        "commit_acks": int(s.commit_acks),
        "takeovers": int(s.takeovers),
        "requeues": int(s.requeues),
        "restarts": int(s.restarts),
        "decided": int(s.decided),
        "latency_hist": hist.tolist(),
        "latency_edges": list(LAT_EDGES),
        "latency_p50": latency_quantile(hist, 0.50, lat_max),
        "latency_p99": latency_quantile(hist, 0.99, lat_max),
        "latency_max": lat_max,
        "heal_gap": int(s.heal_gap),
        "stall_max": int(s.stall_max),
        "duel_max": int(s.duel_max),
        "takeover_round": np.asarray(s.takeover_round).tolist(),
        "rounds": int(s.rounds),
        "quiescent": bool(s.quiescent),
        "region_pairs": region_pairs_dict(
            s.region_offered, s.region_dropped, s.region_cut,
            region_names,
        ),
        **(
            {"windows": windows_to_dict(windows, window_rounds, lat_max)}
            if windows is not None else {}
        ),
    }


def margins_vector(s: TelemetrySummary) -> dict:
    """The near-miss margin subset (the search's fitness vector,
    ROADMAP item 2): how close the lane came to a liveness wedge."""
    return {
        "heal_gap": int(s.heal_gap),
        "stall_max": int(s.stall_max),
        "duel_max": int(s.duel_max),
        "rounds": int(s.rounds),
        "latency_max": int(s.lat_max),
    }


def reduce_lanes_windows(
    w: WindowSummary, window_rounds: int, lat_max: int
) -> dict:
    """Across-lane aggregate of a ``[lanes, W]``-leading window stack:
    per-bucket sums for the count series, per-bucket across-lane MAX
    for stall depth (the deepest any lane stalled in that bucket),
    and per-bucket latency quantiles over the lane-summed histogram
    deltas.  The stress sweep's per-mix windowed column and the
    search's windowed margin series both derive from this dict."""
    summed = WindowSummary(
        offered=np.asarray(w.offered).sum(axis=0),
        dropped=np.asarray(w.dropped).sum(axis=0),
        duped=np.asarray(w.duped).sum(axis=0),
        delayed=np.asarray(w.delayed).sum(axis=0),
        stall_max=np.asarray(w.stall_max).max(axis=0),
        takeovers=np.asarray(w.takeovers).sum(axis=0),
        restarts=np.asarray(w.restarts).sum(axis=0),
        cut=np.asarray(w.cut).sum(axis=0),
        # backlog is a depth, not a rate: the deepest any lane queued
        # in that bucket (summing would read lane count as pressure)
        backlog_max=np.asarray(w.backlog_max).max(axis=0),
        node_offered=np.asarray(w.node_offered).sum(axis=0),
        node_delay=np.asarray(w.node_delay).sum(axis=0),
        decided=np.asarray(w.decided).sum(axis=0),
        lat_hist=np.asarray(w.lat_hist).sum(axis=0),
        phase_hist=np.asarray(w.phase_hist).sum(axis=0),
    )
    return windows_to_dict(summed, window_rounds, lat_max)


def stall_margin_series(w: WindowSummary, patience: int) -> list:
    """The windowed near-miss margin series (ROADMAP item 2's
    trajectory fitness signal): per bucket, the MINIMUM over lanes of
    ``patience - stall_max`` — how many idle rounds of headroom the
    closest lane had left before its commit-ladder stall tripped the
    takeover/restart threshold in that bucket.  ``patience`` is the
    engine's stall threshold (``core/sim.IDLE_RESTART_ROUNDS``); a
    margin <= 0 means some lane actually hit it there.  Works on a
    ``[lanes, W]`` stack or a single ``[W]`` lane."""
    stall = np.asarray(w.stall_max)
    if stall.ndim > 1:
        stall = stall.max(axis=0)
    return (int(patience) - stall).astype(np.int64).tolist()


def lane_stall_margins(w: WindowSummary, patience: int) -> list:
    """Per-LANE fitness vector for the selection loop (evolve): for
    each lane of a ``[lanes, W]`` window stack, the minimum over
    buckets of ``patience - stall_max`` — the tightest liveness
    headroom that genome reached anywhere in its run.  Lower is
    fitter for wedge hunting; <= 0 means the lane actually tripped
    the stall threshold.  Unlike :func:`stall_margin_series` (which
    reduces ACROSS lanes first and so cannot credit a margin to the
    genome that produced it), this keeps the lane axis so selection
    can rank individuals.  A single ``[W]`` lane yields a length-1
    vector."""
    stall = np.asarray(w.stall_max)
    if stall.ndim == 1:
        stall = stall[None, :]
    return (int(patience) - stall.max(axis=1)).astype(np.int64).tolist()


def lane_burn_rates(
    lat_hist, latency_rounds: int, budget_milli: int
) -> list:
    """Per-LANE windowed SLO burn fitness for the serve axis of the
    selection loop: for each lane of a ``[lanes, W, B]`` windowed
    latency-histogram stack, the MAXIMUM over windows of the burn
    rate at ``latency_rounds`` — same bucket-edge and budget
    semantics as the serve judge (``harness._judge_series``): bad =
    decided past the bucket edge covering ``latency_rounds``, burn =
    bad/decided/budget, empty windows burn 0.  Higher is fitter for
    breach hunting; >= the SLO's ``burn_breach`` means that genome's
    lane breached.  A single ``[W, B]`` lane yields a length-1
    vector."""
    import bisect

    hist = np.asarray(lat_hist, np.int64)
    if hist.ndim == 2:
        hist = hist[None, :, :]
    k = bisect.bisect_right(LAT_EDGES, int(latency_rounds))
    tot = hist.sum(axis=2)
    bad = hist[:, :, k:].sum(axis=2)
    budget = max(int(budget_milli), 1) / 1000.0
    out = []
    for li in range(hist.shape[0]):
        burns = [
            round(float(b) / float(t) / budget, 3) if t else 0.0
            for b, t in zip(bad[li], tot[li])
        ]
        out.append(max(burns) if burns else 0.0)
    return out


def reduce_lanes(
    s: TelemetrySummary,
    windows: WindowSummary | None = None,
    window_rounds: int = WINDOW_ROUNDS,
    region_names: tuple = (),
) -> dict:
    """Across-lane aggregate of a ``[lanes]``-leading summary stack —
    the ONE owner of the stack-reduction semantics (never-quiesced
    ``-1`` heal gaps excluded from the min; latency quantiles over
    the summed histogram).  ``windows`` (a ``[lanes, W]`` stack) adds
    the time-resolved ``"windows"`` block.  The stress sweep's
    per-mix block and the search's per-generation margins both derive
    from this dict."""
    gaps = np.asarray(s.heal_gap)
    quiesced = gaps[gaps >= 0]
    hist = np.asarray(s.lat_hist).sum(axis=0)
    lat_max = int(np.asarray(s.lat_max).max())
    win_blk = (
        {"windows": reduce_lanes_windows(windows, window_rounds, lat_max)}
        if windows is not None else {}
    )
    return {
        **win_blk,
        "region_pairs": region_pairs_dict(
            np.asarray(s.region_offered).sum(axis=0),
            np.asarray(s.region_dropped).sum(axis=0),
            np.asarray(s.region_cut).sum(axis=0),
            region_names,
        ),
        "offered": int(np.asarray(s.offered).sum()),
        "dropped": int(np.asarray(s.dropped).sum()),
        "duped": int(np.asarray(s.duped).sum()),
        "delayed": int(np.asarray(s.delayed).sum()),
        "decided": int(np.asarray(s.decided).sum()),
        "takeovers": int(np.asarray(s.takeovers).sum()),
        "requeues": int(np.asarray(s.requeues).sum()),
        "restarts": int(np.asarray(s.restarts).sum()),
        "heal_gap_min": int(quiesced.min()) if quiesced.size else -1,
        "stall_depth_max": int(np.asarray(s.stall_max).max()),
        "duel_depth_max": int(np.asarray(s.duel_max).max()),
        "rounds_max": int(np.asarray(s.rounds).max()),
        "latency_p50": latency_quantile(hist, 0.50, lat_max),
        "latency_p99": latency_quantile(hist, 0.99, lat_max),
        "latency_max": lat_max,
    }
