"""Breach attribution: a deterministic gray-vs-saturation diagnosis
plane over the windowed flight-recorder series.

The SLO monitor (serve/harness.ServeSLO, PR 10) and the per-region
judge (PRs 13-14) NAME breach windows; nothing explained them.  This
module is the missing layer: a pure, deterministic classifier that
consumes only the already-harvested windowed series — the
``windows_to_dict`` block (latency/drop/stall/takeover series, the
PR-15 queue-backlog and per-node delay rings, and the phase-latency
decomposition), plus the run-total ``region_pairs`` block and, when a
serve path reduced them, the per-region latency series — and labels
each breach window with a ranked list of NAMED causes:

- ``saturation`` — the queue backlog grows across buckets while the
  phase decomposition is queue-wait-dominated: the engine is being
  offered more than its service rate.  Drops staying nominal is the
  confirming signal (an overloaded healthy cluster loses nothing).
- ``gray-region`` — some node's (region's, under a preset map)
  per-copy mean delay inflates past its OWN earlier-bucket baseline
  while its drop ratio stays nominal and the backlog stays flat: the
  slow-but-alive outage no liveness verdict catches.  Judged against
  the node's own baseline because WAN presets are asymmetric at rest
  — "ap is slower than us" is the topology, not an outage.
- ``partition`` — copies lost at SEVERED edges (``cut`` series: the
  pre-cut/post-cut delta the post-cut drop counters cannot show)
  with the severed region pair named from ``region_pairs["cut"]``.
- ``duel-churn`` — a takeover/restart burst with the consensus phase
  dominating the decomposition: proposers fighting over ballots, not
  a sick network.

Every signal is integer/median arithmetic on the harvested series —
no PRNG, no wall clock, no dict-order dependence — so the verdict is
byte-identical across replays of the same artifact (the determinism
contract ``python -m tpu_paxos repro`` rides; pinned by
tests/test_diagnose.py).  An ambiguous window (e.g. a gray region
*while* saturating) reports EVERY qualifying cause ranked by score —
never silently picking one — which is exactly the contract ROADMAP
item 3's admission controller needs: shed load on ``saturation``,
never on ``gray-region``.
"""

from __future__ import annotations

import hashlib
import json

import numpy as np

from tpu_paxos.telemetry import recorder as telem

#: Cause names, in canonical (tie-break) order.
CAUSES = ("duel-churn", "gray-region", "partition", "saturation")

#: Stable integer cause codes, next to the string labels: the
#: admission controller's policy table (serve/control.py) and serve
#: verdicts key on CODES, so renaming or reordering a label can never
#: silently rewire a shed/hold policy.  0 is reserved for "unknown";
#: 1..N follow :data:`CAUSES` canonical order.  The mapping is part of
#: the pinned determinism surface (tests/test_control.py) — appending
#: a new cause gets the next free code; existing codes never move.
CAUSE_IDS = {"unknown": 0, **{c: i + 1 for i, c in enumerate(CAUSES)}}

#: Code -> name, for rendering decisions back into reports.
CAUSE_NAMES = {v: k for k, v in CAUSE_IDS.items()}


def cause_code(name: str) -> int:
    """The stable integer code for a cause label (0 for any label the
    table does not know — unknown causes must never match a policy
    row by accident)."""
    return CAUSE_IDS.get(name, 0)

# ---- signal thresholds (integer/fixed-point; part of the pinned
# ---- determinism surface — change them only with the fixtures) ----

#: saturation: bucket backlog must be >= FACTOR x the baseline median
#: (and >= MIN absolutely) to count as growth.
SAT_BACKLOG_FACTOR_MILLI = 2000
SAT_BACKLOG_MIN = 4

#: "drops nominal": observed window drop rate (per 1e4) stays under
#: FACTOR x baseline + FLOOR.
DROP_NOMINAL_FACTOR_MILLI = 2000
DROP_NOMINAL_FLOOR = 100.0

#: gray: a node's per-copy mean delay (milli-rounds) must reach
#: FACTOR x its own baseline AND the absolute floor (one full round).
GRAY_DELAY_FACTOR_MILLI = 1500
GRAY_DELAY_MIN_MILLI = 1000
#: gray attribution: delays charge BOTH edge endpoints, so a gray
#: node's neighbors co-inflate by their traffic share with it (~1/2
#: at 3 nodes, less on bigger clusters); only nodes within 2/3 of
#: the LARGEST inflation delta are named as gray.
GRAY_ATTRIB_NUM, GRAY_ATTRIB_DEN = 2, 3

#: duel-churn: takeover+restart events in the bucket.
CHURN_MIN_EVENTS = 2
CHURN_FACTOR_MILLI = 2000

#: partition: any copy lost at a severed edge is a live cut.
PART_CUT_MIN = 1

#: Representative per-bucket duration for phase-dominance weighting:
#: the bucket's upper edge (overflow = twice the grid).
PHASE_REP = tuple(telem.LAT_EDGES) + (2 * telem.LAT_EDGES[-1],)


def _median(xs) -> int:
    """Deterministic integer median (upper middle) — 0 when empty."""
    xs = sorted(int(x) for x in xs)
    return xs[len(xs) // 2] if xs else 0


def _fmedian(xs) -> float:
    xs = sorted(float(x) for x in xs)
    return xs[len(xs) // 2] if xs else 0.0


def _phase_weights(d: dict, w: int) -> dict:
    """Per-phase latency mass at window ``w``: histogram counts
    weighted by the bucket's representative duration (ints)."""
    ph = d["phase_hist"][w]  # [NUM_PHASES][B]
    return {
        name: sum(
            int(n) * PHASE_REP[b] for b, n in enumerate(ph[pi])
        )
        for pi, name in enumerate(telem.PHASE_NAMES)
    }


def _dominant_phase(weights: dict) -> str | None:
    """The phase carrying the most latency mass (ties break in
    PHASE_NAMES order); None when nothing decided."""
    best, best_w = None, 0
    for name in telem.PHASE_NAMES:
        if weights[name] > best_w:
            best, best_w = name, weights[name]
    return best


def _node_delay_milli(d: dict, w: int) -> list:
    """Per-node mean delay at window ``w`` in milli-rounds per
    involved copy (0 where the node saw no traffic)."""
    nd, no = d["node_delay"][w], d["node_offered"][w]
    return [
        (1000 * int(s)) // int(o) if int(o) else 0
        for s, o in zip(nd, no)
    ]


class SeriesBaseline:
    """Per-run reference levels: medians over the ACTIVE windows not
    under diagnosis (the run's own 'normal'), so every threshold is
    relative to this run's weather, not a global constant."""

    def __init__(self, d: dict, exclude=()):
        decided = d["decided"]
        offered = d["offered"]
        n = len(decided)
        active = [
            w for w in range(n) if int(decided[w]) or int(offered[w])
        ]
        # Load-dependent baselines (backlog, churn events, latency)
        # read the healthy windows ONLY: when every active window is
        # under diagnosis (a run that breached start to finish),
        # 'normal' is idle — the empty medians are 0, and any
        # backlog/burst reads as growth.  The DROP baseline is
        # weather, not load (drops are i.i.d. fault-layer samples;
        # offered load does not move the rate), so it reads ALL
        # active windows — an over-knee burst whose whole run is one
        # breach bucket still compares its drops against the run's
        # own weather instead of an idle 0 that would fake a spike.
        # The per-node DELAY baseline is the MINIMUM over all active
        # windows with traffic, not a median: baseline delay is a
        # topology property (a WAN preset is slow at rest, load does
        # not inflate it), and the healthiest observed bucket is the
        # at-rest floor even when a gray episode covers most of the
        # run — a median would absorb the episode and hide it.
        ref = [w for w in active if w not in set(exclude)]
        self.active = active
        self.ref = ref
        self.drop = _fmedian(d["drop_rate_observed"][w] for w in active)
        self.backlog = _median(d["backlog_max"][w] for w in ref)
        self.events = _median(
            int(d["takeovers"][w]) + int(d["restarts"][w]) for w in ref
        )
        a = len(d["node_offered"][0]) if d["node_offered"] else 0
        # cut windows distort the per-node traffic MIX (a severed
        # node's surviving edges are not its normal edges), so they
        # are excluded from the at-rest delay floor
        cut_free = [w for w in active if not int(d["cut"][w])]
        self.node_delay = [
            min(
                (
                    _node_delay_milli(d, w)[ai]
                    for w in (cut_free or active)
                    if int(d["node_offered"][w][ai])
                ),
                default=0,
            )
            for ai in range(a)
        ]


def _drops_nominal(d: dict, w: int, base: SeriesBaseline) -> bool:
    return float(d["drop_rate_observed"][w]) <= (
        base.drop * DROP_NOMINAL_FACTOR_MILLI / 1000.0
        + DROP_NOMINAL_FLOOR
    )


def _gray_nodes(d: dict, w: int, base: SeriesBaseline) -> list:
    """Nodes whose per-copy mean delay at ``w`` inflated past their
    own at-rest baseline (and the absolute floor), ATTRIBUTED to the
    node(s) carrying the largest inflation delta (delays charge both
    edge endpoints, so a gray node's neighbors co-inflate by their
    traffic share with it): ``[(node, milli, baseline_milli),
    ...]``."""
    cands = []
    for ai, milli in enumerate(_node_delay_milli(d, w)):
        floor = max(
            base.node_delay[ai] * GRAY_DELAY_FACTOR_MILLI // 1000,
            GRAY_DELAY_MIN_MILLI,
        )
        if milli >= floor:
            cands.append((ai, milli, base.node_delay[ai],
                          milli - base.node_delay[ai]))
    if not cands:
        return []
    max_delta = max(c[3] for c in cands)
    return [
        (ai, milli, b) for ai, milli, b, delta in cands
        if delta * GRAY_ATTRIB_DEN >= GRAY_ATTRIB_NUM * max_delta
    ]


def _cut_pair(region_pairs: dict | None):
    """The busiest severed region pair from the run-total
    ``region_pairs["cut"]`` matrix: ``(s, d, count)`` or None."""
    if not region_pairs or "cut" not in region_pairs:
        return None
    cut = region_pairs["cut"]
    best = None
    for s, row in enumerate(cut):
        for dd, c in enumerate(row):
            if int(c) and (best is None or int(c) > best[2]):
                best = (s, dd, int(c))
    return best


def diagnose_window(
    d: dict,
    w: int,
    *,
    base: SeriesBaseline | None = None,
    region_map=None,
    region_names: tuple = (),
    region_pairs: dict | None = None,
    region_series=None,
) -> dict:
    """Label ONE window of a ``windows_to_dict`` block with its
    ranked cause candidates.  ``base`` carries the run's reference
    levels (built once per run; defaults to excluding only ``w``);
    ``region_map``/``region_names`` translate gray nodes to preset
    region names; ``region_pairs`` (the summary block) names severed
    pairs; ``region_series`` (``[R, W, B]``) adds the per-region
    latency confirmation when a serve path reduced one.

    Returns ``{"window", "span", "cause", "candidates", "ambiguous"}``
    — ``candidates`` ranked by score then canonical cause order, and
    ``cause`` is the top candidate's name (``"unknown"`` when no
    recipe fires).  Deterministic: byte-identical JSON for identical
    inputs."""
    if base is None:
        base = SeriesBaseline(d, exclude=(w,))
    wr = int(d["window_rounds"])
    weights = _phase_weights(d, w)
    dom = _dominant_phase(weights)
    drops_ok = _drops_nominal(d, w, base)
    candidates = []

    # -- saturation: backlog growth + queue-wait-dominated latency
    backlog = int(d["backlog_max"][w])
    backlog_grew = (
        backlog >= SAT_BACKLOG_MIN
        and 1000 * backlog
        >= SAT_BACKLOG_FACTOR_MILLI * max(base.backlog, 1)
    )
    if backlog_grew and dom == "queue":
        score = 4 + (1 if drops_ok else 0)
        candidates.append(("saturation", score, {
            "backlog": backlog,
            "backlog_baseline": base.backlog,
            "dominant_phase": dom,
            "drops_nominal": drops_ok,
        }))

    # -- gray-region: per-node delay inflation, drops nominal,
    # -- backlog flat.  A gray node slows — it never severs — so a
    # -- window with severed-edge losses is never gray (and the mix
    # -- shift a cut causes would fake inflation anyway).
    gray = _gray_nodes(d, w, base) if not int(d["cut"][w]) else []
    if gray and drops_ok:
        nodes = [g[0] for g in gray]
        if region_map is not None:
            regions = sorted({int(region_map[a]) for a in nodes})
        else:
            regions = []
        names = [
            telem.region_prefix_names(region_names, r + 1)[r]
            for r in regions
        ]
        score = 4 + (0 if backlog_grew else 1)
        ev = {
            "nodes": nodes,
            "delay_milli": [g[1] for g in gray],
            "delay_baseline_milli": [g[2] for g in gray],
            "drops_nominal": drops_ok,
            "backlog_flat": not backlog_grew,
        }
        if regions:
            ev["regions"] = names
        if region_series is not None and regions:
            # per-region latency confirmation: the named region's own
            # p50 at w above the other regions' — supporting, not
            # required (a gray ACCEPTOR inflates commit/learn phases
            # without moving its own region's proposals)
            rs = np.asarray(region_series)
            cap = telem.PHASE_LAT_CAP
            p50s = [
                telem.latency_quantile(rs[r, w], 0.50, cap)
                for r in range(rs.shape[0])
            ]
            others = [
                p for r, p in enumerate(p50s)
                if r not in regions and p >= 0
            ]
            inflated = any(
                p50s[r] >= 0 and others and p50s[r] >= 2 * max(others)
                for r in regions
            )
            ev["region_latency_inflated"] = bool(inflated)
            score += 1 if inflated else 0
        candidates.append(("gray-region", score, ev))

    # -- partition: copies lost at severed edges
    cut = int(d["cut"][w])
    if cut >= PART_CUT_MIN:
        ev = {"cut_copies": cut}
        pair = _cut_pair(region_pairs)
        if pair is not None:
            ev["pair"] = telem.region_pair_name(
                region_names, pair[0], pair[1]
            )
            ev["pair_cut_total"] = pair[2]
        score = 4 + (1 if int(d["stall_max"][w]) > 0 else 0)
        candidates.append(("partition", score, ev))

    # -- duel-churn: takeover/restart burst + consensus-dominated
    events = int(d["takeovers"][w]) + int(d["restarts"][w])
    if (
        events >= CHURN_MIN_EVENTS
        and 1000 * events >= CHURN_FACTOR_MILLI * max(base.events, 1)
    ):
        score = 4 + (1 if dom == "consensus" else 0)
        candidates.append(("duel-churn", score, {
            "takeovers": int(d["takeovers"][w]),
            "restarts": int(d["restarts"][w]),
            "events_baseline": base.events,
            "dominant_phase": dom,
        }))

    candidates.sort(key=lambda c: (-c[1], CAUSES.index(c[0])))
    return {
        "window": int(w),
        "span": [w * wr, (w + 1) * wr],
        "cause": candidates[0][0] if candidates else "unknown",
        "candidates": [
            {"cause": c, "score": s, "evidence": ev}
            for c, s, ev in candidates
        ],
        "ambiguous": (
            len(candidates) >= 2 and candidates[0][1] == candidates[1][1]
        ),
    }


def diagnose_breaches(
    d: dict,
    breach_windows,
    *,
    region_map=None,
    region_names: tuple = (),
    region_pairs: dict | None = None,
    region_series=None,
) -> dict:
    """Label every named breach window of one run: the diagnosis
    block the SLO verdicts carry (``serve/harness.slo_windows`` via
    ``attach_diagnosis``; fleet serve attaches it per flagged lane).
    The baseline excludes ALL breach windows — the run's healthy
    buckets define 'normal'."""
    breach_windows = [int(w) for w in breach_windows]
    base = SeriesBaseline(d, exclude=breach_windows)
    windows = [
        diagnose_window(
            d, w, base=base,
            region_map=region_map, region_names=region_names,
            region_pairs=region_pairs, region_series=region_series,
        )
        for w in breach_windows
    ]
    causes = sorted({v["cause"] for v in windows})
    # codes alongside the strings: verdict consumers (the admission
    # controller, the serve bench) key on these; strings stay for
    # human-facing reports
    return {
        "windows": windows,
        "causes": causes,
        "cause_ids": sorted(cause_code(c) for c in causes),
    }


def label_windows(
    d: dict,
    *,
    region_map=None,
    region_names: tuple = (),
    region_pairs: dict | None = None,
    region_series=None,
) -> list:
    """Top-cause label per window over the WHOLE series (``None`` for
    quiet/unremarkable buckets) — the generation-telemetry and
    Perfetto-annotation form, where no SLO names breach windows.
    Each window is judged against a baseline that excludes only
    itself, so a mid-run episode stands out against the healthy
    remainder."""
    n = len(d["decided"])
    out = []
    for w in range(n):
        if not (int(d["decided"][w]) or int(d["offered"][w])):
            out.append(None)
            continue
        v = diagnose_window(
            d, w, base=SeriesBaseline(d, exclude=(w,)),
            region_map=region_map, region_names=region_names,
            region_pairs=region_pairs, region_series=region_series,
        )
        out.append(None if v["cause"] == "unknown" else v["cause"])
    return out


def diagnose_series(
    d: dict,
    *,
    region_map=None,
    region_names: tuple = (),
    region_pairs: dict | None = None,
    region_series=None,
) -> dict:
    """Full diagnosis entries (the :func:`diagnose_window` dicts) for
    every active window whose top cause is not ``unknown`` — the
    SLO-free form (``python -m tpu_paxos trace`` renders these as
    annotation instants when no SLO named breach windows)."""
    n = len(d["decided"])
    windows = []
    for w in range(n):
        if not (int(d["decided"][w]) or int(d["offered"][w])):
            continue
        v = diagnose_window(
            d, w, base=SeriesBaseline(d, exclude=(w,)),
            region_map=region_map, region_names=region_names,
            region_pairs=region_pairs, region_series=region_series,
        )
        if v["cause"] != "unknown":
            windows.append(v)
    return {
        "windows": windows,
        "causes": sorted({v["cause"] for v in windows}),
    }


def attach_diagnosis(
    slo_verdict: dict,
    windows_dict: dict,
    *,
    region_map=None,
    region_names: tuple = (),
    region_pairs: dict | None = None,
    region_series=None,
) -> dict:
    """Thread the diagnosis into one ``slo_windows`` verdict: the
    union of the global breach windows and every region's is labeled
    and stored under ``"diagnosis"`` (returns the verdict, mutated).
    No breach windows -> no block (schema stays additive)."""
    ws = set(int(w) for w in slo_verdict.get("breach_windows", ()))
    for v in slo_verdict.get("regions", {}).values():
        ws.update(int(w) for w in v.get("breach_windows", ()))
    if not ws:
        return slo_verdict
    slo_verdict["diagnosis"] = diagnose_breaches(
        windows_dict, sorted(ws),
        region_map=region_map, region_names=region_names,
        region_pairs=region_pairs, region_series=region_series,
    )
    return slo_verdict


def fingerprint(report: dict) -> str:
    """sha256 of the canonical JSON rendering — the replay-parity pin
    (two replays of one artifact must produce byte-identical
    diagnosis)."""
    return hashlib.sha256(
        json.dumps(report, sort_keys=True).encode()
    ).hexdigest()
