"""On-device telemetry plane: the flight recorder and its exporters.

``recorder`` defines the device-side accumulators (per-round protocol
counters, the per-instance latency ledger, near-miss margins) that the
engines carry through their traced round loops when built with
``telemetry=True``; ``export`` renders host-side summaries as
Chrome-trace/Perfetto JSON timelines (``python -m tpu_paxos trace``);
``diagnose`` is the deterministic breach-attribution classifier over
the harvested windowed series (saturation / gray-region / partition /
duel-churn, ranked per breach window).

Submodules are lazily re-exported (PEP 562), mirroring ``core`` and
``fleet``: ``recorder`` is imported by ``core.sim`` only when an
engine is telemetry-armed, and importing the package must not eagerly
drag in jax or the harness stack.
"""

_SUBMODULES = ("recorder", "export", "diagnose")


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"tpu_paxos.telemetry.{name}")
    raise AttributeError(
        f"module 'tpu_paxos.telemetry' has no attribute {name!r}"
    )
