"""Chrome-trace/Perfetto export: render a run as a browsable timeline.

The recorder (telemetry/recorder.py) answers "how did this lane get
here" in numbers; this module answers it visually — a Chrome-trace
JSON (the ``chrome://tracing`` / https://ui.perfetto.dev format,
``traceEvents`` array) with:

- **fault episodes as duration events** on per-node tracks (a paused
  node shows its pause window, a partitioned node its partition
  window; burst-loss windows ride a synthetic "network" track);
- **decisions and commit takeovers as instant events** (decisions on
  a dedicated track with instance/vid/ballot args, takeovers on the
  proposer node's track at the recorder's first-takeover round);
- **counter tracks** (cumulative decided instances over rounds), plus
  the full flight-recorder summary attached as the ``telemetry``
  block of ``otherData``;
- **windowed counter tracks** when the summary carries the
  time-resolved plane (``"windows"`` block, telemetry/recorder
  ``windows_to_dict``): per-bucket latency p50/p99, observed drop
  rate, decisions per window, and stall depth rendered as counter
  series on the SAME timeline as the episode spans — so a latency
  blowout reads directly against the fault that caused it.

One simulated round maps to one trace millisecond (``ROUND_US``).

``python -m tpu_paxos trace <repro-artifact>`` renders any shrunk
wedge artifact: the telemetry is RECOMPUTED at replay (the artifact
schema is closed — no recorder fields are ever stored, pinned by
tests/test_artifact_schema.py), riding the same determinism contract
as ``repro``.  Sharded artifacts replay without the recorder (the
sharded engine is recorder-free for now) — episodes and decisions
still render; the summary block is absent.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

# NOTE: no tpu_paxos.core / jax imports at module level — the CLI
# selects its backend (and provisions a sharded artifact's virtual
# mesh) AFTER import, and backend init is irreversible.

#: Trace microseconds per simulated round (1 round = 1 ms: round
#: numbers read directly off the Perfetto grid in milliseconds).
ROUND_US = 1000

#: Default cap on per-instance decision instants (a million-instance
#: run must not emit a million events; the counter track still shows
#: the totals).  Dropped events are counted in otherData AND called
#: out by a visible annotation instant on the decision track at the
#: cap point; ``python -m tpu_paxos trace --max-decision-events N``
#: overrides per render.
MAX_DECISION_EVENTS = 1024

_NET_TRACK = "network"
_DECISION_TRACK = "decisions"
_TELEMETRY_TRACK = "telemetry"


def _ev(ph, name, pid, tid=0, ts=0, **kw):
    e = {"ph": ph, "name": name, "pid": pid, "tid": tid, "ts": ts}
    e.update(kw)
    return e


def _meta(events, pid, name):
    events.append(
        _ev("M", "process_name", pid, args={"name": name})
    )


def _episode_events(schedule, n_nodes: int, net_pid: int) -> list:
    """Fault episodes as ``X`` (complete) duration events: one per
    affected node per episode, plus burst windows on the network
    track."""
    events = []
    if schedule is None:
        return events
    for e in schedule.episodes:
        ts, dur = e.t0 * ROUND_US, (e.t1 - e.t0) * ROUND_US
        if e.kind == "partition":
            # unlisted nodes form one implicit extra group
            # (core/faults.partition) — they are equally cut off and
            # must show a bar, or the timeline reads as fault-free
            # on exactly the nodes a wedge's quorum math hinges on
            listed = {int(n) for g in e.groups for n in g}
            implicit = tuple(sorted(set(range(n_nodes)) - listed))
            groups = tuple(e.groups) + ((implicit,) if implicit else ())
            for gi, group in enumerate(groups):
                for node in group:
                    events.append(_ev(
                        "X", f"partition side {gi}", int(node), ts=ts,
                        dur=dur, args={"t0": e.t0, "t1": e.t1},
                    ))
        elif e.kind == "one_way":
            for node in e.src:
                events.append(_ev(
                    "X", f"one_way send-dark to {sorted(e.dst)}",
                    int(node), ts=ts, dur=dur,
                    args={"t0": e.t0, "t1": e.t1},
                ))
        elif e.kind == "pause":
            for node in e.nodes:
                events.append(_ev(
                    "X", "pause", int(node), ts=ts, dur=dur,
                    args={"t0": e.t0, "t1": e.t1},
                ))
        elif e.kind == "burst":
            events.append(_ev(
                "X", f"burst drop +{e.drop_rate}/1e4", net_pid,
                ts=ts, dur=dur,
                args={"t0": e.t0, "t1": e.t1, "drop_rate": e.drop_rate},
            ))
        elif e.kind == "gray":
            for node in e.nodes:
                events.append(_ev(
                    "X", f"gray +{e.delay} rounds", int(node), ts=ts,
                    dur=dur,
                    args={"t0": e.t0, "t1": e.t1, "delay": e.delay},
                ))
        elif e.kind == "crash":
            for node in e.nodes:
                events.append(_ev(
                    "i", "crash point", int(node), ts=ts, s="p",
                    args={"t0": e.t0},
                ))
    return events


def _window_counter_events(windows: dict, tele_pid: int) -> list:
    """The windowed series as Perfetto counter tracks: one ``C``
    event per (series, bucket) at the bucket's START round, so the
    curves step exactly on the window grid the recorder accumulated
    on and line up with the episode duration bars.  Empty-bucket
    latency quantiles (-1) are skipped rather than rendered (a -1
    dip would read as a latency collapse)."""
    events = []
    wr = int(windows["window_rounds"])
    n = int(windows["n_windows"])

    def counter(name, series, skip_neg=False):
        for w in range(n):
            v = series[w]
            if skip_neg and v < 0:
                continue
            events.append(_ev(
                "C", name, tele_pid, ts=w * wr * ROUND_US,
                args={name: v},
            ))

    counter("latency p50 (rounds)", windows["latency_p50"],
            skip_neg=True)
    counter("latency p99 (rounds)", windows["latency_p99"],
            skip_neg=True)
    counter("drop rate (/1e4)", windows["drop_rate_observed"])
    counter("decided / window", windows["decided"])
    counter("stall depth", windows["stall_max"])
    counter("takeovers / window", windows["takeovers"])
    return events


def _region_counter_events(
    region_pairs: dict, tele_pid: int, t_end_us: int
) -> list:
    """The per-REGION-pair fault breakdown as counter tracks: one
    ``drop rate r<s>-><d>`` counter per pair with traffic (run-total
    observed rate, rendered flat across the run so a gray/lossy WAN
    link stands out next to the time-resolved tracks).  Rendered only
    for multi-region runs — the 1x1 unassigned collapse says
    nothing the global drop-rate track doesn't."""
    events = []
    n = int(region_pairs.get("n_regions", 1))
    if n <= 1:
        return events
    rates = region_pairs["drop_rate_observed"]
    offered = region_pairs["offered"]
    for s in range(n):
        for d in range(n):
            if not offered[s][d]:
                continue
            name = f"region drop r{s}->r{d} (/1e4)"
            for ts in (0, t_end_us):
                events.append(_ev(
                    "C", name, tele_pid, ts=ts,
                    args={name: rates[s][d]},
                ))
    return events


def chrome_trace(
    cfg, result, summary_dict=None, label="tpu-paxos",
    max_decision_events: int = MAX_DECISION_EVENTS,
) -> dict:
    """Build the Chrome-trace dict for one run.

    ``result`` is a ``core/sim.SimResult``; ``summary_dict`` is the
    flight recorder's ``summary_to_dict`` output (or None for
    recorder-free replays, e.g. sharded artifacts) — when it carries
    the windowed ``"windows"`` block, the series render as counter
    tracks on a dedicated telemetry process.  ``max_decision_events``
    caps the per-instance decision instants; hitting the cap emits a
    visible "N decision instants dropped" annotation at the cap
    point instead of truncating silently."""
    from tpu_paxos.core import values as val

    a = cfg.n_nodes
    net_pid, dec_pid, tele_pid = a, a + 1, a + 2
    windows = (summary_dict or {}).get("windows")
    events = []
    for node in range(a):
        role = " (proposer)" if node in cfg.proposers else ""
        _meta(events, node, f"node {node}{role}")
    _meta(events, net_pid, _NET_TRACK)
    _meta(events, dec_pid, _DECISION_TRACK)
    if windows is not None:
        _meta(events, tele_pid, _TELEMETRY_TRACK)
        events += _window_counter_events(windows, tele_pid)
    region_pairs = (summary_dict or {}).get("region_pairs")
    if region_pairs is not None and windows is not None:
        events += _region_counter_events(
            region_pairs, tele_pid, int(result.rounds) * ROUND_US
        )
    events += _episode_events(cfg.faults.schedule, a, net_pid)

    # decisions: instants on the decision track + a cumulative counter
    chosen_vid = np.asarray(result.chosen_vid)
    chosen_round = np.asarray(result.chosen_round)
    chosen_ballot = np.asarray(result.chosen_ballot)
    decided = np.flatnonzero(chosen_vid != int(val.NONE))
    order = decided[np.argsort(chosen_round[decided], kind="stable")]
    # a negative cap would slice from the tail AND over-count the
    # dropped events; clamp — 0 legitimately means "counters only"
    cap = max(0, int(max_decision_events))
    for k, i in enumerate(order[:cap]):
        events.append(_ev(
            "i", f"decide [{int(i)}]", dec_pid,
            ts=int(chosen_round[i]) * ROUND_US, s="g",
            args={
                "instance": int(i),
                "vid": int(chosen_vid[i]),
                "ballot": int(chosen_ballot[i]),
                "round": int(chosen_round[i]),
            },
        ))
    n_dropped = max(0, int(len(decided)) - cap)
    if n_dropped:
        # the cap must be VISIBLE in the trace itself, not only in
        # otherData: an instant at the last rendered decision's round
        # says exactly how much of the tail is missing
        last_ts = int(chosen_round[order[cap - 1]]) if cap else 0
        events.append(_ev(
            "i", f"{n_dropped} decision instants dropped (cap {cap})",
            dec_pid, ts=last_ts * ROUND_US, s="g",
            args={"dropped": n_dropped, "cap": cap},
        ))
    rounds, counts = np.unique(chosen_round[decided], return_counts=True)
    cum = 0
    for r, n in zip(rounds.tolist(), counts.tolist()):
        cum += n
        events.append(_ev(
            "C", "decided", dec_pid, ts=int(r) * ROUND_US,
            args={"instances": cum},
        ))

    # commit takeovers: instants on the adopting proposer's node track
    if summary_dict is not None:
        for pi, tr in enumerate(summary_dict.get("takeover_round", [])):
            if tr is not None and int(tr) >= 0:
                events.append(_ev(
                    "i", "commit takeover", int(cfg.proposers[pi]),
                    ts=int(tr) * ROUND_US, s="p",
                    args={"proposer": pi, "round": int(tr)},
                ))

    other = {
        "label": label,
        "rounds": int(result.rounds),
        "done": bool(result.done),
        "n_nodes": a,
        "decided": int(len(decided)),
        "decision_events_dropped": n_dropped,
        "decision_events_cap": cap,
        "round_us": ROUND_US,
    }
    if summary_dict is not None:
        other["telemetry"] = summary_dict
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def trace_artifact(
    path: str, max_decision_events: int = MAX_DECISION_EVENTS
) -> dict:
    """Re-execute a repro artifact with the flight recorder armed
    (windowed plane included — the counter tracks come from it) and
    render the Chrome trace.  Telemetry is recomputed at replay —
    never read from (or written to) the artifact, whose schema stays
    closed."""
    from tpu_paxos.core import sim as simm
    from tpu_paxos.harness import shrink as shr
    from tpu_paxos.telemetry import recorder as telem

    case, art = shr.load_artifact(path)
    if case.engine == "sim":
        result, summ, wsum = simm.run_with_telemetry(
            case.cfg, case.workload, case.gates
        )
        summary_dict = telem.summary_to_dict(
            summ, wsum, telem.WINDOW_ROUNDS
        )
    else:
        # sharded replays are recorder-free (build_engine rejects
        # telemetry with axis_name); episodes + decisions still render
        result, _ = shr.run_case(case)
        summary_dict = None
    trace = chrome_trace(
        case.cfg, result, summary_dict, label=path,
        max_decision_events=max_decision_events,
    )
    trace["otherData"]["artifact"] = path
    trace["otherData"]["recorded_violation"] = art["violation"]
    trace["otherData"]["engine"] = case.engine
    return trace


def main(argv=None) -> int:
    """``python -m tpu_paxos trace <artifact>`` — render a repro
    artifact as a Chrome-trace JSON timeline (open in
    https://ui.perfetto.dev or chrome://tracing)."""
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos trace",
        description="render a stress-triage repro artifact as a "
        "Chrome-trace/Perfetto timeline (telemetry recomputed at "
        "replay; the artifact itself is never modified)",
    )
    ap.add_argument("artifact", help="path to a repro .json (written "
                    "by the stress sweep's --triage-dir)")
    ap.add_argument("--out", type=str, default="",
                    help="write the trace JSON here (default: "
                    "<artifact>.trace.json)")
    ap.add_argument("--stdout", action="store_true",
                    help="print the trace JSON to stdout instead of "
                    "writing a file")
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--max-decision-events", type=int,
                    default=MAX_DECISION_EVENTS,
                    help="cap on per-instance decision instants; a "
                    "hit cap renders a visible 'N dropped' "
                    "annotation in the trace")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON status line instead of the "
                    "verdict line")
    ap.add_argument("--log-level", type=str, default="INFO")
    args = ap.parse_args(argv)
    import os

    # same determinism surface as `repro`: replay output must not
    # capture wall clock
    os.environ.setdefault("TPU_PAXOS_DETERMINISTIC", "1")
    from tpu_paxos.__main__ import _emit, _level, _select_backend

    # Peek the artifact header BEFORE backend init (same dance as
    # run_repro): a sharded artifact records the device count its
    # decision log was produced at, and virtual CPU devices cannot be
    # added after the backend initializes.  Malformed artifacts fall
    # through to load_artifact's clean exit-2 schema error.
    devices = 1
    try:
        with open(args.artifact) as f:
            hdr = json.load(f)
        if isinstance(hdr, dict) and hdr.get("engine") == "sharded":
            devices = int(hdr.get("devices", 1))
    except (OSError, ValueError, TypeError):
        devices = 1
    if devices > 1:
        backend = "cpu" if args.backend == "auto" else args.backend
        _select_backend(backend, mesh=devices)
    else:
        _select_backend(args.backend)
    from tpu_paxos.analysis.artifact_schema import ArtifactSchemaError
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger("trace", _level(args))
    try:
        trace = trace_artifact(
            args.artifact,
            max_decision_events=args.max_decision_events,
        )
    except ArtifactSchemaError as e:
        logger.error("%s", e)
        _emit(args, {
            "engine": "trace", "ok": False,
            "schema_error": {"field": e.field, "problem": e.problem},
        })
        return 2
    text = json.dumps(trace, indent=1, sort_keys=True)
    if args.stdout:
        sys.stdout.write(text + "\n")
        return 0
    out = args.out or (args.artifact + ".trace.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        f.write(text + "\n")
    os.replace(tmp, out)
    logger.info("trace written to %s", out)
    _emit(args, {
        "engine": "trace",
        "ok": True,
        "out": out,
        "events": len(trace["traceEvents"]),
        "rounds": trace["otherData"]["rounds"],
        "decided": trace["otherData"]["decided"],
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
