"""Chrome-trace/Perfetto export: render a run as a browsable timeline.

The recorder (telemetry/recorder.py) answers "how did this lane get
here" in numbers; this module answers it visually — a Chrome-trace
JSON (the ``chrome://tracing`` / https://ui.perfetto.dev format,
``traceEvents`` array) with:

- **fault episodes as duration events** on per-node tracks (a paused
  node shows its pause window, a partitioned node its partition
  window; burst-loss windows ride a synthetic "network" track);
- **decisions and commit takeovers as instant events** (decisions on
  a dedicated track with instance/vid/ballot args, takeovers on the
  proposer node's track at the recorder's first-takeover round);
- **counter tracks** (cumulative decided instances over rounds), plus
  the full flight-recorder summary attached as the ``telemetry``
  block of ``otherData``.

One simulated round maps to one trace millisecond (``ROUND_US``).

``python -m tpu_paxos trace <repro-artifact>`` renders any shrunk
wedge artifact: the telemetry is RECOMPUTED at replay (the artifact
schema is closed — no recorder fields are ever stored, pinned by
tests/test_artifact_schema.py), riding the same determinism contract
as ``repro``.  Sharded artifacts replay without the recorder (the
sharded engine is recorder-free for now) — episodes and decisions
still render; the summary block is absent.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

# NOTE: no tpu_paxos.core / jax imports at module level — the CLI
# selects its backend (and provisions a sharded artifact's virtual
# mesh) AFTER import, and backend init is irreversible.

#: Trace microseconds per simulated round (1 round = 1 ms: round
#: numbers read directly off the Perfetto grid in milliseconds).
ROUND_US = 1000

#: Cap on per-instance decision instants (a million-instance run must
#: not emit a million events; the counter track still shows the
#: totals).  Dropped events are counted in otherData.
MAX_DECISION_EVENTS = 1024

_NET_TRACK = "network"
_DECISION_TRACK = "decisions"


def _ev(ph, name, pid, tid=0, ts=0, **kw):
    e = {"ph": ph, "name": name, "pid": pid, "tid": tid, "ts": ts}
    e.update(kw)
    return e


def _meta(events, pid, name):
    events.append(
        _ev("M", "process_name", pid, args={"name": name})
    )


def _episode_events(schedule, n_nodes: int, net_pid: int) -> list:
    """Fault episodes as ``X`` (complete) duration events: one per
    affected node per episode, plus burst windows on the network
    track."""
    events = []
    if schedule is None:
        return events
    for e in schedule.episodes:
        ts, dur = e.t0 * ROUND_US, (e.t1 - e.t0) * ROUND_US
        if e.kind == "partition":
            # unlisted nodes form one implicit extra group
            # (core/faults.partition) — they are equally cut off and
            # must show a bar, or the timeline reads as fault-free
            # on exactly the nodes a wedge's quorum math hinges on
            listed = {int(n) for g in e.groups for n in g}
            implicit = tuple(sorted(set(range(n_nodes)) - listed))
            groups = tuple(e.groups) + ((implicit,) if implicit else ())
            for gi, group in enumerate(groups):
                for node in group:
                    events.append(_ev(
                        "X", f"partition side {gi}", int(node), ts=ts,
                        dur=dur, args={"t0": e.t0, "t1": e.t1},
                    ))
        elif e.kind == "one_way":
            for node in e.src:
                events.append(_ev(
                    "X", f"one_way send-dark to {sorted(e.dst)}",
                    int(node), ts=ts, dur=dur,
                    args={"t0": e.t0, "t1": e.t1},
                ))
        elif e.kind == "pause":
            for node in e.nodes:
                events.append(_ev(
                    "X", "pause", int(node), ts=ts, dur=dur,
                    args={"t0": e.t0, "t1": e.t1},
                ))
        elif e.kind == "burst":
            events.append(_ev(
                "X", f"burst drop +{e.drop_rate}/1e4", net_pid,
                ts=ts, dur=dur,
                args={"t0": e.t0, "t1": e.t1, "drop_rate": e.drop_rate},
            ))
    return events


def chrome_trace(cfg, result, summary_dict=None, label="tpu-paxos") -> dict:
    """Build the Chrome-trace dict for one run.

    ``result`` is a ``core/sim.SimResult``; ``summary_dict`` is the
    flight recorder's ``summary_to_dict`` output (or None for
    recorder-free replays, e.g. sharded artifacts)."""
    from tpu_paxos.core import values as val

    a = cfg.n_nodes
    net_pid, dec_pid = a, a + 1
    events = []
    for node in range(a):
        role = " (proposer)" if node in cfg.proposers else ""
        _meta(events, node, f"node {node}{role}")
    _meta(events, net_pid, _NET_TRACK)
    _meta(events, dec_pid, _DECISION_TRACK)
    events += _episode_events(cfg.faults.schedule, a, net_pid)

    # decisions: instants on the decision track + a cumulative counter
    chosen_vid = np.asarray(result.chosen_vid)
    chosen_round = np.asarray(result.chosen_round)
    chosen_ballot = np.asarray(result.chosen_ballot)
    decided = np.flatnonzero(chosen_vid != int(val.NONE))
    order = decided[np.argsort(chosen_round[decided], kind="stable")]
    for k, i in enumerate(order[:MAX_DECISION_EVENTS]):
        events.append(_ev(
            "i", f"decide [{int(i)}]", dec_pid,
            ts=int(chosen_round[i]) * ROUND_US, s="g",
            args={
                "instance": int(i),
                "vid": int(chosen_vid[i]),
                "ballot": int(chosen_ballot[i]),
                "round": int(chosen_round[i]),
            },
        ))
    rounds, counts = np.unique(chosen_round[decided], return_counts=True)
    cum = 0
    for r, n in zip(rounds.tolist(), counts.tolist()):
        cum += n
        events.append(_ev(
            "C", "decided", dec_pid, ts=int(r) * ROUND_US,
            args={"instances": cum},
        ))

    # commit takeovers: instants on the adopting proposer's node track
    if summary_dict is not None:
        for pi, tr in enumerate(summary_dict.get("takeover_round", [])):
            if tr is not None and int(tr) >= 0:
                events.append(_ev(
                    "i", "commit takeover", int(cfg.proposers[pi]),
                    ts=int(tr) * ROUND_US, s="p",
                    args={"proposer": pi, "round": int(tr)},
                ))

    other = {
        "label": label,
        "rounds": int(result.rounds),
        "done": bool(result.done),
        "n_nodes": a,
        "decided": int(len(decided)),
        "decision_events_dropped": max(
            0, int(len(decided)) - MAX_DECISION_EVENTS
        ),
        "round_us": ROUND_US,
    }
    if summary_dict is not None:
        other["telemetry"] = summary_dict
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def trace_artifact(path: str) -> dict:
    """Re-execute a repro artifact with the flight recorder armed and
    render the Chrome trace.  Telemetry is recomputed at replay —
    never read from (or written to) the artifact, whose schema stays
    closed."""
    from tpu_paxos.core import sim as simm
    from tpu_paxos.harness import shrink as shr
    from tpu_paxos.telemetry import recorder as telem

    case, art = shr.load_artifact(path)
    if case.engine == "sim":
        result, summ = simm.run_with_telemetry(
            case.cfg, case.workload, case.gates
        )
        summary_dict = telem.summary_to_dict(summ)
    else:
        # sharded replays are recorder-free (build_engine rejects
        # telemetry with axis_name); episodes + decisions still render
        result, _ = shr.run_case(case)
        summary_dict = None
    trace = chrome_trace(case.cfg, result, summary_dict, label=path)
    trace["otherData"]["artifact"] = path
    trace["otherData"]["recorded_violation"] = art["violation"]
    trace["otherData"]["engine"] = case.engine
    return trace


def main(argv=None) -> int:
    """``python -m tpu_paxos trace <artifact>`` — render a repro
    artifact as a Chrome-trace JSON timeline (open in
    https://ui.perfetto.dev or chrome://tracing)."""
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos trace",
        description="render a stress-triage repro artifact as a "
        "Chrome-trace/Perfetto timeline (telemetry recomputed at "
        "replay; the artifact itself is never modified)",
    )
    ap.add_argument("artifact", help="path to a repro .json (written "
                    "by the stress sweep's --triage-dir)")
    ap.add_argument("--out", type=str, default="",
                    help="write the trace JSON here (default: "
                    "<artifact>.trace.json)")
    ap.add_argument("--stdout", action="store_true",
                    help="print the trace JSON to stdout instead of "
                    "writing a file")
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON status line instead of the "
                    "verdict line")
    ap.add_argument("--log-level", type=str, default="INFO")
    args = ap.parse_args(argv)
    import os

    # same determinism surface as `repro`: replay output must not
    # capture wall clock
    os.environ.setdefault("TPU_PAXOS_DETERMINISTIC", "1")
    from tpu_paxos.__main__ import _emit, _level, _select_backend

    # Peek the artifact header BEFORE backend init (same dance as
    # run_repro): a sharded artifact records the device count its
    # decision log was produced at, and virtual CPU devices cannot be
    # added after the backend initializes.  Malformed artifacts fall
    # through to load_artifact's clean exit-2 schema error.
    devices = 1
    try:
        with open(args.artifact) as f:
            hdr = json.load(f)
        if isinstance(hdr, dict) and hdr.get("engine") == "sharded":
            devices = int(hdr.get("devices", 1))
    except (OSError, ValueError, TypeError):
        devices = 1
    if devices > 1:
        backend = "cpu" if args.backend == "auto" else args.backend
        _select_backend(backend, mesh=devices)
    else:
        _select_backend(args.backend)
    from tpu_paxos.analysis.artifact_schema import ArtifactSchemaError
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger("trace", _level(args))
    try:
        trace = trace_artifact(args.artifact)
    except ArtifactSchemaError as e:
        logger.error("%s", e)
        _emit(args, {
            "engine": "trace", "ok": False,
            "schema_error": {"field": e.field, "problem": e.problem},
        })
        return 2
    text = json.dumps(trace, indent=1, sort_keys=True)
    if args.stdout:
        sys.stdout.write(text + "\n")
        return 0
    out = args.out or (args.artifact + ".trace.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        f.write(text + "\n")
    os.replace(tmp, out)
    logger.info("trace written to %s", out)
    _emit(args, {
        "engine": "trace",
        "ok": True,
        "out": out,
        "events": len(trace["traceEvents"]),
        "rounds": trace["otherData"]["rounds"],
        "decided": trace["otherData"]["decided"],
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
