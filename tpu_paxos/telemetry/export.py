"""Chrome-trace/Perfetto export: render a run as a browsable timeline.

The recorder (telemetry/recorder.py) answers "how did this lane get
here" in numbers; this module answers it visually — a Chrome-trace
JSON (the ``chrome://tracing`` / https://ui.perfetto.dev format,
``traceEvents`` array) with:

- **fault episodes as duration events** on per-node tracks (a paused
  node shows its pause window, a partitioned node its partition
  window; burst-loss windows ride a synthetic "network" track);
- **decisions and commit takeovers as instant events** (decisions on
  a dedicated track with instance/vid/ballot args, takeovers on the
  proposer node's track at the recorder's first-takeover round);
- **counter tracks** (cumulative decided instances over rounds), plus
  the full flight-recorder summary attached as the ``telemetry``
  block of ``otherData``;
- **windowed counter tracks** when the summary carries the
  time-resolved plane (``"windows"`` block, telemetry/recorder
  ``windows_to_dict``): per-bucket latency p50/p99, observed drop
  rate, decisions per window, and stall depth rendered as counter
  series on the SAME timeline as the episode spans — so a latency
  blowout reads directly against the fault that caused it.

One simulated round maps to one trace millisecond (``ROUND_US``).

``python -m tpu_paxos trace <repro-artifact>`` renders any shrunk
wedge artifact: the telemetry is RECOMPUTED at replay (the artifact
schema is closed — no recorder fields are ever stored, pinned by
tests/test_artifact_schema.py), riding the same determinism contract
as ``repro``.  Sharded artifacts replay without the recorder (the
sharded engine is recorder-free for now) — episodes and decisions
still render; the summary block is absent.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

# NOTE: no tpu_paxos.core / jax imports at module level — the CLI
# selects its backend (and provisions a sharded artifact's virtual
# mesh) AFTER import, and backend init is irreversible.

#: Trace microseconds per simulated round (1 round = 1 ms: round
#: numbers read directly off the Perfetto grid in milliseconds).
ROUND_US = 1000

#: Default cap on per-instance decision instants (a million-instance
#: run must not emit a million events; the counter track still shows
#: the totals).  Dropped events are counted in otherData AND called
#: out by a visible annotation instant on the decision track at the
#: cap point; ``python -m tpu_paxos trace --max-decision-events N``
#: overrides per render.
MAX_DECISION_EVENTS = 1024

#: Default cap on per-instance PHASE FLOW samples: each sampled
#: instance renders its queue/consensus/commit/learn spans on its own
#: row of the ``phases`` process, linked by a flow arrow, so one
#: value's whole life is one connected path through the timeline.
#: The first N decided instances by decision round are sampled
#: (deterministic); ``--max-flow-instances`` overrides.
MAX_FLOW_INSTANCES = 64

_NET_TRACK = "network"
_DECISION_TRACK = "decisions"
_TELEMETRY_TRACK = "telemetry"
_PHASES_TRACK = "phases"


def _ev(ph, name, pid, tid=0, ts=0, **kw):
    e = {"ph": ph, "name": name, "pid": pid, "tid": tid, "ts": ts}
    e.update(kw)
    return e


def _meta(events, pid, name):
    events.append(
        _ev("M", "process_name", pid, args={"name": name})
    )


def _episode_events(schedule, n_nodes: int, net_pid: int) -> list:
    """Fault episodes as ``X`` (complete) duration events: one per
    affected node per episode, plus burst windows on the network
    track."""
    events = []
    if schedule is None:
        return events
    for e in schedule.episodes:
        ts, dur = e.t0 * ROUND_US, (e.t1 - e.t0) * ROUND_US
        if e.kind == "partition":
            # unlisted nodes form one implicit extra group
            # (core/faults.partition) — they are equally cut off and
            # must show a bar, or the timeline reads as fault-free
            # on exactly the nodes a wedge's quorum math hinges on
            listed = {int(n) for g in e.groups for n in g}
            implicit = tuple(sorted(set(range(n_nodes)) - listed))
            groups = tuple(e.groups) + ((implicit,) if implicit else ())
            for gi, group in enumerate(groups):
                for node in group:
                    events.append(_ev(
                        "X", f"partition side {gi}", int(node), ts=ts,
                        dur=dur, args={"t0": e.t0, "t1": e.t1},
                    ))
        elif e.kind == "one_way":
            for node in e.src:
                events.append(_ev(
                    "X", f"one_way send-dark to {sorted(e.dst)}",
                    int(node), ts=ts, dur=dur,
                    args={"t0": e.t0, "t1": e.t1},
                ))
        elif e.kind == "pause":
            for node in e.nodes:
                events.append(_ev(
                    "X", "pause", int(node), ts=ts, dur=dur,
                    args={"t0": e.t0, "t1": e.t1},
                ))
        elif e.kind == "burst":
            events.append(_ev(
                "X", f"burst drop +{e.drop_rate}/1e4", net_pid,
                ts=ts, dur=dur,
                args={"t0": e.t0, "t1": e.t1, "drop_rate": e.drop_rate},
            ))
        elif e.kind == "gray":
            for node in e.nodes:
                events.append(_ev(
                    "X", f"gray +{e.delay} rounds", int(node), ts=ts,
                    dur=dur,
                    args={"t0": e.t0, "t1": e.t1, "delay": e.delay},
                ))
        elif e.kind == "crash":
            for node in e.nodes:
                events.append(_ev(
                    "i", "crash point", int(node), ts=ts, s="p",
                    args={"t0": e.t0},
                ))
    return events


def _window_counter_events(windows: dict, tele_pid: int) -> list:
    """The windowed series as Perfetto counter tracks: one ``C``
    event per (series, bucket) at the bucket's START round, so the
    curves step exactly on the window grid the recorder accumulated
    on and line up with the episode duration bars.  Empty-bucket
    latency quantiles (-1) are skipped rather than rendered (a -1
    dip would read as a latency collapse)."""
    events = []
    wr = int(windows["window_rounds"])
    n = int(windows["n_windows"])

    def counter(name, series, skip_neg=False):
        for w in range(n):
            v = series[w]
            if skip_neg and v < 0:
                continue
            events.append(_ev(
                "C", name, tele_pid, ts=w * wr * ROUND_US,
                args={name: v},
            ))

    counter("latency p50 (rounds)", windows["latency_p50"],
            skip_neg=True)
    counter("latency p99 (rounds)", windows["latency_p99"],
            skip_neg=True)
    counter("drop rate (/1e4)", windows["drop_rate_observed"])
    counter("decided / window", windows["decided"])
    counter("stall depth", windows["stall_max"])
    counter("takeovers / window", windows["takeovers"])
    # PR-15 series: the diagnosis plane's inputs as visible curves —
    # queue depth (saturation), severed-edge losses (partition), and
    # the per-phase latency decomposition (queue-dominated vs
    # consensus-dominated reads directly off the stacked curves)
    if "backlog_max" in windows:
        counter("queue backlog", windows["backlog_max"])
        counter("cut copies / window", windows["cut"])
        for name, series in windows.get("phase_p50", {}).items():
            counter(f"phase {name} p50 (rounds)", series,
                    skip_neg=True)
    return events


def _diagnosis_events(diagnosis: dict, tele_pid: int) -> list:
    """Breach-attribution annotations (telemetry/diagnose.py): one
    instant per diagnosed window at the window's start, named by its
    top cause, with the full ranked candidate list in args — an
    ambiguous window announces every qualifying cause."""
    events = []
    for v in (diagnosis or {}).get("windows", ()):
        ranked = "+".join(c["cause"] for c in v["candidates"]) or "unknown"
        events.append(_ev(
            "i", f"breach w{v['window']}: {ranked}", tele_pid,
            ts=int(v["span"][0]) * ROUND_US, s="p",
            args={
                "window": v["window"],
                "cause": v["cause"],
                "ambiguous": v["ambiguous"],
                "candidates": v["candidates"],
            },
        ))
    return events


def _phase_flow_events(
    phase_ledger: dict,
    chosen_vid,
    chosen_round,
    phases_pid: int,
    max_instances: int = MAX_FLOW_INSTANCES,
) -> tuple[list, int, int]:
    """Causal per-instance phase spans: for a bounded sample of
    decided instances (first N by decision round — deterministic),
    one row of ``X`` slices per instance (queue / consensus / commit /
    learn, where each stamp exists) linked by a flow arrow
    (``s``/``t``/``f`` with the vid as flow id), so one value's whole
    life reads as a connected path.  Returns ``(events, rendered,
    dropped)``."""
    from tpu_paxos.core import values as val

    admit = np.asarray(phase_ledger["admit_round"])
    batch = np.asarray(phase_ledger["batch_round"])
    learned = np.asarray(phase_ledger["learned_round"])
    committed = np.asarray(phase_ledger["committed_round"])
    chosen_vid = np.asarray(chosen_vid)
    chosen_round = np.asarray(chosen_round)
    none = int(val.NONE)
    decided = np.flatnonzero(
        (chosen_vid != none) & (admit != none) & (batch != none)
    )
    order = decided[np.argsort(chosen_round[decided], kind="stable")]
    cap = max(0, int(max_instances))
    events = []
    for slot, i in enumerate(order[:cap].tolist()):
        spans = [
            # queue-wait renders only where it exists (ingest-stamped
            # serve runs); the closed loop admits AT the first batch
            ("queue", int(admit[i]), int(batch[i]), True),
            ("consensus", int(batch[i]), int(chosen_round[i]), False),
            ("commit", int(chosen_round[i]), int(committed[i]), False),
            ("learn", int(chosen_round[i]), int(learned[i]), False),
        ]
        fid = int(chosen_vid[i])
        flow = []
        for name, t0, t1, skip_empty in spans:
            if t0 < 0 or t1 < 0 or t1 < t0 or (skip_empty and t1 == t0):
                continue
            ts = t0 * ROUND_US
            events.append(_ev(
                "X", f"{name} [{i}]", phases_pid, tid=slot, ts=ts,
                dur=max((t1 - t0) * ROUND_US, 1),
                args={"instance": i, "vid": fid, "t0": t0, "t1": t1,
                      "rounds": t1 - t0},
            ))
            flow.append(_ev(
                "t", f"value {fid}", phases_pid, tid=slot, ts=ts,
                id=fid, cat="phase",
            ))
        if flow:
            flow[0]["ph"] = "s"
            if len(flow) > 1:
                flow[-1]["ph"] = "f"
                flow[-1]["bp"] = "e"
            events.extend(flow)
    rendered = min(len(order), cap)
    return events, rendered, max(0, len(order) - cap)


def _region_counter_events(
    region_pairs: dict, tele_pid: int, t_end_us: int
) -> list:
    """The per-REGION-pair fault breakdown as counter tracks: one
    ``drop rate r<s>-><d>`` counter per pair with traffic (run-total
    observed rate, rendered flat across the run so a gray/lossy WAN
    link stands out next to the time-resolved tracks).  Rendered only
    for multi-region runs — the 1x1 unassigned collapse says
    nothing the global drop-rate track doesn't."""
    events = []
    n = int(region_pairs.get("n_regions", 1))
    if n <= 1:
        return events
    from tpu_paxos.telemetry import recorder as telem

    names = telem.region_prefix_names(
        region_pairs.get("names", ()), n
    )
    rates = region_pairs["drop_rate_observed"]
    offered = region_pairs["offered"]
    cut = region_pairs.get("cut")
    for s in range(n):
        for d in range(n):
            if not offered[s][d] and not (cut and cut[s][d]):
                continue
            pair = f"{names[s]}->{names[d]}"
            name = f"region drop {pair} (/1e4)"
            for ts in (0, t_end_us):
                events.append(_ev(
                    "C", name, tele_pid, ts=ts,
                    args={name: rates[s][d]},
                ))
            if cut and cut[s][d]:
                cname = f"region cut {pair} (copies)"
                for ts in (0, t_end_us):
                    events.append(_ev(
                        "C", cname, tele_pid, ts=ts,
                        args={cname: cut[s][d]},
                    ))
    return events


def chrome_trace(
    cfg, result, summary_dict=None, label="tpu-paxos",
    max_decision_events: int = MAX_DECISION_EVENTS,
    phase_ledger: dict | None = None,
    diagnosis: dict | None = None,
    max_flow_instances: int = MAX_FLOW_INSTANCES,
) -> dict:
    """Build the Chrome-trace dict for one run.

    ``result`` is a ``core/sim.SimResult``; ``summary_dict`` is the
    flight recorder's ``summary_to_dict`` output (or None for
    recorder-free replays, e.g. sharded artifacts) — when it carries
    the windowed ``"windows"`` block, the series render as counter
    tracks on a dedicated telemetry process.  ``max_decision_events``
    caps the per-instance decision instants; hitting the cap emits a
    visible "N decision instants dropped" annotation at the cap
    point instead of truncating silently.

    ``phase_ledger`` (the per-instance admit/batch/learned/committed
    stamps, ``sim.run_with_telemetry(return_ledger=True)``) adds the
    CAUSAL plane: a bounded sample of instances rendered as
    flow-linked queue/consensus/commit/learn spans on a ``phases``
    process.  ``diagnosis`` (telemetry/diagnose.py output) adds
    breach-attribution annotation instants on the telemetry track."""
    from tpu_paxos.core import values as val

    a = cfg.n_nodes
    net_pid, dec_pid, tele_pid, phase_pid = a, a + 1, a + 2, a + 3
    windows = (summary_dict or {}).get("windows")
    events = []
    for node in range(a):
        role = " (proposer)" if node in cfg.proposers else ""
        _meta(events, node, f"node {node}{role}")
    _meta(events, net_pid, _NET_TRACK)
    _meta(events, dec_pid, _DECISION_TRACK)
    if windows is not None:
        _meta(events, tele_pid, _TELEMETRY_TRACK)
        events += _window_counter_events(windows, tele_pid)
        events += _diagnosis_events(diagnosis, tele_pid)
    region_pairs = (summary_dict or {}).get("region_pairs")
    if region_pairs is not None and windows is not None:
        events += _region_counter_events(
            region_pairs, tele_pid, int(result.rounds) * ROUND_US
        )
    flows_rendered = flows_dropped = 0
    if phase_ledger is not None:
        _meta(events, phase_pid, _PHASES_TRACK)
        flow_ev, flows_rendered, flows_dropped = _phase_flow_events(
            phase_ledger, result.chosen_vid, result.chosen_round,
            phase_pid, max_flow_instances,
        )
        events += flow_ev
    events += _episode_events(cfg.faults.schedule, a, net_pid)

    # decisions: instants on the decision track + a cumulative counter
    chosen_vid = np.asarray(result.chosen_vid)
    chosen_round = np.asarray(result.chosen_round)
    chosen_ballot = np.asarray(result.chosen_ballot)
    decided = np.flatnonzero(chosen_vid != int(val.NONE))
    order = decided[np.argsort(chosen_round[decided], kind="stable")]
    # a negative cap would slice from the tail AND over-count the
    # dropped events; clamp — 0 legitimately means "counters only"
    cap = max(0, int(max_decision_events))
    for k, i in enumerate(order[:cap]):
        events.append(_ev(
            "i", f"decide [{int(i)}]", dec_pid,
            ts=int(chosen_round[i]) * ROUND_US, s="g",
            args={
                "instance": int(i),
                "vid": int(chosen_vid[i]),
                "ballot": int(chosen_ballot[i]),
                "round": int(chosen_round[i]),
            },
        ))
    n_dropped = max(0, int(len(decided)) - cap)
    if n_dropped:
        # the cap must be VISIBLE in the trace itself, not only in
        # otherData: an instant at the last rendered decision's round
        # says exactly how much of the tail is missing
        last_ts = int(chosen_round[order[cap - 1]]) if cap else 0
        events.append(_ev(
            "i", f"{n_dropped} decision instants dropped (cap {cap})",
            dec_pid, ts=last_ts * ROUND_US, s="g",
            args={"dropped": n_dropped, "cap": cap},
        ))
    rounds, counts = np.unique(chosen_round[decided], return_counts=True)
    cum = 0
    for r, n in zip(rounds.tolist(), counts.tolist()):
        cum += n
        events.append(_ev(
            "C", "decided", dec_pid, ts=int(r) * ROUND_US,
            args={"instances": cum},
        ))

    # commit takeovers: instants on the adopting proposer's node track
    if summary_dict is not None:
        for pi, tr in enumerate(summary_dict.get("takeover_round", [])):
            if tr is not None and int(tr) >= 0:
                events.append(_ev(
                    "i", "commit takeover", int(cfg.proposers[pi]),
                    ts=int(tr) * ROUND_US, s="p",
                    args={"proposer": pi, "round": int(tr)},
                ))

    other = {
        "label": label,
        "rounds": int(result.rounds),
        "done": bool(result.done),
        "n_nodes": a,
        "decided": int(len(decided)),
        "decision_events_dropped": n_dropped,
        "decision_events_cap": cap,
        "round_us": ROUND_US,
    }
    if phase_ledger is not None:
        other["flow_instances"] = flows_rendered
        other["flow_instances_dropped"] = flows_dropped
    if diagnosis is not None:
        other["diagnosis"] = diagnosis
    if summary_dict is not None:
        other["telemetry"] = summary_dict
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def trace_artifact(
    path: str, max_decision_events: int = MAX_DECISION_EVENTS,
    max_flow_instances: int = MAX_FLOW_INSTANCES,
) -> dict:
    """Re-execute a repro artifact with the flight recorder armed
    (windowed plane included — the counter tracks come from it) and
    render the Chrome trace: counter tracks, the per-instance phase
    flow spans, and the diagnosis plane's cause annotations.
    Telemetry is recomputed at replay — never read from (or written
    to) the artifact, whose schema stays closed."""
    from tpu_paxos.core import sim as simm
    from tpu_paxos.harness import shrink as shr
    from tpu_paxos.telemetry import diagnose as diag
    from tpu_paxos.telemetry import recorder as telem

    case, art = shr.load_artifact(path)
    ledger = diagnosis = None
    if case.engine == "sim":
        result, summ, wsum, ledger = simm.run_with_telemetry(
            case.cfg, case.workload, case.gates, return_ledger=True
        )
        summary_dict = telem.summary_to_dict(
            summ, wsum, telem.WINDOW_ROUNDS
        )
        diagnosis = diag.diagnose_series(
            summary_dict["windows"],
            region_pairs=summary_dict["region_pairs"],
        )
    else:
        # sharded replays are recorder-free (build_engine rejects
        # telemetry with axis_name); episodes + decisions still render
        result, _ = shr.run_case(case)
        summary_dict = None
    trace = chrome_trace(
        case.cfg, result, summary_dict, label=path,
        max_decision_events=max_decision_events,
        phase_ledger=ledger,
        diagnosis=diagnosis,
        max_flow_instances=max_flow_instances,
    )
    trace["otherData"]["artifact"] = path
    trace["otherData"]["recorded_violation"] = art["violation"]
    trace["otherData"]["engine"] = case.engine
    return trace


def _serve_ledger(tele_pair, ingest: np.ndarray, chosen_vid) -> dict:
    """The phase-ledger dict for one serve stream: admission from the
    INGEST table (the serving queue's real wait — one owner of the
    hole-fill/out-of-table rules: ``recorder.serve_admit_rounds``),
    batch/learned/committed from the in-loop recorder stamps.
    Post-clock transfers only."""
    import jax.numpy as jnp

    from tpu_paxos.telemetry import recorder as telem

    base = tele_pair[0]
    return {
        "admit_round": np.asarray(telem.serve_admit_rounds(
            jnp.asarray(ingest), jnp.asarray(chosen_vid)
        )),
        "batch_round": np.asarray(base.admit_round),
        "learned_round": np.asarray(base.learned_round),
        "committed_round": np.asarray(base.committed_round),
    }


def trace_serve(args) -> dict:
    """``python -m tpu_paxos trace --serve`` — run an open-loop serve
    (or serve-fleet) stream and render its windowed series, phase
    flow spans, and breach-attribution annotations as a Perfetto
    timeline.  The pre-PR-15 ``trace`` could only replay repro
    artifacts; serving runs — where the SLO monitor and the diagnosis
    plane actually live — had no visual form."""
    import types

    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.core import wan as wanm
    from tpu_paxos.serve import arrivals as arrv
    from tpu_paxos.serve import harness as sh
    from tpu_paxos.telemetry import diagnose as diag

    preset = wanm.PRESETS[args.preset] if args.preset else None
    if preset is not None:
        faults = wanm.wan_fault_config(preset, args.nodes)
        region_map = wanm.node_regions(preset, args.nodes)
        region_names = preset.regions
    else:
        faults = FaultConfig(
            drop_rate=args.drop_rate, dup_rate=args.dup_rate,
            max_delay=args.max_delay, crash_rate=args.crash_rate,
        )
        region_map, region_names = None, ()
    n_values = int(args.values)
    cfg = SimConfig(
        n_nodes=args.nodes,
        n_instances=max(64, 2 * n_values),
        proposers=tuple(range(args.proposers)),
        seed=args.seed,
        max_rounds=args.max_rounds,
        faults=faults,
    )
    slo = (
        sh.ServeSLO(latency_rounds=args.slo_latency,
                    budget_milli=args.slo_budget_milli)
        if args.slo_latency else None
    )
    rate = int(args.rate_milli)
    if args.lanes > 1:
        from tpu_paxos.serve import fleet as sfleet

        lanes = sfleet.fleet_lanes(
            cfg, args.lanes, n_values, rate, args.seed, args.arrivals
        )
        frep = sfleet.serve_fleet_run(
            cfg, lanes,
            rounds_per_window=args.rounds_per_window,
            windows_per_dispatch=args.windows_per_dispatch,
            slo=slo,
            region_map=region_map, region_names=region_names,
        )
        li = int(args.lane)
        if not 0 <= li < frep.n_lanes:
            raise SystemExit(
                f"--lane {li} out of range for --lanes {frep.n_lanes}"
            )
        import jax

        sd = frep.lane_summary(li)
        tele_pair = jax.tree.map(lambda x: x[li], frep.final.tele)
        ingest = np.asarray(frep.final.ingest[li])
        met = frep.final.sim.met
        chosen_vid = np.asarray(met.chosen_vid[li])
        result = types.SimpleNamespace(
            chosen_vid=chosen_vid,
            chosen_round=np.asarray(met.chosen_round[li]),
            chosen_ballot=np.asarray(met.chosen_ballot[li]),
            rounds=frep.rounds, done=frep.done,
        )
        verdict = (frep.slo or {}).get(li)
        diagnosis = (verdict or {}).get("diagnosis")
        region_series = frep.lane_region_windows(li)
        label = f"serve fleet lane {li}/{frep.n_lanes} @ {rate}/1000"
        extra = {
            "engine": "serve_fleet", "lane": li,
            "lanes": frep.n_lanes,
            "breach_lanes": [
                int(i) for i in np.flatnonzero(frep.breach)
            ],
        }
    else:
        vids = np.arange(n_values, dtype=np.int32)
        if rate <= 0:
            rounds = arrv.immediate_rounds(n_values)
        else:
            rounds = arrv.ARRIVAL_BUILDERS[args.arrivals](
                n_values, rate, args.seed
            )
        streams, arrs = arrv.split_round_robin(
            vids, rounds, args.proposers
        )
        rep = sh.serve_run(
            cfg, streams, arrs,
            rounds_per_window=args.rounds_per_window,
            windows_per_dispatch=args.windows_per_dispatch,
            slo=slo,
            region_map=region_map, region_names=region_names,
            keep_state=True,
        )
        ss = rep.final_state
        sd = rep.summary
        tele_pair = ss.tele
        ingest = np.asarray(ss.ingest)
        chosen_vid = rep.chosen_vid
        result = types.SimpleNamespace(
            chosen_vid=rep.chosen_vid,
            chosen_round=np.asarray(ss.sim.met.chosen_round),
            chosen_ballot=rep.chosen_ballot,
            rounds=rep.rounds, done=rep.done,
        )
        diagnosis = (rep.slo or {}).get("diagnosis")
        region_series = rep.region_windows
        label = f"serve @ {rate}/1000 ({args.arrivals})"
        extra = {"engine": "serve", "slo_ok": (
            rep.slo["ok"] if rep.slo is not None else None
        )}
    if diagnosis is None and sd.get("windows") is not None:
        # no SLO (or no breach): annotate notable windows anyway
        diagnosis = diag.diagnose_series(
            sd["windows"],
            region_map=region_map, region_names=tuple(region_names),
            region_pairs=sd.get("region_pairs"),
            region_series=region_series,
        )
    ledger = _serve_ledger(tele_pair, ingest, chosen_vid)
    trace = chrome_trace(
        cfg, result, sd, label=label,
        max_decision_events=args.max_decision_events,
        phase_ledger=ledger,
        diagnosis=diagnosis,
        max_flow_instances=args.max_flow_instances,
    )
    trace["otherData"].update(extra)
    trace["otherData"]["rate_milli"] = rate
    trace["otherData"]["arrivals"] = args.arrivals
    if args.preset:
        trace["otherData"]["preset"] = args.preset
    return trace


def main(argv=None) -> int:
    """``python -m tpu_paxos trace <artifact>`` — render a repro
    artifact as a Chrome-trace JSON timeline (open in
    https://ui.perfetto.dev or chrome://tracing).  ``--serve`` runs
    an open-loop serving stream instead and renders its windowed
    series, phase spans, and diagnosis annotations."""
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos trace",
        description="render a stress-triage repro artifact — or, with "
        "--serve, a fresh open-loop serving run — as a "
        "Chrome-trace/Perfetto timeline (telemetry recomputed at "
        "replay; artifacts are never modified)",
    )
    ap.add_argument("artifact", nargs="?", default="",
                    help="path to a repro .json (written by the "
                    "stress sweep's --triage-dir); omit with --serve")
    ap.add_argument("--serve", action="store_true",
                    help="serve mode: run an open-loop stream "
                    "(serve/harness.py; --lanes N for a fleet "
                    "lane) and export ITS timeline instead of "
                    "replaying an artifact")
    ap.add_argument("--values", type=int, default=128,
                    help="[serve] values in the arriving stream")
    ap.add_argument("--rate-milli", type=int, default=2000,
                    help="[serve] offered load (values/1000 rounds; "
                    "0 = everything at round 0)")
    ap.add_argument("--arrivals", type=str, default="poisson",
                    help="[serve] arrival process (serve/arrivals.py)")
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--proposers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-rounds", type=int, default=20_000)
    ap.add_argument("--rounds-per-window", type=int, default=8)
    ap.add_argument("--windows-per-dispatch", type=int, default=8)
    ap.add_argument("--slo-latency", type=int, default=0,
                    help="[serve] latency SLO in rounds (arms the "
                    "burn-rate monitor + breach attribution)")
    ap.add_argument("--slo-budget-milli", type=int, default=100)
    ap.add_argument("--preset", type=str, default="",
                    help="[serve] WAN topology preset (core/wan.py: "
                    "wan-3region / wan-5region) — arms the per-edge "
                    "fault matrices, the region map, and region-named "
                    "breach attribution")
    ap.add_argument("--lanes", type=int, default=1,
                    help="[serve] >1: run a serve FLEET of this many "
                    "tenant lanes and export --lane's timeline")
    ap.add_argument("--lane", type=int, default=0,
                    help="[serve] which fleet lane to export")
    ap.add_argument("--drop-rate", type=int, default=0)
    ap.add_argument("--dup-rate", type=int, default=0)
    ap.add_argument("--max-delay", type=int, default=0)
    ap.add_argument("--crash-rate", type=int, default=0)
    ap.add_argument("--max-flow-instances", type=int,
                    default=MAX_FLOW_INSTANCES,
                    help="cap on flow-linked per-instance phase-span "
                    "samples on the phases track")
    ap.add_argument("--out", type=str, default="",
                    help="write the trace JSON here (default: "
                    "<artifact>.trace.json)")
    ap.add_argument("--stdout", action="store_true",
                    help="print the trace JSON to stdout instead of "
                    "writing a file")
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--max-decision-events", type=int,
                    default=MAX_DECISION_EVENTS,
                    help="cap on per-instance decision instants; a "
                    "hit cap renders a visible 'N dropped' "
                    "annotation in the trace")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON status line instead of the "
                    "verdict line")
    ap.add_argument("--log-level", type=str, default="INFO")
    args = ap.parse_args(argv)
    import os

    if bool(args.serve) == bool(args.artifact):
        ap.error("exactly one of <artifact> or --serve required")
    if args.serve:
        # fail at the argparse boundary, not as an engine traceback
        for flag, v, floor in (
            ("--values", args.values, 1),
            ("--rounds-per-window", args.rounds_per_window, 1),
            ("--windows-per-dispatch", args.windows_per_dispatch, 1),
            ("--lanes", args.lanes, 1),
            ("--rate-milli", args.rate_milli, 0),
            ("--slo-latency", args.slo_latency, 0),
        ):
            if v < floor:
                ap.error(f"{flag} must be >= {floor} (got {v})")
        if not 0 <= args.lane < args.lanes:
            ap.error(
                f"--lane {args.lane} out of range for "
                f"--lanes {args.lanes}"
            )
    if args.preset:
        from tpu_paxos.core import wan as wanm

        if args.preset not in wanm.PRESETS:
            ap.error(
                f"unknown --preset {args.preset!r} "
                f"(have: {', '.join(sorted(wanm.PRESETS))})"
            )
    # same determinism surface as `repro`: replay output must not
    # capture wall clock
    os.environ.setdefault("TPU_PAXOS_DETERMINISTIC", "1")
    from tpu_paxos.__main__ import _emit, _level, _select_backend

    # Peek the artifact header BEFORE backend init (same dance as
    # run_repro): a sharded artifact records the device count its
    # decision log was produced at, and virtual CPU devices cannot be
    # added after the backend initializes.  Malformed artifacts fall
    # through to load_artifact's clean exit-2 schema error.
    devices = 1
    if not args.serve:
        try:
            with open(args.artifact) as f:
                hdr = json.load(f)
            if isinstance(hdr, dict) and hdr.get("engine") == "sharded":
                devices = int(hdr.get("devices", 1))
        except (OSError, ValueError, TypeError):
            devices = 1
    if devices > 1:
        backend = "cpu" if args.backend == "auto" else args.backend
        _select_backend(backend, mesh=devices)
    else:
        _select_backend(args.backend)
    from tpu_paxos.analysis.artifact_schema import ArtifactSchemaError
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger("trace", _level(args))
    try:
        if args.serve:
            trace = trace_serve(args)
        else:
            trace = trace_artifact(
                args.artifact,
                max_decision_events=args.max_decision_events,
                max_flow_instances=args.max_flow_instances,
            )
    except ArtifactSchemaError as e:
        logger.error("%s", e)
        _emit(args, {
            "engine": "trace", "ok": False,
            "schema_error": {"field": e.field, "problem": e.problem},
        })
        return 2
    text = json.dumps(trace, indent=1, sort_keys=True)
    if args.stdout:
        sys.stdout.write(text + "\n")
        return 0
    out = args.out or (
        (args.artifact + ".trace.json") if args.artifact
        else "serve.trace.json"
    )
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        f.write(text + "\n")
    os.replace(tmp, out)
    logger.info("trace written to %s", out)
    _emit(args, {
        "engine": "trace",
        "ok": True,
        "out": out,
        "events": len(trace["traceEvents"]),
        "rounds": trace["otherData"]["rounds"],
        "decided": trace["otherData"]["decided"],
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
