"""Crash-rejoin durability: a crashed node restores its state from a
checkpoint, rejoins, and catches up through anti-entropy — prefix
consistency holds across the whole cluster.  EXCEEDS the reference,
which persists nothing and aborts the run on any crash (SURVEY §5:
"promises don't survive a crash"; ref member/indet.h:146-150 is the
crash injector, member/paxos.cpp:1029-1073 the learner catch-up this
composes with)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_paxos import checkpoint
from tpu_paxos.harness import validate
from tpu_paxos.membership.engine import MemberSim


def _grow_to(ms, targets):
    for tgt in targets:
        cv = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(cv), max_rounds=2000), tgt


def test_crash_checkpoint_rejoin_catches_up(tmp_path):
    ms = MemberSim(n_nodes=5, n_instances=48, seed=2)
    _grow_to(ms, (1, 2))
    ms.propose(0, 100)
    assert ms.run_until(lambda: ms.chosen(100))

    # fail-stop crash of node 2, then snapshot its (frozen) durable
    # state — the restart artifact a real deployment would have on disk
    ms.crash(2)
    path = os.path.join(tmp_path, "node2.npz")
    checkpoint.save(path, ms.state, meta={"crashed_node": 2})

    # the cluster makes progress without node 2
    for v in (101, 102):
        ms.propose(0, v)
        assert ms.run_until(lambda: ms.chosen(v))
    before = len(ms.applied_log(2))

    # simulate the process death losing RAM: node 2's in-memory state
    # is garbage until the checkpoint restore reconstructs it
    st = ms.state
    ms.state = st._replace(
        learned=st.learned.at[:, 2].set(-1),
        acc_ballot=st.acc_ballot.at[:, 2].set(-1),
        acc_vid=st.acc_vid.at[:, 2].set(-1),
        applied_upto=st.applied_upto.at[2].set(0),
    )

    ms.rejoin_from_checkpoint(2, path)
    assert not bool(ms.state.crashed[2])

    # anti-entropy + the apply frontier catch node 2 up: its applied
    # log reaches the values chosen while it was down
    assert ms.run_until(
        lambda: {100, 101, 102} <= set(ms.applied_log(2).tolist()),
        max_rounds=2000,
    ), f"node 2 did not catch up (applied {ms.applied_log(2)})"
    assert len(ms.applied_log(2)) > before
    validate.check_prefix_consistency(
        [ms.applied_log(i) for i in range(5)]
    )


def test_rejoin_refuses_pre_crash_checkpoint(tmp_path):
    # three acceptors so losing one keeps a live majority (2 of 3)
    ms = MemberSim(n_nodes=3, n_instances=16, seed=0)
    _grow_to(ms, (1, 2))
    path = os.path.join(tmp_path, "early.npz")
    checkpoint.save(path, ms.state)  # node 1 not crashed here
    ms.crash(1)
    with pytest.raises(ValueError, match="predates"):
        ms.rejoin_from_checkpoint(1, path)


def test_rejoin_refuses_live_node_and_stale_epoch(tmp_path):
    """Double-rejoin on a live node, and a snapshot from an earlier
    crash epoch, are both lost-promise hazards and must be refused."""
    ms = MemberSim(n_nodes=5, n_instances=48, seed=6)
    _grow_to(ms, (1, 2))
    ms.crash(2)
    ck1 = os.path.join(tmp_path, "epoch1.npz")
    checkpoint.save(ck1, ms.state)
    ms.rejoin_from_checkpoint(2, ck1)
    # live node: a second rejoin must not roll back its state
    with pytest.raises(ValueError, match="not crashed"):
        ms.rejoin_from_checkpoint(2, ck1)
    # progress, then a second crash: the epoch-1 snapshot is stale
    ms.propose(0, 100)
    assert ms.run_until(lambda: ms.chosen(100))
    ms.crash(2)
    with pytest.raises(ValueError, match="stale epoch"):
        ms.rejoin_from_checkpoint(2, ck1)


def test_crash_guards(tmp_path):
    ms = MemberSim(n_nodes=3, n_instances=16, seed=0)
    with pytest.raises(ValueError, match="driver"):
        ms.crash(0)
    # acceptor view is {0} only: crashing 1 (a non-acceptor) is fine
    ms.crash(1)
    assert 1 in ms.crashed_set()


def test_crash_rejoin_replays_bit_identically(tmp_path):
    """The injection log captures crash + rejoin too, so a recovery
    scenario replays exactly (the checkpoint artifact is part of the
    replay inputs)."""
    ms = MemberSim(n_nodes=5, n_instances=48, seed=4)
    _grow_to(ms, (1, 2))
    ms.propose(0, 100)
    assert ms.run_until(lambda: ms.chosen(100))
    ms.crash(2)
    ck = os.path.join(tmp_path, "n2.npz")
    checkpoint.save(ck, ms.state)
    ms.propose(0, 101)
    assert ms.run_until(lambda: ms.chosen(101))
    ms.rejoin_from_checkpoint(2, ck)
    assert ms.run_until(
        lambda: {100, 101} <= set(ms.applied_log(2).tolist())
    )
    inj = os.path.join(tmp_path, "inj.json")
    ms.save_injections(inj)
    ms2 = MemberSim.replay(inj)
    assert ms2.decision_log() == ms.decision_log()


def test_replay_verifies_rejoin_checkpoint_integrity(tmp_path):
    """The injection log pins the rejoin checkpoint's sha256 +
    geometry at record time (ADVICE round 5): a rewritten file makes
    replay fail loudly with the hash named; a missing file names the
    path — never a silent divergence from the recorded run."""
    import json

    ms = MemberSim(n_nodes=5, n_instances=48, seed=4)
    _grow_to(ms, (1, 2))
    ms.propose(0, 100)
    assert ms.run_until(lambda: ms.chosen(100))
    ms.crash(2)
    ck = os.path.join(tmp_path, "n2.npz")
    checkpoint.save(ck, ms.state)
    ms.rejoin_from_checkpoint(2, ck)
    inj = os.path.join(tmp_path, "inj.json")
    ms.save_injections(inj)

    # the recorded log carries the integrity record
    ops = json.load(open(inj))["ops"]
    rejoin = [o for o in ops if o[1] == "rejoin"][0]
    assert rejoin[2][2]["sha256"] and rejoin[2][2]["n_nodes"] == 5

    # tamper: replace the checkpoint with a different (valid) snapshot
    checkpoint.save(ck, ms.state, meta={"tampered": True})
    with pytest.raises(ValueError, match="sha256"):
        MemberSim.replay(inj)

    # missing file names the path
    os.remove(ck)
    with pytest.raises(ValueError, match="missing"):
        MemberSim.replay(inj)
