"""Smoke the randomized stress sweep (full sweep is `make stress`,
2-seed quick pass is `make stress-quick`)."""

from tpu_paxos.harness import stress
import pytest


@pytest.mark.slow
def test_stress_sweep_smoke(monkeypatch):
    # two representative mixes, one seed each — the full grid runs via
    # `make stress`
    monkeypatch.setattr(
        stress, "MIXES", [stress.MIXES[1], stress.MIXES[4]]
    )
    summary = stress.sweep(n_seeds=1, verbose=False)
    assert summary["ok"], summary["failures"]
    assert summary["runs"] == 2


@pytest.mark.slow
def test_stress_fleet_sweep_smoke():
    """One episode mix, 2 seeds, through the FLEET runner: both seeds
    ride one device dispatch, the on-device verdict passes both, and
    the summary reports lanes/sec alongside the seed count.  (The
    per-lane-workload stacking it relies on is covered fast-tier by
    tests/test_fleet.py::test_per_lane_workloads_same_template.)"""
    summary = stress.sweep_fleet(
        n_seeds=2, verbose=False, mixes=stress.EPISODE_MIXES[:1]
    )
    assert summary["ok"], summary["failures"]
    assert summary["runs"] == 2
    assert summary["lanes"] == 2
    assert summary["seeds_per_mix"] == 2
    assert summary["lanes_per_sec"] > 0


@pytest.mark.slow
def test_stress_fleet_matches_host_loop(monkeypatch):
    """The --fleet route must judge exactly the runs the host loop
    judges: same (mix, seed) grid, both green — and the fleet's lanes
    ARE those runs (decision-log parity pinned in test_fleet.py).
    The mixes differ in their i.i.d. knob rates as well as their
    schedules, and both are runtime inputs now: the second mix must
    reuse the first mix's envelope executable (compiles_per_mix == 0
    — the one-executable stress-envelope ratchet)."""
    from tpu_paxos.fleet import envelope

    envelope.clear_cache()  # a cold cache so the first mix compiles
    mixes = stress.EPISODE_MIXES[:2]
    host = stress.sweep(n_seeds=2, verbose=False, mixes=mixes)
    fleet = stress.sweep_fleet(n_seeds=2, verbose=False, mixes=mixes)
    assert host["ok"] and fleet["ok"]
    assert host["runs"] == fleet["runs"] == 4
    cpm = fleet["compiles_per_mix"]
    assert list(cpm) == [m[0] for m in mixes]
    assert cpm[mixes[0][0]] > 0, cpm  # cold envelope compiled here
    assert cpm[mixes[1][0]] == 0, cpm  # ...and served this mix


@pytest.mark.slow
def test_stress_sweep_episode_mixes_smoke(monkeypatch):
    """The correlated-fault mixes (partition-flap / one-way /
    pause-heavy / pause-crash), two seeds each — the `make
    stress-quick` shape, so the episode schedules and their
    heal-then-converge contract are exercised by `pytest -m slow`."""
    monkeypatch.setattr(stress, "MIXES", list(stress.EPISODE_MIXES))
    summary = stress.sweep(n_seeds=2, verbose=False)
    assert summary["ok"], summary["failures"]
    assert summary["runs"] == 2 * len(stress.EPISODE_MIXES)
