"""Smoke the randomized stress sweep (full sweep is `make stress`)."""

from tpu_paxos.harness import stress
import pytest


@pytest.mark.slow
def test_stress_sweep_smoke(monkeypatch):
    # two representative mixes, one seed each — the full grid runs via
    # `make stress`
    monkeypatch.setattr(
        stress, "MIXES", [stress.MIXES[1], stress.MIXES[4]]
    )
    summary = stress.sweep(n_seeds=1, verbose=False)
    assert summary["ok"], summary["failures"]
    assert summary["runs"] == 2
