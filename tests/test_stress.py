"""Smoke the randomized stress sweep (full sweep is `make stress`,
2-seed quick pass is `make stress-quick`)."""

from tpu_paxos.harness import stress
import pytest


@pytest.mark.slow
def test_stress_sweep_smoke(monkeypatch):
    # two representative mixes, one seed each — the full grid runs via
    # `make stress`
    monkeypatch.setattr(
        stress, "MIXES", [stress.MIXES[1], stress.MIXES[4]]
    )
    summary = stress.sweep(n_seeds=1, verbose=False)
    assert summary["ok"], summary["failures"]
    assert summary["runs"] == 2


@pytest.mark.slow
def test_stress_sweep_episode_mixes_smoke(monkeypatch):
    """The correlated-fault mixes (partition-flap / one-way /
    pause-heavy / pause-crash), two seeds each — the `make
    stress-quick` shape, so the episode schedules and their
    heal-then-converge contract are exercised by `pytest -m slow`."""
    monkeypatch.setattr(stress, "MIXES", list(stress.EPISODE_MIXES))
    summary = stress.sweep(n_seeds=2, verbose=False)
    assert summary["ok"], summary["failures"]
    assert summary["runs"] == 2 * len(stress.EPISODE_MIXES)
