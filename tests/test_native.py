"""tpu_paxos.native — C++ fast-path equivalence vs the pure-Python
reference implementations (the native library builds on demand with
g++; these tests fail rather than skip if the toolchain is missing,
because this environment guarantees g++)."""

import numpy as np
import pytest

from tpu_paxos import native
from tpu_paxos.core import values as val
from tpu_paxos.harness import validate
from tpu_paxos.replay.decision_log import decision_log as render_log

NONE = int(val.NONE)


def test_native_builds():
    assert native.available(), "g++ build of tpu_paxos.native failed"


def _random_learned(rng, i=2000, a=5, holes=0.3):
    """Consistent learned array: one chosen value per instance,
    revealed to a random subset of nodes."""
    # distinct real values (exactly-once must hold by construction)
    chosen = rng.choice(4 * i, size=i, replace=False).astype(np.int32)
    chosen[rng.random(i) < 0.1] = NONE  # undecided instances
    know = rng.random((i, a)) > holes
    learned = np.where(know & (chosen != NONE)[:, None], chosen[:, None], NONE)
    return learned.astype(np.int32), chosen


def test_agreement_equivalence():
    rng = np.random.default_rng(0)
    learned, _ = _random_learned(rng)
    assert native.check_agreement(learned) is None
    validate.check_agreement(learned)  # python path agrees (small size)

    # inject a violation; both paths must catch the same instance
    bad = learned.copy()
    row = np.flatnonzero((bad != NONE).sum(axis=1) >= 2)[7]
    cols = np.flatnonzero(bad[row] != NONE)
    bad[row, cols[1]] = bad[row, cols[0]] + 1
    assert native.check_agreement(bad) == row
    with pytest.raises(validate.InvariantViolation, match=f"instance {row}"):
        validate.check_agreement(bad)


def test_chosen_per_instance_equivalence():
    rng = np.random.default_rng(1)
    learned, chosen = _random_learned(rng)
    nat = native.chosen_per_instance(learned)
    py = validate._chosen_per_instance(learned)
    assert np.array_equal(nat, py)
    visible = (learned != NONE).any(axis=1)
    assert np.array_equal(nat[visible], chosen[visible])


def test_check_unique_both_paths():
    chosen = np.asarray([5, NONE, 9, -7, 12], np.int32)  # -7 = noop
    assert native.check_unique(chosen) is None
    assert native.check_unique(chosen, max_vid=100) is None
    dup = np.asarray([5, 9, 5], np.int32)
    assert native.check_unique(dup) == 5
    assert native.check_unique(dup, max_vid=100) == 5
    # a stale/too-small bound must never yield a false-clean verdict:
    # out-of-range vids fall back to the unbounded sort path
    over = np.asarray([150, 150], np.int32)
    assert native.check_unique(over, max_vid=100) == 150
    assert native.check_unique(np.asarray([150, 99], np.int32), max_vid=100) is None


def test_decision_log_equivalence():
    """Native renderer output is byte-identical to the Python
    renderer's for real + no-op vids."""
    rng = np.random.default_rng(2)
    i, stride = 3000, 100_000
    cv = np.full(i, NONE, np.int32)
    cb = np.full(i, NONE, np.int32)
    decided = rng.random(i) < 0.8
    cv[decided] = (
        rng.integers(0, 4, size=decided.sum()) * stride
        + rng.integers(0, 1000, size=decided.sum())
    ).astype(np.int32)
    noop = decided & (rng.random(i) < 0.2)
    cv[noop] = val.NOOP_BASE - rng.integers(0, 4 * i, size=noop.sum()).astype(
        np.int32
    )
    cb[decided] = rng.integers(1, 1 << 20, size=decided.sum()).astype(np.int32)

    py = render_log(cv, cb, stride=stride, n_instances=i)
    nat = native.render_decision_log(cv, cb, stride=stride, n_instances=i)
    assert nat == py


def test_validate_routes_large_arrays_through_native():
    """Above the size threshold check_agreement uses the C++ path and
    still reports violations through the same exception."""
    rng = np.random.default_rng(3)
    learned, _ = _random_learned(rng, i=40_000, a=5)
    assert learned.size >= validate._NATIVE_MIN_CELLS
    validate.check_agreement(learned)
    validate.check_exactly_once(learned)
    bad = learned.copy()
    row = np.flatnonzero((bad != NONE).sum(axis=1) >= 2)[0]
    cols = np.flatnonzero(bad[row] != NONE)
    bad[row, cols[1]] += 1
    with pytest.raises(validate.InvariantViolation, match="agreement"):
        validate.check_agreement(bad)


def test_native_scale_smoke():
    """1M-instance validation + render completes via the native path
    (this is the load the numpy/Python paths choke on at 10^8)."""
    i, a = 1 << 20, 5
    chosen = np.arange(i, dtype=np.int32)
    learned = np.broadcast_to(chosen[:, None], (i, a)).copy()
    assert native.check_agreement(learned) is None
    assert native.check_unique(chosen, max_vid=i) is None
    out = native.render_decision_log(
        chosen[: 1 << 16], chosen[: 1 << 16] % 7, stride=1 << 30, n_instances=i
    )
    assert out.count("\n") == 1 << 16
