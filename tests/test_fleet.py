"""Fleet runner (tpu_paxos/fleet/): lane-for-lane decision-log parity
with the single-run engine across every episode-mix kind, on-device
verdict correctness, and the search -> shrink -> repro pipeline."""

import hashlib
import json
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as flt
from tpu_paxos.core import sim as simm
from tpu_paxos.fleet import runner as frun
from tpu_paxos.fleet import search as fsearch
from tpu_paxos.fleet import verdict as vdt
from tpu_paxos.harness import shrink as shr
from tpu_paxos.replay.decision_log import decision_log

# One schedule per episode kind (partition / one-way / pause+burst /
# none) — small horizons keep the runs short while exercising every
# runtime-mask dimension.
SCHEDS = [
    flt.FaultSchedule((flt.partition(5, 20, (0, 1), (2, 3, 4)),)),
    flt.FaultSchedule((flt.one_way(5, 25, (0,), (2, 3)),)),
    flt.FaultSchedule((flt.pause(4, 20, 1), flt.burst(8, 18, 2000))),
    None,
]

WL = [np.arange(100, 110, dtype=np.int32),
      np.arange(200, 210, dtype=np.int32)]


def _cfg(seed=0, schedule=None, crash_rate=0):
    return SimConfig(
        n_nodes=5, n_instances=64, proposers=(0, 1), seed=seed,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=300, dup_rate=500, max_delay=2,
                           crash_rate=crash_rate, schedule=schedule),
    )


def _log_sha(r, workload, n_instances):
    stride = int(max(int(np.max(w)) for w in workload)) + 1
    text = decision_log(
        r.chosen_vid, r.chosen_ballot, stride=stride,
        n_instances=n_instances,
    )
    return hashlib.sha256(text.encode()).hexdigest()


LANES = [(sched, seed) for sched in SCHEDS for seed in (0, 1)]


@pytest.fixture(scope="module")
def fleet_fixture():
    """One compiled runner + one 8-lane dispatch (all four
    episode-mix kinds x 2 seeds) shared across this module — the
    fleet compile is the expensive part, and one dispatch IS the
    subsystem's unit."""
    runner = frun.FleetRunner(_cfg(), WL)
    rep = runner.run(
        [seed for _, seed in LANES], [sched for sched, _ in LANES]
    )
    return runner, rep


@pytest.fixture
def fleet_rep(fleet_fixture):
    return fleet_fixture[1]


@pytest.mark.slow
def test_fleet_parity_all_mixes(fleet_rep):
    """THE fleet contract: >= 8 lanes spanning all four episode-mix
    kinds produce, lane for lane, the same decision-log sha256 as
    single core/sim.run executions of the same (cfg, schedule, seed)
    — one compiled executable vs four schedule-specialized ones.
    (The single-run side compiles once per schedule and reuses the
    executable across seeds, the stress sweep's pattern.)

    Slow-tier: the single-run side costs four schedule-specialized
    compiles (~60 s).  Fast-tier coverage of the runtime-vs-static
    parity contract: tests/test_knobs.py's
    test_knob_parity_zero_and_debugconf (lane-vs-single-run sha256
    incl. a partition+pause+burst schedule through the shared
    envelope) and tests/test_schedule_table.py's per-round mask
    parity over every episode kind."""
    import jax

    from tpu_paxos.utils import prng

    lanes = LANES
    rep = fleet_rep
    assert rep.n_lanes == 8
    assert rep.verdict.ok.all(), rep.verdict
    expected = np.unique(np.concatenate(WL))
    i = 0
    for sched in SCHEDS:
        cfg = _cfg(schedule=sched)
        pend, gate, tail, c = simm.prepare_queues(cfg, WL)
        round_fn = simm.build_engine(cfg, c, vid_cap=0)

        @jax.jit
        def go(root, st, _rf=round_fn, _mr=cfg.round_budget):
            return jax.lax.while_loop(
                lambda x: (~x.done) & (x.t < _mr),
                lambda x: _rf(root, x),
                st,
            )

        for seed in (0, 1):
            root = prng.root_key(seed)
            state = simm.init_state(cfg, pend, gate, tail, root)
            single_r = simm.to_result(go(root, state), expected)
            lane_r = rep.lane_result(i)
            assert lane_r.rounds == single_r.rounds, f"lane {i}"
            assert _log_sha(lane_r, WL, 64) == _log_sha(single_r, WL, 64), (
                f"lane {i} (schedule {sched}, seed {seed}) decision "
                "log diverges from the single-run engine"
            )
            i += 1
    # lane_cfg round-trips the per-lane (schedule, seed) back into a
    # single-run config — the shrink hand-off's input
    c0 = rep.lane_cfg(0)
    assert c0.seed == 0 and c0.faults.schedule == SCHEDS[0]
    assert rep.lane_cfg(7).faults.schedule is None
    assert rep.lane_cfg(7).seed == 1


def test_runner_rejects_baked_schedule_and_bad_lane_counts():
    with pytest.raises(ValueError, match="per-lane runtime tables"):
        frun.FleetRunner(_cfg(schedule=SCHEDS[0]), WL)
    runner = frun.FleetRunner(_cfg(), WL)
    with pytest.raises(ValueError, match="one schedule per lane"):
        runner.run([0, 1], [None])


def test_per_lane_workloads_same_template(fleet_fixture):
    """Per-lane (workload, gates) pairs — the stress --fleet path,
    where each seed's workload shuffles the same vid set — stack into
    the runner's compiled shapes (reusing the shared dispatch's
    executable; only the lane count retraces) and still produce
    green, template-judged lanes."""
    runner, _ = fleet_fixture
    wl_rev = [w[::-1].copy() for w in WL]  # same vids, shuffled order
    per_lane = [(WL, None), (wl_rev, None)] * 4  # keep the 8-lane shape
    rep = runner.run(
        [seed for _, seed in LANES], [sched for sched, _ in LANES],
        workloads=per_lane,
    )
    assert rep.verdict.ok.all(), rep.verdict


def test_runner_rejects_workload_outside_envelope(fleet_fixture):
    """The PR-4 expected-set/owner guard is GONE — vid sets and owner
    maps are runtime verdict tables now (tests/test_knobs.py covers
    the accepted cases) — but the envelope's STATIC facts still
    reject: vids past the bitmap bound, and more distinct vids than
    the verdict table holds."""
    runner, _ = fleet_fixture
    other = [np.arange(300, 310, dtype=np.int32),
             np.arange(400, 410, dtype=np.int32)]
    with pytest.raises(ValueError, match="vid bound"):
        runner.run([0], [None], workloads=[(other, None)])
    # same vid range but more DISTINCT vids than the template's table
    wider = [np.arange(100, 111, dtype=np.int32), WL[1]]
    with pytest.raises(ValueError, match="distinct vids"):
        runner.run([0], [None], workloads=[(wider, None)])


def test_mesh_tile_bitwise_parity(fleet_fixture):
    """The shard_map lane tile (2 of the conftest's 8 virtual CPU
    devices) must produce bitwise-identical per-lane results to the
    unmeshed vmap — lanes are independent, so the tile is pure
    placement."""
    import jax

    from tpu_paxos.parallel import mesh as pmesh

    _, rep = fleet_fixture
    mesh = pmesh.make_instance_mesh(2)
    assert mesh.size == 2
    runner_m = frun.FleetRunner(_cfg(), WL, mesh=mesh)
    rep_m = runner_m.run(
        [seed for _, seed in LANES], [sched for sched, _ in LANES]
    )
    for f in ("ok", "rounds", "max_round"):
        assert (getattr(rep_m.verdict, f) == getattr(rep.verdict, f)).all()
    for a, b in zip(jax.tree.leaves(rep_m.final), jax.tree.leaves(rep.final)):
        assert (np.asarray(a) == np.asarray(b)).all()
    # lanes that don't tile the mesh are rejected up front
    with pytest.raises(ValueError, match="tile"):
        runner_m.run([0], [None])


# ---------------- on-device verdict ----------------


def test_verdict_green_and_each_red_dimension(fleet_rep):
    """Doctor a green lane from the shared dispatch (lane 7: no
    schedule) along each verdict dimension — no extra compile."""
    import jax

    cfg = _cfg()
    final = jax.tree.map(lambda x: x[7], fleet_rep.final)
    expected, owner = vdt.expected_owners(cfg, WL)
    v = vdt.lane_verdict(cfg, final, expected, owner)
    assert bool(v.ok) and bool(v.agreement) and bool(v.coverage)
    assert bool(v.quiescent)

    # agreement: two nodes learn different values for one instance
    bad_learned = final.learned.at[0, 0].set(100).at[1, 0].set(101)
    v2 = vdt.lane_verdict(
        cfg, final._replace(learned=bad_learned), expected, owner
    )
    assert not bool(v2.agreement) and not bool(v2.ok)

    # coverage: erase one expected value from the chosen set
    gone = int(expected[0])
    cv = jnp.where(final.met.chosen_vid == gone, jnp.int32(-1),
                   final.met.chosen_vid)
    v3 = vdt.lane_verdict(
        cfg, final._replace(met=final.met._replace(chosen_vid=cv)),
        expected, owner,
    )
    assert not bool(v3.coverage) and not bool(v3.ok)

    # ...but a crashed owner excuses its values
    crashed = final.crashed.at[int(owner[0])].set(True)
    v4 = vdt.lane_verdict(
        cfg,
        final._replace(
            met=final.met._replace(chosen_vid=cv), crashed=crashed
        ),
        expected, owner,
    )
    assert bool(v4.coverage)

    # quiescence: done=False is red unless every proposer crashed
    v5 = vdt.lane_verdict(
        cfg, final._replace(done=jnp.bool_(False)), expected, owner
    )
    assert not bool(v5.quiescent) and not bool(v5.ok)
    all_crashed = final.crashed.at[0].set(True).at[1].set(True)
    v6 = vdt.lane_verdict(
        cfg,
        final._replace(done=jnp.bool_(False), crashed=all_crashed),
        expected, owner,
    )
    assert bool(v6.quiescent)


# ---------------- grammar + search ----------------


def test_sample_schedule_is_seeded_and_valid():
    rng1 = np.random.default_rng(5)
    rng2 = np.random.default_rng(5)
    s1 = [fsearch.sample_schedule(rng1, 5, 4, 96) for _ in range(16)]
    s2 = [fsearch.sample_schedule(rng2, 5, 4, 96) for _ in range(16)]
    assert s1 == s2  # same seed -> same grammar draws
    kinds = set()
    for s in s1:
        assert 1 <= len(s.episodes) <= 4
        assert s.horizon <= 96
        for e in s.episodes:
            kinds.add(e.kind)
            flt.validate_episode(e, 5)  # every draw is encodable
        stm_tabs = __import__(
            "tpu_paxos.fleet.schedule_table", fromlist=["encode_schedule"]
        ).encode_schedule(s, 5, 4)
        assert int(stm_tabs.horizon) == s.horizon
    assert kinds == set(fsearch.KINDS)  # 16 draws cover the grammar


@pytest.mark.slow
def test_search_finds_wedges():
    """A tight decision_round_max turns slow-converging sampled
    schedules into wedges the search must find and confirm through
    the single-run engine (triage disabled here — the shrink +
    artifact + repro leg is the test below and `make fleet-quick`;
    the grammar itself is covered fast-tier above)."""
    summary = fsearch.search(
        n_lanes=4, generations=1, base_seed=2,
        triage_dir=None, decision_round_max=35,
        max_episodes=2, horizon=48, max_wedges=1, verbose=False,
    )
    assert summary["wedges_found"] >= 1, summary
    assert not summary["anomalies"], summary["anomalies"]
    assert summary["ok"]  # synthetic wedges are not real violations
    w = summary["wedges"][0]
    assert w["synthetic"] and "decision_round_max" in w["violation"]
    assert summary["lanes_per_sec"] > 0


@pytest.mark.slow
def test_search_shrinks_and_artifact_reproduces(tmp_path):
    """The fleet-quick acceptance shape in miniature: find a wedge,
    shrink it, and the artifact replays byte-identically through the
    triage stack."""
    summary = fsearch.search(
        n_lanes=4, generations=1, base_seed=2,
        triage_dir=str(tmp_path), decision_round_max=35,
        max_episodes=2, horizon=48, max_wedges=1, verbose=False,
    )
    assert summary["wedges_found"] >= 1, summary
    art = summary["wedges"][0].get("artifact")
    assert art, summary["wedges"][0]
    rep = shr.reproduce(art)
    assert rep["match"], rep
    loaded = json.loads(open(art).read())
    assert "decision_round_max" in loaded["violation"]


@pytest.mark.slow
def test_fleet_cli_end_to_end(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "fleet", "--lanes", "2",
         "--generations", "1", "--quiet", "--backend", "cpu"],
        capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["metric"] == "fleet_search"
    assert summary["lanes_total"] == 2
    assert summary["lanes_per_sec"] > 0
