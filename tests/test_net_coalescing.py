"""Adversarial pin of the calendar-coalescing argument (core/net.py).

The coalescing model claims: when two in-flight copies land on the
same (edge, type) slot, the higher-ballot / newer one wins, and every
such artifact is equivalent to a legal drop of the older copy in the
reference network (ref THNetWork delivers both, but the newer ballot
governs at the acceptor either way, multi/paxos.cpp:1366).  Under the
delivery-time materialization model the calendars hold only per-edge
ballots/presence bits, so the adversarial case — a *delayed duplicate
of an older accept* colliding with a newer accept on one edge — must
resolve to the newer ballot at the calendar layer, and the stale
content cannot resurface at delivery because content is read from the
sending proposer's current state (which has moved past the old
ballot).  These tests construct the collision deliberately, in both
write orders, and then pin the whole-engine safety claim under forced
collisions.
"""

import jax.numpy as jnp
import numpy as np

from tpu_paxos.core import ballot as bal
from tpu_paxos.core import net as netm

S, P, A = 6, 1, 3


def _plan(delay: int, edge_shape):
    """A fault plan with exactly the original copy alive at ``delay``."""
    alive = np.zeros((netm.MAX_COPIES, *edge_shape), bool)
    alive[0] = True
    delays = np.full((netm.MAX_COPIES, *edge_shape), delay, np.int32)
    return jnp.asarray(alive), jnp.asarray(delays)


def _send_accept(net, t, delay, ballot):
    al, dl = _plan(delay, (P, A))
    send = jnp.ones((P,), bool)
    return net._replace(
        acc_req=netm.write_ballot(
            net.acc_req, t, al, dl, jnp.full((P, A), ballot, jnp.int32),
            send[:, None],
        )
    )


def test_delayed_old_dup_collides_with_newer_accept_old_first():
    """Old accept (ballot b1) sent at t=0 with delay 2; newer accept
    (b2 > b1) sent at t=1 with delay 1.  Both land in arrival round 3.
    The newer ballot must win the per-edge slot."""
    b1 = int(bal.make(1, 0))
    b2 = int(bal.make(2, 0))
    net = netm.init_buffers(S, P, A)
    net = _send_accept(net, jnp.int32(0), 2, b1)  # arrives r3
    net = _send_accept(net, jnp.int32(1), 1, b2)  # arrives r3
    assert int(net.acc_req[3 % S, 0, 0]) == b2


def test_delayed_old_dup_collides_with_newer_accept_new_first():
    """Same collision with write order reversed (the duplicate's
    calendar write happens after the newer message's): the stored
    newer ballot must NOT be downgraded."""
    b1 = int(bal.make(1, 0))
    b2 = int(bal.make(2, 0))
    net = netm.init_buffers(S, P, A)
    net = _send_accept(net, jnp.int32(1), 1, b2)  # arrives r3
    net = _send_accept(net, jnp.int32(0), 2, b1)  # arrives r3
    assert int(net.acc_req[3 % S, 0, 0]) == b2


def test_stale_ballot_delivery_is_dropped_by_engine():
    """Delivery-time content validity: an in-flight accept whose
    proposer has since restarted at a higher ballot materializes no
    content (has_acc requires edge ballot == the proposer's CURRENT
    ballot).  Constructed at the engine level: seed an acc_req arrival
    carrying a ballot below the proposer's current one and assert the
    acceptor stores nothing from it."""
    import numpy as _np

    from tpu_paxos.config import SimConfig
    from tpu_paxos.core import sim
    from tpu_paxos.utils import prng

    cfg = SimConfig(
        n_nodes=3, n_instances=8, proposers=(0,), seed=0, max_rounds=50
    )
    pend, gate, tail, c = sim.prepare_queues(cfg, [_np.zeros((0,), _np.int32)])
    root = prng.root_key(0)
    st = sim.init_state(cfg, pend, gate, tail, root)
    old_ballot = bal.make(1, 0)
    cur_ballot = bal.make(5, 0)
    # Proposer 0 is PREPARED at cur_ballot with a quiet in-flight batch
    # (deadlines pushed out so it sends nothing); a stale accept at
    # old_ballot is already in flight, arriving at round t=1.
    st = st._replace(
        prop=st.prop._replace(
            mode=st.prop.mode.at[0].set(sim.PREPARED),
            ballot=st.prop.ballot.at[0].set(cur_ballot),
            cur_batch=st.prop.cur_batch.at[0, 0].set(7),
            own_assign=st.prop.own_assign.at[0, 0].set(7),
            acc_deadline=st.prop.acc_deadline.at[0].set(100),
            acc_retries=st.prop.acc_retries.at[0].set(3),
        ),
        net=st.net._replace(
            acc_req=st.net.acc_req.at[
                1 % st.net.acc_req.shape[0], 0, :
            ].set(old_ballot)
        ),
    )
    round_fn = sim.build_engine(cfg, c)
    st2 = round_fn(root, st)  # t=0: nothing arrives
    st3 = round_fn(root, st2)  # t=1: the stale accept arrives
    # Nothing was stored from the stale delivery (the proposer's
    # current batch is at cur_ballot, the edge ballot is old_ballot),
    # but the stale ballot itself was observed.
    assert bool(jnp.all(st3.acc.acc_ballot == bal.NONE))
    assert bool(jnp.all(st3.acc.acc_vid == -1))
    assert int(jnp.max(st3.acc.max_seen)) >= int(old_ballot)


def test_engine_safety_under_forced_collisions():
    """Whole-engine adversarial run: heavy dup + delay makes same-slot
    collisions of old and new accepts routine; safety (agreement,
    exactly-once) must hold and the run must quiesce."""
    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.core import sim
    from tpu_paxos.harness import validate

    cfg = SimConfig(
        n_nodes=3,
        n_instances=24,
        proposers=(0, 1),
        seed=3,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=5000, min_delay=0, max_delay=4),
    )
    r = sim.run(cfg)
    assert r.done, f"did not quiesce in {r.rounds} rounds"
    validate.check_all(r.learned, r.expected_vids)
