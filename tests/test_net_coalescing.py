"""Adversarial pin of the calendar-coalescing argument (core/net.py).

The coalescing model claims: when two in-flight copies land on the
same (edge, type) slot, the higher-ballot / newer one wins, and every
such artifact is equivalent to a legal drop of the older copy in the
reference network (ref THNetWork delivers both, but the acceptor
processes the older one first or second with the same outcome — the
newer ballot governs, multi/paxos.cpp:1366).  These tests construct
the adversarial case deliberately: a *delayed duplicate of an older
accept* colliding with a newer accept on one edge, in both arrival
orders."""

import jax.numpy as jnp
import numpy as np

from tpu_paxos.core import ballot as bal
from tpu_paxos.core import net as netm
from tpu_paxos.core import values as val

S, P, A, I = 6, 1, 3, 4


def _plan(delay: int, edge_shape):
    """A fault plan with exactly the original copy alive at ``delay``."""
    alive = np.zeros((netm.MAX_COPIES, *edge_shape), bool)
    alive[0] = True
    delays = np.full((netm.MAX_COPIES, *edge_shape), delay, np.int32)
    return jnp.asarray(alive), jnp.asarray(delays)


def _send_accept(net, t, delay, ballot, batch):
    al, dl = _plan(delay, (P, A))
    send = jnp.ones((P,), bool)
    net = net._replace(
        acc_req=netm.write_ballot(
            net.acc_req, t, al, dl, jnp.full((P, A), ballot, jnp.int32),
            send[:, None],
        )
    )
    nb, nbb = netm.write_content(
        net.acc_bat, net.acc_bat_ballot, t, al, dl,
        jnp.asarray(batch, jnp.int32).reshape(P, I),
        jnp.full((P,), ballot, jnp.int32), send,
    )
    return net._replace(acc_bat=nb, acc_bat_ballot=nbb)


def test_delayed_old_dup_collides_with_newer_accept_old_first():
    """Old accept (ballot b1, batch X) sent at t=0 with delay 2; newer
    accept (b2 > b1, batch Y) sent at t=1 with delay 1.  Both land in
    arrival round 3.  The newer must win both the per-edge ballot and
    the batch content."""
    b1 = int(bal.make(1, 0))
    b2 = int(bal.make(2, 0))
    old_batch = [100, 101, val.NONE, val.NONE]
    new_batch = [200, 201, 202, val.NONE]
    net = netm.init_buffers(S, P, A, I)
    net = _send_accept(net, jnp.int32(0), 2, b1, old_batch)  # arrives r3
    net = _send_accept(net, jnp.int32(1), 1, b2, new_batch)  # arrives r3
    slot = 3 % S
    assert int(net.acc_req[slot, 0, 0]) == b2
    assert int(net.acc_bat_ballot[slot, 0]) == b2
    np.testing.assert_array_equal(np.asarray(net.acc_bat[slot, 0]), new_batch)


def test_delayed_old_dup_collides_with_newer_accept_new_first():
    """Same collision with write order reversed (the duplicate's
    calendar write happens after the newer message's): the stored
    newer content must NOT be downgraded."""
    b1 = int(bal.make(1, 0))
    b2 = int(bal.make(2, 0))
    old_batch = [100, 101, val.NONE, val.NONE]
    new_batch = [200, 201, 202, val.NONE]
    net = netm.init_buffers(S, P, A, I)
    net = _send_accept(net, jnp.int32(1), 1, b2, new_batch)  # arrives r3
    net = _send_accept(net, jnp.int32(0), 2, b1, old_batch)  # arrives r3
    slot = 3 % S
    assert int(net.acc_req[slot, 0, 0]) == b2
    assert int(net.acc_bat_ballot[slot, 0]) == b2
    np.testing.assert_array_equal(np.asarray(net.acc_bat[slot, 0]), new_batch)


def test_equal_ballot_batches_merge_union():
    """Two same-ballot accept batches covering disjoint instances (one
    proposer's successive sends) merge by union — neither clobbers the
    other's instances to NONE."""
    b = int(bal.make(3, 0))
    first = [300, val.NONE, val.NONE, val.NONE]
    second = [val.NONE, 301, val.NONE, val.NONE]
    net = netm.init_buffers(S, P, A, I)
    net = _send_accept(net, jnp.int32(0), 2, b, first)
    net = _send_accept(net, jnp.int32(1), 1, b, second)
    slot = 3 % S
    got = np.asarray(net.acc_bat[slot, 0])
    assert got[0] == 300 and got[1] == 301


def test_engine_safety_under_forced_collisions():
    """Whole-engine adversarial run: heavy dup + delay makes same-slot
    collisions of old and new accepts routine; safety (agreement,
    exactly-once) must hold and the run must quiesce."""
    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.core import sim
    from tpu_paxos.harness import validate

    cfg = SimConfig(
        n_nodes=3,
        n_instances=24,
        proposers=(0, 1),
        seed=3,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=5000, min_delay=0, max_delay=4),
    )
    r = sim.run(cfg)
    assert r.done, f"did not quiesce in {r.rounds} rounds"
    validate.check_all(r.learned, r.expected_vids)
