"""Certified selection loop (tpu_paxos/fleet/evolve.py): fitness
reducers, deterministic elitist selection, cause-targeted mutation,
the shared grammar alphabet, churn-schedule genes, and the certified
seeded-wedge recall contract.

Fast tier: every selection-loop component is covered on crafted
[lanes, W] stacks and seeded sampler draws — no engine compile.  The
slow cells (engine-backed end-to-end runs) each name their fast-tier
stand-in in their docstring.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from tpu_paxos.analysis import mc_member
from tpu_paxos.analysis import modelcheck as mc
from tpu_paxos.core import faults as fltm
from tpu_paxos.fleet import evolve as evo
from tpu_paxos.fleet import search as srch
from tpu_paxos.membership import churn_table as ctm
from tpu_paxos.membership import engine as meng
from tpu_paxos.serve import breach as sbr
from tpu_paxos.telemetry import recorder as telem


class _Windows:
    """Just enough of a WindowSummary for the stall reducers."""

    def __init__(self, stall_max):
        self.stall_max = stall_max


# ---------------- fitness reducers (pure numpy) ----------------


def test_lane_stall_margins_ordering():
    """Per-LANE margins keep the lane axis (min over windows of the
    headroom), and their minimum equals the across-lane
    ``stall_margin_series`` minimum — the two fitness views agree on
    how close the closest lane came."""
    stall = np.array([
        [3, 7, 1],   # worst window 7 -> margin 20-7 = 13
        [0, 0, 0],   # idle lane      -> margin 20
        [5, 19, 2],  # near-miss      -> margin 1
    ])
    margins = telem.lane_stall_margins(_Windows(stall), 20)
    assert margins == [13, 20, 1]
    # fitter (lower margin) lanes sort first under evolve's scores
    assert sorted(range(3), key=lambda i: margins[i]) == [2, 0, 1]
    agg = telem.stall_margin_series(_Windows(stall), 20)
    assert min(margins) == min(agg)
    # single-lane [W] input promotes to one lane
    assert telem.lane_stall_margins(_Windows(stall[2]), 20) == [1]


def test_lane_burn_rates_matches_judge_formula():
    """Per-lane burn mirrors serve/harness._judge_series: bad mass is
    everything at latency buckets STRICTLY above the SLO threshold
    (bisect_right over LAT_EDGES), burn = bad/total/budget, and the
    lane's fitness is its worst window."""
    B = len(telem.LAT_EDGES) + 1
    hist = np.zeros((2, 2, B), np.int64)
    # SLO latency 8 rounds -> buckets 0..3 are good, 4.. are bad
    hist[0, 0, 1] = 8
    hist[0, 0, 4] = 2   # burn = 2/10 / 0.2 = 1.0
    hist[0, 1, 0] = 4   # burn = 0
    hist[1, 1, 5] = 5   # burn = 5/5 / 0.2 = 5.0
    burns = telem.lane_burn_rates(hist, 8, 200)
    assert burns == [1.0, 5.0]
    # single-lane [W, B] input promotes
    assert telem.lane_burn_rates(hist[1], 8, 200) == [5.0]
    # empty windows burn nothing
    assert telem.lane_burn_rates(np.zeros((1, 2, B)), 8, 200) == [0.0]


# ---------------- selection (deterministic, elitist) ----------------


def test_select_elites_children_immigrants():
    pop = list("abcdefgh")
    scores = [5.0, 1.0, 7.0, 0.0, 9.0, 2.0, 8.0, 6.0]
    rng = np.random.default_rng(0)
    out = evo.select(
        rng, pop, scores,
        lambda r, pa, pb: ("child", pa, pb),
        make_fresh=lambda r: "fresh",
    )
    assert len(out) == 8
    # elite fraction carried verbatim, best (lowest score) first
    n_elite = max(1, int(evo.ELITE_FRAC * 8))
    assert out[:n_elite] == ["d", "b"]
    # immigrant tail
    n_fresh = int(evo.IMMIGRANT_FRAC * 8)
    assert out[-n_fresh:] == ["fresh"] * n_fresh
    # middle is children of top-half parents only
    top_half = {"d", "b", "f", "a"}
    for c in out[n_elite:-n_fresh]:
        assert c[0] == "child" and {c[1], c[2]} <= top_half
    # no make_fresh -> no immigrant slots
    rng = np.random.default_rng(0)
    out2 = evo.select(rng, pop, scores, lambda r, pa, pb: "c")
    assert "fresh" not in out2 and len(out2) == 8


def test_select_tie_break_is_lane_index():
    rng = np.random.default_rng(0)
    out = evo.select(
        rng, ["x", "y", "z", "w"], [1.0, 1.0, 1.0, 1.0],
        lambda r, pa, pb: "c",
    )
    assert out[0] == "x"  # ties break on index, not dict/hash order


def _seeded_population(seed, n=6, n_nodes=5):
    alphabet = srch.Alphabet.classic()
    rng = np.random.default_rng(seed)
    return alphabet, [
        evo.Genome(
            schedule=alphabet.sample(rng, n_nodes),
            seed=int(rng.integers(0, 1 << 16)),
            churn=srch.sample_churn_schedule(rng, 3),
        )
        for _ in range(n)
    ]


def test_population_sha_pins_elitism_determinism():
    """THE determinism pin for the loop's selection step: the same
    rng seed produces byte-for-byte the same next population (sha256
    over stable genome JSON) — the engine-backed loop inherits this
    because its per-generation rng streams are (base_seed, g, axis)
    tuples.  Fast-tier stand-in for re-running a whole evolve() twice."""
    alphabet, pop = _seeded_population(7)
    scores = [3.0, -1.0, 4.0, 0.0, 2.0, 1.0]

    def child(rng, pa, pb):
        sched = evo.crossover_schedules(
            rng, pa.schedule, pb.schedule, alphabet, 5
        )
        return evo.Genome(
            schedule=evo.mutate_schedule(rng, sched, alphabet, 5),
            seed=int(rng.integers(0, 1 << 16)),
        )

    def fresh(rng):
        return evo.Genome(
            schedule=evo.fresh_schedule(rng, alphabet, 5),
            seed=int(rng.integers(0, 1 << 16)),
        )

    shas = []
    for _ in range(2):
        rng = np.random.default_rng((11, 1, 11))
        nxt = evo.select(rng, pop, scores, child, make_fresh=fresh)
        shas.append(evo.population_sha(nxt))
    assert shas[0] == shas[1]
    # and the sha actually sees the genes: perturb one engine seed
    bumped = list(pop)
    bumped[0] = dataclasses.replace(bumped[0], seed=bumped[0].seed + 1)
    assert evo.population_sha(bumped) != evo.population_sha(pop)


# ---------------- mutation / crossover legality ----------------


def test_mutate_schedule_keeps_crash_discipline():
    alphabet = srch.Alphabet.classic()
    rng = np.random.default_rng(3)
    protected = {0}
    for _ in range(200):
        sched = alphabet.sample(rng, 5)
        out = evo.mutate_schedule(
            rng, sched, alphabet, 5, hunt="duel-churn",
            protected=protected,
        )
        assert 1 <= len(out.episodes) <= alphabet.max_episodes
        crashed = set()
        for e in out.episodes:
            assert e.kind in alphabet.kinds
            if e.kind == "crash":
                crashed |= set(int(n) for n in e.nodes)
        assert len(crashed) <= (5 - 1) // 2
        assert not crashed & protected


def test_crossover_schedules_legal_child():
    alphabet = srch.Alphabet.classic()
    rng = np.random.default_rng(4)
    for _ in range(200):
        a = alphabet.sample(rng, 5)
        b = alphabet.sample(rng, 5)
        out = evo.crossover_schedules(rng, a, b, alphabet, 5)
        assert 1 <= len(out.episodes) <= alphabet.max_episodes
        crashed = {
            int(n) for e in out.episodes if e.kind == "crash"
            for n in e.nodes
        }
        assert len(crashed) <= (5 - 1) // 2


def test_jitter_episode_preserves_width_and_bounds():
    rng = np.random.default_rng(5)
    e = fltm.pause(40, 60, 1)
    for _ in range(50):
        j = evo.jitter_episode(rng, e, 96)
        assert j.t1 - j.t0 == 20
        assert 0 <= j.t0 and j.t1 <= 96 + 20  # width preserved, t0 in range
        assert j.t0 <= 96 - 20


# ---------------- cause-targeted hunting ----------------


def test_hunt_kinds_intersects_alphabet():
    lan = srch.Alphabet.classic()
    gray = srch.Alphabet.classic(gray=True)
    assert evo.hunt_kinds(lan, "gray-region") == ()
    assert evo.hunt_kinds(gray, "gray-region") == ("gray",)
    assert evo.hunt_kinds(lan, "duel-churn") == ("pause", "crash")
    assert evo.hunt_kinds(lan, "saturation") == ("burst",)
    assert evo.hunt_kinds(lan, None) == ()


def test_draw_episode_bias_lands_in_hunted_family():
    """The HUNT_BIAS contract: with a hunt armed, the overwhelming
    majority of mutation draws land inside the hunted cause's episode
    family (expected rate HUNT_BIAS/(HUNT_BIAS+1) plus the unbiased
    path's own mass)."""
    alphabet = srch.Alphabet.classic(gray=True)
    rng = np.random.default_rng(6)
    hits = sum(
        evo.draw_episode(rng, alphabet, 5, hunt="gray-region").kind
        == "gray"
        for _ in range(400)
    )
    assert hits >= 0.7 * 400
    # unbiased draws spread over the whole alphabet
    rng = np.random.default_rng(6)
    kinds = {
        evo.draw_episode(rng, alphabet, 5).kind for _ in range(400)
    }
    assert kinds == set(srch.KINDS_GRAY)


def test_fresh_schedule_always_carries_hunted_gene():
    alphabet = srch.Alphabet.classic()
    rng = np.random.default_rng(8)
    fam = set(evo.CAUSE_FAMILIES["duel-churn"])
    for _ in range(100):
        sched = evo.fresh_schedule(rng, alphabet, 5, hunt="duel-churn")
        assert any(e.kind in fam for e in sched.episodes)


# ---------------- shared alphabet (satellite: one grammar) ----------------


def test_alphabet_classic_preserves_draw_sequence():
    """Refactor guard: the committed Alphabet delegates to the same
    samplers with the same draw order — a seeded rng produces the
    identical schedule through either surface."""
    for gray in (False, True):
        a = srch.Alphabet.classic(gray=gray)
        s1 = a.sample(np.random.default_rng(123), 5)
        s2 = srch.sample_schedule(
            np.random.default_rng(123), 5,
            kinds=srch.KINDS_GRAY if gray else srch.KINDS,
        )
        assert s1.to_dict() == s2.to_dict()


def test_alphabet_member_subset_and_protocol():
    a = srch.Alphabet.classic(gray=True, wan=True)
    m = a.member()
    assert "gray" not in m.kinds and not m.wan
    assert m.protocol() is None
    assert a.protocol() is not None
    with pytest.raises(ValueError):
        srch.Alphabet(kinds=("gray",)).member()
    with pytest.raises(ValueError):
        srch.Alphabet(kinds=("nope",))
    with pytest.raises(ValueError):
        a.sample_episode(np.random.default_rng(0), 5, kinds=("bogus",))


# ---------------- churn-schedule genes (satellite 1) ----------------


def test_sample_churn_schedule_legal_by_construction():
    rng = np.random.default_rng(9)
    step = max(1, 96 // srch.CHURN_T0_GRID)
    drew_some = 0
    for _ in range(300):
        ch = srch.sample_churn_schedule(rng, 3)
        if ch is None:
            continue
        drew_some += 1
        evs = ch.events
        assert 1 <= len(evs) <= 3
        assert evs[0].wait == ctm.WAIT_NONE
        vids = [int(e.vid) for e in evs]
        assert len(set(vids)) == len(vids)
        added = set()
        for e in evs:
            assert int(e.t0) % step == 0
            if int(e.vid) >= meng.CHANGE_BASE:
                tgt, kind = meng.decode_change(int(e.vid))
                assert tgt != 0  # the driver node is never a target
                if kind == meng.ADD_ACCEPTOR:
                    assert tgt not in added
                    added.add(tgt)
                else:
                    assert kind == meng.DEL_ACCEPTOR
                    assert tgt in added  # del only after its add
            else:
                assert int(e.vid) >= srch.CHURN_PLAIN_VID_BASE
        # churn_targets names exactly the membership-change targets
        assert srch.churn_targets(ch) == {
            meng.decode_change(v)[0] for v in vids
            if v >= meng.CHANGE_BASE
        }
    assert drew_some > 100  # the empty draw stays a minority


def test_churn_plain_vid_base_pins_mc_member():
    """Drift pin: the sampler's plain-value vid base must match the
    churn scope's enumerator, or evolve's churn genes and the
    certificate denominator would speak different value alphabets."""
    assert srch.CHURN_PLAIN_VID_BASE == mc_member.PLAIN_VID_BASE


def test_sample_member_schedule_protects_churn_targets():
    rng = np.random.default_rng(10)
    for _ in range(200):
        ch = srch.sample_churn_schedule(rng, 3)
        sched = srch.sample_member_schedule(rng, 3, ch)
        protected = {0} | srch.churn_targets(ch)
        for e in sched.episodes:
            if e.kind == "crash":
                assert not set(int(n) for n in e.nodes) & protected


# ---------------- serve-axis genomes ----------------


def test_serve_genome_validation_and_weather_cfg():
    from tpu_paxos.config import SimConfig

    with pytest.raises(ValueError):
        sbr.ServeGenome("monsoon", ("poisson",), (250,), 0, 0)
    with pytest.raises(ValueError):
        sbr.ServeGenome("calm", ("poisson",), (333,), 0, 0)
    with pytest.raises(ValueError):
        sbr.ServeGenome("calm", ("poisson", "spike"), (250,), 0, 0)
    cfg = SimConfig(n_nodes=3, n_instances=8)
    assert sbr.weather_cfg(cfg, "squall").faults.drop_rate == 2000
    assert sbr.weather_cfg(cfg, "calm").faults.drop_rate == 0


def test_serve_mutation_never_flips_weather():
    """The envelope partition contract: weather is the compile axis,
    so no mutation move may leave the slot's preset (fast-tier
    stand-in for the zero-warm-compile census on the serve axis)."""
    rng = np.random.default_rng(12)
    wl = [np.arange(10), np.arange(10)]
    g = sbr.sample_serve_genome(rng, wl, "breezy", hunt="saturation")
    assert g.weather == "breezy"
    assert all(k in sbr.HUNT_KINDS["saturation"] for k in g.kinds)
    for _ in range(100):
        g = sbr.mutate_serve_genome(rng, g, hunt="saturation")
        assert g.weather == "breezy"
        assert all(k in sbr.ARRIVAL_KINDS for k in g.kinds)
        assert all(r in sbr.RATE_GRID for r in g.rates)


# ---------------- certificate budget + bench guards ----------------


def test_budget_lanes_reads_mc_certificate():
    """The certified-recall denominator comes LIVE from the pinned mc
    certificate (never hard-coded): fleet recalls against the quick
    scope / 4, member against the churn scope / 4."""
    certs = mc.load_certificates()
    for axis, scope in evo.BUDGET_SCOPES.items():
        budget, name, denom = evo._budget_lanes(axis, None)
        assert name == scope
        assert denom == int(certs[scope]["scenarios_reduced"])
        assert budget == denom // evo.BUDGET_DIV
    assert evo._budget_lanes("serve", None) == (None, None, None)


def test_bench_record_withheld_unless_certified():
    assert evo.bench_record({"certified": None}, "takeover") is None
    assert evo.bench_record({"certified": False}, "takeover") is None
    summary = {
        "certified": True, "axis": "fleet", "hunt": "duel-churn",
        "lanes": 8, "base_seed": 0, "budget_scope": "quick",
        "budget_denominator": 928, "budget_lanes": 232,
        "lanes_to_first_find": 56, "lanes_to_shrunk_artifact": 74,
        "replay_match": True, "warm_compiles": 0,
        "generations_run": 7, "compiles_per_generation": [2] + [0] * 6,
    }
    rec = evo.bench_record(summary, "takeover")
    assert rec["metric"] == "evolve_recall"
    assert rec["seeded_wedge"] == "takeover"
    assert rec["lanes_to_shrunk_artifact"] == 74
    assert rec["warm_compiles"] == 0


def test_evolve_rejects_unknown_axis_and_hunt():
    with pytest.raises(ValueError):
        evo.evolve(axis="bogus")
    with pytest.raises(ValueError):
        evo.evolve(axis="fleet", hunt="not-a-cause")


def test_certified_needs_certificate(tmp_path):
    with pytest.raises(ValueError):
        evo.evolve(
            axis="fleet", certified=True,
            cert_path=str(tmp_path / "missing.json"),
        )


# ---------------- engine-backed loops (slow) ----------------


@pytest.fixture(scope="module")
def quick_loop(tmp_path_factory):
    """One evolve-quick-shaped run shared by the slow fleet cells:
    synthetic decision_round_max wedge, 8 lanes, find -> shrink ->
    artifact in generation 0."""
    tdir = tmp_path_factory.mktemp("evolve-triage")
    return evo.evolve(
        axis="fleet", n_lanes=8, generations=2, base_seed=2,
        decision_round_max=35, max_wedges=1, triage_dir=str(tdir),
        verbose=False,
    ), tdir


@pytest.mark.slow
def test_fleet_loop_synthetic_end_to_end(quick_loop):
    """The make evolve-quick contract: sample -> dispatch -> flag ->
    single-run re-derive -> shrink -> schema-closed artifact ->
    byte-identical replay, with the recall accounting split into
    fleet lanes and shrinker evaluations.  Fast-tier stand-ins:
    selection determinism (test_population_sha_pins_elitism_
    determinism), budget read (test_budget_lanes_reads_mc_
    certificate)."""
    s, _ = quick_loop
    assert s["ok"] is True
    assert s["wedges_found"] == 1 and s["real_violations"] == 0
    w = s["wedges"][0]
    assert w["synthetic"] and "decision_round_max" in w["violation"]
    assert s["replay_match"] is True
    assert os.path.exists(s["artifact"])
    assert (
        s["lanes_to_shrunk_artifact"]
        == s["lanes_to_first_find"] + w["shrink_evals"]
    )
    # budget metadata is certificate-derived even outside --certified
    assert s["budget_lanes"] == s["budget_denominator"] // evo.BUDGET_DIV
    assert len(s["population_sha256"]) == 64
    # the artifact file schema stays closed: no shrink_evals inside
    with open(s["artifact"]) as f:
        assert "shrink_evals" not in json.load(f)


@pytest.mark.slow
def test_fleet_loop_zero_warm_compiles(quick_loop):
    """The envelope contract: generation 0 pays the fleet compile(s);
    every later generation reuses the cached executable byte-for-byte
    (the census delta is zero).  Fast-tier stand-in: the serve-axis
    weather-slot pin (test_serve_mutation_never_flips_weather)."""
    s, _ = quick_loop
    assert s["warm_compiles"] == 0
    assert all(c == 0 for c in s["compiles_per_generation"][1:])


@pytest.mark.slow
def test_lane_causes_match_aggregate_on_single_lane():
    """Satellite pin: per-lane breach attribution
    (search.lane_cause_series) and the generation AGGREGATE
    cause_series are the same labeling applied to different
    reductions — on a ONE-lane fleet they must coincide exactly.
    Fast-tier stand-in: the reducers' lane-axis promotion tests."""
    s = evo.evolve(
        axis="fleet", n_lanes=1, generations=1, base_seed=0,
        hunt="duel-churn", decision_round_max=1, max_wedges=0,
        verbose=False,
    )
    m = s["generation_telemetry"][0]["margins"]
    assert "lane_causes" in m, "flagged lane must carry attribution"
    assert m["lane_causes"]["0"] == m["cause_series"]


@pytest.mark.slow
def test_member_axis_loop_smoke(tmp_path):
    """The churn+fault axis: genomes carry ChurnSchedule genes, the
    loop dispatches MemberFleetRunner lanes, and recall is metered
    against the churn certificate denominator.  Fast-tier stand-ins:
    churn sampler legality + the PLAIN_VID_BASE drift pin."""
    s = evo.evolve(
        axis="member", n_lanes=4, generations=2, base_seed=0,
        hunt="duel-churn", max_wedges=2, triage_dir=str(tmp_path),
        verbose=False,
    )
    assert s["axis"] == "member"
    assert s["budget_scope"] == "churn"
    certs = mc.load_certificates()
    assert s["budget_denominator"] == int(
        certs["churn"]["scenarios_reduced"]
    )
    assert s["warm_compiles"] == 0
    assert s["generations_run"] == 2 and s["lanes_total"] == 8
    # the committed churn scope is green, so a wedge here would be a
    # real regression — exactly what ok reports
    assert s["ok"] is (s["real_violations"] == 0)


@pytest.mark.slow
def test_serve_axis_surfaces_diagnosed_breach():
    """The serve axis: offered-load genomes under quantized weather
    slots drive a windowed SLO breach whose attached diagnosis names
    the hunted cause.  Fast-tier stand-ins: burn-rate formula parity
    + serve genome validation/mutation pins."""
    s = evo.evolve(
        axis="serve", n_lanes=6, generations=3, base_seed=0,
        hunt="saturation", max_wedges=4, verbose=False,
    )
    assert s["warm_compiles"] == 0
    assert s["wedges_found"] >= 1
    assert any(
        "saturation" in w.get("causes", ()) for w in s["wedges"]
    ), s["wedges"]
    # breaches are real findings: the loop reports them as not-ok
    assert s["ok"] is False and s["real_violations"] >= 1


@pytest.mark.slow
def test_certified_recall_beats_quarter_budget(tmp_path, monkeypatch):
    """THE recall pin (BENCH_evolve.json's contract): with the PR-1
    commit-takeover wedge re-armed, the duel-churn hunt finds AND
    shrinks the wedge within a QUARTER of the exhaustive quick
    scope's lane budget (scenarios_reduced // 4, read live from the
    certificate), the artifact replays byte-identically, and no
    generation after the first compiles anything.  Fast-tier
    stand-ins: hunt-bias + immigrant-gene pins
    (test_draw_episode_bias_lands_in_hunted_family,
    test_fresh_schedule_always_carries_hunted_gene) and the bench
    withholding guard."""
    from tpu_paxos.harness import shrink as shr

    monkeypatch.setenv("TPU_PAXOS_SEEDED_WEDGE", "takeover")
    s = evo.evolve(
        axis="fleet", n_lanes=8, generations=29, base_seed=0,
        hunt="duel-churn", certified=True, max_wedges=1,
        triage_dir=str(tmp_path), verbose=False,
    )
    assert s["certified"] is True and s["ok"] is True, {
        k: s[k] for k in ("lanes_to_first_find",
                          "lanes_to_shrunk_artifact", "budget_lanes",
                          "replay_match", "warm_compiles")
    }
    assert s["budget_lanes"] == s["budget_denominator"] // 4
    assert s["lanes_to_first_find"] <= s["budget_lanes"]
    assert s["lanes_to_shrunk_artifact"] <= s["budget_lanes"]
    assert s["replay_match"] is True and s["warm_compiles"] == 0
    # the shrunk schedule keeps the wedge's culprit crash gene
    case, _ = shr.load_artifact(s["artifact"])
    kinds = {e.kind for e in case.cfg.faults.schedule.episodes}
    assert "crash" in kinds
    # and the certified summary feeds a non-withheld bench record
    rec = evo.bench_record(s, "takeover")
    assert rec is not None and rec["lanes_to_first_find"] <= 232
