"""Sharded general engine tests (parallel/sharded_sim).

The full protocol ladder — faults, retries, hole-filling, conflict
re-proposal, in-order gates, crashes — sharded over the 8-device
virtual mesh (conftest), judged by the same invariants as the
unsharded engine plus chosen-multiset equality against it (placement
differs by design; the decision SET must not)."""

import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim
from tpu_paxos.core import values as val
from tpu_paxos.harness import validate
from tpu_paxos.parallel import mesh as pmesh
from tpu_paxos.parallel import sharded_sim


def _real(chosen_vid) -> list[int]:
    return sorted(v for v in np.asarray(chosen_vid).tolist() if v >= 0)


def _check(r):
    assert r.done, f"not quiescent after {r.rounds} rounds"
    validate.check_agreement(r.learned)
    validate.check_exactly_once(r.learned, r.expected_vids)
    return validate.check_executed_identical(r.learned)


def test_sharded_sim_fault_free_matches_unsharded_set():
    m = pmesh.make_instance_mesh()
    cfg = SimConfig(n_nodes=5, n_instances=256, proposers=(0, 1), seed=0)
    r = sharded_sim.run_sharded(cfg, m)
    _check(r)
    r1 = sim.run(cfg)
    assert _real(r.chosen_vid) == _real(r1.chosen_vid)


@pytest.mark.slow
def test_sharded_sim_under_reference_faults():
    """debug.conf.sample fault rates, dueling proposers, 8 shards."""
    m = pmesh.make_instance_mesh()
    cfg = SimConfig(
        n_nodes=5,
        n_instances=256,
        proposers=(0, 1),
        seed=1,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    r = sharded_sim.run_sharded(cfg, m)
    _check(r)
    r1 = sim.run(cfg)
    assert _real(r.chosen_vid) == _real(r1.chosen_vid)


@pytest.mark.slow
def test_sharded_sim_same_seed_identical():
    """Determinism survives sharding: same seed, same mesh — byte-equal
    decisions (the member/diff.sh property, ref member/run.sh:1-18)."""
    m = pmesh.make_instance_mesh()
    cfg = SimConfig(
        n_nodes=5,
        n_instances=128,
        proposers=(0, 1),
        seed=3,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    a = sharded_sim.run_sharded(cfg, m)
    b = sharded_sim.run_sharded(cfg, m)
    assert np.array_equal(a.chosen_vid, b.chosen_vid)
    assert np.array_equal(a.chosen_round, b.chosen_round)
    assert np.array_equal(a.learned, b.learned)


def test_sharded_sim_in_order_gates_across_shards():
    """An in-order chain stays shard-affine (split_workload keeps
    chains whole) so proposal order = executed order, even while a
    second proposer floods ungated values over every shard."""
    m = pmesh.make_instance_mesh()
    inorder = np.asarray([10, 11, 12, 13], np.int32)
    gates = [
        np.asarray([int(val.NONE), 10, 11, 12], np.int32),
        np.zeros((0,), np.int32),
    ]
    free = np.arange(100, 140, dtype=np.int32)
    cfg = SimConfig(
        n_nodes=5,
        n_instances=128,
        proposers=(0, 1),
        seed=2,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    r = sharded_sim.run_sharded(cfg, m, workload=[inorder, free], gates=gates)
    executed = _check(r)
    validate.check_in_order_clients(max(executed, key=len), [inorder])


def test_sharded_sim_with_crashes():
    """Minority-capped fail-stop crashes under faults, sharded: the
    surviving majority still drives every value to chosen."""
    m = pmesh.make_instance_mesh()
    cfg = SimConfig(
        n_nodes=5,
        n_instances=256,
        proposers=(0, 1),
        seed=5,
        max_rounds=4000,
        faults=FaultConfig(
            drop_rate=500, dup_rate=1000, max_delay=2, crash_rate=3000
        ),
    )
    r = sharded_sim.run_sharded(cfg, m)
    assert int(r.crashed.sum()) <= (cfg.n_nodes - 1) // 2
    if r.done:
        validate.check_agreement(r.learned)
        validate.check_exactly_once(r.learned, r.expected_vids)
    else:
        # liveness not guaranteed for values whose proposer crashed;
        # safety always is
        validate.check_agreement(r.learned)


def test_sharded_sim_uneven_instances_rejected():
    m = pmesh.make_instance_mesh()
    cfg = SimConfig(n_nodes=3, n_instances=100, proposers=(0,))
    with pytest.raises(ValueError, match="divide"):
        sharded_sim.run_sharded(cfg, m)


def test_split_workload_keeps_chains_whole():
    wl = [np.asarray([10, 11, 12, 20, 21], np.int32)]
    gates = [np.asarray([int(val.NONE), 10, 11, int(val.NONE), 20], np.int32)]
    wls, gts = sharded_sim.split_workload(wl, gates, 2)
    # chain {10,11,12} -> shard 0, chain {20,21} -> shard 1
    assert wls[0][0].tolist() == [10, 11, 12]
    assert wls[1][0].tolist() == [20, 21]
    assert gts[0][0].tolist() == [int(val.NONE), 10, 11]
    assert gts[1][0].tolist() == [int(val.NONE), 20]


def test_split_workload_branching_and_cross_proposer_gates():
    """A fan-out gate (two entries gated on the same vid) and a gate on
    another proposer's value must both land on the gate's shard."""
    wl = [
        np.asarray([10, 11, 12], np.int32),
        np.asarray([30], np.int32),
    ]
    gates = [
        np.asarray([int(val.NONE), 10, 10], np.int32),  # 11, 12 both on 10
        np.asarray([10], np.int32),  # cross-proposer gate
    ]
    wls, gts = sharded_sim.split_workload(wl, gates, 4)
    shard_of = {
        v: s for s in range(4) for pi in range(2) for v in wls[s][pi].tolist()
    }
    assert shard_of[11] == shard_of[10]
    assert shard_of[12] == shard_of[10]
    assert shard_of[30] == shard_of[10]


def test_split_workload_forward_and_cross_proposer_reference():
    """A gate may reference a value that appears LATER in the scan
    (proposer 0's entry gated on proposer 1's value): union-find
    grouping must still co-locate them.  The old first-pass placement
    round-robined the gated entry before seeing its gate, stranding it
    on a shard where the gate never chooses — a permanent wedge."""
    wl = [np.asarray([20], np.int32), np.asarray([10], np.int32)]
    gates = [np.asarray([10], np.int32), np.asarray([int(val.NONE)], np.int32)]
    wls, _ = sharded_sim.split_workload(wl, gates, 2)
    shard_of = {
        v: s for s in range(2) for pi in range(2) for v in wls[s][pi].tolist()
    }
    assert shard_of[20] == shard_of[10]
    # and the whole run completes
    m = pmesh.make_instance_mesh()
    cfg = SimConfig(n_nodes=3, n_instances=64, proposers=(0, 1), seed=0)
    r = sharded_sim.run_sharded(cfg, m, workload=wl, gates=gates)
    _check(r)
    assert sorted(v for v in r.chosen_vid.tolist() if v >= 0) == [10, 20]


@pytest.mark.slow
def test_sharded_sim_seed4_no_wedge():
    """Regression: an early-drained proposer must not noop-fill shard
    space another proposer's conflict-requeued values still need (the
    hole-fill frontier extends only when ALL queues on the shard are
    drained).  Seed 4 wedged the original per-proposer rule."""
    m = pmesh.make_instance_mesh()
    cfg = SimConfig(
        n_nodes=5,
        n_instances=256,
        proposers=(0, 1),
        seed=4,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    r = sharded_sim.run_sharded(cfg, m)
    _check(r)
    assert _real(r.chosen_vid) == _real(sim.run(cfg).chosen_vid)
