"""The sim-engine pallas kernels (core/simkern.py) must be
bit-identical to the jnp formulations they replace — checked here on
the CPU pallas interpreter over randomized inputs, and (opt-in) by
running the whole engine both ways on the real chip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_paxos.core import ballot as bal
from tpu_paxos.core import simkern
from tpu_paxos.core import values as val

A, P = 5, 2
I = simkern.TILE  # one whole tile


def _rand_state(seed):
    r = np.random.RandomState(seed)
    # sparse accepted state with realistic sentinels
    acc_ballot = np.where(
        r.rand(A, I) < 0.3, r.randint(1, 1 << 18, (A, I)), int(bal.NONE)
    ).astype(np.int32)
    acc_vid = np.where(
        acc_ballot != int(bal.NONE), r.randint(0, 1 << 20, (A, I)), int(val.NONE)
    ).astype(np.int32)
    learned = np.where(
        r.rand(A, I) < 0.2, r.randint(0, 1 << 20, (A, I)), int(val.NONE)
    ).astype(np.int32)
    abat = np.where(
        r.rand(P, I) < 0.7, r.randint(0, 1 << 20, (P, I)), int(val.NONE)
    ).astype(np.int32)
    abal = r.randint(1, 1 << 18, (P,)).astype(np.int32)
    return acc_ballot, acc_vid, learned, abat, abal, r


@pytest.mark.parametrize("seed", [0, 1])
def test_store_accepts_matches_jnp(seed):
    acc_ballot, acc_vid, learned, abat, abal, r = _rand_state(seed)
    elig = (r.rand(P, A) < 0.6).astype(bool)

    # jnp reference: the exact loop from core/sim.py's _store_accepts
    is_comm = learned != int(val.NONE)
    best_b = np.full_like(acc_ballot, int(bal.NONE))
    best_v = np.full_like(acc_vid, int(val.NONE))
    for pi in range(P):
        batp = abat[pi]
        ackp = (
            elig[pi][:, None]
            & (batp != int(val.NONE))[None, :]
            & np.where(is_comm, batp[None, :] == learned, abal[pi] >= acc_ballot)
        )
        candp = np.where(ackp & ~is_comm, abal[pi], int(bal.NONE))
        take = candp > best_b
        best_b = np.where(take, candp, best_b)
        best_v = np.where(take, np.broadcast_to(batp[None, :], best_v.shape), best_v)
    do_store = best_b != int(bal.NONE)
    want_b = np.where(do_store, best_b, acc_ballot)
    want_v = np.where(do_store, best_v, acc_vid)

    got_b, got_v = simkern.store_accepts(
        jnp.asarray(acc_ballot), jnp.asarray(acc_vid), jnp.asarray(learned),
        jnp.asarray(abat), jnp.asarray(abal), jnp.asarray(elig),
        interpret=True,
    )
    assert (np.asarray(got_b) == want_b).all()
    assert (np.asarray(got_v) == want_v).all()


@pytest.mark.parametrize("seed", [0, 1])
def test_accum_acks_matches_jnp(seed):
    acc_ballot, acc_vid, learned, cur_batch, ballot, r = _rand_state(seed)
    acks = (r.rand(P, A, I) < 0.2).astype(np.int8)
    amatch_pa = (r.rand(P, A) < 0.6).astype(bool)

    hold = (acc_vid[None] == cur_batch[:, None, :]) & (
        acc_ballot[None] == ballot[:, None, None]
    )
    comm = (learned[None] == cur_batch[:, None, :]) & (
        learned[None] != int(val.NONE)
    )
    want = acks | (
        amatch_pa[:, :, None]
        & (cur_batch != int(val.NONE))[:, None, :]
        & (hold | comm)
    ).astype(np.int8)
    want_n = want.sum(axis=1, dtype=np.int32)

    got, got_n = simkern.accum_acks(
        jnp.asarray(acks), jnp.asarray(cur_batch), jnp.asarray(acc_ballot),
        jnp.asarray(acc_vid), jnp.asarray(learned), jnp.asarray(ballot),
        jnp.asarray(amatch_pa), interpret=True,
    )
    assert (np.asarray(got) == want).all()
    assert (np.asarray(got_n) == want_n).all()


@pytest.mark.tpu
@pytest.mark.skipif(
    os.environ.get("TPU_PAXOS_TPU_TEST") != "1",
    reason="drives the real chip; opt in with TPU_PAXOS_TPU_TEST=1",
)
def test_engine_pallas_matches_jnp_on_real_tpu():
    """Run a whole faulty engine config on the chip with the kernels
    on and off; final decisions and acceptor state must be
    bit-identical."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = ":".join(
        x
        for x in (
            repo,
            env.get("TPU_PAXOS_AXON_SITE", "/root/.axon_site"),
            env.get("PYTHONPATH", ""),
        )
        if x
    )
    env.pop("JAX_PLATFORMS", None)
    code = """
import jax, numpy as np
assert jax.devices()[0].platform == 'tpu', jax.devices()
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.utils import prng
i = simm_tile = __import__('tpu_paxos.core.simkern', fromlist=['TILE']).TILE * 2
cfg = SimConfig(n_nodes=5, n_instances=i, proposers=(0, 1), seed=0,
                max_rounds=4000,
                faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2))
workload = simm.default_workload(cfg)
pend, gate, tail, c = simm.prepare_queues(cfg, workload)
root = prng.root_key(cfg.seed)
finals = []
for up in (True, False):
    st = simm.init_state(cfg, pend, gate, tail, root)
    fn = simm.build_engine(cfg, c, use_pallas=up)
    go = jax.jit(lambda r, s: jax.lax.while_loop(
        lambda x: (~x.done) & (x.t < cfg.max_rounds), lambda x: fn(r, x), s))
    finals.append(go(root, st))
a, b = finals
assert bool(a.done) and bool(b.done)
for name in ('chosen_vid', 'chosen_round', 'chosen_ballot'):
    x, y = np.asarray(getattr(a.met, name)), np.asarray(getattr(b.met, name))
    assert (x == y).all(), name
for get in (lambda s: s.acc.acc_ballot, lambda s: s.acc.acc_vid,
            lambda s: s.learned):
    assert (np.asarray(get(a)) == np.asarray(get(b))).all()
print('SIMKERN_TPU_OK')
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=580,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "SIMKERN_TPU_OK" in proc.stdout
