"""Per-edge fault matrices, gray failures, and the delivery-time cut
(the WAN robustness layer): the sha256 parity contracts that make
every scalar config the degenerate case of the matrix model, the
gray clamp-never-drop semantics, compiled-vs-runtime gray parity, and
the geo repro artifact round trip.

Fleet-backed cells share ONE cached envelope runner (the module
fixture rides fleet/envelope.runner_for, so the whole file pays a
single fleet compile).
"""

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_paxos.config import (
    EdgeFaultConfig,
    FaultConfig,
    ProtocolConfig,
    SimConfig,
)
from tpu_paxos.core import faults as flt
from tpu_paxos.core import net as netm
from tpu_paxos.core import sim as simm
from tpu_paxos.core import wan
from tpu_paxos.harness import shrink as shr
from tpu_paxos.replay.decision_log import decision_log
from tpu_paxos.utils import prng


def _sha(cfg: SimConfig, r) -> str:
    text = decision_log(
        r.chosen_vid, r.chosen_ballot, stride=1024,
        n_instances=cfg.n_instances,
    )
    return hashlib.sha256(text.encode()).hexdigest()


def _cfg(faults: FaultConfig, seed: int = 0, **over) -> SimConfig:
    base = dict(
        n_nodes=3, n_instances=16, proposers=(0, 1), seed=seed,
        max_rounds=2000, faults=faults,
    )
    base.update(over)
    return SimConfig(**base)


# ---------------- config-model validation ----------------


def test_edge_fault_config_validation():
    e = EdgeFaultConfig.uniform(3, drop_rate=500, max_delay=2)
    assert e.n_nodes == 3 and e.delay_bound == 2
    with pytest.raises(ValueError, match="square"):
        EdgeFaultConfig(((0, 0),), ((0,),), ((0,),), ((0,),))
    with pytest.raises(ValueError, match="10000"):
        EdgeFaultConfig.uniform(2, drop_rate=20_000)
    with pytest.raises(ValueError, match="min <= max"):
        EdgeFaultConfig(
            ((0, 0), (0, 0)), ((0, 0), (0, 0)),
            ((2, 0), (0, 0)), ((1, 0), (0, 0)),
        )
    # edges replace the scalar knobs: scalar drop must stay 0
    with pytest.raises(ValueError, match="replace the scalar"):
        FaultConfig(drop_rate=5, max_delay=2, edges=e)
    # the scalar max_delay is the ring bound and must cover the matrix
    with pytest.raises(ValueError, match="ring bound"):
        FaultConfig(max_delay=1, edges=e)
    # cluster-size cross-check lives on SimConfig
    with pytest.raises(ValueError, match="cluster has 5 nodes"):
        SimConfig(n_nodes=5, faults=FaultConfig(max_delay=2, edges=e))
    # JSON round trip (the artifact seam)
    assert EdgeFaultConfig.from_dict(e.to_dict()) == e


def test_gray_episode_validation_and_roundtrip():
    g = flt.gray(2, 9, 1, 2, delay=3)
    assert g.nodes == (1, 2) and g.delay == 3
    with pytest.raises(ValueError, match="at least one node"):
        flt.gray(0, 4, delay=2)
    with pytest.raises(ValueError, match="delay must be >= 1"):
        flt.gray(0, 4, 1, delay=0)
    sched = flt.FaultSchedule((g,))
    assert flt.FaultSchedule.from_dict(sched.to_dict()) == sched
    # named rejection, never silent exclusion: at max_delay=0 the
    # clamp would reduce every gray episode to a no-op
    with pytest.raises(ValueError, match="nonzero ring bound"):
        FaultConfig(schedule=sched)
    FaultConfig(max_delay=2, schedule=sched)  # headroom: fine


# ---------------- sha256 parity: scalar == uniform matrix ----------


def test_scalar_vs_uniform_matrix_sha_parity():
    """THE contract (ISSUE 13): scalar FaultKnobs runs are
    bit-identical to the equivalent uniform [A, A] matrix runs — the
    matrix path samples the same PRNG bits and applies the rates
    elementwise, so every existing schedule/artifact/BENCH baseline
    is the degenerate case of the new model.  Compile-time path; the
    fleet (runtime) twin is pinned below."""
    scalar = FaultConfig(
        drop_rate=500, dup_rate=1000, min_delay=1, max_delay=2,
        crash_rate=1000,
    )
    uniform = FaultConfig(
        max_delay=2, crash_rate=1000,
        edges=EdgeFaultConfig.uniform(
            3, drop_rate=500, dup_rate=1000, min_delay=1, max_delay=2
        ),
    )
    for seed in (0,):
        r_s = simm.run(_cfg(scalar, seed))
        r_u = simm.run(_cfg(uniform, seed))
        assert _sha(_cfg(scalar, seed), r_s) == _sha(_cfg(uniform, seed), r_u)
        assert r_s.rounds == r_u.rounds
        assert (r_s.crashed == r_u.crashed).all()


@pytest.mark.slow
def test_asymmetric_matrix_changes_the_run():
    """The matrix axis is live, not decorative: an asymmetric loss
    matrix must produce a different trajectory than its uniform
    collapse.  (Fast-tier coverage: test_copy_plan_asymmetric_matrix
    pins the per-edge sampling at the copy_plan level.)"""
    m = np.zeros((3, 3), np.int64)
    m[0, 1] = m[1, 0] = 6000  # the 0<->1 link is terrible
    tup = lambda x: tuple(tuple(int(v) for v in row) for row in x)  # noqa: E731
    asym = FaultConfig(max_delay=2, edges=EdgeFaultConfig(
        drop_rate=tup(m), dup_rate=tup(np.zeros_like(m)),
        min_delay=tup(np.zeros_like(m)),
        max_delay=tup(np.full_like(m, 2)),
    ))
    clean = FaultConfig(max_delay=2, edges=EdgeFaultConfig.uniform(
        3, max_delay=2
    ))
    r_a = simm.run(_cfg(asym))
    r_c = simm.run(_cfg(clean))
    assert r_a.done and r_c.done
    # the decision log pins (vid, ballot); loss on one link shows up
    # in the decision ROUNDS (retry ladder), so compare those
    assert not (r_a.chosen_round == r_c.chosen_round).all()


# ---------------- gray semantics ----------------


def test_gray_inflation_clamps_never_drops():
    """copy_plan unit contract: gray inflation adds to every
    surviving copy's delay, clamps at the ring bound, and NEVER
    changes which copies survive."""
    key = prng.root_key(7)
    fc = FaultConfig(drop_rate=2000, dup_rate=1000, max_delay=3)
    kn = jax.tree.map(jnp.asarray, netm.knobs_from_faults(fc))
    al0, dl0 = netm.copy_plan(key, (2, 3), fc, knobs=kn)
    g = jnp.full((2, 3), 2, jnp.int32)
    al1, dl1 = netm.copy_plan(
        key, (2, 3), fc, knobs=kn, gray=g, delay_bound=3
    )
    assert (np.asarray(al0) == np.asarray(al1)).all()  # never drops
    want = np.minimum(np.asarray(dl0) + 2, 3)  # clamp at the bound
    assert (np.asarray(dl1) == want).all()
    # zero inflation is exact (the all-zero gray round of a runtime
    # table traces the same values)
    al2, dl2 = netm.copy_plan(
        key, (2, 3), fc, knobs=kn, gray=jnp.zeros((2, 3), jnp.int32),
        delay_bound=3,
    )
    assert (np.asarray(dl2) == np.asarray(dl0)).all()
    assert (np.asarray(al2) == np.asarray(al0)).all()


def test_gray_run_converges_and_slows():
    """Engine-level gray semantics: a gray node slows decisions but
    the run still quiesces (gray never drops), even when the
    inflation exceeds the ring bound (clamp, not overflow)."""
    sched = flt.FaultSchedule((flt.gray(2, 30, 1, delay=100),))
    gray_cfg = _cfg(FaultConfig(max_delay=2, schedule=sched))
    r_g = simm.run(gray_cfg)
    assert r_g.done  # clamped at ring bound 2 — no lost messages
    r_p = simm.run(_cfg(FaultConfig(max_delay=2)))
    assert r_p.done
    # gray is pure delay: decisions land LATER (the decision rounds
    # move), even though which values win may not change
    assert not (r_g.chosen_round == r_p.chosen_round).all()
    assert r_g.rounds > r_p.rounds


# ---------------- delivery-time cut ----------------


def test_delivery_mask_unit():
    """net.delivery_mask: arrivals on cut edges void, same-side
    arrivals untouched, all-true reach is the identity."""
    p, a = 2, 3
    ar = netm.NetBuffers(
        prep_req=jnp.full((p, a), 7, jnp.int32),
        prep_echo=jnp.full((a, p), 8, jnp.int32),
        rej=jnp.full((a, p), 9, jnp.int32),
        acc_req=jnp.full((p, a), 10, jnp.int32),
        acc_echo=jnp.full((a, p), 11, jnp.int32),
        com_pres=jnp.ones((p, a), jnp.bool_),
        com_rep=jnp.ones((a, p), jnp.bool_),
    )
    reach = np.ones((a, a), bool)
    reach[0, 2] = reach[2, 0] = False  # node 0 <-> node 2 severed
    pn = np.asarray([0, 1])  # proposers on nodes 0 and 1
    reach_pa = jnp.asarray(reach[pn])  # [P, A]
    reach_ap = jnp.asarray(reach[:, pn])  # [A, P]
    cut = netm.delivery_mask(ar, reach_pa, reach_ap)
    # proposer 0 (node 0) -> acceptor 2: voided; -> acceptor 1: alive
    assert int(cut.prep_req[0, 2]) == -1 and int(cut.prep_req[0, 1]) == 7
    assert not bool(cut.com_pres[0, 2]) and bool(cut.com_pres[0, 1])
    # acceptor 2 -> proposer 0 (node 0): voided; -> proposer 1: alive
    assert int(cut.acc_echo[2, 0]) == -1 and int(cut.acc_echo[2, 1]) == 11
    assert not bool(cut.com_rep[2, 0]) and bool(cut.com_rep[2, 1])
    # identity at full reach
    full = netm.delivery_mask(
        ar, jnp.ones((p, a), jnp.bool_), jnp.ones((a, p), jnp.bool_)
    )
    for f in ar._fields:
        assert (np.asarray(getattr(full, f))
                == np.asarray(getattr(ar, f))).all()


@pytest.mark.slow
def test_delivery_cut_drops_inflight_copies():
    """A copy in flight across an edge severed at its arrival round
    is dropped under delivery_cut=True (seed chosen so a cross-cut
    copy is provably in flight: the runs diverge), while a cut-free
    schedule is bit-identical under either flag (exactness).
    (Fast-tier coverage: test_delivery_mask_unit pins the per-edge
    void/pass-through semantics on crafted arrivals.)"""
    sched = flt.FaultSchedule((flt.partition(4, 24, (0, 1), (2, 3, 4)),))
    proto = ProtocolConfig(prepare_delay_min=0, prepare_delay_max=1)
    base = dict(
        n_nodes=5, n_instances=32, proposers=(0, 1), seed=2,
        max_rounds=2000, protocol=proto,
    )
    on = SimConfig(faults=FaultConfig(
        min_delay=2, max_delay=4, schedule=sched, delivery_cut=True,
    ), **base)
    off = SimConfig(faults=FaultConfig(
        min_delay=2, max_delay=4, schedule=sched,
    ), **base)
    r_on, r_off = simm.run(on), simm.run(off)
    assert r_on.done and r_off.done
    assert _sha(on, r_on) != _sha(off, r_off)
    # exact when no edge is ever cut: the armed engine's program only
    # differs where reach masks exist
    clean_on = SimConfig(faults=FaultConfig(
        min_delay=2, max_delay=4, delivery_cut=True,
    ), **base)
    clean_off = SimConfig(faults=FaultConfig(
        min_delay=2, max_delay=4,
    ), **base)
    assert _sha(clean_on, simm.run(clean_on)) == _sha(
        clean_off, simm.run(clean_off)
    )


# ---------------- compiled-constant vs runtime-table gray parity ----


@pytest.fixture(scope="module")
def geo_runner():
    """ONE telemetry-armed envelope runner for every fleet cell in
    this file (fleet/envelope.runner_for — the shared triage-stack
    executable)."""
    from tpu_paxos.fleet import envelope as env

    cfg = SimConfig(
        n_nodes=3, n_instances=16, proposers=(0, 1), seed=0,
        max_rounds=2000, faults=FaultConfig(max_delay=4),
    )
    workload = simm.default_workload(cfg)
    runner = env.runner_for(cfg, workload, None, telemetry=True)
    return runner, cfg, workload


def test_gray_compiled_vs_runtime_table_parity(geo_runner):
    """The PR-4/PR-8 discipline extended to gray: a gray-bearing
    schedule lowered to compiled-constant tables (single run) and to
    a runtime ScheduleTable (fleet lane) must be decision-log
    sha256-IDENTICAL.  The knobs carry ``min_delay=2, max_delay=4``
    so the gray CLAMP SEAM is live: inflated delays (2..4 + 2 = 4..6)
    cross the lane's declared bound (4) while staying under the
    envelope ring (8) — the clamp must be the lane's own bound (a
    runtime knob), or the fleet lane forks from its lane_cfg()
    single-run replay exactly here (caught by review)."""
    runner, cfg, workload = geo_runner
    sched = flt.FaultSchedule((
        flt.gray(2, 18, 1, delay=2),
        flt.pause(6, 12, 2),
    ))
    lane_fc = FaultConfig(min_delay=2, max_delay=4)
    single_cfg = dataclasses.replace(
        cfg, faults=dataclasses.replace(lane_fc, schedule=sched)
    )
    r_single = simm.run(single_cfg, workload)
    rep = runner.run(
        [cfg.seed], [sched],
        workloads=[(workload, None)],
        knobs=[lane_fc],
    )
    r_lane = rep.lane_result(0)
    assert _sha(single_cfg, r_single) == _sha(single_cfg, r_lane)
    # bit-identity, not just log identity: gray moves decision ROUNDS
    assert (r_single.chosen_round == r_lane.chosen_round).all()
    assert r_single.rounds == r_lane.rounds
    assert bool(rep.verdict.ok[0])


def test_fleet_rejects_gray_on_zero_bound_lane(geo_runner):
    """The runtime-table twin of the FaultConfig named rejection: a
    gray schedule on a lane whose declared max_delay is 0 would clamp
    to a silent no-op — the runner must refuse by name."""
    runner, cfg, workload = geo_runner
    sched = flt.FaultSchedule((flt.gray(1, 8, 1, delay=2),))
    with pytest.raises(ValueError, match="nonzero lane max_delay"):
        runner.run(
            [0], [sched],
            workloads=[(workload, None)],
            knobs=[FaultConfig()],
        )


@pytest.mark.slow
def test_fleet_matrix_lane_matches_scalar_single_run(geo_runner):
    """Runtime twin of the scalar==uniform pin: a fleet lane running
    UNIFORM matrix knobs must byte-match the compile-time SCALAR
    single run of the same config — the fleet normalizes every lane
    to matrix form, so this parity is what keeps all pre-matrix
    artifacts replayable.  (Fast-tier coverage:
    test_scalar_vs_uniform_matrix_sha_parity pins the compile-time
    twin, and tests/test_fleet.py's lane-for-lane sha grid pins
    the fleet's matrix-normalized lanes against scalar single
    runs.)"""
    runner, cfg, workload = geo_runner
    scalar = FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2)
    uniform = FaultConfig(max_delay=2, edges=EdgeFaultConfig.uniform(
        3, drop_rate=500, dup_rate=1000, max_delay=2
    ))
    single_cfg = dataclasses.replace(cfg, faults=scalar)
    r_single = simm.run(single_cfg, workload)
    rep = runner.run(
        [cfg.seed, cfg.seed], [None, None],
        workloads=[(workload, None)] * 2,
        knobs=[scalar, uniform],
    )
    sha_single = _sha(single_cfg, r_single)
    for i in (0, 1):
        lane = rep.lane_result(i)
        assert sha_single == _sha(single_cfg, lane)
        assert (r_single.chosen_round == lane.chosen_round).all()


def test_fleet_region_counters(geo_runner):
    """The recorder's region-pair plane: a runtime node->region map
    attributes per-edge offered/dropped to fixed-shape [R, R] totals
    on device."""
    runner, cfg, workload = geo_runner
    lossy = FaultConfig(max_delay=2, edges=EdgeFaultConfig.uniform(
        3, drop_rate=2000, max_delay=1
    ))
    rep = runner.run(
        [0], [None],
        workloads=[(workload, None)],
        knobs=[lossy],
        regions=[np.asarray([0, 0, 1], np.int32)],
    )
    blk = rep.lane_telemetry(0)["region_pairs"]
    assert blk["n_regions"] == 2
    off = np.asarray(blk["offered"])
    assert off.sum() > 0
    # every counted edge lands in some region pair; drops happened
    assert np.asarray(blk["dropped"]).sum() > 0


# ---------------- WAN presets ----------------


def test_wan_presets_shapes_and_bounds():
    for preset in (wan.WAN3, wan.WAN5):
        for n in (3, 5, 7):
            e = wan.edge_faults(preset, n)
            assert e.n_nodes == n
            assert e.delay_bound <= wan.PRESET_DELAY_BOUND
            rmap = wan.node_regions(preset, n)
            assert rmap.shape == (n,)
            assert rmap.max() < preset.n_regions
            # intra-region edges are fast; the longest link dominates
            for s in range(n):
                assert e.min_delay[s][s] == 0
                assert e.drop_rate[s][s] == 0
        fc = wan.wan_fault_config(preset, 5)
        assert fc.edges is not None
        assert fc.max_delay == wan.PRESET_DELAY_BOUND
    with pytest.raises(ValueError, match="ring bound"):
        wan.wan_fault_config(wan.WAN5, 5, delay_bound=2)


def test_region_slo_judgment():
    from tpu_paxos.serve import harness as sharn

    # crafted [W, B] series: bucket 1 is slow (everything > 16 rounds)
    hist = np.zeros((4, 10), np.int64)
    hist[0, 1] = 10  # fast bucket
    hist[1, 6] = 10  # slow bucket: (32, 64]
    hist[2, 1] = 10
    wd = {"window_rounds": 32, "lat_hist": hist.tolist()}
    slo = sharn.region_slo(
        wan.WAN3, {"us": 16, "ap": 64}, latency_rounds=16,
    )
    out = sharn.slo_windows(wd, slo)
    # global 16-round SLO breaches at bucket 1...
    assert out["breach_windows"] == [1]
    # ...the near region (16) breaches with it, the far region's
    # 64-round budget absorbs the WAN hop
    assert out["regions"]["us"]["breach_windows"] == [1]
    assert out["regions"]["ap"]["breach_windows"] == []
    assert out["regions"]["ap"]["ok"] and not out["regions"]["us"]["ok"]
    assert out["regions_ok"] is False and out["ok"] is False
    with pytest.raises(ValueError, match="unknown region"):
        sharn.region_slo(wan.WAN3, {"mars": 8}, latency_rounds=8)


# ---------------- grammar + shrink moves ----------------


def test_search_grammar_gray_and_edge_knobs():
    from tpu_paxos.fleet import search as fsearch

    rng = np.random.default_rng(0)
    kinds = set()
    for _ in range(64):
        e = fsearch.sample_episode(rng, 5, 48, kinds=fsearch.KINDS_GRAY)
        kinds.add(e.kind)
        if e.kind == "gray":
            assert 1 <= e.delay <= fsearch.GRAY_DELAY_MAX
            assert e.nodes
    assert "gray" in kinds
    # the classic alphabet must NOT draw gray (committed wedge
    # artifacts pin the old draw sequence)
    rng2 = np.random.default_rng(0)
    for _ in range(64):
        assert fsearch.sample_episode(rng2, 5, 48).kind != "gray"
    # edge-knob genes: valid FaultConfig, matrices within the bound
    rng3 = np.random.default_rng(1)
    for _ in range(8):
        fc = fsearch.sample_edge_knobs(rng3, 5, 8)
        assert fc.edges is not None
        assert fc.edges.delay_bound <= 8
        assert fc.max_delay == 8


@pytest.mark.slow
def test_shrink_collapses_matrix_and_gray(geo_runner):
    """A geo case (edge matrix + gray episode) whose failure does not
    depend on either must shrink to a scalar, gray-free case — the
    matrix-collapse and gray-delay moves in action.  Uses the
    synthetic decision_round_max check (the established triage-path
    knob), judged through the SAME envelope runner as the other
    cells.  (Fast-tier coverage: test_shrink_geo_moves_stubbed
    drives the same move set through a stubbed judge.)"""
    runner, cfg, workload = geo_runner
    sched = flt.FaultSchedule((flt.gray(2, 10, 1, delay=2),))
    geo = FaultConfig(
        max_delay=4, schedule=sched,
        edges=EdgeFaultConfig.uniform(3, drop_rate=200, max_delay=1),
    )
    case = shr.ReproCase(
        cfg=dataclasses.replace(cfg, faults=geo),
        workload=workload, gates=None, chains=[],
        extra_checks={"decision_round_max": 0},  # always "fails"
    )
    small, viol = shr.shrink_case(case, max_evals=60)
    assert "decision_round_max" in viol
    assert small.cfg.faults.edges is None  # matrix collapsed away
    assert small.cfg.faults.schedule is None  # gray episode dropped


@pytest.mark.slow
def test_geo_repro_artifact_roundtrip(tmp_path, geo_runner):
    """A geo repro artifact (gray episode + edge matrix + delivery
    cut in the config) validates against the schema and replays
    byte-identically in process.  (Fast-tier coverage:
    test_geo_cfg_dict_roundtrip pins the serialization seam without
    an engine run; the CLI e2e twin is test_geo_repro_cli_e2e.)"""
    from tpu_paxos.analysis.artifact_schema import validate_artifact

    runner, cfg, workload = geo_runner
    sched = flt.FaultSchedule((flt.gray(1, 8, 2, delay=2),))
    geo = FaultConfig(
        max_delay=4, schedule=sched, delivery_cut=True,
        edges=EdgeFaultConfig.uniform(3, drop_rate=300, max_delay=1),
    )
    case = shr.ReproCase(
        cfg=dataclasses.replace(cfg, faults=geo),
        workload=workload, gates=None, chains=[],
        extra_checks={"decision_round_max": 0},
    )
    path = str(tmp_path / "geo_repro.json")
    # shrink OFF (max_evals small, but keep the geo structure): pin
    # the artifact for the UNSHRUNK case so edges/gray/delivery_cut
    # all round-trip through the file
    _, viol = shr.run_case(case)
    assert viol is not None
    art = shr.save_artifact(path, case, viol)
    with open(path) as f:
        validate_artifact(json.load(f))
    assert art["cfg"]["faults"]["edges"]["drop_rate"][0][1] == 300
    assert art["cfg"]["faults"]["delivery_cut"] is True
    assert art["cfg"]["faults"]["schedule"]["episodes"][0]["kind"] == "gray"
    out = shr.reproduce(path)
    assert out["match"], out


@pytest.mark.slow
def test_geo_repro_cli_e2e(tmp_path, geo_runner):
    """`python -m tpu_paxos repro` replays a geo artifact
    byte-identically end to end (fast-tier coverage:
    test_geo_repro_artifact_roundtrip replays the same artifact shape
    in process)."""
    import subprocess
    import sys

    runner, cfg, workload = geo_runner
    sched = flt.FaultSchedule((flt.gray(1, 8, 2, delay=2),))
    geo = FaultConfig(
        max_delay=4, schedule=sched,
        edges=EdgeFaultConfig.uniform(3, drop_rate=300, max_delay=1),
    )
    case = shr.ReproCase(
        cfg=dataclasses.replace(cfg, faults=geo),
        workload=workload, gates=None, chains=[],
        extra_checks={"decision_round_max": 0},
    )
    path = str(tmp_path / "geo_repro.json")
    _, viol = shr.run_case(case)
    shr.save_artifact(path, case, viol)
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "repro", path, "--json"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["match"] is True


# ---------------- cheap fast-tier twins ----------------


def test_copy_plan_asymmetric_matrix():
    """Per-edge sampling at the copy_plan level (fast-tier coverage
    for the slow asymmetric engine cell): an edge-shaped drop matrix
    drops ONLY where its entries say, from the same drawn bits the
    uniform matrix sees."""
    key = prng.root_key(3)
    fc = FaultConfig(max_delay=0)
    # edge-shaped [2, 3] rates as pre-sliced matrix-knob views
    drop = jnp.asarray([[10_000, 0, 0], [0, 0, 10_000]], jnp.int32)
    kn = netm.FaultKnobs(
        drop_rate=drop,
        dup_rate=jnp.zeros((2, 3), jnp.int32),
        min_delay=jnp.zeros((2, 3), jnp.int32),
        max_delay=jnp.zeros((2, 3), jnp.int32),
        crash_rate=jnp.int32(0),
        delay_bound=jnp.int32(0),
    )
    al, dl = netm.copy_plan(key, (2, 3), fc, knobs=kn)
    alive0 = np.asarray(al[0])  # original copy survival
    assert not alive0[0, 0] and not alive0[1, 2]  # rate-1e4 edges drop
    assert alive0[0, 1] and alive0[0, 2] and alive0[1, 0] and alive0[1, 1]
    assert (np.asarray(dl) == 0).all()
    # the uniform-rate twin draws the SAME bits: a rate-0 matrix
    # keeps every copy 0 alive
    kz = kn._replace(drop_rate=jnp.zeros((2, 3), jnp.int32))
    al_z, _ = netm.copy_plan(key, (2, 3), fc, knobs=kz)
    assert np.asarray(al_z[0]).all()


def test_geo_cfg_dict_roundtrip():
    """The artifact serialization seam without an engine run
    (fast-tier coverage for the slow in-process replay): a geo config
    (gray schedule + edge matrix + delivery cut) survives
    _cfg_to_dict -> schema validation -> _cfg_from_dict, and a
    classic config writes NO WAN keys (byte-stable format)."""
    from tpu_paxos.analysis.artifact_schema import _FAULTS

    sched = flt.FaultSchedule((flt.gray(1, 8, 2, delay=2),))
    geo = _cfg(FaultConfig(
        max_delay=4, schedule=sched, delivery_cut=True,
        edges=EdgeFaultConfig.uniform(3, drop_rate=300, max_delay=1),
    ))
    d = shr._cfg_to_dict(geo)
    _FAULTS.check(d["faults"], "cfg.faults")
    assert shr._cfg_from_dict(d) == geo
    classic = _cfg(FaultConfig(drop_rate=500, max_delay=2))
    dc = shr._cfg_to_dict(classic)
    assert "edges" not in dc["faults"]
    assert "delivery_cut" not in dc["faults"]
    _FAULTS.check(dc["faults"], "cfg.faults")
    assert shr._cfg_from_dict(dc) == classic


def test_shrink_geo_moves_stubbed(monkeypatch):
    """The geo shrink moves through a stubbed judge (fast-tier
    coverage for the slow envelope-backed collapse cell): with every
    candidate 'still failing', the greedy descent must drop the gray
    episode, collapse the edge matrix, and zero delivery_cut —
    without ever building an illegal config (the max_delay-zeroing
    guard under a live matrix)."""
    sched = flt.FaultSchedule((flt.gray(2, 10, 1, delay=4),))
    geo = _cfg(FaultConfig(
        max_delay=4, schedule=sched, delivery_cut=True,
        edges=EdgeFaultConfig.uniform(3, drop_rate=200, max_delay=1),
    ), seed=5)
    case = shr.ReproCase(
        cfg=geo, workload=simm.default_workload(geo), gates=None,
        chains=[],
    )
    monkeypatch.setattr(shr, "run_case", lambda c: (None, "stub-viol"))
    monkeypatch.setattr(shr, "_runtime_candidate_eval", lambda c: None)
    monkeypatch.setattr(shr, "_runtime_batch_eval", lambda c: None)
    small, viol = shr.shrink_case(case)
    assert viol == "stub-viol"
    assert small.cfg.faults.schedule is None  # gray episode dropped
    assert small.cfg.faults.edges is None  # matrix collapsed
    assert small.cfg.faults.delivery_cut is False
    assert small.cfg.seed == 0


# ---------------- named rejections ----------------


def test_membership_rejects_gray_by_name():
    from tpu_paxos.membership import engine as meng

    sched = flt.FaultSchedule((flt.gray(0, 4, 1, delay=2),))
    with pytest.raises(ValueError, match="gray"):
        meng._check_member_schedule(sched)


def test_mc_scope_rejects_gray_by_name():
    from tpu_paxos.analysis import modelcheck as mc

    base = {
        "n_nodes": 3, "proposers": 1, "horizon": 8, "max_rounds": 64,
        "intervals": [[0, 4]], "kinds": ["pause", "gray"],
        "pause_set_sizes": [1],
    }
    with pytest.raises(mc.ScopeError, match="gray"):
        mc.McScope.from_dict(base)
