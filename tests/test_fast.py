"""Fast fault-free engine: phase semantics + whole-run invariants.

Semantics under test mirror the reference acceptor/proposer rules:
strict-> promise (multi/paxos.cpp:865), >= accept (1366), max-ballot
adoption (1201-1223), quorum n//2+1 (1047).
"""

import jax.numpy as jnp
import numpy as np

from tpu_paxos.core import apply as apl
from tpu_paxos.core import ballot as bal
from tpu_paxos.core import fast
from tpu_paxos.core import values as val
from tpu_paxos.harness import validate


def test_choose_all_basic():
    n_inst, n_nodes = 100, 3
    state = fast.init_state(n_inst, n_nodes)
    vids = jnp.arange(n_inst, dtype=jnp.int32)
    state, n_chosen = fast.choose_all(state, vids, proposer=0, quorum=2)
    assert int(n_chosen) == n_inst
    learned = fast.learned_ia(state)  # [I, A] host view
    validate.check_all(learned, expected_vids=np.arange(n_inst))
    # Every node learned every instance; frontier = I everywhere.
    assert np.asarray(apl.frontiers(learned)).tolist() == [n_inst] * n_nodes


def test_promise_is_strict():
    state = fast.init_state(4, 3)
    b = bal.make(1, 0)
    state, prepared, _, _ = fast.phase1_prepare(state, b, quorum=2)
    assert bool(prepared)
    # Same ballot again: no acceptor promises (strict >), quorum fails.
    _, prepared2, _, _ = fast.phase1_prepare(state, b, quorum=2)
    assert not bool(prepared2)


def test_accept_is_geq():
    state = fast.init_state(4, 3)
    b = bal.make(1, 0)
    state, _, _, _ = fast.phase1_prepare(state, b, quorum=2)
    # Accept with the same promised ballot succeeds (>=).
    vids = jnp.arange(4, dtype=jnp.int32)
    state, chosen = fast.phase2_accept(state, b, vids, quorum=2)
    assert bool(chosen)
    # Lower ballot is rejected by all.
    lower = bal.make(0, 5)
    _, chosen2 = fast.phase2_accept(state, lower, vids, quorum=2)
    assert not bool(chosen2)


def test_adoption_max_ballot_wins():
    n_inst, n_nodes = 3, 3
    state = fast.init_state(n_inst, n_nodes)
    # Acceptor 0 accepted vid 7 at ballot (1,0); acceptor 1 accepted
    # vid 9 at the higher ballot (2,1) for instance 0.
    acc_ballot = np.full((n_nodes, n_inst), int(bal.NONE), np.int32)
    acc_vid = np.full((n_nodes, n_inst), int(val.NONE), np.int32)
    acc_ballot[0, 0], acc_vid[0, 0] = int(bal.make(1, 0)), 7  # [node, inst]
    acc_ballot[1, 0], acc_vid[1, 0] = int(bal.make(2, 1)), 9
    state = state._replace(
        acc_ballot=jnp.asarray(acc_ballot), acc_vid=jnp.asarray(acc_vid)
    )
    b = bal.make(3, 2)
    _, prepared, adopted_ballot, adopted_vid = fast.phase1_prepare(
        state, b, quorum=2
    )
    assert bool(prepared)
    assert int(adopted_vid[0]) == 9  # max accepted ballot wins
    assert int(adopted_ballot[0]) == int(bal.make(2, 1))
    assert int(adopted_vid[1]) == int(val.NONE)


def test_choose_all_respects_preaccepted():
    # A value pre-accepted by one acceptor must be re-proposed by the
    # new proposer for that instance, not overwritten by its own value.
    n_inst, n_nodes = 5, 3
    state = fast.init_state(n_inst, n_nodes)
    acc_ballot = np.full((n_nodes, n_inst), int(bal.NONE), np.int32)
    acc_vid = np.full((n_nodes, n_inst), int(val.NONE), np.int32)
    acc_ballot[1, 2], acc_vid[1, 2] = int(bal.make(1, 1)), 777  # [node, inst]
    state = state._replace(
        acc_ballot=jnp.asarray(acc_ballot), acc_vid=jnp.asarray(acc_vid)
    )
    vids = jnp.arange(n_inst, dtype=jnp.int32)
    state, n_chosen = fast.choose_all(state, vids, proposer=0, quorum=2)
    assert int(n_chosen) == n_inst
    learned = fast.learned_ia(state)
    assert (learned[2] == 777).all()
    validate.check_agreement(learned)


def test_holes_leave_none():
    # Instances with no value (vid NONE) stay unchosen.
    state = fast.init_state(6, 3)
    vids = np.arange(6, dtype=np.int32)
    vids[3] = int(val.NONE)
    state, n_chosen = fast.choose_all(
        state, jnp.asarray(vids), proposer=0, quorum=2
    )
    assert int(n_chosen) == 5  # all but the hole chosen
    learned = fast.learned_ia(state)
    assert (learned[3] == int(val.NONE)).all()
    # Frontier stops at the hole.
    assert np.asarray(apl.frontiers(learned)).tolist() == [3, 3, 3]


def test_validate_catches_disagreement():
    learned = np.zeros((4, 3), np.int32)
    learned[:, :] = np.arange(4)[:, None]
    learned[2, 1] = 99
    try:
        validate.check_agreement(learned)
    except validate.InvariantViolation:
        pass
    else:
        raise AssertionError("disagreement not caught")


def test_validate_catches_duplicate():
    learned = np.zeros((4, 3), np.int32)
    learned[:, :] = np.array([0, 1, 1, 3])[:, None]
    try:
        validate.check_exactly_once(learned)
    except validate.InvariantViolation:
        pass
    else:
        raise AssertionError("duplicate not caught")
