"""Runtime i.i.d. fault knobs (core/net.FaultKnobs) and the
one-executable stress envelope (fleet/envelope.py).

The contract under test: an engine built with ``runtime_knobs=True``
(knobs as traced scalars, always-on masked sampling) is decision-log
IDENTICAL to the compile-time engine per (cfg, schedule, seed) — over
a knob grid spanning all-zero knobs, the reference debug.conf rates,
``max_delay`` at the envelope's ring edge, and a crash+pause mix —
and the envelope cache hands every caller of one envelope the same
compiled executable, so distinct knob mixes, schedules, and shrink
candidates cost dispatches, not compiles.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_paxos.analysis import tracecount
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as flt
from tpu_paxos.core import net as netm
from tpu_paxos.core import sim as simm
from tpu_paxos.fleet import envelope as env
from tpu_paxos.replay.decision_log import decision_log
from tpu_paxos.utils import prng

WL = [np.arange(100, 108, dtype=np.int32),
      np.arange(200, 208, dtype=np.int32)]

SCHED = flt.FaultSchedule((
    flt.partition(4, 16, (0, 1), (2, 3, 4)),
    flt.pause(6, 14, 2),
    flt.burst(5, 12, 1500),
))


def _cfg(n_nodes, fkw, seed=3, max_rounds=4000):
    return SimConfig(
        n_nodes=n_nodes, n_instances=48, proposers=(0, 1), seed=seed,
        max_rounds=max_rounds, faults=FaultConfig(**fkw),
    )


def _log_sha(r):
    stride = int(max(int(np.max(w)) for w in WL)) + 1
    text = decision_log(
        r.chosen_vid, r.chosen_ballot, stride=stride,
        n_instances=len(r.chosen_vid),
    )
    return hashlib.sha256(text.encode()).hexdigest()


def _assert_knob_parity(cfg):
    """Static single-run vs a 1-lane dispatch of the shared envelope
    runner: same decision-log sha256 AND bit-identical result arrays
    for the same (cfg, schedule, seed)."""
    a = simm.run(cfg, WL)
    runner = env.runner_for(cfg, WL)
    fc = cfg.faults
    rep = runner.run(
        [cfg.seed], [fc.schedule],
        workloads=[(WL, None)],
        knobs=[dataclasses.replace(fc, schedule=None)],
    )
    b = rep.lane_result(0)
    assert a.rounds == b.rounds, (a.rounds, b.rounds)
    assert _log_sha(a) == _log_sha(b)
    assert (a.chosen_vid == b.chosen_vid).all()
    assert (a.chosen_round == b.chosen_round).all()
    assert (a.learned == b.learned).all()
    assert (a.crashed == b.crashed).all()
    assert a.done == b.done
    # the lane round-trips to the exact single-run config it mirrors
    # (knobs and schedule baked back over the envelope-normalized base)
    assert rep.lane_cfg(0) == cfg
    return rep


# ---------------- copy_plan: the sampling layer ----------------


def test_copy_plan_knob_parity():
    """The always-on masked forms sample bit-identically to the
    static branches for equal knob values — including zero knobs
    (elided branches) and burst extra_drop composition."""
    key = prng.stream(prng.root_key(9), prng.STREAM_NET_DROP, 5)
    shape = (2, 5)
    cells = [
        FaultConfig(),
        FaultConfig(drop_rate=500),
        FaultConfig(dup_rate=1000),
        FaultConfig(min_delay=1, max_delay=4),
        FaultConfig(drop_rate=500, dup_rate=1000, min_delay=0, max_delay=2),
    ]
    for fc in cells:
        for extra in (None, jnp.int32(1500)):
            al_s, dl_s = netm.copy_plan(key, shape, fc, extra_drop=extra)
            al_k, dl_k = netm.copy_plan(
                key, shape, fc, extra_drop=extra,
                knobs=jax.tree.map(jnp.asarray, netm.knobs_from_faults(fc)),
            )
            assert (np.asarray(al_s) == np.asarray(al_k)).all(), fc
            assert (np.asarray(dl_s) == np.asarray(dl_k)).all(), fc


def test_runtime_knobs_round_fn_requires_knobs():
    cfg = _cfg(3, dict(max_delay=2))
    pend, gate, tail, c = simm.prepare_queues(cfg, WL)
    rf = simm.build_engine(
        cfg, c, vid_cap=0, runtime_schedule=True, runtime_knobs=True
    )
    root = prng.root_key(0)
    st = simm.init_state(cfg, pend, gate, tail, root)
    from tpu_paxos.fleet import schedule_table as stm

    tab = jax.tree.map(jnp.asarray, stm.encode_schedule(None, cfg.n_nodes, 1))
    with pytest.raises(TypeError, match="FaultKnobs"):
        rf(root, st, tab)
    with pytest.raises(TypeError, match="ScheduleTable"):
        rf(root, st, None)


# ---------------- decision-log parity grid ----------------


def test_knob_parity_zero_and_debugconf():
    """Fast grid cells: all-zero knobs and the reference debug.conf
    rates (drop 500 / dup 1000 / delay 2), 3-node geometry.  Both
    cells ride ONE cached envelope executable (the second pays no
    compile — pinned below by the census delta)."""
    census = tracecount.CompileCensus().start()
    _assert_knob_parity(_cfg(3, dict()))
    before = census.engine_counts.get("fleet", 0)
    _assert_knob_parity(
        _cfg(3, dict(drop_rate=500, dup_rate=1000, max_delay=2))
    )
    census.stop()
    assert census.engine_counts.get("fleet", 0) == before, (
        "second knob cell recompiled the fleet executable — the "
        "envelope cache should have served the first cell's"
    )


@pytest.mark.slow
def test_knob_parity_envelope_edge_and_crash_pause():
    """Heavy grid cells, 5-node geometry: ``max_delay`` at the
    envelope's ring edge (the bound itself), and a crash+pause mix
    over a schedule with all three mask dimensions."""
    _assert_knob_parity(
        _cfg(5, dict(drop_rate=200, dup_rate=200, min_delay=2,
                     max_delay=env.MAX_DELAY_BOUND))
    )
    _assert_knob_parity(
        _cfg(5, dict(drop_rate=500, dup_rate=1000, max_delay=2,
                     crash_rate=3000, schedule=SCHED))
    )


# ---------------- envelope cache ----------------


def test_envelope_cache_identity_and_keying():
    cfg = _cfg(3, dict(max_delay=2))
    r1 = env.runner_for(cfg, WL)
    # different knob mix, same envelope -> same compiled runner
    r2 = env.runner_for(
        _cfg(3, dict(drop_rate=2000, dup_rate=500, max_delay=4))
    , WL)
    assert r1 is r2
    # the cached runner is knob-normalized to the envelope
    assert r1.cfg.faults.schedule is None
    assert r1.cfg.faults.max_delay == env.MAX_DELAY_BOUND
    # geometry / budget / ring-bound changes are different envelopes
    assert env.runner_for(
        _cfg(3, dict(max_delay=2), max_rounds=2000), WL
    ) is not r1
    assert env.runner_for(cfg, WL, delay_bound=12) is not r1
    # a cfg whose max_delay exceeds the requested bound is rejected
    with pytest.raises(ValueError, match="delay bound"):
        env.runner_for(_cfg(3, dict(max_delay=6)), WL, delay_bound=4)


def test_runner_knob_validation():
    runner = env.runner_for(_cfg(3, dict(max_delay=2)), WL)
    wl1 = [(WL, None)]
    # cache-shared runners REJECT implicit inputs: the cached
    # template's queue order and base knobs belong to whichever
    # caller warmed the cache (the cache normalizes knobs to zero,
    # so run(knobs=None) would silently drop all faults)
    with pytest.raises(ValueError, match="envelope cache"):
        runner.run([0], [None], workloads=wl1)
    with pytest.raises(ValueError, match="envelope cache"):
        runner.run([0], [None], knobs=[FaultConfig()])
    with pytest.raises(ValueError, match="one knob set per lane"):
        runner.run([0, 1], [None, None], workloads=wl1 * 2,
                   knobs=[FaultConfig()])
    with pytest.raises(ValueError, match="ring bound"):
        runner.run([0], [None], workloads=wl1,
                   knobs=[FaultConfig(max_delay=12)])
    with pytest.raises(ValueError, match="schedule"):
        runner.run(
            [0], [None], workloads=wl1,
            knobs=[FaultConfig(schedule=flt.FaultSchedule(
                (flt.burst(1, 3, 500),)
            ))],
        )
    with pytest.raises(TypeError, match="FaultConfig or FaultKnobs"):
        runner.run([0], [None], workloads=wl1, knobs=[{"drop_rate": 5}])


@pytest.mark.slow
def test_per_lane_vid_sets_are_runtime():
    """Per-lane workloads may change the vid SET and the owner map —
    the verdict's expected/owner tables are runtime inputs now (the
    PR-4 guard is gone); only the envelope's vid bound and table
    shapes are static.

    Slow-tier: a 3-lane envelope compile (~30 s).  Fast-tier coverage
    of runtime per-lane workload/verdict tables: the model checker's
    tiny-scope e2e (tests/test_modelcheck.py) dispatches per-lane
    workloads + gate toggles + expected/owner tables through the
    shared envelope every tier-1 run, and the vid-bound/table-width
    rejections have their own validation-only cells
    (tests/test_fleet.py's lane-table guards)."""
    runner = env.runner_for(_cfg(3, dict(max_delay=2)), WL)
    # swap a value between proposers (old guard's "owner" rejection)
    swapped = [w.copy() for w in WL]
    swapped[0][0], swapped[1][0] = WL[1][0], WL[0][0]
    # shifted vid set inside the bound (old guard's "set" rejection)
    shifted = [WL[0] + 1, WL[1][:-1]]
    rep = runner.run(
        [0, 1, 2], [None] * 3,
        workloads=[(WL, None), (swapped, None), (shifted, None)],
        knobs=[FaultConfig(drop_rate=300, max_delay=2)] * 3,
    )
    assert rep.verdict.ok.all(), rep.verdict
    # each lane is judged against ITS OWN expected set
    assert (rep.expected_lanes[1] == np.unique(np.concatenate(swapped))).all()
    assert (rep.expected_lanes[2] == np.unique(np.concatenate(shifted))).all()
    got = np.sort(rep.lane_result(2).chosen_vid)
    for v in np.unique(np.concatenate(shifted)):
        assert v in got
    # vids past the envelope's bound stay rejected
    with pytest.raises(ValueError, match="vid bound"):
        runner.run(
            [0], [None], workloads=[([WL[0], WL[1] + 700], None)],
            knobs=[FaultConfig()],
        )


# ---------------- shrink rides the envelope ----------------


@pytest.mark.slow
def test_shrink_candidate_eval_matches_run_case():
    """The runtime-knob candidate evaluator and the compile-time
    ``run_case`` agree verdict-for-verdict (green case, failing case,
    knob-zeroed candidate), and successive candidates add ZERO fleet
    compiles — the greedy descent rides one executable.  Slow tier:
    it runs both judges end to end (~45 s); the envelope-reuse census
    pin stays fast-tier in test_knob_parity_zero_and_debugconf, and
    shrink-vs-run_case agreement is re-verified on every triage
    anyway (save_artifact re-judges on the compile-time path)."""
    from tpu_paxos.harness import shrink as shr

    sched = flt.FaultSchedule((flt.partition(5, 35, (0, 1), (2, 3, 4)),))
    cfg = SimConfig(
        n_nodes=5, n_instances=64, proposers=(0, 1), seed=7,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=300, dup_rate=500, max_delay=2,
                           schedule=sched),
    )
    case = shr.ReproCase(
        cfg=cfg, workload=WL, gates=None,
        chains=[np.zeros(0, np.int32)] * 2,
        extra_checks={"decision_round_max": 25},
    )
    ev = shr._runtime_candidate_eval(case)
    assert ev is not None
    _, viol = shr.run_case(case)
    assert viol and "decision_round_max" in viol
    assert ev(case) == viol
    census = tracecount.CompileCensus().start()
    # knob-zeroed and schedule-dropped candidates: same executable
    zeroed = case.with_faults(
        dataclasses.replace(cfg.faults, drop_rate=0, dup_rate=0)
    )
    healed = case.with_schedule(None)
    _, v_zero = shr.run_case(zeroed)
    _, v_heal = shr.run_case(healed)
    assert ev(zeroed) == v_zero
    assert ev(healed) == v_heal
    census.stop()
    assert census.engine_counts.get("fleet", 0) == 0, (
        "shrink candidates recompiled the fleet executable"
    )
    # sharded cases stay on the compile-time path
    assert shr._runtime_candidate_eval(
        dataclasses.replace(case, engine="sharded", devices=2)
    ) is None
