"""Repro artifacts for the SHARDED engine (ROADMAP follow-on): a
failing sharded case saves with engine="sharded" + its device count,
re-executes through parallel/sharded_sim.py, and the CLI provisions
the recorded mesh before replaying byte-identically."""

import json
import subprocess
import sys

import numpy as np
import pytest

from tpu_paxos.analysis.artifact_schema import ArtifactSchemaError
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as flt
from tpu_paxos.harness import shrink as shr

DEVICES = 2  # of the conftest-provisioned 8 virtual CPU devices


def _sharded_case(extra_checks, seed=7):
    sched = flt.FaultSchedule((flt.partition(4, 24, (0,), (1, 2)),))
    wl = [np.arange(100, 108, dtype=np.int32),
          np.arange(200, 208, dtype=np.int32)]
    cfg = SimConfig(
        n_nodes=3, n_instances=64, proposers=(0, 1), seed=seed,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=300, dup_rate=500, max_delay=2,
                           schedule=sched),
    )
    return shr.ReproCase(
        cfg=cfg, workload=wl, gates=None,
        chains=[np.zeros(0, np.int32)] * 2,
        extra_checks=extra_checks, engine="sharded", devices=DEVICES,
    )


def test_sharded_artifact_roundtrip_and_reproduce(tmp_path):
    case = _sharded_case({"decision_round_max": 25})
    _, viol = shr.run_case(case)
    assert viol and "decision_round_max" in viol
    path = str(tmp_path / "repro_sharded.json")
    art = shr.save_artifact(path, case, viol)
    assert art["engine"] == "sharded" and art["devices"] == DEVICES
    loaded, art2 = shr.load_artifact(path)
    assert loaded.engine == "sharded" and loaded.devices == DEVICES
    rep = shr.reproduce(path)
    assert rep["match"], rep
    # schema: the engine selector and device count are validated at
    # load (reusing this artifact — no extra engine runs)
    bad_art = json.loads(open(path).read())
    bad_art["engine"] = "warp-drive"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(bad_art))
    with pytest.raises(ArtifactSchemaError, match="engine"):
        shr.load_artifact(str(bad))
    bad_art["engine"] = "sharded"
    bad_art["devices"] = 0
    bad.write_text(json.dumps(bad_art))
    with pytest.raises(ArtifactSchemaError, match="devices"):
        shr.load_artifact(str(bad))


@pytest.mark.slow
def test_sharded_and_unsharded_runs_differ_only_in_placement(tmp_path):
    """The sharded engine's decision log legitimately differs from the
    unsharded one's (shard-local first-fit placement) — which is WHY
    the artifact records its engine: replaying a sharded artifact
    through core/sim would not byte-compare."""
    case = _sharded_case({})
    r_sh, v_sh = shr.run_case(case)
    r_un, v_un = shr.run_case(
        shr.ReproCase(
            cfg=case.cfg, workload=case.workload, gates=None,
            chains=case.chains,
        )
    )
    assert v_sh is None and v_un is None  # both green on the suite
    chosen_sh = np.sort(r_sh.chosen_vid[r_sh.chosen_vid >= 0])
    chosen_un = np.sort(r_un.chosen_vid[r_un.chosen_vid >= 0])
    # same chosen multiset, placement-independent
    assert (chosen_sh == chosen_un).all()


@pytest.mark.slow
def test_sharded_artifact_cli_repro(tmp_path):
    """End to end: `python -m tpu_paxos repro` must provision the
    recorded device count itself (fresh process, no conftest mesh)."""
    case = _sharded_case({"decision_round_max": 25})
    _, viol = shr.run_case(case)
    path = str(tmp_path / "repro_sharded.json")
    shr.save_artifact(path, case, viol)
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "repro", path, "--json",
         "--backend", "cpu"],
        capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["ok"] and out["match"], out
