"""Checkpoint/resume equivalence — a capability the reference lacks
(it has no persistence at all; SURVEY.md §5): a run interrupted at
round k, saved, restored into a fresh state skeleton, and continued
must produce a byte-identical outcome to the uninterrupted run."""

import numpy as np
import pytest

from tpu_paxos import checkpoint
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim
from tpu_paxos.membership import MemberSim
from tpu_paxos.utils import prng

import jax


def _setup(cfg):
    workload = sim.default_workload(cfg)
    pend, gate, tail, c = sim.prepare_queues(cfg, workload)
    root = prng.root_key(cfg.seed)
    state = sim.init_state(cfg, pend, gate, tail, root)
    expected = np.unique(np.concatenate([np.asarray(w) for w in workload]))
    return workload, pend, gate, tail, c, root, state, expected


@pytest.mark.slow
def test_resume_equivalence_mid_run(tmp_path):
    cfg = SimConfig(
        n_nodes=5,
        n_instances=64,
        proposers=(0, 1),
        seed=7,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    _, pend, gate, tail, c, root, state, expected = _setup(cfg)
    round_fn = sim.build_engine(cfg, c)
    step = jax.jit(lambda s: round_fn(root, s))
    for _ in range(12):  # interrupt mid-protocol, well before quiescence
        state = step(state)
    assert not bool(state.done)

    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, state, {"seed": cfg.seed, "round": int(state.t)})

    # uninterrupted continuation
    full = sim.run_state(cfg, state, root, expected, c)

    # restore into a fresh structural skeleton and continue
    like = sim.init_state(cfg, pend, gate, tail, root)
    restored, meta = checkpoint.restore(path, like)
    assert meta["round"] == 12
    resumed = sim.run_state(cfg, restored, root, expected, c)

    assert resumed.done and full.done
    assert np.array_equal(resumed.chosen_vid, full.chosen_vid)
    assert np.array_equal(resumed.chosen_round, full.chosen_round)
    assert np.array_equal(resumed.chosen_ballot, full.chosen_ballot)
    assert np.array_equal(resumed.learned, full.learned)
    assert resumed.rounds == full.rounds

    # and both equal the never-interrupted from-scratch run
    scratch = sim.run(cfg)
    assert np.array_equal(resumed.chosen_vid, scratch.chosen_vid)
    assert np.array_equal(resumed.learned, scratch.learned)


def test_restore_refuses_mismatched_config(tmp_path):
    cfg = SimConfig(n_nodes=3, n_instances=32, proposers=(0,), seed=0)
    _, pend, gate, tail, c, root, state, _ = _setup(cfg)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, state)

    other = SimConfig(n_nodes=5, n_instances=32, proposers=(0,), seed=0)
    _, p2, g2, t2, c2, r2, like, _ = _setup(other)
    with pytest.raises(ValueError, match="wrong config"):
        checkpoint.restore(path, like)


def test_member_state_roundtrip_mid_churn(tmp_path):
    """Membership engine state checkpoints the same way (it is just a
    pytree); a restored sim continues the churn to completion."""
    ms = MemberSim(n_nodes=3, n_instances=32, seed=0)
    cv = ms.add_acceptor(1)
    ms.run_rounds(2)  # change in flight, not yet applied

    path = str(tmp_path / "member.npz")
    checkpoint.save(path, ms.state)

    ms2 = MemberSim(n_nodes=3, n_instances=32, seed=0)
    ms2.state, _ = checkpoint.restore(path, ms2.state)
    assert ms2.run_until(lambda: ms2.applied(cv), max_rounds=400)
    assert ms2.acceptor_set(0) == {0, 1}


def test_checkpoint_carries_format_version(tmp_path):
    """Every checkpoint records the format string; a stale-format file
    is named as such in the mismatch error (distinguishable from a
    wrong geometry), and an unversioned one is called out too
    (ADVICE round 5)."""
    import json

    import numpy as np

    cfg = SimConfig(n_nodes=3, n_instances=32, proposers=(0,), seed=0)
    _, pend, gate, tail, c, root, state, _ = _setup(cfg)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, state, meta={"k": 1})
    restored, meta = checkpoint.restore(path, state)
    assert meta["format"] == checkpoint.FORMAT and meta["k"] == 1

    # forge a checkpoint from a different format era with a different
    # leaf set: the error must name both format strings
    with np.load(path) as z:
        payload = {k: z[k] for k in z.files if k.startswith("leaf_")}
    payload.pop("leaf_0")
    payload["tpu_paxos_meta"] = np.frombuffer(
        json.dumps({"format": "tpu-paxos-ckpt-v1"}).encode(), dtype=np.uint8
    )
    stale = str(tmp_path / "stale.npz")
    np.savez(stale, **payload)
    with pytest.raises(ValueError, match="tpu-paxos-ckpt-v1.*!= current"):
        checkpoint.restore(stale, state)

    # unversioned (pre-format) checkpoints are named explicitly
    payload["tpu_paxos_meta"] = np.frombuffer(
        json.dumps({}).encode(), dtype=np.uint8
    )
    unver = str(tmp_path / "unversioned.npz")
    np.savez(unver, **payload)
    with pytest.raises(ValueError, match="unversioned"):
        checkpoint.restore(unver, state)
