"""The windowed time-series plane (telemetry/recorder windows): the
host-side reducers on crafted ``[lanes, W]`` stacks, the on-device
window reduction's edge cases (runs shorter than one bucket, rounds
landing exactly on a bucket boundary, overflow clamping), the SLO
burn-rate arithmetic, and the windowed Perfetto counter tracks.

Engine-level neutrality and the serve-side breach pins live with
their subsystems (tests/test_telemetry.py, tests/test_serve.py);
everything here is host arithmetic plus tiny eager jnp ops — no
engine compiles.
"""

import types

import numpy as np

from tpu_paxos.serve import harness as sh
from tpu_paxos.telemetry import export as texport
from tpu_paxos.telemetry import recorder as telem

W = telem.NUM_WINDOWS
B = telem.NUM_LAT_BUCKETS


N_NODES = 3  # crafted-stack node count for the per-node rings


def _mk_windows(**over):
    """A host-numpy WindowSummary with recognizable values."""
    lat = np.zeros((W, B), np.int32)
    lat[0, 1] = 4  # bucket (1, 2]
    lat[2, 4] = 6  # bucket (8, 16]
    phase = np.zeros((W, telem.NUM_PHASES, B), np.int32)
    phase[:, telem.PHASE_CONSENSUS, :] = lat  # closed-loop shape
    base = dict(
        offered=np.asarray([100] + [10] * (W - 1), np.int32),
        dropped=np.asarray([10] + [1] * (W - 1), np.int32),
        duped=np.full(W, 2, np.int32),
        delayed=np.full(W, 3, np.int32),
        stall_max=np.asarray([0, 5] + [1] * (W - 2), np.int32),
        takeovers=np.asarray([0, 1] + [0] * (W - 2), np.int32),
        restarts=np.asarray([2] + [0] * (W - 1), np.int32),
        cut=np.zeros(W, np.int32),
        backlog_max=np.asarray([3] + [0] * (W - 1), np.int32),
        node_offered=np.full((W, N_NODES), 10, np.int32),
        node_delay=np.zeros((W, N_NODES), np.int32),
        decided=lat.sum(axis=1).astype(np.int32),
        lat_hist=lat,
        phase_hist=phase,
    )
    base.update(over)
    return telem.WindowSummary(**base)


# ---------------- host-side reducers ----------------


def test_windows_to_dict():
    d = telem.windows_to_dict(_mk_windows(), 16, lat_max=14)
    assert d["window_rounds"] == 16 and d["n_windows"] == W
    assert d["decided"][0] == 4 and d["decided"][2] == 6
    assert sum(d["decided"]) == 10
    assert d["offered"][0] == 100 and d["dropped"][0] == 10
    assert d["drop_rate_observed"][0] == 1000.0
    assert d["stall_max"][1] == 5 and d["takeovers"][1] == 1
    # per-bucket quantiles: bucket edges clamped to the run max;
    # empty buckets report -1
    assert d["latency_p50"][0] == 2 and d["latency_p99"][0] == 2
    assert d["latency_p50"][2] == 14  # edge 16 clamped to lat_max 14
    assert d["latency_p50"][1] == -1 and d["latency_p99"][1] == -1
    assert d["lat_hist"][2][4] == 6
    assert d["latency_edges"] == list(telem.LAT_EDGES)


def test_reduce_lanes_windows_on_crafted_stack():
    """[lanes, W] reduction: counts sum, stall depth maxes, and the
    quantiles walk the lane-summed per-bucket histograms."""
    import jax

    lane2_lat = np.zeros((W, B), np.int32)
    lane2_lat[2, 6] = 2  # bucket (32, 64] — stretches bucket 2's p99
    lanes = jax.tree.map(
        lambda *xs: np.stack(xs),
        _mk_windows(),
        _mk_windows(
            stall_max=np.asarray([7] + [0] * (W - 1), np.int32),
            lat_hist=lane2_lat,
            decided=lane2_lat.sum(axis=1).astype(np.int32),
        ),
    )
    d = telem.reduce_lanes_windows(lanes, 16, lat_max=40)
    assert d["decided"][0] == 4 and d["decided"][2] == 8
    assert d["offered"][0] == 200
    assert d["stall_max"][0] == 7 and d["stall_max"][1] == 5
    assert d["latency_p50"][2] == 16  # 6 of 8 at (8, 16]
    assert d["latency_p99"][2] == 40  # lane 2's (32, 64] tail, clamped
    # the margin series is min over lanes of (patience - stall):
    # bucket 0 is lane 2's 7-deep stall, bucket 1 lane 1's 5-deep
    m = telem.stall_margin_series(lanes, patience=8)
    assert m[0] == 1 and m[1] == 3 and m[2] == 7
    # single-lane form: no lane axis
    assert telem.stall_margin_series(_mk_windows(), 8)[1] == 3


def test_summary_and_reduce_lanes_windows_integration():
    """summary_to_dict / reduce_lanes grow the windows block only
    when a WindowSummary rides along (additive schema)."""
    import jax

    base = dict(
        msgs=np.arange(7, dtype=np.int32),
        offered=np.full(7, 100, np.int32),
        dropped=np.full(7, 5, np.int32),
        duped=np.full(7, 2, np.int32),
        delayed=np.full(7, 3, np.int32),
        learns=np.int32(48), commit_acks=np.int32(9),
        takeovers=np.int32(1), requeues=np.int32(4),
        restarts=np.int32(2), decided=np.int32(16),
        lat_hist=np.asarray([0, 8, 0, 8, 0, 0, 0, 0, 0, 0], np.int32),
        lat_max=np.int32(5), heal_gap=np.int32(24),
        stall_max=np.int32(3), duel_max=np.int32(4),
        takeover_round=np.asarray([7, -1], np.int32),
        rounds=np.int32(34), quiescent=np.bool_(True),
        region_offered=np.zeros(
            (telem.NUM_REGIONS, telem.NUM_REGIONS), np.int32
        ),
        region_dropped=np.zeros(
            (telem.NUM_REGIONS, telem.NUM_REGIONS), np.int32
        ),
        region_cut=np.zeros(
            (telem.NUM_REGIONS, telem.NUM_REGIONS), np.int32
        ),
    )
    s = telem.TelemetrySummary(**base)
    assert "windows" not in telem.summary_to_dict(s)
    d = telem.summary_to_dict(s, _mk_windows(), 16)
    assert d["windows"]["window_rounds"] == 16
    stack = jax.tree.map(lambda *xs: np.stack(xs), s, s)
    wstack = jax.tree.map(
        lambda *xs: np.stack(xs), _mk_windows(), _mk_windows()
    )
    assert "windows" not in telem.reduce_lanes(stack)
    agg = telem.reduce_lanes(stack, wstack, 16)
    assert agg["windows"]["decided"][0] == 8
    # the stress block and the search margins ride the same seam;
    # reports without a windows stack stay schema-compatible
    from tpu_paxos.fleet import search as fsearch
    from tpu_paxos.harness import stress

    from tpu_paxos.config import FaultConfig, SimConfig

    cfg = SimConfig(n_nodes=3, proposers=(0, 1), n_instances=16,
                    faults=FaultConfig(drop_rate=450))
    rep = types.SimpleNamespace(telemetry=stack, windows=wstack)
    blk = stress._mix_telemetry(rep, cfg)
    assert blk["windows"]["decided"] == agg["windows"]["decided"]
    mar = fsearch._generation_margins(rep)
    assert mar["stall_margin_series"][1] == 3  # patience 8 - stall 5
    assert mar["latency_p99_series"] == agg["windows"]["latency_p99"]
    bare = types.SimpleNamespace(telemetry=stack, windows=None)
    assert "windows" not in stress._mix_telemetry(bare, cfg)
    assert "stall_margin_series" not in fsearch._generation_margins(bare)


# ---------------- on-device reduction edge cases ----------------


def test_window_bucket_boundaries():
    """Rounds landing exactly on a bucket boundary open the NEXT
    bucket; everything past the grid clamps into the overflow."""
    ts = np.asarray(
        [0, 15, 16, 17, 31, 32, 16 * (W - 1) - 1, 16 * (W - 1), 10_000]
    )
    got = [int(telem.window_bucket(t, 16)) for t in ts]
    assert got == [0, 0, 1, 1, 1, 2, W - 2, W - 1, W - 1]


def test_summarize_windows_run_shorter_than_one_bucket():
    """A run that finishes inside bucket 0 puts its whole series
    there — no spill, no dilution."""
    import jax.numpy as jnp

    wins = telem.init_windows(N_NODES)
    chosen_vid = jnp.asarray([100, 101, -1, 102], jnp.int32)
    chosen_round = jnp.asarray([3, 7, -1, 9], jnp.int32)
    admit = jnp.asarray([1, 1, -1, 2], jnp.int32)
    ws = telem.summarize_windows(wins, admit, chosen_vid, chosen_round, 16)
    decided = np.asarray(ws.decided)
    assert decided[0] == 3 and decided[1:].sum() == 0
    hist = np.asarray(ws.lat_hist)
    assert hist[0].sum() == 3 and hist[1:].sum() == 0
    # latencies 2, 6, 7 -> buckets (1,2], (4,8], (4,8]
    assert hist[0][1] == 1 and hist[0][3] == 2


def test_summarize_windows_boundary_and_overflow():
    """A decision exactly ON the bucket boundary lands in the next
    bucket; decisions past the grid clamp into the overflow bucket;
    undecided instances and NONE admissions (no-op fills) never
    enter the series."""
    import jax.numpy as jnp

    wins = telem.init_windows(N_NODES)
    hi = 16 * (W + 3)  # far past the grid
    chosen_vid = jnp.asarray([100, 101, 102, -1, -3], jnp.int32)
    chosen_round = jnp.asarray([15, 16, hi, -1, 20], jnp.int32)
    #                           b0  b1  overflow    noop fill (b1)
    admit = jnp.asarray([10, 10, 10, -1, -1], jnp.int32)
    ws = telem.summarize_windows(wins, admit, chosen_vid, chosen_round, 16)
    decided = np.asarray(ws.decided)
    assert decided[0] == 1 and decided[1] == 2  # noop decides in b1
    assert decided[W - 1] == 1
    hist = np.asarray(ws.lat_hist)
    assert hist[0].sum() == 1 and hist[1].sum() == 1  # noop: no latency
    assert hist[W - 1].sum() == 1
    assert int(ws.lat_hist[W - 1].sum()) == 1
    # accumulated rings pass through untouched
    assert (np.asarray(ws.offered) == 0).all()


# ---------------- the SLO burn-rate arithmetic ----------------


def _slo_windows_dict(lat_hist, wr=32):
    return {"window_rounds": wr, "lat_hist": np.asarray(lat_hist)}


def test_slo_windows_burn_and_breach():
    hist = np.zeros((W, B), np.int64)
    hist[0, 3] = 9   # (4, 8]: good at threshold 8
    hist[0, 5] = 1   # (16, 32]: bad -> 10% in window 0
    hist[3, 3] = 2
    hist[3, 6] = 2   # 50% bad in window 3: the breach
    slo = sh.ServeSLO(latency_rounds=8, budget_milli=200)
    got = sh.slo_windows(_slo_windows_dict(hist), slo)
    assert got["latency_rounds_effective"] == 8
    assert got["decided"][0] == 10 and got["bad"][0] == 1
    assert got["burn"][0] == 0.5  # 10% of a 20% budget
    assert got["burn"][3] == 2.5
    assert got["breach_windows"] == [3]
    assert got["breach_spans"] == [[96, 128]]
    assert got["burn_max"] == 2.5 and not got["ok"]
    # run-total: 3 bad of 14 = 214.3 millis > 200 budget
    assert got["total_bad_milli"] == 214.3 and not got["total_ok"]
    # empty series: vacuously green
    clean = sh.slo_windows(
        _slo_windows_dict(np.zeros((W, B), np.int64)), slo
    )
    assert clean["ok"] and clean["total_ok"] and clean["burn_max"] == 0.0


def test_slo_threshold_quantizes_down_to_edge_grid():
    hist = np.zeros((W, B), np.int64)
    hist[0, 4] = 4  # (8, 16]
    # threshold 10 quantizes DOWN to edge 8: the (8, 16] mass is bad
    slo = sh.ServeSLO(latency_rounds=10, budget_milli=500)
    got = sh.slo_windows(_slo_windows_dict(hist), slo)
    assert got["latency_rounds_effective"] == 8
    assert got["bad"][0] == 4 and not got["ok"]
    # at 16 the same mass is good
    slo16 = sh.ServeSLO(latency_rounds=16, budget_milli=500)
    assert sh.slo_windows(_slo_windows_dict(hist), slo16)["ok"]


# ---------------- windowed Perfetto counter tracks ----------------


def test_window_counter_tracks_render():
    d = telem.windows_to_dict(_mk_windows(), 16, lat_max=14)
    evs = texport._window_counter_events(d, tele_pid=7)
    names = {e["name"] for e in evs}
    assert {"latency p50 (rounds)", "latency p99 (rounds)",
            "drop rate (/1e4)", "decided / window",
            "stall depth", "takeovers / window"} <= names
    assert all(e["ph"] == "C" and e["pid"] == 7 for e in evs)
    # counters step on the window grid, in trace time
    dec = [e for e in evs if e["name"] == "decided / window"]
    assert [e["ts"] for e in dec] == [
        w * 16 * texport.ROUND_US for w in range(W)
    ]
    # empty-bucket quantiles (-1) are skipped, not rendered as dips
    p50 = [e for e in evs if e["name"] == "latency p50 (rounds)"]
    assert len(p50) == 2  # only buckets 0 and 2 decided anything
    assert {e["args"]["latency p50 (rounds)"] for e in p50} == {2, 14}


def test_decision_cap_annotation_visible():
    """The decision-instant cap must announce itself IN the trace: a
    'dropped' instant on the decision track plus the otherData
    counts, controlled by max_decision_events."""
    from tpu_paxos.config import SimConfig

    cfg = SimConfig(n_nodes=3, proposers=(0, 1), n_instances=8)
    result = types.SimpleNamespace(
        chosen_vid=np.arange(100, 108, dtype=np.int32),
        chosen_round=np.arange(1, 9, dtype=np.int32),
        chosen_ballot=np.ones(8, np.int32),
        rounds=10, done=True,
    )
    trace = texport.chrome_trace(cfg, result, None,
                                 max_decision_events=3)
    evs = trace["traceEvents"]
    dec = [e for e in evs if e["name"].startswith("decide [")]
    assert len(dec) == 3
    drop = [e for e in evs if "decision instants dropped" in e["name"]]
    assert len(drop) == 1
    assert drop[0]["args"] == {"dropped": 5, "cap": 3}
    # the annotation sits at the LAST rendered decision's round, so
    # it marks exactly where the timeline goes dark
    assert drop[0]["ts"] == dec[-1]["ts"]
    assert trace["otherData"]["decision_events_dropped"] == 5
    assert trace["otherData"]["decision_events_cap"] == 3
    # under the cap: no annotation, zero dropped
    full = texport.chrome_trace(cfg, result, None)
    assert trace["otherData"]["decided"] == 8
    assert full["otherData"]["decision_events_dropped"] == 0
    assert not [e for e in full["traceEvents"]
                if "dropped" in e["name"]]
