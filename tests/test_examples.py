"""Every example in examples/ must run green (subprocess, CPU)."""

import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(REPO, "examples", "0*.py")))


# The heavy examples ride the slow tier to hold the tier-1 time
# budget; each one's coverage is carried fast-tier elsewhere:
# 02_faulty_run (~19s) by test_faults / test_replay,
# 04_sharded_and_checkpoint (~60-85s: sharded engine + checkpoint
# round-trip in a cold subprocess) by test_sharded / test_checkpoint /
# test_sharded_repro, and 05_crash_rejoin_replay (~9s) by
# test_crash_rejoin / test_replay.  01 and 03 keep the
# examples-run-green contract fast-tier.
_SLOW_EXAMPLES = ("02_", "04_", "05_")


@pytest.mark.parametrize(
    "path",
    [
        pytest.param(
            p,
            id=os.path.basename(p),
            marks=[pytest.mark.slow]
            if os.path.basename(p).startswith(_SLOW_EXAMPLES)
            else [],
        )
        for p in EXAMPLES
    ],
)
def test_example_runs(path):
    # The axon sitecustomize initializes the backend before env vars
    # are read, so JAX_PLATFORMS=cpu in the env is silently ignored —
    # the platform must switch through jax.config before the example's
    # first device use (same pattern as tests/conftest.py).  Without
    # this the examples ran through the device tunnel, ~10x slower.
    env = dict(os.environ)
    p = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys, runpy, jax; "
            "jax.config.update('jax_platforms', 'cpu'); "
            "runpy.run_path(sys.argv[1], run_name='__main__')",
            path,
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "green" in p.stdout or "identically" in p.stdout


def test_examples_exist():
    assert len(EXAMPLES) >= 4
