"""Every example in examples/ must run green (subprocess, CPU)."""

import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(REPO, "examples", "0*.py")))


@pytest.mark.parametrize("path", EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_runs(path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, path],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "green" in p.stdout or "identically" in p.stdout


def test_examples_exist():
    assert len(EXAMPLES) >= 4
