"""CLI surface tests: ``python -m tpu_paxos`` end-to-end in
subprocesses (backend selection must precede jax initialization, so
the CLI cannot run in-process under the test conftest's backend).

Mirrors the reference's harness contract: decision log + invariant
verdict on stdout, exit code 0 iff every invariant holds
(ref multi/main.cpp:566-573)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(*args: str, timeout: int = 420):
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_"))
    }
    # scrub the TPU-plugin path so --backend=cpu owns the platform
    import __graft_entry__ as ge

    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + ge.scrub_pythonpath(env.get("PYTHONPATH", ""))
    )
    return subprocess.run(
        [sys.executable, "-m", "tpu_paxos", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )


# Slow tier (time budget, ~23s cold subprocess): the debug.conf fault
# rates run fast-tier in test_sim.test_reference_fault_rates[0] and
# the knobs debug.conf parity cell; the CLI surface itself is covered
# fast-tier by the fast/member/sharded/json CLI tests below.
@pytest.mark.slow
def test_cli_sim_debug_conf_analog():
    # the transliterated multi/debug.conf.sample line
    p = _run(
        "4", "4", "10", "--seed=0", "--backend=cpu",
        "--net-drop-rate=500", "--net-dup-rate=1000", "--net-max-delay=2",
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "ALL INVARIANTS GREEN" in p.stdout
    # decision log lines in the reference grammar: [inst] = <ballot>(p:c)+n
    assert "] = <" in p.stdout


def test_cli_fast_engine_json():
    p = _run("3", "2", "6", "--engine=fast", "--backend=cpu", "--json")
    assert p.returncode == 0, p.stderr[-2000:]
    summary = json.loads(p.stdout.strip().splitlines()[-1])
    assert summary["ok"] and summary["engine"] == "fast"
    assert summary["chosen"] == 12


def test_cli_member_engine_json():
    p = _run("3", "2", "4", "--engine=member", "--backend=cpu", "--json")
    assert p.returncode == 0, p.stderr[-2000:]
    summary = json.loads(p.stdout.strip().splitlines()[-1])
    assert summary["ok"] and summary["engine"] == "member"
    assert "prefix_consistency" in summary["invariants"]


def test_cli_sharded_2d_mesh():
    p = _run(
        "3", "2", "6", "--backend=cpu", "--mesh=8", "--dcn-hosts=2", "--json"
    )
    assert p.returncode == 0, p.stderr[-2000:]
    summary = json.loads(p.stdout.strip().splitlines()[-1])
    assert summary["ok"]
    assert set(summary["invariants"]) >= {
        "agreement", "exactly_once", "in_order_clients", "quiescence"
    }


def test_cli_rejects_bad_fault_rate():
    p = _run("3", "2", "4", "--backend=cpu", "--net-drop-rate=20000")
    assert p.returncode != 0
    err = (p.stderr + p.stdout).lower()
    assert "drop" in err or "rate" in err


def test_cli_member_record_replay_roundtrip(tmp_path):
    """--record-injections then --replay-injections: the replay's
    decision-log hash must equal the recording run's (the reference's
    member/run.sh record/replay + diff.sh workflow)."""
    log = os.path.join(tmp_path, "inj.json")
    rec = _run(
        "3", "2", "3", "--seed=4", "--backend=cpu", "--engine=member",
        "--json", f"--record-injections={log}",
    )
    assert rec.returncode == 0, rec.stderr[-2000:]
    rec_js = json.loads(rec.stdout.strip().splitlines()[-1])
    assert rec_js["ok"] and os.path.exists(log)

    rep = _run(
        "3", "2", "3", "--backend=cpu", "--engine=member", "--json",
        f"--replay-injections={log}",
    )
    assert rep.returncode == 0, rep.stderr[-2000:]
    rep_js = json.loads(rep.stdout.strip().splitlines()[-1])
    assert (
        rep_js["decision_log_sha256"] == rec_js["decision_log_sha256"]
    ), (rec_js, rep_js)
