"""Runtime schedule-table encoding (fleet/schedule_table.py): the
table-encoded per-round masks must equal core/faults.compile_schedule's
compiled rows for every episode kind and edge case — that equality is
what makes a fleet lane decision-log-identical to a single run."""

import numpy as np
import pytest

from tpu_paxos.core import faults as flt
from tpu_paxos.fleet import schedule_table as stm
from tpu_paxos.harness import stress


def _assert_masks_match(sched, n_nodes, pad=None, extra_rounds=4):
    comp = flt.compile_schedule(sched, n_nodes)
    tab = stm.encode_schedule(sched, n_nodes, max_episodes=pad)
    horizon = comp.horizon if comp is not None else 0
    assert int(tab.horizon) == horizon
    for t in range(horizon + extra_rounds):
        reach, paused, extra, gray = stm.masks_at(tab, t)
        if comp is None:
            assert np.asarray(reach).all()
            assert not np.asarray(paused).any()
            assert int(extra) == 0
            assert not np.asarray(gray).any()
            continue
        tt = min(t, horizon)
        assert (np.asarray(reach) == comp.reach[tt]).all(), f"reach @ t={t}"
        assert (np.asarray(paused) == comp.paused[tt]).all(), f"paused @ t={t}"
        assert int(extra) == int(comp.extra_drop[tt]), f"extra @ t={t}"
        assert (np.asarray(gray) == comp.gray[tt]).all(), f"gray @ t={t}"


@pytest.mark.parametrize(
    "sched",
    [
        stress.SCHED_PARTITION_FLAP,
        stress.SCHED_ONE_WAY,
        stress.SCHED_PAUSE_HEAVY,
        stress.SCHED_PAUSE_CRASH,
    ],
    ids=["partition-flap", "one-way", "pause-heavy", "pause-crash"],
)
def test_stress_mix_schedules_match_compiled_tables(sched):
    _assert_masks_match(sched, 5)


def test_every_kind_with_padding():
    sched = flt.FaultSchedule((
        flt.partition(2, 9, (0, 1), (2,)),
        flt.one_way(3, 12, (0, 4), (1,)),
        flt.pause(1, 7, 3),
        flt.burst(4, 10, 2500),
        flt.gray(3, 11, 2, delay=2),
    ))
    _assert_masks_match(sched, 5)
    # a larger episode capacity pads with never-active slots — masks
    # unchanged
    _assert_masks_match(sched, 5, pad=8)


def test_overlapping_gray_inflations_add():
    sched = flt.FaultSchedule((
        flt.gray(0, 10, 1, delay=2),
        flt.gray(5, 15, 1, 2, delay=3),
    ))
    _assert_masks_match(sched, 3)
    tab = stm.encode_schedule(sched, 3)
    _, _, _, gray = stm.masks_at(tab, 7)
    # node 1 carries both episodes (2 + 3), node 2 only the second
    assert np.asarray(gray).tolist() == [0, 5, 3]


def test_empty_schedule_is_all_clear():
    _assert_masks_match(None, 5)
    _assert_masks_match(flt.FaultSchedule(()), 3)
    tab = stm.encode_schedule(None, 3)
    assert int(tab.horizon) == 0
    assert tab.t0.shape == (1,)  # min capacity 1 so batches stack


def test_touching_intervals():
    """Back-to-back episodes over [0,5) and [5,10): round 5 must read
    the first healed and the second active — half-open semantics."""
    sched = flt.FaultSchedule((
        flt.partition(0, 5, (0,), (1, 2)),
        flt.partition(5, 10, (0, 1), (2,)),
    ))
    _assert_masks_match(sched, 3)
    tab = stm.encode_schedule(sched, 3)
    reach, _, _, _ = stm.masks_at(tab, 5)
    reach = np.asarray(reach)
    assert reach[0, 1] and reach[1, 0]  # first episode healed
    assert not reach[0, 2] and not reach[1, 2]  # second active


def test_full_mesh_partition():
    """Every node its own group: only the diagonal survives."""
    sched = flt.FaultSchedule((
        flt.partition(0, 6, (0,), (1,), (2,), (3,), (4,)),
    ))
    _assert_masks_match(sched, 5)
    tab = stm.encode_schedule(sched, 5)
    reach, _, _, _ = stm.masks_at(tab, 3)
    assert (np.asarray(reach) == np.eye(5, dtype=bool)).all()


def test_overlapping_bursts_add_and_clamp():
    sched = flt.FaultSchedule((
        flt.burst(0, 10, 6000),
        flt.burst(5, 15, 6000),
    ))
    _assert_masks_match(sched, 3)
    tab = stm.encode_schedule(sched, 3)
    _, _, extra, _ = stm.masks_at(tab, 7)
    assert int(extra) == 10_000  # 12000 clamps like the compiled path


def test_one_way_self_edge_never_cut():
    """src and dst overlapping must not cut a node's self-reach (the
    compiled path restores the diagonal after applying cuts)."""
    sched = flt.FaultSchedule((flt.one_way(0, 5, (0, 1), (0, 2)),))
    _assert_masks_match(sched, 3)
    tab = stm.encode_schedule(sched, 3)
    reach, _, _, _ = stm.masks_at(tab, 2)
    assert np.asarray(reach).diagonal().all()


def test_encode_batch_stacks_independent_lanes():
    scheds = [
        flt.FaultSchedule((flt.pause(2, 8, 1),)),
        None,
        flt.FaultSchedule((
            flt.partition(1, 4, (0,), (1, 2)), flt.burst(2, 6, 1000),
        )),
    ]
    tabs = stm.encode_batch(scheds, 3)
    assert tabs.t0.shape == (3, 2)  # capacity = max episodes over lanes
    assert tabs.horizon.tolist() == [8, 0, 6]
    for i, s in enumerate(scheds):
        one = stm.ScheduleTable(*(getattr(tabs, f)[i]
                                  for f in stm.ScheduleTable._fields))
        comp = flt.compile_schedule(s, 3)
        for t in range(10):
            reach, paused, extra, _ = stm.masks_at(one, t)
            if comp is None:
                assert np.asarray(reach).all() and int(extra) == 0
            else:
                tt = min(t, comp.horizon)
                assert (np.asarray(reach) == comp.reach[tt]).all()
                assert (np.asarray(paused) == comp.paused[tt]).all()
                assert int(extra) == int(comp.extra_drop[tt])


def test_capacity_overflow_rejected():
    sched = flt.FaultSchedule((flt.pause(0, 4, 1), flt.pause(2, 6, 0)))
    with pytest.raises(ValueError, match="capacity"):
        stm.encode_schedule(sched, 3, max_episodes=1)


def test_node_range_validated_like_compile_schedule():
    sched = flt.FaultSchedule((flt.pause(0, 4, 7),))
    with pytest.raises(ValueError, match="cluster has 3 nodes"):
        stm.encode_schedule(sched, 3)
    with pytest.raises(ValueError, match="cluster has 3 nodes"):
        flt.compile_schedule(sched, 3)


def test_degenerate_partition_validated_like_compile_schedule():
    sched = flt.FaultSchedule((flt.partition(0, 4, (0, 1, 2)),))
    with pytest.raises(ValueError, match="implicit complement"):
        stm.encode_schedule(sched, 3)
    with pytest.raises(ValueError, match="implicit complement"):
        flt.compile_schedule(sched, 3)
