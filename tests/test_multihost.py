"""Multi-host mesh shape: the 2-D ('dcn', 'i') mesh — hosts on the
outer axis, a host's chips on the inner — must run both sharded
engines with results bit-identical to the 1-D single-host mesh (the
collectives reduce over the full axis tuple; production use swaps the
virtual devices for jax.distributed processes, nothing else changes).
"""

import jax.numpy as jnp
import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import fast
from tpu_paxos.parallel import mesh as pmesh
from tpu_paxos.parallel import sharded as psharded
from tpu_paxos.parallel import sharded_sim
import pytest


def _mesh_2d():
    return pmesh.make_instance_mesh(dcn_hosts=2)


def test_mesh_axes_shapes():
    m2 = _mesh_2d()
    assert m2.axis_names == ("dcn", "i")
    assert m2.devices.shape == (2, 4)
    assert pmesh.instance_axes(m2) == ("dcn", "i")
    m1 = pmesh.make_instance_mesh()
    assert pmesh.instance_axes(m1) == ("i",)


def test_fast_path_2d_mesh_matches_unsharded():
    i, n = 1 << 12, 5
    vids = jnp.arange(i, dtype=jnp.int32)

    st_ref, n_ref = fast.choose_all_jit(
        fast.init_state(i, n), vids, proposer=0, quorum=3
    )

    m2 = _mesh_2d()
    fn = psharded.sharded_choose_all(m2, proposer=0, quorum=3)
    st2 = psharded.init_sharded_state(m2, i, n)
    st2, n2 = fn(st2, pmesh.shard_instances(m2, vids))

    assert int(n_ref) == int(n2) == i
    for name in st_ref._fields:
        a = np.asarray(getattr(st_ref, name))
        b = np.asarray(getattr(st2, name))
        assert (a == b).all(), f"{name} diverges on the dcn x ici mesh"


@pytest.mark.slow
def test_sim_engine_2d_mesh_matches_1d():
    cfg = SimConfig(
        n_nodes=5,
        n_instances=64,
        proposers=(0, 1),
        seed=7,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    r1 = sharded_sim.run_sharded(cfg, pmesh.make_instance_mesh())
    r2 = sharded_sim.run_sharded(cfg, _mesh_2d())
    assert r1.done and r2.done
    # Same seed, same shard count (8 either way, linearized row-major):
    # the whole decision state must be bit-identical across topologies.
    assert (r1.chosen_vid == r2.chosen_vid).all()
    assert (r1.chosen_round == r2.chosen_round).all()
    assert (r1.chosen_ballot == r2.chosen_ballot).all()
    assert (r1.learned == r2.learned).all()
    assert r1.rounds == r2.rounds
