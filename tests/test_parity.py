"""Decision-parity anchor vs the C++ reference binary (BASELINE config 1).

Builds the reference with its own Makefile recipe, runs the debug.conf
workload (time-scaled; fault rates untouched), parses the committed-log
grammar (ref multi/paxos.cpp:18-22), and asserts the reference's own
end-of-run invariants (ref multi/main.cpp:566-573) on BOTH the C++ run
and a tpu_paxos run of the equivalent config — the same external
checker judges both systems.  ``make parity`` runs the full-speed
canonical config end to end.
"""

from __future__ import annotations

import os
import shutil

import numpy as np
import pytest

from tpu_paxos.harness import reference_runner as ref
from tpu_paxos.harness import validate

_HAVE_REF = os.path.isdir(ref.REFERENCE_DIR) and shutil.which("g++")

pytestmark = pytest.mark.skipif(
    not _HAVE_REF, reason="reference sources or g++ unavailable"
)


@pytest.fixture(scope="module")
def reference_run() -> ref.ReferenceRun:
    """One shared fast-config reference run (seed 0)."""
    return ref.run_reference(ref.fast_reference_args(seed=0), timeout=300)


def test_reference_builds_and_passes_own_asserts(reference_run):
    # rc=0 + "All done" = every inline ASSERT and the epilogue checks
    # passed inside the binary (ref multi/main.cpp:566-579).
    assert reference_run.returncode == 0
    assert reference_run.all_done


def test_reference_log_parses_in_grammar(reference_run):
    logs = reference_run.logs
    assert set(logs.keys()) == {0, 1, 2, 3}
    for s, entries in logs.items():
        assert entries, f"server {s} dumped no committed values"
        for e in entries:
            assert e.ballot > 0
            assert 0 <= e.proposer < 4
            if not e.noop:
                assert 0 <= int(e.value) < 40


def test_reference_invariants_rederived(reference_run):
    # Independent re-check of agreement / exactly-once / in-order on
    # the parsed dump — not trusting the binary's own asserts.
    ref.check_reference_invariants(reference_run, srvcnt=4, cltcnt=4, idcnt=10)


def test_equivalent_sim_same_invariants():
    res, in_order = ref.run_equivalent_sim(
        srvcnt=4, cltcnt=4, idcnt=10, seed=0
    )
    assert res.done, f"did not quiesce in {res.rounds} rounds"
    seqs = validate.check_all(res.learned, res.expected_vids)
    validate.check_in_order_clients(seqs[0], in_order)


def test_parity_anchor(reference_run):
    """Both systems, same config shape, same checker: BASELINE's
    'decision parity vs the C++ multi/ binary'."""
    ref.check_reference_invariants(reference_run, srvcnt=4, cltcnt=4, idcnt=10)
    res, in_order = ref.run_equivalent_sim(srvcnt=4, cltcnt=4, idcnt=10, seed=0)
    assert res.done
    seqs = validate.check_all(res.learned, res.expected_vids)
    validate.check_in_order_clients(seqs[0], in_order)
    # Same executed-value multiset on both sides: exactly ids 0..39.
    ref_exec = np.sort(
        np.asarray(
            [int(e.value) for e in reference_run.logs[0] if not e.noop]
        )
    )
    tpu_exec = np.sort(seqs[0])
    np.testing.assert_array_equal(ref_exec, np.arange(40))
    np.testing.assert_array_equal(tpu_exec, np.arange(40))


def test_equivalent_workload_shape():
    workload, gates, in_order = ref.equivalent_workload(4, 4, 10)
    # Every id exactly once across proposers.
    allv = np.sort(np.concatenate(workload))
    np.testing.assert_array_equal(allv, np.arange(40))
    # Gate chains: in-order clients 0,1; ids k=1..5 gated on k-1.
    joined = {
        int(v): int(g)
        for w, gs in zip(workload, gates)
        for v, g in zip(w, gs)
    }
    for c in range(2):
        for k in range(1, 6):
            assert joined[c * 10 + k] == c * 10 + k - 1
        assert joined[c * 10] == -1
        for k in range(6, 10):
            assert joined[c * 10 + k] == -1
    # Free clients fully ungated.
    for c in range(2, 4):
        for k in range(10):
            assert joined[c * 10 + k] == -1
    assert [len(x) for x in in_order] == [6, 6]
