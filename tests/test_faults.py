"""Correlated fault schedules (core/faults.py) through both engines.

Covers: schedule compilation semantics, the heal-then-converge
liveness contract (quiescence gated on the last heal; paused nodes
owed — not excused — after resume), partition / one-way / pause /
burst behavior under the general engine, schedule determinism, the
membership engine under episodes (incl. record/replay), and the
dense-vs-sharded byte-identical decision log on an episode mix."""

import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as flt
from tpu_paxos.core import sim
from tpu_paxos.core import values as val
from tpu_paxos.harness import validate


def _cfg(sched, seed=0, n_nodes=5, n_instances=64, drop=300, **kw):
    return SimConfig(
        n_nodes=n_nodes,
        n_instances=n_instances,
        proposers=(0, 1),
        seed=seed,
        faults=FaultConfig(
            drop_rate=drop, dup_rate=500, max_delay=2, schedule=sched, **kw
        ),
    )


# ---------------- compilation ----------------

def test_compile_schedule_tables():
    sched = flt.FaultSchedule((
        flt.partition(2, 5, (0, 1), (2, 3, 4)),
        flt.one_way(3, 7, (0,), (2,)),
        flt.pause(4, 9, 1),
        flt.burst(1, 4, 2000),
    ))
    c = flt.compile_schedule(sched, 5)
    assert c.horizon == 9 and c.reach.shape == (10, 5, 5)
    # partition window: groups mutually cut, both directions
    assert not c.reach[2, 0, 2] and not c.reach[2, 2, 0]
    assert c.reach[2, 0, 1] and c.reach[2, 2, 3]
    # one_way: only src->dst cut
    assert not c.reach[6, 0, 2] and c.reach[6, 2, 0]
    # self-reachability survives any cut
    assert c.reach[2].diagonal().all()
    # healed row
    assert c.reach[9].all() and not c.paused[9].any()
    assert c.paused[4, 1] and not c.paused[3, 1]
    assert c.extra_drop[1] == 2000 and c.extra_drop[4] == 0


def test_compile_rejects_out_of_range_nodes():
    sched = flt.FaultSchedule((flt.pause(0, 4, 7),))
    with pytest.raises(ValueError, match="node 7"):
        flt.compile_schedule(sched, 5)


def test_episode_validation():
    with pytest.raises(ValueError, match="non-empty"):
        flt.pause(5, 5, 1)
    with pytest.raises(ValueError, match="disjoint"):
        flt.partition(0, 4, (0, 1), (1, 2))
    with pytest.raises(ValueError, match="non-empty"):
        flt.partition(0, 4)
    with pytest.raises(ValueError, match="drop_rate"):
        flt.burst(0, 4, 0)
    # one group listing EVERY node cuts nothing — compile-time error
    with pytest.raises(ValueError, match="nothing is cut"):
        flt.compile_schedule(
            flt.FaultSchedule((flt.partition(0, 4, (0, 1, 2)),)), 3
        )


def test_partition_single_group_uses_implicit_complement():
    """The documented shorthand: partition(t0, t1, (0, 1)) isolates
    {0, 1} from the implicit complement group."""
    c = flt.compile_schedule(
        flt.FaultSchedule((flt.partition(0, 2, (0, 1)),)), 5
    )
    assert not c.reach[0, 0, 2] and not c.reach[0, 3, 1]
    assert c.reach[0, 0, 1] and c.reach[0, 2, 4]


def test_partition_unlisted_nodes_form_implicit_group():
    c = flt.compile_schedule(
        flt.FaultSchedule((flt.partition(0, 2, (0,), (1,)),)), 4
    )
    # 2 and 3 are unlisted: together, cut from both listed groups
    assert c.reach[0, 2, 3] and c.reach[0, 3, 2]
    assert not c.reach[0, 0, 2] and not c.reach[0, 1, 3]


def test_schedule_json_roundtrip():
    sched = flt.FaultSchedule((
        flt.partition(1, 9, (0, 2), (1, 3)),
        flt.one_way(2, 5, (1,), (0, 3)),
        flt.pause(3, 6, 2),
        flt.burst(0, 2, 111),
    ))
    assert flt.FaultSchedule.from_dict(sched.to_dict()) == sched


def test_round_budget_extends_past_horizon():
    sched = flt.FaultSchedule((flt.pause(10, 500, 1),))
    cfg = _cfg(sched)
    assert cfg.round_budget == cfg.max_rounds + 500
    assert _cfg(None).round_budget == _cfg(None).max_rounds


# ---------------- general engine ----------------

def test_partition_heals_and_converges():
    """A partition that strands both proposers away from quorum wedges
    progress during the window; after the heal every invariant holds
    and quiescence is declared at/after the horizon."""
    sched = flt.FaultSchedule((
        flt.partition(4, 40, (0, 1), (2, 3, 4)),
    ))
    r = sim.run(_cfg(sched, seed=3))
    assert r.done
    assert r.rounds >= 40  # done is gated on the last heal
    validate.check_all(r.learned, r.expected_vids)


def test_pause_is_not_a_crash():
    """A paused node resumes and is owed the full log: its learner
    column must be complete at quiescence (a crashed node's would be
    excused), and it must never be reported crashed."""
    sched = flt.FaultSchedule((flt.pause(3, 30, 2),))
    r = sim.run(_cfg(sched, seed=1))
    assert r.done and not r.crashed.any()
    validate.check_all(r.learned, r.expected_vids)
    # node 2's learner column has no holes below the frontier
    hmax = int(np.max(np.flatnonzero(r.chosen_vid != int(val.NONE))))
    assert (r.learned[: hmax + 1, 2] != int(val.NONE)).all()


def test_one_way_cut_and_burst():
    sched = flt.FaultSchedule((
        flt.one_way(2, 25, (0,), (2, 3)),
        flt.burst(5, 20, 4000),
    ))
    r = sim.run(_cfg(sched, seed=5))
    assert r.done
    validate.check_all(r.learned, r.expected_vids)


def test_paused_proposer_values_still_chosen():
    """Proposer node 1 pauses with an undrained queue: its values must
    still be chosen after the heal (no crash-style liveness waiver),
    and no no-op may squat on the space they need."""
    sched = flt.FaultSchedule((flt.pause(2, 36, 1),))
    r = sim.run(_cfg(sched, seed=2))
    assert r.done
    validate.check_all(r.learned, r.expected_vids)


@pytest.mark.slow
def test_schedule_determinism():
    sched = flt.FaultSchedule((
        flt.partition(4, 20, (0, 3), (1, 2, 4)),
        flt.pause(24, 40, 2),
    ))
    a = sim.run(_cfg(sched, seed=9))
    b = sim.run(_cfg(sched, seed=9))
    assert np.array_equal(a.chosen_vid, b.chosen_vid)
    assert np.array_equal(a.chosen_round, b.chosen_round)
    assert np.array_equal(a.learned, b.learned)


@pytest.mark.slow
def test_gate_chains_across_partition_flaps():
    """In-order gate chains survive a flapping-partition schedule."""
    sched = flt.FaultSchedule((
        flt.partition(5, 25, (0, 1), (2, 3, 4)),
        flt.partition(35, 55, (0, 2, 4), (1, 3)),
    ))
    chain = np.asarray([10, 11, 12, 13], np.int32)
    gates = [
        np.asarray([int(val.NONE), 10, 11, 12], np.int32),
        np.zeros((0,), np.int32),
    ]
    free = np.arange(100, 120, dtype=np.int32)
    r = sim.run(_cfg(sched, seed=4, n_instances=128),
                workload=[chain, free], gates=gates)
    assert r.done
    seqs = validate.check_all(r.learned, np.concatenate([chain, free]))
    validate.check_in_order_clients(max(seqs, key=len), [chain])


# ---------------- dense vs sharded ----------------

def test_dense_vs_sharded_byte_identical_on_episode_mix():
    """Same seed + same schedule => byte-identical decision logs
    between the dense engine and the sharded engine on a single-shard
    mesh (the sharded code path — shard_map, collectives, axis-index
    globalization — with placement-identical geometry)."""
    from tpu_paxos.parallel import mesh as pmesh
    from tpu_paxos.parallel import sharded_sim
    from tpu_paxos.replay.decision_log import decision_log

    sched = flt.FaultSchedule((
        flt.partition(4, 22, (0, 1), (2, 3, 4)),
        flt.pause(26, 40, 3),
        flt.burst(8, 16, 2000),
    ))
    cfg = _cfg(sched, seed=6, n_instances=64)
    dense = sim.run(cfg)
    m1 = pmesh.make_instance_mesh(1)
    assert m1.size == 1
    sharded = sharded_sim.run_sharded(cfg, m1)
    assert dense.done and sharded.done

    def render(r):
        return decision_log(
            r.chosen_vid, r.chosen_ballot, stride=1 << 20,
            n_instances=cfg.n_instances,
        )

    assert render(dense) == render(sharded)
    assert np.array_equal(dense.chosen_round, sharded.chosen_round)
    assert np.array_equal(dense.learned, sharded.learned)


@pytest.mark.slow
def test_sharded_episode_mix_multiset_equality():
    """8-shard run under a schedule: placement differs by design, the
    chosen-value multiset and every invariant must not."""
    from tpu_paxos.parallel import mesh as pmesh
    from tpu_paxos.parallel import sharded_sim

    sched = flt.FaultSchedule((
        flt.partition(4, 24, (0, 2), (1, 3, 4)),
        flt.pause(28, 44, 1),
    ))
    cfg = SimConfig(
        n_nodes=5, n_instances=256, proposers=(0, 1), seed=7,
        faults=FaultConfig(drop_rate=300, dup_rate=500, max_delay=2,
                           schedule=sched),
    )
    m = pmesh.make_instance_mesh()
    r = sharded_sim.run_sharded(cfg, m)
    assert r.done
    validate.check_agreement(r.learned)
    validate.check_exactly_once(r.learned, r.expected_vids)
    r1 = sim.run(cfg)
    real = lambda cv: sorted(v for v in np.asarray(cv).tolist() if v >= 0)  # noqa: E731
    assert real(r.chosen_vid) == real(r1.chosen_vid)


# ---------------- membership engine ----------------

def test_member_engine_under_pause_and_partition():
    """Churn + proposals with a pause and a partition episode: prefix
    consistency holds and everything applies after the heal."""
    from tpu_paxos.membership import engine as mem

    sched = flt.FaultSchedule((
        flt.pause(6, 20, 2),
        flt.partition(24, 40, (0, 1), (2, 3)),
    ))
    ms = mem.MemberSim(4, n_instances=64, seed=0, schedule=sched)
    for tgt in (1, 2, 3):
        cv = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(cv), 2000)
    for v in range(6):
        ms.propose(0, v)
        ms.run_rounds(2)
    assert ms.run_until(
        lambda: set(range(6)) <= set(ms.applied_log(0).tolist()), 2000
    )
    validate.check_prefix_consistency(
        [ms.applied_log(a) for a in range(4)]
    )


def test_member_schedule_record_replay_byte_identical(tmp_path):
    """The schedule is part of the recorded identity: replay re-derives
    the same decision log byte-for-byte.  The schedule mixes a pause
    with a deterministic crash point — the kind this engine accepts
    as of PR 12 — so the injection-log round-trip of crash episodes
    (artifact schema satellite) is covered end to end: the crash must
    fire at the same round in the replay or the logs diverge."""
    from tpu_paxos.membership import engine as mem

    sched = flt.FaultSchedule((flt.pause(4, 14, 1), flt.crash(18, 2)))
    ms = mem.MemberSim(3, n_instances=48, seed=5, schedule=sched)
    cv = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(cv), 2000)
    for v in range(4):
        ms.propose(0, v)
        ms.run_rounds(3)
    ms.run_rounds(20)
    assert 2 in ms.crashed_set()  # the recorded run's crash fired
    path = tmp_path / "inj.json"
    ms.save_injections(path)
    replayed = mem.MemberSim.replay(path)
    assert replayed.decision_log() == ms.decision_log()
    assert replayed.schedule == sched
    assert 2 in replayed.crashed_set()
