"""mc controller scope (PR 17): exhaustive model checking of the
admission controller's policy invariants (``analysis/mc_control.py``).

Contracts: the length-stratified sequence codec and the (policy x
sequence | e2e cell) scenario codec are bijections; the host-plane
oracle (``judge_sequence`` — predicted-state reconstruction, not a
re-run of ``decide``'s code) certifies the clean policy grid and
provably catches the seeded shed-on-gray wedge
(``TPU_PAXOS_SEEDED_WEDGE=shed-on-gray``); counterexamples shrink
greedily and land as byte-replaying ``mc-control`` artifacts that
replay WITHOUT the wedge env var (the artifact carries the wedged
policy).

The committed scope's e2e device cells are slow-marked (one
controlled-serve compile); their fast-tier coverage is the host-only
``run_scope`` tests here (same judging path, zero device work) plus
tests/test_control.py's controlled-serve pins on the same geometry.
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_paxos.analysis import mc_control as mcc
from tpu_paxos.analysis import modelcheck as mc
from tpu_paxos.analysis.artifact_schema import ArtifactSchemaError
from tpu_paxos.serve import control as ctl
from tpu_paxos.telemetry import diagnose as diag

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = {
    "tier_bands": [[3, 1, 2]], "patiences": [1], "ladders": [[]],
    "window_sets": [[], ["gray-region"], ["saturation"]],
    "burn_tiers": [0, 900], "max_dispatches": 2, "plan_values": 4,
    "chunk_lanes": 16,
}


def _committed():
    return mc.load_scopes()["control"]


def _tiny_host_scope(**over):
    return mcc.ControlScope.from_dict(dict(TINY, **over))


# ---------------- scope parse / validate ----------------

def test_committed_control_scope_loads_and_registers():
    scope = _committed()
    assert mc.scope_type(scope) == "control"
    enum = mc.enum_for(scope)
    assert isinstance(enum, mcc.ControlEnum)
    # full == reduced: no node group to quotient by
    assert enum.reduced == list(range(enum.total))
    assert enum.total == enum.host_total + enum.n_e2e


def test_validator_named_rules():
    with pytest.raises(mc.ScopeError, match="defer"):
        _tiny_host_scope(tier_bands=[[3, 2, 1]])
    with pytest.raises(mc.ScopeError, match="unknown cause name"):
        _tiny_host_scope(window_sets=[["not-a-cause"]])
    with pytest.raises(mc.ScopeError, match="ascend"):
        _tiny_host_scope(ladders=[[2, 1]])
    with pytest.raises(
        mc.ScopeError, match=rf"\[1, {mcc.MAX_CTL_DISPATCHES}\]"
    ):
        _tiny_host_scope(max_dispatches=mcc.MAX_CTL_DISPATCHES + 1)
    with pytest.raises(mc.ScopeError, match="come together"):
        _tiny_host_scope(e2e_policies=[0])
    with pytest.raises(mc.ScopeError, match="outside the policy grid"):
        _tiny_host_scope(e2e_policies=[1], e2e_arrival_seeds=[0])
    with pytest.raises(mc.ScopeError, match="unknown scope field"):
        _tiny_host_scope(n_nodes=3)


# ---------------- codec ----------------

def test_sequence_codec_inverse_exhaustive():
    """rank -> sequence -> rank is the identity over EVERY bounded
    sequence of the committed scope, lengths stratified correctly."""
    enum = mcc.ControlEnum(_committed())
    for r in range(enum.n_seq):
        seq = enum.seq_unrank(r)
        assert 1 <= len(seq) <= enum.scope.max_dispatches
        assert all(0 <= d < enum.n_letters for d in seq)
        assert enum.seq_rank(seq) == r


def test_scenario_codec_boundaries_and_e2e_tail():
    """decode/encode at both ends of the host plane and across the
    e2e tail boundary — the cells the mixed codec must not shear."""
    enum = mcc.ControlEnum(_committed())
    for i in (0, enum.host_total - 1, enum.host_total, enum.total - 1):
        sc = enum.decode(i)
        assert enum.encode(sc) == i
        assert (sc.seq is None) == (i >= enum.host_total)
    tail = enum.decode(enum.host_total)
    assert tail.e2e_seed == int(enum.scope.e2e_arrival_seeds[0])
    assert tail.policy == int(enum.scope.e2e_policies[0])
    with pytest.raises(IndexError):
        enum.decode(enum.total)


def test_policy_grid_shape_and_order():
    scope = _committed()
    pols = mcc.policy_grid(scope)
    assert len(pols) == (
        len(scope.tier_bands) * len(scope.patiences) * len(scope.ladders)
    )
    # band-major, then patience, then ladder — the codec's documented
    # enumeration order
    p0 = pols[0]
    assert (p0.n_tiers, p0.defer_tier, p0.shed_tier) == scope.tier_bands[0]
    assert p0.patience == scope.patiences[0]


# ---------------- the host oracle ----------------

def test_clean_policy_grid_certifies_over_all_letters():
    """Every committed policy passes every single-letter dispatch —
    the oracle's baseline (the full sweep is the committed
    certificate's job)."""
    scope = _committed()
    enum = mcc.ControlEnum(scope)
    for pi in range(enum.n_policies):
        for letter in enum.letters:
            _, bits = mcc.judge_sequence(
                enum.policies[pi], [letter], scope.plan_values
            )
            assert all(bits.values()), (pi, letter, bits)


def test_gray_veto_catches_wedged_policy():
    """The seeded policy bug: gray-region forced to shed fails the
    veto invariant on every gray-naming window, including gray beside
    saturation."""
    scope = _committed()
    enum = mcc.ControlEnum(scope)
    wedged = ctl.wedged_policy(enum.policies[0])
    for names in (("gray-region",), ("gray-region", "saturation")):
        _, bits = mcc.judge_sequence(
            wedged, [(names, 900)], scope.plan_values
        )
        assert not bits["veto"]
        assert mcc.violation_of(bits) == "ctl-gray-veto"
    # a pure saturation window sheds without degrading granularity
    # under the wedge too — not a veto matter
    _, bits = mcc.judge_sequence(
        wedged, [(("saturation",), 900)], scope.plan_values
    )
    assert bits["veto"]


def test_wedge_env_arms_policy_materialization(monkeypatch):
    enum = mcc.ControlEnum(_committed())
    gray = diag.CAUSE_IDS["gray-region"]
    assert dict(enum.policy_of(0).table).get(gray) != "shed"
    monkeypatch.setenv(
        "TPU_PAXOS_SEEDED_WEDGE", ctl.WEDGE_SHED_ON_GRAY
    )
    assert dict(enum.policy_of(0).table)[gray] == "shed"


def test_trail_legality_rejects_bad_trails():
    # the committed grid's second policy carries the real ladder
    # (1, 2) — a single-rung ladder would make "degrade stays at the
    # same level" vacuously legal
    p = mcc.policy_grid(_committed())[1]
    top = p.top_level
    assert top > 0
    assert mcc._trail_legal(p, [])
    # degrade must land exactly one rung down
    assert not mcc._trail_legal(
        p, [{"action": "degrade", "level": top, "degraded": True}]
    )
    # restore without anything to restore
    assert not mcc._trail_legal(
        p, [{"action": "restore", "level": top, "degraded": False}]
    )
    # unknown action
    assert not mcc._trail_legal(
        p, [{"action": "panic", "level": top, "degraded": False}]
    )
    # legal degrade -> restore round trip
    assert mcc._trail_legal(p, [
        {"action": "degrade", "level": top - 1, "degraded": True},
        {"action": "restore", "level": top, "degraded": False},
    ])


def test_admission_exact_over_degraded_timelines():
    for p in mcc.policy_grid(_committed()):
        assert mcc._admission_exact(p, [True, False, True, True], 6)


def test_shrink_reaches_a_single_dispatch():
    scope = _committed()
    enum = mcc.ControlEnum(scope)
    wedged = ctl.wedged_policy(enum.policies[0])
    gray_li = next(
        li for li, (ws, b) in enumerate(enum.letters)
        if "gray-region" in ws and b > 0
    )
    quiet_li = next(
        li for li, (ws, _) in enumerate(enum.letters) if not ws
    )
    small = mcc.shrink_sequence(
        wedged, enum.letters, (quiet_li, gray_li, quiet_li),
        scope.plan_values,
    )
    assert small == (gray_li,)


# ---------------- artifact replay ----------------

def _artifact(tmp_path, monkeypatch=None):
    scope = _committed()
    enum = mcc.ControlEnum(scope)
    wedged = ctl.wedged_policy(enum.policies[0])
    letters = [(("gray-region",), 900)]
    decisions, bits = mcc.judge_sequence(
        wedged, letters, scope.plan_values
    )
    path = str(tmp_path / "mc_ctl_scenario_0.json")
    mcc.save_ctl_artifact(
        path, scope, wedged, letters,
        mcc.violation_of(bits), decisions,
    )
    return path


def test_artifact_replays_byte_identically(tmp_path, monkeypatch):
    """The artifact carries the wedged policy, so replay is exact and
    wedge-env independent."""
    path = _artifact(tmp_path)
    monkeypatch.delenv("TPU_PAXOS_SEEDED_WEDGE", raising=False)
    rep = mcc.reproduce(path)
    assert rep["match"] and rep["decisions_match"]
    assert rep["violation"] == rep["recorded_violation"] == "ctl-gray-veto"
    assert rep["decision_log_sha256"] == rep["recorded_sha256"]
    assert "[ctl 1] degrade" in rep["decision_log"]


def test_artifact_tamper_and_schema_errors(tmp_path):
    path = _artifact(tmp_path)
    with open(path) as f:
        art = json.load(f)
    # tampered trail: replay must refuse the match
    art["decisions"] = []
    art["violation"] = "none"
    with open(path, "w") as f:
        json.dump(art, f)
    rep = mcc.reproduce(path)
    assert not rep["match"] and not rep["decisions_match"]
    # missing field and wrong engine are schema errors, named
    bad = dict(art)
    del bad["control_log_sha256"]
    with open(path, "w") as f:
        json.dump(bad, f)
    with pytest.raises(ArtifactSchemaError, match="control_log_sha256"):
        mcc.reproduce(path)
    with open(path, "w") as f:
        json.dump(dict(art, engine="serve"), f)
    with pytest.raises(ArtifactSchemaError, match="mc-control"):
        mcc.reproduce(path)


# ---------------- host-plane run_scope ----------------

def test_run_scope_host_only_certifies_clean(tmp_path):
    """A host-only scope (no e2e cells) runs without any device work:
    every nibble f, zero compiles in every chunk, summary shaped for
    the shared certificate machinery."""
    scope = _tiny_host_scope()
    summary = mcc.run_scope(
        scope, triage_dir=str(tmp_path), verbose=False
    )
    assert summary["ok"]
    assert set(summary["verdict_bits"]) == {"f"}
    assert summary["scenarios_full"] == summary["scenarios_reduced"]
    assert all(c == 0 for c in summary["compiles_per_chunk"])
    assert summary["seeded_wedge"] == ""
    assert not os.listdir(tmp_path)


def test_run_scope_finds_and_shrinks_the_seeded_wedge(
    tmp_path, monkeypatch
):
    """THE recall pin: with the wedge armed, every gray-naming host
    scenario fails the veto, the first counterexamples shrink to one
    dispatch, and the dumped artifact replays with the env var
    UNSET."""
    monkeypatch.setenv(
        "TPU_PAXOS_SEEDED_WEDGE", ctl.WEDGE_SHED_ON_GRAY
    )
    scope = _tiny_host_scope()
    summary = mcc.run_scope(
        scope, triage_dir=str(tmp_path), verbose=False,
        max_counterexamples=3,
    )
    assert not summary["ok"]
    assert summary["seeded_wedge"] == ctl.WEDGE_SHED_ON_GRAY
    cx = summary["counterexamples"][0]
    assert cx["violation"] == "ctl-gray-veto"
    assert cx["shrunk_dispatches"] == 1
    assert os.path.basename(cx["artifact"]).startswith(
        "mc_ctl_scenario_"
    )
    monkeypatch.delenv("TPU_PAXOS_SEEDED_WEDGE")
    rep = mcc.reproduce(cx["artifact"])
    assert rep["match"]
    assert rep["violation"] == "ctl-gray-veto"


# ---------------- committed scope + e2e cells (slow tier) -----------

@pytest.mark.slow
def test_control_scope_certifies_committed_with_e2e():
    """Slow tier: the committed control scope end-to-end, e2e device
    cells included — verdict nibbles match the pinned certificate and
    only the first chunk (the first e2e cell) compiles.  Fast-tier
    coverage: the host-only run_scope tests above + test_control.py's
    controlled-serve pins."""
    scope = _committed()
    summary = mcc.run_scope(scope, verbose=False)
    cert = mc.load_certificates()["control"]
    assert summary["ok"], summary["counterexamples"][:2]
    assert summary["verdict_bits_sha256"] == cert["verdict_bits_sha256"]
    assert summary["e2e_cells"] == 2
    assert all(c == 0 for c in summary["compiles_per_chunk"][1:]), (
        summary["compiles_per_chunk"]
    )


@pytest.mark.slow
def test_cli_repro_routes_mc_control_artifacts(tmp_path):
    """Slow tier (cold subprocess): ``python -m tpu_paxos repro``
    routes engine=mc-control through analysis/mc_control.reproduce
    and exits 0 on a byte-exact replay.  Fast-tier coverage: the
    in-process reproduce() roundtrip above."""
    path = _artifact(tmp_path)
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_"))
        and k != "TPU_PAXOS_SEEDED_WEDGE"
    }
    import __graft_entry__ as ge

    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + ge.scrub_pythonpath(env.get("PYTHONPATH", ""))
    )
    p = subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "repro", path,
         "--backend=cpu"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "[ctl 1] degrade" in p.stdout
