"""The flight recorder (tpu_paxos/telemetry/): decision-log
neutrality, summary correctness, and the Chrome-trace exporter.

The load-bearing contract is NEUTRALITY: a telemetry-armed engine must
be decision-log sha256-identical to the plain one for the same (cfg,
schedule, seed) — the recorder consumes no PRNG streams and never
feeds back into ``SimState``.  Pinned here for the general engine's
compile-time path (fast tier) and for fleet lanes — which ARE the
runtime-knob/runtime-schedule path — over a 5-node crash+pause grid
cell (slow tier, it compiles two fleet envelopes).

The stress telemetry block and the trace CLI are golden-JSON pinned
like the paxlint/audit reports: the JSON shape is an interface, so
drift must be deliberate enough to update tests/data/."""

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as flt
from tpu_paxos.core import sim as simm
from tpu_paxos.core import values as val
from tpu_paxos.replay.decision_log import decision_log
from tpu_paxos.telemetry import export as texport
from tpu_paxos.telemetry import recorder as telem

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
WEDGE_ARTIFACT = "stress-triage/repro_fleet_g0_lane0.json"

WL = [np.arange(100, 108, dtype=np.int32),
      np.arange(200, 208, dtype=np.int32)]

SMALL_SCHED = flt.FaultSchedule((
    flt.partition(2, 10, (0,), (1, 2)),
    flt.pause(3, 8, 2),
    flt.burst(4, 9, 1500),
))


def _log_sha(r):
    stride = int(max(int(np.max(w)) for w in WL)) + 1
    text = decision_log(
        r.chosen_vid, r.chosen_ballot, stride=stride,
        n_instances=len(r.chosen_vid),
    )
    return hashlib.sha256(text.encode()).hexdigest()


# ---------------- host-side reducers (no jax) ----------------


def test_latency_quantile():
    # nothing decided
    assert telem.latency_quantile(np.zeros(10, np.int32), 0.99, -1) == -1
    # all latencies in one bucket: the bucket edge, clamped to the max
    h = np.zeros(10, np.int32)
    h[3] = 8  # bucket (4, 8]
    assert telem.latency_quantile(h, 0.50, 7) == 7  # clamp: edge 8 > max 7
    assert telem.latency_quantile(h, 0.99, 8) == 8
    # split across buckets: the quantile walks the cumulative counts
    h = np.zeros(10, np.int32)
    h[1], h[3] = 8, 8  # (1,2] and (4,8]
    assert telem.latency_quantile(h, 0.50, 5) == 2
    assert telem.latency_quantile(h, 0.99, 5) == 5
    # overflow bucket reports the exact observed max
    h = np.zeros(10, np.int32)
    h[-1] = 4
    assert telem.latency_quantile(h, 0.99, 413) == 413
    # p50 <= p99 <= max always holds (the clamp)
    for m in (1, 3, 40, 1000):
        hist = np.asarray([0, 3, 1, 0, 2, 0, 0, 0, 0, 1], np.int32)
        p50 = telem.latency_quantile(hist, 0.50, m)
        p99 = telem.latency_quantile(hist, 0.99, m)
        assert p50 <= p99 <= m


def _mk_summary(**over):
    """A host-numpy TelemetrySummary with recognizable values."""
    base = dict(
        msgs=np.arange(7, dtype=np.int32),
        offered=np.full(7, 100, np.int32),
        dropped=np.full(7, 5, np.int32),
        duped=np.full(7, 2, np.int32),
        delayed=np.full(7, 3, np.int32),
        learns=np.int32(48),
        commit_acks=np.int32(9),
        takeovers=np.int32(1),
        requeues=np.int32(4),
        restarts=np.int32(2),
        decided=np.int32(16),
        lat_hist=np.asarray([0, 8, 0, 8, 0, 0, 0, 0, 0, 0], np.int32),
        lat_max=np.int32(5),
        heal_gap=np.int32(24),
        stall_max=np.int32(3),
        duel_max=np.int32(4),
        takeover_round=np.asarray([7, -1], np.int32),
        rounds=np.int32(34),
        quiescent=np.bool_(True),
        region_offered=np.zeros(
            (telem.NUM_REGIONS, telem.NUM_REGIONS), np.int32
        ),
        region_dropped=np.zeros(
            (telem.NUM_REGIONS, telem.NUM_REGIONS), np.int32
        ),
        region_cut=np.zeros(
            (telem.NUM_REGIONS, telem.NUM_REGIONS), np.int32
        ),
    )
    base.update(over)
    return telem.TelemetrySummary(**base)


def test_summary_to_dict():
    d = telem.summary_to_dict(_mk_summary())
    assert set(d["msgs"]) == set(telem.MSG_NAMES)
    assert d["offered_total"] == 700
    assert d["dropped_total"] == 35
    assert d["drop_rate_observed"] == 500.0  # 35/700 in per-1e4 units
    assert d["latency_p50"] == 2 and d["latency_p99"] == 5
    assert d["latency_hist"] == [0, 8, 0, 8, 0, 0, 0, 0, 0, 0]
    assert d["takeover_round"] == [7, -1]
    assert d["heal_gap"] == 24 and d["quiescent"] is True
    # zero offered edges: the observed rate is 0.0, not a div-by-zero
    z = telem.summary_to_dict(_mk_summary(
        offered=np.zeros(7, np.int32), dropped=np.zeros(7, np.int32)
    ))
    assert z["drop_rate_observed"] == 0.0
    m = telem.margins_vector(_mk_summary())
    assert m == {"heal_gap": 24, "stall_max": 3, "duel_max": 4,
                 "rounds": 34, "latency_max": 5}


def _stack(summaries):
    """[lanes]-stack host summaries the way a FleetReport carries
    them."""
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs), *summaries)


def test_lane_reducers_on_crafted_lanes():
    """The stress mix block and the search's generation margins reduce
    [lanes] summaries; -1 heal gaps (never quiesced) are excluded from
    the min, and the margin vector takes the across-lane extremes."""
    from tpu_paxos.fleet import search as fsearch
    from tpu_paxos.harness import stress

    lanes = _stack([
        _mk_summary(),
        _mk_summary(heal_gap=np.int32(-1), stall_max=np.int32(9),
                    lat_max=np.int32(7), duel_max=np.int32(2),
                    rounds=np.int32(500), quiescent=np.bool_(False)),
        _mk_summary(heal_gap=np.int32(3)),
    ])
    rep = types.SimpleNamespace(telemetry=lanes)
    cfg = SimConfig(n_nodes=3, proposers=(0, 1), n_instances=16,
                    faults=FaultConfig(drop_rate=450))
    blk = stress._mix_telemetry(rep, cfg)
    assert blk["offered"] == 2100 and blk["dropped"] == 105
    assert blk["drop_rate_configured"] == 450
    assert blk["drop_rate_observed"] == 500.0
    assert blk["heal_gap_min"] == 3  # the -1 lane is excluded
    assert blk["stall_depth_max"] == 9
    assert blk["decided"] == 48 and blk["takeovers"] == 3
    mar = fsearch._generation_margins(rep)
    assert mar["heal_gap_min"] == 3
    assert mar["stall_depth_max"] == 9
    assert mar["duel_depth_max"] == 4
    assert mar["rounds_max"] == 500
    assert mar["latency_max"] == 7
    # recorder-free reports reduce to empty blocks, not crashes
    bare = types.SimpleNamespace(telemetry=None)
    assert stress._mix_telemetry(bare, cfg) == {}
    assert fsearch._generation_margins(bare) == {}


# ---------------- the exporter (host-side, crafted run) ----------------


def _crafted_trace():
    sched = flt.FaultSchedule((
        # nodes 1, 2 unlisted: they form the implicit second group
        # (core/faults.partition) and must render a bar too
        flt.partition(2, 6, (0,)),
        flt.one_way(3, 7, (1,), (2,)),
        flt.pause(4, 8, 2),
        flt.burst(5, 9, 2000),
    ))
    cfg = SimConfig(n_nodes=3, proposers=(0, 1), n_instances=4,
                    faults=FaultConfig(schedule=sched))
    result = types.SimpleNamespace(
        chosen_vid=np.asarray([100, int(val.NONE), 200, 101], np.int32),
        chosen_round=np.asarray([5, -1, 5, 9], np.int32),
        chosen_ballot=np.asarray([1, -1, 2, 1], np.int32),
        rounds=11, done=True,
    )
    sd = telem.summary_to_dict(_mk_summary(
        takeover_round=np.asarray([-1, 6], np.int32)
    ))
    return texport.chrome_trace(cfg, result, sd, label="crafted")


def test_chrome_trace_structure():
    trace = _crafted_trace()
    evs = trace["traceEvents"]
    assert all(
        {"ph", "name", "pid", "tid", "ts"} <= set(e) for e in evs
    )
    # every episode kind renders as a complete-duration event
    dur = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert "partition side 0" in dur and "pause" in dur
    assert any(n.startswith("one_way") for n in dur)
    assert any(n.startswith("burst") for n in dur)
    # the implicit partition side (unlisted nodes 1, 2) renders bars
    side1 = [e for e in evs if e["name"] == "partition side 1"]
    assert sorted(e["pid"] for e in side1) == [1, 2]
    p = dur["pause"]
    assert p["pid"] == 2 and p["ts"] == 4000 and p["dur"] == 4000
    # decisions: one instant per decided instance, round-ordered
    dec = [e for e in evs if e["ph"] == "i" and e["name"].startswith("dec")]
    assert len(dec) == 3
    assert [e["args"]["round"] for e in dec] == [5, 5, 9]
    # the takeover instant lands on the adopting proposer's node track
    tk = [e for e in evs if e["name"] == "commit takeover"]
    assert len(tk) == 1 and tk[0]["pid"] == 1 and tk[0]["ts"] == 6000
    # counter track is cumulative
    cts = [e for e in evs if e["ph"] == "C"]
    assert [c["args"]["instances"] for c in cts] == [2, 3]
    other = trace["otherData"]
    assert other["decided"] == 3 and other["rounds"] == 11
    assert other["telemetry"]["takeover_round"] == [-1, 6]
    # recorder-free renders (sharded replays): no telemetry block, no
    # takeover instants (the recorder is their only source)
    sched = flt.FaultSchedule((flt.pause(4, 8, 2),))
    cfg = SimConfig(n_nodes=3, proposers=(0, 1), n_instances=4,
                    faults=FaultConfig(schedule=sched))
    result = types.SimpleNamespace(
        chosen_vid=np.asarray([100, 200, int(val.NONE), 101], np.int32),
        chosen_round=np.asarray([5, 5, -1, 9], np.int32),
        chosen_ballot=np.asarray([1, 2, -1, 1], np.int32),
        rounds=11, done=True,
    )
    bare = texport.chrome_trace(cfg, result, None)
    assert "telemetry" not in bare["otherData"]
    assert not [e for e in bare["traceEvents"]
                if e["name"] == "commit takeover"]
    assert [e for e in bare["traceEvents"] if e["ph"] == "X"]


# ---------------- neutrality: the general engine (fast tier) ----------------


def test_single_run_recorder_parity():
    """run() vs run_with_telemetry(): identical decision logs and
    result arrays for a schedule + i.i.d.-knob mix on the compile-time
    path — with the WINDOWED plane armed (the default), so this is
    also the fast-tier windowed-neutrality pin — and the summary's
    invariants hold against the result, with the windowed series
    summing back to the cumulative one bucket-for-bucket."""
    cfg = SimConfig(
        n_nodes=3, proposers=(0, 1), n_instances=32, seed=3,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2,
                           crash_rate=1000, schedule=SMALL_SCHED),
    )
    a = simm.run(cfg, WL)
    b, summ, wsum = simm.run_with_telemetry(cfg, WL)
    assert _log_sha(a) == _log_sha(b)
    assert (np.asarray(a.chosen_vid) == np.asarray(b.chosen_vid)).all()
    assert (np.asarray(a.chosen_round) == np.asarray(b.chosen_round)).all()
    assert (np.asarray(a.learned) == np.asarray(b.learned)).all()
    assert (np.asarray(a.crashed) == np.asarray(b.crashed)).all()
    assert a.rounds == b.rounds and a.done == b.done
    # summary sanity against the result it rode along with
    assert (np.asarray(summ.msgs) == np.asarray(a.msgs)).all()
    assert int(summ.rounds) == a.rounds
    assert bool(summ.quiescent) == a.done
    decided = int((np.asarray(a.chosen_vid) != int(val.NONE)).sum())
    assert int(summ.decided) == decided
    hist = np.asarray(summ.lat_hist)
    assert 0 < hist.sum() <= decided
    # offered edges bound the per-type fault-layer counters
    assert (np.asarray(summ.dropped) <= np.asarray(summ.offered)).all()
    assert (np.asarray(summ.delayed) <= np.asarray(summ.offered)).all()
    # the schedule healed and the run quiesced: the gap is the
    # liveness margin, positive and round-bounded
    assert 0 <= int(summ.heal_gap) <= a.rounds
    assert int(summ.lat_max) >= 1
    d = telem.summary_to_dict(summ)
    assert d["latency_p50"] <= d["latency_p99"] <= d["latency_max"]
    # the windowed series is consistent with the cumulative summary:
    # per-bucket commit counts, latency deltas, and fault-layer
    # counters all sum back to the run totals, and stall depth's
    # bucket max equals the run max
    assert int(np.asarray(wsum.decided).sum()) == int(summ.decided)
    assert (
        np.asarray(wsum.lat_hist).sum(axis=0) == np.asarray(summ.lat_hist)
    ).all()
    for f in ("offered", "dropped", "duped", "delayed"):
        assert int(np.asarray(getattr(wsum, f)).sum()) == int(
            np.asarray(getattr(summ, f)).sum()
        ), f
    assert int(np.asarray(wsum.restarts).sum()) == int(summ.restarts)
    assert int(np.asarray(wsum.takeovers).sum()) == int(summ.takeovers)
    assert int(np.asarray(wsum.stall_max).max()) == int(summ.stall_max)
    # schedule activity is time-localized: this run decides across
    # more than one bucket (the windowed plane actually resolves time)
    assert int((np.asarray(wsum.decided) > 0).sum()) >= 2
    dw = telem.summary_to_dict(summ, wsum)
    assert dw["windows"]["window_rounds"] == telem.WINDOW_ROUNDS
    assert sum(dw["windows"]["decided"]) == d["decided"]
    # (No window_rounds=0 runtime cell here — that build's program
    # identity with the PR-6 recorder is pinned far more strongly by
    # the HLO tier: sim.run_rounds_telemetry's golden is
    # byte-unchanged across the windowing change, re-checked every
    # `make audit` — and a third engine compile is ~15 s of tier-1.)


def test_engine_flag_validation():
    cfg = SimConfig(n_nodes=3, proposers=(0, 1), n_instances=16)
    pend, gate, tail, c = simm.prepare_queues(cfg, WL)
    with pytest.raises(ValueError, match="sharded"):
        simm.build_engine(cfg, c, vid_cap=0, telemetry=True,
                          axis_name="i")
    rf = simm.build_engine(cfg, c, vid_cap=0, telemetry=True)
    from tpu_paxos.utils import prng

    root = prng.root_key(0)
    st = simm.init_state(cfg, pend, gate, tail, root)
    with pytest.raises(TypeError, match="Telemetry"):
        rf(root, st)


# ---------------- neutrality: fleet lanes / runtime knobs (slow) ----------------


@pytest.mark.slow
def test_fleet_recorder_parity_grid():
    """Recorder on/off sha256 parity where it costs the most: 5-node
    fleet lanes under a partition+pause+burst schedule with
    drop/dup/delay/crash knobs — the runtime-knob path — plus the
    single-run telemetry engine, all four decision-log-identical; and
    the fleet lane's reduced summary equals the single-run summary
    field-for-field (the vmap changes nothing)."""
    from tpu_paxos.fleet import envelope as env

    sched = flt.FaultSchedule((
        flt.partition(4, 16, (0, 1), (2, 3, 4)),
        flt.pause(6, 14, 2),
        flt.burst(5, 12, 1500),
    ))
    cfg = SimConfig(
        n_nodes=5, n_instances=48, proposers=(0, 1), seed=3,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2,
                           crash_rate=3000, schedule=sched),
    )
    fc = cfg.faults
    a = simm.run(cfg, WL)
    b, summ, wsum = simm.run_with_telemetry(cfg, WL)
    r_plain = env.runner_for(cfg, WL)
    r_tel = env.runner_for(cfg, WL, telemetry=True)
    assert r_tel is not r_plain  # the armed twin is its own envelope
    kw = dict(workloads=[(WL, None)] * 2,
              knobs=[dataclasses.replace(fc, schedule=None)] * 2)
    rp = r_plain.run([3, 4], [sched] * 2, **kw)
    rt = r_tel.run([3, 4], [sched] * 2, **kw)
    shas = {_log_sha(a), _log_sha(b),
            _log_sha(rp.lane_result(0)), _log_sha(rt.lane_result(0))}
    assert len(shas) == 1, "recorder or vmap changed the decision log"
    # lane 1 (different seed) agrees between armed and plain fleets
    assert _log_sha(rp.lane_result(1)) == _log_sha(rt.lane_result(1))
    assert rp.verdict.ok.all() and rt.verdict.ok.all()
    # the fleet's reduced lane summary IS the single-run summary —
    # including the [lanes, W] windowed series (same bucket width)
    assert rp.lane_telemetry(0) is None
    assert rp.windows is None and rt.windows is not None
    assert rt.lane_telemetry(0) == telem.summary_to_dict(
        summ, wsum, telem.WINDOW_ROUNDS
    )


@pytest.mark.slow
def test_stress_fleet_telemetry_golden(monkeypatch):
    """The stress sweep's per-mix telemetry block, golden-pinned: the
    block is a pure function of (cfg, seeds) — no wall clock — so any
    drift is a real behaviour change (recorder semantics, engine
    decision path, or mix definition) and must update the golden."""
    from tpu_paxos.harness import stress

    summary = stress.sweep_fleet(
        n_seeds=2, verbose=False, mixes=stress.EPISODE_MIXES[:1]
    )
    assert summary["ok"], summary["failures"]
    got = summary["telemetry"]
    path = os.path.join(DATA, "stress_telemetry_golden.json")
    want = json.load(open(path))
    assert got == want, (
        "stress telemetry block drifted from tests/data/"
        "stress_telemetry_golden.json — if deliberate, re-pin with "
        "tests/data/gen_telemetry_goldens.py"
    )
    blk = got["partition-flap"]
    assert blk["offered"] > 0
    assert blk["latency_p50"] <= blk["latency_p99"] <= blk["latency_max"]


@pytest.mark.slow
def test_trace_cli_golden():
    """``python -m tpu_paxos trace`` on the committed fleet-quick
    wedge artifact emits the exact golden Chrome-trace JSON (telemetry
    recomputed at replay; artifact untouched), exit 0."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    try:
        from _subproc import scrubbed_env
        envv = scrubbed_env()
    finally:
        sys.path.pop(0)
    envv["JAX_PLATFORMS"] = "cpu"
    before = open(os.path.join(REPO, WEDGE_ARTIFACT), "rb").read()
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "trace", WEDGE_ARTIFACT,
         "--stdout"],
        cwd=REPO, env=envv, capture_output=True, text=True, timeout=500,
    )
    assert proc.returncode == 0, proc.stderr
    got = json.loads(proc.stdout)
    want = json.load(open(os.path.join(DATA, "trace_golden.json")))
    assert got == want, (
        "trace JSON drifted from tests/data/trace_golden.json — if "
        "deliberate, re-pin with tests/data/gen_telemetry_goldens.py"
    )
    assert open(os.path.join(REPO, WEDGE_ARTIFACT), "rb").read() == before


def test_trace_serve_mode():
    """``python -m tpu_paxos trace --serve`` (PR 15): a fresh
    open-loop serve run rendered in-process — windowed counter
    tracks, the flow-linked per-instance phase spans on the
    ``phases`` process, and the diagnosis block in otherData.  The
    flow cap drops deterministically (first N by decision round) and
    is announced in otherData, never silently."""
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = texport.main([
            "--serve", "--values", "24", "--rate-milli", "16000",
            "--nodes", "3", "--slo-latency", "64",
            "--max-flow-instances", "8", "--stdout", "--json",
        ])
    assert rc == 0
    # --stdout prints the trace; --json appends the status line
    out = buf.getvalue()
    trace = json.loads(out[:out.rindex("\n{") + 1] if "\n{" in out
                       else out)
    other = trace["otherData"]
    assert other["engine"] == "serve" and other["decided"] == 24
    assert other["flow_instances"] == 8
    assert other["flow_instances_dropped"] == 24 - 8
    assert "diagnosis" in other and "telemetry" in other
    evs = trace["traceEvents"]
    spans = [e for e in evs if e["ph"] == "X"
             and e["name"].split(" ")[0] in telem.PHASE_NAMES]
    assert spans, "no phase spans rendered"
    assert {e["name"].split(" ")[0] for e in spans} >= {"consensus"}
    # every sampled instance's spans are flow-linked (s/t/f chain)
    flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
    assert flows and {e["ph"] for e in flows} >= {"s"}
    # queue-wait spans exist only where ingest-stamped admission
    # waited; consensus spans cover every sampled instance
    per_slot = {}
    for e in spans:
        per_slot.setdefault(e["tid"], set()).add(
            e["name"].split(" ")[0]
        )
    assert len(per_slot) == 8
    assert all("consensus" in ph for ph in per_slot.values())
