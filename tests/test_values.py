"""Value-id interning and no-op encoding (SURVEY component 14)."""

import numpy as np

from tpu_paxos.core import values as val


def test_real_vid_roundtrip():
    stride = 1 << 20
    v = val.real_vid(3, 12345, stride)
    assert int(val.real_proposer_of(v, stride)) == 3
    assert int(val.real_seq_of(v, stride)) == 12345
    assert not bool(val.is_noop(v))
    assert not bool(val.is_none(v))


def test_noop_vid_distinct_and_decodable():
    n_inst = 1000
    seen = set()
    for p in range(3):
        for i in (0, 1, 999):
            v = int(val.noop_vid(i, p, n_inst))
            assert v <= val.NOOP_BASE
            assert bool(val.is_noop(v))
            seen.add(v)
            pp, ii = val.noop_decode(v, n_inst)
            assert (int(pp), int(ii)) == (p, i)
    assert len(seen) == 9


def test_decode_host_matches_device_encoding():
    stride, n_inst = 1 << 20, 777
    p, s, noop = val.decode_host(int(val.real_vid(2, 42, stride)), stride, n_inst)
    assert (p, s, noop) == (2, 42, False)
    p, i, noop = val.decode_host(int(val.noop_vid(5, 1, n_inst)), stride, n_inst)
    assert (p, i, noop) == (1, 5, True)


def test_decode_host_array():
    stride, n_inst = 100, 50
    vids = np.array(
        [int(val.real_vid(1, 7, stride)), int(val.noop_vid(3, 2, n_inst)), 0]
    )
    p, v, noop = val.decode_host_array(vids, stride, n_inst)
    assert p.tolist() == [1, 2, 0]
    assert v.tolist() == [7, 3, 0]
    assert noop.tolist() == [False, True, False]


def test_intern_table():
    t = val.InternTable()
    a = t.intern(b"hello")
    b = t.intern("hello")
    c = t.intern(b"world")
    assert a == b == 0
    assert c == 1
    assert t.payload(0) == b"hello"
    assert len(t) == 2
