"""Open-loop serving harness (tpu_paxos/serve/).

The load-bearing contract is ZERO-LOAD PARITY: a serve run whose
whole stream arrives at round 0 (offered-load-∞, all admitted in
window 0) must be decision-log sha256-IDENTICAL to the closed-loop
engine on the same (cfg, workload) — the serving path (device-side
admission, donated loop state, fixed-span windows that run past
quiescence, ingest-stamped telemetry) may not perturb the protocol.
Alongside: the pipelined and sequential dispatch modes run
bit-identical trajectories (the bench's "at equal p99" is exact), the
admission plan admits every value exactly once at the first window
boundary at or after its arrival, and the ingest-stamped latency
ledger excludes no-op fills and undecided instances.

All engine-bearing cells share ONE serve-driver compile (module
geometry below) plus one closed-loop compile — budget ~20 s fast-tier.
"""

import hashlib
import json

import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.core import values as val
from tpu_paxos.replay.decision_log import decision_log
from tpu_paxos.serve import arrivals as arrv
from tpu_paxos.serve import driver as drv
from tpu_paxos.serve import harness as sh
from tpu_paxos.utils import prng

# One geometry for every engine-bearing cell: a single cached window
# builder (drv.window_for) serves the parity, Poisson, and
# mode-equality tests; only the S=1-vs-S=2 granularity pin pays a
# second (S=1) executable of the same program.
WL = [np.arange(0, 10, dtype=np.int32), np.arange(20, 30, dtype=np.int32)]
R_WINDOW = 8
S_DISPATCH = 2  # windows per dispatch for the shared executable
ADMIT_W = 10  # max stream length: covers the zero-load window-0 block


def _cfg(seed=3):
    return SimConfig(
        n_nodes=3, n_instances=48, proposers=(0, 1), seed=seed,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )


def _sha(chosen_vid, chosen_ballot):
    text = decision_log(
        chosen_vid, chosen_ballot, stride=30, n_instances=len(chosen_vid)
    )
    return hashlib.sha256(text.encode()).hexdigest()


def _serve(cfg, arrs, **kw):
    kw.setdefault("rounds_per_window", R_WINDOW)
    kw.setdefault("windows_per_dispatch", S_DISPATCH)
    kw.setdefault("admit_width", ADMIT_W)
    return sh.serve_run(cfg, WL, arrs, **kw)


# ---------------- arrival processes (pure host) ----------------


def test_poisson_rounds_deterministic_and_sorted():
    a = arrv.poisson_rounds(64, 2000, seed=9)
    b = arrv.poisson_rounds(64, 2000, seed=9)
    assert (a == b).all()
    assert a.dtype == np.int32
    assert (np.diff(a) >= 0).all()
    assert (arrv.poisson_rounds(64, 2000, seed=10) != a).any()
    # rate scales the span: 10x the rate ends ~10x sooner
    fast = arrv.poisson_rounds(64, 20_000, seed=9)
    assert fast[-1] < a[-1]
    with pytest.raises(ValueError, match="immediate_rounds"):
        arrv.poisson_rounds(8, 0, seed=0)


def test_arrivals_imports_jax_free():
    """The admission planner runs on a serving host's ingestion
    thread: ``serve.arrivals`` (and the lazy ``tpu_paxos.serve``
    package import) must not drag in jax.  Subprocess so the
    already-imported jax of this suite can't mask a regression."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "import tpu_paxos.serve\n"
         "from tpu_paxos.serve import arrivals\n"
         "assert 'jax' not in sys.modules, 'jax leaked'\n"
         "assert arrivals.poisson_rounds(4, 1000, 0).dtype.kind == 'i'\n"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_trace_rounds_validation():
    assert (arrv.trace_rounds([0, 1, 1, 5]) == [0, 1, 1, 5]).all()
    with pytest.raises(ValueError, match="nondecreasing"):
        arrv.trace_rounds([3, 2])
    with pytest.raises(ValueError, match="nonnegative"):
        arrv.trace_rounds([-1, 2])
    assert (arrv.immediate_rounds(3) == 0).all()


def test_split_round_robin_preserves_order():
    vids = np.arange(7, dtype=np.int32)
    rounds = np.asarray([0, 0, 1, 2, 2, 3, 9], np.int32)
    streams, arrs = arrv.split_round_robin(vids, rounds, 2)
    assert [s.tolist() for s in streams] == [[0, 2, 4, 6], [1, 3, 5]]
    assert [a.tolist() for a in arrs] == [[0, 1, 2, 9], [0, 2, 3]]
    for a in arrs:
        assert (np.diff(a) >= 0).all()


def test_arrival_plan_admits_each_value_once_at_or_after_arrival():
    rng = np.random.default_rng(4)
    for r_win in (1, 4, 8):
        rounds = np.sort(rng.integers(0, 40, size=23)).astype(np.int32)
        vids = np.arange(23, dtype=np.int32)
        streams, arrs = arrv.split_round_robin(vids, rounds, 2)
        plan = arrv.ArrivalPlan(streams, arrs, r_win)
        k = plan.max_block
        seen = {}
        for j in range(plan.n_windows + 2):  # +2: drain windows empty
            admit, arr = plan.block(j, k)
            for pi in range(2):
                row = admit[pi]
                got = row[row != int(val.NONE)]
                # NONE-padded prefix: values only at the front
                assert (row[len(got):] == int(val.NONE)).all()
                for o, v in enumerate(got):
                    assert int(v) not in seen
                    seen[int(v)] = (j, int(arr[pi, o]))
        assert len(seen) == 23
        for v, (j, a_round) in seen.items():
            # admitted at the first boundary >= arrival, stamped with
            # the TRUE arrival round
            assert a_round == int(rounds[v])
            assert j * r_win >= a_round
            assert j == 0 or (j - 1) * r_win < a_round


def test_arrival_plan_rejects_too_narrow_width():
    plan = arrv.ArrivalPlan(
        [np.arange(6, dtype=np.int32)], [np.zeros(6, np.int32)], 4
    )
    with pytest.raises(ValueError, match="admit_width"):
        plan.block(0, plan.max_block - 1)


def test_realism_arrival_builders_deterministic():
    """The heavy-tailed / bursty / diurnal builders: deterministic
    per seed, sorted int32, seed-sensitive, and each exhibiting its
    shape on a pinned draw (the draws are deterministic, so the shape
    assertions are exact, not statistical)."""
    for name in ("pareto", "bursty", "diurnal"):
        f = arrv.ARRIVAL_BUILDERS[name]
        a, b = f(96, 2000, 5), f(96, 2000, 5)
        assert (a == b).all() and a.dtype == np.int32
        assert (np.diff(a) >= 0).all()
        assert (f(96, 2000, 6) != a).any(), name
        # the shared-signature contract: n_values=0 is an empty
        # stream, never a crash
        assert len(f(0, 2000, 5)) == 0, name
    # pareto: heavy tail — the largest gap dwarfs the mean gap (20x+
    # on this pinned draw; the same-seed exponential peaks at ~6x)
    gaps = np.diff(arrv.pareto_rounds(96, 200, 5))
    assert gaps.max() > 20 * (1000 // 200)
    assert gaps.max() > 2 * np.diff(arrv.poisson_rounds(96, 200, 5)).max()
    # bursty: values share arrival rounds in bursts
    br = arrv.bursty_rounds(96, 2000, 5, burst=8)
    assert len(np.unique(br)) < len(br) // 2
    # diurnal: the peak half-period carries more arrivals than the
    # trough half (rate swings sinusoidally)
    dr = arrv.diurnal_rounds(256, 2000, 5, period=512, depth=0.8)
    phase = (dr % 512) < 256
    assert phase.sum() > (~phase).sum()
    with pytest.raises(ValueError, match="alpha"):
        arrv.pareto_rounds(8, 1000, 0, alpha=1.0)
    with pytest.raises(ValueError, match="burst"):
        arrv.bursty_rounds(8, 1000, 0, burst=0)
    with pytest.raises(ValueError, match="depth"):
        arrv.diurnal_rounds(8, 1000, 0, depth=1.0)
    for name in ("pareto", "bursty", "diurnal"):
        with pytest.raises(ValueError, match="immediate_rounds"):
            arrv.ARRIVAL_BUILDERS[name](8, 0, 0)


def test_ingest_stamps_defeat_coordinated_omission():
    """Acceptance pin for the realism axis: latency is judged from
    INGEST-time stamps, not dispatch-time.  Values arriving just
    AFTER a window boundary stall a full admission window before the
    next upload (the mid-run stall: R-1 rounds of waiting the server
    never sees as work); a coordinated-omission twin that stamps them
    at their dispatch round runs the IDENTICAL trajectory but reports
    every latency exactly that stall shorter.  The harness must
    charge the wait: same decisions, whole histogram shifted, max
    latency exactly +stall.  Shares the module's one executable."""
    cfg = _cfg()
    stall = R_WINDOW - 1
    # true arrivals: 1 past each boundary; the CO twin quantizes each
    # to its admission (dispatch) round — same admission blocks, so
    # bit-identical protocol trajectories
    true_arrs = [
        np.asarray([j * R_WINDOW + 1 for j in range(10)], np.int32)
        for _ in range(2)
    ]
    co_arrs = [a + stall for a in true_arrs]
    a = _serve(cfg, true_arrs)
    b = _serve(cfg, co_arrs)
    assert (a.chosen_vid == b.chosen_vid).all()
    assert (a.chosen_ballot == b.chosen_ballot).all()
    assert a.decided_values == b.decided_values == 20
    # every value's latency shifts by exactly the stall
    assert a.latency_max == b.latency_max + stall
    # the ingest-stamped distribution strictly dominates the CO twin
    ha = np.cumsum(a.summary["latency_hist"])
    hb = np.cumsum(b.summary["latency_hist"])
    assert (ha <= hb).all() and (ha < hb).any()
    assert a.p50 >= b.p50


# ---------------- device-side admission + stamping ----------------


def test_admit_block_appends_at_tail_and_preserves_padding():
    cfg = _cfg()
    pend, gate, tail, c = simm.prepare_queues(cfg, WL)
    st = simm.init_state(
        cfg, np.full_like(pend, int(val.NONE)), gate, np.zeros_like(tail),
        prng.root_key(0),
    )
    blk1 = np.asarray(
        [[0, 1, 2, int(val.NONE)], [20, int(val.NONE)] + [int(val.NONE)] * 2],
        np.int32,
    )
    st = simm.admit_block(st, blk1)
    assert np.asarray(st.prop.tail).tolist() == [3, 1]
    blk2 = np.asarray(
        [[3, int(val.NONE), int(val.NONE), int(val.NONE)],
         [21, 22, int(val.NONE), int(val.NONE)]], np.int32,
    )
    st = simm.admit_block(st, blk2)
    pend2 = np.asarray(st.prop.pend)
    assert pend2[0, :4].tolist() == [0, 1, 2, 3]
    assert pend2[1, :3].tolist() == [20, 21, 22]
    assert np.asarray(st.prop.tail).tolist() == [4, 3]
    # everything at and past tail stays NONE (the ring invariant the
    # engine's window ops and the next admission rely on)
    assert (pend2[0, 4:] == int(val.NONE)).all()
    assert (pend2[1, 3:] == int(val.NONE)).all()


def test_admit_block_wide_block_near_capacity_never_clamps():
    """Regression: a bare dynamic_update_slice clamps its start when
    tail + K passes the row end, silently rewriting LIVE entries
    below tail with the new block — reachable with a wide admission
    block (bursty plan: K > assign_window + 8) on a queue near
    capacity.  admit_block writes through a K-padded row, so only
    NONE padding ever spills and entries below tail are untouched."""
    cfg = _cfg()
    pend, gate, tail, c = simm.prepare_queues(cfg, WL)
    width = pend.shape[1]
    k = width  # pathologically wide block: start would clamp to 0
    pend0 = np.full_like(pend, int(val.NONE))
    near = width - 3  # tail close to the row end
    pend0[0, :near] = np.arange(near, dtype=np.int32) + 1000
    tail0 = np.asarray([near, 0], np.int32)
    st = simm.init_state(cfg, pend0, gate, tail0, prng.root_key(0))
    blk = np.full((2, k), int(val.NONE), np.int32)
    blk[0, 0] = 7  # one real value; the rest is padding
    st2 = simm.admit_block(st, blk)
    out = np.asarray(st2.prop.pend)
    assert (out[0, :near] == pend0[0, :near]).all()  # live entries intact
    assert out[0, near] == 7
    assert (out[0, near + 1:] == int(val.NONE)).all()
    assert np.asarray(st2.prop.tail).tolist() == [near + 1, 0]


def test_serve_admit_rounds_filters_noops_and_undecided():
    import jax.numpy as jnp

    from tpu_paxos.telemetry import recorder as telem

    ingest = jnp.asarray([5, int(val.NONE), 7, 9], jnp.int32)
    chosen = jnp.asarray(
        [0, 2, int(val.NONE), int(val.NOOP_BASE) - 3, 3, 99], jnp.int32
    )
    adm = np.asarray(telem.serve_admit_rounds(ingest, chosen))
    #           vid0  vid2  none  noop  vid3  out-of-table
    assert adm.tolist() == [5, 7, -1, -1, 9, -1]


# ---------------- the serving loop (shared driver compile) ----------


def test_zero_load_parity_decision_log_sha256():
    """Acceptance pin: offered-load-∞ (all values admitted in window
    0) is decision-log sha256-identical to closed-loop ``run()`` —
    the serving path may not perturb the protocol."""
    cfg = _cfg()
    a = simm.run(cfg, WL)
    rep = _serve(cfg, [np.zeros(len(w), np.int32) for w in WL])
    assert rep.done and rep.backlog == 0
    assert _sha(a.chosen_vid, a.chosen_ballot) == _sha(
        rep.chosen_vid, rep.chosen_ballot
    )
    assert (a.chosen_vid == rep.chosen_vid).all()
    assert (a.chosen_ballot == rep.chosen_ballot).all()
    # serve windows run fixed spans PAST quiescence; only the round
    # counter may differ, never the decisions
    assert rep.rounds >= a.rounds


_MID_STREAM_ARRS = [np.sort(a) for a in (
    np.asarray([0, 2, 3, 9, 9, 11, 17, 20, 21, 33], np.int32),
    np.asarray([0, 0, 5, 8, 13, 13, 14, 25, 30, 31], np.int32),
)]  # mid-stream lulls: the engine quiesces between arrivals, so the
#     stop logic's "done AND every admission seen" guard is exercised


def _assert_same_trajectory(a, b):
    assert (a.chosen_vid == b.chosen_vid).all()
    assert (a.chosen_ballot == b.chosen_ballot).all()
    for field in ("p50", "p99", "p999", "latency_max", "decided_values",
                  "backlog"):
        assert getattr(a, field) == getattr(b, field), field
    assert a.summary["latency_hist"] == b.summary["latency_hist"]


def test_pipelined_and_sequential_harvest_equal_trajectories():
    """Host scheduling touches nothing traced: deferred (double-
    buffered) vs blocking harvest produce the same decisions and the
    same latency histogram — only wall clock and the pipeline's one
    extra drain dispatch may differ."""
    cfg = _cfg()
    rp = _serve(cfg, _MID_STREAM_ARRS, pipelined=True)
    rs = _serve(cfg, _MID_STREAM_ARRS, pipelined=False)
    _assert_same_trajectory(rp, rs)
    assert rp.done and rs.done and rp.backlog == 0


@pytest.mark.slow
def test_dispatch_granularity_equal_trajectories():
    """The bench's "at equal p99" is exact: admission happens every
    rounds_per_window rounds stamped with true arrival rounds
    regardless of how many windows one dispatch batches — the S=1
    sequential-dispatch baseline runs the identical trajectory (its
    own executable, hence slow-tier)."""
    cfg = _cfg()
    rp = _serve(cfg, _MID_STREAM_ARRS, pipelined=True)
    rseq = _serve(cfg, _MID_STREAM_ARRS, windows_per_dispatch=1,
                  pipelined=False)
    _assert_same_trajectory(rp, rseq)
    assert rseq.windows_per_dispatch == 1
    assert rseq.dispatches > rp.dispatches


def test_poisson_open_loop_drains_and_measures_latency():
    cfg = _cfg()
    rounds = arrv.poisson_rounds(20, 1500, seed=7)
    vids = np.concatenate(WL)
    # keep each proposer's queue order = WL order: split by vid block,
    # arrival order within block follows the Poisson draw
    arrs = [np.sort(rounds[0::2]), np.sort(rounds[1::2])]
    rep = _serve(cfg, arrs)
    assert rep.done
    assert rep.decided_values == len(vids)
    assert rep.backlog == 0
    assert 0 <= rep.p50 <= rep.p99 <= rep.p999 <= rep.latency_max
    # the histogram carries exactly the stamped real values
    assert sum(rep.summary["latency_hist"]) == len(vids)
    # cumulative decided series is nondecreasing and ends complete
    assert rep.window_decided == sorted(rep.window_decided)
    # mid-run quiescence + later admissions: multiple dispatches, and
    # the final summary is still the full stream's
    assert rep.dispatches >= 2
    assert rep.windows_count == rep.dispatches * S_DISPATCH


def test_window_cache_reuses_executable():
    cfg = _cfg()
    _, _, _, c = simm.prepare_queues(cfg, WL)
    vb = drv.vid_bound_of(WL)
    assert drv.window_for(cfg, c, vb, R_WINDOW) is drv.window_for(
        cfg, c, vb, R_WINDOW
    )
    assert drv.window_for(cfg, c, vb, R_WINDOW + 1) is not drv.window_for(
        cfg, c, vb, R_WINDOW
    )
    # a schedule-bearing cfg must fail LOUDLY even on a warm cache
    # (the key ignores the schedule; a silent hit would drop the
    # requested correlated faults)
    import dataclasses

    from tpu_paxos.core import faults as fltm

    sched_cfg = dataclasses.replace(
        cfg, faults=dataclasses.replace(
            cfg.faults,
            schedule=fltm.FaultSchedule((fltm.pause(1, 3, 0),)),
        ),
    )
    with pytest.raises(ValueError, match="no fault schedule"):
        drv.window_for(sched_cfg, c, vb, R_WINDOW)


# ---------------- the SLO burn-rate monitor ----------------


def test_slo_mid_run_breach_run_total_green():
    """Acceptance pin: a burst episode under load whose mid-run
    latency breach the RUN-TOTAL histogram misses — the final
    distribution meets the declared budget (total_ok), but the
    windowed burn-rate monitor names the burst's bucket as a breach
    window.  Same (S, K, R, window_rounds) shapes as every other
    cell, so this rides the module's one shared executable."""
    cfg = _cfg()
    # trickle at one value per 40 rounds, then a 6-value burst at
    # round 128 (bucket 128 // 32 = 4 of the windowed series)
    arrs = [
        np.asarray(sorted([i * 40 for i in range(7)] + [128] * 3),
                   np.int32)
        for _ in range(2)
    ]
    slo = sh.ServeSLO(latency_rounds=16, budget_milli=400)
    rep = _serve(cfg, arrs, slo=slo)
    assert rep.done and rep.backlog == 0
    assert rep.slo is not None
    # the run-total verdict is GREEN: overall bad fraction is under
    # the budget, so a histogram-only judge calls this run healthy
    assert rep.slo["total_ok"]
    assert rep.slo["total_bad_milli"] <= slo.budget_milli
    # ...but the windowed monitor names breach windows, the burst's
    # bucket among them, with their virtual-round spans
    assert not rep.slo["ok"]
    assert 4 in rep.slo["breach_windows"]
    i4 = rep.slo["breach_windows"].index(4)
    assert rep.slo["breach_spans"][i4] == [128, 160]
    assert rep.slo["burn_max"] >= slo.burn_breach
    # the monitor runs per dispatch: the breach was visible mid-run,
    # not only in the post-hoc report
    assert rep.slo_first_breach_dispatch is not None
    assert rep.slo_first_breach_dispatch <= rep.dispatches
    # the sweep-point rendering carries the verdict and the windowed
    # medians the upgraded knee judgment reads
    pt = sh._point(0, rep)
    assert pt["slo"]["breach_windows"] == rep.slo["breach_windows"]
    assert pt["p50_steady"] >= 1
    assert len(pt["p50_windows"]) == len(rep.windows["decided"])


def test_serve_windowed_plane_consistency():
    """The windowed series is a refinement of the run-total summary
    (same executable as the parity cells): per-bucket decided counts
    and latency deltas sum back to the totals.  (Armed-vs-plain
    trajectory equality for the serve path is pinned by the bench's
    overhead guard; the single-run twin is pinned fast-tier in
    test_telemetry.)"""
    cfg = _cfg()
    rep = _serve(cfg, _MID_STREAM_ARRS)
    w = rep.windows
    assert w is not None and rep.window_rounds == 4 * R_WINDOW
    assert sum(w["decided"]) == rep.summary["decided"]
    total = np.asarray(w["lat_hist"]).sum(axis=0)
    assert total.tolist() == rep.summary["latency_hist"]
    assert sum(w["dropped"]) == rep.summary["dropped_total"]
    # every value decided inside the run's round span
    active = [i for i, n in enumerate(w["decided"]) if n]
    assert active and active[-1] * rep.window_rounds <= rep.rounds


# ---------------- knee judgment (pure host) ----------------


def test_judge_knee_brackets_saturation():
    points = [
        {"rate_milli": 1000, "p50": 10, "sustained": True},
        {"rate_milli": 2000, "p50": 12, "sustained": True},
        {"rate_milli": 4000, "p50": 25, "sustained": True},  # p50 blowup
        {"rate_milli": 8000, "p50": 400, "sustained": False},
    ]
    k = sh.judge_knee(points, factor=2.0)
    assert k["last_sustained_milli"] == 2000
    assert k["first_saturated_milli"] == 4000
    # an all-sustained flat sweep never crossed the knee
    k2 = sh.judge_knee(points[:2], factor=2.0)
    assert k2["last_sustained_milli"] == 2000
    assert k2["first_saturated_milli"] is None
    assert sh.judge_knee([])["first_saturated_milli"] is None
    assert k["p50_metric"] == "p50"  # no windowed series in sight


def test_judge_knee_prefers_windowed_steady_median():
    """Windowed points are judged on the steady-state median: a run
    whose warm-up drags the run-total p50 back under the doubling
    line still saturates when its LAST active window's median has
    blown out — the run-total column alone would misjudge it."""
    points = [
        {"rate_milli": 1000, "p50": 10, "p50_steady": 10,
         "sustained": True},
        # run-total 16 < 2x base, but the tail windows sit at 40:
        # saturation the total hides behind the warm-up
        {"rate_milli": 2000, "p50": 16, "p50_steady": 40,
         "sustained": True},
    ]
    k = sh.judge_knee(points, factor=2.0)
    assert k["p50_metric"] == "p50_steady"
    assert k["last_sustained_milli"] == 1000
    assert k["first_saturated_milli"] == 2000
    # without the windowed series the same totals judge sustained
    bare = [{k2: v for k2, v in pt.items() if k2 != "p50_steady"}
            for pt in points]
    kb = sh.judge_knee(bare, factor=2.0)
    assert kb["first_saturated_milli"] is None


def test_serve_point_shape():
    cfg = _cfg()
    rep = _serve(cfg, [np.zeros(len(w), np.int32) for w in WL])
    pt = sh._point(2000, rep)
    assert pt["sustained"] and pt["decided"] == 20 and pt["backlog"] == 0
    assert json.dumps(pt)  # JSON-ready


# ---------------- CLI (slow: subprocess + its own compile) ----------


@pytest.mark.slow
def test_serve_cli_end_to_end():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "serve", "--values", "24",
         "--rate-milli", "3000", "--nodes", "3", "--backend", "cpu"],
        capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["metric"] == "serve"
    assert summary["decided"] == 24 and summary["ok"]
    assert summary["p50"] <= summary["p99"] <= summary["p999"]
