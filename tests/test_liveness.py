"""Idle-liveness repair: holes left behind must get fixed by survivors.

ADVICE r3: a proposer whose queue drains stays PREPARED forever and
never re-prepares, so log holes and undelivered commits left by a
crashed proposer were never repaired.  The engine now restarts an idle
PREPARED proposer after IDLE_RESTART_ROUNDS rounds of an unresolved
log (core/sim.py stall counter), repairing holes through the normal
no-op hole-filling + committed-value re-adoption path
(ref multi/paxos.cpp:1106-1130, 1184-1197).
"""

import numpy as np

from tpu_paxos.config import SimConfig
from tpu_paxos.core import ballot as bal
from tpu_paxos.core import sim
from tpu_paxos.core import values as val
from tpu_paxos.harness import validate
from tpu_paxos.utils import prng


def test_idle_prepared_proposer_repairs_hole():
    """Instance 1 is chosen and learned everywhere; instance 0 is a
    hole (its proposer crashed before completing it).  The surviving
    proposer is already PREPARED with an empty queue — without the
    stall restart it would idle forever; with it, the hole gets a
    no-op and the run quiesces."""
    cfg = SimConfig(n_nodes=3, n_instances=4, proposers=(0,), seed=0,
                    max_rounds=200)
    workload = [np.zeros((0,), np.int32)]
    pend, gate, tail, c = sim.prepare_queues(cfg, workload)
    root = prng.root_key(cfg.seed)
    st = sim.init_state(cfg, pend, gate, tail, root)
    b = int(bal.make(1, 0))
    chosen = 500
    st = st._replace(
        acc=st.acc._replace(
            promised=jnp_full(st.acc.promised, b),
            max_seen=jnp_full(st.acc.max_seen, b),
            acc_ballot=st.acc.acc_ballot.at[:, 1].set(b),
            acc_vid=st.acc.acc_vid.at[:, 1].set(chosen),
        ),
        learned=st.learned.at[:, 1].set(chosen),  # [acceptor, inst]
        prop=st.prop._replace(
            mode=st.prop.mode.at[0].set(int(sim.PREPARED)),
            count=st.prop.count.at[0].set(1),
            ballot=st.prop.ballot.at[0].set(b),
            promises=st.prop.promises.at[0, :].set(True),
        ),
        met=st.met._replace(
            chosen_vid=st.met.chosen_vid.at[1].set(chosen),
            chosen_round=st.met.chosen_round.at[1].set(0),
            chosen_ballot=st.met.chosen_ballot.at[1].set(b),
        ),
    )
    expected = np.asarray([chosen])
    r = sim.run_state(cfg, st, root, expected, c)
    assert r.done, f"idle proposer never repaired the hole ({r.rounds} rounds)"
    assert bool(val.is_noop(r.chosen_vid[0])), "hole not no-op filled"
    assert int(r.chosen_vid[1]) == chosen
    validate.check_all(r.learned, expected)
    # The repair should happen shortly after the stall patience runs
    # out — not by grinding to max_rounds.
    assert r.rounds < 100


def jnp_full(arr, v):
    import jax.numpy as jnp

    return jnp.full_like(arr, v)


def test_crashed_proposer_holes_repaired_by_survivor():
    """Two proposers; node 1 (a proposer) is crashed from the start
    with its own assignments stranded at instances 2-3 while instance
    4 is already chosen.  Node 0's proposer must no-op-fill the
    stranded instances and finish."""
    cfg = SimConfig(n_nodes=5, n_instances=8, proposers=(0, 1), seed=1,
                    max_rounds=400)
    workload = [np.asarray([10, 11], np.int32), np.zeros((0,), np.int32)]
    pend, gate, tail, c = sim.prepare_queues(cfg, workload)
    root = prng.root_key(cfg.seed)
    st = sim.init_state(cfg, pend, gate, tail, root)
    b1 = int(bal.make(1, 1))
    st = st._replace(
        # acceptor 2 holds a stranded pre-accept from the dead proposer
        acc=st.acc._replace(
            acc_ballot=st.acc.acc_ballot.at[2, 2].set(b1),  # [acc 2, inst 2]
            acc_vid=st.acc.acc_vid.at[2, 2].set(999),
        ),
        crashed=st.crashed.at[1].set(True),
    )
    expected = np.asarray([10, 11, 999])
    r = sim.run_state(cfg, st, root, expected, c)
    assert r.done, f"survivor never finished ({r.rounds} rounds)"
    validate.check_all(r.learned, expected)
    assert 999 in r.chosen_vid.tolist()  # stranded value adopted, not lost
