"""Compile-census guard (analysis/tracecount.py): the counter sees
real XLA compilations exactly once per distinct program, budget
arithmetic flags the right module, and enforcement only arms for
census-comparable (full tier-1-shaped) runs."""

import jax
import jax.numpy as jnp
import pytest

from tpu_paxos.analysis import tracecount


def test_census_counts_fresh_compile_once(compile_census):
    """A distinct program compiles once; a cache hit adds zero.  The
    session census (conftest fixture) and this scoped one both see it
    — listeners stack."""
    local = tracecount.CompileCensus().start()
    local.set_label("probe")

    @jax.jit
    def probe(x):
        return (x * 3.25 + 17.5).sum() - 0.125

    x = jnp.full((13, 9), 2.0)
    before = local.counts.get("probe", 0)
    probe(x).block_until_ready()
    after_first = local.counts.get("probe", 0)
    probe(x).block_until_ready()
    after_second = local.counts.get("probe", 0)
    local.stop()
    assert after_first == before + 1
    assert after_second == after_first  # cached: no recompile
    assert compile_census.total() >= 1  # session census saw it too


def test_census_attributes_compiles_per_engine_scope():
    """engine_scope() is the per-engine attribution axis: a compile
    inside the scope lands on that engine's counter; one outside
    lands on NO_ENGINE."""
    local = tracecount.CompileCensus().start()
    local.set_label("engine-probe")

    @jax.jit
    def scoped(x):
        return (x * 2.75 - 3.5).sum() + 0.0625

    @jax.jit
    def unscoped(x):
        return (x / 1.75 + 42.0).prod()

    x = jnp.full((11, 5), 3.0)
    with tracecount.engine_scope("probe-engine"):
        scoped(x).block_until_ready()
    unscoped(x).block_until_ready()
    local.stop()
    assert local.engine_counts.get("probe-engine", 0) == 1
    assert local.engine_counts.get(tracecount.NO_ENGINE, 0) >= 1
    assert "per engine scope" in local.report()
    assert "probe-engine" in local.report()


def test_engine_scope_nesting_attributes_to_innermost():
    local = tracecount.CompileCensus().start()
    local.set_label("engine-nest")

    @jax.jit
    def inner_fn(x):
        return (x + 7.25).min() * 2.0

    x = jnp.full((3, 3), 1.0)
    with tracecount.engine_scope("outer"):
        with tracecount.engine_scope("inner"):
            inner_fn(x).block_until_ready()
        assert tracecount.current_engine() == "outer"
    local.stop()
    assert local.engine_counts.get("inner", 0) >= 1
    assert "outer" not in local.engine_counts
    assert tracecount.current_engine() == tracecount.NO_ENGINE


def test_run_state_compiles_under_sim_scope():
    """The sim engine's entry point really wraps its compile: a fresh
    tiny config compiled through run_state lands on the 'sim' engine
    counter."""
    import numpy as np

    from tpu_paxos.config import SimConfig
    from tpu_paxos.core import sim as simm
    from tpu_paxos.utils import prng

    cfg = SimConfig(n_nodes=3, n_instances=6, proposers=(0,),
                    max_rounds=64, seed=3)
    workload = [np.asarray([11, 12], np.int32)]
    pend, gate, tail, c = simm.prepare_queues(cfg, workload, None)
    root = prng.root_key(cfg.seed)
    state = simm.init_state(cfg, pend, gate, tail, root)
    local = tracecount.CompileCensus().start()
    local.set_label("sim-scope-probe")
    res = simm.run_state(cfg, state, root,
                         np.asarray([11, 12], np.int32), c, vid_cap=0)
    local.stop()
    assert res.done
    assert local.engine_counts.get("sim", 0) >= 1


def test_census_stop_deactivates():
    local = tracecount.CompileCensus().start()
    local.set_label("stopped")
    local.stop()

    @jax.jit
    def probe2(x):
        return (x - 5.75).prod()

    probe2(jnp.full((7, 3), 1.5)).block_until_ready()
    assert local.counts.get("stopped", 0) == 0


def test_budget_violation_names_culprit():
    c = tracecount.CompileCensus()
    c.counts = {"tests/test_a.py": 12, "tests/test_b.py": 3,
                tracecount.STARTUP: 99}
    budget = {"budgets": {"tests/test_a.py": 10, "tests/test_b.py": 10}}
    violations = c.check_budget(budget)
    assert len(violations) == 1
    assert violations[0].startswith("tests/test_a.py: 12")
    # startup compiles (collection/imports) are never budgeted
    assert not any(tracecount.STARTUP in v for v in violations)


def test_budget_default_cap_for_unknown_modules():
    c = tracecount.CompileCensus()
    c.counts = {"tests/test_new.py": 50}
    assert c.check_budget({"budgets": {}, "default_budget": 40})
    assert not c.check_budget({"budgets": {}, "default_budget": 60})
    assert not c.check_budget({"budgets": {}})  # no default: unjudged


def test_should_enforce_requires_full_visit(monkeypatch):
    monkeypatch.delenv("TPU_PAXOS_COMPILE_CENSUS", raising=False)
    c = tracecount.CompileCensus()
    budget = {"budgets": {"tests/test_a.py": 5, "tests/test_b.py": 5}}
    c.visited = {"tests/test_a.py"}
    assert not c.should_enforce(budget)  # partial run: not comparable
    c.visited = {"tests/test_a.py", "tests/test_b.py", "tests/extra.py"}
    assert c.should_enforce(budget)
    monkeypatch.setenv("TPU_PAXOS_COMPILE_CENSUS", "0")
    assert not c.should_enforce(budget)  # kill switch
    monkeypatch.setenv("TPU_PAXOS_COMPILE_CENSUS", "1")
    c.visited = set()
    assert c.should_enforce(budget)  # forced


def test_pin_roundtrip(tmp_path):
    path = str(tmp_path / "budget.json")
    data = tracecount.save_budget(
        {"tests/test_a.py": 10, tracecount.STARTUP: 7}, path
    )
    loaded = tracecount.load_budget(path)
    assert loaded == data
    # headroom 0.3 + slack 8 over the measured 10; startup excluded
    assert loaded["budgets"] == {"tests/test_a.py": 21}
    assert loaded["event"] == tracecount.COMPILE_EVENT


def test_pin_covers_visited_zero_compile_modules(tmp_path):
    """A module that compiled nothing at pin time still gets a floor
    cap — otherwise it stays uncapped and a later retrace regression
    there passes silently."""
    path = str(tmp_path / "budget.json")
    data = tracecount.save_budget(
        {"tests/test_a.py": 10}, path,
        visited={"tests/test_a.py", "tests/test_quiet.py"},
    )
    assert data["budgets"] == {
        "tests/test_a.py": 21, "tests/test_quiet.py": 8,
    }


@pytest.mark.slow
def test_enforcement_fails_run_with_named_culprit(tmp_path):
    """End-to-end: a pytest session whose compile count exceeds the
    budget exits non-zero and names the culprit module (the CI
    surface).  Forced via TPU_PAXOS_COMPILE_CENSUS=1 with a
    deliberately-impossible budget for one tiny module.  Marked slow
    (spawns a full pytest+jax subprocess); the budget arithmetic and
    sessionfinish wiring have fast unit coverage above."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    budget_path = tmp_path / "tight.json"
    budget_path.write_text(json.dumps({
        "version": 1,
        "event": tracecount.COMPILE_EVENT,
        "budgets": {"tests/test_values.py": 0},
    }))
    from _subproc import scrubbed_env

    env = scrubbed_env(
        extra_prefixes=("TPU_PAXOS_COMPILE",),
        JAX_PLATFORMS="cpu",
        TPU_PAXOS_COMPILE_CENSUS="1",
        TPU_PAXOS_COMPILE_BUDGET=str(budget_path),
    )
    p = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_values.py", "-q",
         "-m", "not slow", "-p", "no:cacheprovider"],
        capture_output=True, text=True, timeout=420, cwd=repo, env=env,
    )
    assert p.returncode != 0, p.stdout[-2000:]
    assert "compile-census budget EXCEEDED" in p.stdout
    assert "tests/test_values.py" in p.stdout  # the named culprit


def test_committed_budget_matches_tier1_suite():
    """The pinned budget file names real tier-1 test modules (a
    renamed/deleted module must be re-pinned, not left stale)."""
    import os

    import pytest

    if os.environ.get("TPU_PAXOS_COMPILE_CENSUS_PIN"):
        pytest.skip("pinning run: the budget file is being regenerated")
    budget = tracecount.load_budget()
    assert budget, "tpu_paxos/analysis/compile_budget.json missing"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for label in budget["budgets"]:
        assert os.path.exists(os.path.join(repo, label)), (
            f"stale compile budget entry {label}: module no longer "
            "exists — re-pin via TPU_PAXOS_COMPILE_CENSUS_PIN"
        )
