"""mc churn scope (PR 17): exhaustive bounded model checking of
membership reconfiguration crossed with faults, through the member
fleet (``analysis/mc_member.py``).

Contracts: the (variant x fault-combo x seed) codec is a bijection
with the scenario index as the stable name, churn variants respect
the ``ChurnSchedule`` grammar (distinct vids, del-after-add, first
event ``WAIT_NONE``), feasibility excludes crashes inside
``{0} | churn targets`` by the named rule, gray is rejected at
parse time by the data-driven :data:`mc_member.MEMBER_UNSUPPORTED_KINDS`
table, and the committed ``churn`` scope certifies clean on device
with zero warm compiles.

The device dispatch tests are slow-marked (member-fleet compile);
their fast-tier coverage is the host-only codec/validator/variant
tests here plus test_modelcheck.py's committed-certificate count pins
and test_member_fleet.py's lane-parity pins on the same runner.
"""

import json
import os

import pytest

from tpu_paxos.analysis import mc_member as mcm
from tpu_paxos.analysis import modelcheck as mc
from tpu_paxos.membership import churn_table as ctm
from tpu_paxos.membership import engine as meng

TINY = {
    "n_nodes": 3, "n_instances": 8, "max_rounds": 100, "horizon": 12,
    "plain_values": 1, "add_targets": [1], "del_targets": [1],
    "t0_grid": [0, 4], "wait_gates": [ctm.WAIT_NONE, ctm.WAIT_APPLIED],
    "max_events": 2,
}


def _committed():
    return mc.load_scopes()["churn"]


# ---------------- scope parse / validate ----------------

def test_committed_churn_scope_loads_and_registers():
    scope = _committed()
    assert mc.scope_type(scope) == "churn"
    assert isinstance(mc.enum_for(scope), mcm.ChurnEnum)
    # "type" is part of the hash: a fault scope with coincidentally
    # equal fields can never collide
    assert mcm.ChurnScope.from_dict(
        {k: v for k, v in scope.to_dict().items() if k != "type"}
    ).sha256() == scope.sha256()


def test_gray_rejected_by_the_data_driven_table():
    """The rejection is table-driven, not string-matched: the error
    text IS the table row, and dropping the row admits the kind."""
    assert "gray" in mcm.MEMBER_UNSUPPORTED_KINDS
    with pytest.raises(mc.ScopeError) as ei:
        mcm.ChurnScope.from_dict(dict(
            TINY, kinds=["gray"], intervals=[[2, 8]],
        ))
    assert mcm.MEMBER_UNSUPPORTED_KINDS["gray"] in str(ei.value)
    # the fault scopes' own rejection table applies transitively
    for kind, reason in mc.UNSUPPORTED_KINDS.items():
        with pytest.raises(mc.ScopeError, match="churn checker"):
            mcm.ChurnScope.from_dict(dict(
                TINY, kinds=[kind], intervals=[[2, 8]],
            ))


def test_validator_named_rules():
    with pytest.raises(mc.ScopeError, match="node 0"):
        mcm.ChurnScope.from_dict(dict(TINY, add_targets=[0]))
    with pytest.raises(mc.ScopeError, match="subset of add_targets"):
        mcm.ChurnScope.from_dict(dict(TINY, del_targets=[2]))
    with pytest.raises(mc.ScopeError, match="horizon"):
        mcm.ChurnScope.from_dict(dict(TINY, t0_grid=[12]))
    with pytest.raises(mc.ScopeError, match="wait_gates"):
        mcm.ChurnScope.from_dict(dict(TINY, wait_gates=[7]))
    with pytest.raises(mc.ScopeError, match=rf"\[1, {ctm.MAX_EVENTS}\]"):
        mcm.ChurnScope.from_dict(
            dict(TINY, max_events=ctm.MAX_EVENTS + 1)
        )
    with pytest.raises(mc.ScopeError, match="no churn letters"):
        mcm.ChurnScope.from_dict(
            dict(TINY, plain_values=0, add_targets=[], del_targets=[])
        )
    with pytest.raises(mc.ScopeError, match="unknown scope field"):
        mcm.ChurnScope.from_dict(dict(TINY, proposers=2))


# ---------------- codec ----------------

def test_codec_bijection_exhaustive_committed():
    """index -> scenario -> index is the identity over the ENTIRE
    committed churn universe, and out-of-range indices raise."""
    enum = mcm.ChurnEnum(_committed())
    for i in range(enum.total):
        assert enum.encode(enum.decode(i)) == i
    for bad in (-1, enum.total):
        with pytest.raises(IndexError):
            enum.decode(bad)


def test_codec_boundaries_at_churn_grid_edges():
    """The first and last index of every variant block decode to that
    variant with the extreme fault rank / seed — the churn-grid
    boundary cells the mixed-radix codec must not shear."""
    enum = mcm.ChurnEnum(_committed())
    per_variant = enum.n_fault_combos * enum.n_seeds
    for vi in range(enum.n_variants):
        lo = enum.decode(vi * per_variant)
        hi = enum.decode((vi + 1) * per_variant - 1)
        assert lo.variant == hi.variant == vi
        assert lo.seed == 0 and mc.combo_rank(
            lo.combo, enum.m, enum.scope.max_fault_episodes
        ) == 0
        assert hi.seed == enum.n_seeds - 1 and mc.combo_rank(
            hi.combo, enum.m, enum.scope.max_fault_episodes
        ) == enum.n_fault_combos - 1


def test_variant_zero_is_the_fault_only_baseline():
    enum = mcm.ChurnEnum(_committed())
    assert enum.variants[0] is None
    sc = enum.decode(0)
    assert sc.variant == 0
    assert enum.churn_of(sc) is None


# ---------------- variant grammar ----------------

def test_variants_obey_the_schedule_grammar():
    """Every enumerated variant materializes to a legal ChurnSchedule:
    distinct vids, dels only after their adds, first wait forced
    ``WAIT_NONE``, later waits drawn from the scope's gates."""
    scope = _committed()
    enum = mcm.ChurnEnum(scope)
    assert enum.n_variants == len(set(map(str, enum.variants)))
    for vi in range(1, enum.n_variants):
        churn = enum.churn_of(
            mcm.ChurnScenario(0, vi, (), 0)
        )
        vids = [e.vid for e in churn.events]
        assert len(vids) == len(set(vids)), vi
        assert churn.events[0].wait == ctm.WAIT_NONE, vi
        assert all(
            e.wait in scope.wait_gates for e in churn.events[1:]
        ), vi
        added = set()
        for e in churn.events:
            if e.vid >= meng.CHANGE_BASE:
                node, kind = meng.decode_change(e.vid)
                if kind == meng.DEL_ACCEPTOR:
                    assert node in added, vi
                else:
                    added.add(node)


def test_plain_and_change_vids_never_collide():
    scope = _committed()
    assert mcm.PLAIN_VID_BASE + scope.plain_values <= meng.CHANGE_BASE
    enum = mcm.ChurnEnum(scope)
    plain = {
        mcm.PLAIN_VID_BASE + arg
        for kind, arg, _ in enum.letters if kind == mcm.EV_PLAIN
    }
    change = {
        meng.change_vid(arg, meng.ADD_ACCEPTOR)
        for kind, arg, _ in enum.letters if kind != mcm.EV_PLAIN
    } | {
        meng.change_vid(arg, meng.DEL_ACCEPTOR)
        for kind, arg, _ in enum.letters if kind != mcm.EV_PLAIN
    }
    assert not plain & change


# ---------------- feasibility ----------------

def test_feasibility_excludes_protected_crashes():
    """Reduced scenarios never crash the driver or a churn-named
    acceptor, the rule actually bites (reduced < full), and every
    excluded scenario is excluded FOR that reason — no silent drops."""
    enum = mcm.ChurnEnum(_committed())
    assert len(enum.reduced) < enum.total
    reduced = set(enum.reduced)
    for i in range(enum.total):
        sc = enum.decode(i)
        protected = {0} | enum.variant_targets(sc.variant)
        crashes = {
            n
            for ci in sc.combo
            for n in enum.fault_alphabet[ci].nodes
            if enum.fault_alphabet[ci].kind == "crash"
        }
        assert (i in reduced) == (not crashes & protected), i


def test_describe_names_the_scenario():
    enum = mcm.ChurnEnum(_committed())
    sc = enum.decode(enum.reduced[-1])
    d = enum.describe(sc)
    assert d["index"] == sc.index
    assert {e["kind"] for e in d["events"]} <= {
        mcm.EV_PLAIN, mcm.EV_ADD, mcm.EV_DEL
    }
    assert d["seed"] == int(enum.scope.seeds[sc.seed])
    json.dumps(d)  # triage-dump serializable


# ---------------- device dispatch (slow tier) ----------------

@pytest.mark.slow
def test_churn_scope_certifies_clean_on_device():
    """Slow tier: the committed churn scope end-to-end — verdict
    nibbles match the pinned certificate and every chunk after the
    first compiles nothing.  Fast-tier coverage: the codec/grammar
    tests above + test_modelcheck.py's certificate count pins."""
    scope = _committed()
    summary = mcm.run_scope(scope, verbose=False)
    cert = mc.load_certificates()["churn"]
    assert summary["ok"], summary["counterexamples"][:2]
    assert summary["verdict_bits_sha256"] == cert["verdict_bits_sha256"]
    assert summary["scenarios_reduced"] == cert["scenarios_reduced"]
    assert all(c == 0 for c in summary["compiles_per_chunk"][1:]), (
        summary["compiles_per_chunk"]
    )


@pytest.mark.slow
def test_churn_counterexample_dumps_named_artifact(tmp_path):
    """Slow tier: a convergence budget too small to finish churn makes
    every churn-bearing lane fail completion — the counterexample path
    must dump deterministic ``mc_member_scenario_<index>.json``
    artifacts carrying the scope hash and the lane's decision-log sha.
    Fast-tier coverage: describe() serializability above."""
    scope = mcm.ChurnScope.from_dict({
        "n_nodes": 3, "n_instances": 8, "max_rounds": 4, "horizon": 2,
        "plain_values": 1, "add_targets": [], "t0_grid": [0],
        "max_events": 1, "seeds": [0], "chunk_lanes": 4,
    })
    summary = mcm.run_scope(
        scope, triage_dir=str(tmp_path), verbose=False,
        max_counterexamples=2,
    )
    assert not summary["ok"]
    cx = summary["counterexamples"][0]
    assert os.path.basename(cx["artifact"]).startswith(
        "mc_member_scenario_"
    )
    with open(cx["artifact"]) as f:
        art = json.load(f)
    assert art["scope_sha256"] == scope.sha256()
    assert art["decision_log_sha256"] == cx["decision_log_sha256"]
