"""The pallas-fused steady-state window must be bit-identical to the
XLA scan path it replaces (bench._steady_state_windows) — run here on
the CPU pallas interpreter; the real kernel runs on TPU in bench.py."""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from tpu_paxos.core import fast, fastwin


def _both(state_args, reps, quorum, span=None):
    i, n = state_args
    vids0 = jnp.arange(i, dtype=jnp.int32)
    ref_step = jax.jit(
        functools.partial(
            bench._steady_state_windows, reps=reps, quorum=quorum, span=span
        )
    )
    st_ref, cnt_ref = ref_step(fast.init_state(i, n), vids0)
    st_new, cnt = fastwin.steady_state_windows_fused(
        fast.init_state(i, n),
        vids0,
        reps=reps,
        quorum=quorum,
        span=span,
        interpret=True,
    )
    assert cnt_ref.shape == cnt.shape == (reps,)
    return st_ref, bench._total(cnt_ref), st_new, cnt


@pytest.mark.parametrize("reps", [1, 3])
def test_fused_matches_scan_bit_identical(reps):
    st_ref, tot_ref, st_new, cnt = _both((fastwin.TILE * 2, 5), reps, 3)
    assert bench._total(cnt) == tot_ref
    for name in ("promised", "max_seen", "acc_ballot", "acc_vid", "learned"):
        a = np.asarray(getattr(st_ref, name))
        b = np.asarray(getattr(st_new, name))
        assert (a == b).all(), f"{name} diverges from the scan path"


def test_fused_no_quorum_chooses_nothing():
    # 3 of 5 acceptors already promised a higher ballot: phase 1 cannot
    # reach quorum, so no window stores or learns anything.
    i, n = fastwin.TILE, 5
    st0 = fast.init_state(i, n)
    # promised high (count=10 in the ballot's high bits), max_seen low:
    # these acceptors promised a ballot this proposer has never seen,
    # so its bump_past(max_seen=0) ballot of count 1 stays below it
    high = 10 << 16
    st0 = st0._replace(
        promised=jnp.array([high, high, high, 0, 0], jnp.int32),
    )
    ref_step = jax.jit(
        functools.partial(bench._steady_state_windows, reps=2, quorum=3)
    )
    vids0 = jnp.arange(i, dtype=jnp.int32)
    st_ref, cnt_ref = ref_step(st0, vids0)
    tot_ref = bench._total(cnt_ref)
    st_new, cnt = fastwin.steady_state_windows_fused(
        fast.init_state(i, n)._replace(
            promised=st0.promised, max_seen=st0.max_seen
        ),
        vids0,
        reps=2,
        quorum=3,
        interpret=True,
    )
    assert tot_ref == 0 and bench._total(cnt) == 0
    assert (np.asarray(st_new.learned) == -1).all()
    for name in ("acc_ballot", "acc_vid", "learned"):
        assert (
            np.asarray(getattr(st_ref, name))
            == np.asarray(getattr(st_new, name))
        ).all()


def test_fused_sharded_span_semantics():
    # span > I (the sharded per-device slice case): window k's vids
    # offset by the global span, identical to the scan path.
    st_ref, tot_ref, st_new, cnt = _both(
        (fastwin.TILE, 3), 2, 2, span=fastwin.TILE * 8
    )
    assert bench._total(cnt) == tot_ref
    assert (
        np.asarray(st_ref.acc_vid) == np.asarray(st_new.acc_vid)
    ).all()


def test_fused_rejects_vid_space_overflow():
    st = fast.init_state(fastwin.TILE, 3)
    vids0 = jnp.arange(fastwin.TILE, dtype=jnp.int32)
    with pytest.raises(ValueError, match="vid space"):
        fastwin.steady_state_windows_fused(
            st,
            vids0,
            reps=(1 << 31) // fastwin.TILE + 1,
            quorum=2,
            interpret=True,
        )


def test_fused_rejects_ragged_instances():
    st = fast.init_state(fastwin.TILE + 128, 3)
    with pytest.raises(ValueError, match="multiple"):
        fastwin.steady_state_windows_fused(
            st,
            jnp.arange(fastwin.TILE + 128, dtype=jnp.int32),
            reps=1,
            quorum=2,
            interpret=True,
        )


def test_fused_iota_vids_matches_explicit():
    i, n = fastwin.TILE * 2, 5
    vids0 = jnp.arange(i, dtype=jnp.int32)
    s1, c1 = fastwin.steady_state_windows_fused(
        fast.init_state(i, n), vids0, reps=2, quorum=3, interpret=True
    )
    s2, c2 = fastwin.steady_state_windows_fused(
        fast.init_state(i, n),
        None,
        reps=2,
        quorum=3,
        interpret=True,
        iota_vids=True,
    )
    assert (np.asarray(c1) == np.asarray(c2)).all()
    for name in s1._fields:
        a = np.asarray(getattr(s1, name))
        b = np.asarray(getattr(s2, name))
        assert (a == b).all(), f"{name} diverges in the iota-vid variant"


@pytest.mark.tpu
@pytest.mark.skipif(
    os.environ.get("TPU_PAXOS_TPU_TEST") != "1",
    reason="drives the real chip; opt in with TPU_PAXOS_TPU_TEST=1",
)
def test_fused_matches_scan_on_real_tpu():
    """Content equivalence on the REAL chip, not the interpreter (the
    interpreter can't catch TPU-lowering bugs — a kernel that corrupts
    values while preserving counts would pass the count-only bench
    asserts).  Runs bench.check_fused_equivalence in a subprocess so
    the conftest's forced-CPU config doesn't apply; the bench warmup
    runs the same check before every fused headline."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = ":".join(
        x
        for x in (
            repo,
            env.get("TPU_PAXOS_AXON_SITE", "/root/.axon_site"),
            env.get("PYTHONPATH", ""),
        )
        if x
    )
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import jax, bench; "
            "assert jax.devices()[0].platform == 'tpu', jax.devices(); "
            "bench.check_fused_equivalence(); print('TPU_EQUIV_OK')",
        ],
        env=env,
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=580,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "TPU_EQUIV_OK" in proc.stdout
