"""Failure triage (harness/shrink.py): greedy schedule shrinking and
the one-command repro artifact.

The deliberately-broken invariant is the artifact-recorded
``decision_round_max`` hook: a partition episode delays decisions past
a tight bound, so the hook fails exactly when the partition is present
— the shrinker must keep the partition, drop the irrelevant episodes,
and the written artifact must reproduce the identical violation with
a byte-identical decision log (sha256), twice, through
``python -m tpu_paxos repro``."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as flt
from tpu_paxos.harness import shrink as shr

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _case(extra_checks, sched, seed=7, max_rounds=4000):
    wl = [
        np.arange(100, 110, dtype=np.int32),
        np.arange(200, 210, dtype=np.int32),
    ]
    cfg = SimConfig(
        n_nodes=5, n_instances=64, proposers=(0, 1), seed=seed,
        max_rounds=max_rounds,
        faults=FaultConfig(drop_rate=300, dup_rate=500, max_delay=2,
                           schedule=sched),
    )
    return shr.ReproCase(
        cfg=cfg, workload=wl, gates=None,
        chains=[np.zeros(0, np.int32)] * 2,
        extra_checks=extra_checks,
    )


@pytest.mark.slow  # one whole engine compile (~29 s) for a 2-line
# refusal guard: the green-case run itself (run_case on a clean mix,
# full suite green) is carried fast-tier by tests/test_stress.py's
# clean-mix sweep cells and tests/test_sim.py; only the
# "shrink refuses a non-failing case" ValueError is unique here
def test_green_case_has_no_violation_and_refuses_shrink():
    case = _case({}, None)
    _, v = shr.run_case(case)
    assert v is None
    with pytest.raises(ValueError, match="does not fail"):
        shr.shrink_case(case)


def test_artifact_roundtrip_and_reproduce(tmp_path):
    """Save -> load -> reproduce: identical violation, stable sha,
    match=True — without shrinking (3 engine runs, fast tier)."""
    sched = flt.FaultSchedule((flt.partition(5, 35, (0, 1), (2, 3, 4)),))
    case = _case({"decision_round_max": 25}, sched)
    _, viol = shr.run_case(case)
    assert viol and "decision_round_max" in viol
    path = str(tmp_path / "repro.json")
    art = shr.save_artifact(path, case, viol)
    assert art["format"] == shr.ARTIFACT_FORMAT
    loaded, art2 = shr.load_artifact(path)
    assert loaded.cfg == case.cfg
    assert art2["violation"] == viol
    rep = shr.reproduce(path)
    assert rep["match"], rep
    assert rep["violation"] == viol


def test_artifact_rejects_unknown_format(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"format": "something-else"}))
    with pytest.raises(ValueError, match="format"):
        shr.load_artifact(str(p))


@pytest.mark.slow
def test_shrinker_isolates_culprit_episode(tmp_path):
    """Three episodes, one culprit: the shrinker must drop the two
    irrelevant ones, narrow the partition, and the artifact must
    reproduce twice via the CLI with byte-identical stdout."""
    sched = flt.FaultSchedule((
        flt.partition(5, 45, (0, 1), (2, 3, 4)),  # the culprit
        flt.pause(50, 60, 3),  # irrelevant: after all decisions
        flt.burst(2, 8, 1500),  # irrelevant: too short to matter
    ))
    case = _case({"decision_round_max": 40}, sched)
    small, viol = shr.shrink_case(case, max_evals=40)
    eps = small.cfg.faults.schedule.episodes
    assert [e.kind for e in eps] == ["partition"]
    # the interval was narrowed (bisection trims the tail)
    assert eps[0].t1 - eps[0].t0 < 40
    path = str(tmp_path / "repro.json")
    shr.save_artifact(path, small, viol)

    def run_cli():
        return subprocess.run(
            [sys.executable, "-m", "tpu_paxos", "repro", path, "--json"],
            capture_output=True, text=True, cwd=REPO, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    p1, p2 = run_cli(), run_cli()
    assert p1.returncode == 0, p1.stderr[-2000:]
    assert p2.returncode == 0, p2.stderr[-2000:]
    # byte-identical stdout: decision log + JSON verdict
    assert p1.stdout == p2.stdout
    verdict = json.loads(p1.stdout.strip().splitlines()[-1])
    assert verdict["ok"] and verdict["violation"] == viol


@pytest.mark.slow
def test_sweep_triage_writes_artifact_on_failure(tmp_path, monkeypatch):
    """A stress sweep with a failure-injecting validator writes a repro
    artifact and records its path in the failure entry."""
    from tpu_paxos.harness import stress, validate

    def broken(r, cfg, workload, chains):
        raise validate.InvariantViolation("injected: always fails")

    monkeypatch.setattr(stress, "_validate_run", broken)
    monkeypatch.setattr(stress, "MIXES", [stress.MIXES[1]])
    # the shrinker judges candidates with the REAL suite (shr.validate_run
    # is untouched), so candidate runs are green and the case itself
    # 'fails' only under the injected validator — triage must degrade
    # gracefully: the failure is recorded with a triage_error, never
    # masked.  Then check the genuine path: a real extra-check failure
    # produces an artifact directly through shr.triage.
    summary = stress.sweep(
        n_seeds=1, verbose=False, triage_dir=str(tmp_path)
    )
    assert not summary["ok"]
    assert summary["failures"][0]["error"].startswith("injected")
    sched = flt.FaultSchedule((flt.partition(5, 45, (0, 1), (2, 3, 4)),))
    case = _case({"decision_round_max": 40}, sched)
    art = shr.triage(case, str(tmp_path / "direct.json"), max_evals=20)
    assert os.path.exists(tmp_path / "direct.json")
    rep = shr.reproduce(str(tmp_path / "direct.json"))
    assert rep["match"] and rep["violation"] == art["violation"]
