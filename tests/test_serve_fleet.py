"""Fleet serving (tpu_paxos/serve/fleet.py).

The load-bearing contract is SINGLE-LANE PARITY: every lane of a
fleet serve dispatch must be decision-log sha256-IDENTICAL to the
single-stream harness (``serve/harness.serve_run``) on the same
(cfg, stream, seed) at the same dispatch granularity — the lane
program is the single driver's window vmapped, and vmapping may not
perturb the protocol.  Alongside: the on-device per-lane SLO verdict
is a conservative superset of the host judge (only breaching lanes
pay the series transfer; the host names breach windows per
(lane, region)), the per-region windowed latency series reduced on
device equal the single harness's post-clock host twin, warm
dispatches of a cached envelope cost zero XLA compiles, and the
shard_map lane tile is bitwise-identical to the unmeshed vmap.

Engine-cell budget: the module shares ONE fleet executable (the
2-lane, S=2, K=10 shape below) across every fast engine cell, and
reuses test_serve.py's module geometry so the single-run parity twins
hit the serve driver's already-warm ``window_for`` cache.  The 8-lane
heterogeneous grid and the mesh tile pay their own executables and
ride the slow tier; their fast coverage is the 2-lane parity cell and
the crafted-verdict cells here.
"""

import dataclasses
import hashlib

import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.replay.decision_log import decision_log
from tpu_paxos.serve import arrivals as arrv
from tpu_paxos.serve import fleet as sfl
from tpu_paxos.serve import harness as sh
from tpu_paxos.telemetry import recorder as telem

# test_serve.py's module geometry: the single-run twins reuse its
# cached window builder (window_for keys ignore the seed), so parity
# cells cost fleet compiles only.
WL = [np.arange(0, 10, dtype=np.int32), np.arange(20, 30, dtype=np.int32)]
R_WINDOW = 8
S_DISPATCH = 2
ADMIT_W = 10


def _cfg(seed=3):
    return SimConfig(
        n_nodes=3, n_instances=48, proposers=(0, 1), seed=seed,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )


def _sha(chosen_vid, chosen_ballot):
    text = decision_log(
        chosen_vid, chosen_ballot, stride=30, n_instances=len(chosen_vid)
    )
    return hashlib.sha256(text.encode()).hexdigest()


def _lane_at_rate(rate_milli, aseed, seed):
    """One tenant stream of the module workload at an offered rate
    (0 = offered-load-∞), arrival order per proposer preserved."""
    if rate_milli <= 0:
        rounds = arrv.immediate_rounds(20)
    else:
        rounds = arrv.poisson_rounds(20, rate_milli, aseed)
    arrs = [np.sort(rounds[0::2]), np.sort(rounds[1::2])]
    return sfl.ServeLane(WL, arrs, seed)


def _fleet(cfg, lanes, **kw):
    kw.setdefault("rounds_per_window", R_WINDOW)
    kw.setdefault("windows_per_dispatch", S_DISPATCH)
    kw.setdefault("admit_width", ADMIT_W)
    return sfl.serve_fleet_run(cfg, lanes, **kw)


def _serve_twin(cfg, lane, **kw):
    kw.setdefault("rounds_per_window", R_WINDOW)
    kw.setdefault("windows_per_dispatch", S_DISPATCH)
    kw.setdefault("admit_width", ADMIT_W)
    return sh.serve_run(
        dataclasses.replace(cfg, seed=lane.seed), lane.workload,
        lane.arrivals, **kw,
    )


# ---------------- single-lane parity (THE contract) ----------------


def test_single_lane_parity_two_lane_heterogeneous():
    """Fast-tier parity cell: a 2-lane heterogeneous-rate dispatch
    (distinct arrival processes AND distinct engine seeds) is
    decision-log sha256-identical PER LANE to the single-stream
    harness — the full 8-lane grid rides the slow tier
    (test_single_lane_parity_eight_lane_grid)."""
    cfg = _cfg()
    lanes = [_lane_at_rate(1500, 7, 3), _lane_at_rate(4000, 8, 4)]
    rep = _fleet(cfg, lanes)
    assert rep.done and rep.backlog == 0
    for li, ln in enumerate(lanes):
        single = _serve_twin(cfg, ln)
        cv, cb = rep.lane_chosen(li)
        assert _sha(cv, cb) == _sha(
            single.chosen_vid, single.chosen_ballot
        ), f"lane {li}"
        assert int(rep.decided[li]) == single.decided_values
        # the lane's windowed series equals the single run's too (the
        # recorder rode the same donated loop state)
        lw = rep.lane_summary(li)["windows"]
        assert lw["lat_hist"] == single.windows["lat_hist"]
        assert lw["decided"] == single.windows["decided"]


def test_single_lane_region_series_match_host_twin():
    """The on-device per-region windowed latency series of a fleet
    lane equal the single harness's post-clock host recomputation
    (recorder.region_window_hist_host) — and partition the global
    windowed histogram.  Shares the module's 2-lane executable-shape
    with the cell above... but regions ride runtime inputs, so this
    is the SAME executable, not a new compile."""
    cfg = _cfg()
    rmap = np.asarray([0, 1, 0], np.int32)  # proposer 0 -> us, 1 -> eu
    lanes = [_lane_at_rate(1500, 7, 3), _lane_at_rate(4000, 8, 4)]
    rep = _fleet(cfg, lanes, region_map=rmap, region_names=("us", "eu"))
    for li, ln in enumerate(lanes):
        single = _serve_twin(
            cfg, ln, region_map=rmap, region_names=("us", "eu")
        )
        rw = rep.lane_region_windows(li)
        assert (rw == single.region_windows).all(), f"lane {li}"
        # the per-region series partition the global one
        lw = rep.lane_summary(li)["windows"]
        assert rw.sum(axis=0).tolist() == lw["lat_hist"]
        # both declared regions saw traffic (proposers split us/eu)
        assert rw[0].sum() > 0 and rw[1].sum() > 0


@pytest.mark.slow
def test_single_lane_parity_eight_lane_grid():
    """The acceptance grid: an 8-lane heterogeneous-rate stack —
    fast-tier small cell (two zero-load lanes = offered-load-∞, a
    trickle tier, a bursty-arrival lane, and a fast tier) — each lane
    decision-log sha256-identical to its single-run twin.  Fast-tier
    coverage: test_single_lane_parity_two_lane_heterogeneous (2-lane
    cell, same program at a smaller lane shape)."""
    cfg = _cfg()
    lanes = []
    for li, rm in enumerate([0, 0, 800, 1500, 1500, 4000, 8000, 16000]):
        ln = _lane_at_rate(rm, 20 + li, 30 + li)
        lanes.append(ln)
    # one bursty-arrival lane (the realism axis through the fleet)
    rounds = arrv.bursty_rounds(20, 2000, seed=5, burst=4)
    lanes[4] = sfl.ServeLane(
        WL, [np.sort(rounds[0::2]), np.sort(rounds[1::2])], 34
    )
    rep = _fleet(cfg, lanes)
    assert rep.done and rep.backlog == 0
    for li, ln in enumerate(lanes):
        single = _serve_twin(cfg, ln)
        cv, cb = rep.lane_chosen(li)
        assert _sha(cv, cb) == _sha(
            single.chosen_vid, single.chosen_ballot
        ), f"lane {li}"
        assert int(rep.decided[li]) == single.decided_values


# ---------------- the on-device SLO verdict ----------------


def _host_breach_lanes(hists, region_hists, slo, region_names):
    """The host judge's breach set over a crafted stack — the
    authority the device verdict must be a superset of."""
    out = []
    for i in range(hists.shape[0]):
        v = sh.slo_windows(
            {"window_rounds": 32, "lat_hist": hists[i]},
            slo, region_series=region_hists[i],
            region_names=region_names,
        )
        breach = bool(v["breach_windows"]) or any(
            r["breach_windows"] for r in v.get("regions", {}).values()
        )
        out.append(breach)
    return np.asarray(out)


def test_device_slo_verdict_superset_of_host_judge():
    """The transfer gate: every lane the host judge would flag (incl.
    via a per-region series, incl. a burn rate landing EXACTLY on the
    threshold) must be device-flagged — a missed flag would silently
    hide a breach.  Crafted [lanes, W, B] stacks, no engine."""
    import jax.numpy as jnp

    w, b = telem.NUM_WINDOWS, telem.NUM_LAT_BUCKETS
    r = telem.NUM_REGIONS
    slo = sh.ServeSLO(
        latency_rounds=16, budget_milli=250, regions=(("us", 8),)
    )
    lanes = 5
    hists = np.zeros((lanes, w, b), np.int64)
    rws = np.zeros((lanes, r, w, b), np.int64)
    # lane 0: clean (all fast)
    hists[0, 0, 1] = 40
    # lane 1: global breach (half the window past 16 rounds)
    hists[1, 2, 1] = 20
    hists[1, 2, 6] = 20
    # lane 2: burn EXACTLY at threshold (10 bad of 40 at budget 250
    # -> burn 1.0) — the boundary the BURN_EPS margin exists for
    hists[2, 3, 1] = 30
    hists[2, 3, 6] = 10
    # lane 3: global green, but region 'us' (8-round budget) breaches
    # on its OWN series
    hists[3, 1, 2] = 40  # latency (2, 4] — fine globally
    rws[3, 0, 1, 4] = 40  # us traffic at (8, 16] — all bad for us
    # lane 4: clean, with benign region traffic
    hists[4, 0, 1] = 40
    rws[4, 0, 0, 1] = 40
    for i in range(lanes):
        if not rws[i].any():
            rws[i, 0] = hists[i]  # regions partition the global series
    host = _host_breach_lanes(hists, rws, slo, ("us",))
    slo_args = sfl._slo_args(slo, ("us",))
    dev = np.asarray(sfl._slo_breach(
        jnp.asarray(hists, jnp.int32), jnp.asarray(rws, jnp.int32),
        *[jnp.asarray(x) for x in slo_args],
    ))
    assert host.tolist() == [False, True, True, True, False]
    # superset: no host-flagged lane is ever device-missed
    assert (dev | ~host).all(), (dev, host)
    # and on this stack the verdicts agree exactly (the margin only
    # admits extra flags within rounding epsilon of the threshold)
    assert dev.tolist() == host.tolist()


def test_slo_args_inert_and_fallback_thresholds():
    b = telem.NUM_LAT_BUCKETS
    k, rk, budget, burn = sfl._slo_args(None, ())
    assert int(k) == b and (rk == b).all()
    slo = sh.ServeSLO(
        latency_rounds=16, budget_milli=100,
        regions=(("us", 8), ("ap", 64)),
    )
    # 'us' has a series slot; 'ap' does not and folds into the global
    # bucket index (min — conservative)
    k, rk, budget, burn = sfl._slo_args(slo, ("us",))
    import bisect

    k_us = bisect.bisect_right(telem.LAT_EDGES, 8)
    k_ap = bisect.bisect_right(telem.LAT_EDGES, 64)
    k_g = bisect.bisect_right(telem.LAT_EDGES, 16)
    assert int(rk[0]) == k_us and (rk[1:] == b).all()
    assert int(k) == min(k_g, k_ap)
    assert int(budget) == 100 and int(burn) == 1000


def test_breaching_lanes_only_confirmed_and_named_per_region():
    """Engine cell (module executable): an SLO fleet where the
    on-device verdict flags breaching lanes; the report's ``slo``
    dict holds host-confirmed verdicts for EXACTLY the flagged lanes,
    with per-(lane, region) breach windows judged on each region's
    OWN series."""
    cfg = _cfg()
    # lane 0: trickle + a 6-value burst at round 128 (test_serve's
    # mid-run breach shape); lane 1: the same trickle without the
    # burst
    burst = [
        np.asarray(sorted([i * 40 for i in range(7)] + [128] * 3),
                   np.int32)
        for _ in range(2)
    ]
    calm = [np.asarray([i * 40 for i in range(10)], np.int32)
            for _ in range(2)]
    lanes = [sfl.ServeLane(WL, burst, 3), sfl.ServeLane(WL, calm, 3)]
    rmap = np.asarray([0, 1, 0], np.int32)
    slo = sh.ServeSLO(
        latency_rounds=16, budget_milli=400, regions=(("us", 16),)
    )
    rep = _fleet(cfg, lanes, slo=slo, region_map=rmap,
                 region_names=("us", "eu"))
    assert rep.done and rep.backlog == 0
    flagged = set(int(i) for i in np.flatnonzero(rep.breach))
    assert rep.slo is not None
    # confirmed verdicts exist for exactly the flagged lanes — the
    # unflagged lanes never paid the series transfer
    assert set(rep.slo) == flagged
    # the burst lane is flagged, its burst bucket named, and its
    # region verdict judged on the region's OWN series
    assert 0 in flagged
    v = rep.slo[0]
    assert 4 in v["breach_windows"]
    assert v["regions"]["us"]["series"] == "region"
    # monitoring saw it mid-run
    assert rep.first_breach_dispatch[0] is not None
    assert rep.first_breach_dispatch[0] <= rep.dispatches


# ---------------- envelope cache + zero warm compiles ----------------


def test_envelope_cache_identity_and_schedule_rejection():
    from tpu_paxos.core import faults as fltm
    from tpu_paxos.fleet import envelope as envm

    cfg = _cfg()
    _, _, _, c = simm.prepare_queues(cfg, WL)
    r1 = envm.serve_fleet_for(cfg, c, 30, R_WINDOW, window_rounds=32)
    r2 = envm.serve_fleet_for(cfg, c, 30, R_WINDOW, window_rounds=32)
    assert r1 is r2
    # seeds are runtime data: a different-seed cfg shares the runner
    r3 = envm.serve_fleet_for(
        dataclasses.replace(cfg, seed=99), c, 30, R_WINDOW,
        window_rounds=32,
    )
    assert r3 is r1
    assert envm.serve_fleet_for(
        cfg, c, 30, R_WINDOW, window_rounds=64
    ) is not r1
    sched_cfg = dataclasses.replace(
        cfg, faults=dataclasses.replace(
            cfg.faults,
            schedule=fltm.FaultSchedule((fltm.pause(1, 3, 0),)),
        ),
    )
    with pytest.raises(ValueError, match="no fault schedule"):
        envm.serve_fleet_for(sched_cfg, c, 30, R_WINDOW, window_rounds=32)


def test_warm_dispatches_cost_zero_compiles(compile_census):
    """The envelope claim live: after the module's first 2-lane
    dispatch warmed the executable, a fresh fleet run at DIFFERENT
    rates, seeds, SLO thresholds, and region maps costs zero XLA
    compiles — they are all runtime data of the one cached program."""
    cfg = _cfg()
    # identical shapes to the warm cells above; different everything
    # else
    lanes = [_lane_at_rate(2500, 17, 13), _lane_at_rate(6000, 18, 14)]
    before = compile_census.engine_counts.get("serve_fleet", 0)
    rep = _fleet(
        cfg, lanes,
        slo=sh.ServeSLO(latency_rounds=32, budget_milli=200),
        region_map=np.asarray([1, 0, 1], np.int32),
        region_names=("us", "eu"),
    )
    assert rep.done
    assert compile_census.engine_counts.get("serve_fleet", 0) == before


# ---------------- shard_map lane tile ----------------


@pytest.mark.slow
def test_mesh_tile_bitwise_parity():
    """The shard_map lane tile (2 of the conftest's 8 virtual CPU
    devices) produces bitwise-identical per-lane state, decisions,
    and breach vectors to the unmeshed vmap — lanes are independent,
    so the tile is pure placement.  Slow tier: the tiled program is
    its own executable; fast coverage is the unmeshed module cells
    (same lane program) + fleet/runner's fast mesh-parity pin."""
    import jax

    from tpu_paxos.parallel import mesh as pmesh

    cfg = _cfg()
    lanes = [_lane_at_rate(1500, 7, 3), _lane_at_rate(4000, 8, 4)]
    slo = sh.ServeSLO(latency_rounds=16, budget_milli=400)
    rep = _fleet(cfg, lanes, slo=slo)
    mesh = pmesh.make_instance_mesh(2)
    assert mesh.size == 2
    rep_m = _fleet(cfg, lanes, slo=slo, mesh=mesh)
    for a, b in zip(jax.tree.leaves(rep.final), jax.tree.leaves(rep_m.final)):
        assert (np.asarray(a) == np.asarray(b)).all()
    assert (rep_m.breach == rep.breach).all()
    assert (rep_m.decided == rep.decided).all()
    # lanes that don't tile the mesh are rejected up front
    with pytest.raises(ValueError, match="tile"):
        _fleet(cfg, lanes[:1], mesh=mesh)


# ---------------- validation ----------------


def test_lane_validation_errors():
    cfg = _cfg()
    with pytest.raises(ValueError, match="at least one lane"):
        sfl.serve_fleet_run(cfg, [])
    with pytest.raises(ValueError, match="one value stream per proposer"):
        sfl.serve_fleet_run(
            cfg, [sfl.ServeLane([WL[0]], [np.zeros(10, np.int32)], 0)]
        )
    with pytest.raises(ValueError, match="admit_width"):
        _fleet(cfg, [_lane_at_rate(0, 0, 3)], admit_width=2)
    with pytest.raises(ValueError, match="window_rounds must be positive"):
        sfl.ServeFleetRunner(cfg, 64, 30, R_WINDOW, 0)
