"""Ballot encoding/bumping vs the reference rules
(ref multi/paxos.cpp:792-799: ballot = (count<<16)|index, bumped past
max seen)."""

import jax.numpy as jnp
import numpy as np

from tpu_paxos.core import ballot as bal


def test_encode_decode_roundtrip():
    for count, node in [(1, 0), (1, 5), (7, 65535), (32000, 3)]:
        b = bal.make(count, node)
        assert int(bal.count_of(b)) == count
        assert int(bal.node_of(b)) == node


def test_ordering_count_dominates_node():
    # (2, 0) > (1, 65535): count is the high-order field.
    assert int(bal.make(2, 0)) > int(bal.make(1, 65535))
    # Same count: node breaks ties.
    assert int(bal.make(3, 4)) > int(bal.make(3, 2))


def test_bump_past_simple():
    count, b = bal.bump_past(0, 2, 0)
    assert int(count) == 1
    assert int(b) == int(bal.make(1, 2))


def test_bump_past_exceeds_max_seen():
    # Seen ballot (5, 7); node 2 must reach count 6 to beat it
    # (count 5, node 2 < count 5, node 7).
    seen = bal.make(5, 7)
    count, b = bal.bump_past(0, 2, seen)
    assert int(b) > int(seen)
    assert int(bal.node_of(b)) == 2
    assert int(count) == 6


def test_bump_past_same_count_higher_node_ok():
    # Seen (5, 1); node 2's count-5 ballot already beats it, but count
    # must still advance past our own previous count.
    seen = bal.make(5, 1)
    count, b = bal.bump_past(4, 2, seen)
    assert int(b) > int(seen)
    assert int(count) == 5


def test_bump_past_monotone_self():
    # Repeated bumps strictly increase even with max_seen = 0.
    count = jnp.int32(0)
    prev = 0
    for _ in range(5):
        count, b = bal.bump_past(count, 3, 0)
        assert int(b) > prev
        prev = int(b)


def test_bump_past_vectorized():
    counts = jnp.array([0, 4, 9], jnp.int32)
    nodes = jnp.array([0, 1, 2], jnp.int32)
    seen = jnp.array([int(bal.make(5, 7)), 0, int(bal.make(9, 9))], jnp.int32)
    new_counts, bs = bal.bump_past(counts, nodes, seen)
    assert np.all(np.asarray(bs) > np.asarray(seen))
    assert np.all(np.asarray(new_counts) > np.asarray(counts))
