"""Host-injection record/replay (component 9's escape hatch): the
engine is deterministic in (seed, round); the host driver's injection
schedule is the one nondeterministic input.  Recording it must make
any driver — including one paced by wall clock — replay
bit-identically (ref member/indet.h:182-194, member/indet.cpp:24-119,
member/diff.sh:1-3)."""

import os
import time

import numpy as np
import pytest

from tpu_paxos.membership.engine import MemberSim


def _drive_with_sleeps(seed: int) -> MemberSim:
    """A genuinely wall-clock-paced driver: tiny sleeps between marks
    make the landing round of each injection depend on real time."""
    ms = MemberSim(n_nodes=4, n_instances=32, seed=seed)
    plan = [("propose", 0, 100), ("add", 1), ("propose", 1, 101)]
    next_mark = time.monotonic() + 0.005
    while plan or not (ms.chosen(100) and ms.chosen(101)):
        ms.run_rounds(1)
        if plan and time.monotonic() >= next_mark:
            kind, *args = plan.pop(0)
            if kind == "propose":
                ms.propose(args[0], args[1])
            else:
                ms.add_acceptor(args[0])
            next_mark = time.monotonic() + 0.005
        assert int(ms.state.t) < 4000, "driver did not converge"
    return ms


def test_wall_clock_driver_replays_bit_identically(tmp_path):
    ms = _drive_with_sleeps(seed=3)
    path = os.path.join(tmp_path, "inj.json")
    ms.save_injections(path)
    ms2 = MemberSim.replay(path)
    assert ms2.decision_log() == ms.decision_log()
    # the full engine state agrees too, not just the rendered log
    for name in ("chosen_vid", "chosen_round", "chosen_ballot", "learned"):
        a = np.asarray(getattr(ms.state, name))
        b = np.asarray(getattr(ms2.state, name))
        assert (a == b).all(), f"{name} diverges under replay"


def test_replay_rejects_unknown_version(tmp_path):
    import json

    p = os.path.join(tmp_path, "bad.json")
    with open(p, "w") as f:
        json.dump({"version": 99}, f)
    with pytest.raises(ValueError, match="version"):
        MemberSim.replay(p)


def test_injections_record_through_membership_ops():
    ms = MemberSim(n_nodes=3, n_instances=16, seed=0)
    ms.propose(0, 100)
    cv = ms.add_acceptor(1)
    assert [op for _, op, _ in ms.injections] == ["propose", "propose"]
    assert ms.injections[1][2] == [0, cv]  # change vid recorded via propose
