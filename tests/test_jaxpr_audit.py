"""jaxpr-audit: IR rules, registry sweep, op/cost budget, CLI.

Three layers, mirroring tests/test_paxlint*.py:

- **Tier-1 enforcement**: ``test_repo_audit_within_budget`` runs the
  full audit in-process against the committed ``op_budget.json`` —
  tightening a pin below the measured count fails THIS test naming
  the entry point (the acceptance contract).
- **Fixture layer**: one seeded violation per IR rule
  (tests/data/audit_fixture.py) that the checker must flag, and a
  clean twin it must pass.
- **CLI layer**: golden-JSON report pinned byte-for-byte
  (tests/data/jaxpr_audit_golden.json) and a budget-breach e2e run
  asserting exit code, the named entry point, and the triage-dir
  jaxpr dump.
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_paxos.analysis import ir_rules, jaxpr_audit
from tpu_paxos.analysis import registry as regm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_PROVIDER = os.path.join(REPO, "tests", "data", "audit_fixture.py")
GOLDEN = os.path.join(REPO, "tests", "data", "jaxpr_audit_golden.json")


# ---------------- registry + repo audit ----------------

@pytest.fixture(scope="module")
def repo_report():
    """One full audit of the shipped tree, shared by the module."""
    return jaxpr_audit.run_audit(root=REPO)


def test_repo_audit_within_budget(repo_report):
    # the tier-1 hook: IR findings, sweep problems, and op/cost budget
    # breaches all land here with the culprit named in the report
    assert repo_report["ok"], json.dumps(
        {k: repo_report[k] for k in ("findings", "sweep", "budget")},
        indent=1, sort_keys=True,
    )


def test_every_provider_registers_entries(repo_report):
    entries = regm.collect()
    by_module: dict[str, int] = {}
    for name in regm.AUDIT_PROVIDERS:
        mod = regm.provider_module(name)
        by_module[name] = len(mod.audit_entries())
    assert all(n >= 1 for n in by_module.values()), by_module
    # both engines + the sharded path are in the report
    for expected in ("sim.run_rounds", "member.round",
                     "sharded.choose_all", "sharded_sim.run_rounds",
                     "fast.choose_all", "simkern.store_accepts",
                     "simkern.accum_acks"):
        assert expected in repo_report["entries"], expected
    assert len(entries) == len(repo_report["entries"])


def test_registry_rejects_duplicate_names(tmp_path):
    prov = tmp_path / "dup_provider.py"
    prov.write_text(
        "from tpu_paxos.analysis.registry import AuditEntry\n"
        "def audit_entries():\n"
        "    b = lambda: (lambda x: x, (1,))\n"
        "    return [AuditEntry('d.same', b), AuditEntry('d.same', b)]\n"
    )
    names = jaxpr_audit._load_provider_arg(str(prov))
    with pytest.raises(regm.RegistryError, match="duplicate"):
        regm.collect(names)


def test_registry_rejects_missing_provider_fn(tmp_path):
    prov = tmp_path / "empty_provider.py"
    prov.write_text("x = 1\n")
    names = jaxpr_audit._load_provider_arg(str(prov))
    with pytest.raises(regm.RegistryError, match="audit_entries"):
        regm.collect(names)


# ---------------- unregistered-function sweep ----------------

def _sweep_of(tmp_path, source: str, entries_src: str) -> list[dict]:
    prov = tmp_path / "sweep_provider.py"
    prov.write_text(
        "from tpu_paxos.analysis.registry import AuditEntry\n"
        + source + "\n" + entries_src
    )
    names = jaxpr_audit._load_provider_arg(str(prov))
    return jaxpr_audit.run_sweep(names, root=str(tmp_path))


def test_sweep_flags_unregistered_jit_surface(tmp_path):
    problems = _sweep_of(
        tmp_path,
        "import jax\n"
        "def rogue(x):\n"
        "    return jax.jit(lambda y: y)(x)\n",
        "def audit_entries():\n    return []\n",
    )
    assert [p["kind"] for p in problems] == ["unregistered_surface"]
    assert problems[0]["surface"] == "rogue"


def test_sweep_accepts_covered_and_exempt(tmp_path):
    problems = _sweep_of(
        tmp_path,
        "import jax\n"
        "def covered(x):\n"
        "    def inner(y):\n"
        "        return jax.jit(lambda z: z)(y)\n"
        "    return inner(x)\n"
        "def debug_only(x):\n"
        "    return jax.jit(lambda z: z)(x)\n"
        "AUDIT_EXEMPT = {'debug_only': 'debug helper, never in the "
        "round path'}\n",
        # prefix cover: "covered" also covers the nested "covered.inner"
        "def audit_entries():\n"
        "    return [AuditEntry('s.c', lambda: (lambda x: x, (1,)),"
        " covers=('covered',))]\n",
    )
    assert problems == []


def test_sweep_coverage_is_scoped_per_module(tmp_path):
    """A covers= name in one provider must not silently cover a
    same-named surface in ANOTHER provider — coverage is per module,
    or the opt-in guarantee is gone."""
    a = tmp_path / "prov_a.py"
    a.write_text(
        "from tpu_paxos.analysis.registry import AuditEntry\n"
        "import jax\n"
        "def shared_name(x):\n"
        "    return jax.jit(lambda y: y)(x)\n"
        "def audit_entries():\n"
        "    return [AuditEntry('a.e', lambda: (lambda x: x, (1,)),"
        " covers=('shared_name',))]\n"
    )
    b = tmp_path / "prov_b.py"
    b.write_text(
        "import jax\n"
        "def shared_name(x):\n"
        "    return jax.jit(lambda y: y)(x)\n"
        "def audit_entries():\n"
        "    return []\n"
    )
    names = jaxpr_audit._load_provider_arg(f"{a},{b}")
    problems = jaxpr_audit.run_sweep(names, root=str(tmp_path))
    assert [(p["kind"], p["surface"]) for p in problems] == [
        ("unregistered_surface", "shared_name")
    ]
    assert problems[0]["module"].endswith("prov_b")


def test_sweep_catches_module_level_jit_assignment(tmp_path):
    problems = _sweep_of(
        tmp_path,
        "import jax\n"
        "def f(x):\n    return x\n"
        "f_jit = jax.jit(f)\n",
        "def audit_entries():\n    return []\n",
    )
    assert [p["surface"] for p in problems] == ["f_jit"]


def test_sweep_catches_partial_jit_decorator(tmp_path):
    # the standard static-args idiom must not slip past the sweep
    problems = _sweep_of(
        tmp_path,
        "import functools\nimport jax\n"
        "@functools.partial(jax.jit, static_argnames=('k',))\n"
        "def stepper(x, k):\n"
        "    return x * k\n",
        "def audit_entries():\n    return []\n",
    )
    assert [p["surface"] for p in problems] == ["stepper"]


def test_scoped_providers_do_not_report_stale_pins(tmp_path):
    # auditing a provider subset against the full committed budget:
    # untraced engine entries are NOT stale (they are still
    # registered, just out of scope this run)
    report = jaxpr_audit.run_audit(
        providers=("tpu_paxos.core.fast",),
        budget_path=jaxpr_audit.DEFAULT_BUDGET,
        triage_dir=str(tmp_path), root=REPO,
    )
    assert report["budget"]["stale"] == []
    assert report["budget"]["violations"] == []
    assert report["ok"], report["budget"]


# ---------------- IR rule fixtures (hot + clean twin) ----------------

@pytest.fixture(scope="module")
def fixture_entries():
    names = jaxpr_audit._load_provider_arg(FIXTURE_PROVIDER)
    return {e.name: e for e in regm.collect(names)}


def _findings_for(entries, name):
    entry = entries[name]
    closed, _fn, _args = jaxpr_audit.trace_entry(entry)
    return ir_rules.check_entry(entry, closed)


@pytest.mark.parametrize("rule", ["ir201", "ir202", "ir203", "ir204",
                                  "ir205"])
def test_ir_rule_flags_hot_and_passes_clean(fixture_entries, rule):
    hot = _findings_for(fixture_entries, f"fixture.{rule}_hot")
    clean = _findings_for(fixture_entries, f"fixture.{rule}_clean")
    assert rule.upper() in {f.rule for f in hot}, hot
    assert clean == [], clean


def test_ir202_names_the_primitive_path(fixture_entries):
    hot = _findings_for(fixture_entries, "fixture.ir202_hot")
    paths = {f.path for f in hot if f.rule == "IR202"}
    # the widening is named by its traced primitive, even though the
    # source hides it behind a helper function
    assert any(p.endswith("/convert_element_type") for p in paths), paths


def test_entry_allow_waives_rule(fixture_entries):
    import dataclasses

    hot = fixture_entries["fixture.ir204_hot"]
    waived = dataclasses.replace(
        hot, allow=("IR204",), why="fixture waiver"
    )
    closed, _fn, _args = jaxpr_audit.trace_entry(waived)
    assert ir_rules.check_entry(waived, closed) == []


def test_engine_allow_is_scoped_not_global(repo_report):
    # sim.run_rounds waives IR204 (unique-key compaction sorts) — the
    # waiver must not leak: the fixture audit still flags IR204
    entries = {e.name: e for e in regm.collect()}
    assert "IR204" in entries["sim.run_rounds"].allow
    assert entries["sim.run_rounds"].why  # a waiver needs its reason
    assert "IR204" not in entries["member.round"].allow


# ---------------- op/cost budget machinery ----------------

def test_check_budget_names_entry_and_delta():
    measured = {"sim.run_rounds": {"ops": 120, "flops": 10}}
    budget = {"backend": "cpu",
              "entries": {"sim.run_rounds": {"ops": 100, "flops": 50}}}
    violations, stale = jaxpr_audit.check_budget(
        measured, budget, backend="cpu"
    )
    assert len(violations) == 1 and stale == []
    v = violations[0]
    assert v["entry"] == "sim.run_rounds" and v["key"] == "ops"
    assert v["measured"] == 120 and v["cap"] == 100
    assert "sim.run_rounds" in v["detail"]


def test_check_budget_unpinned_entry_is_a_violation():
    violations, _ = jaxpr_audit.check_budget(
        {"new.entry": {"ops": 5}}, {"entries": {}}, backend="cpu"
    )
    assert [v["entry"] for v in violations] == ["new.entry"]
    assert "re-pin" in violations[0]["detail"]


def test_check_budget_stale_entry_is_flagged():
    _, stale = jaxpr_audit.check_budget(
        {}, {"entries": {"gone.entry": {"ops": 5}}}, backend="cpu"
    )
    assert stale == ["gone.entry"]


def test_check_budget_cost_keys_need_matching_backend():
    measured = {"e": {"ops": 10, "flops": 999}}
    budget = {"backend": "tpu", "entries": {"e": {"ops": 50, "flops": 1}}}
    # flops cap pinned on tpu is not comparable on cpu: only ops judged
    violations, _ = jaxpr_audit.check_budget(measured, budget,
                                             backend="cpu")
    assert violations == []
    violations, _ = jaxpr_audit.check_budget(measured, budget,
                                             backend="tpu")
    assert [v["key"] for v in violations] == ["flops"]


def test_save_budget_headroom_and_roundtrip(tmp_path):
    path = str(tmp_path / "budget.json")
    data = jaxpr_audit.save_budget(
        {"e": {"ops": 100, "flops": 10, "prims": {"add": 3}}}, path,
        headroom=0.3, slack=8, backend="cpu",
    )
    assert data["entries"]["e"] == {"ops": 138, "flops": 21}
    assert jaxpr_audit.load_budget(path) == data


@pytest.mark.slow
def test_budget_breach_dumps_jaxpr_in_process(tmp_path, repo_report):
    # Slow-tier: re-traces the full 17-entry registry against a tight
    # budget (~23 s).  Fast-tier coverage: the budget-machinery units
    # (test_save_budget_caps_with_headroom_and_slack,
    # test_budget_backend_gate_and_staleness, tests/data fixtures)
    # plus the repo-green repo_report assertion; the breach -> exit 1
    # -> named-entry -> triage-dump surface stays pinned end to end
    # by the slow CLI e2e below.
    tight = {
        "version": 1, "backend": repo_report["backend"],
        "headroom": 0.3, "slack": 8,
        "entries": {
            name: {"ops": (1 if name == "member.round"
                           else m["ops"] + 100)}
            for name, m in sorted(repo_report["entries"].items())
        },
    }
    bpath = tmp_path / "tight.json"
    bpath.write_text(json.dumps(tight))
    triage = tmp_path / "triage"
    report = jaxpr_audit.run_audit(
        budget_path=str(bpath), triage_dir=str(triage), root=REPO
    )
    assert not report["ok"]
    assert [v["entry"] for v in report["budget"]["violations"]] == [
        "member.round"
    ]
    dumps = report["budget"]["dumped"]
    assert len(dumps) == 1 and os.path.exists(dumps[0])
    text = open(dumps[0], encoding="utf-8").read()
    assert "member.round" in text and "lambda" in text


# ---------------- CLI (subprocess) ----------------

def _audit(args, cwd=REPO):
    from _subproc import scrubbed_env

    env = scrubbed_env(
        extra_prefixes=("TPU_PAXOS_OP_BUDGET",), JAX_PLATFORMS="cpu"
    )
    return subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "audit", *args],
        capture_output=True, text=True, timeout=500, cwd=cwd, env=env,
    )


def test_cli_golden_json():
    p = _audit(["--json", "--no-budget", "--providers",
                "tests/data/audit_fixture.py"])
    assert p.returncode == 1, p.stderr[-2000:]  # seeded findings present
    got = json.loads(p.stdout)
    with open(GOLDEN, encoding="utf-8") as fh:
        want = json.load(fh)
    assert got == want, (
        "audit JSON report drifted from tests/data/jaxpr_audit_golden"
        ".json — if intentional, regenerate: python -m tpu_paxos audit "
        "--json --no-budget --providers tests/data/audit_fixture.py\n"
        + json.dumps(got, indent=1, sort_keys=True)
    )


@pytest.mark.slow
def test_cli_budget_breach_e2e(tmp_path):
    with open(jaxpr_audit.DEFAULT_BUDGET, encoding="utf-8") as fh:
        budget = json.load(fh)
    budget["entries"]["sharded_sim.run_rounds"]["ops"] = 1
    bpath = tmp_path / "tight.json"
    bpath.write_text(json.dumps(budget))
    triage = tmp_path / "triage"
    p = _audit(["--budget", str(bpath), "--triage-dir", str(triage)])
    assert p.returncode == 1, p.stdout + p.stderr[-2000:]
    assert "sharded_sim.run_rounds" in p.stdout  # culprit named
    assert "re-pin" in p.stdout
    dump = triage / "jaxpr_sharded_sim_run_rounds.txt"
    assert dump.exists()


def test_cli_list_and_rules():
    p = _audit(["--list"])
    assert p.returncode == 0, p.stderr[-2000:]
    assert "sim.run_rounds" in p.stdout
    assert "mesh_axes=i" in p.stdout
    p = _audit(["--rules"])
    assert p.returncode == 0
    for rid in ("IR201", "IR202", "IR203", "IR204", "IR205"):
        assert rid in p.stdout


@pytest.mark.slow
def test_cli_repo_audit_exits_zero():
    p = _audit([])
    assert p.returncode == 0, p.stdout + p.stderr[-2000:]
    assert "0 findings" in p.stdout
    assert "0 budget violations" in p.stdout
