"""paxlint rule fixtures: one true-positive and one true-negative per
rule, pragma/baseline mechanics, and the repo-is-clean contract.

Every fixture is a tiny source snippet linted via
``lint.lint_source`` (``replay_critical=True`` puts DET rules in
scope without needing a package on disk).  The golden-JSON CLI test
and the jax-free import guard live in ``test_paxlint_cli.py``."""

import json
import os

import pytest

from tpu_paxos.analysis import lint
from tpu_paxos.analysis import rules_ctl  # noqa: F401  (registers RULES)
from tpu_paxos.analysis import rules_det  # noqa: F401
from tpu_paxos.analysis import rules_jax  # noqa: F401
from tpu_paxos.analysis import rules_shard  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(src: str, **kw) -> list[str]:
    return [f.rule for f in lint.lint_source(src, **kw)]


# ---------------- DET001: wall-clock ----------------

def test_det001_true_positive_replay_critical():
    src = "import time\n\ndef stamp():\n    return time.time()\n"
    assert rules_of(src) == ["DET001"]


def test_det001_true_positive_sink_function_outside_closure():
    # wall-clock formatted into written bytes is flagged even outside
    # the replay-critical closure (the utils/log.py failure mode)
    src = (
        "import time\n\n"
        "def log_line(stream, msg):\n"
        "    stream.write(f'[{time.time()}] {msg}')\n"
    )
    assert rules_of(src, replay_critical=False) == ["DET001"]


def test_det001_true_negative_outside_scope():
    # plain host timing in a non-sink function outside the closure
    src = (
        "import time\n\n"
        "def elapsed():\n    return time.perf_counter()\n"
    )
    assert rules_of(src, replay_critical=False) == []


# ---------------- DET002: unseeded randomness ----------------

def test_det002_true_positive():
    src = (
        "import random\n\n"
        "def backoff():\n    return random.random()\n"
    )
    assert rules_of(src) == ["DET002"]


def test_det002_legacy_numpy_global_flagged():
    src = "import numpy as np\n\ndef f():\n    return np.random.rand(3)\n"
    assert rules_of(src) == ["DET002"]


def test_det002_true_negative_seeded():
    # the sanctioned patterns: jax.random streams, seeded Generators
    src = (
        "import jax\nimport numpy as np\n\n"
        "def f(seed):\n"
        "    k = jax.random.fold_in(jax.random.PRNGKey(seed), 3)\n"
        "    return jax.random.uniform(k), np.random.default_rng(seed)\n"
    )
    assert rules_of(src) == []


# ---------------- DET003: unordered iteration ----------------

def test_det003_true_positive_set_iteration():
    src = (
        "def log_members(members):\n"
        "    return ' '.join(str(m) for m in set(members))\n"
    )
    assert rules_of(src) == ["DET003"]


def test_det003_repo_idiom_set_accessor():
    src = (
        "def dump(sim):\n"
        "    return [x for x in sim.acceptor_set()]\n"
    )
    assert rules_of(src) == ["DET003"]


def test_det003_true_negative_sorted():
    src = (
        "def log_members(members):\n"
        "    return ' '.join(str(m) for m in sorted(set(members)))\n"
    )
    assert rules_of(src) == []


def test_det003_true_negative_order_insensitive():
    # reductions and membership tests never leak order
    src = (
        "def f(a, b):\n"
        "    return len(set(a) & set(b)), min(set(a)), 3 in set(b)\n"
    )
    assert rules_of(src) == []


# -- dataflow-aware DET003: set/dict-view kinds through locals --

def test_det003_dataflow_set_through_local():
    src = (
        "def emit(out, xs):\n"
        "    s = set(xs)\n"
        "    for x in s:\n"
        "        out.write(str(x))\n"
    )
    findings = lint.lint_source(src)
    assert [f.rule for f in findings] == ["DET003"]
    assert "set-typed by assignment" in findings[0].message


def test_det003_dataflow_chained_local():
    # one hop of name-to-name propagation: t = s = set(...)-ish chains
    src = (
        "def emit(out, xs):\n"
        "    s = set(xs)\n"
        "    t = s\n"
        "    for x in t:\n"
        "        out.write(str(x))\n"
    )
    assert rules_of(src) == ["DET003"]


def test_det003_dataflow_true_negative_sorted_assignment():
    # the local holds a LIST (sorted) — iteration is deterministic
    src = (
        "def emit(out, xs):\n"
        "    s = sorted(set(xs))\n"
        "    for x in s:\n"
        "        out.write(str(x))\n"
    )
    assert rules_of(src) == []


def test_det003_dataflow_true_negative_reassigned():
    # any non-set rebinding poisons the name: no false positive
    src = (
        "def emit(out, xs):\n"
        "    s = set(xs)\n"
        "    s = list(range(3))\n"
        "    for x in s:\n"
        "        out.write(str(x))\n"
    )
    assert rules_of(src) == []


def test_det003_dataflow_true_negative_loop_target():
    # a name that is also a for-target is not a tracked set
    src = (
        "def emit(out, xs):\n"
        "    s = set(xs)\n"
        "    for s in ([1], [2]):\n"
        "        for x in s:\n"
        "            out.write(str(x))\n"
    )
    assert rules_of(src) == []


def test_det003_dataflow_set_local_sorted_at_site():
    # sorting at the iteration site clears the tracked local too
    src = (
        "def emit(out, xs):\n"
        "    s = set(xs)\n"
        "    for x in sorted(s):\n"
        "        out.write(str(x))\n"
    )
    assert rules_of(src) == []


def test_det003_dataflow_augassign_preserves_set_kind():
    src = (
        "def emit(out, xs, ys):\n"
        "    s = set(xs)\n"
        "    s |= set(ys)\n"
        "    for x in s:\n"
        "        out.write(str(x))\n"
    )
    assert rules_of(src) == ["DET003"]


def test_det003_dataflow_dict_view_through_local_in_sink():
    src = (
        "import json\n\n"
        "def emit(summary):\n"
        "    view = summary.items()\n"
        "    print(json.dumps([k for k, v in view]))\n"
    )
    assert "DET003" in rules_of(src, replay_critical=False)


def test_det003_dataflow_true_negative_param_shadow():
    # a parameter conditionally defaulted to a set stays untracked:
    # the caller may pass a sorted list for it
    src = (
        "def emit(out, xs, s=None):\n"
        "    if s is None:\n"
        "        s = set(xs)\n"
        "    for x in s:\n"
        "        out.write(str(x))\n"
    )
    assert rules_of(src) == []


def test_det003_dataflow_scopes_are_separate():
    # a set-typed name in one function must not taint a sibling's
    src = (
        "def a(out, xs):\n"
        "    s = set(xs)\n"
        "    return len(s)\n\n"
        "def b(out, s):\n"
        "    for x in s:\n"
        "        out.write(str(x))\n"
    )
    assert rules_of(src) == []


def test_det003_dict_view_in_sink():
    src = (
        "import json\n\n"
        "def emit(summary):\n"
        "    print(json.dumps({k: v for k, v in summary.items()}))\n"
    )
    assert "DET003" in rules_of(src, replay_critical=False)


def test_det003_dict_view_ok_outside_sink():
    # insertion order is deterministic in-process; only flag when it
    # escapes through a serialization sink
    src = (
        "def total(d):\n"
        "    out = 0\n"
        "    for k, v in d.items():\n        out += v\n"
        "    return out\n"
    )
    assert rules_of(src) == []


# ---------------- DET004: jax.config.update containment ----------------

def test_det004_true_positive_anywhere():
    src = (
        "import jax\n\n"
        "def setup():\n"
        "    jax.config.update('jax_threefry_partitionable', False)\n"
    )
    assert rules_of(src, replay_critical=False) == ["DET004"]


def test_det004_true_negative_in_prng(tmp_path):
    # the one sanctioned home; exercised on a real path layout
    pkg = tmp_path / "tpu_paxos" / "utils"
    pkg.mkdir(parents=True)
    (pkg / "prng.py").write_text(
        "import jax\njax.config.update('jax_threefry_partitionable', True)\n"
    )
    findings = lint.lint_files(str(tmp_path), ["tpu_paxos/utils/prng.py"])
    assert [f.rule for f in findings] == []


# ---------------- JAX101: traced-value branching ----------------

JIT_IF = (
    "import jax\n\n"
    "@jax.jit\n"
    "def step(state):\n"
    "    if state > 0:\n        return state\n"
    "    return -state\n"
)


def test_jax101_true_positive_decorator():
    assert rules_of(JIT_IF, replay_critical=False) == ["JAX101"]


def test_jax101_true_positive_lax_body():
    src = (
        "import jax\n\n"
        "def outer(st0):\n"
        "    def body(st):\n"
        "        while st < 4:\n            st = st + 1\n"
        "        return st\n"
        "    return jax.lax.while_loop(lambda s: s < 10, body, st0)\n"
    )
    assert rules_of(src, replay_critical=False) == ["JAX101"]


def test_jax101_true_negative_static_argnames():
    src = (
        "import jax\n\n"
        "def choose(state, quorum):\n"
        "    if quorum > 1:\n        return state\n"
        "    return -state\n\n"
        "choose_jit = jax.jit(choose, static_argnames=('quorum',))\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_jax101_true_negative_shape_and_none_tests():
    src = (
        "import jax\n\n"
        "@jax.jit\n"
        "def f(x, y=None):\n"
        "    if x.ndim > 1 and y is None:\n        return x.sum()\n"
        "    return x\n"
    )
    assert rules_of(src, replay_critical=False) == []


# ---------------- JAX102: mutable capture ----------------

def test_jax102_true_positive_module_mutable():
    src = (
        "import jax\n\n"
        "SCALE = [2.0]\n\n"
        "@jax.jit\n"
        "def f(x):\n    return x * SCALE[0]\n"
    )
    assert rules_of(src, replay_critical=False) == ["JAX102"]


def test_jax102_true_positive_global_stmt():
    src = (
        "import jax\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    global counter\n"
        "    return x\n"
    )
    assert rules_of(src, replay_critical=False) == ["JAX102"]


def test_jax101_nested_helper_inside_jit_is_traced():
    # factoring the branch into a nested helper must not hide it
    src = (
        "import jax\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    def inner(y):\n"
        "        if y > 0:\n            return y\n"
        "        return -y\n"
        "    return inner(x)\n"
    )
    assert rules_of(src, replay_critical=False) == ["JAX101"]


def test_jax102_true_negative_immutable_capture():
    src = (
        "import jax\n\n"
        "SCALES = (2.0, 3.0)\nNAMES = ['a']\n\n"
        "@jax.jit\n"
        "def f(x):\n    return x * SCALES[0]\n\n"
        "def host():\n    return NAMES[0]\n"
    )
    assert rules_of(src, replay_critical=False) == []


# ---------------- JAX103: host sync in loop ----------------

def test_jax103_true_positive():
    src = (
        "import numpy as np\n\n"
        "def drive(sim):\n"
        "    for _ in range(100):\n"
        "        sim.state = sim.step()\n"
        "        if np.asarray(sim.state.done):\n            break\n"
    )
    assert rules_of(src, replay_critical=False) == ["JAX103"]


def test_jax103_true_positive_item():
    src = (
        "def drive(steps, st):\n"
        "    while st.t.item() < 10:\n        st = step(st)\n"
    )
    assert rules_of(src, replay_critical=False) == ["JAX103"]


def test_jax103_true_negative_hoisted_and_host_lists():
    # sync after the loop + np.asarray on plain host data: both fine
    src = (
        "import numpy as np\n\n"
        "def drive(sim, workload):\n"
        "    for w in workload:\n"
        "        sim.push(np.asarray(w, np.int32))\n"
        "    return np.asarray(sim.state.done)\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_jax103_loop_else_runs_once():
    src = (
        "def drive(sim):\n"
        "    for _ in range(100):\n"
        "        sim.push()\n"
        "    else:\n"
        "        final = sim.state.x.item()\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_jax103_for_iter_evaluates_once():
    src = (
        "import numpy as np\n\n"
        "def scan(st):\n"
        "    for v in np.asarray(st.own_assign):\n"
        "        use(v)\n"
    )
    assert rules_of(src, replay_critical=False) == []


# ---------------- JAX104: missing static_argnames ----------------

def test_jax104_true_positive():
    src = (
        "import jax\nimport jax.numpy as jnp\n\n"
        "@jax.jit\n"
        "def init(n):\n    return jnp.zeros(n)\n"
    )
    assert rules_of(src, replay_critical=False) == ["JAX104"]


def test_jax104_true_negative_with_static():
    src = (
        "import jax\nimport jax.numpy as jnp\n\n"
        "def init(n):\n    return jnp.zeros(n)\n\n"
        "init_jit = jax.jit(init, static_argnames=('n',))\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_jax101_static_declaration_survives_double_marking():
    # a function can be both a lax body and a named jit target: the
    # static_argnames declaration must win regardless of which
    # marking is encountered first
    src = (
        "import jax\n\n"
        "def step(st, n):\n"
        "    if n > 0:\n        return st\n"
        "    return -st\n\n"
        "step_jit = jax.jit(step, static_argnames=('n',))\n"
        "def outer(st0):\n"
        "    return jax.lax.while_loop(lambda s: s[1] < 3, step, st0)\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_jax104_lax_bodies_exempt():
    # lax bodies can't take static_argnames; range over a traced
    # carry is JAX101's business, not JAX104's
    src = (
        "import jax\n\n"
        "def outer(st0):\n"
        "    return jax.lax.while_loop(lambda s: s < 10,\n"
        "                              lambda s: s + 1, st0)\n"
    )
    assert rules_of(src, replay_critical=False) == []


# ---------------- CTL001: raw cause-code literals ----------------

def test_ctl001_true_positive_subscript_key():
    src = (
        "def is_gray(dc):\n"
        "    return dc['cause_id'] == 2\n"
    )
    assert rules_of(src, replay_critical=False) == ["CTL001"]


def test_ctl001_true_positive_membership():
    # `in`/`not in` against a cause_ids list is the same smell
    src = (
        "def vetoed(dc):\n"
        "    return 2 in dc['cause_ids']\n"
    )
    assert rules_of(src, replay_critical=False) == ["CTL001"]


def test_ctl001_true_positive_call_result():
    src = (
        "from tpu_paxos.telemetry import diagnose as diag\n\n"
        "def f(name):\n"
        "    return diag.cause_code(name) != 3\n"
    )
    assert rules_of(src, replay_critical=False) == ["CTL001"]


def test_ctl001_true_negative_named_lookup():
    # the sanctioned spelling: compare against the named table row
    src = (
        "from tpu_paxos.telemetry import diagnose as diag\n\n"
        "def is_gray(dc):\n"
        "    return dc['cause_id'] == diag.CAUSE_IDS['gray-region']\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_ctl001_true_negative_unrelated_int_compare():
    # int literals against non-cause expressions are none of CTL001's
    # business
    src = (
        "def f(dc):\n"
        "    return dc['level'] == 2 and len(dc['windows']) > 0\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_ctl001_true_negative_bool_literal():
    # True/False are ints to the interpreter but not wire codes
    src = (
        "def f(dc):\n"
        "    return dc['cause_known'] == True  # noqa: E712\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_ctl001_exempt_in_table_owner(tmp_path):
    # diagnose.py OWNS the name<->code table; relating literals to
    # names there is the module's whole job
    src = "CAUSE_IDS = {'unknown': 0}\nOK = CAUSE_IDS['unknown'] == 0\n"
    assert rules_of(src, replay_critical=False,
                    path="tpu_paxos/telemetry/diagnose.py") == []


# ---------------- SH001: sharding primitives stay in parallel/ ------

def test_sh001_true_positive_partitionspec_import():
    src = "from jax.sharding import PartitionSpec as P\n"
    assert rules_of(src, replay_critical=False) == ["SH001"]


def test_sh001_true_positive_raw_shard_map_import():
    # both spellings of the raw tiling import are the same bypass
    src = "from jax.experimental.shard_map import shard_map\n"
    assert rules_of(src, replay_critical=False) == ["SH001"]
    src = "import jax.experimental.shard_map\n"
    assert rules_of(src, replay_critical=False) == ["SH001"]


def test_sh001_true_positive_dotted_reference():
    # no import to catch: the dotted reference itself bakes in the
    # hand-built spec
    src = (
        "import jax\n\n"
        "def spec():\n"
        "    return jax.sharding.PartitionSpec('i')\n"
    )
    assert rules_of(src, replay_critical=False) == ["SH001"]


def test_sh001_true_negative_table_and_mesh_surface():
    # the sanctioned spelling: specs from the committed table, tiling
    # through the validating wrapper
    src = (
        "from tpu_paxos.parallel import mesh as pmesh\n"
        "from tpu_paxos.parallel import partition_rules as prules\n\n"
        "def tile(fn, mesh, state):\n"
        "    spec = prules.tree_spec('fleet', state, mesh.axis_names)\n"
        "    return pmesh.shard_map(\n"
        "        fn, mesh, in_specs=(spec,), out_specs=spec)\n"
    )
    assert rules_of(src, replay_critical=False) == []


def test_sh001_true_negative_unrelated_jax_sharding_import():
    # Mesh itself is not a spec primitive; importing it is not the
    # smell SH001 hunts
    src = "from jax.sharding import Mesh\n"
    assert rules_of(src, replay_critical=False) == []


def test_sh001_exempt_in_parallel_owner():
    src = "from jax.sharding import PartitionSpec as P\n"
    assert rules_of(src, replay_critical=False,
                    path="tpu_paxos/parallel/mesh.py") == []


def test_sh001_pragma_suppresses():
    src = (
        "from jax.sharding import PartitionSpec as P"
        "  # paxlint: allow[SH001] fixture builds a raw collective\n"
    )
    assert rules_of(src, replay_critical=False) == []


# ---------------- pragmas ----------------

def test_pragma_same_line():
    src = (
        "import time\n\n"
        "def stamp():\n"
        "    return time.time()  # paxlint: allow[DET001] zeroed later\n"
    )
    assert rules_of(src) == []


def test_pragma_standalone_line_above():
    src = (
        "import time\n\n"
        "def stamp():\n"
        "    # paxlint: allow[DET001] zeroed in deterministic mode\n"
        "    return time.time()\n"
    )
    assert rules_of(src) == []


def test_pragma_wrong_rule_does_not_suppress():
    src = (
        "import time\n\n"
        "def stamp():\n"
        "    return time.time()  # paxlint: allow[DET002]\n"
    )
    assert rules_of(src) == ["DET001"]


def test_pragma_star_suppresses_all():
    src = (
        "import time\n\n"
        "def stamp():\n"
        "    return time.time()  # paxlint: allow[*] legacy\n"
    )
    assert rules_of(src) == []


# ---------------- baseline mechanics ----------------

def test_baseline_consumes_findings():
    f = lint.Finding("DET001", "a.py", 3, 0, "m", "h")
    remaining, stale = lint.apply_baseline(
        [f, f], {("DET001", "a.py"): 2}
    )
    assert remaining == [] and stale == []


def test_baseline_stale_entry_reported():
    remaining, stale = lint.apply_baseline([], {("DET001", "a.py"): 2})
    assert remaining == []
    assert stale == [{"rule": "DET001", "file": "a.py", "unused": 2}]


def test_baseline_undercount_leaves_findings():
    f = lint.Finding("DET001", "a.py", 3, 0, "m", "h")
    remaining, stale = lint.apply_baseline(
        [f, f], {("DET001", "a.py"): 1}
    )
    assert len(remaining) == 1 and stale == []


def test_path_scoped_run_skips_out_of_selection_baseline(tmp_path):
    """A baseline entry for a file OUTSIDE the linted selection is not
    stale — it never had the chance to match (regression: `python -m
    tpu_paxos lint tpu_paxos/core` used to fail on the engine.py
    baseline entry)."""
    (tmp_path / "clean.py").write_text("x = 1\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({
        "version": 1,
        "entries": [{"rule": "JAX103", "file": "elsewhere.py", "count": 1}],
    }))
    report = lint.run_lint(
        root=str(tmp_path), paths=["clean.py"], baseline_path=str(bl)
    )
    assert report["ok"], report
    assert report["stale_baseline"] == []
    # ... but a full (unscoped) run of the same root does report it
    full = lint.run_lint(root=str(tmp_path), baseline_path=str(bl))
    assert not full["ok"] and full["stale_baseline"]


def test_repo_path_scoped_lint_is_clean():
    report = lint.run_lint(root=REPO, paths=["tpu_paxos/core"])
    assert report["ok"], json.dumps(report, indent=1)


def test_overlapping_paths_lint_each_file_once():
    # dir + file inside it: no double-counted findings past baseline
    report = lint.run_lint(
        root=REPO, paths=["tpu_paxos", "tpu_paxos/membership/engine.py"]
    )
    assert report["ok"], json.dumps(report, indent=1)


def test_missing_path_fails_loudly(tmp_path):
    with pytest.raises(FileNotFoundError):
        lint.run_lint(root=str(tmp_path), paths=["no_such_file.py"])


# ---------------- the repo ships clean, baseline exact ----------------

@pytest.mark.parametrize("use_baseline", [True, False])
def test_repo_lint_contract(use_baseline):
    """The committed tree has zero unsuppressed findings and the
    committed baseline is EXACT: every entry corresponds 1:1 to a
    live finding (no stale debt), proven by comparing the baselined
    count against a baseline-free run."""
    with_bl = lint.run_lint(root=REPO)
    assert with_bl["ok"], json.dumps(with_bl, indent=1)
    assert with_bl["findings"] == []
    assert with_bl["stale_baseline"] == []
    if use_baseline:
        return
    without = lint.run_lint(root=REPO, baseline_path=None)
    # exactly the baselined findings reappear without the baseline
    assert len(without["findings"]) == with_bl["baselined"]
    committed = lint.load_baseline(lint.DEFAULT_BASELINE)
    got: dict = {}
    for f in without["findings"]:
        got[(f["rule"], f["file"])] = got.get((f["rule"], f["file"]), 0) + 1
    assert got == committed


def test_replay_closure_includes_log_via_package_init():
    """Regression for the reachability analysis: core/sim.py imports
    tpu_paxos.utils.prng, which executes utils/__init__.py, which
    imports utils.log — so the logger IS replay-critical even though
    no replay module names it directly."""
    files = lint.walk_files(REPO)
    closure = lint.replay_closure(files, REPO)
    assert "tpu_paxos.utils.log" in closure
    assert "tpu_paxos.core.sim" in closure
    # the analysis package itself is not replay-critical
    assert "tpu_paxos.analysis.lint" not in closure


def test_every_rule_documented():
    assert set(lint.RULES) == {
        "CTL001",
        "DET001", "DET002", "DET003", "DET004",
        "JAX101", "JAX102", "JAX103", "JAX104",
        "SH001",
    }


# ---------------- --fix scaffolding (analysis/fix.py) ----------------

def _plan(tmp_path, src):
    from tpu_paxos.analysis import fix

    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "mod.py").write_text(src)
    report = lint.run_lint(
        root=str(tmp_path), paths=["pkg/mod.py"], baseline_path=None
    )
    return report, fix.plan_fixes(report, str(tmp_path))


def _fixed_text(plans):
    return plans["pkg/mod.py"][1]


def test_fix_det003_wraps_iteration_in_sorted(tmp_path):
    from tpu_paxos.analysis import fix

    src = (
        "def emit(items):\n"
        "    s = set(items)\n"
        "    out = []\n"
        "    for x in s:\n"
        "        out.append(x)\n"
        "    print(out)\n"
    )
    report, plans = _plan(tmp_path, src)
    assert [f["rule"] for f in report["findings"]] == ["DET003"]
    assert "    for x in sorted(s):\n" in _fixed_text(plans)
    fix.apply_fixes(plans, str(tmp_path))
    report2 = lint.run_lint(
        root=str(tmp_path), paths=["pkg/mod.py"], baseline_path=None
    )
    assert report2["findings"] == []


def test_fix_det003_wraps_whole_dict_view_call(tmp_path):
    src = (
        "import json\n\n"
        "def dump(stream, d):\n"
        "    for k, v in d.items():\n"
        "        stream.write(json.dumps([k, v]))\n"
    )
    _report, plans = _plan(tmp_path, src)
    assert "    for k, v in sorted(d.items()):\n" in _fixed_text(plans)


def test_fix_det003_multiline_expression(tmp_path):
    src = (
        "def emit(items, extra):\n"
        "    for x in set(\n"
        "        items + extra\n"
        "    ):\n"
        "        print(x)\n"
    )
    _report, plans = _plan(tmp_path, src)
    fixed = _fixed_text(plans)
    assert "    for x in sorted(set(\n" in fixed
    assert "    )):\n" in fixed
    # the rewrite must still parse
    import ast

    ast.parse(fixed)


def test_fix_pragma_scaffold_indented_with_todo(tmp_path):
    from tpu_paxos.analysis import fix

    src = (
        "import time\n\n"
        "def log_line(stream, msg):\n"
        "    stream.write(f'[{time.time()}] {msg}')\n"
    )
    report, plans = _plan(tmp_path, src)
    assert [f["rule"] for f in report["findings"]] == ["DET001"]
    fixed = _fixed_text(plans)
    assert (
        "    # paxlint: allow[DET001] " + fix.TODO_REASON + "\n"
        "    stream.write(f'[{time.time()}] {msg}')\n"
    ) in fixed
    fix.apply_fixes(plans, str(tmp_path))
    report2 = lint.run_lint(
        root=str(tmp_path), paths=["pkg/mod.py"], baseline_path=None
    )
    assert report2["findings"] == []  # scaffold suppresses until review


def test_fix_true_negative_clean_file_no_plans(tmp_path):
    from tpu_paxos.analysis import fix

    src = "def ok(xs):\n    return sorted(set(xs))\n"
    report, plans = _plan(tmp_path, src)
    assert report["findings"] == []
    assert plans == {}
    assert fix.render_diff(plans) == ""


def test_fix_mixed_findings_apply_bottom_up(tmp_path):
    from tpu_paxos.analysis import fix

    src = (
        "import time\n\n"
        "def emit(items):\n"
        "    s = set(items)\n"
        "    for x in s:\n"
        "        print(x)\n"
        "    print(time.time())\n"
    )
    _report, plans = _plan(tmp_path, src)
    fixed = _fixed_text(plans)
    assert "    for x in sorted(s):\n" in fixed
    assert "    # paxlint: allow[DET001]" in fixed
    fix.apply_fixes(plans, str(tmp_path))
    report2 = lint.run_lint(
        root=str(tmp_path), paths=["pkg/mod.py"], baseline_path=None
    )
    assert report2["findings"] == []


def test_fix_same_line_wrap_and_pragma_do_not_corrupt(tmp_path):
    # DET003 and DET001 on ONE line: the pragma insert must not shift
    # the wrap's coordinates (wraps run first, inserts bottom-up)
    from tpu_paxos.analysis import fix

    src = (
        "import time\n\n"
        "def emit(stream, s):\n"
        "    for x in s & {1}: stream.write(str(time.time()))\n"
    )
    report, plans = _plan(tmp_path, src)
    assert {f["rule"] for f in report["findings"]} == {
        "DET001", "DET003"
    }
    fixed = _fixed_text(plans)
    assert "for x in sorted(s & {1}):" in fixed
    assert "    # paxlint: allow[DET001]" in fixed
    import ast

    ast.parse(fixed)
    fix.apply_fixes(plans, str(tmp_path))
    report2 = lint.run_lint(
        root=str(tmp_path), paths=["pkg/mod.py"], baseline_path=None
    )
    assert report2["findings"] == []


def test_fix_skips_unparseable_file_without_crashing(tmp_path):
    from tpu_paxos.analysis import fix

    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "bad.py").write_text("def broken(:\n")
    (tmp_path / "pkg" / "mod.py").write_text(
        "def emit(xs):\n    for x in set(xs):\n        print(x)\n"
    )
    report = lint.run_lint(
        root=str(tmp_path), paths=["pkg"], baseline_path=None
    )
    assert "PARSE" in {f["rule"] for f in report["findings"]}
    plans = fix.plan_fixes(report, str(tmp_path))  # must not raise
    assert set(plans) == {"pkg/mod.py"}


def test_fix_plan_is_dry_run_and_apply_refuses_stale(tmp_path):
    import pytest as _pytest

    from tpu_paxos.analysis import fix

    src = "import time\n\ndef f(s):\n    s.write(str(time.time()))\n"
    _report, plans = _plan(tmp_path, src)
    path = tmp_path / "pkg" / "mod.py"
    assert path.read_text() == src  # planning never writes
    path.write_text(src + "\n# drifted\n")
    with _pytest.raises(RuntimeError, match="changed since"):
        fix.apply_fixes(plans, str(tmp_path))


def test_fix_stale_apply_writes_nothing_at_all(tmp_path):
    # staleness in ANY planned file must abort BEFORE the first write
    # — never leave the tree half-fixed
    import pytest as _pytest

    from tpu_paxos.analysis import fix

    (tmp_path / "pkg").mkdir()
    a = "import time\n\ndef f(s):\n    s.write(str(time.time()))\n"
    b = "import time\n\ndef g(s):\n    s.write(str(time.time()))\n"
    (tmp_path / "pkg" / "a.py").write_text(a)
    (tmp_path / "pkg" / "b.py").write_text(b)
    report = lint.run_lint(
        root=str(tmp_path), paths=["pkg"], baseline_path=None
    )
    plans = fix.plan_fixes(report, str(tmp_path))
    assert set(plans) == {"pkg/a.py", "pkg/b.py"}
    (tmp_path / "pkg" / "b.py").write_text(b + "# drifted\n")
    with _pytest.raises(RuntimeError, match="b.py changed since"):
        fix.apply_fixes(plans, str(tmp_path))
    assert (tmp_path / "pkg" / "a.py").read_text() == a  # untouched


def test_fix_never_plans_a_corrupting_rewrite(tmp_path):
    # a finding on a backslash-continuation line: the pragma would
    # split the continuation — the plan must drop the file, not ship
    # unimportable code
    src = (
        "import time\n\n"
        "def f(s):\n"
        "    x = 1 + \\\n"
        "        time.time()\n"
        "    s.write(str(x))\n"
    )
    report, plans = _plan(tmp_path, src)
    assert [f["rule"] for f in report["findings"]] == ["DET001"]
    assert plans == {}
