"""Membership engine tests — member/ parity.

Mirrors the reference churn harness: a 1-node bootstrap cluster grows
by AddAcceptor (waiting for Applied between changes, ref
member/main.cpp:121-146), values are proposed round-robin while churn
is in flight (ref member/main.cpp:204-212), acceptors are then
deleted, and every node's applied log must be a prefix of node 0's
(ref member/main.cpp:260-265)."""

import numpy as np
import pytest

from tpu_paxos.harness import validate
from tpu_paxos.membership import (
    ADD_ACCEPTOR,
    DEL_ACCEPTOR,
    MemberSim,
    change_vid,
    decode_change,
)


def _drain(ms: MemberSim, vids) -> None:
    ok = ms.run_until(lambda: all(ms.chosen(v) for v in vids), max_rounds=2000)
    assert ok, f"values not chosen after {int(ms.state.t)} rounds"


def _check_prefix(ms: MemberSim, n: int):
    logs = [ms.applied_log(i) for i in range(n)]
    validate.check_prefix_consistency(logs)
    return logs


def test_change_vid_roundtrip():
    for node in (0, 3, 6):
        for kind in range(8):
            assert decode_change(change_vid(node, kind)) == (node, kind)


def test_bootstrap_single_node_chooses():
    ms = MemberSim(n_nodes=3, n_instances=16, seed=0)
    ms.propose(0, 5)
    _drain(ms, [5])
    assert ms.applied(5)
    assert ms.applied_log(0).tolist() == [5]


def test_add_acceptor_updates_views_and_version():
    ms = MemberSim(n_nodes=3, n_instances=16, seed=0)
    vid = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(vid), max_rounds=400)
    assert ms.acceptor_set(0) == {0, 1}
    assert ms.acceptor_set(1) == {0, 1}
    v = np.asarray(ms.state.version)
    assert v[0] == 1 and v[1] == 1  # acceptor change bumps version
    assert v[2] == 0  # node 2 is not a member yet


def test_del_acceptor():
    ms = MemberSim(n_nodes=3, n_instances=32, seed=0)
    a = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(a), max_rounds=400)
    d = ms.del_acceptor(1, via=0)
    assert ms.run_until(lambda: ms.applied(d), max_rounds=400)
    assert ms.acceptor_set(0) == {0}
    assert 1 not in ms.learner_set(0)  # DEL_ACCEPTOR demotes to gone
    assert np.asarray(ms.state.version)[0] == 2


def test_proposals_during_membership_change():
    """Values proposed while a change is in flight must still land
    exactly once, with prefix-consistent logs."""
    ms = MemberSim(n_nodes=3, n_instances=32, seed=0)
    ms.propose(0, 100)
    c = ms.add_acceptor(1)
    ms.propose(0, 101)
    assert ms.run_until(
        lambda: ms.applied(c) and ms.chosen(100) and ms.chosen(101),
        max_rounds=800,
    )
    logs = _check_prefix(ms, 2)
    assert sorted(logs[0].tolist()) == [100, 101]


def test_churn_grow_then_shrink_baseline_config5():
    """The member/main.cpp churn schedule at n=5 (grow 1->5 by
    AddAcceptor, values interleaved, then shrink back), plus growth to
    7 — covering BASELINE config 5's 5->7 reconfiguration mid-log."""
    n = 7
    ms = MemberSim(n_nodes=n, n_instances=96, seed=0)
    next_vid = [0]

    def burst(k=2, via=0):
        out = []
        for _ in range(k):
            v = next_vid[0]
            next_vid[0] += 1
            ms.propose(via, v)
            out.append(v)
        return out

    proposed = []
    # grow 1 -> 5 (the member/ run.sh shape), proposing between changes
    for tgt in range(1, 5):
        proposed += burst()
        c = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=2000), tgt
    assert ms.acceptor_set(0) == {0, 1, 2, 3, 4}
    # mid-log 5 -> 7 reconfiguration (BASELINE config 5)
    for tgt in (5, 6):
        proposed += burst()
        c = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=2000), tgt
    assert ms.acceptor_set(0) == set(range(7))
    # values proposed via later members too
    proposed += burst(via=3)
    _drain(ms, proposed)
    # shrink back to {0}
    for tgt in range(1, 7):
        c = ms.del_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=2000), tgt
    assert ms.acceptor_set(0) == {0}
    proposed_final = burst()
    _drain(ms, proposed_final)

    logs = _check_prefix(ms, n)
    # node 0 applied every real value exactly once
    assert sorted(logs[0].tolist()) == sorted(proposed + proposed_final)
    counts = np.unique(logs[0], return_counts=True)[1]
    assert (counts == 1).all()


def test_version_gates_stale_accepts():
    """A proposer with a stale view must not get values accepted until
    it catches up (ref member/paxos.cpp:1702, 1747): after a change
    applies, the old version's quorum is dead."""
    ms = MemberSim(n_nodes=3, n_instances=32, seed=0)
    c = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(c), max_rounds=400)
    v0 = int(np.asarray(ms.state.version)[0])
    # both members now at the same version; a proposal still lands
    ms.propose(1, 200)
    assert ms.run_until(lambda: ms.chosen(200), max_rounds=800)
    assert int(np.asarray(ms.state.version)[1]) == v0
    _check_prefix(ms, 2)
