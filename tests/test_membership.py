"""Membership engine tests — member/ parity.

Mirrors the reference churn harness: a 1-node bootstrap cluster grows
by AddAcceptor (waiting for Applied between changes, ref
member/main.cpp:121-146), values are proposed round-robin while churn
is in flight (ref member/main.cpp:204-212), acceptors are then
deleted, and every node's applied log must be a prefix of node 0's
(ref member/main.cpp:260-265)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_paxos.core import values as val
from tpu_paxos.harness import validate
from tpu_paxos.membership import (
    ADD_ACCEPTOR,
    DEL_ACCEPTOR,
    MemberSim,
    change_vid,
    decode_change,
)


def _drain(ms: MemberSim, vids) -> None:
    ok = ms.run_until(lambda: all(ms.chosen(v) for v in vids), max_rounds=2000)
    assert ok, f"values not chosen after {int(ms.state.t)} rounds"


def _check_prefix(ms: MemberSim, n: int):
    logs = [ms.applied_log(i) for i in range(n)]
    validate.check_prefix_consistency(logs)
    return logs


def test_change_vid_roundtrip():
    for node in (0, 3, 6):
        for kind in range(8):
            assert decode_change(change_vid(node, kind)) == (node, kind)


def test_bootstrap_single_node_chooses():
    ms = MemberSim(n_nodes=3, n_instances=16, seed=0)
    ms.propose(0, 5)
    _drain(ms, [5])
    assert ms.applied(5)
    assert ms.applied_log(0).tolist() == [5]


def test_add_acceptor_updates_views_and_version():
    ms = MemberSim(n_nodes=3, n_instances=16, seed=0)
    vid = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(vid), max_rounds=400)
    assert ms.acceptor_set(0) == {0, 1}
    assert ms.acceptor_set(1) == {0, 1}
    v = np.asarray(ms.state.version)
    assert v[0] == 1 and v[1] == 1  # acceptor change bumps version
    assert v[2] == 0  # node 2 is not a member yet


def test_del_acceptor():
    ms = MemberSim(n_nodes=3, n_instances=32, seed=0)
    a = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(a), max_rounds=400)
    d = ms.del_acceptor(1, via=0)
    assert ms.run_until(lambda: ms.applied(d), max_rounds=400)
    assert ms.acceptor_set(0) == {0}
    assert 1 not in ms.learner_set(0)  # DEL_ACCEPTOR demotes to gone
    assert np.asarray(ms.state.version)[0] == 2


def test_proposals_during_membership_change():
    """Values proposed while a change is in flight must still land
    exactly once, with prefix-consistent logs."""
    ms = MemberSim(n_nodes=3, n_instances=32, seed=0)
    ms.propose(0, 100)
    c = ms.add_acceptor(1)
    ms.propose(0, 101)
    assert ms.run_until(
        lambda: ms.applied(c) and ms.chosen(100) and ms.chosen(101),
        max_rounds=800,
    )
    logs = _check_prefix(ms, 2)
    assert sorted(logs[0].tolist()) == [100, 101]


def test_churn_grow_then_shrink_baseline_config5():
    """The member/main.cpp churn schedule at n=5 (grow 1->5 by
    AddAcceptor, values interleaved, then shrink back), plus growth to
    7 — covering BASELINE config 5's 5->7 reconfiguration mid-log."""
    n = 7
    ms = MemberSim(n_nodes=n, n_instances=96, seed=0)
    next_vid = [0]

    def burst(k=2, via=0):
        out = []
        for _ in range(k):
            v = next_vid[0]
            next_vid[0] += 1
            ms.propose(via, v)
            out.append(v)
        return out

    proposed = []
    # grow 1 -> 5 (the member/ run.sh shape), proposing between changes
    for tgt in range(1, 5):
        proposed += burst()
        c = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=2000), tgt
    assert ms.acceptor_set(0) == {0, 1, 2, 3, 4}
    # mid-log 5 -> 7 reconfiguration (BASELINE config 5)
    for tgt in (5, 6):
        proposed += burst()
        c = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=2000), tgt
    assert ms.acceptor_set(0) == set(range(7))
    # values proposed via later members too
    proposed += burst(via=3)
    _drain(ms, proposed)
    # shrink back to {0}
    for tgt in range(1, 7):
        c = ms.del_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=2000), tgt
    assert ms.acceptor_set(0) == {0}
    proposed_final = burst()
    _drain(ms, proposed_final)

    logs = _check_prefix(ms, n)
    # node 0 applied every real value exactly once
    assert sorted(logs[0].tolist()) == sorted(proposed + proposed_final)
    counts = np.unique(logs[0], return_counts=True)[1]
    assert (counts == 1).all()


def test_same_version_members_still_choose():
    """Sanity companion to the stale-version test: two members at the
    same version are NOT gated — a proposal through the newer member
    lands."""
    ms = MemberSim(n_nodes=3, n_instances=32, seed=0)
    c = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(c), max_rounds=400)
    v0 = int(np.asarray(ms.state.version)[0])
    ms.propose(1, 200)
    assert ms.run_until(lambda: ms.chosen(200), max_rounds=800)
    assert int(np.asarray(ms.state.version)[1]) == v0
    _check_prefix(ms, 2)


def test_stale_version_proposer_blocked_until_catchup():
    """The real version gate (ref member/paxos.cpp:1702, 1747): a
    proposer whose view lags behind an acceptor change must get NOTHING
    accepted — no promise, no accept, no choice — until its learn
    frontier catches up, after which its proposal lands normally.

    Construction: after two acceptor changes and a few plain values,
    node 1 is rewound to its bootstrap state (seed view {0}, version 0,
    empty learner log).  Its catch-up is paced by the one-instance-per-
    round anti-entropy pull, which opens a multi-round stale window to
    observe the gate acting."""
    ms = MemberSim(n_nodes=3, n_instances=32, seed=1)
    a = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(a), max_rounds=400)
    # plain values BETWEEN the changes, so node 1's rewound frontier
    # must pull through them (one per round) before reaching change b
    fill = [100, 101, 102, 103]
    for v in fill:
        ms.propose(0, v)
    _drain(ms, fill)
    b = ms.add_acceptor(2)
    assert ms.run_until(lambda: ms.applied(b), max_rounds=400)
    v_cur = int(np.asarray(ms.state.version)[0])
    assert v_cur == 2

    st = ms.state
    n = ms.n
    seed_row = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    ms.state = st._replace(
        learners=st.learners.at[1].set(seed_row),
        proposers=st.proposers.at[1].set(seed_row),
        acceptors=st.acceptors.at[1].set(seed_row),
        version=st.version.at[1].set(0),
        applied_upto=st.applied_upto.at[1].set(0),
        learned=st.learned.at[:, 1].set(val.NONE),
        prepared=st.prepared.at[1].set(False),
    )
    ms.propose(1, 300)

    # While node 1's version lags, the gate must hold: 300 is never
    # promised into existence — no acceptor stores it, nobody chooses
    # it, and node 1 never reaches prepared (its rewound view's quorum
    # is acceptor 0, which is at version 2 and drops its prepares).
    stale_rounds = 0
    # paxlint: allow[JAX103] per-round observation IS this test's purpose
    while int(np.asarray(ms.state.version)[1]) < v_cur:
        # paxlint: allow[JAX103] per-round observation IS this test's purpose
        assert not np.any(np.asarray(ms.state.acc_vid) == 300)
        assert not ms.chosen(300)
        # paxlint: allow[JAX103] per-round observation IS this test's purpose
        assert not bool(np.asarray(ms.state.prepared)[1])
        ms.run_rounds(1)
        stale_rounds += 1
        assert stale_rounds < 200, "node 1 never caught up"
    # the gate had a real multi-round window to act in
    assert stale_rounds >= 3

    # Caught up: the proposal now lands and logs stay prefix-consistent.
    assert ms.run_until(lambda: ms.chosen(300), max_rounds=800)
    _check_prefix(ms, 3)


def test_in_order_client_host_gated():
    """member/'s in-order seam: the host proposes each value only
    after the previous one is chosen (the driver pattern of ref
    member/main.cpp:138-140), and the applied order matches proposal
    order — values land while churn is in flight."""
    ms = MemberSim(n_nodes=3, n_instances=32, seed=0)
    c = ms.add_acceptor(1)
    chain = [300, 301, 302, 303]
    assert ms.propose_in_order(0, chain)
    assert ms.run_until(lambda: ms.applied(c), max_rounds=800)
    log = ms.applied_log(0).tolist()
    assert [v for v in log if v in chain] == chain
    _check_prefix(ms, 2)


def test_orphaned_accepted_value_repaired_by_idle_proposer():
    """A value accepted by a live acceptor whose proposer died before
    choosing it must still be chosen: an idle live proposer's
    idle-liveness re-prepare adopts and re-accepts it.  Without the
    repair the apply frontier of every node wedges at the orphan."""
    ms = MemberSim(n_nodes=3, n_instances=16, seed=0)
    a = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(a), max_rounds=400)
    b = ms.add_acceptor(2)
    assert ms.run_until(lambda: ms.applied(b), max_rounds=400)
    st = ms.state
    # craft the orphan at the next free instance: acceptor 1 holds 777
    # accepted at a low real ballot, nobody chose it, no pending work
    # exists anywhere
    k = int(np.max(np.flatnonzero(np.asarray(st.chosen_vid) != val.NONE))) + 1
    assert k < ms.i, "setup grew past capacity; the injection would clamp"
    orphan_ballot = (1 << 16) | 1
    ms.state = st._replace(
        acc_ballot=st.acc_ballot.at[k, 1].set(orphan_ballot),
        acc_vid=st.acc_vid.at[k, 1].set(777),
    )
    assert ms.run_until(lambda: ms.chosen(777), max_rounds=400), (
        "orphaned accepted value never repaired"
    )
    # and it flows through to every live node's applied log
    assert ms.run_until(
        lambda: all(777 in ms.applied_log(i).tolist() for i in range(3)),
        max_rounds=400,
    )
    _check_prefix(ms, 3)


def test_del_live_acceptor_guard():
    """Deleting a live acceptor while crashed ones remain would leave
    the view without a live majority — the host-side guard refuses."""
    ms = MemberSim(n_nodes=5, n_instances=48, seed=0)
    for tgt in (1, 2, 3, 4):
        c = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=2000), tgt
    st = ms.state
    ms.state = st._replace(
        crashed=st.crashed.at[1].set(True).at[2].set(True)
    )
    # view {0..4}: quorum 3, live {0,3,4} — deleting live 3 would leave
    # 2 live of a 3-quorum view
    with pytest.raises(ValueError, match="delete crashed members first"):
        ms.del_acceptor(3)
    # the mirror hazard: adding a crashed node inflates the quorum
    with pytest.raises(ValueError, match="has crashed"):
        ms.add_acceptor(2)
    # deleting a crashed member is the sanctioned repair
    d = ms.del_acceptor(1)
    assert ms.run_until(lambda: ms.applied(d), max_rounds=2000)
    assert ms.acceptor_set(0) == {0, 2, 3, 4}
    # pipelined deletions are checked against the PROJECTED view: del 3
    # alone is fine, but a queued del 4 on top of the un-applied del 3
    # would leave live {0} of a 2-quorum view (a naive per-call check
    # against the current view would admit both and wedge the cluster)
    ms.del_acceptor(3)
    with pytest.raises(ValueError, match="live acceptors"):
        ms.del_acceptor(4)


def test_churn_with_crashes_survivors_progress():
    """The composed capability the reference cannot demonstrate live:
    random fail-stop crashes (ref member/indet.h:146-150 RandomFailure
    semantics, minority-capped) DURING live reconfiguration, with the
    surviving majority completing the churn and every log — including
    the frozen logs of crashed nodes — prefix-consistent."""
    n = 7
    # ~56-round run: 8000/1e6 per node-round makes crashes near-certain
    # (this seed admits three) while the admission cap keeps a live
    # majority in every view
    ms = MemberSim(n_nodes=n, n_instances=96, seed=2, crash_rate=8000)
    proposed = []
    nv = [0]

    def burst(k=2):
        out = []
        for _ in range(k):
            ms.propose(0, nv[0])
            out.append(nv[0])
            nv[0] += 1
        return out

    # Grow, skipping targets that have already crashed (the reference's
    # driver would have aborted the whole run at the first crash; a
    # live operator does not add dead nodes).
    for tgt in range(1, n):
        if tgt in ms.crashed_set():
            continue
        proposed += burst()
        c = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=3000), tgt

    # Shrink back to {0} in the engine's safe order (crashed members
    # first — see MemberSim.next_shrink_target).
    for _ in range(2 * n):
        tgt = ms.next_shrink_target()
        if tgt is None:
            break
        c = ms.del_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(c), max_rounds=3000), tgt
    assert ms.acceptor_set(0) == {0}

    proposed += burst()
    _drain(ms, proposed)

    # The run is only meaningful if crashes actually happened.
    assert len(ms.crashed_set()) >= 1, "tune seed/crash_rate: no crash fired"
    logs = [ms.applied_log(i) for i in range(n)]
    validate.check_prefix_consistency(logs)
    assert sorted(logs[0].tolist()) == sorted(proposed)
    counts = np.unique(logs[0], return_counts=True)[1]
    assert (counts == 1).all()


@pytest.mark.slow
def test_churn_at_config5_literal_size():
    """BASELINE config 5 at its literal size: reconfiguration churn
    with a 1M-instance log (grow 1->7 with values in flight, shrink
    back to 5, Applied sequencing, prefix consistency)."""
    ms = MemberSim(n_nodes=7, n_instances=1 << 20, seed=5)
    vid = 100
    for tgt in range(1, 7):
        ms.propose(0, vid)
        vid += 1
        cv = ms.add_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(cv), max_rounds=4000), tgt
    for tgt in (6, 5):
        cv = ms.del_acceptor(tgt)
        assert ms.run_until(lambda: ms.applied(cv), max_rounds=4000), tgt
    assert ms.run_until(
        lambda: all(ms.chosen(v) for v in range(100, vid)), max_rounds=4000
    )
    validate.check_prefix_consistency([ms.applied_log(i) for i in range(7)])
    assert sorted(ms.acceptor_set(0)) == [0, 1, 2, 3, 4]
