"""bench.py must print exactly one parseable JSON line with the
required keys (the driver parses it verbatim)."""

import json
import os
import subprocess
import sys
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(env_extra):
    env = dict(os.environ)
    env.update(env_extra)
    env["JAX_PLATFORMS"] = "cpu"
    # The axon sitecustomize (on PYTHONPATH) breaks
    # xla_force_host_platform_device_count; drop it for CPU subprocesses.
    env["PYTHONPATH"] = os.pathsep.join(
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        timeout=600,
        check=True,
    )
    lines = [l for l in out.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, f"expected one JSON line, got: {out.stdout!r}"
    return json.loads(lines[0])


@pytest.mark.slow
def test_bench_json_contract():
    """Smoke the headline path plus the secondary sim record at a tiny
    size; the heavyweight sharded subprocess records are exercised by
    the real bench run and skipped here for suite latency."""
    rec = _run(
        {
            "TPU_PAXOS_BENCH_INSTANCES": "4096",
            "TPU_PAXOS_BENCH_REPS": "2",
            "TPU_PAXOS_BENCH_SIM_INSTANCES": "4096",
            "TPU_PAXOS_BENCH_SHARDED_CHILD": "0",
        }
    )
    assert rec["metric"] == "paxos_instances_per_sec_to_chosen"
    assert rec["unit"] == "instances/sec"
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 0
    sim_recs = [s for s in rec["secondary"] if s.get("engine") == "sim"]
    assert sim_recs and sim_recs[0]["done"] is True
    assert sim_recs[0]["rounds_to_chosen"]["p90"] >= 1


def test_bench_sharded_mode():
    rec = _run(
        {
            "TPU_PAXOS_BENCH_INSTANCES": "4096",
            "TPU_PAXOS_BENCH_REPS": "2",
            "TPU_PAXOS_BENCH_SHARDED": "1",
            "TPU_PAXOS_BENCH_SECONDARY": "0",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
    )
    assert rec["config"]["sharded"] is True
    assert rec["config"]["devices"] == 8
