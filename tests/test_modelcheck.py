"""smallcheck (tpu_paxos/analysis/modelcheck.py): codec bijection,
symmetry-reduction canonical forms, chunk-boundary coverage, crash
points, the scope certificate, the seeded-wedge recall pin, and the
batched-shrinker parity pin.

The codec/symmetry/chunking layers are pure host enumeration and run
against the COMMITTED scope file, so a scope edit that breaks the
bijection fails here before it reaches a device.  The dispatch layer
runs fast-tier on a tiny 3-node scope (one small fleet compile); the
quick-scope wedge recall and the full-scope certificate smoke are
slow-tier (they pay real sweeps).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from tpu_paxos.analysis import modelcheck as mc
from tpu_paxos.analysis import triage
from tpu_paxos.core import faults as flt


def _committed_scopes():
    return mc.load_scopes()


TINY = {
    "n_nodes": 3, "proposers": 2, "horizon": 12, "max_rounds": 400,
    "intervals": [[2, 8]], "kinds": ["pause", "burst", "crash"],
    "pause_set_sizes": [1], "burst_rates": [2000],
    "crash_rounds": [4], "crash_set_sizes": [1], "max_episodes": 2,
    "knob_tiers": [{"drop_rate": 500, "max_delay": 2}],
    "gate_tiers": [True, False], "seeds": [0], "chunk_lanes": 4,
    "n_ids": 2, "n_free": 2,
}


# ---------------- codec ----------------

def test_codec_roundtrip_bijection_committed_scopes():
    """THE codec contract: index -> scenario -> index is the identity
    over the ENTIRE cross product of every committed scope — fault,
    churn, AND control scopes through their own codecs (small scopes
    are swept exhaustively; large ones over a stride to stay cheap,
    plus both boundary indices).  Dispatch goes through
    ``mc.enum_for``, the same registry the CLI uses."""
    scopes = _committed_scopes()
    assert {"quick", "full", "gray", "churn", "control"} <= set(scopes)
    for name, scope in scopes.items():
        enum = mc.enum_for(scope)
        idxs = (
            range(enum.total) if enum.total <= 5000
            else [*range(0, enum.total, 97), 0, enum.total - 1]
        )
        for i in idxs:
            sc = enum.decode(i)
            assert enum.encode(sc) == i, (name, i)
        with pytest.raises(IndexError):
            enum.decode(enum.total)
        with pytest.raises(IndexError):
            enum.decode(-1)


def test_combo_rank_unrank_inverse_all_sizes():
    m, k_max = 7, 3
    n = mc.n_combos(m, k_max)
    seen = set()
    for r in range(n):
        combo = mc.combo_unrank(r, m, k_max)
        assert len(combo) <= k_max
        assert list(combo) == sorted(set(combo))
        assert mc.combo_rank(combo, m, k_max) == r
        seen.add(combo)
    assert len(seen) == n  # bijective: no combo repeats
    with pytest.raises(IndexError):
        mc.combo_unrank(n, m, k_max)
    with pytest.raises(ValueError):
        mc.combo_rank((1, 1), m, k_max)  # not strictly increasing


def test_decoded_scenarios_materialize_and_are_distinct():
    """Every reduced quick-scope scenario materializes a valid
    (schedule, knobs, seed) triple, and the materialized schedules
    within one combo-rank block differ only along the declared
    axes."""
    scope = _committed_scopes()["quick"]
    enum = mc.ScopeEnum(scope)
    for i in enum.reduced[:200]:
        sc = enum.decode(i)
        sched = enum.schedule_of(sc)
        if sched is not None:
            assert len(sched.episodes) <= scope.max_episodes
            assert sched.horizon <= scope.horizon
        enum.faults_of(sc)  # FaultConfig validation runs
        d = enum.describe(sc)
        assert d["index"] == i


# ---------------- symmetry reduction ----------------

def test_canonical_form_idempotent_and_unique_per_orbit():
    """canon(canon(x)) == canon(x) for every combo, and each
    permutation orbit contains exactly one canonical member — the
    reduction never drops an orbit or keeps two spellings of one."""
    scope = _committed_scopes()["quick"]
    enum = mc.ScopeEnum(scope)
    assert enum._perms, "quick scope should have movable nodes"
    orbits = {}
    for cr in range(enum.n_combos):
        combo = mc.combo_unrank(cr, enum.m, scope.max_episodes)
        canon = enum.canon_combo(combo)
        assert enum.canon_combo(canon) == canon  # idempotent
        orbits.setdefault(canon, set()).add(combo)
    for canon, members in orbits.items():
        n_canon = sum(
            1 for c in members if enum.canon_combo(c) == c
        )
        assert n_canon == 1, (canon, members)
        assert canon in members  # the representative is enumerable


def test_reduction_preserves_scenario_blocks():
    """The reduced index list is exactly the canonical+feasible
    combos' full per-combo blocks, in increasing order — no scenario
    of a kept combo is dropped, none of a skipped combo leaks in."""
    scope = _committed_scopes()["quick"]
    enum = mc.ScopeEnum(scope)
    per_combo = enum.n_tiers * enum.n_gates * enum.n_seeds
    kept = {
        cr for cr in range(enum.n_combos)
        if enum.canon_combo(
            mc.combo_unrank(cr, enum.m, scope.max_episodes)
        ) == mc.combo_unrank(cr, enum.m, scope.max_episodes)
        and enum.combo_feasible(
            mc.combo_unrank(cr, enum.m, scope.max_episodes)
        )
    }
    expect = [
        i for cr in sorted(kept)
        for i in range(cr * per_combo, (cr + 1) * per_combo)
    ]
    assert enum.reduced == expect


def test_crash_minority_cap_filters_combos():
    """Combos crashing more than a minority are excluded from the
    dispatch set (no quorum survives; a 'wedge' there is vacuous)."""
    scope = mc.McScope.from_dict(dict(
        TINY, crash_rounds=[4, 6], max_episodes=2,
    ))
    enum = mc.ScopeEnum(scope)  # 3 nodes -> minority cap is 1
    over = [
        combo for cr in range(enum.n_combos)
        for combo in [mc.combo_unrank(cr, enum.m, scope.max_episodes)]
        if not enum.combo_feasible(combo)
    ]
    assert over, "expected some two-node crash combos"
    for combo in over:
        crashed = set()
        for i in combo:
            e = enum.alphabet[i]
            if e.kind == "crash":
                crashed.update(e.nodes)
        assert len(crashed) > 1
        # and none of their scenarios are dispatched
        cr = mc.combo_rank(combo, enum.m, scope.max_episodes)
        per = enum.n_tiers * enum.n_gates * enum.n_seeds
        assert not (set(enum.reduced)
                    & set(range(cr * per, (cr + 1) * per)))


# ---------------- chunking ----------------

def test_chunk_boundary_coverage():
    """No scenario skipped or duplicated across chunks; only the last
    chunk pads, by repeating its final lane."""
    scope = _committed_scopes()["quick"]
    enum = mc.ScopeEnum(scope)
    lanes = scope.chunk_lanes
    chunks = mc.chunk_pad(enum.reduced, lanes)
    covered = [i for chunk, n_real in chunks for i in chunk[:n_real]]
    assert covered == enum.reduced  # exact coverage, in order
    for chunk, n_real in chunks[:-1]:
        assert n_real == lanes  # only the last chunk may pad
    last, n_real = chunks[-1]
    assert len(last) == lanes
    assert last[n_real:] == [last[n_real - 1]] * (lanes - n_real)
    assert mc.chunk_pad([], lanes) == []
    with pytest.raises(ValueError):
        mc.chunk_pad([1], 0)


# ---------------- scope validation ----------------

def test_scope_validation_errors():
    with pytest.raises(mc.ScopeError, match="unknown scope field"):
        mc.McScope.from_dict(dict(TINY, bogus=1))
    with pytest.raises(mc.ScopeError, match="missing field"):
        mc.McScope.from_dict({"n_nodes": 3})
    with pytest.raises(mc.ScopeError, match="unknown episode kind"):
        mc.McScope.from_dict(dict(TINY, kinds=["pause", "meteor"]))
    with pytest.raises(mc.ScopeError, match="crash_rounds"):
        mc.McScope.from_dict(dict(TINY, crash_rounds=[]))
    with pytest.raises(mc.ScopeError, match="interval"):
        mc.McScope.from_dict(dict(TINY, intervals=[[8, 2]]))
    with pytest.raises(mc.ScopeError, match="knob tier"):
        mc.McScope.from_dict(
            dict(TINY, knob_tiers=[{"drop_rate": 99999}])
        )
    with pytest.raises(mc.ScopeError, match="schedule"):
        mc.McScope.from_dict(
            dict(TINY, knob_tiers=[{"schedule": None}])
        )


def test_mc_cli_exits_2_on_scope_errors(tmp_path):
    assert mc.main(["--scope-file", str(tmp_path / "nope.json")]) == 2
    bad = tmp_path / "scopes.json"
    bad.write_text("{}")
    assert mc.main(["--scope-file", str(bad)]) == 2
    bad.write_text(json.dumps({"quick": dict(TINY, kinds=["meteor"])}))
    assert mc.main(["--scope-file", str(bad), "--scope", "quick"]) == 2


# ---------------- crash points (faults layer) ----------------

def test_crash_episode_tables_and_compiled_rows():
    e = flt.crash(4, 1)
    assert (e.t0, e.t1, e.nodes) == (4, 5, (1,))
    cut, paused, extra, cmask, _gray = flt.episode_tables(e, 3)
    assert not cut.any() and not paused.any() and extra == 0
    assert cmask.tolist() == [False, True, False]
    with pytest.raises(ValueError, match="t0 \\+ 1"):
        flt.Episode("crash", 2, 9, nodes=(1,))
    with pytest.raises(ValueError, match="at least one node"):
        flt.Episode("crash", 2, 3)
    # compiled rows are CUMULATIVE: crashed from t0 through row h
    sched = flt.FaultSchedule((flt.pause(2, 8, 0), flt.crash(4, 1)))
    comp = flt.compile_schedule(sched, 3)
    assert comp.has_crash and comp.horizon == 8
    assert not comp.crashed[:4].any()
    assert comp.crashed[4:, 1].all()  # incl. row h: never un-crashes
    assert not comp.crashed[:, [0, 2]].any()


def test_crashes_at_matches_compiled_rows():
    import jax.numpy as jnp  # noqa: F401  (device mask computation)

    from tpu_paxos.fleet import schedule_table as stm

    sched = flt.FaultSchedule((
        flt.crash(3, 2), flt.pause(1, 6, 0), flt.crash(7, 0),
    ))
    comp = flt.compile_schedule(sched, 4)
    tab = stm.encode_schedule(sched, 4, max_episodes=4)
    for t in range(comp.horizon + 3):
        want = comp.crashed[min(t, comp.horizon)]
        got = np.asarray(stm.crashes_at(tab, t))
        assert (got == want).all(), t
        # the existing three masks stay untouched by crash letters
        reach, paused, extra, _gray = stm.masks_at(tab, t)
        assert np.asarray(reach).all()


def test_membership_engine_accepts_crash_episodes():
    """PR 8 made the membership engine REJECT crash episodes (its
    round body never read the crash rows); the device-resident
    rework wired them in, so acceptance — with the actual fail-stop —
    is now the contract.  Node 0 stays the one rejection: it is the
    harness driver (the host ``crash()`` injector's rule)."""
    from tpu_paxos.membership import engine as mem

    ms = mem.MemberSim(
        3, n_instances=64,
        schedule=flt.FaultSchedule((flt.crash(2, 1),)),
    )
    ms.propose(0, 9)
    assert ms.run_until(lambda: ms.chosen(9), max_rounds=200)
    ms.run_rounds(4)
    assert 1 in ms.crashed_set()
    with pytest.raises(ValueError, match="node 0"):
        mem.MemberSim(
            3, n_instances=64,
            schedule=flt.FaultSchedule((flt.crash(2, 0),)),
        )


# ---------------- dispatch + certificate (tiny scope) ----------------

@pytest.fixture(scope="module")
def tiny_run():
    scope = mc.McScope.from_dict(TINY)
    summary = mc.run_scope(scope, verbose=False)
    return scope, summary


def test_tiny_scope_runs_clean_with_zero_warm_compiles(tiny_run):
    scope, s = tiny_run
    enum = mc.ScopeEnum(scope)
    assert s["ok"] and not s["counterexamples"] and not s["anomalies"]
    assert s["scenarios_reduced"] == len(enum.reduced)
    assert len(s["verdict_bits"]) == len(enum.reduced)
    assert s["verdict_bits"] == "f" * len(enum.reduced)
    # THE envelope contract: zero XLA compiles after the first chunk
    assert s["compiles_per_chunk"][0] > 0
    assert all(c == 0 for c in s["compiles_per_chunk"][1:])


def test_certificate_roundtrip_and_drift_naming(tiny_run, tmp_path):
    scope, s = tiny_run
    enum = mc.ScopeEnum(scope)
    cert = mc.make_certificate(s)
    path = str(tmp_path / "cert.json")
    mc.save_certificate(path, "tiny", cert)
    pinned = mc.load_certificates(path)["tiny"]
    assert mc.check_certificate(pinned, s, enum) == []
    # a verdict drift names the FIRST diverging scenario's full index
    drifted = dict(s)
    bits = list(s["verdict_bits"])
    bits[3] = "7"  # ok bit cleared at reduced position 3
    drifted["verdict_bits"] = "".join(bits)
    fails = mc.check_certificate(pinned, drifted, enum)
    assert len(fails) == 1
    assert f"scenario index {enum.reduced[3]}" in fails[0]
    # a scope edit names the drifted field, not a scenario
    fails = mc.check_certificate(
        dict(pinned, scope_sha256="0" * 64), s, enum
    )
    assert "scope_sha256" in fails[0]
    # verdict pins are backend-gated like the flops/HLO pins
    assert mc.check_certificate(
        dict(pinned, backend="tpu",
             verdict_bits="0" * len(s["verdict_bits"])),
        s, enum,
    ) == []
    # chunk-limited runs are never certifiable
    with pytest.raises(ValueError, match="chunk-limited"):
        mc.make_certificate(dict(s, chunks_run=s["chunks"] - 1))


def test_scope_episode_ceiling_matches_fleet_envelope():
    """MAX_SCOPE_EPISODES is hardcoded (the scope layer stays
    jax-free) but must track the fleet's default episode capacity —
    it is what lets the mc sweep and the shrinker's candidate
    evaluators share one compiled executable."""
    from tpu_paxos.fleet import runner as frun

    assert mc.MAX_SCOPE_EPISODES == frun.MAX_EPISODES
    with pytest.raises(mc.ScopeError, match="max_episodes"):
        mc.McScope.from_dict(
            dict(TINY, max_episodes=mc.MAX_SCOPE_EPISODES + 1)
        )


# ---------------- gray axis ----------------

def test_gray_delay_ceiling_matches_fleet_envelope():
    """MAX_GRAY_DELAY is hardcoded (the scope layer stays jax-free)
    but must track the fleet envelope's delay-ring bound — the clamp
    is what makes the delay-tier axis finite."""
    from tpu_paxos.fleet import envelope

    assert mc.MAX_GRAY_DELAY == envelope.MAX_DELAY_BOUND
    gray = dict(
        TINY, kinds=["gray"], gray_set_sizes=[1], gray_delays=[2],
        knob_tiers=[{"drop_rate": 0, "max_delay": 4}],
    )
    mc.McScope.from_dict(gray).validate()  # baseline accepted
    with pytest.raises(mc.ScopeError, match=r"\[1, 8\]"):
        mc.McScope.from_dict(dict(gray, gray_delays=[9])).validate()
    with pytest.raises(mc.ScopeError, match="distinct"):
        mc.McScope.from_dict(dict(gray, gray_delays=[2, 2])).validate()
    # the fleet's named zero-max_delay rejection, moved to parse time
    with pytest.raises(mc.ScopeError, match="max_delay >= 1"):
        mc.McScope.from_dict(
            dict(gray, knob_tiers=[{"drop_rate": 0}])
        ).validate()


def test_gray_letters_materialize_at_tier_boundaries():
    """The committed gray scope's letters carry exactly the declared
    delay tiers, and rank/unrank is the identity at the first and
    last index of every per-combo block touching a gray letter."""
    scope = _committed_scopes()["gray"]
    enum = mc.enum_for(scope)
    letters = mc.episode_alphabet(scope)
    grays = [ep for ep in letters if ep.kind == "gray"]
    assert grays, "committed gray scope must produce gray letters"
    assert {ep.delay for ep in grays} == set(scope.gray_delays)
    per_combo = enum.n_tiers * enum.n_gates * enum.n_seeds
    for cr in range(enum.n_combos):
        for i in (cr * per_combo, (cr + 1) * per_combo - 1):
            sc = enum.decode(i)
            assert enum.encode(sc) == i
            sched = enum.schedule_of(sc)
            if sched is not None:
                for ep in sched.episodes:
                    if ep.kind == "gray":
                        assert ep.delay in scope.gray_delays


def test_gray_broken_symmetry_one_canonical_per_orbit():
    """Gray letters break node symmetry the same way crash letters do
    — the reduction must still keep exactly one spelling per
    permutation orbit over the gray scope's alphabet."""
    scope = _committed_scopes()["gray"]
    enum = mc.enum_for(scope)
    assert enum._perms, "gray scope should have movable nodes"
    orbits = {}
    for cr in range(enum.n_combos):
        combo = mc.combo_unrank(cr, enum.m, scope.max_episodes)
        canon = enum.canon_combo(combo)
        assert enum.canon_combo(canon) == canon
        orbits.setdefault(canon, set()).add(combo)
    for canon, members in orbits.items():
        assert sum(
            1 for c in members if enum.canon_combo(c) == c
        ) == 1, (canon, members)


# ---------------- committed certificates ----------------

def test_committed_certificates_pin_all_scopes_and_counts():
    """Every committed scope has a pinned certificate whose shape
    fields match the LIVE enumeration — scenario counts are pinned
    numbers, not run output.  A scope edit that changes the universe
    fails here without touching a device."""
    certs = mc.load_certificates()
    expect_counts = {
        "quick": (2116, 928),
        "full": (25674, 7242),
        "gray": (121, 52),
        "churn": (441, 302),
        "control": (8882, 8882),
    }
    for name, scope in _committed_scopes().items():
        enum = mc.enum_for(scope)
        cert = certs[name]
        assert cert["scope_sha256"] == scope.sha256(), name
        assert cert["scenarios_full"] == enum.total == \
            expect_counts[name][0], name
        assert cert["scenarios_reduced"] == len(enum.reduced) == \
            expect_counts[name][1], name
        assert cert["counterexamples"] == 0, name
        assert len(cert["verdict_bits"]) == len(enum.reduced), name


def test_mc_artifacts_live_in_the_triage_namespace():
    assert "mc_" in triage.DUMP_PREFIXES
    assert (
        triage.dump_name("mc", "scenario_42", "json")
        == "mc_scenario_42.json"
    )


# ---------------- seeded-wedge recall (slow) ----------------

@pytest.mark.slow
def test_seeded_wedge_found_shrunk_and_replayed(tmp_path, monkeypatch):
    """THE recall pin: with the PR-1 pause-crash commit-TAKEOVER
    wedge re-introduced (TPU_PAXOS_SEEDED_WEDGE=takeover), the quick
    scope's exhaustive enumeration finds a counterexample, shrinks it
    through the batched triage stack into an ``mc_scenario_<index>``
    artifact, and the artifact replays byte-identically
    (decision-log sha256) — and the pinned quick certificate reports
    the drift by scenario index."""
    from tpu_paxos.harness import shrink as shr

    monkeypatch.setenv("TPU_PAXOS_SEEDED_WEDGE", "takeover")
    scopes = _committed_scopes()
    scope = scopes["quick"]
    enum = mc.ScopeEnum(scope)
    s = mc.run_scope(
        scope, verbose=False, triage_dir=str(tmp_path),
        max_counterexamples=1,
    )
    assert not s["ok"] and s["counterexamples"]
    assert s["seeded_wedge"] == "takeover"
    cx = s["counterexamples"][0]
    idx = cx["scenario"]["index"]
    # the wedge shape: a deterministic crash point is in the scenario
    kinds = {e["kind"] for e in cx["scenario"]["episodes"]}
    assert "crash" in kinds
    # found exhaustively -> named by its stable full-codec index, and
    # the artifact carries the deterministic mc_ name
    art_path = cx["artifact"]
    assert os.path.basename(art_path) == f"mc_scenario_{idx}.json"
    assert cx.get("triage_error") is None
    # byte-identical replay (decision-log sha256), wedge still armed
    rep = shr.reproduce(art_path)
    assert rep["match"], rep
    # the shrunk schedule kept a crash episode (the culprit axis)
    case, art = shr.load_artifact(art_path)
    sched = case.cfg.faults.schedule
    assert sched is not None and any(
        e.kind == "crash" for e in sched.episodes
    )
    # certificate drift: the pinned quick cert (pinned green) must
    # fail against this run, naming a scenario index
    pinned = mc.load_certificates().get("quick")
    assert pinned is not None, "quick certificate must be committed"
    fails = mc.check_certificate(
        dict(pinned, verdict_bits=pinned["verdict_bits"][
            : len(s["verdict_bits"])
        ]),
        s, enum,
    )
    assert fails and "scenario index" in fails[0]


# ---------------- batched shrinker parity (slow) ----------------

@pytest.mark.slow
def test_batched_shrink_parity_with_sequential(monkeypatch):
    """The PR-5 follow-on's contract: the batched candidate evaluator
    is verdict-for-verdict identical to the sequential one, and the
    whole greedy descent lands on the SAME shrunk case either way."""
    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.harness import shrink as shr

    sched = flt.FaultSchedule((
        flt.partition(5, 35, (0, 1), (2, 3, 4)),
        flt.pause(10, 20, 3),
    ))
    cfg = SimConfig(
        n_nodes=5, n_instances=64, proposers=(0, 1), seed=7,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=300, dup_rate=500, max_delay=2,
                           schedule=sched),
    )
    wl = [np.arange(100, 110, dtype=np.int32),
          np.arange(200, 210, dtype=np.int32)]
    case = shr.ReproCase(
        cfg=cfg, workload=wl, gates=None,
        chains=[np.zeros(0, np.int32)] * 2,
        extra_checks={"decision_round_max": 25},
    )
    # evaluator-level parity: one dispatch == N sequential verdicts
    ev = shr._runtime_candidate_eval(case)
    batch = shr._runtime_batch_eval(case)
    assert ev is not None and batch is not None
    cands = [
        case.with_schedule(sched.without(0)),
        case.with_schedule(sched.without(1)),
        case.with_faults(dataclasses.replace(cfg.faults, drop_rate=0)),
        dataclasses.replace(
            case, cfg=dataclasses.replace(cfg, seed=0)
        ),
    ]
    assert batch(cands) == [ev(c) for c in cands]
    # descent-level parity: identical shrunk case and violation
    small_b, viol_b = shr.shrink_case(case, batch=True)
    small_s, viol_s = shr.shrink_case(case, batch=False)
    assert viol_b == viol_s
    assert small_b.cfg == small_s.cfg
    assert [w.tolist() for w in small_b.workload] == [
        w.tolist() for w in small_s.workload
    ]
    # sharded cases cannot ride the runtime engine in either shape
    assert shr._runtime_batch_eval(
        dataclasses.replace(case, engine="sharded", devices=2)
    ) is None


# ---------------- full-scope certificate smoke (slow) ----------------

@pytest.mark.slow
def test_full_scope_counts_and_verdict_prefix_match_certificate():
    """``make mc`` stays out of tier-1; this smoke pins that the full
    scope's enumeration matches its committed certificate exactly and
    that the first chunks' verdict bits reproduce the pinned prefix
    (same backend)."""
    import jax

    scope = _committed_scopes()["full"]
    enum = mc.ScopeEnum(scope)
    pinned = mc.load_certificates().get("full")
    assert pinned is not None, "full certificate must be committed"
    for f in mc._CERT_SHAPE_FIELDS:
        if f == "scope_sha256":
            assert pinned[f] == scope.sha256()
    assert pinned["scenarios_full"] == enum.total
    assert pinned["scenarios_reduced"] == len(enum.reduced)
    assert pinned["counterexamples"] == 0
    s = mc.run_scope(scope, verbose=False, chunk_limit=2)
    assert s["ok"]
    fails = mc.check_certificate(
        dict(pinned, verdict_bits=pinned["verdict_bits"][
            : len(s["verdict_bits"])
        ]),
        s, enum,
    )
    if jax.default_backend() == pinned["backend"]:
        assert fails == [], fails
