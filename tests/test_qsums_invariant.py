"""Event-coverage invariant for the cached quiescence counts
(ADVICE round 5, core/sim.py q_change).

Correctness of the quiet-round skip rests on a hand-enumerated event
list covering every mutation of the counted arrays
(chosen/learned/cur_batch/own_assign/head/tail).  This test pins the
invariant at runtime: step the engine round by round and recompute
the counts unconditionally from the post-round state — the cached
``qsums``/``qhmax`` must match EVERY round, not just on measured
ones.  A future edit that writes a counted array outside the listed
conds shows up here as a drift on the first quiet round after it."""

import jax
import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as flt
from tpu_paxos.core import sim as simm
from tpu_paxos.utils import prng

NONE = -1


def _expected_counts(st, n_instances):
    chosen = np.asarray(st.met.chosen_vid)
    learned = np.asarray(st.learned)  # [A, I]
    cur_batch = np.asarray(st.prop.cur_batch)
    own = np.asarray(st.prop.own_assign)
    head = np.asarray(st.prop.head)
    tail = np.asarray(st.prop.tail)
    inflight = (cur_batch != NONE) & (chosen[None] == NONE)
    sums = np.concatenate([
        [np.sum(chosen != NONE)],
        (learned != NONE).sum(axis=1),
        inflight.sum(axis=1),
        (head != tail).astype(np.int64),
        (own != NONE).sum(axis=1),
    ]).astype(np.int32)
    idx = np.arange(n_instances)
    hmax = int(np.where(chosen != NONE, idx, -1).max())
    return sums, hmax


def _check_run(cfg, max_rounds=600):
    pend, gate, tail, c = simm.prepare_queues(cfg, simm.default_workload(cfg))
    root = prng.root_key(cfg.seed)
    st = simm.init_state(cfg, pend, gate, tail, root)
    round_fn = jax.jit(simm.build_engine(cfg, c, vid_cap=0))
    rounds = 0
    while not bool(st.done) and rounds < min(cfg.round_budget, max_rounds):
        st = round_fn(root, st)
        rounds += 1
        sums, hmax = _expected_counts(st, cfg.n_instances)
        # paxlint: allow[JAX103] recompute-and-compare every round is the invariant
        got = np.asarray(st.qsums)
        assert np.array_equal(got, sums), (
            f"round {rounds}: cached qsums {got.tolist()} != "
            f"recomputed {sums.tolist()}"
        )
        assert int(st.qhmax) == hmax, (
            f"round {rounds}: cached qhmax {int(st.qhmax)} != {hmax}"
        )
    assert bool(st.done), f"no quiescence in {rounds} rounds"


def test_qsums_match_under_iid_faults():
    """debug.conf-rate faults, no crashes: the cache path (not the
    every-round crash fallback) must stay exactly current."""
    cfg = SimConfig(
        n_nodes=5, n_instances=48, proposers=(0, 1), seed=11,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    _check_run(cfg)


# Slow tier (time budget): the i.i.d. cell keeps the invariant
# fast-tier, and the slow multi-seed sweep below covers episode mixes.
@pytest.mark.slow
def test_qsums_match_under_episode_schedule():
    """Same assertion through a partition + pause + burst schedule:
    episode masking must not open an un-enumerated mutation path."""
    sched = flt.FaultSchedule((
        flt.partition(4, 20, (0, 1), (2, 3, 4)),
        flt.pause(24, 40, 2),
        flt.burst(8, 16, 2500),
    ))
    cfg = SimConfig(
        n_nodes=5, n_instances=48, proposers=(0, 1), seed=3,
        faults=FaultConfig(drop_rate=300, dup_rate=500, max_delay=2,
                           schedule=sched),
    )
    _check_run(cfg)


@pytest.mark.slow
def test_qsums_match_multi_seed_faulty():
    """Multi-seed sweep of the invariant, i.i.d. and episode mixes."""
    sched = flt.FaultSchedule((
        flt.partition(6, 26, (0, 2), (1, 3, 4)),
        flt.pause(30, 46, 1),
    ))
    for seed in range(4):
        for schedule in (None, sched):
            cfg = SimConfig(
                n_nodes=5, n_instances=48, proposers=(0, 1), seed=seed,
                faults=FaultConfig(
                    drop_rate=700, dup_rate=1000, max_delay=3,
                    schedule=schedule,
                ),
            )
            _check_run(cfg, max_rounds=1500)
