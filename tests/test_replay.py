"""Replay-diff: two same-seed runs must produce byte-identical
decision logs, including under fault injection — the framework's
equivalent of the reference's record/replay diff test
(ref member/run.sh:1-18, member/diff.sh:1-3: byte-identical stdout is
the pass criterion)."""

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim
from tpu_paxos.core import values as val
from tpu_paxos.replay import decision_log

STRIDE = 1024


def _log(cfg: SimConfig) -> bytes:
    r = sim.run(cfg)
    assert r.done
    return decision_log(
        r.chosen_vid, r.chosen_ballot, STRIDE, cfg.n_instances
    ).encode()


def test_replay_diff_fault_free():
    cfg = SimConfig(n_nodes=3, n_instances=16, proposers=(0,), seed=11)
    assert _log(cfg) == _log(cfg)


def test_replay_diff_under_faults():
    cfg = SimConfig(
        n_nodes=5,
        n_instances=32,
        proposers=(0, 1),
        seed=12,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=3),
    )
    a, b = _log(cfg), _log(cfg)
    assert a == b
    assert len(a) > 0


def test_log_grammar():
    """Lines follow the reference grammar: [i] = <ballot>(p:vid)±..."""
    cfg = SimConfig(n_nodes=3, n_instances=8, proposers=(0,), seed=0)
    r = sim.run(cfg)
    text = decision_log(r.chosen_vid, r.chosen_ballot, STRIDE, cfg.n_instances)
    lines = text.strip().splitlines()
    assert lines, "log is empty"
    import re

    pat = re.compile(r"^\[\d+\] = <\d+>\(\d+:\d+\)[+-]")
    for line in lines:
        assert pat.match(line), line


def test_log_renders_membership_changes():
    """Membership-change vids render with the m+/m- grammar
    (ref multi/paxos.cpp:20-22)."""
    from tpu_paxos.membership import (
        ADD_ACCEPTOR,
        DEL_ACCEPTOR,
        change_vid,
        membership_suffix,
    )

    chosen = np.asarray(
        [change_vid(1, ADD_ACCEPTOR), 7, change_vid(1, DEL_ACCEPTOR)], np.int32
    )
    ballots = np.asarray([65536, 65536, 65537], np.int32)
    text = decision_log(
        chosen, ballots, STRIDE, 3, membership=membership_suffix
    )
    lines = text.splitlines()
    assert lines[0].endswith("m+1=node:1")
    assert lines[1].endswith(")+7")
    assert lines[2].endswith("m-1")


def test_log_renders_noops():
    """A run with adoption-forced holes must render '-' no-op lines."""
    from tpu_paxos.core import ballot as bal
    from tpu_paxos.utils import prng

    cfg = SimConfig(n_nodes=3, n_instances=8, proposers=(0,), seed=0)
    workload = [np.asarray([50], np.int32)]
    pend, gate, tail, c = sim.prepare_queues(cfg, workload)
    root = prng.root_key(cfg.seed)
    st = sim.init_state(cfg, pend, gate, tail, root)
    st = st._replace(
        acc=st.acc._replace(
            acc_ballot=st.acc.acc_ballot.at[0, 2].set(int(bal.make(1, 2))),
            acc_vid=st.acc.acc_vid.at[0, 2].set(999),  # [acceptor, inst]
        )
    )
    r = sim.run_state(cfg, st, root, np.asarray([50, 999]), c)
    assert r.done
    text = decision_log(r.chosen_vid, r.chosen_ballot, STRIDE, cfg.n_instances)
    noop_lines = [ln for ln in text.splitlines() if ln.endswith(")-")]
    assert len(noop_lines) == 2  # instances 0 and 1 were hole-filled
