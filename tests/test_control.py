"""Adaptive serving — the cause-aware admission controller
(tpu_paxos/serve/control.py).

The load-bearing contracts, in order:

- INERT PARITY: a controlled run with ``control=None`` (all-True keep
  masks, fixed granularity, no decisions) is decision-log
  sha256-IDENTICAL to ``harness.serve_run`` on the same plan — the
  controller's machinery may not perturb the protocol when it is not
  acting.  This is the controller-off == pre-controller pin.
- CAUSE-AWARE POLICY: the stable integer cause codes
  (telemetry/diagnose.CAUSE_IDS) are pinned exactly, and ``decide``
  obeys the policy table on seeded cause schedules — shed on
  saturation, NEVER shed on a gray-region-attributed window (the veto
  holds even when saturation fired beside it), hold steady through
  duel-churn and partition, restore after ``patience`` calm
  dispatches.
- ADMISSION LEDGER: ``ControlledPlan`` admits every value exactly
  once, charges deferred values their TRUE queue-wait (original
  arrival stamps), preserves FIFO within a tier, and with no floors
  reproduces ``ArrivalPlan.block`` exactly.
- REPLAY: a controlled run's artifact (policy + decision trail,
  schema-closed) replays decision-log sha256-identically.

Engine-bearing fast cells share ONE controlled-window compile (the
module geometry below mirrors tests/test_serve.py) plus one serve and
one fleet twin for the parity pins.  The heavy spike A/B (the
BENCH_serve_control.json shape: 1000 values on a 2048-instance
admission-capped engine, two full runs) is marked slow — its fast-tier
coverage is the decide() policy pins + the ControlledPlan shed/defer
mechanics + the inert-parity and determinism cells below.
"""

import copy
import hashlib
import json

import numpy as np
import pytest

from tpu_paxos.analysis.artifact_schema import (
    ArtifactSchemaError,
    validate_artifact,
)
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.replay.decision_log import decision_log
from tpu_paxos.serve import arrivals as arrv
from tpu_paxos.serve import control as ctl
from tpu_paxos.serve import fleet as sfl
from tpu_paxos.serve import harness as sh
from tpu_paxos.telemetry import diagnose as dg

# ---- module geometry: one controlled-window compile for every
# engine-bearing fast cell (mirrors tests/test_serve.py)
WL = [np.arange(0, 10, dtype=np.int32), np.arange(20, 30, dtype=np.int32)]
R_WINDOW = 8
S_DISPATCH = 2
ADMIT_W = 10
W_ROUNDS = 32

SAT = dg.CAUSE_IDS["saturation"]
GRAY = dg.CAUSE_IDS["gray-region"]
DUEL = dg.CAUSE_IDS["duel-churn"]
PART = dg.CAUSE_IDS["partition"]


def _cfg(seed=3):
    return SimConfig(
        n_nodes=3, n_instances=48, proposers=(0, 1), seed=seed,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )


def _arrs(seed=7, rate=4000):
    rounds = arrv.poisson_rounds(20, rate, seed)
    return [np.sort(rounds[0::2]), np.sort(rounds[1::2])]


def _sha(cv, cb):
    return hashlib.sha256(
        decision_log(cv, cb, stride=30, n_instances=len(cv)).encode()
    ).hexdigest()


# ---------------- stable cause codes --------------------------------


def test_cause_ids_pinned_exactly():
    # the policy table, the artifact schema, and the decision log all
    # key on these integers — renumbering breaks committed artifacts
    assert dg.CAUSE_IDS == {
        "unknown": 0,
        "duel-churn": 1,
        "gray-region": 2,
        "partition": 3,
        "saturation": 4,
    }
    assert dg.CAUSE_NAMES[4] == "saturation"
    # paxlint: allow[CTL001] this test pins the wire encoding itself
    assert dg.cause_code("gray-region") == 2
    # paxlint: allow[CTL001] this test pins the wire encoding itself
    assert dg.cause_code("never-heard-of-it") == 0


# ---------------- policy declaration --------------------------------


def test_policy_defaults_and_table():
    p = ctl.ControlPolicy()
    t = dict(p.table)
    assert t[SAT] == "shed"
    assert t[GRAY] == "never"
    assert t[DUEL] == "hold"
    assert t[PART] == "hold"


def test_policy_validation_rejects_bad_shapes():
    with pytest.raises(ValueError):
        ctl.ControlPolicy(n_tiers=2, defer_tier=2, shed_tier=1)
    with pytest.raises(ValueError):
        ctl.ControlPolicy(ladder=(4, 2))  # must ascend
    with pytest.raises(ValueError):
        ctl.ControlPolicy(table=((SAT, "explode"),))
    with pytest.raises(ValueError):
        ctl.ControlPolicy(table=((SAT, "shed"), (SAT, "hold")))


def test_policy_dict_roundtrip_exact():
    p = ctl.ControlPolicy(
        n_tiers=4, defer_tier=2, shed_tier=3, burn_low_milli=250,
        patience=3, ladder=(1, 2, 4),
    )
    assert ctl.policy_from_dict(ctl.policy_to_dict(p)) == p


# ---------------- decide(): the cause-aware policy table ------------
# Seeded cause schedules: each test drives decide() with an explicit
# (window, cause-codes) trail — the deterministic distillation of what
# diagnose_breaches names on a seeded run.


def test_decide_sheds_on_saturation():
    p = ctl.ControlPolicy()
    st = ctl.ControllerState(level=p.top_level)
    rec = ctl.decide(p, st, dispatch=3, burn_milli=2000,
                     new_windows=[(5, (SAT,))])
    assert rec["action"] == "degrade"
    assert rec["windows"] == [5]
    assert rec["cause_ids"] == [SAT]
    assert st.degraded


def test_decide_never_sheds_on_gray_region():
    p = ctl.ControlPolicy()
    st = ctl.ControllerState(level=p.top_level)
    rec = ctl.decide(p, st, dispatch=3, burn_milli=2000,
                     new_windows=[(5, (GRAY,))])
    assert rec["action"] == "hold"
    assert not st.degraded


def test_decide_gray_vetoes_saturation_in_same_window():
    # the veto is per WINDOW: gray beside saturation still blocks the
    # shed — ambiguous evidence must not trigger load shedding
    p = ctl.ControlPolicy()
    st = ctl.ControllerState(level=p.top_level)
    rec = ctl.decide(p, st, dispatch=3, burn_milli=2000,
                     new_windows=[(5, (SAT, GRAY))])
    assert rec["action"] == "hold"
    assert not st.degraded


def test_decide_holds_through_duel_churn_and_partition():
    p = ctl.ControlPolicy()
    for code in (DUEL, PART):
        st = ctl.ControllerState(level=p.top_level)
        rec = ctl.decide(p, st, dispatch=2, burn_milli=2000,
                         new_windows=[(1, (code,))])
        assert rec["action"] == "hold"
        assert not st.degraded
        assert st.calm == 0


def test_decide_restore_after_patience_calm_dispatches():
    p = ctl.ControlPolicy(patience=2)
    st = ctl.ControllerState(level=p.top_level)
    ctl.decide(p, st, dispatch=1, burn_milli=2000,
               new_windows=[(0, (SAT,))])
    assert st.degraded
    assert ctl.decide(p, st, dispatch=2, burn_milli=0,
                      new_windows=[]) is None
    rec = ctl.decide(p, st, dispatch=3, burn_milli=0, new_windows=[])
    assert rec["action"] == "restore"
    assert not st.degraded
    # a hot dispatch resets the calm counter
    st2 = ctl.ControllerState(level=p.top_level)
    ctl.decide(p, st2, dispatch=1, burn_milli=2000,
               new_windows=[(0, (SAT,))])
    ctl.decide(p, st2, dispatch=2, burn_milli=0, new_windows=[])
    ctl.decide(p, st2, dispatch=3, burn_milli=9000, new_windows=[])
    assert st2.calm == 0 and st2.degraded


def test_decide_ladder_steps_down_then_back_up():
    p = ctl.ControlPolicy(ladder=(1, 2, 4), patience=1)
    st = ctl.ControllerState(level=p.top_level)
    assert st.level == 2
    ctl.decide(p, st, dispatch=1, burn_milli=2000,
               new_windows=[(0, (SAT,))])
    assert st.level == 1
    ctl.decide(p, st, dispatch=2, burn_milli=2000,
               new_windows=[(1, (SAT,))])
    assert st.level == 0  # floor: never below ladder[0]
    ctl.decide(p, st, dispatch=3, burn_milli=2000,
               new_windows=[(2, (SAT,))])
    assert st.level == 0
    for d in (4, 5, 6):
        ctl.decide(p, st, dispatch=d, burn_milli=0, new_windows=[])
    assert st.level == p.top_level and not st.degraded


# ---------------- ControlledPlan: the admission queue ---------------


def _plan(prios=None, rate=500, n=12):
    vids = np.arange(n, dtype=np.int32)
    if rate:
        rounds = arrv.poisson_rounds(n, rate, 5)
    else:
        rounds = arrv.immediate_rounds(n)  # offered-load-∞ limit
    streams, arrs = arrv.split_round_robin(vids, rounds, 2)
    if prios is None:
        pr = None
    else:
        pr = [np.asarray([prios[int(v)] for v in s], np.int32)
              for s in streams]
    return streams, arrs, ctl.ControlledPlan(streams, arrs, pr, R_WINDOW)


def test_controlled_plan_inert_matches_arrival_plan_block():
    streams, arrs, cp = _plan()
    ap = arrv.ArrivalPlan(streams, arrs, R_WINDOW)
    for j in range(ap.n_windows):
        admit, arr = ap.block(j, ADMIT_W)
        a2, r2, keep = cp.take(j, ADMIT_W)
        np.testing.assert_array_equal(admit, a2)
        np.testing.assert_array_equal(arr, r2)
        assert keep[a2 != arrv.NONE].all()
        assert not keep[a2 == arrv.NONE].any()
    assert cp.exhausted and cp.shed_count == 0


def test_controlled_plan_window_order_enforced():
    _, _, cp = _plan()
    cp.take(0, ADMIT_W)
    with pytest.raises(ValueError):
        cp.take(2, ADMIT_W)


def test_controlled_plan_shed_floor_sheds_declared_tier_once():
    prios = {v: (2 if v % 3 == 2 else 0) for v in range(12)}
    streams, _, cp = _plan(prios)
    admitted, shed = [], []
    j = 0
    while not cp.exhausted:
        admit, _, keep = cp.take(j, ADMIT_W, shed_floor=2)
        admitted += [int(v) for v in admit[keep]]
        j += 1
    shed = [r["vid"] for r in cp.shed_records]
    assert sorted(admitted + shed) == list(range(12))  # exactly once
    assert set(shed) == {v for v, t in prios.items() if t == 2}
    assert cp.shed_count == len(shed)
    assert all(r["tier"] == 2 for r in cp.shed_records)


def test_controlled_plan_defer_charges_true_arrival():
    # deferred values keep their ORIGINAL arrival stamps, so a later
    # admission charges the full queue-wait — deferral cannot launder
    # latency
    prios = {v: (1 if v < 4 else 0) for v in range(12)}
    streams, arrs, cp = _plan(prios)
    orig = {}
    for s, a in zip(streams, arrs):
        for v, r in zip(s, a):
            orig[int(v)] = int(r)
    seen = {}
    j = 0
    while not cp.exhausted:
        floors = {"defer_floor": 1} if j == 0 else {}
        admit, arr, keep = cp.take(j, ADMIT_W, **floors)
        for v, r in zip(admit[keep], arr[keep]):
            seen[int(v)] = int(r)
        j += 1
    assert seen == orig  # every value admitted, true stamps intact
    assert cp.shed_count == 0


def test_controlled_plan_deferred_rejoin_ahead_fifo_within_tier():
    # window 0 defers tier-1; on release they lead the queue ahead of
    # later same-tier arrivals, in their original order
    prios = {v: 1 for v in range(12)}
    streams, _, cp = _plan(prios)
    a0, _, k0 = cp.take(0, ADMIT_W, defer_floor=1)
    assert not k0.any()  # everything in window 0 deferred
    order = {int(p): [] for p in range(2)}
    j = 1
    while not cp.exhausted:
        admit, _, keep = cp.take(j, ADMIT_W)
        for pi in range(2):
            order[pi] += [int(v) for v in admit[pi][keep[pi]]]
        j += 1
    for pi, s in enumerate(streams):
        assert order[pi] == [int(v) for v in s]  # FIFO preserved


def test_controlled_plan_width_spill_stays_queued():
    streams, _, cp = _plan(rate=0)  # everything arrives at round 0
    k = 3
    got = []
    j = 0
    while not cp.exhausted:
        admit, _, keep = cp.take(j, k)
        assert keep.sum() <= 2 * k
        got += [int(v) for v in admit[keep]]
        j += 1
    assert sorted(got) == list(range(12))


# ---------------- inert parity + determinism (engine) ---------------


def test_inert_controller_decision_log_sha_matches_serve_run():
    # controller-off == the PR-15 serving path, byte for byte
    cfg = _cfg()
    arrs = _arrs()
    base = sh.serve_run(
        cfg, WL, arrs, rounds_per_window=R_WINDOW,
        windows_per_dispatch=S_DISPATCH, admit_width=ADMIT_W,
        window_rounds=W_ROUNDS,
    )
    rep = ctl.controlled_serve_run(
        cfg, WL, arrs, control=None, rounds_per_window=R_WINDOW,
        windows_per_dispatch=S_DISPATCH, admit_width=ADMIT_W,
        window_rounds=W_ROUNDS,
    )
    assert rep.decisions == [] and rep.shed_count == 0
    assert _sha(rep.chosen_vid, rep.chosen_ballot) == _sha(
        base.chosen_vid, base.chosen_ballot
    )
    # the combined decision log == the protocol log when the control
    # trail is empty plus the (empty-trail) control section
    assert rep.decision_log_sha256 == hashlib.sha256(
        (decision_log(rep.chosen_vid, rep.chosen_ballot, stride=30,
                      n_instances=len(rep.chosen_vid))
         + ctl.control_log([])).encode()
    ).hexdigest()


def test_controlled_run_deterministic_and_artifact_replays(tmp_path):
    cfg = _cfg()
    arrs = _arrs()
    slo = sh.ServeSLO(latency_rounds=16, budget_milli=150)
    kw = dict(
        control=ctl.ControlPolicy(), slo=slo,
        rounds_per_window=R_WINDOW, windows_per_dispatch=S_DISPATCH,
        admit_width=ADMIT_W, window_rounds=W_ROUNDS,
    )
    a = ctl.controlled_serve_run(cfg, WL, arrs, **kw)
    b = ctl.controlled_serve_run(cfg, WL, arrs, **kw)
    assert a.decision_log_sha256 == b.decision_log_sha256
    assert a.decisions == b.decisions
    # artifact round trip: schema-validated save, byte-exact replay
    path = str(tmp_path / "ctl.json")
    art = ctl.save_artifact(path, a)
    validate_artifact(art)
    out = ctl.reproduce(path)
    assert out["match"] and out["decisions_match"]
    assert out["decision_log_sha256"] == a.decision_log_sha256


# ---------------- artifact schema: serve block ----------------------
# The committed spike artifact doubles as the canonical serve-engine
# artifact literal — keeping it schema-valid IS the compatibility pin.


def _serve_art():
    with open("artifacts/serve_control_spike.json") as f:
        return json.load(f)


def test_committed_spike_artifact_schema_valid():
    validate_artifact(_serve_art())


def test_serve_engine_requires_serve_block_and_vice_versa():
    art = _serve_art()
    a = copy.deepcopy(art)
    del a["serve"]
    with pytest.raises(ArtifactSchemaError):
        validate_artifact(a)
    b = copy.deepcopy(art)
    b["engine"] = "sim"
    with pytest.raises(ArtifactSchemaError):
        validate_artifact(b)


def test_serve_block_is_schema_closed():
    art = copy.deepcopy(_serve_art())
    art["serve"]["control"]["surprise"] = 1
    with pytest.raises(ArtifactSchemaError) as ei:
        validate_artifact(art)
    assert "surprise" in str(ei.value)
    art2 = copy.deepcopy(_serve_art())
    art2["serve"]["control"]["table"][0]["action"] = "explode"
    with pytest.raises(ArtifactSchemaError):
        validate_artifact(art2)


def test_serve_arrivals_rows_must_match_workload():
    art = copy.deepcopy(_serve_art())
    art["serve"]["arrivals"] = art["serve"]["arrivals"][:1]
    with pytest.raises(ArtifactSchemaError):
        validate_artifact(art)


# ---------------- fleet: controlled lanes + sweep verdict -----------


def test_controlled_fleet_inert_matches_serve_fleet():
    cfg = _cfg()
    arrs = _arrs()
    lanes = [sfl.ServeLane(WL, arrs, 0), sfl.ServeLane(WL, arrs, 1)]
    slo = sh.ServeSLO(latency_rounds=128, budget_milli=150)
    kw = dict(
        rounds_per_window=R_WINDOW, windows_per_dispatch=S_DISPATCH,
        admit_width=ADMIT_W, window_rounds=W_ROUNDS, slo=slo,
    )
    base = sfl.serve_fleet_run(cfg, lanes, **kw)
    rep = ctl.controlled_fleet_run(
        cfg, lanes, control=ctl.ControlPolicy(), **kw
    )
    assert isinstance(rep, ctl.ControlFleetReport)
    assert rep.shed_total == 0 and rep.lane_shed == [0, 0]
    assert rep.done and rep.backlog == 0
    for i in range(2):
        cv_b, cb_b = base.lane_chosen(i)
        cv_c, cb_c = rep.lane_chosen(i)
        assert _sha(cv_c, cb_c) == _sha(cv_b, cb_b)


def _verdict_summary(*, controlled, floor_shed=0, floor_slo_ok=True,
                     high_slo_ok=False, sustained=True):
    def pt(rate, shed, ok):
        p = {
            "rate_milli": rate, "sustained": sustained,
            "slo": {"0": {"ok": ok}},
        }
        if controlled:
            p["shed"] = shed
        return p

    s = {"cells": {"1": {"points": [
        pt(1000, floor_shed, floor_slo_ok),
        pt(8000, 5, high_slo_ok),
    ]}}}
    if controlled:
        s["control"] = ctl.policy_to_dict(ctl.ControlPolicy())
    return s


def test_sweep_verdict_floor_shed_cannot_exit_zero():
    # the satellite fix: a controller shedding its way to zero backlog
    # at the FLOOR rate is masking saturation — the sweep must red
    assert sfl.sweep_verdict(
        _verdict_summary(controlled=True, floor_shed=0)
    )
    assert not sfl.sweep_verdict(
        _verdict_summary(controlled=True, floor_shed=3)
    )
    assert not sfl.sweep_verdict(
        _verdict_summary(controlled=True, floor_slo_ok=False)
    )
    # controlled sweeps tolerate breaches at EXPLORATORY rates...
    assert sfl.sweep_verdict(
        _verdict_summary(controlled=True, high_slo_ok=False)
    )
    # ...uncontrolled sweeps keep the old any-breach-reds rule
    assert not sfl.sweep_verdict(
        _verdict_summary(controlled=False, high_slo_ok=False)
    )
    assert sfl.sweep_verdict(
        _verdict_summary(controlled=False, high_slo_ok=True)
    )
    assert not sfl.sweep_verdict(
        _verdict_summary(controlled=False, sustained=False)
    )
    assert not sfl.sweep_verdict({"cells": {}})


def test_fleet_policy_rejects_ladder_and_missing_slo():
    cfg = _cfg()
    lanes = [sfl.ServeLane(WL, _arrs(), 0)]
    with pytest.raises(ValueError):
        ctl.controlled_fleet_run(
            cfg, lanes, control=ctl.ControlPolicy(ladder=(1, 2)),
            slo=sh.ServeSLO(latency_rounds=16),
        )
    with pytest.raises(ValueError):
        ctl.controlled_fleet_run(
            cfg, lanes, control=ctl.ControlPolicy(), slo=None
        )


# ---------------- bench guard: the record-or-error gate -------------


def _ab(**over):
    ab = {
        "off": {"breach_windows": [5, 6, 7, 8]},
        "on": {"breach_windows": [5, 6, 7], "causes": ["saturation"]},
        "fewer_breach_windows": True,
        "breach_rounds_off": 128,
        "breach_rounds_on": 96,
        "gray_shed_violations": [],
        "sheds": 51,
        "decisions": 3,
        "policy": {}, "slo": {},
        "replay": {"match": True, "decision_log_sha256": "ab" * 32},
    }
    ab.update(over)
    return ab


def test_bench_serve_control_record_guards():
    import bench

    ok = bench._serve_control_record(_ab(), 0, {"devices": 1})
    assert "error" not in ok
    assert ok["value"] == {"off": 128, "on": 96}
    # each withhold condition is fatal and names its reason
    for bad, why in [
        (bench._serve_control_record(_ab(), 2, {}), "compile"),
        (bench._serve_control_record(
            _ab(off={"breach_windows": []}), 0, {}), "breached nowhere"),
        (bench._serve_control_record(
            _ab(gray_shed_violations=[6]), 0, {}), "gray"),
        (bench._serve_control_record(
            _ab(fewer_breach_windows=False), 0, {}), "strictly"),
        (bench._serve_control_record(_ab(sheds=0), 0, {}), "zero shed"),
        (bench._serve_control_record(
            _ab(replay={"match": False}), 0, {}), "replay"),
    ]:
        assert "error" in bad and why in bad["error"]


def test_committed_bench_record_is_a_passing_record():
    with open("BENCH_serve_control.json") as f:
        rec = json.load(f)
    assert rec["engine"] == "serve_control"
    assert "error" not in rec
    assert rec["value"]["on"] < rec["value"]["off"]
    assert rec["sheds"] > 0
    assert rec["gray_shed_violations"] == []
    assert rec["warm_compiles_measured"] == 0
    assert rec["replay"]["match"]


# ---------------- the spike A/B (slow: the bench shape) -------------


@pytest.mark.slow
def test_spike_ab_controller_wins_and_never_sheds_on_gray(tmp_path):
    """The BENCH_serve_control.json judgment, re-run end to end: two
    full 1000-value runs on the admission-capped 2048-instance engine
    (~minutes).  Fast-tier coverage of the same contracts:
    test_decide_* (the policy table on seeded cause schedules),
    test_controlled_plan_* (shed/defer ledger),
    test_inert_controller_decision_log_sha_matches_serve_run and
    test_controlled_run_deterministic_and_artifact_replays (parity +
    replay), test_committed_bench_record_is_a_passing_record (the
    committed outcome)."""
    cfg = SimConfig(
        n_nodes=3, n_instances=2048, proposers=(0, 1), seed=3,
        max_rounds=8000, assign_window=8,
    )
    slo = sh.ServeSLO(latency_rounds=16, budget_milli=150)
    out = ctl.spike_ab(
        cfg, 1000, 2000, slo=slo, seed=0,
        rounds_per_window=4, windows_per_dispatch=2,
        spike_factor=4, spike_start_frac=0.25, spike_len_frac=0.5,
        window_rounds=32,
        artifact_path=str(tmp_path / "spike.json"),
    )
    assert out["ok"], out
    off = out["off"]["breach_windows"]
    on = out["on"]["breach_windows"]
    assert off and len(on) < len(off)
    assert set(on) <= set(off)  # fewer AND no new breach windows
    assert out["sheds"] > 0
    assert out["gray_shed_violations"] == []
    assert "saturation" in out["on"]["causes"]
    assert out["replay"]["match"]
