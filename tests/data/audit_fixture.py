"""Seeded-violation fixtures for the jaxpr audit: one entry per IR
rule that the audit MUST flag, and a clean twin it must pass.  Loaded
as an audit provider via ``--providers tests/data/audit_fixture.py``
(tests/test_jaxpr_audit.py and the golden CLI report).

Each hot fixture hides its violation the way a real regression would:
the IR202 widening sits behind a helper function (invisible to the
AST lint — that is the whole point of the trace-time tier), the IR201
callback inside a scanned body, the IR203 collective behind
shard_map.
"""

import numpy as np

from tpu_paxos.analysis.registry import AuditEntry

#: 16 KiB table: over the hot entry's 1 KiB const budget, under the
#: clean twin's default 64 KiB.
_TABLE = np.arange(4096, dtype=np.int32)


def _widen(x):
    """The helper hiding an int64 widening (IR202's seeded leak)."""
    import jax.numpy as jnp

    return x.astype(jnp.int64)


def _scan(body_extra):
    import jax.numpy as jnp
    from jax import lax

    def fn(xs):
        def body(c, x):
            return c + body_extra(x), x

        c, _ = lax.scan(body, jnp.int32(0), xs)
        return c

    return fn, (jnp.arange(4, dtype=jnp.int32),)


def _build_ir201_hot():
    import jax
    import jax.numpy as jnp

    def host_echo(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct((), jnp.int32), x
        )

    return _scan(host_echo)


def _build_ir201_clean():
    return _scan(lambda x: x)


def _build_ir202_hot():
    import jax.numpy as jnp

    def fn(x):
        return _widen(x) + 1

    return fn, (jnp.arange(4, dtype=jnp.int32),)


def _build_ir202_clean():
    import jax.numpy as jnp

    def fn(x):
        return x.astype(jnp.int32) + 1

    return fn, (jnp.arange(4, dtype=jnp.int32),)


def _build_ir203():
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P  # paxlint: allow[SH001] IR203 fixture builds a raw collective on purpose

    from tpu_paxos.parallel import mesh as pmesh

    mesh = pmesh.make_instance_mesh(1)

    def body(x):
        return x + lax.psum(jnp.sum(x), pmesh.INSTANCE_AXIS)

    fn = pmesh.shard_map(
        body, mesh, in_specs=(P(pmesh.INSTANCE_AXIS),),
        out_specs=P(pmesh.INSTANCE_AXIS),
    )
    return fn, (jnp.arange(8, dtype=jnp.int32),)


def _build_ir204(stable: bool):
    def build():
        import jax.numpy as jnp
        from jax import lax

        def fn(x):
            return lax.sort(x, is_stable=stable)

        return fn, (jnp.arange(8, dtype=jnp.int32),)

    return build


def _build_ir205():
    import jax.numpy as jnp

    def fn(x):
        return x + jnp.asarray(_TABLE)

    return fn, (jnp.zeros((4096,), jnp.int32),)


def audit_entries():
    return [
        AuditEntry("fixture.ir201_hot", _build_ir201_hot, cost=False),
        AuditEntry("fixture.ir201_clean", _build_ir201_clean, cost=False),
        AuditEntry("fixture.ir202_hot", _build_ir202_hot, cost=False,
                   x64=True),
        AuditEntry("fixture.ir202_clean", _build_ir202_clean, cost=False),
        AuditEntry("fixture.ir203_hot", _build_ir203, cost=False,
                   covers=("_build_ir203",)),
        AuditEntry("fixture.ir203_clean", _build_ir203, cost=False,
                   mesh_axes=("i",)),
        AuditEntry("fixture.ir204_hot", _build_ir204(False), cost=False),
        AuditEntry("fixture.ir204_clean", _build_ir204(True), cost=False),
        AuditEntry("fixture.ir205_hot", _build_ir205, cost=False,
                   const_budget=1024),
        AuditEntry("fixture.ir205_clean", _build_ir205, cost=False),
    ]
