"""Re-pin the flight-recorder goldens (run from the repo root):

    JAX_PLATFORMS=cpu python tests/data/gen_telemetry_goldens.py

Writes tests/data/stress_telemetry_golden.json (the sweep_fleet
per-mix telemetry block for EPISODE_MIXES[0], 2 seeds — the
test_stress_fleet_telemetry_golden shape) and
tests/data/trace_golden.json (the trace CLI's Chrome-trace JSON for
the committed fleet-quick wedge artifact).  Both are pure functions
of the determinism contract; re-pin only for deliberate recorder,
engine, or mix changes."""

import json
import os

DATA = os.path.dirname(os.path.abspath(__file__))
WEDGE_ARTIFACT = "stress-triage/repro_fleet_g0_lane0.json"


def main():
    os.environ.setdefault("TPU_PAXOS_DETERMINISTIC", "1")
    from tpu_paxos.harness import stress
    from tpu_paxos.telemetry import export as texport

    summary = stress.sweep_fleet(
        n_seeds=2, verbose=False, mixes=stress.EPISODE_MIXES[:1]
    )
    assert summary["ok"], summary["failures"]
    out = os.path.join(DATA, "stress_telemetry_golden.json")
    with open(out, "w") as f:
        json.dump(summary["telemetry"], f, indent=1, sort_keys=True)
        f.write("\n")
    print("wrote", out)

    trace = texport.trace_artifact(WEDGE_ARTIFACT)
    out = os.path.join(DATA, "trace_golden.json")
    with open(out, "w") as f:
        json.dump(trace, f, indent=1, sort_keys=True)
        f.write("\n")
    print("wrote", out)


if __name__ == "__main__":
    main()
