"""Seeded compiled-artifact regressions for the hlo audit.

Loaded as an audit provider via ``--providers tests/data/hlo_fixture.py``
(tests/test_hlo_audit.py and its CLI e2e layer).  Two entries, each
hiding its regression behind an env flag the way a real one would ship
— behind a config flag nobody flips in review:

- ``hlofix.donated`` — a jitted state-recycling step that donates its
  state arg.  ``TPU_PAXOS_HLO_FIXTURE_DROP_DONATION=1`` silently drops
  ``donate_argnums`` (the wrapper-re-jit / flag regression); the
  donation checker must fail naming the entry and the parameter.
- ``hlofix.widen`` — a small golden-pinned kernel.
  ``TPU_PAXOS_HLO_FIXTURE_WIDEN=1`` routes it through a float detour
  (dtype widening -> extra ``convert`` instructions in the compiled
  module); the per-primitive budget and/or the golden diff must fail
  naming the entry, with the diff dumped to the triage dir.

The flags are read at module-exec time: ``jaxpr_audit._load_provider_arg``
re-executes the file on every load, so a test flips the env var and
reloads to arm a regression.
"""

import os

from tpu_paxos.analysis.registry import AuditEntry

_DROP_DONATION = os.environ.get(
    "TPU_PAXOS_HLO_FIXTURE_DROP_DONATION", "") not in ("", "0")
_WIDEN = os.environ.get(
    "TPU_PAXOS_HLO_FIXTURE_WIDEN", "") not in ("", "0")

_N = 64


def _make_recycle():
    """The product-style jit under donation test: state in, state out,
    same shapes/dtypes — the compiler CAN alias every leaf, so a
    missing alias means the donation was dropped, not unusable."""
    import jax

    def recycle(state, delta):
        return {
            "acc": state["acc"] + delta,
            "seen": state["seen"] | (delta > 0),
        }

    donate = () if _DROP_DONATION else (0,)
    return jax.jit(recycle, donate_argnums=donate)


def _widen_detour(y):
    """The seeded widening, hidden behind a helper like IR202's: four
    converts (i32->f32->i32 twice) — enough to breach a clean-pinned
    convert cap, and a guaranteed golden diff."""
    import jax.numpy as jnp

    y = y.astype(jnp.float32) * 1.5
    y = y.astype(jnp.int32)
    z = (y + 1).astype(jnp.float32)
    return (z * 2.0).astype(jnp.int32)


def audit_entries():
    import jax.numpy as jnp

    def build_donated():
        state = {
            "acc": jnp.arange(_N, dtype=jnp.int32),
            "seen": jnp.zeros((_N,), jnp.bool_),
        }
        delta = jnp.ones((_N,), jnp.int32)
        fn = _make_recycle()
        return fn, (state, delta)

    def build_widen():
        x = jnp.arange(_N, dtype=jnp.int32)

        def fn(x):
            y = x * 3 + 7
            if _WIDEN:
                y = _widen_detour(y)
            return y - x

        return fn, (x,)

    return [
        AuditEntry(
            "hlofix.donated", build_donated,
            covers=("_make_recycle",),
            donate_argnums=(0,),
            cost=False,
        ),
        AuditEntry(
            "hlofix.widen", build_widen,
            cost=False,
            hlo_golden=True,
        ),
    ]
