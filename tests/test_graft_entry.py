"""Driver contract: entry() jit-compiles; dryrun_multichip(8) works both
in-process (devices available) and via subprocess re-exec when jax is
already initialized on a too-small backend — the exact pattern the
driver uses (it runs bench on the 1-chip TPU backend first, then calls
dryrun_multichip(8))."""

import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    state, n_chosen = jax.jit(fn)(*args)
    assert int(n_chosen) == args[1].shape[0]


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_with_jax_preinitialized_small():
    """Reproduce the driver environment: jax initialized on a 1-device
    backend before dryrun_multichip is called.  MULTICHIP_r02 failed
    exactly here; the fix re-execs in a clean subprocess."""
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "try:\n"
        "    jax.config.update('jax_num_cpu_devices', 1)\n"
        "except AttributeError:\n"
        "    pass\n"  # pre-0.5 jax: 1 CPU device is the default anyway
        "assert len(jax.devices()) == 1\n"  # backend initialized, 1 device
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n"
        "print('PREINIT_OK')\n"
    )
    import __graft_entry__ as g

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + g.scrub_pythonpath(env.get("PYTHONPATH", ""))
    )
    env.pop("JAX_NUM_CPU_DEVICES", None)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "PREINIT_OK" in proc.stdout
    assert "dryrun_multichip ok" in proc.stdout
