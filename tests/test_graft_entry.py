"""Driver contract: entry() jit-compiles; dryrun_multichip(8) runs on
the virtual CPU mesh and keeps invariants."""

import jax


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    state, n_chosen = jax.jit(fn)(*args)
    assert int(n_chosen) == args[1].shape[0]


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
