"""General-engine tests: the TPU equivalents of the reference's
self-checking simulations (ref multi/main.cpp harness semantics).

Every run finishes by checking the whole-run invariants from
harness/validate.py — agreement, exactly-once vs the expected value
set, identical executed sequences (ref multi/main.cpp:567-573)."""

import numpy as np
import pytest

from tpu_paxos.config import FaultConfig, ProtocolConfig, SimConfig
from tpu_paxos.core import ballot as bal
from tpu_paxos.core import sim
from tpu_paxos.core import values as val
from tpu_paxos.harness import validate
from tpu_paxos.utils import prng


def _check(r: sim.SimResult, expected=None):
    assert r.done, f"sim did not quiesce in {r.rounds} rounds"
    validate.check_all(
        r.learned, r.expected_vids if expected is None else expected
    )


def test_single_proposer_fault_free():
    r = sim.run(SimConfig(n_nodes=3, n_instances=16, proposers=(0,), seed=0))
    _check(r)
    # one prepare round trip + one accept + one commit + quiesce
    assert r.rounds <= 10


def test_five_nodes_single_proposer():
    r = sim.run(SimConfig(n_nodes=5, n_instances=32, proposers=(2,), seed=1))
    _check(r)


def test_one_node_cluster():
    # quorum 1: a 1-node cluster must still choose (degenerate Paxos)
    r = sim.run(SimConfig(n_nodes=1, n_instances=8, proposers=(0,), seed=0))
    _check(r)


def test_dueling_proposers_baseline_config3():
    """BASELINE config 3: 5-node, 2 dueling proposers, randomized
    ballot backoff; liveness = bounded rounds-to-chosen."""
    r = sim.run(SimConfig(n_nodes=5, n_instances=32, proposers=(0, 1), seed=0))
    _check(r)
    assert r.rounds_to_chosen.size > 0
    assert r.rounds < 200  # liveness: anti-dueling backoff converges


# Seed 0 carries the debug.conf-rates coverage fast-tier; the extra
# seeds re-run the same program (one compile, ~13-17s each) and ride
# the slow tier to hold the tier-1 time budget.
@pytest.mark.parametrize(
    "seed",
    [0,
     pytest.param(1, marks=pytest.mark.slow),
     pytest.param(2, marks=pytest.mark.slow)],
)
def test_reference_fault_rates(seed):
    """The debug.conf.sample workload shape: drop 500/10000,
    dup 1000/10000, delay 0..max (ref multi/debug.conf.sample:1),
    two proposers contending."""
    cfg = SimConfig(
        n_nodes=5,
        n_instances=32,
        proposers=(0, 1),
        seed=seed,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, min_delay=0, max_delay=3),
    )
    r = sim.run(cfg)
    _check(r)


def test_heavy_drop_still_converges():
    cfg = SimConfig(
        n_nodes=3,
        n_instances=8,
        proposers=(0,),
        seed=5,
        max_rounds=50_000,
        faults=FaultConfig(drop_rate=3000),  # 30% drop
    )
    r = sim.run(cfg)
    _check(r)


def test_adoption_and_noop_hole_fill():
    """A dead proposer left a pre-accepted value at instance 2 on one
    acceptor; the new proposer must adopt it, fill instances 0-1 with
    no-ops (ref multi/paxos.cpp:1106-1130), and put its own values
    above (ref multi/paxos.cpp:1047-1182)."""
    cfg = SimConfig(n_nodes=3, n_instances=8, proposers=(0,), seed=0)
    workload = [np.asarray([50, 51], np.int32)]
    pend, gate, tail, c = sim.prepare_queues(cfg, workload)
    root = prng.root_key(cfg.seed)
    st = sim.init_state(cfg, pend, gate, tail, root)
    # vid 999 pre-accepted at instance 2 on acceptor 0 only, from a
    # proposer on node 2 that is now silent.
    dead_ballot = int(bal.make(1, 2))
    st = st._replace(
        acc=st.acc._replace(
            acc_ballot=st.acc.acc_ballot.at[0, 2].set(dead_ballot),
            acc_vid=st.acc.acc_vid.at[0, 2].set(999),  # [acceptor, inst]
        )
    )
    r = sim.run_state(cfg, st, root, np.asarray([50, 51, 999]), c)
    assert r.done
    assert bool(val.is_noop(r.chosen_vid[0])) and bool(val.is_noop(r.chosen_vid[1]))
    assert r.chosen_vid[2] == 999
    assert set(r.chosen_vid[3:5].tolist()) == {50, 51}
    validate.check_all(r.learned, np.asarray([50, 51, 999]))
    # the no-op holes must not block the apply frontier
    seqs = validate.check_executed_identical(r.learned)
    assert [s.tolist() for s in seqs] == [[999, 50, 51]] * 3


def test_conflict_reproposal():
    """Proposer 0 initially assigned vid 100 to instance 0, but vid 777
    (another node's value) was pre-accepted there at a higher ballot.
    On commit of 777, vid 100 must be re-queued and re-chosen at a
    fresh instance (ref multi/paxos.cpp:1540-1569)."""
    cfg = SimConfig(n_nodes=3, n_instances=8, proposers=(0,), seed=0)
    workload = [np.zeros((0,), np.int32)]
    pend, gate, tail, c = sim.prepare_queues(cfg, workload)
    root = prng.root_key(cfg.seed)
    st = sim.init_state(cfg, pend, gate, tail, root)
    rival = int(bal.make(7, 1))
    st = st._replace(
        acc=st.acc._replace(
            acc_ballot=st.acc.acc_ballot.at[1, 0].set(rival),
            acc_vid=st.acc.acc_vid.at[1, 0].set(777),  # [acceptor, inst]
        ),
        prop=st.prop._replace(
            own_assign=st.prop.own_assign.at[0, 0].set(100),
        ),
    )
    expected = np.asarray([100, 777])
    r = sim.run_state(cfg, st, root, expected, c)
    assert r.done
    assert r.chosen_vid[0] == 777
    assert 100 in r.chosen_vid.tolist()
    validate.check_all(r.learned, expected)


def test_in_order_client_gating():
    """In-order clients: each value proposable only after the previous
    one is chosen (ref multi/main.cpp:398-401), and the executed order
    must match proposal order (ref multi/main.cpp:202-212)."""
    vids = np.asarray([10, 11, 12, 13], np.int32)
    gates = [np.asarray([int(val.NONE), 10, 11, 12], np.int32)]
    cfg = SimConfig(n_nodes=3, n_instances=16, proposers=(0,), seed=0)
    r = sim.run(cfg, workload=[vids], gates=gates)
    _check(r)
    executed = validate.check_executed_identical(r.learned)[0]
    validate.check_in_order_clients(executed, [vids])


def test_value_status_lifecycle():
    """The Callback-SPI surface (ref member/paxos.h:142-163): a chosen
    value reports accepted/applied with its instance, round, ballot,
    and learner count; an unknown vid is pending."""
    cfg = SimConfig(n_nodes=3, n_instances=16, proposers=(0,), seed=0)
    vids = np.asarray([40, 41], np.int32)
    r = sim.run(cfg, workload=[vids])
    _check(r)
    st = r.value_status(40)
    assert st["status"] == "applied"  # quiescent run: all nodes learned
    assert st["learners"] == 3 and st["ballot"] > 0 and st["round"] >= 0
    assert r.chosen_vid[st["instance"]] == 40
    assert r.value_status(999)["status"] == "pending"
    # sentinels must never alias undecided/no-op instances
    assert r.value_status(-1)["status"] == "pending"
    assert r.value_status(-5)["status"] == "pending"


def test_dump_helpers_format():
    from tpu_paxos.utils import dump

    assert dump.dump_hex(b"\x00\xff\x10") == "00 FF 10"
    assert dump.dump_hex(bytes(300)).endswith("(+44 bytes)")
    s = dump.dump_array("chosen", np.asarray([[5, -1], [7, 8]], np.int32), 3)
    assert s == "chosen[2, 2]:int32= 5 . 7 .. (+1)"


def test_run_state_derives_gate_cap():
    """run_state without an explicit vid_cap must still enforce gates
    (derived from the state's own gate array) — a gate-bearing state
    silently run ungated would choose the whole chain at once."""
    from tpu_paxos.utils import prng

    vids = np.asarray([10, 11, 12, 13], np.int32)
    gates = [np.asarray([int(val.NONE), 10, 11, 12], np.int32)]
    cfg = SimConfig(n_nodes=3, n_instances=16, proposers=(0,), seed=0)
    pend, gate, tail, c = sim.prepare_queues(cfg, [vids], gates)
    root = prng.root_key(cfg.seed)
    st = sim.init_state(cfg, pend, gate, tail, root)
    r = sim.run_state(cfg, st, root, vids, c)  # no vid_cap passed
    assert r.done
    rounds_of = {
        int(v): int(rr)
        for v, rr in zip(r.chosen_vid, r.chosen_round)
        if v >= 0
    }
    assert rounds_of[10] < rounds_of[11] < rounds_of[12] < rounds_of[13]


def test_in_order_under_faults_and_contention():
    """In-order client on proposer 0 while proposer 1 floods free
    values, under reference fault rates — order must still hold."""
    inorder = np.asarray([10, 11, 12], np.int32)
    gates = [
        np.asarray([int(val.NONE), 10, 11], np.int32),
        np.zeros((0,), np.int32),
    ]
    free = np.asarray([20, 21, 22, 23, 24], np.int32)
    cfg = SimConfig(
        n_nodes=5,
        n_instances=32,
        proposers=(0, 1),
        seed=2,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    r = sim.run(cfg, workload=[inorder, free], gates=gates)
    _check(r)
    executed = validate.check_executed_identical(r.learned)
    validate.check_in_order_clients(max(executed, key=len), [inorder])


def test_crash_minority_safety_and_liveness():
    """member/-style random fail-stop crashes, capped at a minority
    (ref member/indet.h:146-150).  Safety must always hold; with a
    surviving majority and a surviving proposer the run completes."""
    cfg = SimConfig(
        n_nodes=5,
        n_instances=16,
        proposers=(0, 1),
        seed=4,
        max_rounds=50_000,
        faults=FaultConfig(crash_rate=5_000),  # 0.5% per node per round
    )
    r = sim.run(cfg)
    assert r.crashed.sum() <= 2  # minority cap
    # safety regardless of liveness
    validate.check_agreement(r.learned)
    validate.check_executed_identical(r.learned)
    if r.done:
        validate.check_all(r.learned, r.expected_vids)


@pytest.mark.slow
def test_same_seed_identical_outcome():
    """Determinism: the full decision record is a pure function of
    (config, seed) — the engine-level half of the reference's
    record/replay guarantee (ref member/run.sh:1-18)."""
    cfg = SimConfig(
        n_nodes=5,
        n_instances=32,
        proposers=(0, 1),
        seed=9,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=3),
    )
    r1, r2 = sim.run(cfg), sim.run(cfg)
    np.testing.assert_array_equal(r1.chosen_vid, r2.chosen_vid)
    np.testing.assert_array_equal(r1.chosen_round, r2.chosen_round)
    np.testing.assert_array_equal(r1.chosen_ballot, r2.chosen_ballot)
    np.testing.assert_array_equal(r1.learned, r2.learned)
    np.testing.assert_array_equal(r1.msgs, r2.msgs)
    assert r1.rounds == r2.rounds


@pytest.mark.slow
def test_different_seed_different_schedule():
    """Different seeds must actually change the fault schedule (guards
    against the PRNG being wired to nothing)."""
    mk = lambda s: sim.run(  # noqa: E731
        SimConfig(
            n_nodes=5,
            n_instances=32,
            proposers=(0, 1),
            seed=s,
            faults=FaultConfig(drop_rate=2000, dup_rate=1000, max_delay=3),
        )
    )
    r1, r2 = mk(1), mk(2)
    assert r1.rounds != r2.rounds or not np.array_equal(
        r1.chosen_round, r2.chosen_round
    )


def test_message_counters_populated():
    r = sim.run(SimConfig(n_nodes=3, n_instances=16, proposers=(0,), seed=0))
    # prepare, prepare_reply, accept, accept_reply, commit, commit_reply
    assert r.msgs[0] > 0 and r.msgs[1] > 0
    assert r.msgs[3] > 0 and r.msgs[4] > 0
    assert r.msgs[5] > 0 and r.msgs[6] > 0


def test_conflict_requeue_cap_carry_over():
    """More simultaneous conflicts than the per-round requeue cap
    (assign_window): the overflow must stay in own_assign and drain on
    later rounds — no conflicted value may be lost.  12 own
    assignments all lose to rival pre-accepted values with a 4-wide
    window, so the requeue compaction needs 3+ rounds to drain."""
    k = 12
    cfg = SimConfig(
        n_nodes=3, n_instances=64, proposers=(0,), seed=0, assign_window=4
    )
    workload = [np.zeros((0,), np.int32)]
    pend, gate, tail, c = sim.prepare_queues(cfg, workload)
    root = prng.root_key(cfg.seed)
    st = sim.init_state(cfg, pend, gate, tail, root)
    rival = int(bal.make(7, 1))
    insts = np.arange(k)
    st = st._replace(
        acc=st.acc._replace(
            acc_ballot=st.acc.acc_ballot.at[1, insts].set(rival),
            acc_vid=st.acc.acc_vid.at[1, insts].set(700 + insts),
        ),
        prop=st.prop._replace(
            own_assign=st.prop.own_assign.at[0, insts].set(100 + insts),
        ),
    )
    expected = np.concatenate([100 + insts, 700 + insts]).astype(np.int32)
    r = sim.run_state(cfg, st, root, expected, c)
    assert r.done
    # every rival won its original instance; every displaced own value
    # was re-chosen elsewhere, exactly once
    assert (r.chosen_vid[:k] == 700 + insts).all()
    chosen = set(r.chosen_vid[r.chosen_vid >= 0].tolist())
    assert set((100 + insts).tolist()) <= chosen
    validate.check_all(r.learned, expected)
